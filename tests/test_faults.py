"""Chaos suite: the deterministic fault-injection layer
(runtime/faults.py) and the degraded-mode verdict pipeline it proves
(TPU→oracle circuit breaker, atomic loader swap with rollback,
stream reconnect-with-resume, isolated kvstore/clustermesh/dnsproxy
failures).

The fast tests here run in tier-1. Tests marked ``chaos`` (the
golden-corpus replays under injected failures) are also ``slow`` —
the ``make chaos`` lane runs them seeded and standalone so chaos cost
never rides the tier-1 timing budget.
"""

import threading
import time

import numpy as np
import pytest

from cilium_tpu.core.config import Config
from cilium_tpu.core.flow import Flow, Protocol, TrafficDirection
from cilium_tpu.runtime import faults, simclock
from cilium_tpu.runtime.faults import FaultInjected, FaultPlan, FaultRule
from cilium_tpu.runtime.loader import Loader
from cilium_tpu.runtime.metrics import (
    BREAKER_FALLBACK_VERDICTS,
    BREAKER_RECOVERIES,
    BREAKER_TRIPS,
    DNSPROXY_FALLBACKS,
    FAULTS_INJECTED,
    KVSTORE_WATCH_ERRORS,
    LOADER_ROLLBACKS,
    METRICS,
    STREAM_RECONNECTS,
)
from cilium_tpu.runtime.service import CircuitBreaker, VerdictService


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """A leaked armed plan would fail unrelated tests — enforce."""
    assert faults.active() is None
    yield
    faults.clear()


def _metric(name, labels=None):
    return METRICS.get(name, labels)


# ---------------------------------------------------------------------------
# FaultPlan determinism


def test_plan_fires_deterministically_per_seed():
    def run(seed):
        plan = FaultPlan([FaultRule("p", prob=0.5)], seed=seed)
        for _ in range(300):
            plan.check("p")
        return plan.trace()["p"]

    assert run(7) == run(7)
    assert run(7) != run(8)
    fires = sum(f for _, f in run(7))
    assert 80 < fires < 220  # prob 0.5 actually samples


def test_plan_times_after_and_counts():
    plan = FaultPlan([FaultRule("p", times=2, after=3)], seed=0)
    fired = [plan.check("p") is not None for _ in range(10)]
    assert fired == [False] * 3 + [True, True] + [False] * 5
    assert plan.counts("p") == (10, 2)
    assert plan.counts("unknown") == (0, 0)


def test_plan_trace_is_thread_order_free():
    """Per-point decisions depend only on per-point hit order, so two
    points hammered from interleaved threads still produce the same
    per-point traces as a serial run."""
    def run(threaded):
        plan = FaultPlan([FaultRule("a", prob=0.3),
                          FaultRule("b", prob=0.7)], seed=42)
        if threaded:
            ts = [threading.Thread(
                target=lambda p: [plan.check(p) for _ in range(200)],
                args=(p,)) for p in ("a", "b")]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        else:
            for _ in range(200):
                plan.check("a")
            for _ in range(200):
                plan.check("b")
        return plan.trace()

    assert run(False) == run(True)


def test_maybe_fail_noop_without_plan_and_raises_with():
    faults.maybe_fail("engine.dispatch")  # disarmed: no-op
    plan = FaultPlan([FaultRule("engine.dispatch", times=1)])
    before = _metric(FAULTS_INJECTED, {"point": "engine.dispatch"})
    with faults.inject(plan):
        with pytest.raises(FaultInjected):
            faults.maybe_fail("engine.dispatch")
        faults.maybe_fail("engine.dispatch")  # times exhausted
    assert faults.active() is None
    assert _metric(FAULTS_INJECTED,
                   {"point": "engine.dispatch"}) == before + 1


def test_plan_chooses_the_exception_type():
    plan = FaultPlan([FaultRule("x", exc=ConnectionError)])
    with faults.inject(plan):
        with pytest.raises(ConnectionError):
            faults.maybe_fail("x")


def test_registered_points_cover_the_documented_seams():
    # points register at the owning module's import — pull in the seams
    import cilium_tpu.clustermesh  # noqa: F401
    import cilium_tpu.engine.verdict  # noqa: F401
    import cilium_tpu.fqdn.dnsproxy  # noqa: F401
    import cilium_tpu.identity_kvstore  # noqa: F401
    import cilium_tpu.kvstore  # noqa: F401
    import cilium_tpu.policy.compiler.bankplan  # noqa: F401
    import cilium_tpu.runtime.canary  # noqa: F401
    import cilium_tpu.runtime.fleetserve  # noqa: F401
    import cilium_tpu.runtime.stream  # noqa: F401
    import cilium_tpu.runtime.tenant  # noqa: F401

    pts = faults.registered_points()
    for p in ("engine.dispatch", "loader.swap", "loader.bank_compile",
              "stream.frame.server",
              "stream.frame.client", "stream.credit", "service.admit",
              "service.drain", "kvstore.watch", "kvstore.churn_storm",
              "clustermesh.session", "dnsproxy.query",
              "fleet.heartbeat", "fleet.handoff",
              "canary.dispatch", "tenant.quota"):
        assert p in pts, p


# ---------------------------------------------------------------------------
# CircuitBreaker state machine (fake clock — no sleeping)


def test_breaker_trips_after_consecutive_failures_and_recovers():
    now = [0.0]
    br = CircuitBreaker(failure_threshold=3, probe_interval=5.0,
                        clock=lambda: now[0])
    trips0 = _metric(BREAKER_TRIPS)
    recov0 = _metric(BREAKER_RECOVERIES)
    # two failures + a success: consecutive counter resets
    br.record_failure()
    br.record_failure()
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED
    for _ in range(3):
        assert br.allow_primary()
        br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    assert _metric(BREAKER_TRIPS) == trips0 + 1
    # OPEN: no probe until the interval elapses
    assert not br.allow_primary()
    now[0] = 5.1
    assert br.allow_primary()          # the single HALF_OPEN probe
    assert br.state == CircuitBreaker.HALF_OPEN
    assert not br.allow_primary()      # concurrent caller keeps falling back
    br.record_failure()                # probe failed → OPEN, timer re-armed
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow_primary()
    now[0] = 10.3
    assert br.allow_primary()
    br.record_success()                # probe succeeded → CLOSED
    assert br.state == CircuitBreaker.CLOSED
    assert _metric(BREAKER_RECOVERIES) == recov0 + 1
    assert _metric(BREAKER_TRIPS) == trips0 + 1  # no double trip
    assert [e for e, _ in br.events] == [
        "trip", "probe", "probe-failed", "probe", "recover"]


# ---------------------------------------------------------------------------
# Loader: atomic swap with rollback


def _tiny_policy(port):
    from cilium_tpu.core.identity import IdentityAllocator
    from cilium_tpu.core.labels import LabelSet
    from cilium_tpu.policy.api import (
        EndpointSelector,
        IngressRule,
        PortProtocol,
        PortRule,
        Rule,
    )
    from cilium_tpu.policy.mapstate import PolicyResolver
    from cilium_tpu.policy.repository import Repository
    from cilium_tpu.policy.selectorcache import SelectorCache

    rules = [Rule(
        endpoint_selector=EndpointSelector.from_labels(app="db"),
        ingress=(IngressRule(
            from_endpoints=(EndpointSelector.from_labels(app="web"),),
            to_ports=(PortRule(ports=(
                PortProtocol(port, Protocol.TCP),)),)),),
    )]
    alloc = IdentityAllocator()
    db = alloc.allocate(LabelSet.from_dict({"app": "db"}))
    web = alloc.allocate(LabelSet.from_dict({"app": "web"}))
    cache = SelectorCache(alloc)
    repo = Repository()
    repo.add(rules, sanitize=False)
    per_identity = {db: PolicyResolver(repo, cache).resolve(
        alloc.lookup(db))}
    return per_identity, db, web


def _flow(web, db, port):
    return Flow(src_identity=web, dst_identity=db, dport=port,
                protocol=Protocol.TCP,
                direction=TrafficDirection.INGRESS)


@pytest.mark.parametrize("offload", [False, True])
def test_loader_swap_rollback_keeps_previous_revision(offload):
    cfg = Config()
    cfg.enable_tpu_offload = offload
    cfg.loader.enable_cache = False
    loader = Loader(cfg)
    per1, db, web = _tiny_policy(5432)
    loader.regenerate(per1, revision=1)
    engine1 = loader.engine
    rollbacks0 = _metric(LOADER_ROLLBACKS)

    per2, _, _ = _tiny_policy(6000)
    with faults.inject(FaultPlan([FaultRule("loader.swap", times=1)])):
        with pytest.raises(FaultInjected):
            loader.regenerate(per2, revision=2)
        # mid-swap crash: the PREVIOUS table serves, not a torn state
        assert loader.engine is engine1
        assert loader.revision == 1
        assert loader.per_identity is per1
        out = loader.engine.verdict_flows([_flow(web, db, 5432)])
        assert int(out["verdict"][0]) == 1  # rev-1 semantics intact
        assert _metric(LOADER_ROLLBACKS) == rollbacks0 + 1
        # injection exhausted (times=1): the retry succeeds
        loader.regenerate(per2, revision=2)
    assert loader.revision == 2
    out = loader.engine.verdict_flows(
        [_flow(web, db, 5432), _flow(web, db, 6000)])
    assert [int(v) for v in out["verdict"]] == [2, 1]


def test_loader_fallback_engine_tracks_revision():
    from cilium_tpu.policy.oracle import OracleVerdictEngine

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.enable_cache = False
    loader = Loader(cfg)
    per1, db, web = _tiny_policy(5432)
    loader.regenerate(per1, revision=1)
    fb1 = loader.fallback_engine
    assert isinstance(fb1, OracleVerdictEngine)
    assert fb1 is loader.fallback_engine  # cached per revision
    out = fb1.verdict_flows([_flow(web, db, 5432)])
    assert int(out["verdict"][0]) == 1
    per2, _, _ = _tiny_policy(6000)
    loader.regenerate(per2, revision=2)
    fb2 = loader.fallback_engine
    assert fb2 is not fb1
    assert int(fb2.verdict_flows(
        [_flow(web, db, 5432)])["verdict"][0]) == 2
    # gate off: the active oracle IS the fallback (no duplicate build)
    loader2 = Loader(Config())
    loader2.regenerate(per1, revision=1)
    assert loader2.fallback_engine is loader2.engine


# ---------------------------------------------------------------------------
# Service: breaker-guarded verdict paths


def _service(tmp_path, per_identity, offload=True, threshold=2,
             probe_interval=60.0):
    cfg = Config()
    cfg.enable_tpu_offload = offload
    cfg.loader.enable_cache = False
    cfg.breaker.failure_threshold = threshold
    cfg.breaker.probe_interval = probe_interval
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    svc = VerdictService(loader, str(tmp_path / "svc.sock"))
    svc.start()
    return svc


def test_service_device_failure_degrades_to_oracle(tmp_path):
    """Repeated engine.dispatch faults: every answer stays CORRECT
    (served by the oracle), the breaker trips, and when injection
    stops the half-open probe recovers the device lane."""
    from cilium_tpu.runtime.service import VerdictClient

    per, db, web = _tiny_policy(5432)
    svc = _service(tmp_path, per, threshold=2, probe_interval=0.05)
    probe_advance = 1.0   # > probe_interval: the timer reads expired
    want = {5432: 1, 5433: 2}
    trips0 = _metric(BREAKER_TRIPS)
    recov0 = _metric(BREAKER_RECOVERIES)
    fallb0 = _metric(BREAKER_FALLBACK_VERDICTS)
    try:
        client = VerdictClient(svc.socket_path)
        plan = FaultPlan([FaultRule("engine.dispatch", times=2)], seed=1)
        with faults.inject(plan):
            for port, w in list(want.items()) * 3:
                resp = client.call({"op": "verdict", "flows": [
                    {"source": {"identity": int(web)},
                     "destination": {"identity": int(db)},
                     "l4": {"TCP": {"destination_port": port}},
                     "traffic_direction": "INGRESS"}]})
                assert resp["verdicts"] == [w], (port, resp)
            assert plan.counts("engine.dispatch")[1] == 2
        assert _metric(BREAKER_TRIPS) == trips0 + 1
        assert _metric(BREAKER_FALLBACK_VERDICTS) > fallb0
        # injection over: advance the breaker's clock past the probe
        # interval (no wall-clock sleep — ISSUE-10 virtual time); the
        # next request half-open probes the device lane and recovers
        svc.verdictor.breaker.clock = \
            lambda: simclock.now() + probe_advance
        resp = client.call({"op": "verdict", "flows": [
            {"source": {"identity": web},
             "destination": {"identity": db},
             "l4": {"TCP": {"destination_port": 5432}},
             "traffic_direction": "INGRESS"}]})
        assert resp["verdicts"] == [1]
        assert svc.verdictor.breaker.state == CircuitBreaker.CLOSED
        assert _metric(BREAKER_RECOVERIES) == recov0 + 1
        client.close()
    finally:
        svc.stop()


def test_microbatcher_check_survives_device_faults(tmp_path):
    """The per-request MicroBatcher path ('check' op) serves correct
    verdicts from the oracle while the device lane is down."""
    from cilium_tpu.runtime.service import VerdictClient

    per, db, web = _tiny_policy(5432)
    svc = _service(tmp_path, per, threshold=1, probe_interval=60.0)
    try:
        client = VerdictClient(svc.socket_path)
        with faults.inject(FaultPlan(
                [FaultRule("engine.dispatch")], seed=0)):  # always fail
            for port, w in ((5432, 1), (5433, 2), (5432, 1)):
                resp = client.call({"op": "check", "flow": {
                    "source": {"identity": int(web)},
                    "destination": {"identity": int(db)},
                    "l4": {"TCP": {"destination_port": port}},
                    "traffic_direction": "INGRESS"}})
                assert resp["verdict"] == w
        assert svc.verdictor.breaker.state == CircuitBreaker.OPEN
        client.close()
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Stream: per-chunk degradation + client reconnect-with-resume


def _stream_flows(web, db, n=64):
    return [_flow(web, db, 5432 if i % 2 == 0 else 5433)
            for i in range(n)]


def test_stream_server_chunk_fault_fails_only_its_seq(tmp_path):
    from cilium_tpu.runtime.stream import StreamClient

    per, db, web = _tiny_policy(5432)
    svc = _service(tmp_path, per, offload=False)
    try:
        client = StreamClient(svc.socket_path, timeout=30.0)
        flows = _stream_flows(web, db, 32)
        with faults.inject(FaultPlan(
                [FaultRule("stream.frame.server", times=1)], seed=0)):
            seqs = [client.send_flows(flows) for _ in range(4)]
            client.finish()
        errors, ok = 0, 0
        for seq in seqs:
            try:
                v = client.result(seq)
                ok += 1
                assert list(v) == [1, 2] * 16
            except RuntimeError:
                errors += 1
        assert (errors, ok) == (1, 3)  # exactly the faulted seq failed
        client.close()
    finally:
        svc.stop()


def test_stream_device_fault_degrades_chunk_to_oracle(tmp_path):
    """With the TPU gate on, an engine.dispatch fault inside a stream
    chunk serves THAT chunk from the oracle — same verdicts, no error
    frame, breaker accounting engaged."""
    from cilium_tpu.runtime.stream import StreamClient

    per, db, web = _tiny_policy(5432)
    svc = _service(tmp_path, per, threshold=2, probe_interval=60.0)
    fallb0 = _metric(BREAKER_FALLBACK_VERDICTS)
    try:
        client = StreamClient(svc.socket_path, timeout=60.0)
        flows = _stream_flows(web, db, 32)
        with faults.inject(FaultPlan(
                [FaultRule("engine.dispatch", times=3)], seed=0)):
            seqs = [client.send_flows(flows) for _ in range(6)]
            client.finish()
            for seq in seqs:
                assert list(client.result(seq)) == [1, 2] * 16
        assert _metric(BREAKER_FALLBACK_VERDICTS) >= fallb0 + 3 * 32
        client.close()
    finally:
        svc.stop()


def test_stream_client_reconnects_and_resumes(tmp_path):
    """An injected connection drop mid-stream: the client re-dials
    with backoff, re-handshakes, re-sends unacked chunks, and every
    verdict lands — zero mismatches, reconnect counted."""
    from cilium_tpu.runtime.stream import StreamClient

    per, db, web = _tiny_policy(5432)
    svc = _service(tmp_path, per, offload=False)
    rec0 = _metric(STREAM_RECONNECTS)
    try:
        client = StreamClient(svc.socket_path, timeout=60.0,
                              reconnect=True, backoff_base=0.01)
        flows = _stream_flows(web, db, 16)
        # drop the connection on the 2nd received frame
        with faults.inject(FaultPlan([FaultRule(
                "stream.frame.client", after=1, times=1,
                exc=ConnectionError)], seed=3)):
            seqs = [client.send_flows(flows) for _ in range(5)]
            client.finish()
            for seq in seqs:
                assert list(client.result(seq)) == [1, 2] * 8
        assert _metric(STREAM_RECONNECTS) == rec0 + 1
        client.close()
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# kvstore / clustermesh / dnsproxy isolation


def test_kvstore_watch_fault_is_isolated_from_the_writer():
    from cilium_tpu.kvstore import KVStore

    store = KVStore()
    seen = []
    store.watch_prefix("k/", lambda ev: seen.append(ev.key),
                       replay=False)
    errs0 = _metric(KVSTORE_WATCH_ERRORS)
    with faults.inject(FaultPlan(
            [FaultRule("kvstore.watch", times=1)], seed=0)):
        store.set("k/1", "a")   # delivery faulted — writer unaffected
        store.set("k/2", "b")   # next event delivers normally
    assert store.get("k/1") == "a"  # the COMMIT was never at risk
    assert seen == ["k/2"]
    assert _metric(KVSTORE_WATCH_ERRORS) == errs0 + 1


def test_clustermesh_session_fault_drops_one_event_not_the_session():
    from cilium_tpu.clustermesh import IP_PREFIX, RemoteCluster
    from cilium_tpu.core.identity import IdentityAllocator
    from cilium_tpu.ipcache import IPCache
    from cilium_tpu.kvstore import KVStore

    alloc = IdentityAllocator()
    ipcache = IPCache(alloc)
    store = KVStore()
    rc = RemoteCluster("c1", store, alloc, ipcache).connect()
    with faults.inject(FaultPlan(
            [FaultRule("clustermesh.session", times=1)], seed=0)):
        store.set(IP_PREFIX + "c1/10.1.0.1/32",
                  '{"prefix": "10.1.0.1/32", "labels": ["k8s:app=a"]}')
        store.set(IP_PREFIX + "c1/10.1.0.2/32",
                  '{"prefix": "10.1.0.2/32", "labels": ["k8s:app=b"]}')
    # first event was eaten by the fault; the session survived and
    # ingested the second
    assert rc.num_entries() == 1
    assert ipcache.lookup("10.1.0.2") is not None
    rc.disconnect()


def test_dnsproxy_device_fault_falls_back_to_regex():
    from cilium_tpu.fqdn.dnsproxy import DNSProxy
    from cilium_tpu.policy.api.l7 import PortRuleDNS

    proxy = DNSProxy(use_tpu=True)
    proxy.update_allowed(1, 53, [PortRuleDNS(match_pattern="*.corp.io")])
    qnames = ["a.corp.io", "evil.net", "b.corp.io"]
    fb0 = _metric(DNSPROXY_FALLBACKS)
    with faults.inject(FaultPlan(
            [FaultRule("dnsproxy.query")], seed=0)):  # device always sick
        got = proxy.check_batch(1, 53, qnames)
    assert list(got) == [True, False, True]
    assert _metric(DNSPROXY_FALLBACKS) == fb0 + 1
    # healthy again: the banked path answers identically
    assert list(proxy.check_batch(1, 53, qnames)) == [True, False, True]


# ---------------------------------------------------------------------------
# The acceptance chaos replay: golden corpus under injected device
# failures — zero verdict mismatches, breaker trips + recovers, and
# the same plan + seed reproduces the identical event trace twice.


def _chaos_corpus_replay(seed):
    """One full degraded-mode replay of the golden corpus with a
    manually-advanced breaker clock (no wall-clock in the loop — the
    whole event sequence is a pure function of the plan). Returns
    (verdicts, fault trace, breaker events, counter deltas)."""
    from cilium_tpu.agent import Agent
    from cilium_tpu.runtime.service import ResilientVerdictor
    from tests.test_controlplane_golden import build_agent, build_flows

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.configure_logging = False
    agent, ids = build_agent(Agent(cfg))
    try:
        clock = [0.0]
        breaker = CircuitBreaker(failure_threshold=2,
                                 probe_interval=5.0,
                                 clock=lambda: clock[0])
        verdictor = ResilientVerdictor(agent.loader, breaker=breaker)
        flows = build_flows(ids)
        chunks = [flows[i:i + 8] for i in range(0, len(flows), 8)]
        # fires on device-dispatch hits 1..4: hits 1-2 trip the
        # breaker, the probes at chunks 6 and 10 fail (hits 3-4), the
        # probe at chunk 14 succeeds — recovery mid-replay
        plan = FaultPlan([FaultRule("engine.dispatch", times=4)],
                         seed=seed)
        t0 = _metric(BREAKER_TRIPS)
        r0 = _metric(BREAKER_RECOVERIES)
        f0 = _metric(BREAKER_FALLBACK_VERDICTS)
        verdicts = []
        with faults.inject(plan):
            for i, chunk in enumerate(chunks):
                if i in (6, 10, 14):
                    clock[0] += 10.0  # probe timer expires
                verdicts.extend(verdictor.verdicts(chunk))
        deltas = (_metric(BREAKER_TRIPS) - t0,
                  _metric(BREAKER_RECOVERIES) - r0,
                  _metric(BREAKER_FALLBACK_VERDICTS) - f0)
        return verdicts, plan.trace(), list(breaker.events), deltas
    finally:
        agent.stop()


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_corpus_zero_mismatch_trip_and_recover():
    import json
    import os

    golden_path = os.path.join(os.path.dirname(__file__), "golden",
                               "corpus_verdicts.json")
    with open(golden_path) as fp:
        golden = json.load(fp)["verdicts"]

    v1, trace1, events1, (trips, recoveries, fallbacks) = \
        _chaos_corpus_replay(seed=11)
    # the headline: repeated device-dispatch failures during the
    # replay and NOT ONE wrong verdict
    assert v1 == golden
    assert trips >= 1, "breaker never tripped under injected failures"
    assert recoveries >= 1, "breaker never recovered after injection"
    assert fallbacks >= 8, "no verdicts actually rode the oracle lane"
    assert ("trip", "open") in events1
    assert ("recover", "closed") in events1
    assert events1[-1] == ("recover", "closed")

    # replayability: same plan + seed → identical fault trace AND
    # identical breaker transition sequence
    v2, trace2, events2, _ = _chaos_corpus_replay(seed=11)
    assert v2 == golden
    assert trace2 == trace1
    assert events2 == events1


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_stream_replay_with_drops_and_device_faults(tmp_path):
    """The online stream under BOTH failure modes at once: connection
    drops (client resumes) and device faults (chunks degrade to the
    oracle) — the drained verdicts still match the oracle bit-for-bit."""
    from cilium_tpu.runtime.stream import StreamClient

    per, db, web = _tiny_policy(5432)
    svc = _service(tmp_path, per, threshold=2, probe_interval=0.02)
    try:
        flows = _stream_flows(web, db, 64)
        oracle = svc.loader.fallback_engine
        want = [int(v) for v in
                oracle.verdict_flows(flows)["verdict"]]
        client = StreamClient(svc.socket_path, timeout=60.0,
                              reconnect=True, backoff_base=0.01,
                              reconnect_seed=5)
        plan = FaultPlan([
            FaultRule("engine.dispatch", prob=0.4, times=5),
            FaultRule("stream.frame.client", after=2, times=2,
                      exc=ConnectionError),
        ], seed=23)
        got = {}
        with faults.inject(plan):
            seqs = [client.send_flows(flows) for _ in range(10)]
            client.finish()
            for seq in seqs:
                got[seq] = list(client.result(seq))
        for seq in seqs:
            assert got[seq] == want, f"verdict mismatch in seq {seq}"
        client.close()
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# ISSUE 5: overload/drain fault points + the drain→restart warm cycle


def test_admission_fault_forces_an_explicit_shed(tmp_path):
    """An injected service.admit fault is a SHED — the request is
    refused explicitly (counted, flagged), never half-admitted."""
    from cilium_tpu.runtime.service import VerdictClient

    per, db, web = _tiny_policy(5432)
    svc = _service(tmp_path, per, offload=False)
    try:
        client = VerdictClient(svc.socket_path)
        flow = {"source": {"identity": int(web)},
                "destination": {"identity": int(db)},
                "l4": {"TCP": {"destination_port": 5432}},
                "traffic_direction": "INGRESS"}
        plan = FaultPlan([FaultRule("service.admit", times=1)], seed=3)
        with faults.inject(plan):
            shed = client.call({"op": "check", "flow": flow})
            assert shed["shed"] is True and shed["reason"] == "fault"
            # the fault budget is spent: the next request serves
            ok = client.call({"op": "check", "flow": flow})
            assert ok["verdict"] == 1 and "shed" not in ok
        assert plan.counts("service.admit") == (2, 1)
        client.close()
    finally:
        svc.stop()


def test_drain_fault_leaves_gate_draining_and_retry_succeeds(tmp_path):
    """A crash between stop-admitting and the flush (service.drain
    point): the drain op errors, the gate STAYS draining (fail-safe:
    no half-open re-admission), and a retried drain completes."""
    from cilium_tpu.runtime.service import VerdictClient

    per, _db, _web = _tiny_policy(5432)
    svc = _service(tmp_path, per, offload=False)
    try:
        client = VerdictClient(svc.socket_path)
        with faults.inject(FaultPlan(
                [FaultRule("service.drain", times=1)], seed=9)):
            resp = client.call({"op": "drain"})
            assert "error" in resp
            assert svc.gate.draining  # fail-safe: still draining
            retry = client.call({"op": "drain"})
            assert retry["ok"] is True
        client.close()
    finally:
        svc.stop()


def test_stream_credit_grant_loss_degrades_not_corrupts(tmp_path):
    """An injected stream.credit fault LOSES one grant: the client's
    window shrinks by one but every verdict still lands and matches —
    credit loss costs pacing, never correctness."""
    from cilium_tpu.runtime.stream import StreamClient

    per, db, web = _tiny_policy(5432)
    svc = _service(tmp_path, per, offload=False)
    try:
        flows = _stream_flows(web, db, 32)
        want = [int(v) for v in
                svc.loader.engine.verdict_flows(flows)["verdict"]]
        client = StreamClient(svc.socket_path, timeout=30.0)
        window = client._credits
        assert window and window > 1
        plan = FaultPlan([FaultRule("stream.credit", times=1)], seed=4)
        with faults.inject(plan):
            seqs = [client.send_flows(flows) for _ in range(6)]
            client.finish()
            for seq in seqs:
                assert list(client.result(seq)) == want
        assert plan.counts("stream.credit")[1] == 1
        # exactly one grant was lost → steady-state window is one low
        with client._cond:
            assert client._credits == window - 1
        client.close()
    finally:
        svc.stop()


def test_drain_restart_cycle_is_verdict_clean_and_warm(tmp_path):
    """THE ISSUE 5 acceptance cycle: requests in flight when the drain
    begins finish with REAL verdicts (zero ERRORs); the warm snapshot
    lands; a fresh loader (new process stand-in, same cache dir)
    restores it with ZERO recompilation and reproduces the golden
    corpus verdict-identically."""
    from cilium_tpu.runtime.metrics import WARM_RESTORES
    from cilium_tpu.runtime.service import VerdictClient

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.cache_dir = str(tmp_path / "cache")
    per, db, web = _tiny_policy(5432)
    loader = Loader(cfg)
    loader.regenerate(per, revision=7)
    svc = VerdictService(loader, str(tmp_path / "svc.sock"))
    svc.start()

    corpus = [{"source": {"identity": int(web)},
               "destination": {"identity": int(db)},
               "l4": {"TCP": {"destination_port": p}},
               "traffic_direction": "INGRESS"}
              for p in (5432, 5433, 80, 5432, 9999)]
    try:
        client = VerdictClient(svc.socket_path)
        golden = client.call({"op": "verdict", "flows": corpus})
        assert "verdicts" in golden

        # in-flight requests racing the drain: every ADMITTED check
        # resolves with a real verdict, sheds are explicit
        results = []
        lock = threading.Lock()

        def caller():
            c = VerdictClient(svc.socket_path)
            for _ in range(12):
                r = c.call({"op": "check", "flow": corpus[0]})
                with lock:
                    results.append(r)
            c.close()

        threads = [threading.Thread(target=caller) for _ in range(4)]
        for t in threads:
            t.start()
        drained = svc.drain()
        for t in threads:
            t.join(timeout=30.0)
        assert drained["ok"] and drained["warm_snapshot"] is True
        admitted = [r for r in results if not r.get("shed")]
        shed = [r for r in results if r.get("shed")]
        assert all(r["verdict"] == 1 for r in admitted), admitted[:5]
        assert all(r["reason"] for r in shed)
        client.close()
    finally:
        svc.stop()

    # "restart": a fresh loader over the same artifact cache — no
    # policy replay, no fingerprint walk, no compile
    compiles0 = METRICS.histo_count("cilium_tpu_compile_seconds")
    warm0 = _metric(WARM_RESTORES)
    cfg2 = Config()
    cfg2.enable_tpu_offload = True
    cfg2.loader.cache_dir = str(tmp_path / "cache")
    loader2 = Loader(cfg2)
    assert loader2.restore_warm() is True
    assert loader2.revision == 7
    assert _metric(WARM_RESTORES) == warm0 + 1
    assert METRICS.histo_count("cilium_tpu_compile_seconds") \
        == compiles0, "warm restore recompiled"

    svc2 = VerdictService(loader2, str(tmp_path / "svc2.sock"))
    svc2.start()
    try:
        client2 = VerdictClient(svc2.socket_path)
        replay = client2.call({"op": "verdict", "flows": corpus})
        assert replay["verdicts"] == golden["verdicts"]
        client2.close()
    finally:
        svc2.stop()


# ---------------------------------------------------------------------------
# ISSUE 7: verdict-memo staleness across loader swap / rollback /
# warm restore — a policy commit can never serve a memoized verdict
# computed under a previous revision.


def _memo_session(loader, cfg, flows):
    from cilium_tpu.engine.verdict import CaptureReplay
    from cilium_tpu.ingest.columnar import flows_to_columns

    cols = flows_to_columns(flows)
    replay = CaptureReplay(loader.engine, cols.l7, cols.offsets,
                           cols.blob, cfg.engine, gen=cols.gen,
                           loader=loader)
    replay.stage_rows(cols.rec, cols.l7)
    replay.stage_unique()
    return replay, cols


def test_memo_invalidates_across_swap_rollback_warm_restore(tmp_path):
    """One replay session with a HOT memo, driven through every
    serving-state transition: revision swap (verdicts follow the new
    policy), rollback (verdicts stay with the surviving revision),
    snapshot/warm-restore (verdicts return with the restored
    revision) — each CONTENT-changing transition invalidates the
    touched memo rows (bank-scoped since ISSUE 8: a CNP change drops
    only rows of the identities it selects, counted under
    reason=bank-swap; a rollback stays a full policy-swap drop) and
    every answer is bit-equal to the serving engine's verdict_flows."""
    from cilium_tpu.runtime.metrics import VERDICT_MEMO_INVALIDATIONS

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    per1, db, web = _tiny_policy(5432)
    loader.regenerate(per1, revision=1)
    flows = [_flow(web, db, 5432), _flow(web, db, 6000)] * 6

    replay, cols = _memo_session(loader, cfg, flows)

    def session_verdicts():
        out = replay.verdict_chunk(cols.rec, cols.l7)
        return [int(v) for v in out["verdict"]]

    def engine_verdicts():
        return [int(v) for v in
                loader.engine.verdict_flows(flows)["verdict"]]

    # memo hot under rev 1: 5432 allowed, 6000 dropped
    assert session_verdicts() == [1, 2] * 6 == engine_verdicts()
    memo = replay.memo
    inv0 = memo.invalidations
    bsw0 = _metric(VERDICT_MEMO_INVALIDATIONS,
                   {"reason": "bank-swap"})

    # CNP change: only 6000 allowed now — the hot memo must flip WITH
    # the swap, not serve rev-1 answers. The db identity's fingerprint
    # changed, so the invalidation is bank-scoped, not a full drop.
    per2, _, _ = _tiny_policy(6000)
    loader.regenerate(per2, revision=2)
    assert session_verdicts() == [2, 1] * 6 == engine_verdicts()
    assert replay.memo.invalidations >= inv0 + 1
    assert _metric(VERDICT_MEMO_INVALIDATIONS,
                   {"reason": "bank-swap"}) >= bsw0 + 1

    # mid-swap crash: rollback restores rev 2 — the session keeps
    # answering rev-2 semantics, never a torn state (a rollback is a
    # conservative FULL drop: reason=policy-swap)
    psw0 = _metric(VERDICT_MEMO_INVALIDATIONS,
                   {"reason": "policy-swap"})
    with faults.inject(FaultPlan([FaultRule("loader.swap", times=1)])):
        with pytest.raises(FaultInjected):
            loader.regenerate(per1, revision=3)
        assert loader.revision == 2
        assert session_verdicts() == [2, 1] * 6 == engine_verdicts()
    assert _metric(VERDICT_MEMO_INVALIDATIONS,
                   {"reason": "policy-swap"}) >= psw0 + 1

    # drain-style snapshot at rev 2, move on to rev 3, then warm
    # restore: the session must follow BACK to the restored revision
    assert loader.snapshot_warm() is True
    loader.regenerate(per1, revision=3)
    assert session_verdicts() == [1, 2] * 6 == engine_verdicts()
    assert loader.restore_warm() is True
    assert loader.revision == 2
    assert session_verdicts() == [2, 1] * 6 == engine_verdicts()


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_memo_golden_corpus_stable_across_cnp_change():
    """The acceptance replay for the verdict memo: the golden corpus
    replays through a memo-hot session, a policy re-commit lands
    mid-session, and the corpus verdicts are IDENTICAL before and
    after, matching the serving engine both times. Since ISSUE 8 the
    re-commit of a BYTE-IDENTICAL snapshot is a no-change delta: the
    memo must survive it UNTOUCHED (zero invalidations, hits keep
    accruing) — the churn-proof half of the staleness contract."""
    from cilium_tpu.agent import Agent
    from cilium_tpu.auth import AUTH_UNENFORCED
    from tests.test_controlplane_golden import build_agent, build_flows

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.configure_logging = False
    agent, ids = build_agent(Agent(cfg))
    try:
        flows = build_flows(ids)
        loader = agent.loader
        replay, cols = _memo_session(loader, cfg, flows)

        def session_verdicts():
            out = replay.verdict_chunk(
                cols.rec, cols.l7, authed_pairs=AUTH_UNENFORCED)
            return [int(v) for v in out["verdict"]]

        def engine_verdicts():
            return [int(v) for v in loader.engine.verdict_flows(
                flows, authed_pairs=AUTH_UNENFORCED)["verdict"]]

        before = session_verdicts()
        assert before == engine_verdicts()
        assert replay.memo is not None and replay.memo.hits > 0
        inv0 = replay.memo.invalidations
        hits0 = replay.memo.hits

        # the SAME snapshot re-commits under a new revision (identity
        # churn that netted out): a no-change delta — the memo keeps
        # serving, bit-identically, without a drop or a refill
        loader.regenerate(loader.per_identity,
                          revision=loader.revision + 1)
        after = session_verdicts()
        assert after == before, "memo served stale verdicts after swap"
        assert after == engine_verdicts()
        assert replay.memo.invalidations == inv0, \
            "no-change commit dropped the memo"
        assert replay.memo.hits > hits0
    finally:
        agent.stop()


# ---------------------------------------------------------------------------
# ISSUE 8: churn-proof policy plane — per-bank compile failure
# isolation, identity churn-storm delivery loss, and the warm-restart
# memo-retention regression.


def _paths_policy(paths):
    """_tiny_policy with an HTTP path allow-list (drives DFA banks)."""
    from cilium_tpu.core.identity import IdentityAllocator
    from cilium_tpu.core.labels import LabelSet
    from cilium_tpu.policy.api import (
        EndpointSelector,
        IngressRule,
        PortProtocol,
        PortRule,
        Rule,
    )
    from cilium_tpu.policy.api.l7 import L7Rules, PortRuleHTTP
    from cilium_tpu.policy.mapstate import PolicyResolver
    from cilium_tpu.policy.repository import Repository
    from cilium_tpu.policy.selectorcache import SelectorCache

    rules = [Rule(
        endpoint_selector=EndpointSelector.from_labels(app="db"),
        ingress=(IngressRule(
            from_endpoints=(EndpointSelector.from_labels(app="web"),),
            to_ports=(PortRule(
                ports=(PortProtocol(80, Protocol.TCP),),
                rules=L7Rules(http=tuple(
                    PortRuleHTTP(path=p, method="GET")
                    for p in paths))),)),),
    )]
    alloc = IdentityAllocator()
    db = alloc.allocate(LabelSet.from_dict({"app": "db"}))
    web = alloc.allocate(LabelSet.from_dict({"app": "web"}))
    cache = SelectorCache(alloc)
    repo = Repository()
    repo.add(rules, sanitize=False)
    return ({db: PolicyResolver(repo, cache).resolve(
        alloc.lookup(db))}, db, web)


def _http_flow(web, db, path):
    from cilium_tpu.core.flow import HTTPInfo, L7Type

    return Flow(src_identity=web, dst_identity=db, dport=80,
                protocol=Protocol.TCP,
                direction=TrafficDirection.INGRESS, l7=L7Type.HTTP,
                http=HTTPInfo(method="GET", path=path))


def test_bank_compile_fault_quarantines_only_its_bank(tmp_path):
    """loader.bank_compile fires on the one changed bank of a CNP
    add: the regeneration COMMITS (no abort, no rollback), every
    unchanged bank serves golden verdicts bit-identically, the
    quarantine is counted, and the TTL retry recovers the bank."""
    from cilium_tpu.runtime.metrics import BANK_QUARANTINED

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.engine.bank_size = 4
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    paths = [f"/p{i}/.*" for i in range(16)]
    per1, db, web = _paths_policy(paths)
    loader.regenerate(per1, revision=1)
    golden_flows = [_flow(web, db, 5432)] + \
        [_http_flow(web, db, f"/p{i}/x") for i in range(16)] + \
        [_http_flow(web, db, "/nope")]
    golden = [int(v) for v in
              loader.engine.verdict_flows(golden_flows)["verdict"]]
    rollbacks0 = _metric(LOADER_ROLLBACKS)
    q0 = _metric(BANK_QUARANTINED, {"field": "path"})

    per2, db, web = _paths_policy(paths + ["/fresh/.*"])
    with faults.inject(FaultPlan(
            [FaultRule("loader.bank_compile", times=1)])):
        loader.regenerate(per2, revision=2)  # commits despite the fault
    assert loader.revision == 2
    assert _metric(LOADER_ROLLBACKS) == rollbacks0, \
        "bank failure escalated to a full rollback"
    assert _metric(BANK_QUARANTINED, {"field": "path"}) == q0 + 1
    # unchanged banks: bit-identical golden verdicts
    after = [int(v) for v in
             loader.engine.verdict_flows(golden_flows)["verdict"]]
    assert after == golden
    # the failed bank's new pattern fails CLOSED while quarantined
    out = loader.engine.verdict_flows([_http_flow(web, db, "/fresh/x")])
    assert int(out["verdict"][0]) == 2
    # TTL retry: recompile succeeds, the new pattern enforces
    for q in loader.bank_registry._quarantine.values():
        q.until = 0.0
    loader.regenerate(per2, revision=3)
    out = loader.engine.verdict_flows([_http_flow(web, db, "/fresh/x")])
    assert int(out["verdict"][0]) == 5
    assert not loader._degraded


def test_compile_worker_death_retries_then_serves_correctly(tmp_path):
    """ISSUE 13: a compile.worker death mid-regeneration is absorbed
    by the queue's retry — the CNP add COMMITS, the new rule enforces,
    nothing quarantines, and the respawn counter moved."""
    from cilium_tpu.runtime.metrics import COMPILE_WORKER_DEATHS

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.engine.bank_size = 4
    cfg.compile.workers = 1
    cfg.compile.backoff_base_s = 0.01
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    paths = [f"/p{i}/.*" for i in range(8)]
    per1, db, web = _paths_policy(paths)
    loader.regenerate(per1, revision=1)
    deaths0 = _metric(COMPILE_WORKER_DEATHS)

    per2, db, web = _paths_policy(paths + ["/fresh/.*"])
    with faults.inject(FaultPlan(
            [FaultRule("compile.worker", times=1)])):
        loader.regenerate(per2, revision=2)
    assert loader.revision == 2
    assert _metric(COMPILE_WORKER_DEATHS) == deaths0 + 1
    assert not loader._degraded, \
        "a single worker death must be retried, not quarantined"
    out = loader.engine.verdict_flows([_http_flow(web, db, "/fresh/x")])
    assert int(out["verdict"][0]) == 5
    loader.close()


def test_compile_worker_death_exhaustion_quarantines_with_cover(
        tmp_path):
    """Retry budget exhausted by repeated worker deaths: the bank
    quarantines — its NEW pattern fails CLOSED, unchanged banks serve
    bit-identically — and the exhausted-fault recovery recompiles."""
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.engine.bank_size = 4
    cfg.compile.workers = 1
    cfg.compile.max_retries = 1
    cfg.compile.backoff_base_s = 0.01
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    paths = [f"/p{i}/.*" for i in range(8)]
    per1, db, web = _paths_policy(paths)
    loader.regenerate(per1, revision=1)
    golden_flows = [_http_flow(web, db, f"/p{i}/x") for i in range(8)]
    golden = [int(v) for v in
              loader.engine.verdict_flows(golden_flows)["verdict"]]

    per2, db, web = _paths_policy(paths + ["/fresh/.*"])
    with faults.inject(FaultPlan(
            [FaultRule("compile.worker", times=10)])):
        loader.regenerate(per2, revision=2)
    assert loader.revision == 2
    assert loader._degraded, "exhausted retries must quarantine"
    after = [int(v) for v in
             loader.engine.verdict_flows(golden_flows)["verdict"]]
    assert after == golden, "unchanged banks must serve bit-identically"
    out = loader.engine.verdict_flows([_http_flow(web, db, "/fresh/x")])
    assert int(out["verdict"][0]) == 2, "uncovered pattern fails CLOSED"
    # recovery: TTL lapse + regenerate with the fault exhausted
    for q in loader.bank_registry._quarantine.values():
        q.until = 0.0
    loader.regenerate(per2, revision=3)
    out = loader.engine.verdict_flows([_http_flow(web, db, "/fresh/x")])
    assert int(out["verdict"][0]) == 5
    assert not loader._degraded
    loader.close()


def test_artifact_fetch_fault_degrades_to_recompile_not_crash(
        tmp_path):
    """ISSUE 13: a lost/corrupt distributed bank artifact
    (artifact.fetch fires on a fresh loader sharing the cache dir)
    recompiles — verdicts identical, nothing quarantined, fetch
    corruption counted."""
    from cilium_tpu.runtime.metrics import BANK_ARTIFACT_FETCHES

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.engine.bank_size = 4
    cfg.loader.cache_dir = str(tmp_path / "cache")
    paths = [f"/p{i}/.*" for i in range(8)]
    per1, db, web = _paths_policy(paths)
    producer = Loader(cfg)
    producer.regenerate(per1, revision=1)
    golden_flows = [_http_flow(web, db, f"/p{i}/x") for i in range(8)]
    golden = [int(v) for v in
              producer.engine.verdict_flows(golden_flows)["verdict"]]
    producer.close()

    # a fresh "host" fetches bank artifacts instead of compiling —
    # and every fetch faults: the plane recompiles, never crashes.
    # (The whole-policy artifact is blinded so the per-bank path runs;
    # bank-artifact reads still reach the real cache.)
    consumer = Loader(cfg)
    consumer._cache.get = lambda key, _real=consumer._cache.get: (
        None if not key.startswith("bankart-") else _real(key))
    corrupt0 = _metric(BANK_ARTIFACT_FETCHES, {"result": "corrupt"})
    with faults.inject(FaultPlan(
            [FaultRule("artifact.fetch", prob=1.0, times=None)])):
        consumer.regenerate(per1, revision=1)
    assert _metric(BANK_ARTIFACT_FETCHES,
                   {"result": "corrupt"}) > corrupt0
    assert not consumer._degraded
    got = [int(v) for v in
           consumer.engine.verdict_flows(golden_flows)["verdict"]]
    assert got == golden
    consumer.close()


def test_corrupt_artifact_plus_compile_failure_quarantines_with_cover(
        tmp_path):
    """The combined ISSUE-13 outage: the distributed artifact is lost
    (artifact.fetch fires) AND the recompile fails (loader.bank_compile
    fires) — the bank must reach QUARANTINE-WITH-COVER: unchanged
    banks bit-identical, the uncovered pattern fails CLOSED, and the
    plane recovers once the faults exhaust and the TTL lapses."""
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.engine.bank_size = 4
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    paths = [f"/p{i}/.*" for i in range(8)]
    per1, db, web = _paths_policy(paths)
    loader.regenerate(per1, revision=1)
    golden_flows = [_http_flow(web, db, f"/p{i}/x") for i in range(8)]
    golden = [int(v) for v in
              loader.engine.verdict_flows(golden_flows)["verdict"]]

    per2, db, web = _paths_policy(paths + ["/fresh/.*"])
    with faults.inject(FaultPlan([
            FaultRule("artifact.fetch", times=8),
            FaultRule("loader.bank_compile", times=1)])):
        loader.regenerate(per2, revision=2)
    assert loader.revision == 2
    assert loader._degraded, "lost artifact + failed compile must " \
        "quarantine"
    after = [int(v) for v in
             loader.engine.verdict_flows(golden_flows)["verdict"]]
    assert after == golden
    out = loader.engine.verdict_flows([_http_flow(web, db, "/fresh/x")])
    assert int(out["verdict"][0]) == 2, "uncovered pattern fails CLOSED"
    for q in loader.bank_registry._quarantine.values():
        q.until = 0.0
    loader.regenerate(per2, revision=3)
    assert not loader._degraded
    out = loader.engine.verdict_flows([_http_flow(web, db, "/fresh/x")])
    assert int(out["verdict"][0]) == 5
    loader.close()


def test_fresh_loader_fetches_bank_artifacts_instead_of_compiling(
        tmp_path):
    """The distribution path itself: with a shared artifact cache, a
    restarted/remote loader serves the same policy with ZERO bank
    compiles (all groups fetched, checksum-verified)."""
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.engine.bank_size = 4
    cfg.loader.cache_dir = str(tmp_path / "cache")
    paths = [f"/p{i}/.*" for i in range(8)]
    per1, db, web = _paths_policy(paths)
    producer = Loader(cfg)
    producer.regenerate(per1, revision=1)
    assert producer.bank_registry.compiles > 0
    producer.close()

    consumer = Loader(cfg)
    # defeat the whole-policy artifact hit so the per-bank path runs
    consumer._cache.get = lambda key, _real=consumer._cache.get: (
        None if not key.startswith("bankart-") else _real(key))
    consumer.regenerate(per1, revision=1)
    assert consumer.bank_registry.compiles == 0, \
        "every bank should have been fetched, not compiled"
    assert consumer.bank_registry.artifact_hits > 0
    got = [int(v) for v in consumer.engine.verdict_flows(
        [_http_flow(web, db, "/p3/x")])["verdict"]]
    assert got == [5]
    consumer.close()


def test_kvstore_churn_storm_loses_deliveries_not_correctness():
    """kvstore.churn_storm drops identity add/delete deliveries on a
    watching allocator mid-burst: the dropped events are isolated and
    counted, the WRITER's own allocations (and the verdicts they
    drive) are untouched, and a fresh replay-then-follow converges to
    the store's true mapping."""
    from cilium_tpu.core.labels import LabelSet
    from cilium_tpu.identity_kvstore import ClusterIdentityAllocator
    from cilium_tpu.kvstore import KVStore

    store = KVStore()
    writer = ClusterIdentityAllocator(store).start()
    watcher_events = []
    watcher = ClusterIdentityAllocator(
        store, on_change=lambda nid, lbl: watcher_events.append(
            (int(nid), lbl))).start()

    fired0 = _metric(FAULTS_INJECTED, {"point": "kvstore.churn_storm"})
    errs0 = _metric(KVSTORE_WATCH_ERRORS)
    with faults.inject(FaultPlan(
            [FaultRule("kvstore.churn_storm", prob=0.4)], seed=11)):
        ids = [writer.allocate(LabelSet.from_dict({"app": f"a{i}"}))
               for i in range(24)]
    assert _metric(FAULTS_INJECTED,
                   {"point": "kvstore.churn_storm"}) > fired0
    assert _metric(KVSTORE_WATCH_ERRORS) > errs0
    # the writer itself is authoritative: every id resolves locally
    for i, nid in enumerate(ids):
        assert writer.lookup_by_labels(
            LabelSet.from_dict({"app": f"a{i}"})) == nid
    # the storm-hit watcher lost SOME deliveries but never corrupted:
    # everything it did see matches the writer's mapping
    for nid, lbl in watcher_events:
        if lbl is not None:
            assert writer.lookup_by_labels(lbl) == nid
    # a fresh replay-then-follow (restart after the storm) converges
    fresh = ClusterIdentityAllocator(store).start()
    for i, nid in enumerate(ids):
        assert fresh.lookup_by_labels(
            LabelSet.from_dict({"app": f"a{i}"})) == nid
    writer.close()
    watcher.close()
    fresh.close()


# ---------------------------------------------------------------------------
# ISSUE 16: serving-fleet fault points — heartbeat loss runs the
# suspicion clock down to a FAIL-CLOSED death, and an interrupted
# handoff never leaves a stream leased on two live hosts.


def _fleet_world(tmp_path, hosts=3):
    from cilium_tpu.ingest import synth
    from cilium_tpu.runtime.fleetserve import FleetRouter, HostReplica

    scenario = synth.scenario_by_name("http", 24, 64)
    per_identity, scenario = synth.realize_scenario(scenario)
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    replicas = [HostReplica(i, loader, capacity=8, lease_ttl_s=60.0,
                            pack_interval_s=0.01)
                for i in range(hosts)]
    router = FleetRouter(replicas, heartbeat_interval_s=1.0,
                         suspicion_ttl_s=3.0, spill_headroom=0.0)
    return router, loader, scenario


def test_fleet_heartbeat_loss_suspicion_is_fail_closed(tmp_path):
    """Armed fleet.heartbeat fires eat every replica's beats: once
    the suspicion TTL lapses, the sweep declares them dead (counted),
    every lease closes (exact books), new admits shed coherently, and
    a submit against a dead placement is the TYPED resume error —
    never fail-open service from a host nobody has heard from."""
    from cilium_tpu.runtime.fleetserve import HostDead
    from cilium_tpu.runtime.metrics import FLEET_HOST_DEATHS
    from cilium_tpu.runtime.serveloop import ShedError

    clk = simclock.VirtualClock()
    with simclock.use(clk):
        router, loader, _ = _fleet_world(tmp_path)
        leases = {}
        for k in range(6):
            _host, lease = router.connect(f"s{k}")
            leases[f"s{k}"] = lease
        deaths0 = _metric(FLEET_HOST_DEATHS)
        with faults.inject(FaultPlan(
                [FaultRule("fleet.heartbeat", times=9)], seed=0)):
            died = []
            for dt in (1.0, 1.0, 1.1):  # 3 beat rounds, all lost
                clk.advance(dt)
                died += router.beat()
        assert sorted(died) == sorted(r.name for r in router.replicas)
        assert _metric(FLEET_HOST_DEATHS) == deaths0 + 3
        # fail-closed: no live host → a coherent explicit shed
        with pytest.raises(ShedError):
            router.connect("fresh")
        # a dead placement is the typed resume path, never stream-fatal
        with pytest.raises(HostDead):
            router.submit("s0", leases["s0"], None)
        assert router.books() == (0, 0)
        assert router.conservation_violation() is None
        # warm rejoin: resume re-grants exactly once, books exact
        for r in router.replicas:
            router.rejoin(r.name)
        router.connect("s0", resume=True)
        assert router.books() == (1, 1)
        assert router.conservation_violation() is None


def test_fleet_handoff_interrupt_conserves_leases(tmp_path):
    """A fleet.handoff fire interrupts the dead host's lease
    migration mid-batch: the un-re-granted remainder stays UNPLACED
    (client-resume territory) — at no instant does any stream hold
    leases on two live hosts, and the fleet books stay exact through
    the interrupt and through every later resume."""
    clk = simclock.VirtualClock()
    with simclock.use(clk):
        router, loader, _ = _fleet_world(tmp_path)
        streams = [f"h{k}" for k in range(9)]
        for s in streams:
            router.connect(s)
        counts = {}
        for s in streams:
            host = router.placements[s]
            counts[host] = counts.get(host, 0) + 1
        victim = max(counts, key=lambda h: counts[h])
        doomed = counts[victim]
        assert doomed >= 2  # the interrupt needs a batch to cut
        with faults.inject(FaultPlan(
                [FaultRule("fleet.handoff", times=1)], seed=0)):
            router.kill(victim)
        assert router.partial_handoffs == 1
        assert router.handoffs == 0  # the fire cut the whole batch
        assert router.conservation_violation() is None
        bal, occ = router.books()
        assert bal == occ
        # every stream resumes somewhere LIVE, still without a dup
        for s in streams:
            host, _lease = router.connect(s, resume=True)
            assert host != victim
        assert router.conservation_violation() is None
        assert router.books() == (len(streams), len(streams))


def test_warm_restore_same_artifact_keeps_memo(tmp_path):
    """ISSUE-8 satellite regression: a drain → warm-restore cycle
    whose artifact key is UNCHANGED must not drop the device memo or
    the unique-row buffer — the restarted service keeps its memo hit
    ratio instead of re-verdicting the whole row universe."""
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    per1, db, web = _tiny_policy(5432)
    loader.regenerate(per1, revision=1)
    flows = [_flow(web, db, 5432), _flow(web, db, 6000)] * 8

    replay, cols = _memo_session(loader, cfg, flows)
    out = replay.verdict_chunk(cols.rec, cols.l7)
    golden = [int(v) for v in out["verdict"]]
    memo = replay.memo
    assert memo is not None and memo.hits > 0
    inv0 = memo.invalidations
    misses0 = memo.misses
    hits0 = memo.hits
    uniq_buf = replay.unique_rows
    assert uniq_buf is not None

    # drain-style snapshot, then an immediate warm restore (process
    # kept, artifact unchanged — the warm-restart fast path)
    assert loader.snapshot_warm() is True
    assert loader.restore_warm() is True
    after = replay.verdict_chunk(cols.rec, cols.l7)
    assert [int(v) for v in after["verdict"]] == golden
    assert memo.invalidations == inv0, \
        "same-key warm restore dropped the memo"
    assert memo.misses == misses0, "memo refilled after warm restore"
    assert memo.hits > hits0
    assert replay.unique_rows is uniq_buf, \
        "unique-row device buffer was re-staged"


# ---------------------------------------------------------------------------
# ISSUE 20: tenant.quota + canary.dispatch fault points


def test_tenant_quota_fault_falls_to_conservative_default():
    """A LOST quota read (tenant.quota fires) must return the
    conservative default share — bounded, never unbounded — counted
    ``fault-default``; once the fault exhausts, the live entry serves
    again and a lapsed TTL reads as the default too."""
    from cilium_tpu.runtime.metrics import TENANT_QUOTA_READS
    from cilium_tpu.runtime.tenant import TenantQuotas

    now = [0.0]
    quotas = TenantQuotas(default_share=0.25, ttl_s=10.0,
                          clock=lambda: now[0])
    quotas.set_share("a", 0.9)
    fd0 = _metric(TENANT_QUOTA_READS, {"result": "fault-default"})
    live0 = _metric(TENANT_QUOTA_READS, {"result": "live"})
    with faults.inject(FaultPlan([FaultRule("tenant.quota", times=1)])):
        assert quotas.share_of("a") == 0.25, \
            "faulted quota read must be the conservative default"
        assert quotas.share_of("a") == 0.9, \
            "after the fault exhausts the live entry serves"
    assert _metric(TENANT_QUOTA_READS,
                   {"result": "fault-default"}) == fd0 + 1
    assert _metric(TENANT_QUOTA_READS, {"result": "live"}) == live0 + 1
    # TTL lapse at EXACTLY the tick (closed boundary) → default
    now[0] = 10.0
    assert quotas.share_of("a") == 0.25


def test_canary_dispatch_fault_aborts_canary_serving_untouched():
    """A failed shadow dispatch (canary.dispatch fires) must ABORT the
    canary — staged generation dropped, serving generation untouched,
    commit refused as aborted — never crash the serve path."""
    from cilium_tpu.core.flow import Verdict
    from cilium_tpu.runtime.canary import (
        STATE_ABORTED,
        CanaryController,
    )
    from cilium_tpu.runtime.metrics import CANARY_COMMITS

    cfg = Config()
    cfg.enable_tpu_offload = True
    loader = Loader(cfg)
    per1, db, web = _tiny_policy(5432)
    loader.regenerate(per1, revision=1)
    flows = [_flow(web, db, 5432), _flow(web, db, 6000)]
    served = [int(v) for v in
              loader.engine.verdict_flows(flows)["verdict"]]

    canary = CanaryController(loader, sample_fraction=1.0,
                              diff_budget=0.0, min_samples=1)
    canary.stage(per1, revision=2)
    ab0 = _metric(CANARY_COMMITS, {"result": "aborted"})
    with faults.inject(FaultPlan([FaultRule("canary.dispatch",
                                            times=1)])):
        canary.observe_chunk(flows, served)  # must not raise
    assert canary.state == STATE_ABORTED
    assert loader.canary_engine is None, "staged generation dropped"
    assert loader.revision == 1, "serving generation untouched"
    assert _metric(CANARY_COMMITS, {"result": "aborted"}) == ab0 + 1
    after = [int(v) for v in
             loader.engine.verdict_flows(flows)["verdict"]]
    assert after == served
    assert Verdict(after[0]) is not None  # decodable, not ERROR junk
    loader.close()
