"""policy/compiler/compilequeue.py: the fleet-scale bank-compile work
queue — priority classes, work-key dedup, worker-death retry with
backoff, deadline lapse, bounded in-flight, drain — plus its
integration with the sharded BankRegistry (pending→cover, late
results, artifact fetch, TTL escalation)."""

import threading
import time

import pytest

from cilium_tpu.core.config import EngineConfig
from cilium_tpu.policy.compiler.bankplan import (
    BankRegistry,
    bank_key,
    partition_patterns,
    registry_shard_of,
)
from cilium_tpu.policy.compiler.compilequeue import (
    PRIO_BACKGROUND,
    PRIO_SERVING,
    CompileQueue,
    QueueDraining,
    WorkerDied,
    work_key,
)
from cilium_tpu.runtime import faults, simclock
from cilium_tpu.runtime.checkpoint import (
    ArtifactCache,
    BankArtifactStore,
)


def _cfg(bank_size=4):
    cfg = EngineConfig()
    cfg.bank_size = bank_size
    return cfg


def _queue(**kw):
    kw.setdefault("workers", 2)
    kw.setdefault("deadline_s", 5.0)
    return CompileQueue(**kw)


# ---------------------------------------------------------------------------
# queue mechanics


def test_submit_wait_roundtrip():
    q = _queue()
    try:
        t = q.submit("k1", lambda: 41 + 1)
        assert q.wait(t, timeout=10.0)
        assert t.error is None and t.result == 42
    finally:
        q.close()


def test_work_key_dedup_single_execution():
    """N racing submitters of one content key → ONE execution; every
    waiter observes the one result."""
    q = _queue(workers=4)
    runs = []
    done = threading.Barrier(9)
    tasks = []
    lock = threading.Lock()

    def fn():
        runs.append(1)
        time.sleep(0.05)          # hold the task in flight
        return "compiled"

    def submitter():
        done.wait()
        t = q.submit("hot", fn)
        with lock:
            tasks.append(t)

    threads = [threading.Thread(target=submitter) for _ in range(8)]
    for t in threads:
        t.start()
    done.wait()
    for t in threads:
        t.join(timeout=10)
    try:
        assert len(tasks) == 8
        for t in tasks:
            assert q.wait(t, timeout=10.0) and t.result == "compiled"
        assert len(runs) == 1, "dedup failed: same key ran twice"
        assert q.dedup_hits == 7
    finally:
        q.close()


def test_priority_serving_pops_before_background():
    """With one worker held busy, a serving task submitted AFTER a
    pile of background tasks still runs before them."""
    order = []
    gate = threading.Event()
    q = _queue(workers=1)
    try:
        q.submit("hold", lambda: (gate.wait(5), order.append("hold")))
        for i in range(3):
            q.submit(f"bg{i}", (lambda i=i: order.append(f"bg{i}")),
                     prio=PRIO_BACKGROUND)
        ts = q.submit("urgent", lambda: order.append("serving"),
                      prio=PRIO_SERVING)
        gate.set()
        assert q.wait(ts, timeout=10.0)
        assert order.index("serving") == 1, order   # right after hold
    finally:
        q.close()


def test_worker_death_retries_then_succeeds_and_respawns():
    """An armed compile.worker fault kills the worker mid-task: the
    task re-queues with backoff and succeeds on retry; the pool
    respawned (the next task still runs)."""
    q = _queue(workers=1, backoff_base_s=0.01)
    try:
        with faults.inject(faults.FaultPlan(
                [faults.FaultRule("compile.worker", times=1)])):
            t = q.submit("k", lambda: "ok")
            assert q.wait(t, timeout=10.0)
            assert t.error is None and t.result == "ok"
            assert q.worker_deaths == 1 and q.retries == 1
            t2 = q.submit("k2", lambda: "still alive")
            assert q.wait(t2, timeout=10.0) and t2.result == "still alive"
    finally:
        q.close()


def test_worker_death_exhaustion_fails_task():
    q = _queue(workers=1, max_retries=2, backoff_base_s=0.01)
    try:
        with faults.inject(faults.FaultPlan(
                [faults.FaultRule("compile.worker", times=10)])):
            t = q.submit("doomed", lambda: "never")
            assert q.wait(t, timeout=10.0)
            assert isinstance(t.error, WorkerDied)
    finally:
        q.close()


def test_compile_exception_fails_immediately_no_retry():
    q = _queue(workers=1)
    try:
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("bad pattern")

        t = q.submit("bad", bad)
        assert q.wait(t, timeout=10.0)
        assert isinstance(t.error, ValueError)
        assert len(calls) == 1, "deterministic failure was retried"
        assert q.retries == 0
    finally:
        q.close()


def test_deadline_lapse_under_virtual_time_exact_tick():
    """A compile still in flight at EXACTLY the deadline tick lapses
    the waiter (cover serves); the late completion is stored and
    counted."""
    clock = simclock.VirtualClock()
    with simclock.use(clock):
        q = CompileQueue(workers=1, deadline_s=10.0)
        release = threading.Event()

        def slow():
            release.wait(5.0)     # real wait: worker busy, no virtual
            return "late"

        t = q.submit("slow", slow)
        waiter_done = []

        def waiter():
            waiter_done.append(q.wait(t))

        th = threading.Thread(target=waiter)
        th.start()
        deadline = t.deadline
        for _ in range(200):      # the waiter must park first
            if clock._heap:
                break
            time.sleep(0.005)
        clock.advance_to(deadline)           # the EXACT tick
        th.join(timeout=5.0)
        assert waiter_done == [False], "exact-tick deadline must lapse"
        assert q.deadline_lapses == 1
        release.set()
        for _ in range(400):
            if t.done:
                break
            time.sleep(0.005)
        assert t.done and t.result == "late"
        assert q.late_results == 1
        q.close()


def test_bounded_pending_blocks_producer():
    q = CompileQueue(workers=1, max_pending=2, deadline_s=5.0)
    gate = threading.Event()
    try:
        q.submit("a", lambda: gate.wait(5))
        q.submit("b", lambda: None)
        state = {"submitted": False}

        def third():
            q.submit("c", lambda: None)
            state["submitted"] = True

        th = threading.Thread(target=third)
        th.start()
        time.sleep(0.1)
        assert not state["submitted"], \
            "submit did not block at max_pending"
        gate.set()
        th.join(timeout=5.0)
        assert state["submitted"]
    finally:
        q.close()


def test_drain_while_compiling_finishes_inflight_then_refuses():
    """The drain-while-compiling boundary: a task running at drain
    time completes and its result lands; new submits refuse."""
    q = _queue(workers=1)
    gate = threading.Event()
    t = q.submit("inflight", lambda: (gate.wait(5), "done")[1])
    th = threading.Thread(target=lambda: q.drain(timeout=30.0))
    th.start()
    time.sleep(0.05)
    gate.set()
    th.join(timeout=10.0)
    assert t.done and t.result == "done"
    with pytest.raises(QueueDraining):
        q.submit("new", lambda: None)
    q.close()


def test_close_fails_pending_tasks_loudly():
    q = _queue(workers=1)
    gate = threading.Event()
    q.submit("hold", lambda: gate.wait(5))
    t = q.submit("queued", lambda: "never ran")
    q.close()
    gate.set()
    assert q.wait(t, timeout=5.0)
    assert isinstance(t.error, QueueDraining)


# ---------------------------------------------------------------------------
# registry integration


def test_registry_queue_path_matches_serial_path():
    """The queued compile_field output is bit-identical to the serial
    registry's (same banks, same stats shape)."""
    import numpy as np

    pats = [f"/api/v{i}/.*" for i in range(24)]
    cfg = _cfg()
    serial = BankRegistry()
    q = CompileQueue(workers=3, deadline_s=30.0)
    queued = BankRegistry(queue=q)
    try:
        b1, s1 = serial.compile_field("path", pats, cfg)
        b2, s2 = queued.compile_field("path", pats, cfg)
        assert s1.bank_keys == s2.bank_keys
        assert set(s1.rebuilt) == set(s2.rebuilt)
        assert np.array_equal(b1.pattern_bank, b2.pattern_bank)
        assert np.array_equal(b1.pattern_lane, b2.pattern_lane)
        for x, y in zip(b1.banks, b2.banks):
            assert np.array_equal(x.trans, y.trans)
            assert np.array_equal(x.accept, y.accept)
        # reuse on the second build
        _, s3 = queued.compile_field("path", pats, cfg)
        assert s3.rebuilt == () and s3.reused == len(s3.bank_keys)
    finally:
        queued.close()


def test_registry_worker_death_exhaustion_quarantines_with_cover():
    """compile.worker deaths past the retry budget fail the bank into
    quarantine: the PREVIOUS cover serves its patterns, new patterns
    fail closed, and the registry is not degraded after recovery."""
    pats = [f"/svc/p{i}/.*" for i in range(8)]
    cfg = _cfg()
    q = CompileQueue(workers=1, max_retries=1, backoff_base_s=0.01)
    reg = BankRegistry(queue=q)
    try:
        _, s0 = reg.compile_field("path", pats, cfg)
        assert not s0.quarantined
        grown = pats + ["/svc/new/.*"]
        with faults.inject(faults.FaultPlan(
                [faults.FaultRule("compile.worker", times=10)])):
            banked, s1 = reg.compile_field("path", grown, cfg)
        assert s1.quarantined, "exhausted retries must quarantine"
        assert reg._quarantine, "TTL stamp missing"
        # every pattern still has a lane (cover or dead bank)
        assert len(banked.patterns) == len(grown)
        # recovery: expire the TTL, recompile cleanly
        for qq in reg._quarantine.values():
            qq.until = 0.0
        _, s2 = reg.compile_field("path", grown, cfg)
        assert not s2.quarantined and not reg._quarantine
    finally:
        reg.close()


def test_registry_ttl_escalates_on_repeated_failures():
    clock = [1000.0]
    reg = BankRegistry(quarantine_ttl_s=10.0, clock=lambda: clock[0])
    cfg = _cfg()
    pats = ["/a/.*", "/b/.*"]
    with faults.inject(faults.FaultPlan(
            [faults.FaultRule("loader.bank_compile", times=99)])):
        reg.compile_field("path", pats, cfg)
        (key, q1), = [(k, q.until - clock[0])
                      for k, q in reg._quarantine.items()]
        assert q1 == pytest.approx(10.0)       # first failure: exact
        clock[0] += 11.0
        reg.compile_field("path", pats, cfg)
        q2 = reg._quarantine[key].until - clock[0]
        assert q2 > 15.0, "repeated failure did not escalate the TTL"
        clock[0] += q2 + 1.0
        reg.compile_field("path", pats, cfg)
        q3 = reg._quarantine[key].until - clock[0]
        assert q3 > q2 * 1.5, "TTL did not keep escalating"


def test_background_kick_rebuilds_expired_quarantine():
    clock = [0.0]
    q = CompileQueue(workers=1, backoff_base_s=0.01)
    reg = BankRegistry(quarantine_ttl_s=5.0, clock=lambda: clock[0],
                       queue=q)
    cfg = _cfg()
    pats = [f"/k{i}/.*" for i in range(4)]
    try:
        with faults.inject(faults.FaultPlan(
                [faults.FaultRule("loader.bank_compile", times=1)])):
            _, s = reg.compile_field("path", pats, cfg)
        assert s.quarantined
        assert reg.kick_expired_rebuilds() == 0     # TTL not lapsed
        clock[0] += 6.0
        n = reg.kick_expired_rebuilds()
        assert n == 1
        for _ in range(400):
            if not reg._quarantine:
                break
            time.sleep(0.005)
        assert not reg._quarantine, \
            "background rebuild did not clear the quarantine"
        _, s2 = reg.compile_field("path", pats, cfg)
        assert not s2.quarantined and s2.rebuilt == ()
    finally:
        reg.close()


# ---------------------------------------------------------------------------
# artifact distribution


def test_artifact_fetch_skips_compile_and_verifies_checksum(tmp_path):
    cfg = _cfg()
    pats = [f"/art/{i}/.*" for i in range(6)]
    cache = ArtifactCache(str(tmp_path))
    store = BankArtifactStore(cache)
    producer = BankRegistry(artifacts=store)
    producer.compile_field("path", pats, cfg)
    assert producer.compiles > 0

    consumer = BankRegistry(artifacts=store)
    _, s = consumer.compile_field("path", pats, cfg)
    assert consumer.compiles == 0, "artifact fetch should skip compile"
    assert consumer.artifact_hits == len(s.bank_keys)
    assert set(s.fetched) == set(s.bank_keys)


def test_corrupt_artifact_degrades_to_recompile_counted(tmp_path):
    import os

    from cilium_tpu.runtime.metrics import BANK_ARTIFACT_FETCHES, METRICS

    cfg = _cfg()
    pats = ["/c1/.*", "/c2/.*"]
    cache = ArtifactCache(str(tmp_path))
    store = BankArtifactStore(cache)
    producer = BankRegistry(artifacts=store)
    producer.compile_field("path", pats, cfg)
    # flip payload bytes INSIDE every bank artifact (outer pickle
    # stays valid — only the checksum can catch this): never a crash,
    # never a silently wrong bank
    import pickle

    for name in os.listdir(str(tmp_path)):
        if name.startswith("bankart-"):
            p = str(tmp_path / name)
            entry = pickle.load(open(p, "rb"))
            payload = bytearray(entry["payload"])
            payload[len(payload) // 2] ^= 0xFF
            entry["payload"] = bytes(payload)
            pickle.dump(entry, open(p, "wb"))
    corrupt0 = METRICS._counters.get(
        (BANK_ARTIFACT_FETCHES, (("result", "corrupt"),)), 0)
    consumer = BankRegistry(artifacts=store)
    _, s = consumer.compile_field("path", pats, cfg)
    assert not s.fetched and consumer.compiles > 0
    assert not s.quarantined
    corrupt1 = METRICS._counters.get(
        (BANK_ARTIFACT_FETCHES, (("result", "corrupt"),)), 0)
    assert corrupt1 > corrupt0


def test_artifact_fetch_fault_point_degrades_to_recompile(tmp_path):
    cfg = _cfg()
    pats = ["/f1/.*"]
    cache = ArtifactCache(str(tmp_path))
    store = BankArtifactStore(cache)
    producer = BankRegistry(artifacts=store)
    producer.compile_field("path", pats, cfg)
    consumer = BankRegistry(artifacts=store)
    with faults.inject(faults.FaultPlan(
            [faults.FaultRule("artifact.fetch", times=1)])):
        _, s = consumer.compile_field("path", pats, cfg)
    assert not s.fetched and consumer.compiles > 0
    assert not s.quarantined


# ---------------------------------------------------------------------------
# sharding


def test_registry_shards_bound_bytes_and_evict():
    cfg = _cfg(bank_size=2)
    reg = BankRegistry(shards=4, max_bytes=64 << 10, max_groups=64)
    pats = [f"/evict/{i}/seg{i % 7}/.*" for i in range(48)]
    reg.compile_field("path", pats, cfg)
    assert reg.bytes <= 64 << 10 + 4096
    # shard placement is a pure function of the key
    for key in list(reg._quarantine) or []:
        assert 0 <= registry_shard_of(key, 4) < 4


def test_shard_of_is_stable_and_spread():
    cfg = _cfg()
    opts = (cfg.max_dfa_states, cfg.max_quantifier, False)
    keys = [bank_key(g, opts)
            for g in partition_patterns(
                [f"/spread/{i}/.*" for i in range(64)], 4)]
    shards = {registry_shard_of(k, 8) for k in keys}
    assert len(shards) > 1, "shard function collapsed"
    assert all(registry_shard_of(k, 8) == registry_shard_of(k, 8)
               for k in keys)


def test_work_key_is_pure_function_of_bank_key():
    assert work_key("abc") == work_key("abc")
    assert work_key("abc") != work_key("abd")


# ---------------------------------------------------------------------------
# per-tenant weighted-fair queueing + occupancy bound (ISSUE 20)


def test_wfq_claims_follow_tenant_virtual_time():
    """Single worker, a plugged head, then a backlog of two tenants
    with weights 2:1 — the claim order is the deterministic WFQ walk
    (lowest virtual finish time, 1/weight charged per claim, ties on
    tenant name then submit order), NOT pure submit order."""
    release = threading.Event()
    order = []
    q = CompileQueue(workers=1, deadline_s=30.0, max_pending=32,
                     weight_of=lambda t: 2.0 if t == "big" else 1.0)
    try:
        def mk(name):
            def fn():
                order.append(name)
                return name
            return fn

        plug = q.submit("plug", lambda: release.wait(10.0))
        # the worker is busy in the plug: the backlog queues untouched
        tasks = []
        for i in range(3):
            tasks.append(q.submit(f"big-{i}", mk(f"big-{i}"),
                                  tenant="big"))
            tasks.append(q.submit(f"small-{i}", mk(f"small-{i}"),
                                  tenant="small"))
        release.set()
        assert q.wait(plug, timeout=10.0)
        for t in tasks:
            assert q.wait(t, timeout=10.0)
        # vtime walk: big charges 0.5/claim, small 1.0/claim; ties
        # break on tenant name — byte-deterministic, pinned exactly
        assert order == ["big-0", "small-0", "big-1", "big-2",
                         "small-1", "small-2"]
    finally:
        q.close()


def test_tenant_occupancy_bound_blocks_only_the_storming_tenant():
    """Tenant a at its occupancy cap (tenant_max_share × max_pending
    live tasks) blocks a's NEXT submit — while tenant b's submit
    sails through the same queue at the same moment."""
    release = threading.Event()
    q = CompileQueue(workers=1, deadline_s=30.0, max_pending=4,
                     tenant_max_share=0.5)      # a's cap: 2 live
    try:
        a0 = q.submit("a-0", lambda: release.wait(10.0), tenant="a")
        a1 = q.submit("a-1", lambda: "a1", tenant="a")
        assert q.status()["tenant_inflight"] == {"a": 2}

        entered = threading.Event()
        unblocked = threading.Event()

        def storm():
            entered.set()
            q.submit("a-2", lambda: "a2", tenant="a")
            unblocked.set()

        th = threading.Thread(target=storm, daemon=True)
        th.start()
        assert entered.wait(5.0)
        # a is at its bound: the submit parks instead of returning
        assert not unblocked.wait(0.6)
        # b is untouched by a's storm: same queue, instant admission
        b0 = q.submit("b-0", lambda: "b0", tenant="b")
        assert q.status()["tenant_inflight"]["b"] == 1
        # capacity frees → ONLY then does a's parked submit return
        release.set()
        assert unblocked.wait(10.0)
        th.join(10.0)
        for t in (a0, a1, b0):
            assert q.wait(t, timeout=10.0)
        assert q.wait(q.submit("a-2", lambda: "a2", tenant="a"),
                      timeout=10.0)
    finally:
        q.close()
