"""Timing boundary cases the schedule searcher is blind to without
explicit pins (ISSUE 10 satellite): deadline lapse at the exact tick,
quarantine TTL expiry racing a regeneration, breaker half-open under
concurrent probes, and a credit grant landing during reconnect. All
under virtual time — the boundaries are EXACT, not sleep-approximate.

ISSUE 11 adds the slot-lease boundaries of the continuously-batched
serving loop (runtime/serveloop.py): lease expiry racing a drain,
lease grant during reconnect-with-resume never double-counted, and
ring-full admission shedding with an explicit reason.
"""

import threading

import pytest

from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.simclock import VirtualClock


def _serve_world(tmp_path, capacity=2, ttl=10.0):
    """A tiny real serving slice: compiled policy → ServeLoop, driven
    inline (no thread) so every boundary is an exact virtual tick."""
    from cilium_tpu.core.config import Config
    from cilium_tpu.ingest import synth
    from cilium_tpu.ingest.binary import (
        capture_from_bytes,
        capture_to_bytes,
    )
    from cilium_tpu.runtime.loader import Loader
    from cilium_tpu.runtime.serveloop import ServeLoop

    scenario = synth.scenario_by_name("http", 12, 64)
    per_identity, scenario = synth.realize_scenario(scenario)
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    sections = capture_from_bytes(
        capture_to_bytes(scenario.flows[:16]))
    loop = ServeLoop(loader, capacity=capacity, lease_ttl_s=ttl,
                     pack_interval_s=0.01)
    return loop, sections


# ---------------------------------------------------------------------------
# 1) deadline lapse at the exact tick


def test_admission_deadline_at_the_exact_tick_sheds():
    """A request whose deadline equals now() EXACTLY has zero budget:
    the gate sheds it (reason deadline) — `remaining <= 0` — and one
    virtual tick earlier it admits. The boundary is pinned closed."""
    from cilium_tpu.runtime.admission import (
        AdmissionGate,
        SHED_DEADLINE,
    )

    clk = VirtualClock(start=50.0)
    with simclock.use(clk):
        gate = AdmissionGate(max_pending=8, depth_fn=lambda: 0)
        ok, reason = gate.admit(deadline=clk.now())       # exact tick
        assert (ok, reason) == (False, SHED_DEADLINE)
        ok, _ = gate.admit(deadline=clk.now() + 1e-6)     # one tick in
        assert ok


def test_microbatcher_reaps_an_entry_expiring_at_the_exact_tick():
    """An entry whose deadline == now at dispatch is reaped (deadline
    <= now), never spent a batch slot on; one whose deadline is one
    tick later dispatches."""
    from cilium_tpu.core.flow import Flow, Verdict
    from cilium_tpu.runtime.service import MicroBatcher, _Pending

    clk = VirtualClock(start=10.0)
    with simclock.use(clk):
        served = []
        mb = MicroBatcher(lambda flows: served.append(len(flows))
                          or [int(Verdict.FORWARDED)] * len(flows),
                          batch_max=4, deadline_ms=1.0)
        exact = _Pending(Flow(), clk.now(), None)          # lapses NOW
        live = _Pending(Flow(), clk.now() + 1e-6, None)
        out = mb._reap([exact, live])
        assert out == [live]
        assert exact.box == [int(Verdict.ERROR)]
        assert exact.ev.is_set()
        mb.close()


# ---------------------------------------------------------------------------
# 2) quarantine TTL expiry racing a regeneration


def test_quarantine_ttl_expiry_races_regeneration():
    """A regeneration that starts at EXACTLY the quarantine TTL tick
    retries the bank (now >= until); one tick earlier it must keep
    serving the stale cover without a retry compile. Either way the
    pattern set served is consistent — the boundary changes WHEN the
    retry happens, never correctness."""
    from cilium_tpu.core.config import EngineConfig
    from cilium_tpu.policy.compiler.bankplan import BankRegistry
    from cilium_tpu.runtime import faults
    from cilium_tpu.runtime.faults import FaultPlan, FaultRule

    clk = VirtualClock()
    with simclock.use(clk):
        reg = BankRegistry(quarantine_ttl_s=30.0)
        cfg = EngineConfig(bank_size=2)
        pats = ["/a/.*", "/b/.*", "/c/.*", "/d/.*"]
        reg.compile_field("path", pats, cfg)        # healthy baseline
        with faults.inject(FaultPlan(
                [FaultRule("loader.bank_compile", times=1)])):
            _, stats = reg.compile_field("path", pats + ["/e/.*"],
                                         cfg)
        assert stats.quarantined, "fault must quarantine a group"
        quarantined = set(stats.quarantined)
        compiles_q = reg.compiles

        # one tick BEFORE expiry: stale cover keeps serving, no retry
        clk.advance(30.0 - 1e-3)
        assert reg.expired_quarantines() == ()
        _, stats2 = reg.compile_field("path", pats + ["/e/.*"], cfg)
        assert set(stats2.quarantined) == quarantined
        assert reg.compiles == compiles_q   # no retry compile yet

        # AT the expiry tick: the next regeneration retries + recovers
        clk.advance(1e-3)
        assert set(reg.expired_quarantines()) == quarantined
        _, stats3 = reg.compile_field("path", pats + ["/e/.*"], cfg)
        assert not stats3.quarantined
        assert reg.compiles > compiles_q    # the retry compiled


# ---------------------------------------------------------------------------
# 3) breaker half-open with concurrent probes


def test_breaker_half_open_admits_exactly_one_concurrent_probe():
    """N threads hit allow_primary at the exact probe-interval tick:
    EXACTLY one becomes the half-open probe; the rest keep falling
    back (a thundering herd onto a sick device would defeat the
    probe). A failed probe re-arms the timer at the failure instant."""
    from cilium_tpu.runtime.service import CircuitBreaker

    clk = VirtualClock()
    with simclock.use(clk):
        br = CircuitBreaker(failure_threshold=1, probe_interval=5.0)
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        clk.advance(5.0)                     # exactly the interval
        results = []
        lock = threading.Lock()
        start = threading.Barrier(8)

        def prober():
            start.wait()
            got = br.allow_primary()
            with lock:
                results.append(got)

        ts = [threading.Thread(target=prober) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=5.0)
        assert results.count(True) == 1, results
        assert br.state == CircuitBreaker.HALF_OPEN
        # failed probe: OPEN again, timer re-armed from NOW — one tick
        # shy of the new interval stays closed to probes
        br.record_failure()                  # re-armed at now=5.0
        clk.advance_to(10.0 - 1e-6)
        assert not br.allow_primary()
        clk.advance_to(10.0)                 # exactly interval later
        assert br.allow_primary()


# ---------------------------------------------------------------------------
# 4) credit grant arriving during reconnect


def test_credit_grant_arriving_during_reconnect_is_not_lost():
    """The client's credit window is rebuilt from the re-handshake
    minus re-sent unacked chunks; a grant that lands immediately after
    (the server answering a resumed chunk) must ADD to that window —
    the reconnect must never double-count or drop it. Pure client-side
    state-machine check, driven through the same lock/condition the
    recv loop uses."""
    from cilium_tpu.runtime.stream import StreamClient

    clk = VirtualClock()
    with simclock.use(clk):
        client = StreamClient.__new__(StreamClient)   # no socket I/O
        client._lock = threading.Lock()
        client._cond = threading.Condition(client._lock)
        client.timeout = 5.0
        client._done = False
        client._credit_window = 4
        client._credits = 0                 # exhausted pre-drop
        client._unacked = {7: ("", b"img7"), 8: ("", b"img8")}
        # reconnect path: fresh window minus the 2 re-sent chunks
        with client._cond:
            client._credits = max(
                0, client._credit_window - len(client._unacked))
        assert client._credits == 2
        # the resumed session answers seq 7 AND grants a credit — the
        # recv-loop bookkeeping for a grant frame during resume:
        with client._cond:
            client._credits += 1
            client._cond.notify_all()
        with client._cond:
            client._unacked.pop(7)
        assert client._credits == 3
        # a sender blocked at zero credit wakes on the grant: window
        # accounting and the wait predicate agree
        client._acquire_credit()
        assert client._credits == 2


def test_lease_expiry_racing_a_drain_loses_no_verdict(tmp_path):
    """A lease that expires at EXACTLY the drain tick: drain packs
    pending chunks BEFORE releasing leases, so the chunk still gets a
    real verdict; the slot is released exactly once (as drained, not
    double-counted as expired), and the books stay exact."""
    clk = VirtualClock()
    with simclock.use(clk):
        loop, sections = _serve_world(tmp_path, ttl=10.0)
        lease = loop.connect("s0")
        ticket = loop.submit(lease, *sections)
        # advance to EXACTLY the lease expiry tick, then drain
        # without an intervening pack cycle — the race, pinned
        clk.advance_to(lease.expires_at)
        flushed = loop.drain()
        assert flushed == ticket.n
        assert ticket.done and ticket.error is None
        assert len(ticket.verdicts) == ticket.n
        st = loop.status()
        # released once, as a drain release — never ALSO expired
        assert (st["grants"], st["expiries"], st["releases"]) \
            == (1, 0, 1)
        assert st["occupancy"] == 0


def test_lease_expires_at_the_exact_tick_between_packs(tmp_path):
    """One tick short of the TTL the lease survives a pack cycle; AT
    the tick it expires: the slot returns, pending work resolves as
    an explicit lease-expired error (never silently lost), and a
    submit on the dead lease raises LeaseExpired."""
    from cilium_tpu.runtime.serveloop import LeaseExpired

    clk = VirtualClock()
    with simclock.use(clk):
        loop, sections = _serve_world(tmp_path, ttl=10.0)
        lease = loop.connect("s0")
        clk.advance_to(lease.expires_at - 1e-6)
        loop.step()
        assert lease.active and loop.status()["occupancy"] == 1
        ticket = loop.submit(lease, *sections)   # renews the lease
        assert lease.expires_at == clk.now() + 10.0
        clk.advance_to(lease.expires_at)         # idle to the tick
        # enqueue pending work JUST as the TTL lapses: the expiry
        # sweep must resolve it explicitly
        loop.step()
        assert not lease.active
        assert loop.status()["expiries"] == 1
        with pytest.raises(LeaseExpired):
            loop.submit(lease, *sections)
        # the renewed-then-packed first chunk was served normally
        assert ticket.done


def test_reconnect_with_resume_never_double_counts_a_grant(tmp_path):
    """Reconnect-with-resume against a LIVE lease renews and returns
    the SAME lease with no second grant; against a lease expired at
    exactly the reconnect tick it re-grants — once. The grant counter
    counts streams, not dial attempts."""
    clk = VirtualClock()
    with simclock.use(clk):
        loop, sections = _serve_world(tmp_path, ttl=10.0)
        lease = loop.connect("s0")
        assert loop.grants == 1
        # storm of re-dials against the live lease: same object, no
        # new grants, expiry deadline renewed each time
        clk.advance(5.0)
        for _ in range(4):
            again = loop.connect("s0", resume=True)
            assert again is lease
        assert loop.grants == 1
        assert lease.expires_at == clk.now() + 10.0
        # ONE tick before expiry: still a resume, still no grant
        clk.advance_to(lease.expires_at - 1e-6)
        assert loop.connect("s0", resume=True) is lease
        assert loop.grants == 1
        # AT the expiry tick: the lease is dead — resume re-grants a
        # fresh lease (counted once); books stay exact
        clk.advance_to(lease.expires_at)
        fresh = loop.connect("s0", resume=True)
        assert fresh is not lease
        assert loop.grants == 2
        st = loop.status()
        assert st["grants"] - st["expiries"] - st["releases"] \
            == st["occupancy"] == 1


def test_ring_full_sheds_with_explicit_reason(tmp_path):
    """A stream past the ring's slot capacity sheds with reason
    ``ring-full`` — explicit, counted on the admission series, and
    retryable: a released slot admits the next connect."""
    from cilium_tpu.runtime.admission import SHED_RING_FULL
    from cilium_tpu.runtime.metrics import ADMISSION_SHED, METRICS
    from cilium_tpu.runtime.serveloop import ShedError

    clk = VirtualClock()
    with simclock.use(clk):
        loop, sections = _serve_world(tmp_path, capacity=2)
        a = loop.connect("s0")
        loop.connect("s1")
        shed_before = METRICS.get(ADMISSION_SHED, labels={
            "surface": "serve", "class": "data",
            "reason": SHED_RING_FULL})
        with pytest.raises(ShedError) as exc:
            loop.connect("s2")
        assert exc.value.reason == SHED_RING_FULL
        assert METRICS.get(ADMISSION_SHED, labels={
            "surface": "serve", "class": "data",
            "reason": SHED_RING_FULL}) == shed_before + 1
        # retryable: a freed slot admits the shed stream
        loop.disconnect(a)
        lease = loop.connect("s2")
        assert lease.active


def test_acquire_credit_times_out_on_virtual_clock_without_grant():
    """A wedged consumer surfaces as TimeoutError after the VIRTUAL
    timeout — no real seconds slept."""
    from cilium_tpu.runtime.stream import StreamClient

    clk = VirtualClock()
    with simclock.use(clk):
        client = StreamClient.__new__(StreamClient)
        client._lock = threading.Lock()
        client._cond = threading.Condition(client._lock)
        client.timeout = 30.0               # 30 VIRTUAL seconds
        client._done = False
        client._credits = 0
        client._credit_window = 4
        boom = []

        def sender():
            try:
                client._acquire_credit()
            except TimeoutError:
                boom.append(True)

        t = threading.Thread(target=sender)
        t.start()
        while not clk._by_seq:
            threading.Event().wait(0.002)
        clk.advance(30.1)
        t.join(timeout=5.0)
        assert boom == [True]


# ---------------------------------------------------------------------------
# ISSUE 13: fleet compile-plane boundaries — per-bank deadline lapse
# at the exact virtual tick, worker-death retry exhaustion reaching
# quarantine-with-cover, and drain-while-compiling.


def _queued_registry(workers=1, deadline_s=10.0, max_retries=1,
                     backoff_base_s=0.5):
    from cilium_tpu.core.config import EngineConfig
    from cilium_tpu.policy.compiler.bankplan import BankRegistry
    from cilium_tpu.policy.compiler.compilequeue import CompileQueue

    cfg = EngineConfig()
    cfg.bank_size = 4
    q = CompileQueue(workers=workers, deadline_s=deadline_s,
                     max_retries=max_retries,
                     backoff_base_s=backoff_base_s)
    return BankRegistry(queue=q), cfg


def test_compile_deadline_lapses_at_the_exact_tick_serves_cover(
        tmp_path):
    """A bank compile still in flight at EXACTLY its deadline tick
    stops blocking the build: the bank is PENDING (cover for covered
    patterns, fail-closed dead bank for the rest — never an abort),
    and the late completion lands in the registry so the NEXT build
    reuses it with zero compiles."""
    import time as _time

    clk = VirtualClock(start=100.0)
    with simclock.use(clk):
        reg, cfg = _queued_registry(deadline_s=10.0)
        try:
            gate = threading.Event()
            orig = reg._compile_group

            def slow(group, opts):
                gate.wait(5.0)       # REAL stall: worker busy
                return orig(group, opts)

            reg._compile_group = slow
            pats = ["/d1/.*", "/d2/.*"]
            out = {}

            def build():
                out["res"] = reg.compile_field("path", pats, cfg)

            th = threading.Thread(target=build)
            th.start()
            # the waiter must park on the virtual heap first
            for _ in range(400):
                if clk._heap:
                    break
                _time.sleep(0.005)
            clk.advance_to(110.0)            # the EXACT deadline tick
            th.join(timeout=10.0)
            banked, stats = out["res"]
            assert stats.pending, "exact-tick lapse must mark pending"
            assert stats.quarantined == stats.pending
            assert reg.pending_serves == 1
            # no prior cover: patterns fail CLOSED via a dead bank
            assert len(banked.patterns) == len(pats)
            gate.set()
            for _ in range(400):
                if not reg._pending_keys:
                    break
                _time.sleep(0.005)
            assert not reg._pending_keys, "late result did not land"
            _, s2 = reg.compile_field("path", pats, cfg)
            assert not s2.quarantined and s2.rebuilt == ()
            assert s2.reused == len(s2.bank_keys)
        finally:
            reg.close()


def test_worker_death_backoff_gates_on_the_exact_virtual_tick():
    """The in-queue retry's backoff gate is virtual: one tick before
    ``not_before`` the retry does not run; AT the tick it does. (The
    gate also carries a REAL-time release valve so a blocked DST
    driver can't deadlock on it — the base here is large enough that
    only the virtual release is in play within this test's window.)"""
    import time as _time

    from cilium_tpu.runtime import faults

    clk = VirtualClock(start=0.0)
    with simclock.use(clk):
        from cilium_tpu.policy.compiler.compilequeue import CompileQueue

        q = CompileQueue(workers=1, backoff_base_s=5.0, max_retries=3)
        try:
            with faults.inject(faults.FaultPlan(
                    [faults.FaultRule("compile.worker", times=1)])):
                t = q.submit("k", lambda: "ok")
                # death happens promptly (real time); the retry then
                # parks until now + backoff on the VIRTUAL clock
                for _ in range(400):
                    if q.worker_deaths == 1:
                        break
                    _time.sleep(0.005)
                assert q.worker_deaths == 1
                nb = t.not_before
                assert nb > clk.now()
                clk.advance_to(nb - 0.001)
                _time.sleep(0.1)
                assert not t.done, "retry ran BEFORE its backoff gate"
                clk.advance_to(nb)           # the exact tick
                assert q.wait(t, timeout=30.0)
                assert t.result == "ok"
        finally:
            q.close()


def test_worker_death_exhaustion_reaches_quarantine_with_cover():
    """Retry exhaustion under virtual time: every retry consumed by a
    death leaves the bank quarantined; previously-compiled patterns
    ride their cover, new ones fail closed — the fail-closed pin of
    the ISSUE-13 acceptance."""
    import time as _time

    from cilium_tpu.core.flow import Verdict  # noqa: F401 — doc anchor
    from cilium_tpu.runtime import faults

    clk = VirtualClock(start=0.0)
    with simclock.use(clk):
        # a LONG deadline so the pending-lapse path cannot preempt the
        # exhaustion path; the retries release through the gate's
        # real-time valve (exactly how a blocked DST driver survives)
        reg, cfg = _queued_registry(max_retries=1, backoff_base_s=0.1,
                                    deadline_s=1000.0)
        try:
            pats = [f"/w{i}/.*" for i in range(4)]
            _, s0 = reg.compile_field("path", pats, cfg)
            assert not s0.quarantined
            grown = pats + ["/w-new/.*"]
            with faults.inject(faults.FaultPlan(
                    [faults.FaultRule("compile.worker", times=10)])):
                out = {}

                def build():
                    out["res"] = reg.compile_field("path", grown, cfg)

                th = threading.Thread(target=build)
                th.start()
                th.join(timeout=30.0)
            assert "res" in out, "build wedged on the backoff gate"
            banked, s1 = out["res"]
            assert s1.quarantined, "exhaustion must quarantine"
            assert not s1.pending, "exhaustion, not a deadline lapse"
            assert reg._quarantine
            # the changed bank's patterns: covered ones ride the old
            # cover, the new one binds to a lane (dead bank or cover)
            assert len(banked.patterns) == len(grown)
        finally:
            reg.close()


# ---------------------------------------------------------------------------
# ISSUE 16: serving-fleet handoff boundaries — a lease lapsing at the
# EXACT tick its host is declared dead, a chunk submitted to a host
# that died between admit and submit, and a rejoin racing the handoff
# of the rejoining host's own old leases.


def _fleet_world(tmp_path, hosts=3, capacity=8, ttl=10.0):
    from cilium_tpu.core.config import Config
    from cilium_tpu.ingest import synth
    from cilium_tpu.ingest.binary import (
        capture_from_bytes,
        capture_to_bytes,
    )
    from cilium_tpu.runtime.fleetserve import FleetRouter, HostReplica
    from cilium_tpu.runtime.loader import Loader

    scenario = synth.scenario_by_name("http", 12, 64)
    per_identity, scenario = synth.realize_scenario(scenario)
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    sections = capture_from_bytes(
        capture_to_bytes(scenario.flows[:16]))
    replicas = [HostReplica(i, loader, capacity=capacity,
                            lease_ttl_s=ttl, pack_interval_s=0.01)
                for i in range(hosts)]
    router = FleetRouter(replicas, heartbeat_interval_s=1.0,
                         suspicion_ttl_s=3.0, spill_headroom=0.0)
    return router, loader, sections


def test_lease_expiring_at_the_exact_death_tick_never_double_counts(
        tmp_path):
    """A lease whose TTL lapses at EXACTLY the tick its host is
    declared dead: the abandonment releases the slot exactly once
    (as a close — never ALSO swept as an expiry), the handoff
    re-grant on a survivor counts exactly one new grant, and the
    fleet books stay exact through the coincidence."""
    clk = VirtualClock()
    with simclock.use(clk):
        router, loader, sections = _fleet_world(tmp_path, ttl=10.0)
        host, lease = router.connect("race-0")
        dead = next(r for r in router.replicas if r.name == host)
        # advance to EXACTLY the lease expiry tick, then declare the
        # host dead without an intervening pack — the race, pinned
        clk.advance_to(lease.expires_at)
        assert lease.expired
        router.kill(host)
        st = dead.loop.status()
        assert (st["grants"], st["expiries"], st["releases"]) \
            == (1, 0, 1), "abandon must release ONCE, never also expire"
        assert st["occupancy"] == 0
        # the handoff re-granted on a survivor — exactly one grant,
        # never one on each side of the death
        assert router.conservation_violation() is None
        bal, occ = router.books()
        assert bal == occ == 1
        placed = router.placements.get("race-0")
        assert placed is not None and placed != host


def test_submit_to_host_dead_between_admit_and_submit_resumes(
        tmp_path):
    """Admit lands, the host dies, THEN the chunk arrives: the submit
    raises the TYPED HostDead (the client's resume signal, never a
    stream-fatal error), and the reconnect-with-resume replay serves
    the chunk on a survivor with the books exact."""
    from cilium_tpu.runtime.fleetserve import HostDead

    clk = VirtualClock()
    with simclock.use(clk):
        router, loader, sections = _fleet_world(tmp_path)
        host, lease = router.connect("gap-0")
        # the death slips into the admit→submit gap; the handoff is
        # fully interrupted so the stream is left UNPLACED (the
        # client-resume face of the race, not the migrated face)
        from cilium_tpu.runtime import faults as _faults

        with _faults.inject(_faults.FaultPlan(
                [_faults.FaultRule("fleet.handoff", times=1)])):
            router.kill(host)
        with pytest.raises(HostDead):
            router.submit("gap-0", lease, sections)
        # the typed error drives the replay: resume, re-submit, serve
        host2, lease2 = router.connect("gap-0", resume=True)
        assert host2 != host
        ticket = router.submit("gap-0", lease2, sections)
        router.step_all()
        assert ticket.done and ticket.error is None
        assert len(ticket.verdicts) == ticket.n
        assert router.conservation_violation() is None
        bal, occ = router.books()
        assert bal == occ == 1


def test_rejoin_racing_the_handoff_of_its_own_old_leases(tmp_path):
    """The rejoining host comes back while its OWN old leases are
    still mid-migration (the handoff was interrupted after one
    re-grant): already-migrated streams stay pinned to their
    survivor, unmigrated ones may resume onto the rejoined host's
    FRESH ring — and at no point does any stream hold leases on two
    live hosts, including the rejoined incarnation vs its survivors."""
    from cilium_tpu.runtime import faults as _faults

    clk = VirtualClock()
    with simclock.use(clk):
        router, loader, sections = _fleet_world(tmp_path)
        streams = [f"r{k}" for k in range(8)]
        for s in streams:
            router.connect(s)
        counts = {}
        for s in streams:
            h = router.placements[s]
            counts[h] = counts.get(h, 0) + 1
        victim = max(counts, key=lambda h: counts[h])
        assert counts[victim] >= 2
        # interrupt AFTER one re-grant: one stream migrated, the rest
        # of the victim's streams left unplaced
        with _faults.inject(_faults.FaultPlan(
                [_faults.FaultRule("fleet.handoff", times=1,
                                   after=1)])):
            router.kill(victim)
        assert router.partial_handoffs == 1
        assert router.handoffs == 1
        # the rejoin races the unfinished migration
        router.rejoin(victim)
        rejoined = next(r for r in router.replicas
                        if r.name == victim)
        assert rejoined.alive and not rejoined.loop.lease_ids(), \
            "the rejoined incarnation must start with a FRESH ring"
        # every stream resumes: pinned ones stay put, unplaced ones
        # may land on the rejoined host — exactly one live lease each
        pinned_before = {s: router.placements[s] for s in streams
                         if s in router.placements}
        for s in streams:
            router.connect(s, resume=True)
        for s, h in pinned_before.items():
            assert router.placements[s] == h, \
                "a pinned stream moved during the rejoin race"
        assert router.conservation_violation() is None
        bal, occ = router.books()
        assert bal == occ == len(streams)
    """Drain racing an in-flight bank compile: the compile finishes,
    its result lands in the registry (and the artifact store), and
    the drained queue refuses new work instead of buffering it."""
    import time as _time

    import pytest as _pytest

    from cilium_tpu.policy.compiler.compilequeue import QueueDraining
    from cilium_tpu.runtime.checkpoint import (
        ArtifactCache,
        BankArtifactStore,
    )

    clk = VirtualClock(start=0.0)
    with simclock.use(clk):
        from cilium_tpu.core.config import EngineConfig
        from cilium_tpu.policy.compiler.bankplan import BankRegistry
        from cilium_tpu.policy.compiler.compilequeue import CompileQueue

        cfg = EngineConfig()
        cfg.bank_size = 4
        q = CompileQueue(workers=1, deadline_s=30.0)
        store = BankArtifactStore(ArtifactCache(str(tmp_path)))
        reg = BankRegistry(queue=q, artifacts=store)
        gate = threading.Event()
        orig = reg._compile_group

        def slow(group, opts):
            gate.wait(5.0)
            return orig(group, opts)

        reg._compile_group = slow
        pats = ["/dr1/.*"]
        out = {}
        th = threading.Thread(
            target=lambda: out.update(
                res=reg.compile_field("path", pats, cfg)))
        th.start()
        _time.sleep(0.05)                    # compile is in flight
        drained = {}
        dth = threading.Thread(
            target=lambda: drained.update(ok=q.drain(timeout=60.0)))
        dth.start()
        _time.sleep(0.05)
        gate.set()                           # the compile completes
        dth.join(timeout=10.0)
        th.join(timeout=10.0)
        assert drained["ok"] is True
        _, s = out["res"]
        assert s.rebuilt and not s.quarantined, \
            "drain abandoned an in-flight compile"
        assert reg._group_count() == len(s.bank_keys)
        with _pytest.raises(QueueDraining):
            q.submit("post-drain", lambda: None)
        # ...and the artifact was published before the drain finished
        assert store.fetch(s.rebuilt[0]) is not None
        reg.close()


# ---------------------------------------------------------------------------
# ISSUE 17: fleet observability boundaries — a traced stream whose
# host is declared dead at EXACTLY its lease-expiry tick still
# stitches to ONE trace id with a bumped causal epoch, and the fleet
# event journal folds to the router's books through the coincidence.


def test_trace_stitches_when_death_lands_on_the_exact_expiry_tick(
        tmp_path):
    """Host death at EXACTLY the traced lease's expiry tick: the
    abandoned chunk resolves as lease-closed (not silently expired),
    the replay adopts the SAME trace id at a bumped causal epoch, and
    the stitched timeline orders epoch 0 strictly before epoch 1 with
    both hosts attributed — the kill → abandon → re-grant → replay
    chain is one trace even when the TTL and the death coincide."""
    from cilium_tpu.runtime.tracing import TRACER

    clk = VirtualClock()
    with simclock.use(clk):
        router, loader, sections = _fleet_world(tmp_path, ttl=10.0)
        prev_enabled, prev_rate = TRACER.enabled, TRACER.sample_rate
        TRACER.configure(enabled=True, sample_rate=1.0)
        try:
            host, lease = router.connect("tb-0")
            with TRACER.trace("stream.chunk", stream="tb-0") as ctx:
                ticket = router.submit("tb-0", lease, sections)
            tid = ctx.trace_id
            assert ticket.trace_id == tid and ticket.epoch == 0
            # advance to EXACTLY the expiry tick, then declare the
            # host dead with no intervening pack — the race, pinned
            clk.advance_to(lease.expires_at)
            assert lease.expired
            router.kill(host)
            assert ticket.done and ticket.error == "lease-closed"
            # the replay adopts the SAME id at a bumped epoch
            host2, lease2 = router.connect("tb-0", resume=True)
            assert host2 != host
            t2 = router.submit("tb-0", lease2, sections)
            assert t2.trace_id == tid
            assert t2.epoch > ticket.epoch
            router.step_all()
            assert t2.done and t2.error is None
            stitched = router.trace(tid)
            assert stitched["stitched"] is True
            assert stitched["epochs"] == [0, 1]
            assert host in stitched["hosts"]
            assert host2 in stitched["hosts"]
            names = [r["name"] for r in stitched["records"]]
            assert "fleet.handoff" in names
            # epoch ordering is strict even though the wall stamps of
            # both sides share the exact same virtual tick
            epochs = [r.get("epoch", 0) for r in stitched["records"]]
            assert epochs == sorted(epochs)
            # the journal folds to the router's books through the
            # expiry/death coincidence
            assert router.journal_consistent() is None
            assert router.conservation_violation() is None
        finally:
            TRACER.configure(enabled=prev_enabled,
                             sample_rate=prev_rate)


# ---------------------------------------------------------------------------
# ISSUE 20: tenant-fairness and canary boundaries — quantum rotation
# at the exact virtual tick, counter-walk sample selection identical
# under every PYTHONHASHSEED, and a quota lapse landing on the exact
# tick of a regeneration's admission decisions.


def test_fairness_quantum_rotates_at_the_exact_virtual_tick():
    """A tenant shed for hogging the window is forgiven at EXACTLY
    start+quantum — the rotation boundary is closed (now >= start +
    quantum). One tick before the quantum the storming tenant still
    sheds; AT the tick the window is fresh and the same tenant
    admits. The rotation also lands on the quantum grid, never on
    'whenever the next request happened to arrive'."""
    from cilium_tpu.runtime.admission import (
        CLASS_DATA,
        SHED_TENANT_QUOTA,
        AdmissionGate,
    )
    from cilium_tpu.runtime.tenant import FairShareWindow

    clk = VirtualClock(start=100.0)
    with simclock.use(clk):
        fair = FairShareWindow(quantum_s=5.0, max_share=0.3)
        gate = AdmissionGate(max_pending=8, control_reserve=2,
                             depth_fn=lambda: 6, fairness=fair)
        assert gate.admit(CLASS_DATA, tenant="b") == (True, "")
        # a storms until the window judges it over cap AND fair share
        shed = False
        for _ in range(12):
            ok, reason = gate.admit(CLASS_DATA, tenant="a")
            if not ok:
                assert reason == SHED_TENANT_QUOTA
                shed = True
                break
        assert shed, "storming tenant must shed within the window"
        # one tick BEFORE the quantum boundary: still the same window,
        # the storm is still on the books, a still sheds
        clk.advance_to(100.0 + 5.0 - 1e-6)
        ok, reason = gate.admit(CLASS_DATA, tenant="a")
        assert (ok, reason) == (False, SHED_TENANT_QUOTA)
        # AT exactly start+quantum: fresh window, a is forgiven
        clk.advance_to(105.0)
        assert gate.admit(CLASS_DATA, tenant="a") == (True, "")
        assert fair.window_start() == 105.0   # grid, not arrival time
        # an idle gap of 2.5 quanta later: the window start is still
        # on the grid (105 + 2*5), not at the arrival tick
        clk.advance_to(105.0 + 12.5)
        fair.note("b")
        assert fair.window_start() == 115.0


def test_canary_sample_selection_identical_under_hashseeds():
    """Sample selection is a pure counter walk — floor(c*f) !=
    floor((c-1)*f) — so the SAME chunks are sampled on every host and
    under every PYTHONHASHSEED. Three fresh interpreters with seeds
    0/1/2 must pick byte-identical counter sets of exactly
    floor(n*f) chunks."""
    import os
    import subprocess
    import sys

    prog = (
        "from cilium_tpu.runtime.loader import Loader\n"
        "from cilium_tpu.core.config import Config\n"
        "from cilium_tpu.runtime.canary import CanaryController\n"
        "class _L:\n"
        "    pass\n"
        "c = CanaryController(_L(), sample_fraction=0.37)\n"
        "picked = [i for i in range(1, 201) if c.should_sample(i)]\n"
        "print(len(picked), ','.join(map(str, picked)))\n"
    )
    outs = []
    for seed in ("0", "1", "2"):
        env = dict(os.environ,
                   PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", prog], env=env,
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        outs.append(out.stdout.strip())
    assert outs[0] == outs[1] == outs[2]
    count = int(outs[0].split()[0])
    assert count == int(200 * 0.37)          # exactly floor(n*f)


def test_tenant_quota_lapse_races_a_regeneration():
    """Tenant a's quota TTL expires at EXACTLY the tick its own
    regeneration lands admission decisions. The boundary is closed
    (expires_at <= now): AT the tick the conservative default share
    applies — a's data-plane burst sheds tenant-quota — while the
    regeneration's CLASS_CONTROL traffic is exempt and sails through,
    so a quota lapse can never starve the control plane that would
    refresh it. One tick earlier the live quota still holds."""
    from cilium_tpu.runtime.admission import (
        CLASS_CONTROL,
        CLASS_DATA,
        SHED_TENANT_QUOTA,
        AdmissionGate,
    )
    from cilium_tpu.runtime.metrics import METRICS, TENANT_QUOTA_READS
    from cilium_tpu.runtime.tenant import (
        FairShareWindow,
        TenantQuotas,
    )

    clk = VirtualClock(start=0.0)
    with simclock.use(clk):
        quotas = TenantQuotas(default_share=0.2, ttl_s=10.0)
        quotas.set_share("a", 0.95)          # expires_at == 10.0
        fair = FairShareWindow(quantum_s=1000.0, max_share=0.2)
        gate = AdmissionGate(max_pending=8, control_reserve=2,
                             depth_fn=lambda: 6, fairness=fair,
                             quotas=quotas)
        fair.note("b")
        lapsed0 = METRICS.get(TENANT_QUOTA_READS,
                              {"result": "lapsed"})
        # one tick BEFORE expiry: the live 0.95 quota admits the burst
        clk.advance_to(10.0 - 1e-6)
        for _ in range(6):
            assert gate.admit(CLASS_DATA, tenant="a") == (True, "")
        # AT exactly expires_at — the regeneration tick: data sheds on
        # the conservative default, control admits
        clk.advance_to(10.0)
        ok, reason = gate.admit(CLASS_DATA, tenant="a")
        assert (ok, reason) == (False, SHED_TENANT_QUOTA)
        assert gate.admit(CLASS_CONTROL, tenant="a") == (True, "")
        assert METRICS.get(TENANT_QUOTA_READS,
                           {"result": "lapsed"}) == lapsed0 + 1
        # the quota store dropped the entry — a later refresh (the
        # regeneration's control plane got through) restores service
        quotas.set_share("a", 0.95)
        assert gate.admit(CLASS_DATA, tenant="a") == (True, "")
