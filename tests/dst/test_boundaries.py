"""Timing boundary cases the schedule searcher is blind to without
explicit pins (ISSUE 10 satellite): deadline lapse at the exact tick,
quarantine TTL expiry racing a regeneration, breaker half-open under
concurrent probes, and a credit grant landing during reconnect. All
under virtual time — the boundaries are EXACT, not sleep-approximate.
"""

import threading

import pytest

from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.simclock import VirtualClock


# ---------------------------------------------------------------------------
# 1) deadline lapse at the exact tick


def test_admission_deadline_at_the_exact_tick_sheds():
    """A request whose deadline equals now() EXACTLY has zero budget:
    the gate sheds it (reason deadline) — `remaining <= 0` — and one
    virtual tick earlier it admits. The boundary is pinned closed."""
    from cilium_tpu.runtime.admission import (
        AdmissionGate,
        SHED_DEADLINE,
    )

    clk = VirtualClock(start=50.0)
    with simclock.use(clk):
        gate = AdmissionGate(max_pending=8, depth_fn=lambda: 0)
        ok, reason = gate.admit(deadline=clk.now())       # exact tick
        assert (ok, reason) == (False, SHED_DEADLINE)
        ok, _ = gate.admit(deadline=clk.now() + 1e-6)     # one tick in
        assert ok


def test_microbatcher_reaps_an_entry_expiring_at_the_exact_tick():
    """An entry whose deadline == now at dispatch is reaped (deadline
    <= now), never spent a batch slot on; one whose deadline is one
    tick later dispatches."""
    from cilium_tpu.core.flow import Flow, Verdict
    from cilium_tpu.runtime.service import MicroBatcher, _Pending

    clk = VirtualClock(start=10.0)
    with simclock.use(clk):
        served = []
        mb = MicroBatcher(lambda flows: served.append(len(flows))
                          or [int(Verdict.FORWARDED)] * len(flows),
                          batch_max=4, deadline_ms=1.0)
        exact = _Pending(Flow(), clk.now(), None)          # lapses NOW
        live = _Pending(Flow(), clk.now() + 1e-6, None)
        out = mb._reap([exact, live])
        assert out == [live]
        assert exact.box == [int(Verdict.ERROR)]
        assert exact.ev.is_set()
        mb.close()


# ---------------------------------------------------------------------------
# 2) quarantine TTL expiry racing a regeneration


def test_quarantine_ttl_expiry_races_regeneration():
    """A regeneration that starts at EXACTLY the quarantine TTL tick
    retries the bank (now >= until); one tick earlier it must keep
    serving the stale cover without a retry compile. Either way the
    pattern set served is consistent — the boundary changes WHEN the
    retry happens, never correctness."""
    from cilium_tpu.core.config import EngineConfig
    from cilium_tpu.policy.compiler.bankplan import BankRegistry
    from cilium_tpu.runtime import faults
    from cilium_tpu.runtime.faults import FaultPlan, FaultRule

    clk = VirtualClock()
    with simclock.use(clk):
        reg = BankRegistry(quarantine_ttl_s=30.0)
        cfg = EngineConfig(bank_size=2)
        pats = ["/a/.*", "/b/.*", "/c/.*", "/d/.*"]
        reg.compile_field("path", pats, cfg)        # healthy baseline
        with faults.inject(FaultPlan(
                [FaultRule("loader.bank_compile", times=1)])):
            _, stats = reg.compile_field("path", pats + ["/e/.*"],
                                         cfg)
        assert stats.quarantined, "fault must quarantine a group"
        quarantined = set(stats.quarantined)
        compiles_q = reg.compiles

        # one tick BEFORE expiry: stale cover keeps serving, no retry
        clk.advance(30.0 - 1e-3)
        assert reg.expired_quarantines() == ()
        _, stats2 = reg.compile_field("path", pats + ["/e/.*"], cfg)
        assert set(stats2.quarantined) == quarantined
        assert reg.compiles == compiles_q   # no retry compile yet

        # AT the expiry tick: the next regeneration retries + recovers
        clk.advance(1e-3)
        assert set(reg.expired_quarantines()) == quarantined
        _, stats3 = reg.compile_field("path", pats + ["/e/.*"], cfg)
        assert not stats3.quarantined
        assert reg.compiles > compiles_q    # the retry compiled


# ---------------------------------------------------------------------------
# 3) breaker half-open with concurrent probes


def test_breaker_half_open_admits_exactly_one_concurrent_probe():
    """N threads hit allow_primary at the exact probe-interval tick:
    EXACTLY one becomes the half-open probe; the rest keep falling
    back (a thundering herd onto a sick device would defeat the
    probe). A failed probe re-arms the timer at the failure instant."""
    from cilium_tpu.runtime.service import CircuitBreaker

    clk = VirtualClock()
    with simclock.use(clk):
        br = CircuitBreaker(failure_threshold=1, probe_interval=5.0)
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        clk.advance(5.0)                     # exactly the interval
        results = []
        lock = threading.Lock()
        start = threading.Barrier(8)

        def prober():
            start.wait()
            got = br.allow_primary()
            with lock:
                results.append(got)

        ts = [threading.Thread(target=prober) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=5.0)
        assert results.count(True) == 1, results
        assert br.state == CircuitBreaker.HALF_OPEN
        # failed probe: OPEN again, timer re-armed from NOW — one tick
        # shy of the new interval stays closed to probes
        br.record_failure()                  # re-armed at now=5.0
        clk.advance_to(10.0 - 1e-6)
        assert not br.allow_primary()
        clk.advance_to(10.0)                 # exactly interval later
        assert br.allow_primary()


# ---------------------------------------------------------------------------
# 4) credit grant arriving during reconnect


def test_credit_grant_arriving_during_reconnect_is_not_lost():
    """The client's credit window is rebuilt from the re-handshake
    minus re-sent unacked chunks; a grant that lands immediately after
    (the server answering a resumed chunk) must ADD to that window —
    the reconnect must never double-count or drop it. Pure client-side
    state-machine check, driven through the same lock/condition the
    recv loop uses."""
    from cilium_tpu.runtime.stream import StreamClient

    clk = VirtualClock()
    with simclock.use(clk):
        client = StreamClient.__new__(StreamClient)   # no socket I/O
        client._lock = threading.Lock()
        client._cond = threading.Condition(client._lock)
        client.timeout = 5.0
        client._done = False
        client._credit_window = 4
        client._credits = 0                 # exhausted pre-drop
        client._unacked = {7: ("", b"img7"), 8: ("", b"img8")}
        # reconnect path: fresh window minus the 2 re-sent chunks
        with client._cond:
            client._credits = max(
                0, client._credit_window - len(client._unacked))
        assert client._credits == 2
        # the resumed session answers seq 7 AND grants a credit — the
        # recv-loop bookkeeping for a grant frame during resume:
        with client._cond:
            client._credits += 1
            client._cond.notify_all()
        with client._cond:
            client._unacked.pop(7)
        assert client._credits == 3
        # a sender blocked at zero credit wakes on the grant: window
        # accounting and the wait predicate agree
        client._acquire_credit()
        assert client._credits == 2


def test_acquire_credit_times_out_on_virtual_clock_without_grant():
    """A wedged consumer surfaces as TimeoutError after the VIRTUAL
    timeout — no real seconds slept."""
    from cilium_tpu.runtime.stream import StreamClient

    clk = VirtualClock()
    with simclock.use(clk):
        client = StreamClient.__new__(StreamClient)
        client._lock = threading.Lock()
        client._cond = threading.Condition(client._lock)
        client.timeout = 30.0               # 30 VIRTUAL seconds
        client._done = False
        client._credits = 0
        client._credit_window = 4
        boom = []

        def sender():
            try:
                client._acquire_credit()
            except TimeoutError:
                boom.append(True)

        t = threading.Thread(target=sender)
        t.start()
        while not clk._by_seq:
            threading.Event().wait(0.002)
        clk.advance(30.1)
        t.join(timeout=5.0)
        assert boom == [True]
