"""Planted-bug validation (`make dst-validate`, ISSUE 10 acceptance):
re-introduce a known FIXED bug behind ``CILIUM_TPU_DST_MUTATION`` and
prove the schedule search catches it within a bounded seed budget and
shrinks the failing schedule to a ≤5-event regression case."""

import pytest

from cilium_tpu.runtime import dst, faults

pytestmark = [pytest.mark.slow, pytest.mark.dst]

#: seeds the searcher may burn before we call the mutation missed —
#: both known mutations are caught well inside this budget
SEED_BUDGET = 25


def test_mutations_are_documented():
    assert set(faults.MUTATIONS) >= {"rollback-artifact-key",
                                     "positional-banks"}
    assert not faults.mutation_active("rollback-artifact-key")


@pytest.mark.parametrize("mutation,invariants", [
    # PR-7's real bug: rollback left _last_artifact_key at the aborted
    # revision → a later warm snapshot/restore stages the WRONG policy
    ("rollback-artifact-key", {"oracle-agreement", "session-stale"}),
    # pre-PR-8 positional bank grouping: one delete shifts every later
    # bank → O(policy) compiles per update
    ("positional-banks", {"o-delta-compile"}),
])
def test_planted_bug_is_caught_and_shrunk(mutation, invariants,
                                          monkeypatch):
    monkeypatch.setenv(faults.MUTATION_ENV, mutation)
    ran, failing = dst.search(SEED_BUDGET)
    assert failing is not None, \
        f"{mutation} not caught within {SEED_BUDGET} seeds"
    assert failing["violation"]["invariant"] in invariants, \
        failing["violation"]
    small = dst.shrink(failing["seed"], failing["events"])
    assert small["violation"] is not None
    assert len(small["events"]) <= 5, small["events"]
    # the UNMUTATED tree does not violate on the shrunken schedule —
    # the case isolates the planted bug, not a harness artifact
    monkeypatch.delenv(faults.MUTATION_ENV)
    clean = dst.run_schedule(small["seed"], events=small["events"])
    assert clean["violation"] is None, clean["violation"]
