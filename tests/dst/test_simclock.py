"""Virtual-clock unit tests: the DST layer's foundation
(runtime/simclock.py). Driven mode must be exact (waiters wake at
their deadline, in deadline order); autojump must advance only at
quiescence; the module-level seam must late-bind so objects built
before a test installs its clock still follow it."""

import threading

import pytest

from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.simclock import RealClock, VirtualClock


def test_real_clock_is_the_default_and_delegates():
    assert isinstance(simclock.get(), RealClock)
    ev = simclock.event()
    assert isinstance(ev, threading.Event)
    ev.set()
    assert simclock.wait_on(ev, 0.01)
    assert simclock.now() > 0
    assert simclock.wall() > 1_000_000_000


def test_virtual_now_wall_perf_advance():
    clk = VirtualClock(start=100.0)
    with simclock.use(clk):
        assert simclock.now() == 100.0
        assert simclock.wall() == simclock.VIRTUAL_EPOCH + 100.0
        clk.advance(2.5)
        assert simclock.now() == 102.5
        assert simclock.perf() == 102.5      # virtual measurement
        assert clk.simulated == pytest.approx(2.5)


def test_use_restores_previous_clock_on_exit():
    before = simclock.get()
    with simclock.use(VirtualClock()):
        assert simclock.get() is not before
    assert simclock.get() is before


def test_sleep_parks_until_advance_and_wakes_at_its_deadline():
    clk = VirtualClock()
    order = []
    lock = threading.Lock()
    with simclock.use(clk):
        def sleeper(name, dt):
            woke = simclock.sleep(dt)   # the exact virtual wake instant
            with lock:
                order.append((name, round(woke, 6)))

        ts = [threading.Thread(target=sleeper, args=("b", 2.0)),
              threading.Thread(target=sleeper, args=("a", 1.0))]
        for t in ts:
            t.start()
        # wait until both are parked
        deadline = 200
        while len(clk._by_seq) < 2 and deadline:
            threading.Event().wait(0.005)
            deadline -= 1
        assert len(clk._by_seq) == 2
        clk.advance(3.0)
        for t in ts:
            t.join(timeout=5.0)
    assert sorted(order, key=lambda x: x[1]) == [("a", 1.0),
                                                 ("b", 2.0)]


def test_wait_on_clock_event_fires_and_times_out():
    clk = VirtualClock()
    with simclock.use(clk):
        ev = simclock.event()
        got = []

        def waiter():
            got.append(simclock.wait_on(ev, timeout=5.0))

        t = threading.Thread(target=waiter)
        t.start()
        while not clk._by_seq:
            threading.Event().wait(0.002)
        ev.set()                      # fires BEFORE the deadline
        t.join(timeout=5.0)
        assert got == [True]

        ev2 = simclock.event()
        got2 = []
        t2 = threading.Thread(
            target=lambda: got2.append(simclock.wait_on(ev2, 5.0)))
        t2.start()
        while not clk._by_seq:
            threading.Event().wait(0.002)
        clk.advance(5.0)              # deadline passes: timeout
        t2.join(timeout=5.0)
        assert got2 == [False]


def test_wait_for_predicate_and_virtual_timeout():
    clk = VirtualClock()
    with simclock.use(clk):
        cond = threading.Condition()
        state = {"ready": False}
        results = []

        def waiter(timeout):
            with cond:
                results.append(simclock.wait_for(
                    cond, lambda: state["ready"], timeout))

        t = threading.Thread(target=waiter, args=(10.0,))
        t.start()
        while not clk._by_seq:
            threading.Event().wait(0.002)
        with cond:
            state["ready"] = True
            cond.notify_all()
        t.join(timeout=5.0)
        assert results == [True]

        state["ready"] = False
        t2 = threading.Thread(target=waiter, args=(1.0,))
        t2.start()
        while not clk._by_seq:
            threading.Event().wait(0.002)
        clk.advance(1.5)              # virtual deadline lapses
        t2.join(timeout=5.0)
        assert results == [True, False]


def test_autojump_advances_only_at_quiescence():
    clk = VirtualClock(autojump=0.005)
    with simclock.use(clk):
        done = []

        def sleeper():
            simclock.sleep(30.0)      # would be 30 real seconds
            done.append(simclock.now())

        t = threading.Thread(target=sleeper)
        t.start()
        t.join(timeout=10.0)          # autojump must release it fast
        assert done and done[0] == pytest.approx(30.0)
        assert clk.simulated == pytest.approx(30.0)


def test_advance_steps_through_intermediate_deadlines():
    """A sleeper woken mid-advance may schedule NEW earlier work; the
    clock must step deadline-by-deadline, never overshoot."""
    clk = VirtualClock()
    seen = []
    with simclock.use(clk):
        def chain():
            seen.append(round(simclock.sleep(1.0), 6))

        t = threading.Thread(target=chain)
        t.start()
        while not clk._by_seq:
            threading.Event().wait(0.002)
        clk.advance(10.0)
        t.join(timeout=5.0)
    # the sleeper woke at ITS deadline, not the advance target
    assert seen == [1.0]
    assert clk.now() == 10.0


def test_late_binding_objects_follow_an_installed_clock():
    """A breaker built under the real clock follows a virtual clock
    installed afterwards — the module functions late-bind."""
    from cilium_tpu.runtime.service import CircuitBreaker

    br = CircuitBreaker(failure_threshold=1, probe_interval=5.0)
    clk = VirtualClock()
    with simclock.use(clk):
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert not br.allow_primary()     # probe timer not expired
        clk.advance(5.1)
        assert br.allow_primary()         # virtual expiry → probe
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED
