"""Identity churn-storm regeneration batching (ISSUE 10 satellite):
a burst of identity add/delete events coalesces behind a debounce
window into O(1) regenerations, counted, under virtual time."""

import threading

import pytest

from cilium_tpu.identity_kvstore import RegenDebouncer
from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.metrics import METRICS
from cilium_tpu.runtime.simclock import VirtualClock

COALESCED = "cilium_tpu_identity_regen_coalesced_total"


def test_storm_of_100_events_fires_once():
    """100 events inside the window → exactly ONE regeneration; the
    99 absorbed events land on the coalesced counter."""
    clk = VirtualClock(autojump=0.003)
    with simclock.use(clk):
        fires = []
        deb = RegenDebouncer(lambda: fires.append(simclock.now()),
                             window_s=0.05)
        before = METRICS.get(COALESCED)
        for _ in range(100):
            deb.note()
        # quiet: the window closes one virtual tick after the last
        # event — autojump crosses it without sleeping
        deadline = threading.Event()
        for _ in range(2000):
            if deb.fires:
                break
            deadline.wait(0.005)
        deb.close()
        assert deb.fires == 1
        assert len(fires) == 1
        assert METRICS.get(COALESCED) - before == 99


def test_spaced_events_each_rearm_the_window_until_max_delay():
    """Events spaced inside the window keep re-arming it, but
    max_delay bounds the staleness: a sustained storm still
    regenerates, at the bounded cadence — never at event rate."""
    clk = VirtualClock(autojump=0.003)
    with simclock.use(clk):
        fires = []
        deb = RegenDebouncer(lambda: fires.append(round(
            simclock.now(), 3)), window_s=0.05, max_delay_s=0.2)
        stop = threading.Event()

        def stormer():
            # an event every 0.03 virtual s for 0.6 virtual s: the
            # window (0.05) never goes quiet, so only max_delay fires
            for _ in range(20):
                deb.note()
                simclock.sleep(0.03)
            stop.set()

        t = threading.Thread(target=stormer)
        t.start()
        t.join(timeout=30.0)
        assert stop.is_set()
        deb.close(flush=True)
        # 0.6s of sustained storm / 0.2s max delay ≈ 3 fires (+ the
        # final flush) — O(duration/max_delay), never O(20 events)
        assert 1 <= deb.fires <= 6, (deb.fires, fires)


def test_window_zero_degrades_to_synchronous_per_event():
    fires = []
    deb = RegenDebouncer(lambda: fires.append(1), window_s=0.0)
    for _ in range(5):
        deb.note()
    assert len(fires) == 5
    deb.close()


def test_flush_fires_pending_synchronously_and_close_is_idempotent():
    clk = VirtualClock()
    with simclock.use(clk):
        fires = []
        deb = RegenDebouncer(lambda: fires.append(1), window_s=10.0)
        deb.note()
        deb.note()
        assert not fires            # window still open (virtual)
        deb.flush()
        assert len(fires) == 1
        deb.close()
        deb.close()
        deb.note()                  # after close: dropped, no crash
        assert len(fires) == 1


def test_fire_exception_does_not_kill_the_debouncer():
    clk = VirtualClock(autojump=0.003)
    with simclock.use(clk):
        calls = []

        def boom():
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("regen failed")

        deb = RegenDebouncer(boom, window_s=0.02)
        deb.note()
        ev = threading.Event()
        for _ in range(1000):
            if calls:
                break
            ev.wait(0.005)
        assert calls, "first window never fired"
        deb.note()                  # the NEXT window must still fire
        for _ in range(1000):
            if len(calls) >= 2:
                break
            ev.wait(0.005)
        deb.close()
        assert len(calls) >= 2


def test_agent_identity_hook_is_debounced():
    """The agent wiring: _on_cluster_identity updates the selector
    cache synchronously but routes regeneration through the
    debouncer (the storm assertion at the integration seam)."""
    from cilium_tpu.agent import Agent
    from cilium_tpu.core.config import Config
    from cilium_tpu.core.labels import LabelSet

    cfg = Config()
    cfg.configure_logging = False
    agent = Agent(cfg)
    try:
        regen_calls = []
        agent._identity_debounce.fire = \
            lambda: regen_calls.append(simclock.now())
        clk = VirtualClock(autojump=0.003)
        with simclock.use(clk):
            for k in range(100):
                agent._on_cluster_identity(
                    10_000 + k,
                    LabelSet.from_dict({"storm": f"s{k}"}))
            ev = threading.Event()
            for _ in range(2000):
                if agent._identity_debounce.fires:
                    break
                ev.wait(0.005)
            assert agent._identity_debounce.fires == 1
            assert len(regen_calls) == 1
            # the selector cache saw every event synchronously
            assert agent.selector_cache is not None
    finally:
        agent.stop()
