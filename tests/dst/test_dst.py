"""The DST runner (runtime/dst.py): schedule generation, byte-exact
replay (in-process and across PYTHONHASHSEEDs), a clean-tree search
slice, the ddmin shrinker, and replay of the committed regression
corpus. The full 200-schedule sweep is `make dst`; the planted-bug
proofs are tests/dst/test_planted.py (`make dst-validate`)."""

import json
import os
import subprocess
import sys

import pytest

from cilium_tpu.runtime import dst

REGRESSION_DIR = os.path.join(os.path.dirname(__file__), "regressions")


def test_generate_is_seeded_and_self_contained():
    a = dst.generate(11)
    b = dst.generate(11)
    c = dst.generate(12)
    assert a == b
    assert a != c
    assert all(isinstance(ev, list) and isinstance(ev[0], str)
               for ev in a)
    # self-contained: a schedule round-trips through JSON verbatim
    assert json.loads(json.dumps(a)) == a


def test_schedule_digest_stable():
    evs = dst.generate(5)
    assert dst.schedule_digest(evs) == dst.schedule_digest(list(evs))
    assert dst.schedule_digest(evs) != dst.schedule_digest(evs[:-1])


@pytest.mark.slow
@pytest.mark.dst
def test_same_seed_replays_byte_identical_in_process():
    r1 = dst.run_schedule(3)
    r2 = dst.run_schedule(3)
    assert r1["digest"] == r2["digest"]
    assert r1["trace"] == r2["trace"]
    assert r1["violation"] is None


@pytest.mark.slow
@pytest.mark.dst
def test_trace_byte_identical_across_three_hashseeds():
    """The acceptance pin: the same DST_SEED produces a byte-identical
    event trace across 3 runs AND 3 PYTHONHASHSEEDs."""
    digests = set()
    for hashseed in ("0", "1", "42"):
        env = dict(os.environ, PYTHONHASHSEED=hashseed,
                   JAX_PLATFORMS="cpu")
        env.pop("CILIUM_TPU_DST_MUTATION", None)
        out = subprocess.run(
            [sys.executable, "-c",
             "from cilium_tpu.runtime import dst; "
             "print(dst.run_schedule(7)['digest'])"],
            capture_output=True, text=True, timeout=480, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        digests.add(out.stdout.strip().splitlines()[-1])
    assert len(digests) == 1, digests


@pytest.mark.slow
@pytest.mark.dst
def test_clean_tree_slice_has_zero_violations():
    """A tier-friendly slice of `make dst`: the shipped tree violates
    no invariant over a handful of seeded schedules."""
    ran, failing = dst.search(6, seed0=100)
    assert ran == 6
    assert failing is None, failing and failing["violation"]


def test_shrink_is_ddmin_minimal_on_a_synthetic_predicate(monkeypatch):
    """The shrinker contract, isolated from the (slow) world: ddmin
    over run_schedule keeps any subset that still violates and stops
    at 1-minimality."""
    def fake_run(seed, events=None, cache_dir=None, max_events=12):
        events = events if events is not None else dst.generate(seed)
        # "violates" iff the schedule still contains BOTH markers
        bad = (["fault", "loader.swap", 1] in events
               and ["drain-restore"] in events)
        return {"seed": seed, "events": events, "trace": [],
                "digest": "x", "schedule_digest": "y",
                "violation": ({"index": 0, "invariant": "synthetic",
                               "detail": ""} if bad else None)}

    monkeypatch.setattr(dst, "run_schedule", fake_run)
    events = [["traffic"], ["fault", "loader.swap", 1], ["advance", 2.0],
              ["storm", 8], ["drain-restore"], ["traffic"], ["churn",
              "add", 0]]
    best = dst.shrink(0, events)
    assert best["violation"] is not None
    assert sorted(map(str, best["events"])) == sorted(map(str, [
        ["fault", "loader.swap", 1], ["drain-restore"]]))


@pytest.mark.slow
@pytest.mark.dst
@pytest.mark.parametrize("case", sorted(
    os.listdir(REGRESSION_DIR)) if os.path.isdir(REGRESSION_DIR)
    else [])
def test_regression_corpus_replays(case, monkeypatch):
    """Every shrunken schedule committed under regressions/ must keep
    reproducing its violation (with its recorded mutation armed) —
    the committable-regression half of the shrink contract."""
    with open(os.path.join(REGRESSION_DIR, case)) as fp:
        data = json.load(fp)
    assert data["format"] == dst.SCHEDULE_FORMAT
    if data.get("mutation"):
        monkeypatch.setenv("CILIUM_TPU_DST_MUTATION", data["mutation"])
    else:
        monkeypatch.delenv("CILIUM_TPU_DST_MUTATION", raising=False)
    res = dst.run_schedule(data["seed"], events=data["events"])
    assert res["violation"] is not None, \
        f"{case} no longer reproduces its violation"
    assert res["violation"]["invariant"] == \
        data["violation"]["invariant"]


def test_dst_stamp_rides_bench_lines(monkeypatch):
    """Provenance satellite: CILIUM_TPU_DST_SEED/_DIGEST on the
    environment land as the `dst` rider on every stamped bench line."""
    from cilium_tpu.runtime.provenance import stamp

    monkeypatch.setenv("CILIUM_TPU_DST_SEED", "41")
    monkeypatch.setenv("CILIUM_TPU_DST_DIGEST", "abc123")
    line = stamp({"metric": "x", "value": 1}, rtt=False)
    assert line["dst"] == {"dst_seed": 41, "schedule_digest": "abc123"}
    monkeypatch.delenv("CILIUM_TPU_DST_SEED")
    line2 = stamp({"metric": "x", "value": 1}, rtt=False)
    assert "dst" not in line2
