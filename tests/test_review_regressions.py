"""Regression tests for review findings (round-1 code review):

1. L7-wildcard-wins across two PortRules on the same port
2. flows without an L7 record must not match L7 rules (engine)
3. non-ASCII strings: UTF-8 byte-level matching, no crash
4. merged entries with multiple L7 protocol families keep all families
5. mid-pattern (?i) rejected (Python re would crash at verdict time)
6. duplicate header instances: any-instance semantics both sides
"""

import numpy as np
import pytest

from cilium_tpu.core.flow import (
    Flow,
    HTTPInfo,
    KafkaInfo,
    L7Type,
    Protocol,
    TrafficDirection,
    Verdict,
)
from cilium_tpu.core.identity import IdentityAllocator
from cilium_tpu.core.labels import LabelSet
from cilium_tpu.policy.api import (
    EndpointSelector,
    IngressRule,
    L7Rules,
    PortProtocol,
    PortRule,
    PortRuleDNS,
    PortRuleHTTP,
    PortRuleKafka,
    Rule,
)
from cilium_tpu.policy.mapstate import PolicyResolver
from cilium_tpu.policy.oracle import OracleVerdictEngine
from cilium_tpu.policy.repository import Repository
from cilium_tpu.policy.selectorcache import SelectorCache
from cilium_tpu.engine.verdict import CompiledPolicy, VerdictEngine

ING = TrafficDirection.INGRESS
F, D, R = int(Verdict.FORWARDED), int(Verdict.DROPPED), int(Verdict.REDIRECTED)


def _engines(rules, endpoints):
    alloc = IdentityAllocator()
    ids = {n: alloc.allocate(LabelSet.from_dict(l))
           for n, l in endpoints.items()}
    cache = SelectorCache(alloc)
    repo = Repository()
    repo.add(rules, sanitize=False)
    resolver = PolicyResolver(repo, cache)
    per_identity = {
        ids[n]: resolver.resolve(alloc.lookup(ids[n])) for n in endpoints
    }
    return (OracleVerdictEngine(per_identity),
            VerdictEngine(CompiledPolicy.build(per_identity)), ids)


def _both(oracle, engine, flows):
    want = oracle.verdict_flows(flows)["verdict"]
    got = engine.verdict_flows(flows)["verdict"]
    np.testing.assert_array_equal(got, want)
    return list(want)


def test_l7_wildcard_wins_across_port_rules():
    # one IngressRule with two PortRules on port 80: plain allow +
    # HTTP-restricted — the plain allow's wildcard must survive
    rules = [Rule(
        endpoint_selector=EndpointSelector.from_labels(app="srv"),
        ingress=(IngressRule(to_ports=(
            PortRule(ports=(PortProtocol(80, Protocol.TCP),)),
            PortRule(ports=(PortProtocol(80, Protocol.TCP),),
                     rules=L7Rules(http=(PortRuleHTTP(method="GET"),))),
        )),),
    )]
    oracle, engine, ids = _engines(rules, {"srv": {"app": "srv"},
                                           "cli": {"app": "cli"}})
    flows = [Flow(src_identity=ids["cli"], dst_identity=ids["srv"],
                  dport=80, protocol=Protocol.TCP, direction=ING,
                  l7=L7Type.HTTP,
                  http=HTTPInfo(method="POST", path="/x"))]
    verdicts = _both(oracle, engine, flows)
    assert verdicts == [F]  # wildcard wins → FORWARDED, not dropped


def test_non_l7_flow_does_not_match_l7_rules():
    rules = [Rule(
        endpoint_selector=EndpointSelector.from_labels(app="kafka"),
        ingress=(IngressRule(to_ports=(PortRule(
            ports=(PortProtocol(9092, Protocol.TCP),),
            rules=L7Rules(kafka=(PortRuleKafka(role="produce"),)),
        ),)),),
    )]
    oracle, engine, ids = _engines(rules, {"kafka": {"app": "kafka"},
                                           "cli": {"app": "cli"}})
    plain_tcp = Flow(src_identity=ids["cli"], dst_identity=ids["kafka"],
                     dport=9092, protocol=Protocol.TCP, direction=ING)
    empty_http_rule_target = Flow(
        src_identity=ids["cli"], dst_identity=ids["kafka"], dport=9092,
        protocol=Protocol.TCP, direction=ING, l7=L7Type.KAFKA,
        kafka=KafkaInfo(api_key=0, topic="t"))
    verdicts = _both(oracle, engine, [plain_tcp, empty_http_rule_target])
    assert verdicts == [D, R]


def test_utf8_strings_no_crash_and_match():
    rules = [Rule(
        endpoint_selector=EndpointSelector.from_labels(app="srv"),
        ingress=(IngressRule(to_ports=(PortRule(
            ports=(PortProtocol(80, Protocol.TCP),),
            rules=L7Rules(http=(PortRuleHTTP(path="/café/.*"),)),
        ),)),),
    )]
    oracle, engine, ids = _engines(rules, {"srv": {"app": "srv"},
                                           "cli": {"app": "cli"}})
    def flow(path):
        return Flow(src_identity=ids["cli"], dst_identity=ids["srv"],
                    dport=80, protocol=Protocol.TCP, direction=ING,
                    l7=L7Type.HTTP, http=HTTPInfo(method="GET", path=path))
    verdicts = _both(oracle, engine,
                     [flow("/café/中文"), flow("/cafe/x"), flow("/café/")])
    assert verdicts[0] == R
    assert verdicts[1] == D


def test_mixed_protocol_families_merge():
    # two rules, same port, one HTTP one DNS → merged entry keeps both
    sel = EndpointSelector.from_labels(app="multi")
    rules = [
        Rule(endpoint_selector=sel,
             ingress=(IngressRule(to_ports=(PortRule(
                 ports=(PortProtocol(5353, Protocol.UDP),),
                 rules=L7Rules(http=(PortRuleHTTP(path="/h"),)),
             ),)),)),
        Rule(endpoint_selector=sel,
             ingress=(IngressRule(to_ports=(PortRule(
                 ports=(PortProtocol(5353, Protocol.UDP),),
                 rules=L7Rules(dns=(PortRuleDNS(match_name="ok.io"),)),
             ),)),)),
    ]
    oracle, engine, ids = _engines(rules, {"multi": {"app": "multi"},
                                           "cli": {"app": "cli"}})
    from cilium_tpu.core.flow import DNSInfo

    dns_flow = Flow(src_identity=ids["cli"], dst_identity=ids["multi"],
                    dport=5353, protocol=Protocol.UDP, direction=ING,
                    l7=L7Type.DNS, dns=DNSInfo(query="ok.io"))
    verdicts = _both(oracle, engine, [dns_flow])
    assert verdicts == [R]  # dns family must not be dropped from ruleset


def test_mid_pattern_inline_flag_rejected():
    from cilium_tpu.policy.compiler import regex_parser as rp

    with pytest.raises(rp.RegexError):
        rp.parse("abc(?i)def")
    assert rp.parse("(?i)abc") is not None


def test_duplicate_headers_any_instance():
    rules = [Rule(
        endpoint_selector=EndpointSelector.from_labels(app="srv"),
        ingress=(IngressRule(to_ports=(PortRule(
            ports=(PortProtocol(80, Protocol.TCP),),
            rules=L7Rules(http=(PortRuleHTTP(headers=("X-A: 1",)),)),
        ),)),),
    )]
    oracle, engine, ids = _engines(rules, {"srv": {"app": "srv"},
                                           "cli": {"app": "cli"}})
    def flow(headers):
        return Flow(src_identity=ids["cli"], dst_identity=ids["srv"],
                    dport=80, protocol=Protocol.TCP, direction=ING,
                    l7=L7Type.HTTP,
                    http=HTTPInfo(method="GET", path="/", headers=headers))
    verdicts = _both(oracle, engine, [
        flow((("X-A", "1"), ("X-A", "2"))),   # any instance matches → allow
        flow((("X-A", "2"),)),                # no instance matches → drop
    ])
    assert verdicts == [R, D]
