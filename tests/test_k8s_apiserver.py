"""K8s layer (SURVEY §2.4): fake-apiserver list/watch semantics and the
Reflector/Informer contract the reference's pkg/k8s watchers rely on.
"""

import collections
import threading
import time

import pytest

from cilium_tpu.k8s.apiserver import (
    APIServer,
    Conflict,
    K8sClient,
    NotFound,
    ResourceStore,
    WatchGone,
)
from cilium_tpu.k8s.informer import Informer


def cnp(name, ns="default", port="80"):
    return {
        "apiVersion": "cilium.io/v2",
        "kind": "CiliumNetworkPolicy",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"app": "web"}}],
                "toPorts": [{"ports": [
                    {"port": port, "protocol": "TCP"}]}],
            }],
        },
    }


# -- store semantics ------------------------------------------------------

def test_crud_and_resource_versions():
    s = ResourceStore()
    a = s.create("ciliumnetworkpolicies", cnp("a"))
    b = s.create("ciliumnetworkpolicies", cnp("b"))
    assert int(b["metadata"]["resourceVersion"]) > \
        int(a["metadata"]["resourceVersion"])
    assert a["metadata"]["uid"] != b["metadata"]["uid"]
    got = s.get("ciliumnetworkpolicies", "default", "a")
    assert got["spec"] == cnp("a")["spec"]
    listing = s.list("ciliumnetworkpolicies")
    assert {o["metadata"]["name"] for o in listing["items"]} == {"a", "b"}
    assert listing["resource_version"] == b["metadata"]["resourceVersion"]
    gone = s.delete("ciliumnetworkpolicies", "default", "a")
    assert gone["metadata"]["name"] == "a"
    with pytest.raises(NotFound):
        s.get("ciliumnetworkpolicies", "default", "a")


def test_create_conflict_and_unknown_resource():
    s = ResourceStore()
    s.create("ciliumnetworkpolicies", cnp("a"))
    with pytest.raises(Conflict):
        s.create("ciliumnetworkpolicies", cnp("a"))
    with pytest.raises(NotFound):
        s.list("widgets")


def test_update_optimistic_concurrency_and_generation():
    s = ResourceStore()
    a = s.create("ciliumnetworkpolicies", cnp("a"))
    fresh = cnp("a", port="443")
    fresh["metadata"]["resourceVersion"] = a["metadata"]["resourceVersion"]
    a2 = s.update("ciliumnetworkpolicies", fresh)
    assert a2["metadata"]["generation"] == 2  # spec changed
    assert a2["metadata"]["uid"] == a["metadata"]["uid"]
    # stale rv conflicts (optimistic concurrency)
    stale = cnp("a", port="8080")
    stale["metadata"]["resourceVersion"] = a["metadata"]["resourceVersion"]
    with pytest.raises(Conflict):
        s.update("ciliumnetworkpolicies", stale)
    # rv-less update is a forced write (kubectl replace --force analog)
    forced = cnp("a", port="9090")
    a3 = s.update("ciliumnetworkpolicies", forced)
    assert a3["metadata"]["generation"] == 3


def test_cluster_scoped_resources_drop_namespace():
    s = ResourceStore()
    node = s.create("ciliumnodes", {
        "metadata": {"name": "n1", "namespace": "ignored"},
        "spec": {"podCIDR": "10.0.0.0/24"}})
    assert "namespace" not in node["metadata"]
    assert s.get("ciliumnodes", "", "n1")["spec"]["podCIDR"] \
        == "10.0.0.0/24"


def test_watch_replays_strictly_after_rv_and_follows():
    s = ResourceStore()
    a = s.create("ciliumnetworkpolicies", cnp("a"))
    b = s.create("ciliumnetworkpolicies", cnp("b"))
    seen = []
    w = s.watch("ciliumnetworkpolicies",
                a["metadata"]["resourceVersion"], seen.append)
    try:
        # replay: only b (strictly after a's rv)
        assert [e["object"]["metadata"]["name"] for e in seen] == ["b"]
        assert seen[0]["type"] == "ADDED"
        s.delete("ciliumnetworkpolicies", "default", "b")
        assert seen[-1]["type"] == "DELETED"
        # other resources don't leak into this watch
        s.create("ciliumnodes", {"metadata": {"name": "n1"}})
        assert all(e["object"]["kind"] == "CiliumNetworkPolicy"
                   for e in seen)
    finally:
        w.stop()


def test_watch_gone_on_instance_change_or_future_rv():
    """A reflector resuming against a RESTARTED apiserver (fresh store,
    rv counter reset) must get 410 immediately — a coincidentally-valid
    rv from the old history silently resumes into the wrong history
    otherwise. Both guards: instance token mismatch, and future rv."""
    s = ResourceStore()
    s.create("ciliumnetworkpolicies", cnp("a"))
    rv = s.list("ciliumnetworkpolicies")["resource_version"]
    # same instance + current rv: fine
    s.watch("ciliumnetworkpolicies", rv, lambda e: None,
            instance=s.instance).stop()
    with pytest.raises(WatchGone):
        s.watch("ciliumnetworkpolicies", rv, lambda e: None,
                instance="someone-elses-history")
    with pytest.raises(WatchGone):
        s.watch("ciliumnetworkpolicies", str(int(rv) + 50),
                lambda e: None, instance=s.instance)


def test_watch_gone_when_history_compacted():
    s = ResourceStore()
    s._events = collections.deque(maxlen=4)  # tiny retention
    first = s.create("ciliumnetworkpolicies", cnp("a"))
    for i in range(6):
        s.create("ciliumnetworkpolicies", cnp(f"x{i}"))
    with pytest.raises(WatchGone):
        s.watch("ciliumnetworkpolicies",
                first["metadata"]["resourceVersion"], lambda e: None)
    # watching from the current list rv is always fine
    rv = s.list("ciliumnetworkpolicies")["resource_version"]
    s.watch("ciliumnetworkpolicies", rv, lambda e: None).stop()


# -- socket server + client -----------------------------------------------

def test_client_crud_apply_and_errors(tmp_path):
    server = APIServer(str(tmp_path / "k8s.sock")).start()
    try:
        c = K8sClient(server.socket_path)
        made = c.create("ciliumnetworkpolicies", cnp("a"))
        assert made["metadata"]["uid"]
        with pytest.raises(Conflict):
            c.create("ciliumnetworkpolicies", cnp("a"))
        with pytest.raises(NotFound):
            c.get("ciliumnetworkpolicies", "nope")
        # apply: update existing without handing in an rv
        applied = c.apply("ciliumnetworkpolicies", cnp("a", port="443"))
        assert applied["metadata"]["generation"] == 2
        # apply: creates missing
        c.apply("ciliumnetworkpolicies", cnp("b"))
        names = {o["metadata"]["name"]
                 for o in c.list("ciliumnetworkpolicies")["items"]}
        assert names == {"a", "b"}
        c.delete("ciliumnetworkpolicies", "b")
        assert len(c.list("ciliumnetworkpolicies")["items"]) == 1
    finally:
        server.stop()


def test_informer_converges_under_concurrent_churn(tmp_path):
    """Property: after a storm of concurrent writers (create/update/
    delete races, conflict retries), every informer's local store
    converges to exactly the server's final listing. Exercises the
    rv-ordered delivery guarantee — with emission and delivery in
    separate critical sections, a stale object's event can arrive last
    and stick in the informer cache until a relist."""
    import random

    server = APIServer(str(tmp_path / "k8s.sock")).start()
    c = K8sClient(server.socket_path)
    inf = Informer(K8sClient(server.socket_path),
                   "ciliumnetworkpolicies").start()
    names = [f"obj-{i}" for i in range(6)]

    def writer(seed):
        rng = random.Random(seed)
        cli = K8sClient(server.socket_path)
        for i in range(40):
            name = rng.choice(names)
            op = rng.random()
            try:
                if op < 0.5:
                    cli.apply("ciliumnetworkpolicies",
                              cnp(name, port=str(1000 + seed * 100 + i)))
                elif op < 0.75:
                    cli.create("ciliumnetworkpolicies", cnp(name))
                else:
                    cli.delete("ciliumnetworkpolicies", name)
            except (Conflict, NotFound):
                pass  # racing writers; both are expected outcomes

    threads = [threading.Thread(target=writer, args=(s,))
               for s in range(4)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not any(t.is_alive() for t in threads)

        final = {o["metadata"]["name"]: o["metadata"]["resourceVersion"]
                 for o in c.list("ciliumnetworkpolicies")["items"]}

        def synced():
            with inf._lock:
                mine = {n: o["metadata"]["resourceVersion"]
                        for (_, n), o in inf.store.items()}
            return mine == final

        converged = wait_until(synced, timeout=30)
        with inf._lock:  # snapshot for the diagnostic: the watch
            cached = {n: o["metadata"]["resourceVersion"]  # thread may
                      for (_, n), o in inf.store.items()}  # still run
        assert converged, (final, cached)
        # specs match too, not just versions
        for o in c.list("ciliumnetworkpolicies")["items"]:
            key = (o["metadata"].get("namespace", ""),
                   o["metadata"]["name"])
            assert inf.store[key]["spec"] == o["spec"]
    finally:
        inf.stop()
        server.stop()


# -- informer -------------------------------------------------------------

def wait_until(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_informer_sync_follow_update_delete(tmp_path):
    server = APIServer(str(tmp_path / "k8s.sock")).start()
    events = []
    lock = threading.Lock()

    def rec(kind):
        def h(*objs):
            with lock:
                events.append((kind, objs[-1]["metadata"]["name"]))
        return h

    try:
        c = K8sClient(server.socket_path)
        c.create("ciliumnetworkpolicies", cnp("pre"))
        inf = Informer(c, "ciliumnetworkpolicies",
                       on_add=rec("add"), on_update=rec("update"),
                       on_delete=rec("del")).start()
        try:
            # initial list is synchronous
            assert ("add", "pre") in events
            c.create("ciliumnetworkpolicies", cnp("live"))
            assert wait_until(lambda: ("add", "live") in events)
            c.apply("ciliumnetworkpolicies", cnp("live", port="443"))
            assert wait_until(lambda: ("update", "live") in events)
            c.delete("ciliumnetworkpolicies", "live")
            assert wait_until(lambda: ("del", "live") in events)
            assert ("live", ) not in inf.store
        finally:
            inf.stop()
    finally:
        server.stop()


def test_informer_relists_across_server_restart(tmp_path):
    """The Reflector contract: a dead apiserver (or compacted watch)
    means relist — changes made while the watcher was blind surface as
    deltas, including deletes."""
    path = str(tmp_path / "k8s.sock")
    server = APIServer(path).start()
    events = []

    def rec(kind):
        return lambda *objs: events.append(
            (kind, objs[-1]["metadata"]["name"]))

    c = K8sClient(path)
    c.create("ciliumnetworkpolicies", cnp("keep"))
    c.create("ciliumnetworkpolicies", cnp("drop"))
    inf = Informer(c, "ciliumnetworkpolicies",
                   on_add=rec("add"), on_update=rec("update"),
                   on_delete=rec("del")).start()
    try:
        assert {("add", "keep"), ("add", "drop")} <= set(events)
        lists_before = inf.list_count
        server.stop()
        # a NEW apiserver (fresh store: rv restarts) — while the
        # informer was blind, 'drop' vanished and 'new' appeared
        server = APIServer(path).start()
        c2 = K8sClient(path)
        c2.create("ciliumnetworkpolicies", cnp("keep"))
        c2.create("ciliumnetworkpolicies", cnp("new"))
        assert wait_until(lambda: inf.list_count > lists_before
                          and ("add", "new") in events
                          and ("del", "drop") in events, timeout=30)
        assert ("default", "drop") not in inf.store
        assert ("default", "new") in inf.store
    finally:
        inf.stop()
        server.stop()
