"""Regression tests for the round-1 review-4 findings."""

import numpy as np
import pytest

from cilium_tpu.ipam import ClusterPool, NodeAllocator, PoolExhausted


def test_endpoint_repin_to_taken_ip_keeps_old_state():
    """A failed re-pin must not tear down the endpoint's existing IP."""
    from cilium_tpu.agent import Agent
    from cilium_tpu.core.config import Config

    cfg = Config()
    agent = Agent(cfg)
    try:
        ep1 = agent.endpoint_add(1, {"app": "a"}, ipv4="10.0.0.5")
        agent.endpoint_add(2, {"app": "b"}, ipv4="10.0.0.6")
        with pytest.raises(PoolExhausted):
            agent.endpoint_add(1, {"app": "a"}, ipv4="10.0.0.6")
        # old pin fully intact: endpoint, ipcache entry, IPAM ownership
        assert agent.endpoint_manager.get(1).ipv4 == "10.0.0.5"
        assert agent.ipcache.lookup("10.0.0.5") == ep1.identity
        with pytest.raises(PoolExhausted):
            agent.ipam.allocate_ip("10.0.0.5")
    finally:
        agent.stop()


def test_cluster_pool_cursor_reclaims_released():
    pool = ClusterPool("10.128.0.0/20", node_mask_size=24)
    cidrs = [pool.allocate_node_cidr(f"n{i}") for i in range(16)]
    assert len(set(cidrs)) == 16
    with pytest.raises(PoolExhausted):
        pool.allocate_node_cidr("overflow")
    pool.release_node_cidr("n3")
    assert pool.allocate_node_cidr("n3b") == cidrs[3]  # wraps to the hole


def test_cluster_pool_allocation_is_fast_for_many_nodes():
    # /8 pool, /24 nodes: must not rescan 2^16 subnets per allocation
    import time

    pool = ClusterPool("10.0.0.0/8", node_mask_size=24)
    t0 = time.monotonic()
    for i in range(2000):
        pool.allocate_node_cidr(f"node-{i}")
    assert time.monotonic() - t0 < 2.0


def test_tp_state_count_guard():
    from cilium_tpu.parallel.tp import MAX_TP_STATES, _check_state_count

    _check_state_count(MAX_TP_STATES - 1)
    with pytest.raises(ValueError):
        _check_state_count(MAX_TP_STATES)


def test_pipeline_releases_consumed_batches():
    import jax

    from cilium_tpu.parallel.pipeline import run_pipelined

    seen_staged = []

    def step(arrays, batch):
        return {"x": batch["x"] + 1}

    batches = [{"x": np.full((4,), i, dtype=np.int32)} for i in range(6)]

    outs = run_pipelined(step, {}, batches, depth=2)
    vals = [int(np.asarray(o["x"])[0]) for o in outs]
    assert vals == [1, 2, 3, 4, 5, 6]
