"""Content-addressed automaton banks (policy/compiler/bankplan.py) +
the loader's churn-proof policy plane (ISSUE 8): the partition is a
pure function of the pattern set, a CNP add/delete recompiles O(Δ)
banks, a per-bank compile failure quarantines only its bank, and
commits carry bank-scoped invalidation deltas instead of a global
memo drop."""

import tempfile

import numpy as np
import pytest

from cilium_tpu.core.config import Config, EngineConfig
from cilium_tpu.core.flow import (
    Flow,
    HTTPInfo,
    L7Type,
    Protocol,
    TrafficDirection,
)
from cilium_tpu.policy.compiler.bankplan import (
    BankRegistry,
    bank_key,
    partition_patterns,
)
from cilium_tpu.policy.compiler.dfa import compile_patterns, match_bank_numpy
from cilium_tpu.runtime import faults
from cilium_tpu.runtime.faults import FaultPlan, FaultRule
from cilium_tpu.runtime.loader import Loader, identity_fingerprints


# ---------------------------------------------------------------------------
# Partition: pure function of the set, O(Δ) locality


def test_partition_is_a_pure_function_of_the_set():
    pats = [f"/svc{i}/.*" for i in range(50)]
    a = partition_patterns(pats, 8)
    b = partition_patterns(list(reversed(pats)), 8)       # order-free
    c = partition_patterns(pats + pats[:10], 8)           # dup-free
    assert a == b == c
    assert sorted(p for g in a for p in g) == sorted(set(pats))


def test_partition_add_then_delete_returns_original_banks():
    """The property the churn plane rests on: any add/delete sequence
    that nets out returns the EXACT original bank set (same groups,
    same content-addressed keys)."""
    base = [f"/svc{i}/.*" for i in range(60)]
    opts = (8192, 64, False)
    orig = partition_patterns(base, 8)
    orig_keys = [bank_key(g, opts) for g in orig]
    for extra in (["/zzz/.*"], ["/aaa/.*", "/mmm/.*"],
                  [f"/churn{i}/x" for i in range(9)]):
        grown = partition_patterns(base + extra, 8)
        shrunk = partition_patterns(
            [p for p in base + extra if p not in set(extra)], 8)
        assert shrunk == orig
        assert [bank_key(g, opts) for g in shrunk] == orig_keys
        assert grown != orig  # the add really moved SOME bank


def test_partition_perturbation_is_local():
    """One added pattern changes O(1) groups, not O(total) — the
    content-defined boundary property (positional grouping failed
    exactly this: one mid-list delete shifted every later bank)."""
    base = [f"/svc{i}/.*" for i in range(120)]
    before = set(partition_patterns(base, 8))
    for extra in ("/added/a.*", "/added/b.*", "/zz/tail.*"):
        after = set(partition_patterns(base + [extra], 8))
        changed = after ^ before
        # an add splits/extends at most the group it lands in (plus
        # its neighbour when the new pattern is itself a boundary)
        assert len(changed) <= 4, (extra, len(changed))


def test_bank_key_distinguishes_patterns_and_opts():
    g = ("/a/.*", "/b/.*")
    assert bank_key(g, (8192, 64, False)) != \
        bank_key(g, (8192, 64, True))
    assert bank_key(g, (8192, 64, False)) != \
        bank_key(("/a/.*",), (8192, 64, False))
    assert len(bank_key(g, (8192, 64, False))) == 24


# ---------------------------------------------------------------------------
# Registry: parity, reuse, quarantine


def _matches(banked, strings):
    """(row, pattern) accept set via the CPU reference scan."""
    L = max(32, max(len(s) for s in strings))
    data = np.zeros((len(strings), L), dtype=np.uint8)
    lens = np.zeros(len(strings), dtype=np.int32)
    for i, s in enumerate(strings):
        data[i, : len(s)] = np.frombuffer(s, dtype=np.uint8)
        lens[i] = len(s)
    out = set()
    for bi, bank in enumerate(banked.banks):
        w = match_bank_numpy(bank, data, lens)
        for p_i in range(banked.n_patterns):
            if int(banked.pattern_bank[p_i]) != bi:
                continue
            lane = int(banked.pattern_lane[p_i])
            for row in range(len(strings)):
                if w[row, lane // 32] >> (lane % 32) & 1:
                    out.add((row, banked.patterns[p_i]))
    return out


def test_registry_matches_greedy_compiler_bit_for_bit():
    cfg = EngineConfig(bank_size=4)
    pats = [f"/api/v{i}/.*" for i in range(20)] + ["GET", "PUT"]
    banked, stats = BankRegistry().compile_field("path", pats, cfg)
    greedy = compile_patterns(pats, bank_size=4)
    probes = [b"/api/v3/x", b"/api/v15/yy", b"GET", b"/nope"]
    assert _matches(banked, probes) == _matches(greedy, probes)
    assert len(stats.rebuilt) == len(stats.bank_keys) >= 3


def test_registry_reuses_unchanged_groups():
    cfg = EngineConfig(bank_size=4)
    pats = [f"/api/v{i}/.*" for i in range(24)]
    reg = BankRegistry()
    _, s1 = reg.compile_field("path", pats, cfg)
    c0 = reg.compiles
    # unchanged set → zero compiles; one add → O(1) compiles
    _, s2 = reg.compile_field("path", pats, cfg)
    assert reg.compiles == c0 and s2.reused == len(s2.bank_keys)
    _, s3 = reg.compile_field("path", pats + ["/new/.*"], cfg)
    assert 1 <= reg.compiles - c0 <= 2
    assert set(s3.bank_keys) & set(s1.bank_keys), \
        "an add rebuilt every bank"


def test_quarantined_bank_serves_cover_then_fails_closed():
    """A forced compile failure on a CHANGED bank: unchanged banks are
    byte-identically reused, the failed bank's pre-existing patterns
    serve from the last-good cover, and its genuinely-new patterns
    fail CLOSED (never match → allow-list denies)."""
    cfg = EngineConfig(bank_size=4)
    base = [f"/api/v{i}/.*" for i in range(16)]
    reg = BankRegistry(quarantine_ttl_s=30.0)
    banked0, s0 = reg.compile_field("path", base, cfg)
    with faults.inject(FaultPlan(
            [FaultRule("loader.bank_compile", times=1)])):
        banked1, s1 = reg.compile_field("path", base + ["/new/.*"],
                                        cfg)
    assert len(s1.quarantined) == 1
    assert reg.quarantine_events == 1
    probes = [b"/api/v3/x", b"/api/v12/y", b"/new/x"]
    before = _matches(banked0, probes)
    after = _matches(banked1, probes)
    # every pre-existing pattern matches exactly as before...
    assert {(r, p) for r, p in after if p != "/new/.*"} == before
    # ...and the uncompiled new pattern NEVER matches (fail closed)
    assert not any(p == "/new/.*" for _, p in after)


def test_quarantine_ttl_retry_recovers():
    clock = [0.0]
    cfg = EngineConfig(bank_size=4)
    reg = BankRegistry(quarantine_ttl_s=10.0, clock=lambda: clock[0])
    base = [f"/api/v{i}/.*" for i in range(8)]
    reg.compile_field("path", base, cfg)
    with faults.inject(FaultPlan(
            [FaultRule("loader.bank_compile", times=1)])):
        _, s1 = reg.compile_field("path", base + ["/new/.*"], cfg)
    assert s1.quarantined
    # inside the TTL: no re-attempt (still quarantined), no compile
    c0 = reg.compiles
    _, s2 = reg.compile_field("path", base + ["/new/.*"], cfg)
    assert s2.quarantined and reg.compiles == c0
    assert reg.quarantined_serves >= 1
    # past the TTL: the retry compiles and clears the quarantine
    clock[0] = 11.0
    assert reg.expired_quarantines() != ()
    banked3, s3 = reg.compile_field("path", base + ["/new/.*"], cfg)
    assert not s3.quarantined and reg.compiles == c0 + 1
    assert any(p == "/new/.*" for _, p in
               _matches(banked3, [b"/new/x"]))


# ---------------------------------------------------------------------------
# Loader integration: O(Δ) compile, no-op commits, degraded handling


def _policy(paths, port=80):
    from cilium_tpu.core.identity import IdentityAllocator
    from cilium_tpu.core.labels import LabelSet
    from cilium_tpu.policy.api import (
        EndpointSelector,
        IngressRule,
        PortProtocol,
        PortRule,
        Rule,
    )
    from cilium_tpu.policy.api.l7 import L7Rules, PortRuleHTTP
    from cilium_tpu.policy.mapstate import PolicyResolver
    from cilium_tpu.policy.repository import Repository
    from cilium_tpu.policy.selectorcache import SelectorCache

    rules = [Rule(
        endpoint_selector=EndpointSelector.from_labels(app="db"),
        ingress=(IngressRule(
            from_endpoints=(EndpointSelector.from_labels(app="web"),),
            to_ports=(PortRule(
                ports=(PortProtocol(port, Protocol.TCP),),
                rules=L7Rules(http=tuple(
                    PortRuleHTTP(path=p, method="GET")
                    for p in paths))),)),),
    )]
    alloc = IdentityAllocator()
    db = alloc.allocate(LabelSet.from_dict({"app": "db"}))
    web = alloc.allocate(LabelSet.from_dict({"app": "web"}))
    cache = SelectorCache(alloc)
    repo = Repository()
    repo.add(rules, sanitize=False)
    return ({db: PolicyResolver(repo, cache).resolve(alloc.lookup(db))},
            db, web)


def _http_flow(web, db, path, port=80):
    return Flow(src_identity=web, dst_identity=db, dport=port,
                protocol=Protocol.TCP,
                direction=TrafficDirection.INGRESS, l7=L7Type.HTTP,
                http=HTTPInfo(method="GET", path=path))


@pytest.fixture()
def tpu_loader(tmp_path):
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.engine.bank_size = 4
    cfg.loader.cache_dir = str(tmp_path / "cache")
    return Loader(cfg)


def test_loader_cnp_add_recompiles_o_delta_banks(tpu_loader):
    loader = tpu_loader
    paths = [f"/p{i}/.*" for i in range(24)]
    per1, db, web = _policy(paths)
    loader.regenerate(per1, revision=1)
    banks_total = len(loader._bank_plan.get("path", ()))
    assert banks_total >= 4, "scale the policy up: too few banks"
    c0 = loader.bank_registry.compiles
    per2, db, web = _policy(paths + ["/new/.*"])
    loader.regenerate(per2, revision=2)
    delta_compiles = loader.bank_registry.compiles - c0
    assert 1 <= delta_compiles <= 3, \
        f"1-path add recompiled {delta_compiles} groups " \
        f"(of {banks_total}) — not O(Δ)"
    out = loader.engine.verdict_flows(
        [_http_flow(web, db, "/new/x"), _http_flow(web, db, "/p3/x"),
         _http_flow(web, db, "/zz")])
    assert [int(v) for v in out["verdict"]] == [5, 5, 2]


def test_loader_noop_regenerate_keeps_engine_and_emits_noop_delta(
        tpu_loader):
    from cilium_tpu.engine import memo

    loader = tpu_loader
    per1, db, web = _policy([f"/p{i}/.*" for i in range(8)])
    loader.regenerate(per1, revision=1)
    engine1 = loader.engine
    per_same, _, _ = _policy([f"/p{i}/.*" for i in range(8)])
    loader.regenerate(per_same, revision=2)
    assert loader.engine is engine1
    assert loader.revision == 2
    d = memo.POLICY_GENERATION.deltas_since(memo.policy_generation() - 1)
    assert d.is_noop


def test_loader_bank_compile_failure_quarantines_not_aborts(
        tpu_loader):
    loader = tpu_loader
    paths = [f"/p{i}/.*" for i in range(12)]
    per1, db, web = _policy(paths)
    loader.regenerate(per1, revision=1)
    per2, db, web = _policy(paths + ["/fail/.*"])
    golden = [_http_flow(web, db, "/p3/x"), _http_flow(web, db, "/zz")]
    before = [int(v) for v in
              loader.engine.verdict_flows(golden)["verdict"]]
    with faults.inject(FaultPlan(
            [FaultRule("loader.bank_compile", times=1)])):
        loader.regenerate(per2, revision=2)   # must NOT raise
    assert loader.revision == 2
    assert loader._degraded
    st = loader.bank_status()
    assert st["degraded"] and st["quarantine_events"] >= 1
    # every other bank serves bit-identical verdicts; the failed
    # bank's new pattern fails closed
    out = loader.engine.verdict_flows(
        golden + [_http_flow(web, db, "/fail/x")])
    assert [int(v) for v in out["verdict"]][:2] == before
    assert int(out["verdict"][2]) == 2
    # degraded builds are never cached under the clean key: the TTL
    # retry recompiles and recovers
    for q in loader.bank_registry._quarantine.values():
        q.until = 0.0
    loader.regenerate(per2, revision=3)
    assert not loader._degraded
    out = loader.engine.verdict_flows([_http_flow(web, db, "/fail/x")])
    assert int(out["verdict"][0]) == 5


def test_identity_fingerprints_change_only_for_touched_identities():
    per1, db, web = _policy([f"/p{i}/.*" for i in range(4)])
    per2, db2, web2 = _policy([f"/p{i}/.*" for i in range(4)] +
                              ["/new/.*"])
    fp1 = identity_fingerprints(per1)
    fp2 = identity_fingerprints(per2)
    assert fp1.keys() == fp2.keys()
    assert fp1 != fp2                 # the selected identity moved
    # and a byte-identical snapshot fingerprints identically
    per3, _, _ = _policy([f"/p{i}/.*" for i in range(4)])
    assert identity_fingerprints(per3) == fp1


def test_bank_isolation_off_falls_back_to_positional_path(tmp_path):
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.bank_isolation = False
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    assert loader.bank_registry is None
    per1, db, web = _policy(["/a/.*", "/b/.*"])
    loader.regenerate(per1, revision=1)
    out = loader.engine.verdict_flows(
        [_http_flow(web, db, "/a/x"), _http_flow(web, db, "/c/x")])
    assert [int(v) for v in out["verdict"]] == [5, 2]
    assert loader.bank_status() == {"enabled": False}


def test_hypothesis_add_delete_property():
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    pat = st.text(alphabet="abcxyz/.*", min_size=1, max_size=12)

    @settings(max_examples=50, deadline=None)
    @given(base=st.lists(pat, max_size=40, unique=True),
           extra=st.lists(pat, max_size=6, unique=True))
    def prop(base, extra):
        before = partition_patterns(base, 4)
        withx = partition_patterns(base + extra, 4)
        after = partition_patterns(
            [p for p in base + extra if p not in set(extra)
             or p in set(base)], 4)
        assert after == before
        # every pattern appears in exactly one group
        flat = [p for g in withx for p in g]
        assert sorted(flat) == sorted(set(base) | set(extra))

    prop()