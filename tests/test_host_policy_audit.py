"""CCNP nodeSelector (host policy) + policy audit mode.

VERDICT r2 item 4. References: CiliumClusterwideNetworkPolicy.Spec
.NodeSelector + host-firewall enforcement on the host endpoint
(`pkg/k8s/apis/cilium.io/v2`); `pkg/option ·PolicyAuditMode` +
the datapath's audit verdict (flowpb AUDIT=4).
"""

import numpy as np
import pytest

from cilium_tpu.agent import Agent
from cilium_tpu.core.config import Config
from cilium_tpu.core.flow import Flow, Verdict
from cilium_tpu.core.identity import ReservedIdentity
from cilium_tpu.policy.api.cnp import load_cnp_yaml_text
from cilium_tpu.policy.api.rule import SanitizeError

CCNP_NODE = """
apiVersion: cilium.io/v2
kind: CiliumClusterwideNetworkPolicy
metadata: {name: host-fw}
spec:
  nodeSelector: {matchLabels: {node-role: worker}}
  ingress:
  - fromEntities: [cluster]
    toPorts: [{ports: [{port: "22", protocol: TCP}]}]
"""

CNP_PODS = """
apiVersion: cilium.io/v2
kind: CiliumNetworkPolicy
metadata: {name: pod-wide}
spec:
  endpointSelector: {}
  ingress:
  - toPorts: [{ports: [{port: "80", protocol: TCP}]}]
"""


def _agent(offload, audit=False):
    cfg = Config()
    cfg.enable_tpu_offload = offload
    cfg.policy_audit_mode = audit
    cfg.configure_logging = False
    return Agent(cfg)


def test_node_selector_parses_and_requires_ccnp():
    (ccnp,) = load_cnp_yaml_text(CCNP_NODE)
    assert ccnp.rules[0].node_selector
    with pytest.raises(SanitizeError):
        load_cnp_yaml_text(CCNP_NODE.replace(
            "CiliumClusterwideNetworkPolicy", "CiliumNetworkPolicy"))
    with pytest.raises(SanitizeError):
        load_cnp_yaml_text(CCNP_NODE.replace(
            "spec:", "spec:\n  endpointSelector: {}"))


@pytest.mark.parametrize("offload", [False, True])
def test_host_policy_scopes_to_host_endpoint(offload):
    """The nodeSelector CCNP enforces on the host endpoint (identity
    1) and ONLY there; the wildcard pod CNP keeps its hands off the
    host endpoint."""
    agent = _agent(offload)
    try:
        host = agent.host_endpoint_add({"node-role": "worker"})
        pod = agent.endpoint_add(11, {"app": "web"})
        client = agent.endpoint_add(12, {"app": "cli"})
        assert host.identity == int(ReservedIdentity.HOST)
        for cnp in load_cnp_yaml_text(CCNP_NODE + "---\n" + CNP_PODS):
            agent.policy_add(cnp)

        flows = [
            # host:22 from an in-cluster peer — allowed by host policy
            Flow(src_identity=client.identity, dst_identity=host.identity,
                 dport=22),
            # host:80 — the pod-wide CNP must NOT allow it on the host
            Flow(src_identity=client.identity, dst_identity=host.identity,
                 dport=80),
            # pod:80 — pod CNP applies; pod:22 — host CCNP must not
            Flow(src_identity=client.identity, dst_identity=pod.identity,
                 dport=80),
            Flow(src_identity=client.identity, dst_identity=pod.identity,
                 dport=22),
        ]
        got = [int(v) for v in
               agent.loader.engine.verdict_flows(flows)["verdict"]]
        assert got == [int(Verdict.FORWARDED), int(Verdict.DROPPED),
                       int(Verdict.FORWARDED), int(Verdict.DROPPED)]
    finally:
        agent.stop()


@pytest.mark.parametrize("offload", [False, True])
def test_audit_mode_flips_dropped_to_audit_only(offload):
    """Audit mode: every would-be DROPPED becomes AUDIT=4; FORWARDED
    and REDIRECTED verdicts are untouched — on both backends."""
    outs = {}
    for audit in (False, True):
        agent = _agent(offload, audit=audit)
        try:
            svc = agent.endpoint_add(1, {"app": "svc"})
            cli = agent.endpoint_add(2, {"app": "cli"})
            for cnp in load_cnp_yaml_text("""
apiVersion: cilium.io/v2
kind: CiliumNetworkPolicy
metadata: {name: l7}
spec:
  endpointSelector: {matchLabels: {app: svc}}
  ingress:
  - fromEndpoints: [{matchLabels: {app: cli}}]
    toPorts: [{ports: [{port: "80", protocol: TCP}],
               rules: {http: [{method: GET, path: "/ok/.*"}]}}]
"""):
                agent.policy_add(cnp)
            from cilium_tpu.core.flow import HTTPInfo, L7Type

            flows = [
                Flow(src_identity=cli.identity, dst_identity=svc.identity,
                     dport=80, l7=L7Type.HTTP,
                     http=HTTPInfo(method="GET", path="/ok/x")),
                Flow(src_identity=cli.identity, dst_identity=svc.identity,
                     dport=80, l7=L7Type.HTTP,
                     http=HTTPInfo(method="GET", path="/deny/x")),
                Flow(src_identity=cli.identity, dst_identity=svc.identity,
                     dport=81),
            ]
            outs[audit] = [int(v) for v in
                           agent.loader.engine.verdict_flows(
                               flows)["verdict"]]
        finally:
            agent.stop()
    assert outs[False] == [int(Verdict.REDIRECTED), int(Verdict.DROPPED),
                           int(Verdict.DROPPED)]
    assert outs[True] == [int(Verdict.REDIRECTED), int(Verdict.AUDIT),
                          int(Verdict.AUDIT)]


@pytest.mark.parametrize("offload", [False, True])
def test_per_endpoint_audit_mode(offload):
    """VERDICT r3 item 5: endpoint A in PolicyAuditMode AUDITs its
    would-be denial while endpoint B's IDENTICAL flow DROPs — the
    audit bit is per-endpoint in the staged tables, not a fleet-wide
    scalar — on both backends, and flipping the option back restores
    enforcement."""
    agent = _agent(offload, audit=False)
    try:
        a = agent.endpoint_add(1, {"app": "a"})
        b = agent.endpoint_add(2, {"app": "b"})
        cli = agent.endpoint_add(3, {"app": "cli"})
        for cnp in load_cnp_yaml_text("""
apiVersion: cilium.io/v2
kind: CiliumNetworkPolicy
metadata: {name: a}
spec:
  endpointSelector: {matchLabels: {app: a}}
  ingress:
  - fromEndpoints: [{matchLabels: {app: cli}}]
    toPorts: [{ports: [{port: "80", protocol: TCP}]}]
---
apiVersion: cilium.io/v2
kind: CiliumNetworkPolicy
metadata: {name: b}
spec:
  endpointSelector: {matchLabels: {app: b}}
  ingress:
  - fromEndpoints: [{matchLabels: {app: cli}}]
    toPorts: [{ports: [{port: "80", protocol: TCP}]}]
"""):
            agent.policy_add(cnp)
        agent.endpoint_config(1, policy_audit_mode=True)

        flows = [
            # identical denied flows (port 81 not allowed): A audits,
            # B drops
            Flow(src_identity=cli.identity, dst_identity=a.identity,
                 dport=81),
            Flow(src_identity=cli.identity, dst_identity=b.identity,
                 dport=81),
            # allowed traffic unaffected on both
            Flow(src_identity=cli.identity, dst_identity=a.identity,
                 dport=80),
            Flow(src_identity=cli.identity, dst_identity=b.identity,
                 dport=80),
        ]
        got = [int(v) for v in
               agent.loader.engine.verdict_flows(flows)["verdict"]]
        assert got == [int(Verdict.AUDIT), int(Verdict.DROPPED),
                       int(Verdict.FORWARDED), int(Verdict.FORWARDED)]

        # the bit round-trips off: enforcement restores
        agent.endpoint_config(1, policy_audit_mode=False)
        got = [int(v) for v in
               agent.loader.engine.verdict_flows(flows[:2])["verdict"]]
        assert got == [int(Verdict.DROPPED), int(Verdict.DROPPED)]
    finally:
        agent.stop()


def test_audit_mode_engine_oracle_parity():
    """Hypothesis-lite sweep: audit engine == audit oracle across the
    synth http scenario, and equals the non-audit verdicts with
    DROPPED→AUDIT substituted."""
    from cilium_tpu.ingest import synth
    from cilium_tpu.policy.oracle import OracleVerdictEngine
    from cilium_tpu.runtime.loader import Loader

    scenario = synth.synth_http_scenario(n_rules=20, n_flows=200)
    per_identity, scenario = synth.realize_scenario(scenario)

    cfg = Config()
    cfg.enable_tpu_offload = True
    base = Loader(cfg).regenerate(per_identity, revision=1) \
        .verdict_flows(scenario.flows)["verdict"]

    cfg_a = Config()
    cfg_a.enable_tpu_offload = True
    cfg_a.policy_audit_mode = True
    audited = Loader(cfg_a).regenerate(per_identity, revision=1) \
        .verdict_flows(scenario.flows)["verdict"]

    oracle = OracleVerdictEngine(per_identity, audit=True) \
        .verdict_flows(scenario.flows)["verdict"]

    np.testing.assert_array_equal(audited, oracle)
    want = np.where(base == int(Verdict.DROPPED), int(Verdict.AUDIT),
                    base)
    np.testing.assert_array_equal(audited, want)
    assert int(Verdict.AUDIT) in audited.tolist()
