"""runtime/tenant.py (ISSUE 20): the tenant partition vocabulary —
range/weight parsing, the identity→tenant map, the TTL'd quota store
with its conservative default, the rotating weighted-fair admission
window — and the AdmissionGate's tenant-fairness integration (a
storming tenant sheds ``tenant-quota`` with the tenant on the label
while other tenants keep admitting)."""

import pytest

from cilium_tpu.core.config import Config
from cilium_tpu.runtime import admission
from cilium_tpu.runtime.admission import (
    CLASS_CONTROL,
    CLASS_DATA,
    SHED_TENANT_QUOTA,
    AdmissionGate,
)
from cilium_tpu.runtime.metrics import ADMISSION_SHED, METRICS
from cilium_tpu.runtime.tenant import (
    DEFAULT_TENANT,
    FairShareWindow,
    TenantMap,
    TenantQuotas,
    parse_ranges,
    parse_weights,
)


def _metric(name, labels=None):
    return METRICS.get(name, labels)


# ---------------------------------------------------------------------------
# parsing


def test_parse_ranges_and_weights():
    assert parse_ranges(["a:100-199", "b:200-299"]) == (
        ("a", 100, 199), ("b", 200, 299))
    assert parse_weights(["a:2.0", "b:0.5"]) == {"a": 2.0, "b": 0.5}


@pytest.mark.parametrize("bad", ["a", "a:", ":100-200", "a:100",
                                 "a:-200"])
def test_parse_ranges_rejects_malformed_at_config_time(bad):
    with pytest.raises(ValueError):
        parse_ranges([bad])


def test_parse_weights_rejects_zero_and_negative():
    # a zero-weight tenant could never drain its queue
    with pytest.raises(ValueError):
        parse_weights(["a:0"])
    with pytest.raises(ValueError):
        parse_weights(["a:-1.5"])


# ---------------------------------------------------------------------------
# TenantMap


def test_tenant_map_first_match_wins_and_default():
    tm = TenantMap(ranges=("a:100-199", "b:150-299"),
                   weights=("a:2.0",))
    assert tm.tenant_of(100) == "a"
    assert tm.tenant_of(199) == "a"
    assert tm.tenant_of(150) == "a"      # overlapping: first declared
    assert tm.tenant_of(200) == "b"
    assert tm.tenant_of(5) == DEFAULT_TENANT
    assert tm.weight_of("a") == 2.0
    assert tm.weight_of("b") == 1.0      # undeclared weighs 1.0
    assert tm.tenants() == ("a", "b")


def test_tenant_map_from_config():
    cfg = Config()
    cfg.tenant.ranges = ("x:1-10",)
    cfg.tenant.default_tenant = "house"
    tm = TenantMap.from_config(cfg)
    assert tm.tenant_of(5) == "x"
    assert tm.tenant_of(99) == "house"


# ---------------------------------------------------------------------------
# TenantQuotas


def test_quota_ttl_lapses_at_exactly_the_tick():
    now = [0.0]
    q = TenantQuotas(default_share=0.3, ttl_s=10.0,
                     clock=lambda: now[0])
    q.set_share("a", 0.8)
    assert q.share_of("a") == 0.8
    now[0] = 10.0 - 1e-9
    assert q.share_of("a") == 0.8
    now[0] = 10.0                        # closed boundary: lapsed AT
    assert q.share_of("a") == 0.3
    # the lapse dropped the entry — a refresh starts a fresh TTL
    q.set_share("a", 0.9)
    now[0] = 19.0
    assert q.share_of("a") == 0.9
    assert q.status()["default_share"] == 0.3


# ---------------------------------------------------------------------------
# FairShareWindow


def test_window_rotates_at_exactly_the_quantum_tick():
    now = [0.0]
    w = FairShareWindow(quantum_s=1.0, max_share=0.5,
                        clock=lambda: now[0])
    for _ in range(4):
        w.note("a")
    assert w.counts() == {"a": 4}
    now[0] = 1.0 - 1e-9
    w.note("a")
    assert w.counts() == {"a": 5}        # still the same window
    now[0] = 1.0                         # closed boundary: rotate AT
    w.note("a")
    assert w.counts() == {"a": 1}
    # rotation lands on the quantum grid even after an idle gap
    now[0] = 5.7
    w.note("b")
    assert w.window_start() == 5.0


def test_over_share_judges_current_share_not_next_request():
    """Two equal tenants at exact equilibrium both ADMIT (alternation,
    not mutual shed); the tenant strictly past both the cap and its
    weighted fair share is over."""
    w = FairShareWindow(quantum_s=100.0, max_share=0.4,
                        clock=lambda: 0.0)
    for _ in range(3):
        w.note("a")
        w.note("b")
    # 50/50: both past the 0.4 cap but AT fair share — neither sheds
    assert not w.over_share("a")
    assert not w.over_share("b")
    w.note("a")                          # a: 4/7 > cap and > 0.5 fair
    assert w.over_share("a")
    assert not w.over_share("b")


def test_over_share_lone_tenant_never_penalized():
    w = FairShareWindow(quantum_s=100.0, max_share=0.2,
                        clock=lambda: 0.0)
    for _ in range(50):
        w.note("a")
    # frac 1.0 > cap, but fair share among {a} alone is 1.0
    assert not w.over_share("a")


def test_over_share_respects_weights_and_cap_override():
    w = FairShareWindow(quantum_s=100.0, max_share=0.1,
                        weight_of=lambda t: 3.0 if t == "big" else 1.0,
                        clock=lambda: 0.0)
    for _ in range(3):
        w.note("big")
    w.note("small")
    # big holds 3/4 = fair share exactly (3/(3+1)) — not over
    assert not w.over_share("big")
    assert not w.over_share("small")
    w.note("big")                        # 4/5 > 0.75 fair
    assert w.over_share("big")
    # a generous per-tenant quota cap overrides the window ceiling
    assert not w.over_share("big", share_cap=0.9)


def test_empty_window_is_never_over_share():
    w = FairShareWindow(clock=lambda: 0.0)
    assert not w.over_share("anyone")


# ---------------------------------------------------------------------------
# AdmissionGate integration


def _fair_gate(depth, max_share=0.5, quotas=None):
    fair = FairShareWindow(quantum_s=1000.0, max_share=max_share,
                           clock=lambda: 0.0)
    gate = AdmissionGate(max_pending=8, control_reserve=2,
                         depth_fn=lambda: depth,
                         fairness=fair, quotas=quotas)
    return gate, fair


def test_storming_tenant_sheds_tenant_quota_with_tenant_label():
    gate, _ = _fair_gate(depth=6)
    shed0 = _metric(ADMISSION_SHED,
                    {"surface": "service", "class": CLASS_DATA,
                     "reason": SHED_TENANT_QUOTA, "tenant": "a"})
    # b takes a modest share first
    for _ in range(2):
        assert gate.admit(CLASS_DATA, tenant="b") == (True, "")
    # a storms: once past cap AND fair share, a sheds tenant-quota
    a_admitted = a_shed = 0
    for _ in range(10):
        ok, reason = gate.admit(CLASS_DATA, tenant="a")
        if ok:
            a_admitted += 1
        else:
            assert reason == SHED_TENANT_QUOTA
            a_shed += 1
    assert a_admitted > 0 and a_shed > 0
    assert _metric(ADMISSION_SHED,
                   {"surface": "service", "class": CLASS_DATA,
                    "reason": SHED_TENANT_QUOTA,
                    "tenant": "a"}) == shed0 + a_shed
    # b is NOT over its share: b still admits after a's storm
    assert gate.admit(CLASS_DATA, tenant="b") == (True, "")


def test_fairness_only_applies_when_congested():
    # depth at half the bound or below: a lone burst rides idle
    # capacity freely — fairness is a congestion policy, not a tax
    gate, _ = _fair_gate(depth=4)
    for _ in range(20):
        assert gate.admit(CLASS_DATA, tenant="a") == (True, "")


def test_control_class_never_tenant_shed():
    gate, fair = _fair_gate(depth=6)
    for _ in range(10):
        fair.note("a")
    assert gate.admit(CLASS_CONTROL, tenant="a") == (True, "")


def test_quota_store_feeds_the_fairness_ceiling():
    now = [0.0]
    quotas = TenantQuotas(default_share=0.2, ttl_s=10.0,
                          clock=lambda: now[0])
    quotas.set_share("a", 0.95)
    gate, fair = _fair_gate(depth=6, max_share=0.2, quotas=quotas)
    fair.note("b")
    # a's generous LIVE quota (0.95) overrides the 0.2 window ceiling
    for _ in range(6):
        assert gate.admit(CLASS_DATA, tenant="a") == (True, "")
    # the quota lapses → conservative default 0.2: a now sheds
    now[0] = 10.0
    ok, reason = gate.admit(CLASS_DATA, tenant="a")
    assert (ok, reason) == (False, SHED_TENANT_QUOTA)
    # b keeps admitting through a's lapse
    assert gate.admit(CLASS_DATA, tenant="b") == (True, "")


def test_tenantless_requests_keep_pre_tenant_series_shape():
    """A tenant-less admit/shed must not grow a tenant label — the
    pre-ISSUE-20 series stay byte-identical for existing dashboards."""
    gate = AdmissionGate(max_pending=1, depth_fn=lambda: 1)
    shed0 = _metric(ADMISSION_SHED,
                    {"surface": "service", "class": CLASS_DATA,
                     "reason": admission.SHED_QUEUE_FULL})
    assert gate.admit(CLASS_DATA) == (False, admission.SHED_QUEUE_FULL)
    assert _metric(ADMISSION_SHED,
                   {"surface": "service", "class": CLASS_DATA,
                    "reason": admission.SHED_QUEUE_FULL}) == shed0 + 1
