"""HeaderMatches mismatch actions + secret-backed values (VERDICT r1
missing #10).

Reference ``pkg/policy/api/http.go ·HeaderMatch``: "" (FAIL) denies on
mismatch, LOG allows and annotates the access log (our l7_log lane),
ADD/DELETE/REPLACE allow with a proxy-side rewrite; values may come
from k8s Secrets (our SecretStore) — an unresolvable secret on a FAIL
match fails closed.
"""

import pytest

from cilium_tpu.agent import Agent
from cilium_tpu.core.config import Config
from cilium_tpu.core.flow import Flow, HTTPInfo, L7Type, TrafficDirection
from cilium_tpu.policy.api import SanitizeError
from cilium_tpu.policy.api.cnp import load_cnp_yaml_text

CNP = """
apiVersion: cilium.io/v2
kind: CiliumNetworkPolicy
metadata: {name: hm}
spec:
  endpointSelector: {matchLabels: {app: svc}}
  ingress:
  - fromEndpoints: [{matchLabels: {app: peer}}]
    toPorts:
    - ports: [{port: "80", protocol: TCP}]
      rules:
        http:
        - path: "/fail/.*"
          headerMatches:
          - {name: X-Req, value: "yes"}
        - path: "/log/.*"
          headerMatches:
          - {name: X-Trace, value: "on", mismatch: LOG}
        - path: "/rewrite/.*"
          headerMatches:
          - {name: X-Inject, value: v1, mismatch: REPLACE}
        - path: "/secret/.*"
          headerMatches:
          - {name: X-Token, mismatch: "", secret: {namespace: ns, name: tok}}
"""


def _agent(offload: bool) -> Agent:
    cfg = Config()
    cfg.enable_tpu_offload = offload
    cfg.configure_logging = False
    return Agent(cfg).start()


def _http(agent, svc, peer, path, headers=()):
    return Flow(src_identity=peer.identity, dst_identity=svc.identity,
                dport=80, direction=TrafficDirection.INGRESS,
                l7=L7Type.HTTP,
                http=HTTPInfo(method="GET", path=path, host="svc.local",
                              headers=tuple(headers)))


@pytest.mark.parametrize("offload", [False, True])
def test_mismatch_actions(offload):
    agent = _agent(offload)
    try:
        svc = agent.endpoint_add(1, {"app": "svc"})
        peer = agent.endpoint_add(2, {"app": "peer"})
        agent.policy_add(load_cnp_yaml_text(CNP)[0])

        flows = [
            # FAIL: header present → allow; missing → deny
            _http(agent, svc, peer, "/fail/x", [("X-Req", "yes")]),
            _http(agent, svc, peer, "/fail/x"),
            # LOG: mismatch still allows, but raises l7_log
            _http(agent, svc, peer, "/log/x", [("X-Trace", "on")]),
            _http(agent, svc, peer, "/log/x"),
            # REPLACE: never gates
            _http(agent, svc, peer, "/rewrite/x"),
        ]
        out = agent.process_flows(flows)
        assert [int(v) for v in out["verdict"]] == [5, 2, 5, 5, 5]
        assert [bool(x) for x in out["l7_log"]] == \
            [False, False, False, True, False]

        # the REPLACE rewrite is carried for the proxy layer
        if offload:
            rewrites = [r for rule in
                        agent.loader.engine.policy.header_rewrites
                        for r in rule]
            assert ("REPLACE", "X-Inject", "v1") in rewrites
    finally:
        agent.stop()


@pytest.mark.parametrize("offload", [False, True])
def test_secret_backed_value(offload):
    agent = _agent(offload)
    try:
        svc = agent.endpoint_add(1, {"app": "svc"})
        peer = agent.endpoint_add(2, {"app": "peer"})
        agent.policy_add(load_cnp_yaml_text(CNP)[0])

        f_good = _http(agent, svc, peer, "/secret/x",
                       [("X-Token", "s3cr3t")])

        # secret missing → FAIL match fails CLOSED (rule dead)
        out = agent.process_flows([f_good])
        assert int(out["verdict"][0]) == 2

        # secret lands → matching value allows, wrong value denies
        agent.secret_set("ns", "tok", "s3cr3t")
        out = agent.process_flows([
            f_good,
            _http(agent, svc, peer, "/secret/x", [("X-Token", "nope")]),
        ])
        assert [int(v) for v in out["verdict"]] == [5, 2]

        # rotation re-resolves
        agent.secret_set("ns", "tok", "other")
        out = agent.process_flows([f_good])
        assert int(out["verdict"][0]) == 2
    finally:
        agent.stop()


def test_sanitize_rejects_bad_actions():
    with pytest.raises(SanitizeError):
        for cnp in load_cnp_yaml_text("""
apiVersion: cilium.io/v2
kind: CiliumNetworkPolicy
metadata: {name: bad}
spec:
  endpointSelector: {matchLabels: {app: svc}}
  ingress:
  - toPorts:
    - ports: [{port: "80", protocol: TCP}]
      rules:
        http:
        - headerMatches: [{name: X, mismatch: EXPLODE}]
"""):
            for rule in cnp.rules:
                rule.sanitize()


def test_yaml_bool_header_value_rejected():
    """`value: yes` (unquoted) parses as a YAML bool — compiling it to
    the literal 'True' would deny what the author wrote; reject at
    parse instead."""
    with pytest.raises(SanitizeError):
        load_cnp_yaml_text("""
apiVersion: cilium.io/v2
kind: CiliumNetworkPolicy
metadata: {name: bool-val}
spec:
  endpointSelector: {matchLabels: {app: svc}}
  ingress:
  - toPorts:
    - ports: [{port: "80", protocol: TCP}]
      rules:
        http:
        - headerMatches: [{name: X-Req, value: yes}]
""")
