"""Agent ↔ fake-apiserver integration (SURVEY §2.4 "resource watchers
feed policy repo" + CEP/CiliumNode status publication, §3.2 CNP path).
"""

import time

from cilium_tpu.agent import Agent
from cilium_tpu.core.config import Config
from cilium_tpu.core.flow import Flow
from cilium_tpu.k8s.apiserver import APIServer, K8sClient, NotFound
from cilium_tpu.kvstore import KVStore


def cnp_obj(name, port="5432", ns="default", app="web"):
    return {
        "apiVersion": "cilium.io/v2",
        "kind": "CiliumNetworkPolicy",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "endpointSelector": {"matchLabels": {"app": "db"}},
            "ingress": [{
                "fromEndpoints": [{"matchLabels": {"app": app}}],
                "toPorts": [{"ports": [
                    {"port": port, "protocol": "TCP"}]}],
            }],
        },
    }


def make_agent(socket_path, tmp_path=None):
    cfg = Config()
    cfg.k8s_api_socket = socket_path
    cfg.configure_logging = False
    return Agent(config=cfg, kvstore=KVStore()).start()


def verdicts(agent, db, web, dport=5432):
    out = agent.process_flows([
        Flow(src_identity=web.identity, dst_identity=db.identity,
             dport=dport),
        Flow(src_identity=db.identity, dst_identity=db.identity,
             dport=dport),
    ])
    return [int(v) for v in out["verdict"]]


def wait_until(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_cnp_lifecycle_drives_enforcement(tmp_path):
    server = APIServer(str(tmp_path / "k8s.sock")).start()
    c = K8sClient(server.socket_path)
    # a CNP applied BEFORE the agent starts must be enforced at start
    # (initial informer list is synchronous — WaitForCacheSync)
    c.create("ciliumnetworkpolicies", cnp_obj("allow-web"))
    agent = make_agent(server.socket_path)
    try:
        db = agent.endpoint_add(1, {"app": "db"})
        web = agent.endpoint_add(2, {"app": "web"})
        agent.endpoint_manager.regenerate_all(wait=True)
        assert verdicts(agent, db, web) == [1, 2]  # FORWARDED, DROPPED

        # live update: rule now selects a different peer → web drops
        c.apply("ciliumnetworkpolicies", cnp_obj("allow-web", app="api"))
        assert wait_until(
            lambda: verdicts(agent, db, web) == [2, 2]), \
            verdicts(agent, db, web)

        # back to allowing web on another port
        c.apply("ciliumnetworkpolicies", cnp_obj("allow-web",
                                                 port="6000"))
        assert wait_until(
            lambda: verdicts(agent, db, web, dport=6000) == [1, 2])
        # the old port is gone (upsert replaced, not accumulated)
        assert verdicts(agent, db, web, dport=5432) == [2, 2]

        # delete: no rule selects db → default-allow (no policy)
        c.delete("ciliumnetworkpolicies", "allow-web")
        assert wait_until(
            lambda: verdicts(agent, db, web) == [1, 1])
    finally:
        agent.stop()
        server.stop()


def test_unparseable_cnp_keeps_previous_state(tmp_path):
    server = APIServer(str(tmp_path / "k8s.sock")).start()
    c = K8sClient(server.socket_path)
    agent = make_agent(server.socket_path)
    try:
        db = agent.endpoint_add(1, {"app": "db"})
        web = agent.endpoint_add(2, {"app": "web"})
        c.create("ciliumnetworkpolicies", cnp_obj("allow-web"))
        assert wait_until(lambda: verdicts(agent, db, web) == [1, 2])
        # a bad update (invalid protocol → SanitizeError) must not
        # wipe enforcement
        bad = cnp_obj("allow-web")
        bad["spec"]["ingress"][0]["toPorts"][0]["ports"][0][
            "protocol"] = "BOGUS"
        c.apply("ciliumnetworkpolicies", bad)
        time.sleep(0.5)
        assert verdicts(agent, db, web) == [1, 2]
    finally:
        agent.stop()
        server.stop()


def test_ccnp_ingest(tmp_path):
    server = APIServer(str(tmp_path / "k8s.sock")).start()
    c = K8sClient(server.socket_path)
    agent = make_agent(server.socket_path)
    try:
        db = agent.endpoint_add(1, {"app": "db"})
        web = agent.endpoint_add(2, {"app": "web"})
        ccnp = cnp_obj("cluster-allow")
        ccnp["kind"] = "CiliumClusterwideNetworkPolicy"
        del ccnp["metadata"]["namespace"]
        c.create("ciliumclusterwidenetworkpolicies", ccnp)
        assert wait_until(lambda: verdicts(agent, db, web) == [1, 2])
        c.delete("ciliumclusterwidenetworkpolicies", "cluster-allow")
        assert wait_until(lambda: verdicts(agent, db, web) == [1, 1])
    finally:
        agent.stop()
        server.stop()


def test_publish_node_conflict_is_best_effort(tmp_path):
    """Two publishers (periodic sync controller vs explicit sync) can
    race apply's get→update on the CiliumNode object; the loser's
    Conflict must stay inside publish_node (it converges next tick),
    exactly like publish_endpoint — a full-suite-load flake before
    the fix."""
    from cilium_tpu.k8s.apiserver import Conflict

    server = APIServer(str(tmp_path / "k8s.sock")).start()
    agent = make_agent(server.socket_path)
    try:
        bridge = agent.k8s_bridge

        def conflicting_apply(plural, obj):
            raise Conflict("stale resourceVersion 1 (current 3)")

        original = bridge.client.apply
        bridge.client.apply = conflicting_apply
        try:
            bridge.publish_node()  # must not raise
        finally:
            bridge.client.apply = original
        bridge.publish_node()      # and the real path still works
    finally:
        agent.stop()
        server.stop()


def test_cep_and_node_status_published(tmp_path):
    server = APIServer(str(tmp_path / "k8s.sock")).start()
    c = K8sClient(server.socket_path)
    agent = make_agent(server.socket_path)
    try:
        ep = agent.endpoint_add(7, {"app": "db"},
                                named_ports={"pg": 5432})
        cep = c.get("ciliumendpoints", "node-0-ep-7")
        st = cep["status"]
        assert st["id"] == 7
        assert st["identity"]["id"] == int(ep.identity)
        assert "k8s:app=db" in st["identity"]["labels"]
        assert st["networking"]["addressing"][0]["ipv4"] == ep.ipv4
        assert st["named-ports"] == [{"name": "pg", "port": 5432}]
        # the periodic sync converges status drift (policy revision)
        agent.endpoint_manager.regenerate_all(wait=True)
        agent.k8s_bridge.sync_endpoint_status()
        cep = c.get("ciliumendpoints", "node-0-ep-7")
        assert cep["status"]["policy"]["revision"] == ep.policy_revision
        # node object exists
        node = c.get("ciliumnodes", agent.config.node_name)
        assert node["kind"] == "CiliumNode"
        # removal withdraws the CEP
        agent.endpoint_remove(7)
        try:
            c.get("ciliumendpoints", "node-0-ep-7")
            assert False, "CEP not withdrawn"
        except NotFound:
            pass
    finally:
        agent.stop()
        server.stop()


def test_k8s_cli_apply_get_delete(tmp_path, capsys):
    """`cilium-tpu k8s apply/get/delete` drives the apiserver like
    kubectl, straight from a corpus YAML file."""
    import yaml

    from cilium_tpu.cli import main as cli_main

    server = APIServer(str(tmp_path / "k8s.sock")).start()
    sock = server.socket_path
    f = tmp_path / "cnp.yaml"
    f.write_text(yaml.safe_dump(cnp_obj("from-cli")))
    try:
        assert cli_main(["k8s", "apply", "--socket", sock,
                         "-f", str(f)]) == 0
        capsys.readouterr()
        assert cli_main(["k8s", "get", "--socket", sock,
                         "ciliumnetworkpolicies", "from-cli"]) == 0
        got = __import__("json").loads(capsys.readouterr().out)
        assert got["spec"]["endpointSelector"][
            "matchLabels"]["app"] == "db"
        # apply again = update (no conflict), then delete
        assert cli_main(["k8s", "apply", "--socket", sock,
                         "-f", str(f)]) == 0
        assert cli_main(["k8s", "delete", "--socket", sock,
                         "ciliumnetworkpolicies", "from-cli"]) == 0
        assert cli_main(["k8s", "get", "--socket", sock,
                         "ciliumnetworkpolicies", "from-cli"]) == 1
    finally:
        server.stop()


def test_cep_sync_prunes_orphans(tmp_path):
    """A CEP this node owns but whose endpoint no longer exists is
    pruned by the periodic sync (stale status must not outlive the
    endpoint — the reference's CEP GC)."""
    server = APIServer(str(tmp_path / "k8s.sock")).start()
    c = K8sClient(server.socket_path)
    agent = make_agent(server.socket_path)
    try:
        agent.endpoint_add(9, {"app": "db"})
        # simulate a stale CEP left by a crashed prior incarnation
        c.apply("ciliumendpoints", {
            "metadata": {"name": "node-0-ep-99", "namespace": "default"},
            "status": {"id": 99, "networking":
                       {"node": agent.config.node_name}}})
        # another node's CEP must NOT be pruned
        c.apply("ciliumendpoints", {
            "metadata": {"name": "other-ep-50", "namespace": "default"},
            "status": {"id": 50, "networking": {"node": "other-node"}}})
        agent.k8s_bridge.sync_endpoint_status()
        names = {o["metadata"]["name"]
                 for o in c.list("ciliumendpoints")["items"]}
        assert names == {"node-0-ep-9", "other-ep-50"}
    finally:
        agent.stop()
        server.stop()
