"""Continuously-batched serving loop (runtime/serveloop.py +
engine/ring.py): the ring's packed dispatch must be verdict-bit-equal
to the engine's direct path across interleaved streams, memo-hit rows
must provably skip H2D (the bytes-saved counter is arithmetic, not
vibes), leases/sheds/faults must be explicit and exact, and the ring
must ride policy hot-swaps through the PR-8 delta path — including
the ISSUE-11 narrowing to family (bank-reference) granularity."""

import numpy as np
import pytest

from cilium_tpu.core.config import Config
from cilium_tpu.ingest import synth
from cilium_tpu.ingest.binary import (
    capture_from_bytes,
    capture_to_bytes,
)
from cilium_tpu.runtime import faults, simclock
from cilium_tpu.runtime.loader import Loader
from cilium_tpu.runtime.serveloop import (
    ChunkTicket,
    LeaseExpired,
    ServeLoop,
    ShedError,
)
from cilium_tpu.runtime.simclock import VirtualClock


def _world(tmp_path, name="http", n_rules=60, capacity=64,
           ttl=60.0, serve_kw=None):
    scenario = synth.scenario_by_name(name, n_rules, 1024)
    per_identity, scenario = synth.realize_scenario(scenario)
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    loop = ServeLoop(loader, capacity=capacity, lease_ttl_s=ttl,
                     pack_interval_s=0.01, **(serve_kw or {}))
    return loop, loader, scenario


def _sections(flows):
    return capture_from_bytes(capture_to_bytes(flows))


def _direct(loader, flows):
    return [int(v) for v in
            loader.engine.verdict_flows(flows)["verdict"]]


# ---------------------------------------------------------------------------
# packed dispatch: many streams, one launch, bit-equal


@pytest.mark.parametrize("name", ["http", "kafka", "fqdn", "generic"])
def test_ring_pack_is_bit_equal_across_interleaved_streams(
        tmp_path, name):
    clk = VirtualClock()
    with simclock.use(clk):
        loop, loader, scenario = _world(tmp_path, name=name)
        flows = scenario.flows[:600]
        want = _direct(loader, flows)
        leases = [loop.connect(f"s{i}") for i in range(4)]
        tickets = []
        for k, i in enumerate(range(0, 600, 75)):
            chunk = flows[i:i + 75]
            tickets.append((i, loop.submit(leases[k % 4],
                                           *_sections(chunk))))
        packs_before = loop.ring.packs
        served = loop.step()
        # one fused pack served every stream's pending chunks
        assert loop.ring.packs == packs_before + 1
        assert served == 600
        got = [None] * 600
        for i, t in tickets:
            assert t.done and t.error is None
            got[i:i + t.n] = [int(v) for v in t.verdicts]
        assert got == want


def test_memo_hit_rows_provably_skip_h2d(tmp_path):
    """The selective-copy claim as arithmetic: a chunk whose rows are
    ALL ring-resident ships only 4-byte ids — the bytes-saved counter
    grows by exactly known_rows x (row_bytes - 4) and bytes shipped
    by exactly n x 4."""
    clk = VirtualClock()
    with simclock.use(clk):
        loop, loader, scenario = _world(tmp_path)
        flows = scenario.flows[:256]
        lease = loop.connect("s0")
        loop.submit(lease, *_sections(flows))
        loop.step()
        assert loop.ring.bytes_saved > 0   # dedup within the chunk
        row_bytes = loop.ring.session.row_width * 4
        saved0 = loop.ring.bytes_saved
        shipped0 = loop.ring.bytes_shipped
        hits0 = loop.ring.session.memo.hits
        # the SAME traffic again: zero novel rows, pure memo serve
        t = loop.submit(lease, *_sections(flows))
        loop.step()
        assert t.done and t.error is None
        assert loop.ring.bytes_saved - saved0 \
            == len(flows) * (row_bytes - 4)
        assert loop.ring.bytes_shipped - shipped0 == len(flows) * 4
        assert loop.ring.session.memo.hits > hits0


def test_per_slot_pending_bound_sheds_queue_full(tmp_path):
    from cilium_tpu.runtime.admission import SHED_QUEUE_FULL

    clk = VirtualClock()
    with simclock.use(clk):
        loop, loader, scenario = _world(
            tmp_path, serve_kw={"max_slot_pending": 2})
        lease = loop.connect("s0")
        sections = _sections(scenario.flows[:8])
        loop.submit(lease, *sections)
        loop.submit(lease, *sections)
        with pytest.raises(ShedError) as exc:
            loop.submit(lease, *sections)
        assert exc.value.reason == SHED_QUEUE_FULL
        # the pack drains the backlog; the slot accepts again
        loop.step()
        loop.submit(lease, *sections)


# ---------------------------------------------------------------------------
# fault points: explicit sheds, transient pack failure retries


def test_serve_fault_points_shed_explicitly(tmp_path):
    from cilium_tpu.runtime.admission import SHED_FAULT

    clk = VirtualClock()
    with simclock.use(clk):
        loop, loader, scenario = _world(tmp_path)
        sections = _sections(scenario.flows[:8])
        with faults.inject(faults.FaultPlan([
                faults.FaultRule("serve.lease", times=1)])):
            with pytest.raises(ShedError) as exc:
                loop.connect("s0")
            assert exc.value.reason == SHED_FAULT
            lease = loop.connect("s0")   # fault exhausted: admitted
        with faults.inject(faults.FaultPlan([
                faults.FaultRule("serve.ring_slot", times=1)])):
            with pytest.raises(ShedError) as exc:
                loop.submit(lease, *sections)
            assert exc.value.reason == SHED_FAULT
            t = loop.submit(lease, *sections)   # next chunk fine
        loop.step()
        assert t.done and t.error is None


def test_transient_dispatch_fault_retries_next_cycle(tmp_path):
    """An engine.dispatch fault fails ONE pack cycle: the batch goes
    back to the slots' heads and the next cycle serves it — the
    ticket resolves with real verdicts, nothing is lost."""
    clk = VirtualClock()
    with simclock.use(clk):
        loop, loader, scenario = _world(tmp_path)
        flows = scenario.flows[:64]
        want = _direct(loader, flows)
        lease = loop.connect("s0")
        t = loop.submit(lease, *_sections(flows))
        with faults.inject(faults.FaultPlan([
                faults.FaultRule("engine.dispatch", times=1)])):
            with pytest.raises(Exception):
                loop.step()              # the faulted cycle
            assert not t.done            # batch restored, not lost
            loop.step()                  # retry succeeds
        assert t.done and t.error is None
        assert [int(v) for v in t.verdicts] == want


# ---------------------------------------------------------------------------
# hot-swap safety + family-granular (bank-reference) invalidation


def _churn_world(tmp_path):
    """A policy whose per-identity HTTP vs DNS rule families can
    churn independently — the family-granularity fixture."""
    from cilium_tpu.core.flow import (
        DNSInfo,
        Flow,
        HTTPInfo,
        L7Type,
        Protocol,
        TrafficDirection,
    )
    from cilium_tpu.core.identity import IdentityAllocator
    from cilium_tpu.core.labels import LabelSet
    from cilium_tpu.policy.api import (
        EndpointSelector,
        IngressRule,
        PortProtocol,
        PortRule,
        Rule,
    )
    from cilium_tpu.policy.api.l7 import (
        L7Rules,
        PortRuleDNS,
        PortRuleHTTP,
    )
    from cilium_tpu.policy.mapstate import PolicyResolver
    from cilium_tpu.policy.repository import Repository
    from cilium_tpu.policy.selectorcache import SelectorCache

    alloc = IdentityAllocator()
    web = alloc.allocate(LabelSet.from_dict({"app": "web"}))
    dbs = [alloc.allocate(LabelSet.from_dict({"app": f"db{i}"}))
           for i in range(3)]
    rules_of = {i: [("http", f"/svc{i}/p{j}/.*") for j in range(4)]
                + [("dns", f"api{i}.corp.io")] for i in range(3)}

    def resolve():
        repo = Repository()
        rules = []
        for i in range(3):
            http = tuple(PortRuleHTTP(path=p, method="GET")
                         for k, p in rules_of[i] if k == "http")
            dns = tuple(PortRuleDNS(match_name=p)
                        for k, p in rules_of[i] if k == "dns")
            rules.append(Rule(
                endpoint_selector=EndpointSelector.from_labels(
                    app=f"db{i}"),
                ingress=(IngressRule(
                    from_endpoints=(
                        EndpointSelector.from_labels(app="web"),),
                    to_ports=(
                        PortRule(ports=(PortProtocol(80, Protocol.TCP),),
                                 rules=L7Rules(http=http)),
                        PortRule(ports=(PortProtocol(53, Protocol.UDP),),
                                 rules=L7Rules(dns=dns)),)),),
            ))
        repo.add(rules, sanitize=False)
        resolver = PolicyResolver(repo, SelectorCache(alloc))
        return {db: resolver.resolve(alloc.lookup(db)) for db in dbs}

    def http_flow(i, path):
        return Flow(src_identity=web, dst_identity=dbs[i], dport=80,
                    protocol=Protocol.TCP,
                    direction=TrafficDirection.INGRESS,
                    l7=L7Type.HTTP,
                    http=HTTPInfo(method="GET", path=path))

    def dns_flow(i, q):
        return Flow(src_identity=web, dst_identity=dbs[i], dport=53,
                    protocol=Protocol.UDP,
                    direction=TrafficDirection.INGRESS,
                    l7=L7Type.DNS, dns=DNSInfo(query=q))

    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.engine.bank_size = 2
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    loader.regenerate(resolve(), revision=1)
    return loader, rules_of, resolve, http_flow, dns_flow


def test_ring_survives_policy_hot_swap_with_family_granular_refill(
        tmp_path):
    """A commit that changes ONLY identity 0's HTTP rules refills
    only identity 0's HTTP memo rows — its DNS rows and every other
    identity's rows keep serving from the memo (the PR-8 "remaining
    headroom", closed). Refills are counted as misses; verdicts stay
    bit-equal to the new serving engine throughout."""
    clk = VirtualClock()
    with simclock.use(clk):
        loader, rules_of, resolve, http_flow, dns_flow = \
            _churn_world(tmp_path)
        loop = ServeLoop(loader, capacity=8, lease_ttl_s=60.0,
                         pack_interval_s=0.01)
        corpus = []
        for i in range(3):
            corpus += [http_flow(i, f"/svc{i}/p{j}/x")
                       for j in range(4)]
            corpus.append(dns_flow(i, f"api{i}.corp.io"))
            corpus.append(dns_flow(i, "evil.net"))
        lease = loop.connect("s0")
        t = loop.submit(lease, *_sections(corpus * 4))
        loop.step()
        assert [int(v) for v in t.verdicts] == \
            _direct(loader, corpus * 4)
        memo = loop.ring.session.memo
        misses0, inval0 = memo.misses, memo.invalidations
        n_unique = loop.ring.session.n_rows
        # the identity whose rules churn, and its per-family unique
        # row counts, straight from the session's (ep, l7t, dport)
        # mirror
        pairs = loop.ring.session._row_eps[:n_unique]
        id0 = min(ep for ep, _, _ in pairs)  # dbs[0]: lowest identity
        id0_http = sum(1 for ep, l7t, _ in pairs
                       if ep == id0 and l7t == 1)
        id0_all = sum(1 for ep, _, _ in pairs if ep == id0)
        assert 0 < id0_http < id0_all      # both families present
        # churn ONLY identity 0's HTTP family
        rules_of[0].append(("http", "/churn/added/.*"))
        loader.regenerate(resolve(), revision=2)
        t2 = loop.submit(lease, *_sections(corpus * 4))
        loop.step()
        # still bit-equal to the NEW serving engine
        assert [int(v) for v in t2.verdicts] == \
            _direct(loader, corpus * 4)
        # family-granular: the refill re-missed EXACTLY identity 0's
        # http rows — its DNS rows (and every other identity) kept
        # serving from the memo. Identity-granular would have
        # refilled id0_all; a full drop would re-miss everything.
        refilled = memo.misses - misses0
        assert refilled == id0_http
        assert memo.invalidations == inval0 + 1
        assert loop.ring.session.n_rows == n_unique  # no new rows yet
        # the NEW rule answers on a fresh chunk (new row = new miss)
        probe = http_flow(0, "/churn/added/x")
        t3 = loop.submit(lease, *_sections([probe] * 8))
        loop.step()
        assert [int(v) for v in t3.verdicts] == \
            _direct(loader, [probe] * 8)


def test_family_delta_affects_matrix():
    """PolicyDelta.affects: the granularity ladder, exactly."""
    from cilium_tpu.engine.memo import (
        FAMILY_ALL,
        PolicyDelta,
        affected_row_ids,
    )

    full = PolicyDelta(full=True)
    assert full.affects(1, 1) and full.affects(2, 3)
    ident = PolicyDelta.banks({7}, set())
    assert ident.affects(7, 1) and ident.affects(7, 3)
    assert not ident.affects(8, 1)
    fam = PolicyDelta.banks({7, 9}, set(),
                            identity_families={(7, "http"),
                                               (9, FAMILY_ALL)})
    assert fam.affects(7, 1)           # http row of 7
    assert not fam.affects(7, 3)       # dns row of 7 survives
    assert not fam.affects(7, 0)       # l4-only row survives
    assert fam.affects(9, 3) and fam.affects(9, 0)   # structural
    eps = np.array([7, 7, 8, 9, 7])
    l7s = np.array([1, 3, 1, 0, 0])
    assert affected_row_ids(fam, eps, l7s).tolist() == [0, 3]
    # merge: families-blind x family-scoped widens to identity level
    merged = fam.merge(PolicyDelta.banks({7}, set()))
    assert merged.affects(7, 3)
    # family-scoped x family-scoped stays narrow
    merged2 = fam.merge(PolicyDelta.banks(
        {5}, set(), identity_families={(5, "dns")}))
    assert not merged2.affects(7, 3) and merged2.affects(5, 3)


def test_port_delta_affects_matrix():
    """ISSUE 13: the bank-reference (port) rung of the granularity
    ladder — exact ports narrow, PORT_ALL widens, port info only
    survives a merge when both sides carry it."""
    from cilium_tpu.engine.memo import (
        PORT_ALL,
        PolicyDelta,
        affected_row_ids,
    )

    d = PolicyDelta.banks(
        {7}, set(), identity_families={(7, "http")},
        identity_family_ports={(7, "http", 8080)})
    assert d.affects(7, 1, 8080)
    assert not d.affects(7, 1, 80)     # same identity+family, other port
    assert d.affects(7, 1)             # port-blind consumer: family level
    assert not d.affects(7, 3, 8080)   # dns row untouched
    wide = PolicyDelta.banks(
        {7}, set(), identity_families={(7, "http")},
        identity_family_ports={(7, "http", PORT_ALL)})
    assert wide.affects(7, 1, 80) and wide.affects(7, 1, 8080)
    eps = np.array([7, 7, 7, 8])
    l7s = np.array([1, 1, 3, 1])
    dps = np.array([8080, 80, 53, 8080])
    assert affected_row_ids(d, eps, l7s, dports=dps).tolist() == [0]
    assert affected_row_ids(d, eps, l7s).tolist() == [0, 1]
    # merge: ports survive only when both sides carry them
    d2 = PolicyDelta.banks(
        {9}, set(), identity_families={(9, "dns")},
        identity_family_ports={(9, "dns", 53)})
    m = d.merge(d2)
    assert not m.affects(7, 1, 80) and m.affects(9, 3, 53)
    blind = PolicyDelta.banks({5}, set(),
                              identity_families={(5, "http")})
    m2 = d.merge(blind)
    assert m2.affects(7, 1, 80), \
        "merging a ports-blind delta must widen to all ports"


# ---------------------------------------------------------------------------
# drain + the wired stream service


def test_drain_flushes_pending_and_releases_all_leases(tmp_path):
    clk = VirtualClock()
    with simclock.use(clk):
        loop, loader, scenario = _world(tmp_path)
        flows = scenario.flows[:128]
        want = _direct(loader, flows)
        leases = [loop.connect(f"s{i}") for i in range(3)]
        tickets = [loop.submit(leases[i], *_sections(flows))
                   for i in range(3)]
        flushed = loop.drain()
        assert flushed == 3 * len(flows)
        for t in tickets:
            assert [int(v) for v in t.verdicts] == want
        st = loop.status()
        assert st["occupancy"] == 0 and st["draining"]
        with pytest.raises(ShedError):
            loop.connect("late")


def test_stream_service_through_ring_is_bit_equal(tmp_path):
    """The streaming golden through the WIRED path: VerdictService
    with Config.serve.enabled routes StreamSession chunks through
    ring slot leases; verdicts are bit-equal to the engine and the
    lease releases at end-of-stream."""
    import os

    from cilium_tpu.runtime.service import VerdictService
    from cilium_tpu.runtime.stream import StreamClient

    scenario = synth.scenario_by_name("http", 60, 1024)
    per_identity, scenario = synth.realize_scenario(scenario)
    cfg = Config()
    cfg.enable_tpu_offload = True
    cfg.serve.enabled = True
    cfg.serve.pack_interval_ms = 2.0
    cfg.loader.cache_dir = str(tmp_path / "cache")
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    flows = scenario.flows[:600]
    want = _direct(loader, flows)
    sock = str(tmp_path / "v.sock")
    svc = VerdictService(loader, sock)
    svc.start()
    try:
        client = StreamClient(sock)
        seqs = [client.send_flows(flows[i:i + 150])
                for i in range(0, 600, 150)]
        got = []
        for s in seqs:
            got.extend(int(v) for v in client.result(s))
        client.finish()
        client.close()
        assert got == want
        st = svc.serveloop.status()
        assert st["grants"] >= 1
        assert st["occupancy"] == 0          # lease released
        assert st["bytes_saved"] > 0         # memo bypass happened
        assert os.path.exists(sock)
    finally:
        svc.stop()


def test_ticket_wait_times_out_on_virtual_clock():
    clk = VirtualClock()
    with simclock.use(clk):
        t = ChunkTicket(4)
        import threading

        got = []

        def waiter():
            try:
                t.wait(timeout=5.0)
            except TimeoutError:
                got.append(True)

        th = threading.Thread(target=waiter)
        th.start()
        while not clk._by_seq:
            threading.Event().wait(0.002)
        clk.advance(5.1)
        th.join(timeout=5.0)
        assert got == [True]


def test_lease_expired_submit_raises_and_releases(tmp_path):
    clk = VirtualClock()
    with simclock.use(clk):
        loop, loader, scenario = _world(tmp_path, ttl=5.0)
        lease = loop.connect("s0")
        clk.advance(5.0)
        with pytest.raises(LeaseExpired):
            loop.submit(lease, *_sections(scenario.flows[:8]))
        assert loop.status()["occupancy"] == 0
        assert loop.status()["expiries"] == 1


def test_lifetime_counters_exact_under_concurrent_bumps(tmp_path):
    """The PR-18 stats-lock regression gate: the lifetime counters
    are bumped from client threads AND the pack thread, sometimes
    while ``_lock`` is held (the gate path) and sometimes not — they
    ride a dedicated leaf lock, so (a) ``_shed`` must not deadlock
    when invoked WITH the loop lock held, and (b) concurrent bumps
    must never lose an update. Deterministic under the fix (the lock
    makes every increment atomic); pre-fix this flaked on preemption
    mid ``+=``. Virtual clock, no sleeps."""
    import sys
    import threading

    clk = VirtualClock()
    with simclock.use(clk):
        loop, _loader, _scenario = _world(tmp_path)
        # (a) the gate path: _shed under the loop lock — a counter
        # guarded by _lock itself would self-deadlock right here
        before = loop.sheds
        with loop._lock:
            loop._shed("queue-full")
        assert loop.sheds == before + 1

        # (b) exactness: hammer the counter from racing threads with
        # an aggressive switch interval so a bare += would drop bumps
        n_threads, per_thread = 8, 400
        old = sys.getswitchinterval()
        sys.setswitchinterval(1e-6)
        try:
            start = threading.Barrier(n_threads)

            def bump():
                start.wait()
                for _ in range(per_thread):
                    loop._shed("queue-full")

            threads = [threading.Thread(target=bump)
                       for _ in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            sys.setswitchinterval(old)
        assert loop.sheds == before + 1 + n_threads * per_thread
