#!/usr/bin/env python
"""Service-level tail-latency benchmark: Unix socket → MicroBatcher →
engine, under concurrent load.

VERDICT r2 item 3 / SURVEY.md §7 hard part #5: the micro-batcher
trades p99 latency for MXU utilization — this measures that trade
honestly, in two regimes:

* **Closed loop** (the original sweep): N client threads each run a
  think-time-free request loop. Throughput is COUPLED to latency
  (each thread has one request in flight), so this regime can never
  fill large batches — it measures the lightly-loaded latency floor.
* **Open loop** (VERDICT r3 item 4): requests arrive on a Poisson
  schedule at a FIXED offered rate, independent of responses — the
  regime micro-batching exists for. Latency is measured from the
  SCHEDULED arrival time (wrk2-style), so a backed-up service shows
  honest queueing delay instead of coordinated omission. The sweep
  raises offered load until saturation (achieved < 90% of offered)
  and reports the throughput-vs-p99 curve plus the achieved
  batch-size distribution.

Every sample is CLIENT-OBSERVED wall time over the verdict service's
Unix socket (4B-length-prefixed JSON — the same protocol the C++ shim
speaks); ≥200 samples per point so p99 is a real quantile, not a max.

``--shim`` adds a lane driving the C++ shim
(shim/libcilium_shim.so → cshim_on_data with Kafka produce records)
so the native client path is on record too.

Prints one JSON line per sweep point and writes the full sweep to
``--out`` (SERVICE_LATENCY artifact).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time


def build_engine(n_rules: int):
    from cilium_tpu.core.config import Config
    from cilium_tpu.ingest import synth
    from cilium_tpu.runtime.loader import Loader

    scenario = synth.synth_http_scenario(n_rules=n_rules, n_flows=2000)
    per_identity, scenario = synth.realize_scenario(scenario)
    cfg = Config.from_env()
    cfg.enable_tpu_offload = True
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    return loader, scenario


#: MicroBatcher flush-size histogram key (METRICS internal layout)
_HIST_KEY = ("cilium_tpu_microbatch_size", ())


def _prewarm(service, scenario, batch_max: int) -> None:
    """Compile every pow2 batch shape the padded flush can produce —
    an XLA compile inside a timed window would report compiler
    latency, not service latency."""
    size = 1
    while size <= batch_max:
        service.bridge._verdicts(scenario.flows[:size])
        size *= 2


def _hist_mark() -> int:
    from cilium_tpu.runtime.metrics import METRICS

    return METRICS.histo_count(_HIST_KEY[0])


def _batches_since(mark: int):
    from cilium_tpu.runtime.metrics import METRICS

    return METRICS.samples_since(_HIST_KEY[0], mark)


def _quantiles(latencies: list) -> dict:
    """samples/p50/p95/p99/max in ms (sorts in place); zeros when no
    samples landed so every point carries the same schema."""
    latencies.sort()
    n = len(latencies)
    if n == 0:
        return {"samples": 0, "p50_ms": 0.0, "p95_ms": 0.0,
                "p99_ms": 0.0, "max_ms": 0.0}

    def q(p: float) -> float:
        return round(latencies[min(n - 1, int(n * p))] * 1e3, 3)

    return {"samples": n, "p50_ms": q(0.50), "p95_ms": q(0.95),
            "p99_ms": q(0.99),
            "max_ms": round(latencies[-1] * 1e3, 3)}


def run_point(loader, scenario, deadline_ms: float, batch_max: int,
              threads: int, per_thread: int, warmup: int,
              sock_dir: str) -> dict:
    from cilium_tpu.ingest.hubble import flow_to_dict
    from cilium_tpu.runtime.service import VerdictClient, VerdictService

    sock = os.path.join(sock_dir, f"svc_{deadline_ms}.sock")
    service = VerdictService(loader, sock, batch_max=batch_max,
                             deadline_ms=deadline_ms)
    service.start()
    _prewarm(service, scenario, batch_max)
    # distinct request templates per thread, pre-serialized
    reqs = [{"op": "check", "flow": flow_to_dict(f)}
            for f in scenario.flows[:threads * 64]]
    n_batches_before = _hist_mark()

    lat_lock = threading.Lock()
    latencies: list = []
    errors = [0]
    start_barrier = threading.Barrier(threads + 1)
    done_barrier = threading.Barrier(threads + 1)

    def worker(tid: int):
        # EVERY exit path must pass both barriers or main blocks
        # forever waiting for threads+1 parties
        client = None
        mine = reqs[tid::threads] or reqs
        try:
            client = VerdictClient(sock)
            for i in range(warmup):
                client.call(mine[i % len(mine)])
        except Exception:
            with lat_lock:
                errors[0] += 1
            client = None
        start_barrier.wait()
        out = []
        try:
            if client is not None:
                for i in range(per_thread):
                    t0 = time.perf_counter()
                    resp = client.call(mine[i % len(mine)])
                    dt = time.perf_counter() - t0
                    if "verdict" not in resp:
                        with lat_lock:
                            errors[0] += 1
                    out.append(dt)
        except Exception:
            with lat_lock:
                errors[0] += 1
        with lat_lock:
            latencies.extend(out)
        done_barrier.wait()
        if client is not None:
            client.close()

    workers = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(threads)]
    for w in workers:
        w.start()
    start_barrier.wait()
    t_wall0 = time.perf_counter()
    done_barrier.wait()
    t_wall = time.perf_counter() - t_wall0
    for w in workers:
        w.join(timeout=30)
    service.stop()

    sizes = _batches_since(n_batches_before)
    qs = _quantiles(latencies)
    return {
        "deadline_ms": deadline_ms,
        "batch_max": batch_max,
        "threads": threads,
        "errors": errors[0],
        "throughput_rps": round(qs["samples"] / t_wall, 1)
        if qs["samples"] else 0.0,
        **qs,
        "mean_batch_size": round(sum(sizes) / len(sizes), 1) if sizes
        else 0,
    }


def run_open_point(loader, scenario, deadline_ms: float, batch_max: int,
                   rate_rps: float, duration_s: float, conns: int,
                   warmup: int, sock_dir: str,
                   drain_workers: int = 1) -> dict:
    """One open-loop point: a Poisson arrival schedule at
    ``rate_rps`` drives ``conns`` connections; workers pull the next
    scheduled arrival from a shared cursor, sleep until it, send, and
    record latency FROM THE SCHEDULED TIME — a worker that falls
    behind charges the backlog to the measurement instead of silently
    thinning the offered load (coordinated omission)."""
    from cilium_tpu.ingest.hubble import flow_to_dict
    from cilium_tpu.runtime.service import VerdictClient, VerdictService

    sock = os.path.join(sock_dir, f"svc_open_{deadline_ms}.sock")
    service = VerdictService(loader, sock, batch_max=batch_max,
                             deadline_ms=deadline_ms,
                             drain_workers=drain_workers)
    service.start()
    try:
        _prewarm(service, scenario, batch_max)
        reqs = [{"op": "check", "flow": flow_to_dict(f)}
                for f in scenario.flows[:512]]
        # fixed-seed Poisson schedule (reproducible offered load)
        rng = random.Random(1234)
        arrivals = []
        t = 0.0
        while t < duration_s:
            t += rng.expovariate(rate_rps)
            arrivals.append(t)

        cursor = [0]
        lock = threading.Lock()
        latencies: list = []
        errors = [0]
        base_time = [0.0]
        ready = threading.Barrier(conns + 1)
        done = threading.Barrier(conns + 1)

        def worker(tid: int):
            # EVERY exit path passes BOTH barriers: main sorts the
            # latency list after `done`, so a straggler extending it
            # later would corrupt the sort
            client = None
            try:
                client = VerdictClient(sock)
                for i in range(warmup):
                    client.call(reqs[(tid + i) % len(reqs)])
            except Exception:
                with lock:
                    errors[0] += 1
                if client is not None:
                    client.close()  # don't leak the connected fd
                client = None
            ready.wait()
            out = []
            try:
                if client is not None:
                    base = base_time[0]
                    while True:
                        with lock:
                            i = cursor[0]
                            cursor[0] += 1
                        if i >= len(arrivals):
                            break
                        sched = base + arrivals[i]
                        now = time.perf_counter()
                        if sched > now:
                            time.sleep(sched - now)
                        resp = client.call(reqs[i % len(reqs)])
                        dt = time.perf_counter() - sched
                        if "verdict" not in resp:
                            with lock:
                                errors[0] += 1
                        out.append(dt)
            except Exception:
                with lock:
                    errors[0] += 1
            with lock:
                latencies.extend(out)
            done.wait()
            if client is not None:
                client.close()

        workers = [threading.Thread(target=worker, args=(c,),
                                    daemon=True) for c in range(conns)]
        for w in workers:
            w.start()
        # workers block on the barrier until base_time is set; warmup
        # has fully finished once every worker reaches the barrier, so
        # the histogram mark taken HERE excludes warmup batches from
        # the reported batch-size distribution
        base_time[0] = time.perf_counter() + 0.05
        ready.wait()
        n_before = _hist_mark()
        done.wait()
        # wall from the SCHEDULE ORIGIN, not barrier release: the
        # 50ms lead-in must not dilute achieved_rps into a false
        # saturation verdict at short durations
        wall = time.perf_counter() - base_time[0]
        for w in workers:
            w.join(timeout=30)
    finally:
        service.stop()

    sizes = _batches_since(n_before)
    qs = _quantiles(latencies)
    return {
        "deadline_ms": deadline_ms,
        "offered_rps": rate_rps,
        "achieved_rps": round(qs["samples"] / max(wall, 1e-9), 1)
        if qs["samples"] else 0.0,
        "errors": errors[0],
        **qs,
        "mean_batch_size": round(sum(sizes) / len(sizes), 1)
        if sizes else 0,
        "max_batch_size": int(max(sizes)) if sizes else 0,
        "batch_max": batch_max,
        "conns": conns,
        "drain_workers": drain_workers,
    }


def run_shim_point(loader, deadline_ms: float, batch_max: int,
                   per_thread: int, threads: int, sock_dir: str):
    """Kafka produce records through the C++ shim (native client path):
    cshim_on_data → socket → parser → MicroBatcher → engine."""
    import ctypes
    import subprocess

    from cilium_tpu.runtime.service import VerdictService

    repo = os.path.dirname(os.path.abspath(__file__))
    lib_path = os.path.join(repo, "shim", "libcilium_shim.so")
    if not os.path.exists(lib_path):
        try:
            subprocess.run(["make", "-C", os.path.join(repo, "shim")],
                           check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            return None
    lib = ctypes.CDLL(lib_path)
    lib.cshim_connect.argtypes = [ctypes.c_char_p]
    lib.cshim_on_new_connection.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_char_p]
    lib.cshim_on_data.argtypes = [
        ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
    # disconnect returns void — the c_int default would read garbage
    lib.cshim_disconnect.restype = None

    from cilium_tpu.proxylib.kafka import encode_request

    sock = os.path.join(sock_dir, "svc_shim.sock")
    service = VerdictService(loader, sock, batch_max=batch_max,
                             deadline_ms=deadline_ms)
    service.start()
    try:
        if lib.cshim_connect(sock.encode()) != 0:
            return None
        # latency is what this lane measures — the record parses and
        # verdicts regardless of whether the synth policy allows it
        payload = encode_request(0, 1, 7, "bench", "synth-topic")
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        ops = (ctypes.c_int32 * 16)()
        lib.cshim_on_new_connection(b"kafka", 1, 1, 1001, 1002, 9092,
                                    b"")
        lat = []
        for i in range(per_thread):
            t0 = time.perf_counter()
            lib.cshim_on_data(1, 0, 0, buf, len(payload), ops, 8)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        n = len(lat)
        return {
            "lane": "cpp_shim_kafka", "deadline_ms": deadline_ms,
            "samples": n,
            "p50_ms": round(lat[n // 2] * 1e3, 3),
            "p99_ms": round(lat[min(n - 1, int(n * 0.99))] * 1e3, 3),
        }
    finally:
        try:
            lib.cshim_disconnect()
        except Exception:
            pass
        service.stop()


def _device_rtt_ms(loader, probes: int = 10) -> float:
    """Median H2D+readback round-trip for a tiny array — the tunnel
    RTT floor every device-verdict batch pays at least once. The
    stream lane's p99 criterion is expressed against this."""
    import jax
    import numpy as np

    device = getattr(loader.engine, "device", None)
    xs = np.zeros(16, dtype=np.int32)
    times = []
    for _ in range(probes):
        t0 = time.perf_counter()
        np.asarray(jax.device_put(xs, device))
        times.append(time.perf_counter() - t0)
    times.sort()
    return round(times[len(times) // 2] * 1e3, 3)


def run_stream_point(loader, scenario, chunk_records: int,
                     rate_records_s: float, duration_s: float,
                     sock_dir: str, pipeline_depth: int = 8) -> dict:
    """Open-loop point over the chunked binary STREAM transport
    (runtime/stream.py): capture-image chunks are sent on a Poisson
    schedule at a fixed offered record rate; per-chunk latency is
    measured from the SCHEDULED send time (coordinated-omission-safe,
    like run_open_point). This is the serving-path answer to the
    request-response protocol's one-RTT-per-batch floor: with D chunks
    in flight the tunnel RTT amortizes D-ways."""
    import numpy as np

    from cilium_tpu.engine.verdict import flowbatch_to_host_dict  # noqa: F401 (jit warm import)
    from cilium_tpu.ingest.binary import (
        capture_field_widths,
        capture_from_bytes,
        capture_to_bytes,
    )
    from cilium_tpu.runtime.service import VerdictService
    from cilium_tpu.runtime.stream import StreamClient

    sock = os.path.join(sock_dir, f"svc_stream_{int(rate_records_s)}.sock")
    service = VerdictService(loader, sock)
    service.start()
    try:
        # pre-serialized chunk pool (client-side encode cost is real
        # but belongs to the traffic source, not the measured service).
        # Tile the scenario's flows so every image carries EXACTLY
        # chunk_records — a short flow pool must not silently shrink
        # the chunks (and the reported per-chunk record rate)
        flows = list(scenario.flows)
        while len(flows) < chunk_records * 4:
            flows = flows + flows
        images = []
        for i in range(0, len(flows) - chunk_records + 1,
                       chunk_records):
            images.append(capture_to_bytes(flows[i:i + chunk_records]))
            if len(images) >= 16:
                break
        _, l7, offsets, _blob, _gen = capture_from_bytes(images[0])
        widths = capture_field_widths(l7, offsets)
        client = StreamClient(sock, widths=widths,
                              timeout=max(120.0, duration_s * 3),
                              pipeline_depth=pipeline_depth)
        # prewarm with EVERY image: compiles the padded chunk bucket
        # AND settles the incremental session's tables (string/row
        # interning + growth flushes happen here, not in the measured
        # window — the window then measures steady-state serving, the
        # regime the criterion is about; cold-session cost is its own
        # number, reported as warmup_s)
        t_warm = time.perf_counter()
        for img in images:
            client.result(client.send_image(img))
        warmup_s = time.perf_counter() - t_warm

        chunk_rate = rate_records_s / chunk_records
        rng = random.Random(99)
        arrivals, t = [], 0.0
        while t < duration_s:
            t += rng.expovariate(chunk_rate)
            arrivals.append(t)
        sched_of: dict = {}
        lock = threading.Lock()
        done_recv = threading.Event()
        completions: list = []
        n_records = [0]
        errors = [0]

        def collector():
            try:
                for seq, verdicts in client.results():
                    now = time.perf_counter()
                    with lock:
                        sched = sched_of.pop(seq, None)
                        if isinstance(verdicts, Exception):
                            errors[0] += 1  # failed seq; keep draining
                        elif sched is not None:
                            completions.append(now - sched)
                            n_records[0] += len(verdicts)
            except Exception:
                with lock:
                    errors[0] += 1
            done_recv.set()

        col = threading.Thread(target=collector, daemon=True)
        col.start()
        base = time.perf_counter() + 0.05
        for i, a in enumerate(arrivals):
            sched = base + a
            now = time.perf_counter()
            if sched > now:
                time.sleep(sched - now)
            img = images[i % len(images)]
            # send + register under ONE lock hold: the collector can
            # receive the verdict on its thread before we register the
            # seq, but it can't pop it until we release
            with lock:
                sched_of[client.send_image(img)] = sched
        client.finish()
        done_recv.wait(timeout=60)
        wall = time.perf_counter() - base
        client.close()
    finally:
        service.stop()

    qs = _quantiles(completions)
    return {
        "lane": "stream",
        "warmup_s": round(warmup_s, 2),
        "chunk_records": chunk_records,
        "offered_records_s": rate_records_s,
        "achieved_records_s": round(n_records[0] / max(wall, 1e-9), 1),
        "offered_chunks_s": round(chunk_rate, 2),
        "pipeline_depth": pipeline_depth,
        "errors": errors[0],
        **qs,
    }


import re as _re

_TRANSIENT_RE = _re.compile(
    r"connection|reset|refused|broken ?pipe|timed out|unavailable|"
    r"read body|EOF", _re.I)


def _safe_point(lane: str, fn, *a, **kw):
    """Lane isolation (perf ledger): a sweep point that dies on a
    transient connection error gets exactly ONE retry; a second (or
    non-transient) failure records a structured failure point —
    ``{lane, failed, error, attempts}`` — and the sweep continues
    instead of losing the whole artifact."""
    for attempt in (1, 2):
        try:
            return fn(*a, **kw)
        except Exception as e:  # noqa: BLE001 — any point death must
            # degrade to a structured record, not kill the sweep
            err = f"{type(e).__name__}: {e}"
            if attempt == 1 and _TRANSIENT_RE.search(err):
                print(f"[{lane}] transient point failure, one retry: "
                      f"{err[:200]}", file=sys.stderr)
                continue
            print(f"[{lane}] point failed ({attempt} attempt(s)): "
                  f"{err[:200]}", file=sys.stderr)
            return {"lane": lane, "failed": True, "error": err[:500],
                    "attempts": attempt}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=1000)
    ap.add_argument("--deadlines", default="0.5,2,8",
                    help="comma-separated MicroBatcher deadlines (ms)")
    ap.add_argument("--threads", type=int, default=16)
    ap.add_argument("--per-thread", type=int, default=50,
                    help="timed requests per thread (total = threads x "
                         "this; keep >= 200 total for a real p99)")
    ap.add_argument("--warmup", type=int, default=10)
    ap.add_argument("--batch-max", type=int, default=256)
    ap.add_argument("--shim", action="store_true",
                    help="add the C++-shim kafka lane")
    ap.add_argument("--no-open", action="store_true",
                    help="skip the open-loop (Poisson fixed-rate) sweep")
    ap.add_argument("--open-rates", default=None,
                    help="comma-separated offered rates (rps); default "
                         "doubles from 500 until saturation")
    ap.add_argument("--open-deadline", type=float, default=8.0,
                    help="MicroBatcher deadline (ms) for the open-loop "
                         "sweep (the batching-regime deadline)")
    ap.add_argument("--open-duration", type=float, default=3.0,
                    help="seconds of offered load per open-loop point")
    ap.add_argument("--drain-workers", type=int, default=1,
                    help="MicroBatcher drain workers for the open-loop "
                         "sweep (2 pipelines batch k+1 against batch "
                         "k's device round-trip)")
    ap.add_argument("--open-conns", type=int, default=256,
                    help="client connections serving the arrival "
                         "schedule. The protocol is request-response "
                         "per connection, so in-flight requests — "
                         "and therefore the max achievable batch — "
                         "are capped at this count (a proxy opens "
                         "many connections in production for the "
                         "same reason)")
    ap.add_argument("--stream", action="store_true",
                    help="add the chunked-binary-stream open-loop "
                         "sweep (the serving-path transport)")
    ap.add_argument("--stream-rates", default=None,
                    help="comma-separated offered record rates "
                         "(records/s); default doubles from 100000 "
                         "until saturation")
    ap.add_argument("--stream-chunk", type=int, default=4096,
                    help="records per stream chunk")
    ap.add_argument("--stream-duration", type=float, default=5.0,
                    help="seconds of offered load per stream point")
    ap.add_argument("--stream-depth", type=int, default=8,
                    help="server pipeline depth (dispatched chunks in "
                         "flight)")
    ap.add_argument("--stream-only", action="store_true",
                    help="skip the closed/open JSON-protocol sweeps")
    ap.add_argument("--out", default=None,
                    help="write the full sweep JSON here")
    ap.add_argument("--trace", action="store_true",
                    help="leave the flight recorder on during the "
                         "sweep (default: disabled, so the bench "
                         "measures the un-instrumented hot path; the "
                         "tracing-overhead A/B runs once with and "
                         "once without this flag)")
    args = ap.parse_args()

    # the flight recorder defaults ON for serving processes; a bench
    # must measure the disarmed path unless tracing is the experiment
    from cilium_tpu.runtime.tracing import TRACER

    TRACER.configure(enabled=bool(args.trace))

    # honor JAX_PLATFORMS even with a PJRT plugin site on the path
    # (env alone does not always win — same guard as bench.py)
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # without the persistent cache, every sweep process recompiled all
    # ~9 pow2 batch buckets at 10-20s each through the tunnel — the
    # round-4 first TPU sweep's windows were mostly compile time
    from cilium_tpu.runtime.xla_cache import enable_persistent_cache

    enable_persistent_cache()

    import tempfile

    loader, scenario = build_engine(args.rules)
    sock_dir = tempfile.mkdtemp(prefix="ct_svcbench_")
    points = []
    if args.stream:
        rtt = _device_rtt_ms(loader)
        print(json.dumps({"metric": "device_rtt_probe",
                          "value": rtt, "unit": "ms median",
                          "vs_baseline": 0.0}), flush=True)
        if args.stream_rates:
            rates = [float(x) for x in args.stream_rates.split(",")]
            adaptive = False
        else:
            rates, adaptive = [100_000.0], True
        i = 0
        while i < len(rates):
            rate = rates[i]
            pt = _safe_point(
                "stream", run_stream_point, loader, scenario,
                args.stream_chunk, rate, args.stream_duration,
                sock_dir, pipeline_depth=args.stream_depth)
            if pt.get("failed"):
                points.append(pt)
                i += 1
                continue
            pt["device_rtt_ms"] = rtt
            points.append(pt)
            print(json.dumps({
                "metric": f"service_stream_{int(rate)}rps_"
                          f"{args.rules}rules",
                "value": pt["achieved_records_s"],
                "unit": "verdicts/s online (stream)",
                "vs_baseline": round(
                    pt["achieved_records_s"] / 1e5, 3), **pt}),
                flush=True)
            saturated = (pt["achieved_records_s"] < 0.9 * rate
                         or pt["samples"] == 0)
            if adaptive and not saturated and rate < 5e7:
                rates.append(rate * 2)
            i += 1
    if args.stream_only:
        if args.out:
            from cilium_tpu.runtime.provenance import stamp

            with open(args.out, "w") as f:
                json.dump(stamp({"rules": args.rules,
                                 "points": points}), f, indent=1)
        return 0
    for d in (float(x) for x in args.deadlines.split(",")):
        pt = _safe_point("closed", run_point, loader, scenario, d,
                         args.batch_max, args.threads, args.per_thread,
                         args.warmup, sock_dir)
        points.append(pt)
        if pt.get("failed"):
            continue
        print(json.dumps({
            "metric": f"service_check_latency_d{d}ms_{args.rules}rules",
            "value": pt["p99_ms"], "unit": "ms p99 (client-observed)",
            "vs_baseline": 0.0, **pt}), flush=True)
    if args.shim:
        pt = run_shim_point(loader, 2.0, args.batch_max,
                            max(200, args.per_thread), 1, sock_dir)
        if pt is not None:
            points.append(pt)
            print(json.dumps({
                "metric": "service_shim_kafka_latency_d2.0ms",
                "value": pt["p99_ms"], "unit": "ms p99",
                "vs_baseline": 0.0, **pt}), flush=True)

    open_points = []
    if not args.no_open:
        # open-loop throughput-vs-p99 curve (VERDICT r3 item 4): fixed
        # offered rates until saturation — the regime where the
        # batcher actually fills batches
        d = args.open_deadline
        if args.open_rates:
            rates = [float(x) for x in args.open_rates.split(",")]
            adaptive = False
        else:
            rates, adaptive = [500.0], True
        i = 0
        while i < len(rates):
            rate = rates[i]
            pt = _safe_point(
                "open_loop", run_open_point, loader, scenario, d,
                args.batch_max, rate, args.open_duration,
                args.open_conns, args.warmup, sock_dir,
                drain_workers=args.drain_workers)
            if pt.get("failed"):
                open_points.append(pt)
                i += 1
                continue
            pt["lane"] = "open_loop"
            open_points.append(pt)
            print(json.dumps({
                "metric": f"service_open_loop_d{d}ms_"
                          f"{int(rate)}rps_{args.rules}rules",
                "value": pt["p99_ms"], "unit": "ms p99 (from scheduled "
                "arrival)", "vs_baseline": 0.0, **pt}), flush=True)
            saturated = (pt["achieved_rps"] < 0.9 * rate
                         or pt["samples"] == 0)
            if adaptive and not saturated and rate < 65536:
                rates.append(rate * 2)
            i += 1
        points.extend(open_points)
    if args.out:
        # provenance fingerprint + versioned schema (perf ledger)
        from cilium_tpu.runtime.provenance import stamp

        with open(args.out, "w") as f:
            json.dump(stamp({"rules": args.rules, "points": points}),
                      f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
