#!/usr/bin/env python
"""Service-level tail-latency benchmark: Unix socket → MicroBatcher →
engine, under concurrent closed-loop load.

VERDICT r2 item 3 / SURVEY.md §7 hard part #5: the micro-batcher
trades p99 latency for MXU utilization — this measures that trade
honestly. Per deadline setting (default 0.5/2/8 ms), N client threads
each run a closed loop of single-record ``check`` requests over the
verdict service's Unix socket (4B-length-prefixed JSON — the same
protocol the C++ shim speaks); every sample is CLIENT-OBSERVED wall
time (socket + JSON + queueing + batcher deadline + engine). ≥200
samples per point so p99 is a real quantile, not a max.

``--shim`` adds a lane driving the C++ shim
(shim/libcilium_shim.so → cshim_on_data with Kafka produce records)
so the native client path is on record too.

Prints one JSON line per sweep point and writes the full sweep to
``--out`` (SERVICE_LATENCY artifact).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def build_engine(n_rules: int):
    from cilium_tpu.core.config import Config
    from cilium_tpu.ingest import synth
    from cilium_tpu.runtime.loader import Loader

    scenario = synth.synth_http_scenario(n_rules=n_rules, n_flows=2000)
    per_identity, scenario = synth.realize_scenario(scenario)
    cfg = Config.from_env()
    cfg.enable_tpu_offload = True
    loader = Loader(cfg)
    loader.regenerate(per_identity, revision=1)
    return loader, scenario


def run_point(loader, scenario, deadline_ms: float, batch_max: int,
              threads: int, per_thread: int, warmup: int,
              sock_dir: str) -> dict:
    from cilium_tpu.ingest.hubble import flow_to_dict
    from cilium_tpu.runtime.metrics import METRICS
    from cilium_tpu.runtime.service import VerdictClient, VerdictService

    sock = os.path.join(sock_dir, f"svc_{deadline_ms}.sock")
    service = VerdictService(loader, sock, batch_max=batch_max,
                             deadline_ms=deadline_ms)
    service.start()
    # pre-warm every pow2 batch shape the padded flush can produce —
    # an XLA compile inside the timed window would report compiler
    # latency, not service latency
    size = 1
    while size <= batch_max:
        service.bridge._verdicts(scenario.flows[:size])
        size *= 2
    # distinct request templates per thread, pre-serialized
    reqs = [{"op": "check", "flow": flow_to_dict(f)}
            for f in scenario.flows[:threads * 64]]
    hist_key = ("cilium_tpu_microbatch_size", ())
    n_batches_before = len(METRICS._histos.get(hist_key, ()))

    lat_lock = threading.Lock()
    latencies: list = []
    errors = [0]
    start_barrier = threading.Barrier(threads + 1)
    done_barrier = threading.Barrier(threads + 1)

    def worker(tid: int):
        # EVERY exit path must pass both barriers or main blocks
        # forever waiting for threads+1 parties
        client = None
        mine = reqs[tid::threads] or reqs
        try:
            client = VerdictClient(sock)
            for i in range(warmup):
                client.call(mine[i % len(mine)])
        except Exception:
            with lat_lock:
                errors[0] += 1
            client = None
        start_barrier.wait()
        out = []
        try:
            if client is not None:
                for i in range(per_thread):
                    t0 = time.perf_counter()
                    resp = client.call(mine[i % len(mine)])
                    dt = time.perf_counter() - t0
                    if "verdict" not in resp:
                        with lat_lock:
                            errors[0] += 1
                    out.append(dt)
        except Exception:
            with lat_lock:
                errors[0] += 1
        with lat_lock:
            latencies.extend(out)
        done_barrier.wait()
        if client is not None:
            client.close()

    workers = [threading.Thread(target=worker, args=(t,), daemon=True)
               for t in range(threads)]
    for w in workers:
        w.start()
    start_barrier.wait()
    t_wall0 = time.perf_counter()
    done_barrier.wait()
    t_wall = time.perf_counter() - t_wall0
    for w in workers:
        w.join(timeout=30)
    service.stop()

    sizes = METRICS._histos.get(hist_key, ())[n_batches_before:]
    latencies.sort()
    n = len(latencies)
    if n == 0:  # every worker failed before timing anything
        return {"deadline_ms": deadline_ms, "batch_max": batch_max,
                "threads": threads, "samples": 0, "errors": errors[0],
                "throughput_rps": 0.0, "p50_ms": 0.0, "p95_ms": 0.0,
                "p99_ms": 0.0, "max_ms": 0.0, "mean_batch_size": 0}

    def q(p: float) -> float:
        return latencies[min(n - 1, int(n * p))] * 1e3

    return {
        "deadline_ms": deadline_ms,
        "batch_max": batch_max,
        "threads": threads,
        "samples": n,
        "errors": errors[0],
        "throughput_rps": round(n / t_wall, 1),
        "p50_ms": round(q(0.50), 3),
        "p95_ms": round(q(0.95), 3),
        "p99_ms": round(q(0.99), 3),
        "max_ms": round(latencies[-1] * 1e3, 3),
        "mean_batch_size": round(sum(sizes) / len(sizes), 1) if sizes
        else 0,
    }


def run_shim_point(loader, deadline_ms: float, batch_max: int,
                   per_thread: int, threads: int, sock_dir: str):
    """Kafka produce records through the C++ shim (native client path):
    cshim_on_data → socket → parser → MicroBatcher → engine."""
    import ctypes
    import subprocess

    from cilium_tpu.runtime.service import VerdictService

    repo = os.path.dirname(os.path.abspath(__file__))
    lib_path = os.path.join(repo, "shim", "libcilium_shim.so")
    if not os.path.exists(lib_path):
        try:
            subprocess.run(["make", "-C", os.path.join(repo, "shim")],
                           check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError):
            return None
    lib = ctypes.CDLL(lib_path)
    lib.cshim_connect.argtypes = [ctypes.c_char_p]
    lib.cshim_on_new_connection.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_char_p]
    lib.cshim_on_data.argtypes = [
        ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int]

    from cilium_tpu.proxylib.kafka import encode_request

    sock = os.path.join(sock_dir, "svc_shim.sock")
    service = VerdictService(loader, sock, batch_max=batch_max,
                             deadline_ms=deadline_ms)
    service.start()
    try:
        if lib.cshim_connect(sock.encode()) != 0:
            return None
        # latency is what this lane measures — the record parses and
        # verdicts regardless of whether the synth policy allows it
        payload = encode_request(0, 1, 7, "bench", "synth-topic")
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        ops = (ctypes.c_int32 * 16)()
        lib.cshim_on_new_connection(b"kafka", 1, 1, 1001, 1002, 9092,
                                    b"")
        lat = []
        for i in range(per_thread):
            t0 = time.perf_counter()
            lib.cshim_on_data(1, 0, 0, buf, len(payload), ops, 8)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        n = len(lat)
        return {
            "lane": "cpp_shim_kafka", "deadline_ms": deadline_ms,
            "samples": n,
            "p50_ms": round(lat[n // 2] * 1e3, 3),
            "p99_ms": round(lat[min(n - 1, int(n * 0.99))] * 1e3, 3),
        }
    finally:
        try:
            lib.cshim_disconnect()
        except Exception:
            pass
        service.stop()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=1000)
    ap.add_argument("--deadlines", default="0.5,2,8",
                    help="comma-separated MicroBatcher deadlines (ms)")
    ap.add_argument("--threads", type=int, default=16)
    ap.add_argument("--per-thread", type=int, default=50,
                    help="timed requests per thread (total = threads x "
                         "this; keep >= 200 total for a real p99)")
    ap.add_argument("--warmup", type=int, default=10)
    ap.add_argument("--batch-max", type=int, default=256)
    ap.add_argument("--shim", action="store_true",
                    help="add the C++-shim kafka lane")
    ap.add_argument("--out", default=None,
                    help="write the full sweep JSON here")
    args = ap.parse_args()

    # honor JAX_PLATFORMS even with a PJRT plugin site on the path
    # (env alone does not always win — same guard as bench.py)
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import tempfile

    loader, scenario = build_engine(args.rules)
    sock_dir = tempfile.mkdtemp(prefix="ct_svcbench_")
    points = []
    for d in (float(x) for x in args.deadlines.split(",")):
        pt = run_point(loader, scenario, d, args.batch_max,
                       args.threads, args.per_thread, args.warmup,
                       sock_dir)
        points.append(pt)
        print(json.dumps({
            "metric": f"service_check_latency_d{d}ms_{args.rules}rules",
            "value": pt["p99_ms"], "unit": "ms p99 (client-observed)",
            "vs_baseline": 0.0, **pt}), flush=True)
    if args.shim:
        pt = run_shim_point(loader, 2.0, args.batch_max,
                            max(200, args.per_thread), 1, sock_dir)
        if pt is not None:
            points.append(pt)
            print(json.dumps({
                "metric": "service_shim_kafka_latency_d2.0ms",
                "value": pt["p99_ms"], "unit": "ms p99",
                "vs_baseline": 0.0, **pt}), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"rules": args.rules, "points": points}, f,
                      indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
