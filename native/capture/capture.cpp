// Binary flow-capture codec: the perf-ring-buffer / PolicyVerdictNotify
// analog (reference: bpf/lib/events.h defines fixed-size C event
// records; pkg/monitor consumes them). Flow tuples are fixed 32-byte
// little-endian records so the Python side ingests them zero-copy as a
// numpy structured array — no per-record parsing on the hot path.
//
// File layout:
//   header (16B): magic "CTCAP1\0\0" | u32 version | u32 record_count
//   records (32B each, packed):
//     u32 src_identity | u32 dst_identity | u16 dport | u16 sport |
//     u8 proto | u8 direction | u8 l7_type | u8 verdict | f64 time |
//     u32 reserved0 | u32 reserved1
//
// L7 payloads (paths/qnames/topics) are not carried here — neither are
// they in the reference's ring events (L7 arrives via the accesslog
// path); JSONL remains the capture format for L7 flows.
//
// C ABI so ctypes loads it without pybind11. All functions return
// >=0 on success, negative error codes otherwise.

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

constexpr char MAGIC[8] = {'C', 'T', 'C', 'A', 'P', '1', '\0', '\0'};
constexpr uint32_t VERSION = 1;

#pragma pack(push, 1)
struct Header {
  char magic[8];
  uint32_t version;
  uint32_t record_count;
};

struct Record {
  uint32_t src_identity;
  uint32_t dst_identity;
  uint16_t dport;
  uint16_t sport;
  uint8_t proto;
  uint8_t direction;
  uint8_t l7_type;
  uint8_t verdict;
  double time;
  uint32_t reserved0;
  uint32_t reserved1;
};
#pragma pack(pop)

static_assert(sizeof(Header) == 16, "header must be 16 bytes");
static_assert(sizeof(Record) == 32, "record must be 32 bytes");

}  // namespace

extern "C" {

// error codes
enum {
  CT_OK = 0,
  CT_ERR_IO = -1,
  CT_ERR_MAGIC = -2,
  CT_ERR_VERSION = -3,
  CT_ERR_TRUNCATED = -4,
};

int ct_capture_record_size() { return (int)sizeof(Record); }

// Write `n` records to `path` (whole-file write; the writer owns the
// file). Returns CT_OK or a negative error.
int ct_capture_write(const char* path, const void* records, uint32_t n) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return CT_ERR_IO;
  Header h;
  std::memcpy(h.magic, MAGIC, sizeof(MAGIC));
  h.version = VERSION;
  h.record_count = n;
  int rc = CT_OK;
  if (std::fwrite(&h, sizeof(h), 1, f) != 1) rc = CT_ERR_IO;
  if (rc == CT_OK && n > 0 &&
      std::fwrite(records, sizeof(Record), n, f) != n)
    rc = CT_ERR_IO;
  if (std::fclose(f) != 0 && rc == CT_OK) rc = CT_ERR_IO;
  return rc;
}

// Validate the header; returns the record count (>=0) or an error.
int ct_capture_count(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return CT_ERR_IO;
  Header h;
  int rc;
  if (std::fread(&h, sizeof(h), 1, f) != 1) {
    rc = CT_ERR_TRUNCATED;
  } else if (std::memcmp(h.magic, MAGIC, sizeof(MAGIC)) != 0) {
    rc = CT_ERR_MAGIC;
  } else if (h.version != VERSION) {
    rc = CT_ERR_VERSION;
  } else {
    // the byte length must back the declared count: a torn write must
    // not read as a shorter-but-valid capture
    if (std::fseek(f, 0, SEEK_END) != 0) {
      rc = CT_ERR_IO;
    } else {
      long size = std::ftell(f);
      long want = (long)sizeof(Header) + (long)h.record_count * 32;
      rc = (size == want) ? (int)h.record_count : CT_ERR_TRUNCATED;
    }
  }
  std::fclose(f);
  return rc;
}

// Read up to `max` records starting at record `offset` into `out`.
// Returns the number read (>=0) or a negative error.
int ct_capture_read(const char* path, void* out, uint32_t max,
                    uint32_t offset) {
  int total = ct_capture_count(path);
  if (total < 0) return total;
  if (offset >= (uint32_t)total) return 0;
  uint32_t n = (uint32_t)total - offset;
  if (n > max) n = max;
  FILE* f = std::fopen(path, "rb");
  if (!f) return CT_ERR_IO;
  int rc;
  if (std::fseek(f, (long)sizeof(Header) + (long)offset * 32,
                 SEEK_SET) != 0) {
    rc = CT_ERR_IO;
  } else if (std::fread(out, sizeof(Record), n, f) != n) {
    rc = CT_ERR_TRUNCATED;
  } else {
    rc = (int)n;
  }
  std::fclose(f);
  return rc;
}

}  // extern "C"
