// Binary flow-capture codec: the perf-ring-buffer / PolicyVerdictNotify
// analog (reference: bpf/lib/events.h defines fixed-size C event
// records; pkg/monitor consumes them). Flow tuples are fixed 32-byte
// little-endian records so the Python side ingests them zero-copy as a
// numpy structured array — no per-record parsing on the hot path.
//
// File layout:
//   header (16B): magic "CTCAP1\0\0" | u32 version | u32 record_count
//   records (32B each, packed):
//     u32 src_identity | u32 dst_identity | u16 dport | u16 sport |
//     u8 proto | u8 direction | u8 l7_type | u8 verdict | f64 time |
//     u32 reserved0 | u32 reserved1
//
// Version 1 carries L3/L4 tuples only. Version 2 appends an L7
// SIDECAR so HTTP/Kafka/DNS payloads replay from the binary format
// too (the reference's accesslog path equivalent, columnar): a shared
// string table (u32 offsets + one blob; string 0 is always "") plus
// one fixed 32-byte L7 record per flow referencing it. The Python
// side ingests both sections zero-copy and featurizes with pure
// numpy gathers — no per-flow objects anywhere (VERDICT r2 item 2).
//
// v2 file layout:
//   Header (16B) | Record × count | L7Header (16B) |
//   u32 offsets × (n_strings+1) | blob bytes | L7Record × count
//
// C ABI so ctypes loads it without pybind11. All functions return
// >=0 on success, negative error codes otherwise.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

constexpr char MAGIC[8] = {'C', 'T', 'C', 'A', 'P', '1', '\0', '\0'};
constexpr uint32_t VERSION = 1;
constexpr uint32_t VERSION_L7 = 2;
// v3 = v2 + a GENERIC section after the L7 records: per flow a u32
// l7proto string index plus fmax (key, value) u32 string-index pairs
// (record size 4 + 8*fmax). fmax rides the L7Header's reserved word.
constexpr uint32_t VERSION_L7G = 3;

#pragma pack(push, 1)
struct Header {
  char magic[8];
  uint32_t version;
  uint32_t record_count;
};

struct Record {
  uint32_t src_identity;
  uint32_t dst_identity;
  uint16_t dport;
  uint16_t sport;
  uint8_t proto;
  uint8_t direction;
  uint8_t l7_type;
  uint8_t verdict;
  double time;
  uint32_t reserved0;
  uint32_t reserved1;
};

struct L7Header {
  uint32_t n_strings;
  uint32_t reserved;
  uint64_t blob_bytes;
};

// string-table references; index 0 is the empty string by convention
struct L7Record {
  uint32_t path;
  uint32_t method;
  uint32_t host;
  uint32_t headers;   // serialized canonical header block
  uint32_t qname;     // sanitized at write time
  uint32_t kafka_client;
  uint32_t kafka_topic;
  int16_t kafka_api_key;
  int16_t kafka_api_version;
};
#pragma pack(pop)

static_assert(sizeof(Header) == 16, "header must be 16 bytes");
static_assert(sizeof(Record) == 32, "record must be 32 bytes");
static_assert(sizeof(L7Header) == 16, "l7 header must be 16 bytes");
static_assert(sizeof(L7Record) == 32, "l7 record must be 32 bytes");

// reads the validated header; returns 0 on success, error code else
int read_header(FILE* f, Header* h) {
  if (std::fread(h, sizeof(*h), 1, f) != 1) return -4;
  if (std::memcmp(h->magic, MAGIC, sizeof(MAGIC)) != 0) return -2;
  if (h->version != VERSION && h->version != VERSION_L7 &&
      h->version != VERSION_L7G)
    return -3;
  return 0;
}

}  // namespace

extern "C" {

// error codes
enum {
  CT_OK = 0,
  CT_ERR_IO = -1,
  CT_ERR_MAGIC = -2,
  CT_ERR_VERSION = -3,
  CT_ERR_TRUNCATED = -4,
};

int ct_capture_record_size() { return (int)sizeof(Record); }

// Write `n` records to `path` (whole-file write; the writer owns the
// file). Returns CT_OK or a negative error.
int ct_capture_write(const char* path, const void* records, uint32_t n) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return CT_ERR_IO;
  Header h;
  std::memcpy(h.magic, MAGIC, sizeof(MAGIC));
  h.version = VERSION;
  h.record_count = n;
  int rc = CT_OK;
  if (std::fwrite(&h, sizeof(h), 1, f) != 1) rc = CT_ERR_IO;
  if (rc == CT_OK && n > 0 &&
      std::fwrite(records, sizeof(Record), n, f) != n)
    rc = CT_ERR_IO;
  if (std::fclose(f) != 0 && rc == CT_OK) rc = CT_ERR_IO;
  return rc;
}

// Write `n` records plus the L7 sidecar (version-2 capture).
// `offsets` has n_strings+1 entries; offsets[0] must be 0 and
// offsets[n_strings] == blob_bytes.
int ct_capture_write_l7(const char* path, const void* records, uint32_t n,
                        const void* l7_records, const uint32_t* offsets,
                        uint32_t n_strings, const void* blob,
                        uint64_t blob_bytes) {
  if (n_strings == 0 || offsets[0] != 0 ||
      offsets[n_strings] != blob_bytes)
    return CT_ERR_TRUNCATED;
  FILE* f = std::fopen(path, "wb");
  if (!f) return CT_ERR_IO;
  Header h;
  std::memcpy(h.magic, MAGIC, sizeof(MAGIC));
  h.version = VERSION_L7;
  h.record_count = n;
  L7Header lh;
  lh.n_strings = n_strings;
  lh.reserved = 0;
  lh.blob_bytes = blob_bytes;
  int rc = CT_OK;
  if (std::fwrite(&h, sizeof(h), 1, f) != 1) rc = CT_ERR_IO;
  if (rc == CT_OK && n > 0 &&
      std::fwrite(records, sizeof(Record), n, f) != n)
    rc = CT_ERR_IO;
  if (rc == CT_OK && std::fwrite(&lh, sizeof(lh), 1, f) != 1)
    rc = CT_ERR_IO;
  if (rc == CT_OK &&
      std::fwrite(offsets, sizeof(uint32_t), n_strings + 1, f) !=
          n_strings + 1)
    rc = CT_ERR_IO;
  if (rc == CT_OK && blob_bytes > 0 &&
      std::fwrite(blob, 1, blob_bytes, f) != blob_bytes)
    rc = CT_ERR_IO;
  if (rc == CT_OK && n > 0 &&
      std::fwrite(l7_records, sizeof(L7Record), n, f) != n)
    rc = CT_ERR_IO;
  if (std::fclose(f) != 0 && rc == CT_OK) rc = CT_ERR_IO;
  return rc;
}

// Write a version-3 capture: v2 sections plus the GENERIC section
// (`gen` = n records of 4 + 8*gen_fmax bytes each; gen_fmax > 0).
int ct_capture_write_l7g(const char* path, const void* records,
                         uint32_t n, const void* l7_records,
                         const uint32_t* offsets, uint32_t n_strings,
                         const void* blob, uint64_t blob_bytes,
                         const void* gen, uint32_t gen_fmax) {
  if (gen_fmax == 0 || n_strings == 0 || offsets[0] != 0 ||
      offsets[n_strings] != blob_bytes)
    return CT_ERR_TRUNCATED;
  FILE* f = std::fopen(path, "wb");
  if (!f) return CT_ERR_IO;
  Header h;
  std::memcpy(h.magic, MAGIC, sizeof(MAGIC));
  h.version = VERSION_L7G;
  h.record_count = n;
  L7Header lh;
  lh.n_strings = n_strings;
  lh.reserved = gen_fmax;
  lh.blob_bytes = blob_bytes;
  size_t gen_bytes = (size_t)n * (4 + 8 * (size_t)gen_fmax);
  int rc = CT_OK;
  if (std::fwrite(&h, sizeof(h), 1, f) != 1) rc = CT_ERR_IO;
  if (rc == CT_OK && n > 0 &&
      std::fwrite(records, sizeof(Record), n, f) != n)
    rc = CT_ERR_IO;
  if (rc == CT_OK && std::fwrite(&lh, sizeof(lh), 1, f) != 1)
    rc = CT_ERR_IO;
  if (rc == CT_OK &&
      std::fwrite(offsets, sizeof(uint32_t), n_strings + 1, f) !=
          n_strings + 1)
    rc = CT_ERR_IO;
  if (rc == CT_OK && blob_bytes > 0 &&
      std::fwrite(blob, 1, blob_bytes, f) != blob_bytes)
    rc = CT_ERR_IO;
  if (rc == CT_OK && n > 0 &&
      std::fwrite(l7_records, sizeof(L7Record), n, f) != n)
    rc = CT_ERR_IO;
  if (rc == CT_OK && n > 0 &&
      std::fwrite(gen, 1, gen_bytes, f) != gen_bytes)
    rc = CT_ERR_IO;
  if (std::fclose(f) != 0 && rc == CT_OK) rc = CT_ERR_IO;
  return rc;
}

// -- streaming columnar record-batch writer ---------------------------
//
// The file layout interleaves sections (records | strings | l7 | gen),
// so a one-shot writer forces the caller to assemble every section in
// memory first. This writer accepts RECORD BATCHES instead: base
// records stream straight to the file as they arrive, the trailing
// fixed-width sections (L7 + GENERIC rows, 32 and 4+8*fmax bytes per
// record) buffer in growable arrays, and finish() lays down the string
// table + buffered sections and patches the header count. Memory held
// is O(records x trailing-row width), never the string blob or the
// base records.

namespace {

struct BatchWriter {
  FILE* f;
  uint32_t n;
  uint32_t gen_fmax;  // 0 = v2 capture
  unsigned char* l7;
  size_t l7_cap;
  unsigned char* gen;
  size_t gen_cap;
};

int grow(unsigned char** buf, size_t* cap, size_t need) {
  if (need <= *cap) return CT_OK;
  size_t want = *cap ? *cap : 4096;
  while (want < need) want *= 2;
  unsigned char* p = (unsigned char*)std::realloc(*buf, want);
  if (!p) return CT_ERR_IO;
  *buf = p;
  *cap = want;
  return CT_OK;
}

void writer_free(BatchWriter* w) {
  if (w->f) std::fclose(w->f);
  std::free(w->l7);
  std::free(w->gen);
  std::free(w);
}

}  // namespace

// Open a streaming writer; gen_fmax 0 writes a v2 capture, >0 a v3
// with that many pair slots per GENERIC row. Returns NULL on error.
void* ct_capture_writer_open(const char* path, uint32_t gen_fmax) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  BatchWriter* w = (BatchWriter*)std::calloc(1, sizeof(BatchWriter));
  if (!w) {
    std::fclose(f);
    return nullptr;
  }
  w->f = f;
  w->gen_fmax = gen_fmax;
  Header h;
  std::memcpy(h.magic, MAGIC, sizeof(MAGIC));
  h.version = gen_fmax ? VERSION_L7G : VERSION_L7;
  h.record_count = 0;  // patched by finish()
  if (std::fwrite(&h, sizeof(h), 1, f) != 1) {
    writer_free(w);
    return nullptr;
  }
  return w;
}

// Append one record batch: n base records (streamed to disk), their n
// L7 rows (buffered), and — for a v3 writer — their n GENERIC rows of
// 4 + 8*gen_fmax bytes (buffered; pass NULL for a v2 writer).
int ct_capture_writer_batch(void* wp, const void* records,
                            const void* l7_records, const void* gen,
                            uint32_t n) {
  BatchWriter* w = (BatchWriter*)wp;
  if (!w || !w->f) return CT_ERR_IO;
  if (n == 0) return CT_OK;
  if (w->gen_fmax != 0 && gen == nullptr) return CT_ERR_TRUNCATED;
  if (std::fwrite(records, sizeof(Record), n, w->f) != n)
    return CT_ERR_IO;
  size_t l7_bytes = (size_t)n * sizeof(L7Record);
  if (grow(&w->l7, &w->l7_cap,
           (size_t)w->n * sizeof(L7Record) + l7_bytes) != CT_OK)
    return CT_ERR_IO;
  std::memcpy(w->l7 + (size_t)w->n * sizeof(L7Record), l7_records,
              l7_bytes);
  if (w->gen_fmax != 0) {
    size_t row = 4 + 8 * (size_t)w->gen_fmax;
    if (grow(&w->gen, &w->gen_cap, ((size_t)w->n + n) * row) != CT_OK)
      return CT_ERR_IO;
    std::memcpy(w->gen + (size_t)w->n * row, gen, (size_t)n * row);
  }
  w->n += n;
  return CT_OK;
}

// Write the string table + buffered trailing sections, patch the
// header count, close and free the writer (always freed, even on
// error). Returns the record count (>=0) or a negative error.
int ct_capture_writer_finish(void* wp, const uint32_t* offsets,
                             uint32_t n_strings, const void* blob,
                             uint64_t blob_bytes) {
  BatchWriter* w = (BatchWriter*)wp;
  if (!w) return CT_ERR_IO;
  int rc = CT_OK;
  if (n_strings == 0 || offsets[0] != 0 ||
      offsets[n_strings] != blob_bytes)
    rc = CT_ERR_TRUNCATED;
  L7Header lh;
  lh.n_strings = n_strings;
  lh.reserved = w->gen_fmax;
  lh.blob_bytes = blob_bytes;
  if (rc == CT_OK && std::fwrite(&lh, sizeof(lh), 1, w->f) != 1)
    rc = CT_ERR_IO;
  if (rc == CT_OK &&
      std::fwrite(offsets, sizeof(uint32_t), n_strings + 1, w->f) !=
          n_strings + 1)
    rc = CT_ERR_IO;
  if (rc == CT_OK && blob_bytes > 0 &&
      std::fwrite(blob, 1, blob_bytes, w->f) != blob_bytes)
    rc = CT_ERR_IO;
  if (rc == CT_OK && w->n > 0 &&
      std::fwrite(w->l7, sizeof(L7Record), w->n, w->f) != w->n)
    rc = CT_ERR_IO;
  if (rc == CT_OK && w->gen_fmax != 0 && w->n > 0) {
    size_t gen_bytes = (size_t)w->n * (4 + 8 * (size_t)w->gen_fmax);
    if (std::fwrite(w->gen, 1, gen_bytes, w->f) != gen_bytes)
      rc = CT_ERR_IO;
  }
  if (rc == CT_OK) {
    Header h;
    std::memcpy(h.magic, MAGIC, sizeof(MAGIC));
    h.version = w->gen_fmax ? VERSION_L7G : VERSION_L7;
    h.record_count = w->n;
    if (std::fseek(w->f, 0, SEEK_SET) != 0 ||
        std::fwrite(&h, sizeof(h), 1, w->f) != 1)
      rc = CT_ERR_IO;
  }
  int n = (int)w->n;
  if (std::fclose(w->f) != 0 && rc == CT_OK) rc = CT_ERR_IO;
  w->f = nullptr;
  writer_free(w);
  return rc == CT_OK ? n : rc;
}

// Abandon a streaming writer: close, free, leave whatever bytes were
// written (the header still says 0 records, so readers reject it as
// truncated rather than misparse).
int ct_capture_writer_abort(void* wp) {
  BatchWriter* w = (BatchWriter*)wp;
  if (!w) return CT_ERR_IO;
  writer_free(w);
  return CT_OK;
}

// Validate the header; returns the record count (>=0) or an error.
int ct_capture_count(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return CT_ERR_IO;
  Header h;
  int rc = read_header(f, &h);
  if (rc == 0) {
    // the byte length must back the declared count: a torn write must
    // not read as a shorter-but-valid capture
    long want = -1;
    if (h.version == VERSION) {
      want = (long)sizeof(Header) + (long)h.record_count * 32;
    } else {
      L7Header lh;
      if (std::fseek(f, (long)h.record_count * 32, SEEK_CUR) != 0 ||
          std::fread(&lh, sizeof(lh), 1, f) != 1) {
        rc = CT_ERR_TRUNCATED;
      } else {
        want = (long)sizeof(Header) + (long)h.record_count * 32 +
               (long)sizeof(L7Header) +
               (long)(lh.n_strings + 1) * 4 + (long)lh.blob_bytes +
               (long)h.record_count * 32;
        if (h.version == VERSION_L7G) {
          // reserved carries gen fmax; record = 4 + 8*fmax bytes
          if (lh.reserved == 0) {
            rc = CT_ERR_TRUNCATED;
          } else {
            want += (long)h.record_count * (4 + 8 * (long)lh.reserved);
          }
        }
      }
    }
    if (rc == 0) {
      if (std::fseek(f, 0, SEEK_END) != 0) {
        rc = CT_ERR_IO;
      } else {
        rc = (std::ftell(f) == want) ? (int)h.record_count
                                     : CT_ERR_TRUNCATED;
      }
    }
  } else if (rc == -4) {
    rc = CT_ERR_TRUNCATED;
  }
  std::fclose(f);
  return rc;
}

// Sidecar geometry: fills n_strings/blob_bytes (0/0 for a v1 capture).
// Returns the record count (>=0) or an error.
int ct_capture_l7_info(const char* path, uint32_t* n_strings,
                       uint64_t* blob_bytes) {
  *n_strings = 0;
  *blob_bytes = 0;
  int total = ct_capture_count(path);
  if (total < 0) return total;
  FILE* f = std::fopen(path, "rb");
  if (!f) return CT_ERR_IO;
  Header h;
  int rc = read_header(f, &h);
  if (rc == 0 && (h.version == VERSION_L7 || h.version == VERSION_L7G)) {
    L7Header lh;
    if (std::fseek(f, (long)h.record_count * 32, SEEK_CUR) != 0 ||
        std::fread(&lh, sizeof(lh), 1, f) != 1) {
      rc = CT_ERR_TRUNCATED;
    } else {
      *n_strings = lh.n_strings;
      *blob_bytes = lh.blob_bytes;
    }
  }
  std::fclose(f);
  return rc == 0 ? total : rc;
}

// Read the whole sidecar (caller sized the buffers via l7_info).
int ct_capture_read_l7(const char* path, void* l7_records,
                       uint32_t* offsets, void* blob) {
  uint32_t n_strings;
  uint64_t blob_bytes;
  int total = ct_capture_l7_info(path, &n_strings, &blob_bytes);
  if (total < 0) return total;
  if (n_strings == 0) return CT_ERR_VERSION;  // v1: no sidecar
  FILE* f = std::fopen(path, "rb");
  if (!f) return CT_ERR_IO;
  int rc = CT_OK;
  if (std::fseek(f,
                 (long)sizeof(Header) + (long)total * 32 +
                     (long)sizeof(L7Header),
                 SEEK_SET) != 0)
    rc = CT_ERR_IO;
  if (rc == CT_OK &&
      std::fread(offsets, sizeof(uint32_t), n_strings + 1, f) !=
          n_strings + 1)
    rc = CT_ERR_TRUNCATED;
  if (rc == CT_OK && blob_bytes > 0 &&
      std::fread(blob, 1, blob_bytes, f) != blob_bytes)
    rc = CT_ERR_TRUNCATED;
  if (rc == CT_OK && total > 0 &&
      std::fread(l7_records, sizeof(L7Record), total, f) !=
          (size_t)total)
    rc = CT_ERR_TRUNCATED;
  std::fclose(f);
  return rc == CT_OK ? total : rc;
}

// Read up to `max` records starting at record `offset` into `out`.
// Returns the number read (>=0) or a negative error.
int ct_capture_read(const char* path, void* out, uint32_t max,
                    uint32_t offset) {
  int total = ct_capture_count(path);
  if (total < 0) return total;
  if (offset >= (uint32_t)total) return 0;
  uint32_t n = (uint32_t)total - offset;
  if (n > max) n = max;
  FILE* f = std::fopen(path, "rb");
  if (!f) return CT_ERR_IO;
  int rc;
  if (std::fseek(f, (long)sizeof(Header) + (long)offset * 32,
                 SEEK_SET) != 0) {
    rc = CT_ERR_IO;
  } else if (std::fread(out, sizeof(Record), n, f) != n) {
    rc = CT_ERR_TRUNCATED;
  } else {
    rc = (int)n;
  }
  std::fclose(f);
  return rc;
}

}  // extern "C"
