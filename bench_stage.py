#!/usr/bin/env python
"""Staging microbench: capture → staged replay session, decomposed.

The fast lane behind ``make bench-stage``: where ``bench.py``'s e2e
lane buries session staging inside a full throughput run, this bench
measures ONLY the ingest/staging pipeline the columnar-ingest work
targets — columnar capture write, file open/section reads, and the
CaptureReplay staging phases (string-table device scans / whole-file
featurize / hash dedup / unique-table H2D), plus the verdict-memo
fill — and prints one provenance-stamped JSON line per lane
(``bench_schema`` + fingerprint, like every official bench line, so
``cilium-tpu perf-report`` can trend them and attribute regressions).

Two staging samples are taken in-process: ``cold`` (first session —
pays jit tracing and whatever the persistent XLA cache cannot serve)
and ``warm`` (second session over the same shapes — the steady state
a daemon or repeat bench sees). The headline ``stage_ms`` metric is
the cold number: that is what a fresh replay pays.

Usage: python bench_stage.py [--rules 1000] [--capture-flows 200000]
       [--config http] [--verbose]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="http",
                    choices=["http", "fqdn", "kafka", "generic"])
    ap.add_argument("--rules", type=int, default=1000)
    ap.add_argument("--capture-flows", type=int, default=200000)
    ap.add_argument("--scenario-flows", type=int, default=10000)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    def log(msg: str) -> None:
        if args.verbose:
            print(msg, file=sys.stderr)

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from cilium_tpu.core.config import Config
    from cilium_tpu.engine.verdict import CaptureReplay
    from cilium_tpu.ingest import binary, synth
    from cilium_tpu.runtime.metrics import (
        CAPTURE_STAGE_SECONDS,
        METRICS,
    )
    from cilium_tpu.runtime.provenance import stamp

    cfg = Config.from_env()
    cfg.enable_tpu_offload = True

    scenario = synth.scenario_by_name(args.config, args.rules,
                                      args.scenario_flows)
    per_identity, scenario = synth.realize_scenario(scenario)

    from cilium_tpu.runtime.loader import Loader

    engine = Loader(cfg).regenerate(per_identity, revision=1)

    cap = os.path.join(tempfile.gettempdir(),
                       f"ct_stage_{os.getuid()}_{args.config}_"
                       f"{args.rules}r_{args.capture_flows}f.bin")
    t0 = time.perf_counter()
    n = synth.write_scenario_capture(cap, scenario, args.capture_flows)
    write_ms = round((time.perf_counter() - t0) * 1e3, 1)
    log(f"columnar capture write: {n} records in {write_ms}ms")

    t0 = time.perf_counter()
    rec_all = binary.map_capture(cap)
    l7_all, offsets, blob = binary.read_l7_sidecar(cap)
    gen_all = binary.read_gen_sidecar(cap)
    open_ms = round((time.perf_counter() - t0) * 1e3, 1)

    # memo-fill is deliberately NOT in the stage split: stage_ms
    # covers ingest staging only (the memo fill is the compile/warm
    # analog, reported as memo_fill_ms) — sum(split) ≤ stage_ms holds
    # here exactly as on bench.py's e2e lines
    phases = ("tables", "featurize", "dedup", "table-h2d")

    def marks():
        return {ph: METRICS.histo_sum(CAPTURE_STAGE_SECONDS,
                                      {"phase": ph})
                for ph in phases}

    def stage_once():
        mark0 = marks()
        t0 = time.perf_counter()
        replay = CaptureReplay(engine, l7_all, offsets, blob,
                               cfg.engine, gen=gen_all)
        replay.stage_rows(rec_all, l7_all)
        ratio = replay.stage_unique(
            drop_if_ratio_at_least=cfg.engine.stage_unique_drop_ratio)
        if replay.row_idx is not None:
            replay.stage_unique_device()
        stage_ms = round((time.perf_counter() - t0) * 1e3, 1)
        memo_fill_ms = None
        if replay.row_idx is not None and cfg.engine.verdict_memo:
            import numpy as np

            t1 = time.perf_counter()
            memo = replay.stage_verdict_memo()
            np.asarray(memo.table[:2])  # completion-forced
            memo_fill_ms = round((time.perf_counter() - t1) * 1e3, 1)
        split = {ph: round((after - mark0[ph]) * 1e3, 1)
                 for ph, after in marks().items()}
        return replay, stage_ms, split, ratio, memo_fill_ms

    replay, cold_ms, cold_split, ratio, cold_fill = stage_once()
    _, warm_ms, warm_split, _, warm_fill = stage_once()
    log(f"stage cold {cold_ms}ms {cold_split}; "
        f"warm {warm_ms}ms {warm_split}")

    lanes = [
        {"metric": f"stage_ms_{args.config}_{args.rules}rules",
         "value": cold_ms, "unit": "ms (cold session staging)",
         "vs_baseline": 0.0,
         "stage_ms": cold_ms, "stage_phases_ms": cold_split,
         "stage_warm_ms": warm_ms, "stage_warm_phases_ms": warm_split,
         "memo_fill_ms": cold_fill, "memo_fill_warm_ms": warm_fill,
         "capture_records": int(len(rec_all)),
         "unique_rows": int(replay.n_unique),
         "dedup_ratio": round(ratio, 6),
         "capture_write_ms": write_ms, "capture_open_ms": open_ms},
    ]
    rc = 0
    for lane in lanes:
        stamp(lane)
        print(json.dumps(lane), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
