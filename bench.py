#!/usr/bin/env python
"""Benchmark: L7 policy verdicts/sec on TPU.

Primary config (BASELINE.json configs[1]): 1k HTTP path/header regex
rules × 10k Hubble-replayed HTTP flows; the engine computes the full
L3/L4 + L7 verdict per flow. Baseline target: 10M verdicts/sec/chip
(`BASELINE.json ·north_star`); ``vs_baseline`` = value / 10e6.

Timing methodology (docs/PLATFORM.md "measurement integrity", round
5): ``jax.block_until_ready`` is NOT a reliable completion barrier on
the tunneled platform — block-only loops can report the DISPATCH
rate. Every timed region therefore ends in a forced 2-element verdict
readback (``_force``), windows are sized ≥ ~15× the tunnel RTT by
cycling staged batches, staging H2D is drained before sampling, and
every line carries a tunnel-RTT marker plus min/max across windows.
Batches are staged from host numpy; full verdict values and oracle
checks still read back only after the last timer stops.

Prints exactly ONE JSON line per config (the BASELINE metric is
throughput AND latency, so the line carries both):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "p50_ms": N, "p99_ms": N}

``--config all`` runs every BASELINE config and prints one line each
(the default single-config invocation still prints exactly one line).

Resilience (VERDICT r2 item 1): the axon tunnel fails transiently
(backend init UNAVAILABLE, wedged relays — docs/PLATFORM.md), and a
poisoned or half-initialized process must never time anything. The
outer process therefore never imports jax: per config it (a) probes the
backend in a throwaway subprocess with a hard timeout, (b) runs the
actual benchmark in a fresh ``--inner`` subprocess, and (c) retries
both on backend failure (exit code 42 / probe timeout) with bounded
backoff. On final failure it emits ONE parseable JSON line
(``bench_failed_backend``) instead of a traceback, so the driver's
capture always parses. Knobs via env for tests:
CILIUM_TPU_BENCH_RETRIES (5), CILIUM_TPU_BENCH_BACKOFF (30s),
CILIUM_TPU_BENCH_PROBE_TIMEOUT (180s), CILIUM_TPU_BENCH_TIMEOUT
(3600s), CILIUM_TPU_BENCH_FAIL_FILE (failure injection: file holding a
count of backend failures to simulate).

Usage: python bench.py [--rules 1000] [--flows 10000] [--iters 20]
       [--config http|fqdn|kafka|mixed|clustermesh|all] [--check]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

#: exit code an --inner / --probe subprocess uses to report "the
#: backend failed to initialize" (distinct from bench logic failures)
_BACKEND_FAIL_RC = 42


def _inject_backend_failure() -> bool:
    """Test hook: CILIUM_TPU_BENCH_FAIL_FILE names a file holding an
    integer count of backend-init failures to simulate. Each probe or
    inner run decrements it; while positive, the process behaves
    exactly like a tunnel UNAVAILABLE (exit 42 before touching jax)."""
    path = os.environ.get("CILIUM_TPU_BENCH_FAIL_FILE")
    if not path or not os.path.exists(path):
        return False
    try:
        with open(path) as f:
            n = int(f.read().strip() or 0)
    except ValueError:
        return False
    if n <= 0:
        return False
    with open(path, "w") as f:
        f.write(str(n - 1))
    print("injected backend failure (test hook)", file=sys.stderr)
    return True


def _inject_run_failure() -> None:
    """Test hook (lane-isolation retry): CILIUM_TPU_BENCH_RUN_FAIL_FILE
    names a file holding a count of TRANSIENT run failures to simulate
    AFTER backend init — the r05 kafka ``remote_compile`` connection
    reset regime, distinct from the exit-42 backend-init hook."""
    path = os.environ.get("CILIUM_TPU_BENCH_RUN_FAIL_FILE")
    if not path or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            n = int(f.read().strip() or 0)
    except ValueError:
        return
    if n <= 0:
        return
    with open(path, "w") as f:
        f.write(str(n - 1))
    raise ConnectionResetError(
        "injected transient run failure (test hook): remote_compile: "
        "read body: connection reset")


def _init_backend() -> None:
    """Import jax and touch the backend; exit 42 on any failure so the
    outer retry loop can tell 'backend unavailable' from a bench bug."""
    if _inject_backend_failure():
        sys.exit(_BACKEND_FAIL_RC)
    try:
        import jax

        # honor JAX_PLATFORMS even when a plugin site (axon) is on the
        # path: the env var alone does not always win over a registered
        # PJRT plugin in a fresh process — the config update does
        if os.environ.get("JAX_PLATFORMS"):
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        # persistent XLA compilation cache: every --inner run is a
        # fresh process, and a TPU compile through the tunnel costs
        # 10-20s — five table-scan shapes alone put ~80s into
        # stage_ms before this (BENCH_ALL_r04 first run). With the
        # cache, repeat shapes load in milliseconds across processes.
        from cilium_tpu.runtime.xla_cache import enable_persistent_cache

        enable_persistent_cache()
        jax.devices()
    except Exception as e:  # noqa: BLE001 — any init error means retry
        print(f"backend init failed: {e}", file=sys.stderr)
        sys.exit(_BACKEND_FAIL_RC)


def _probe() -> int:
    """Throwaway-process backend probe (PLATFORM.md checklist #6): init
    the backend and run+read back one tiny computation. A wedged tunnel
    hangs here — the outer applies a hard timeout and kills us."""
    _init_backend()
    import jax.numpy as jnp
    import numpy as np

    got = np.asarray(jnp.arange(8) + 1)
    if got.tolist() != list(range(1, 9)):
        print(f"probe readback corrupt: {got.tolist()}", file=sys.stderr)
        return _BACKEND_FAIL_RC
    print("probe-ok", flush=True)
    return 0

#: per-config BASELINE flow/tuple shapes (generic is the proxylib
#: l7proto lane — not a BASELINE config, shaped like kafka)
_DEFAULT_FLOWS = {"http": 10000, "fqdn": 10000, "kafka": 100000,
                  "mixed": 1000000, "clustermesh": 100000,
                  "generic": 100000}
#: per-config BASELINE rule counts (configs[0] is "100 DNS names x 10
#: regex rules"; http is the 1k-rule north-star shape)
_DEFAULT_RULES = {"http": 1000, "fqdn": 10, "kafka": 1000,
                  "mixed": 0, "clustermesh": 0, "generic": 200}


def _uniquify_flows(flows):
    """Clone flows so every record carries a UNIQUE string (query-
    suffixed path / instance-suffixed kafka client / qname-left
    label / extra generic pair), defeating both the row dedup and the
    string-table dedup — the high-cardinality capture regime.

    Family caveat (visible in the line's ``unique_rows``): only
    byte-SCANNED fields (http path/host/headers, dns qname) can make
    rows genuinely unique. Kafka strings and generic (key, value)
    pairs intern against the POLICY's vocabulary at featurize time —
    every rule-irrelevant unique value maps to the same "unknown"
    id, so their uniqueness collapses before the device and the
    dedup ratio stays tiny BY CONSTRUCTION (matching semantics, not
    a benchmarking shortcut). The http config is therefore the
    honest ratio≈1 lane.

    Mix caveat: path regexes are FULL-match, so flows matched by an
    exact-path rule (no trailing wildcard) flip to deny under the
    suffix — ~25% of verdicts at synth shapes (pinned non-degenerate
    by tests/test_bench_helpers.py). The workload is therefore
    *different traffic*, but the step's cost is verdict-independent
    (every lane computes regardless of outcome), so the throughput
    comparison against the dedup line stands; the --check oracle
    differential runs on the same modified flows either way."""
    import dataclasses

    for i, f in enumerate(flows):
        if f.http is not None:
            f = dataclasses.replace(
                f, http=dataclasses.replace(
                    f.http, path=f"{f.http.path}?u={i}"))
        elif f.kafka is not None:
            f = dataclasses.replace(
                f, kafka=dataclasses.replace(
                    f.kafka, client_id=f"{f.kafka.client_id}-u{i}"))
        elif f.dns is not None and f.dns.query:
            f = dataclasses.replace(
                f, dns=dataclasses.replace(
                    f.dns, query=f"u{i}.{f.dns.query}"))
        elif f.generic is not None:
            # an extra field pair is invisible to l7 dict matching
            # (rules match on their OWN keys) but unique per record
            f = dataclasses.replace(
                f, generic=dataclasses.replace(
                    f.generic,
                    fields={**f.generic.fields, "u": str(i)}))
        yield f


def _force(out):
    """Force REMOTE COMPLETION of a dispatched verdict step with a
    2-element readback — THE load-bearing measurement primitive of
    the round-5 protocol (docs/PLATFORM.md "measurement integrity"):
    ``jax.block_until_ready`` is not a reliable completion barrier on
    the tunneled platform, so every timed region must end here. The
    in-order execution queue means forcing the LAST output implies
    everything before it finished."""
    import numpy as np

    np.asarray(out["verdict"][:2])


def _tunnel_rtt_probe(n: int = 7):
    """(p50_ms, p99_ms) of a tiny H2D+readback round-trip — the
    tunnel-health marker every official line carries (VERDICT r4 item
    4: a 4× run-to-run spread is unfalsifiable without it)."""
    import jax
    import numpy as np

    xs = np.zeros(16, dtype=np.int32)
    np.asarray(jax.device_put(xs))  # connection warm
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(jax.device_put(xs))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return (round(ts[len(ts) // 2] * 1e3, 3),
            round(ts[-1] * 1e3, 3))


def _bench_from_capture(args, cfg, engine, scenario, arrays, log):
    """The north-star lane: file→verdict END-TO-END over a stored
    v2/v3 Hubble capture (binary base records + L7 sidecar + generic
    section). Session STAGING — string tables DFA-scanned on device,
    the whole file featurized into one row block — is paid once per
    file and reported as stage_ms; every timed sample then covers
    row-slice → device_put → verdict step → FORCED COMPLETION
    (``_force``), and throughput windows dispatch the whole file
    sequentially R× (H2D of chunk i+1 overlaps device compute of
    chunk i) with one forced readback at the end (round-5 protocol,
    docs/PLATFORM.md "measurement integrity")."""
    import jax
    import numpy as np

    from cilium_tpu.engine.verdict import CaptureReplay
    from cilium_tpu.ingest import binary

    cap = args.from_capture
    if not os.path.exists(cap):
        flows = scenario.flows
        reps = -(-args.capture_flows // len(flows))
        flows_out = (flows * reps)[:args.capture_flows]
        if getattr(args, "capture_cardinality", "low") == "high":
            # VERDICT r4 item 2: the dedup id stream rides ~1%
            # cardinality, a synthetic-capture property. This lane
            # makes EVERY record's 15-tuple unique (a per-record path
            # suffix the policy's /prefix/.* rules still match), so
            # stage_unique declines and the windows stream full rows —
            # the honest ratio≈1 regime
            flows_out = list(_uniquify_flows(flows_out))
        n = binary.write_capture_l7(cap, flows_out)
        log(f"wrote v{binary.capture_version(cap)} capture {cap}: "
            f"{n} records")
    rec_all = binary.map_capture(cap)
    l7_all, offsets, blob = binary.read_l7_sidecar(cap)
    gen_all = binary.read_gen_sidecar(cap)  # None below v3
    # replay session staging, paid once per file and reported as
    # stage_ms: per-field string tables DFA-scanned ONCE on device
    # (the pkg/fqdn/re regex-LRU analog, batch-computed) and the
    # whole capture featurized into one [N, 15(+gen)] int32 row block
    # — each timed chunk then costs a contiguous slice + device_put
    # (per-chunk featurize would cap e2e at ~19M rows/s host-side,
    # under the device's rate)
    from cilium_tpu.runtime.metrics import CAPTURE_STAGE_SECONDS, METRICS

    def _stage_marks():
        return {ph: METRICS.histo_sum(CAPTURE_STAGE_SECONDS,
                                      {"phase": ph})
                for ph in ("tables", "featurize", "dedup",
                           "table-h2d")}

    stage_mark0 = _stage_marks()
    t_stage0 = time.perf_counter()
    replay = CaptureReplay(engine, l7_all, offsets, blob, cfg.engine,
                           gen=gen_all)
    rows_all = replay.stage_rows(rec_all, l7_all)
    # dedup stream (CaptureReplay.stage_unique): over the tunneled
    # TPU the 60B/row H2D stream caps e2e at ~3M rows/s (BENCH_r04
    # first capture) — per-flow row ids into a device-resident
    # unique-row table cut that to 2-4B/row. Fall back to plain row
    # streaming when the capture doesn't repeat enough to pay for
    # the gather indirection (Config.engine.stage_unique_drop_ratio).
    dedup_ratio = replay.stage_unique(
        drop_if_ratio_at_least=cfg.engine.stage_unique_drop_ratio)
    use_dedup = replay.row_idx is not None
    if use_dedup:
        replay.stage_unique_device()  # inside stage timing, honestly
    stage_s = time.perf_counter() - t_stage0
    # the stage_ms phase split (perf ledger): per-phase deltas of the
    # CaptureReplay staging spans — the 12.5s stage_ms, decomposed
    stage_phases_ms = {
        ph: round((after - stage_mark0[ph]) * 1e3, 1)
        for ph, after in _stage_marks().items()}
    log(f"session staging (tables + featurize + dedup): "
        f"{stage_s * 1e3:.1f}ms; split {stage_phases_ms}; unique rows "
        f"{replay.n_unique}/{len(rows_all)} "
        f"({dedup_ratio:.3f}) → {'id' if use_dedup else 'row'} stream")
    # device verdict memo (engine/memo.py): every unique row verdicted
    # ONCE, windows then gather memoized outputs by id — the ≥99%-
    # duplicate replay regime stops re-deriving verdicts. OUTSIDE
    # stage_ms by methodology: the fill is the compile/warm analog
    # (the non-memo lane's step compile is also untimed), and it is
    # reported separately as memo_fill_ms for honesty.
    memo = None
    memo_fill_ms = None
    if use_dedup and cfg.engine.verdict_memo:
        t_memo0 = time.perf_counter()
        memo = replay.stage_verdict_memo()
        np.asarray(memo.table[:2])  # completion-forced
        memo_fill_ms = round((time.perf_counter() - t_memo0) * 1e3, 1)
        log(f"verdict memo: {memo.filled} unique rows filled in "
            f"{memo_fill_ms}ms")
    bs = min(len(rec_all),
             getattr(args, "replay_chunk", None)
             or (args.flows if args.flows is not None
                 else _DEFAULT_FLOWS[args.config]))
    nch = len(rec_all) // bs

    if memo is not None:
        row_idx = replay.row_idx

        def encode_chunk(c):
            return jax.device_put(row_idx[c * bs:(c + 1) * bs])

        def step(arrays_, idx_dev):  # memoized replay: one gather
            return memo.gather(idx_dev)
    elif use_dedup:
        row_idx = replay.row_idx

        def encode_chunk(c):
            return {"rows": replay.unique_rows,
                    "idx": jax.device_put(row_idx[c * bs:(c + 1) * bs])}

        def step(arrays_, batch):  # the capture-specialized step
            return replay._step(arrays_, replay.table_words, batch)
    else:
        def encode_chunk(c):
            return {"rows": jax.device_put(rows_all[c * bs:(c + 1) * bs])}

        def step(arrays_, batch):  # the capture-specialized step
            return replay._step(arrays_, replay.table_words, batch)

    _force(step(arrays, encode_chunk(0)))  # compile/warm + drain

    # per-chunk completion latency: dispatch → verdicts READ BACK
    # (includes one tunnel RTT — the rtt marker on the line bounds
    # it); sustained per-chunk time derives from the windows below
    n_lat = 200  # p99 must be a real quantile, not a max-of-few
    lat = []
    for i in range(n_lat):
        t0 = time.perf_counter()
        out = step(arrays, encode_chunk(i % nch))
        _force(out)
        lat.append(time.perf_counter() - t0)
    lat.sort()

    # e2e throughput: sequential replay, completion-forced windows.
    # The file is replayed R× per window so the end-of-window RTT and
    # any dispatch pipelining are <~7% of the window (calibrated from
    # a probe pass). Median of 5; min/max ride the line so a cross-
    # run spread is attributable (VERDICT r4 item 4).
    t0 = time.perf_counter()
    out = None
    for c in range(nch):
        out = step(arrays, encode_chunk(c))
    _force(out)
    t_probe = time.perf_counter() - t0
    reps = max(1, int(1.5 / max(t_probe, 1e-3)))
    window_times = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(reps):
            for c in range(nch):
                out = step(arrays, encode_chunk(c))
        _force(out)
        window_times.append(time.perf_counter() - t0)
    t = sorted(window_times)[len(window_times) // 2]
    e2e_vps = reps * nch * bs / t

    # provenance-lane overhead (ISSUE 14): identical windows, but the
    # window-end consumption also materializes the provenance
    # surfaces — the attribution lane readback, cited generations off
    # the memo's host bookkeeping, and a sample of packed provenance
    # words. The attribution lane itself is computed by the fused
    # step EITHER WAY (it is an output lane, not a second dispatch),
    # so this measures exactly the marginal consumption cost the
    # perf-report gate holds ≤2%. Windows run as INTERLEAVED A/B
    # pairs with the arm ORDER alternating per pair — a fixed
    # base-then-prov order reads ~2% of pure cache/frequency drift
    # as "overhead" on the CI host (measured); alternation cancels
    # it, leaving the real marginal cost.
    from cilium_tpu.engine.attribution import pack_word

    def _consume_provenance(out_, c):
        l7m = np.asarray(out_["l7_match"])
        if memo is not None:
            gens = memo.cited_gens(
                row_idx[c * bs:(c + 1) * bs][:len(l7m)])
        else:
            gens = np.zeros(min(8, len(l7m)), dtype=np.int64)
        for k in range(min(8, len(l7m))):
            pack_word(int(l7m[k]), 1, memo is not None,
                      int(gens[k]) if k < len(gens) else 0)

    def _window(consume: bool) -> float:
        t0 = time.perf_counter()
        last_c = 0
        w_out = None
        for _ in range(reps):
            for c in range(nch):
                w_out = step(arrays, encode_chunk(c))
                last_c = c
        _force(w_out)
        if consume:
            _consume_provenance(w_out, last_c)
        return time.perf_counter() - t0

    base_times, prov_times = [], []
    for pair in range(6):
        first_prov = bool(pair % 2)
        a = _window(consume=first_prov)
        b = _window(consume=not first_prov)
        (prov_times if first_prov else base_times).append(a)
        (base_times if first_prov else prov_times).append(b)
    t_base = sorted(base_times)[len(base_times) // 2]
    t_prov = sorted(prov_times)[len(prov_times) // 2]
    provenance_overhead_pct = round(
        max(0.0, (t_prov - t_base) / t_base) * 100, 3)
    rtt_p50, rtt_max = _tunnel_rtt_probe()
    # per-chunk device-time attribution (perf ledger): h2d / gather /
    # mapstate / resolve decomposition of one replay chunk, with the
    # compile-vs-execute split — the coverage contract the artifact
    # carries (attributed ≥ ~90% of the measured chunk wall)
    from cilium_tpu.engine.phases import CapturePhaseProbe

    attribution = CapturePhaseProbe(replay).measure(0, bs, reps=5)
    log(f"e2e capture replay: {len(rec_all)} records (chunk={bs}), "
        f"{e2e_vps:,.0f} verdicts/s file→device, "
        f"p50={lat[len(lat) // 2] * 1e3:.2f}ms "
        f"p99={lat[int(len(lat) * 0.99)] * 1e3:.2f}ms per chunk; "
        f"tunnel rtt {rtt_p50:.0f}ms")
    return {
        "e2e_verdicts_per_sec": round(e2e_vps, 1),
        "e2e_vps_min": round(reps * nch * bs / max(window_times), 1),
        "e2e_vps_max": round(reps * nch * bs / min(window_times), 1),
        "e2e_windows": len(window_times),
        "e2e_window_reps": reps,
        "timing": "completion-forced (readback at window end)",
        "tunnel_rtt_ms": rtt_p50,
        "tunnel_rtt_max_ms": rtt_max,
        "cardinality": getattr(args, "capture_cardinality", "low"),
        "e2e_p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
        "e2e_p99_ms": round(lat[min(len(lat) - 1,
                                    int(len(lat) * 0.99))] * 1e3, 3),
        "capture_records": int(len(rec_all)),
        # once-per-file session staging (string-table scans + whole-
        # file featurize + row dedup) — on the line for honesty,
        # outside the timed region by methodology
        "stage_ms": round(stage_s * 1e3, 1),
        # the perf-ledger split of that stage_ms, by phase
        "stage_phases_ms": stage_phases_ms,
        # per-chunk phase attribution + compile/execute split
        "attribution": attribution,
        # marginal cost of consuming the provenance surfaces (lane
        # readback + cited gens + packed words) vs verdict-only
        # windows; perf-report gates it against the declared budget
        "provenance_overhead_pct": provenance_overhead_pct,
        "provenance_budget_pct": 2.0,
        # dedup stream accounting, so the ratio behind the e2e rate
        # is visible: unique 15-tuples / total records, and which
        # stream the windows used ("id+memo" = row ids gathering
        # device-memoized verdicts; "id" = ids through the full step;
        # "row" = full 60B/flow rows)
        "unique_rows": int(replay.n_unique),
        "stream": ("id+memo" if memo is not None
                   else "id" if use_dedup else "row"),
        "chunk": int(bs),
        # verdict-memo accounting: fill wall (once per policy
        # revision, outside stage_ms — the compile/warm analog) and
        # the session's lifetime hit/miss counters
        "memo": memo is not None,
        **({"memo_fill_ms": memo_fill_ms,
            "memo_hits": int(memo.hits),
            "memo_misses": int(memo.misses)} if memo is not None
           else {}),
    }


def _bench_kafka_frames(args, cfg, engine, scenario, arrays, step, log):
    """VERDICT r4 item 7: config[2] says "100k produce/fetch records"
    — the headline kafka rate is the ACL-match rate over ALREADY-
    PARSED records (the regime the engine serves: proxylib parses on
    the wire path). This sub-lane runs the comparable full pipeline —
    wire frames → proxylib/kafka.py parse → featurize → device verdict
    — so both rates sit on the artifact line."""
    import jax
    import numpy as np

    from cilium_tpu.engine.verdict import (
        encode_flows,
        flowbatch_to_host_dict,
    )
    from cilium_tpu.proxylib.kafka import (
        API_FETCH,
        API_METADATA,
        API_PRODUCE,
        encode_request,
        parse_request_records,
    )

    flows = [f for f in scenario.flows
             if f.kafka is not None
             and f.kafka.api_key in (API_PRODUCE, API_FETCH,
                                     API_METADATA)]
    if not flows:
        return {}
    # wire frames for the records (the synthetic encoder emits the
    # classic v0/v1 layouts; version pinned accordingly so the walk
    # parses the layout that was actually encoded)
    frames = [encode_request(
        f.kafka.api_key, 0 if f.kafka.api_key == API_METADATA else 1,
        i & 0x7FFFFFFF, f.kafka.client_id, f.kafka.topic)
        for i, f in enumerate(flows)]
    # compile the batch shape outside the windows
    fb = encode_flows(flows, engine.policy.kafka_interns, cfg.engine)
    batch = {k: jax.device_put(v)
             for k, v in flowbatch_to_host_dict(fb).items()}
    _force(step(arrays, batch))  # compile + drain

    windows, parse_s = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        # the walker takes the frame BODY (the 4-byte size prefix is
        # the shim's framing layer, stripped before parse everywhere)
        infos = [parse_request_records(fr[4:])[0] for fr in frames]
        t1 = time.perf_counter()
        for f, info in zip(flows, infos):
            f.kafka = info
        fb = encode_flows(flows, engine.policy.kafka_interns,
                          cfg.engine)
        batch = {k: jax.device_put(v)
                 for k, v in flowbatch_to_host_dict(fb).items()}
        out = step(arrays, batch)
        _force(out)  # force completion
        windows.append(time.perf_counter() - t0)
        parse_s.append(t1 - t0)
    n = len(flows)
    t = sorted(windows)[len(windows) // 2]
    tp = sorted(parse_s)[len(parse_s) // 2]
    log(f"kafka frames→verdict: {n} wire frames, parse "
        f"{n / tp:,.0f}/s, full pipeline {n / t:,.0f}/s "
        f"(headline = ACL match rate, parse excluded)")
    return {
        "frames_to_verdict_per_sec": round(n / t, 1),
        "frames_parse_per_sec": round(n / tp, 1),
        "frames": n,
        "headline_note": "ACL match rate, parse excluded",
    }


def _bench_regen(args, log) -> dict:
    """Regeneration latency (VERDICT r2 item 5; reference:
    ``cilium_policy_regeneration_time_stats_seconds`` + the distillery
    benches): time-to-staged-revision for (a) a COLD 1k-rule compile,
    (b) INCREMENTAL regenerations after ±1 rule (warm BankCache:
    only banks whose pattern membership changed recompile), and (c) a
    warm-restart restage from the on-disk artifact cache. The disk
    cache is disabled for (a)/(b) so compiles are timed, not disk
    hits."""
    import tempfile

    from cilium_tpu.core.config import Config
    from cilium_tpu.ingest import synth
    from cilium_tpu.runtime.loader import Loader

    n_rules = args.rules if args.rules is not None else 1000

    def build(n):
        per_identity, _ = synth.realize_scenario(
            synth.synth_http_scenario(n_rules=n, n_flows=8))
        return per_identity

    base = build(n_rules)
    plus = build(n_rules + 1)   # one rule appended at the end

    cfg = Config.from_env()
    cfg.enable_tpu_offload = True
    cfg.loader.enable_cache = False
    loader = Loader(cfg)
    t0 = time.perf_counter()
    loader.regenerate(base, revision=1)
    cold_s = time.perf_counter() - t0
    log(f"cold compile+stage: {cold_s:.2f}s ({n_rules} rules)")

    iters = max(6, args.iters)
    h0, m0 = loader.bank_cache.hits, loader.bank_cache.misses
    # phase attribution (VERDICT r4 item 6): per-iteration deltas of
    # the loader's policy_compile / policy_stage spans say WHERE an
    # outlier iteration spent its time (remainder = resolve/
    # fingerprint/host assembly)
    from cilium_tpu.runtime.metrics import METRICS

    def _span_total(name):
        return METRICS.histo_sum("cilium_tpu_span_seconds",
                                 {"span": name})

    times, phases = [], []
    for i in range(iters):
        per = plus if i % 2 == 0 else base
        c0, s0 = _span_total("policy_compile"), _span_total("policy_stage")
        t0 = time.perf_counter()
        loader.regenerate(per, revision=2 + i)
        dt = time.perf_counter() - t0
        times.append(dt)
        phases.append((dt, _span_total("policy_compile") - c0,
                       _span_total("policy_stage") - s0))
    hits = loader.bank_cache.hits - h0
    misses = loader.bank_cache.misses - m0
    worst = max(phases, key=lambda p: p[0])
    worst_i = phases.index(worst)
    worst_phase = ("compile" if worst[1] >= max(worst[2],
                                                worst[0] - worst[1]
                                                - worst[2])
                   else "stage" if worst[2] >= worst[0] - worst[1]
                   - worst[2] else "host-assembly")
    times.sort()
    p50 = times[len(times) // 2]
    p99 = times[min(len(times) - 1, int(len(times) * 0.99))]
    log(f"incremental regen: p50={p50 * 1e3:.1f}ms p99={p99 * 1e3:.1f}ms "
        f"bank cache {hits}/{hits + misses} hits; worst iter #{worst_i} "
        f"{worst[0] * 1e3:.0f}ms = compile {worst[1] * 1e3:.0f}ms + "
        f"stage {worst[2] * 1e3:.0f}ms + other "
        f"{(worst[0] - worst[1] - worst[2]) * 1e3:.0f}ms → {worst_phase}")

    # warm-restart lane: a NEW loader (fresh process analog) restages
    # the identical ruleset from the content-addressed artifact cache
    cfg2 = Config.from_env()
    cfg2.enable_tpu_offload = True
    cfg2.loader.cache_dir = tempfile.mkdtemp(prefix="ct_regen_")
    l2 = Loader(cfg2)
    l2.regenerate(base, revision=1)          # populates the cache
    l3 = Loader(cfg2)
    t0 = time.perf_counter()
    l3.regenerate(base, revision=1)          # artifact hit + restage
    restage_s = time.perf_counter() - t0
    log(f"artifact-cache restage: {restage_s * 1e3:.1f}ms")

    return {
        "metric": f"policy_regen_latency_{n_rules}rules",
        "value": round(p50 * 1e3, 1),
        "unit": "ms to staged revision (incremental, warm bank cache)",
        "vs_baseline": 0.0,
        "incr_p50_ms": round(p50 * 1e3, 1),
        "incr_p99_ms": round(p99 * 1e3, 1),
        # the worst incremental iteration, decomposed (tail
        # attribution): which phase ate it, and whether it was the
        # first-seen-ruleset warmup (iter 0 compiles the +1 rule's
        # bank once; steady-state alternation then hits the cache)
        "incr_worst_iter": worst_i,
        "incr_worst_ms": round(worst[0] * 1e3, 1),
        "incr_worst_compile_ms": round(worst[1] * 1e3, 1),
        "incr_worst_stage_ms": round(worst[2] * 1e3, 1),
        "incr_worst_phase": worst_phase,
        "cold_ms": round(cold_s * 1e3, 1),
        "bank_cache_hit_rate": round(hits / max(1, hits + misses), 4),
        "artifact_restage_ms": round(restage_s * 1e3, 1),
    }


def run_config(config: str, args) -> dict:
    import jax
    import numpy as np

    from cilium_tpu.core.config import Config
    from cilium_tpu.engine.verdict import (
        encode_flows,
        flowbatch_to_host_dict,
    )
    from cilium_tpu.ingest import synth
    from cilium_tpu.runtime.loader import Loader
    from cilium_tpu.runtime.metrics import SpanStat

    def log(msg: str) -> None:
        if args.verbose:
            print(msg, file=sys.stderr)

    _inject_run_failure()  # lane-isolation test hook (transient regime)

    if config == "regen":
        return _bench_regen(args, log)

    n_flows = args.flows if args.flows is not None else _DEFAULT_FLOWS[config]
    n_rules = (args.rules if args.rules is not None
               else _DEFAULT_RULES[config])

    import contextlib

    @contextlib.contextmanager
    def maybe_trace():
        """jax.profiler trace of the timed passes (--profile). The
        finally preserves the partial trace when a timed pass raises
        (the runs one most wants to profile) instead of leaving a
        dangling profiler session."""
        if not args.profile:
            yield
            return
        jax.profiler.start_trace(args.profile)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            log(f"profiler trace written to {args.profile}")

    if config in ("http", "fqdn", "kafka", "generic"):
        # shared dispatch with `cilium-tpu capture synth` — one place
        # owns the BASELINE scenario shapes
        scenario = synth.scenario_by_name(config, n_rules, n_flows)
    elif config == "mixed":
        # BASELINE configs[3]: examples/policies corpus × synthetic tuples
        corpus = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "examples", "policies")
        scenario = synth.synth_mixed_scenario(corpus, n_tuples=n_flows)
    elif config == "clustermesh":
        # BASELINE configs[4]: 10k identities × 5k CNP, streaming
        scenario = synth.synth_clustermesh_scenario(
            n_identities=10000, n_policies=5000, n_flows=n_flows)
    streaming = config in ("mixed", "clustermesh")
    per_identity, scenario = synth.realize_scenario(scenario)

    cfg = Config.from_env()
    cfg.enable_tpu_offload = True
    loader = Loader(cfg)
    with SpanStat("bench_compile") as compile_span:
        engine = loader.regenerate(per_identity, revision=1)
    log(f"compile+stage: {compile_span.seconds:.1f}s "
        f"(cache dir {cfg.loader.cache_dir})")

    fb = encode_flows(scenario.flows, engine.policy.kafka_interns, cfg.engine)
    # the engine's STAGED step — the fused megakernel unless
    # CILIUM_TPU_KERNEL_IMPL=legacy, in which case jax.jit(verdict_step)
    # (engine/verdict.py): the device lane measures what serves
    step = engine._step
    arrays = engine._arrays

    host = flowbatch_to_host_dict(fb)
    if streaming:
        # configs[3]/[4] methodology: stream the whole tuple set once,
        # chunked at the engine batch size. Every timed call sees a
        # first-use buffer (no repeat → no caching layer can shortcut),
        # and all chunks are staged to HBM before the timer starts so
        # the timed region has zero H2D traffic and zero readbacks.
        bs = cfg.engine.batch_size
        n_total = fb.size
        n_chunks = n_total // bs
        if n_chunks < args.warmup + 4:  # compile + >=1 latency + >=2 tput
            return {"metric": "bench_failed_setup", "value": 0,
                    "unit": "too few chunks", "vs_baseline": 0.0}
        chunks = []
        for c in range(n_chunks):
            sl = slice(c * bs, (c + 1) * bs)
            chunks.append({k: jax.device_put(v[sl]) for k, v in host.items()})
        jax.block_until_ready(chunks)

        out = step(arrays, chunks[0])
        _force(out)  # compile + drain staging H2D
        for i in range(args.warmup):
            out = step(arrays, chunks[1 + i])
        _force(out)

        with maybe_trace():
            # latency pass: COMPLETION-FORCED per chunk (dispatch →
            # verdicts read back; includes one tunnel RTT — see
            # _force()'s contract: block_until_ready
            # is not a reliable completion barrier on this platform)
            n_lat = max(1, min(32, n_chunks - 1 - args.warmup - 2))
            times = []
            for c in range(1 + args.warmup, 1 + args.warmup + n_lat):
                t0 = time.perf_counter()
                out = step(arrays, chunks[c])
                _force(out)
                times.append(time.perf_counter() - t0)
            # throughput pass: dispatch the whole remaining stream,
            # force completion ONCE at the end (the in-order queue
            # means the last chunk's readback implies all finished)
            first = 1 + args.warmup + n_lat
            t0 = time.perf_counter()
            for c in range(first, n_chunks):
                out = step(arrays, chunks[c])
            _force(out)
            t_probe = time.perf_counter() - t0
            # cycle the stream so the window is ≥ ~15× the tunnel RTT
            # (repeat executions measured identical to first-use on
            # this platform — matmul control, PLATFORM.md round 5)
            reps = max(1, int(1.5 / max(t_probe, 1e-3)))
            t_stream0 = time.perf_counter()
            outs = []
            for _ in range(reps):
                outs = [step(arrays, chunks[c])
                        for c in range(first, n_chunks)]
            _force(outs[-1])
            t_stream = time.perf_counter() - t_stream0
        out = outs[-1]
        n_timed = (n_chunks - first) * bs * reps
        vps = n_timed / t_stream
        times.sort()
        p50_ms = times[len(times) // 2] * 1e3
        p99_ms = times[min(len(times) - 1, int(len(times) * 0.99))] * 1e3
        log(f"streamed {n_timed} of {n_total} flows in {t_stream:.3f}s "
            f"(chunk={bs}, per-chunk completion p50={p50_ms:.2f}ms, "
            f"p99={p99_ms:.2f}ms incl. tunnel RTT) "
            f"verdicts/s={vps:,.0f}")
    else:
        # Distinct, differently-permuted device copies per call — warmup
        # and timed — so no caching layer (compiler CSE, platform replay)
        # can shortcut repeat executions. Built from HOST numpy: a device
        # round trip here would poison the process (docs/PLATFORM.md).
        prng = np.random.default_rng(0)
        # compile + warmup copies; latency and throughput passes stage
        # their own copies one WINDOW at a time (≤ iters extra copies
        # resident) so raising the sample count cannot balloon HBM.
        # ALL copies are distinct permutations so every timed call is
        # first-use.
        n_lat = max(args.lat_iters, args.iters)
        batches = []
        for _ in range(args.warmup + 1):
            perm = prng.permutation(fb.size)
            batches.append({k: jax.device_put(v[perm])
                            for k, v in host.items()})
        jax.block_until_ready(batches)

        out = step(arrays, batches[0])
        jax.block_until_ready(out)  # compile
        for i in range(args.warmup):
            out = step(arrays, batches[1 + i])
        jax.block_until_ready(out)
        del batches

        with maybe_trace():
            # latency pass: block per call (per-batch latency; enough
            # samples that p99 is a quantile, not the sample max),
            # staged in windows of `iters` distinct copies
            times = []
            while len(times) < n_lat:
                wb = []
                for _ in range(min(args.iters, n_lat - len(times))):
                    perm = prng.permutation(fb.size)
                    wb.append({k: jax.device_put(v[perm])
                               for k, v in host.items()})
                jax.block_until_ready(wb)
                # drain: the H2D staging above may still be in flight
                # (block_until_ready is unreliable, see _force());
                # without this the first sample absorbs the backlog
                _force(step(arrays, wb[0]))
                for batch in wb:
                    t0 = time.perf_counter()
                    out = step(arrays, batch)
                    # completion-forced (round-5 measurement-integrity
                    # finding): the sample includes one tunnel RTT;
                    # sustained per-batch time = window_time / iters
                    _force(out)
                    times.append(time.perf_counter() - t0)
            times.sort()
            med = times[len(times) // 2]
            n = len(scenario.flows)
            # throughput pass: per window, stage `iters` distinct
            # permuted buffers untimed, then dispatch them reps×
            # (cycling — repeats measured identical to first-use, see
            # the matmul control) with ONE forced completion at the
            # end — compute overlaps dispatch, as a real replay
            # pipeline runs. Median of 5 windows: the
            # tunneled transport's run-to-run jitter is ±30% on
            # identical binaries, so a single window reports tunnel
            # luck; the median is the defensible sustained figure (the
            # streaming configs are single-window by construction —
            # one first-use pass over the whole tuple set).
            window_times = []
            reps = 1
            for w in range(5):
                wb = []
                for _ in range(args.iters):
                    perm = prng.permutation(fb.size)
                    wb.append({k: jax.device_put(v[perm])
                               for k, v in host.items()})
                jax.block_until_ready(wb)
                # drain staging (see the latency pass) so the timed
                # region never absorbs in-flight H2D
                _force(step(arrays, wb[0]))
                if w == 0:
                    # calibration: size every window ≥ ~15× the RTT by
                    # cycling the staged batches (repeats measured
                    # identical to first-use — matmul control)
                    t0 = time.perf_counter()
                    outs = [step(arrays, b) for b in wb]
                    _force(outs[-1])
                    t_probe = time.perf_counter() - t0
                    reps = max(1, int(1.5 / max(t_probe, 1e-3)))
                t0 = time.perf_counter()
                for _ in range(reps):
                    outs = [step(arrays, b) for b in wb]
                _force(outs[-1])  # force completion
                window_times.append(time.perf_counter() - t0)
            t_all = sorted(window_times)[len(window_times) // 2]
        out = outs[-1]
        vps = n * args.iters * reps / t_all
        p50_ms = med * 1e3
        p99_ms = times[min(len(times) - 1, int(len(times) * 0.99))] * 1e3
        log(f"batch={n} completion latency: median={p50_ms:.2f}ms "
            f"p99={p99_ms:.2f}ms (incl. tunnel RTT); "
            f"pipelined verdicts/s={vps:,.0f}")

    # e2e capture-replay lane (completion-forced like every lane;
    # runs before the full post-timing readbacks below). Default
    # ON for the http config — the north star is "replaying a Hubble
    # capture", so the official line must carry the e2e rate.
    e2e = None
    cap = getattr(args, "from_capture", None)
    cap_is_auto = cap == "auto"
    if cap_is_auto:
        # every config except regen is capture-capable as of round 5
        # per-user dir (no cross-user /tmp collisions or symlink
        # planting); key carries every shape knob so a stale file
        # from a different scenario can't be silently reused
        d = os.path.join(tempfile.gettempdir(),
                         f"ct_bench_{os.getuid()}")
        os.makedirs(d, exist_ok=True)
        card = getattr(args, "capture_cardinality", "low")
        # mixed's flows derive from the examples/policies corpus, not
        # (n_rules, n_flows) alone — fingerprint the corpus contents
        # into the key or a corpus edit silently reuses stale traffic
        corpus_tag = ""
        if config == "mixed":
            import hashlib

            h = hashlib.sha256()
            for root, _, files in sorted(os.walk(corpus)):
                for name in sorted(files):
                    p = os.path.join(root, name)
                    h.update(name.encode())
                    with open(p, "rb") as fh:
                        h.update(fh.read())
            corpus_tag = f"_c{h.hexdigest()[:8]}"
        cap = os.path.join(
            d, f"cap_{config}_{n_rules}r_{n_flows}b_"
               f"{args.capture_flows}f{corpus_tag}"
               f"{'_hicard' if card == 'high' else ''}_v2.bin")
    elif cap in (None, "", "none"):
        cap = None
    if cap is not None:
        args.from_capture = cap
        try:
            e2e = _bench_from_capture(args, cfg, engine, scenario,
                                      arrays, log)
        except Exception:
            # ONLY an auto-managed cache file may be rewritten — a
            # user-supplied capture is their data, and the error is
            # theirs to see
            if cap_is_auto and os.path.exists(cap):
                os.unlink(cap)
                e2e = _bench_from_capture(args, cfg, engine, scenario,
                                          arrays, log)
            else:
                raise

    # kafka frames→verdict sub-lane (wire parse INCLUDED) — still no
    # readbacks; rides before the post-timing section like e2e
    kafka_frames = {}
    if config == "kafka":
        kafka_frames = _bench_kafka_frames(args, cfg, engine, scenario,
                                           arrays, step, log)

    # ---- timing is over; readbacks are safe now -----------------------
    log(f"verdict mix: "
        f"{np.bincount(np.asarray(out['verdict']), minlength=6).tolist()}")

    # live-path device-time attribution (perf ledger): one probe pass
    # over a single batch — h2d / mapstate / dfa-scan / resolve plus
    # the compile-vs-execute split. Runs after the timed windows (its
    # forced readbacks are safe here); the capture lane carries its own
    # capture-path attribution instead
    attribution = None
    if e2e is None:
        from cilium_tpu.engine.phases import EnginePhaseProbe

        n_probe = min(fb.size, 4096)
        probe_host = {k: v[:n_probe] for k, v in host.items()}
        attribution = EnginePhaseProbe(engine).measure(probe_host,
                                                       reps=5)
        log(f"phase attribution: {attribution['phases_ms']} "
            f"coverage={attribution['coverage']}")

    if args.check:
        from cilium_tpu.policy.oracle import OracleVerdictEngine

        sample = scenario.flows[:500]
        want = OracleVerdictEngine(per_identity).verdict_flows(sample)["verdict"]
        got = engine.verdict_flows(sample)["verdict"]
        bad = int((got != want).sum())
        if bad:
            return {"metric": "bench_failed_check",
                    "value": bad, "unit": "mismatches",
                    "vs_baseline": 0.0}
        log("oracle check: OK")

    # http/fqdn/kafka wrap their N sub-rules in one Rule — n_rules is
    # the meaningful count there; mixed/clustermesh have real rule lists
    if streaming:
        n_rules = len(scenario.rules)
    if e2e is not None:
        # the north-star line: value = file→verdict e2e rate; the
        # device-only rate rides alongside for comparison
        return {
            "metric": f"e2e_capture_replay_{config}_{n_rules}rules",
            "value": e2e["e2e_verdicts_per_sec"],
            "unit": "verdicts/s",
            "vs_baseline": round(e2e["e2e_verdicts_per_sec"] / 10e6, 4),
            "p50_ms": e2e["e2e_p50_ms"],
            "p99_ms": e2e["e2e_p99_ms"],
            "device_verdicts_per_sec": round(vps, 1),
            "device_p50_ms": round(p50_ms, 3),
            "device_p99_ms": round(p99_ms, 3),
            "capture_records": e2e["capture_records"],
            "stage_ms": e2e["stage_ms"],
            "stage_phases_ms": e2e["stage_phases_ms"],
            "attribution": e2e["attribution"],
            "compile_ms": round(compile_span.seconds * 1e3, 1),
            "unique_rows": e2e["unique_rows"],
            "stream": e2e["stream"],
            "chunk": e2e["chunk"],
            "memo": e2e["memo"],
            **({k: e2e[k] for k in ("memo_fill_ms", "memo_hits",
                                    "memo_misses") if k in e2e}),
            "provenance_overhead_pct": e2e["provenance_overhead_pct"],
            "provenance_budget_pct": e2e["provenance_budget_pct"],
            "e2e_vps_min": e2e["e2e_vps_min"],
            "e2e_vps_max": e2e["e2e_vps_max"],
            "e2e_windows": e2e["e2e_windows"],
            "tunnel_rtt_ms": e2e["tunnel_rtt_ms"],
            "tunnel_rtt_max_ms": e2e["tunnel_rtt_max_ms"],
            "cardinality": e2e["cardinality"],
        }
    return {
        "metric": f"l7_verdicts_per_sec_{config}_{n_rules}rules",
        "value": round(vps, 1),
        "unit": ("verdicts/s (ACL match, parse excluded)"
                 if config == "kafka" else "verdicts/s"),
        "vs_baseline": round(vps / 10e6, 4),
        # the BASELINE metric's second half: per-batch verdict latency
        "p50_ms": round(p50_ms, 3),
        "p99_ms": round(p99_ms, 3),
        "compile_ms": round(compile_span.seconds * 1e3, 1),
        **({"attribution": attribution} if attribution else {}),
        **kafka_frames,
    }


def _inner_cmd(config: str, args) -> list:
    cmd = [sys.executable, os.path.abspath(__file__), "--inner",
           "--config", config,
           "--iters", str(args.iters),
           "--lat-iters", str(args.lat_iters),
           "--warmup", str(args.warmup)]
    if args.rules is not None:
        cmd += ["--rules", str(args.rules)]
    if args.flows is not None:
        cmd += ["--flows", str(args.flows)]
    if args.check:
        cmd.append("--check")
    if getattr(args, "from_capture", None) and config != "regen":
        cmd += ["--from-capture", args.from_capture,
                "--capture-flows", str(args.capture_flows),
                "--replay-chunk", str(args.replay_chunk),
                "--capture-cardinality",
                getattr(args, "capture_cardinality", "low")]
    if args.verbose:
        cmd.append("--verbose")
    if args.profile:
        prof = args.profile
        if args.config == "all":
            prof = os.path.join(prof, config)
        cmd += ["--profile", prof]
    return cmd


import re as _re

#: transient-infrastructure error smells in a bench_failed_run line —
#: the r05 kafka lane's mid-run `remote_compile` connection reset is
#: the type specimen. One bounded retry; a second failure stands.
_TRANSIENT_RUN_RE = _re.compile(
    r"connection reset|connection dropped|read body|UNAVAILABLE|"
    r"DEADLINE_EXCEEDED|timed out|Connection refused|"
    r"ConnectionResetError|ConnectionError|BrokenPipe", _re.I)


def _parse_bench_line(stdout: bytes):
    """The inner's (single) JSON line, or None."""
    try:
        lines = [ln for ln in stdout.decode("utf-8", "replace")
                 .splitlines() if ln.strip()]
        return json.loads(lines[-1]) if lines else None
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None


def _run_config_resilient(config: str, args, max_attempts=None) -> int:
    """Probe + run one config in fresh subprocesses with bounded retry.

    Returns the rc to contribute; ALWAYS leaves exactly one JSON line
    on stdout for the config (the inner's line, or a
    ``bench_failed_backend`` line after the last attempt). Lane
    isolation (perf ledger): a lane that dies MID-RUN on a transient
    connection error gets exactly ONE retry, and its final failure
    line is enriched with a structured ``{lane, attempts, transient}``
    record — the sweep continues either way instead of losing the lane
    silently."""
    import subprocess

    retries = max_attempts if max_attempts is not None else int(
        os.environ.get("CILIUM_TPU_BENCH_RETRIES", "5"))
    backoff = float(os.environ.get("CILIUM_TPU_BENCH_BACKOFF", "30"))
    probe_timeout = float(
        os.environ.get("CILIUM_TPU_BENCH_PROBE_TIMEOUT", "180"))
    bench_timeout = float(
        os.environ.get("CILIUM_TPU_BENCH_TIMEOUT", "3600"))
    me = os.path.abspath(__file__)
    last_err = ""
    lane_retry_used = False
    attempts_run = 0

    for attempt in range(1, retries + 1):
        if attempt > 1:
            print(f"[{config}] backend attempt {attempt}/{retries} "
                  f"after {backoff:.0f}s backoff", file=sys.stderr)
            time.sleep(backoff)
        # 1) probe in a throwaway process: a wedged tunnel hangs, a
        #    down backend exits 42 — either way this process never
        #    times anything and is cheap to kill
        try:
            p = subprocess.run(
                [sys.executable, me, "--probe"],
                capture_output=True, timeout=probe_timeout, text=True)
        except subprocess.TimeoutExpired:
            last_err = f"probe timed out after {probe_timeout:.0f}s"
            continue
        if p.returncode != 0:
            last_err = (p.stderr or "").strip()[-500:] or \
                f"probe rc={p.returncode}"
            continue
        # 2) the real run, in its own fresh process
        try:
            r = subprocess.run(
                _inner_cmd(config, args), stdout=subprocess.PIPE,
                timeout=bench_timeout)
        except subprocess.TimeoutExpired:
            last_err = f"bench timed out after {bench_timeout:.0f}s"
            continue
        if r.returncode == _BACKEND_FAIL_RC:
            last_err = "backend init failed in bench process"
            continue
        if r.returncode != 0 and not r.stdout.strip():
            # inner crashed after init without printing its JSON line
            # (e.g. tunnel died mid-bench) — the one-line contract must
            # hold, and a mid-bench death is worth a retry
            last_err = f"bench process died rc={r.returncode}"
            continue
        attempts_run += 1
        line = _parse_bench_line(r.stdout)
        if (r.returncode != 0 and line is not None
                and str(line.get("metric", "")).startswith(
                    "bench_failed_run")):
            err = f"{line.get('unit', '')} {line.get('error', '')}"
            if _TRANSIENT_RUN_RE.search(err) and not lane_retry_used:
                # one bounded lane retry for the transient mid-run
                # regime (r05 kafka): this attempt burned no backend
                # budget — the backend answered, the lane's connection
                # died
                lane_retry_used = True
                last_err = err.strip()[-500:]
                print(f"[{config}] transient lane failure, one retry: "
                      f"{last_err[:200]}", file=sys.stderr)
                continue
            # structured per-lane failure record, then the run
            # continues with the other lanes
            line.update({"lane": config, "attempts": attempts_run,
                         "transient":
                             bool(_TRANSIENT_RUN_RE.search(err))})
            sys.stdout.write(json.dumps(line) + "\n")
            sys.stdout.flush()
            return r.returncode
        sys.stdout.buffer.write(r.stdout)
        sys.stdout.flush()
        return r.returncode

    print(json.dumps({
        "metric": f"bench_failed_backend_{config}",
        "value": 0,
        "unit": f"attempts={retries}",
        "vs_baseline": 0.0,
        "error": last_err[-500:],
        # structured lane-failure record (perf ledger): perf-report's
        # failure ledger keys on these
        "lane": config,
        "attempts": retries,
        "transient": True,
    }), flush=True)
    return _BACKEND_FAIL_RC


def _watch(args) -> int:
    """Self-arming TPU evidence capture (VERDICT r3 item 1): loop a
    probe-with-timeout until the tunnel answers, then run the full
    evidence sweep — ``--config all`` (the official http line with its
    e2e capture-replay rate, plus every other BASELINE config and the
    regen lane) and the service-latency sweep — writing dated
    artifacts. The watcher itself never imports jax (a wedged probe
    only ever kills a throwaway subprocess), so it can run for hours
    without being poisoned by the tunnel (docs/PLATFORM.md).

    Artifacts (repo root, tagged by --watch TAG):
      BENCH_ALL_{tag}.json       one JSON line per config
      SERVICE_LATENCY_{tag}.json the bench_service.py sweep
      WATCH_{tag}.log            timestamped probe/sweep history

    Knobs: CILIUM_TPU_WATCH_INTERVAL (s between failed probes, 300),
    CILIUM_TPU_WATCH_MAX_HOURS (give up, 24). Exit 0 = sweep captured;
    3 = deadline expired with the tunnel still down."""
    import subprocess

    interval = float(os.environ.get("CILIUM_TPU_WATCH_INTERVAL", "300"))
    max_hours = float(os.environ.get("CILIUM_TPU_WATCH_MAX_HOURS", "24"))
    probe_timeout = float(
        os.environ.get("CILIUM_TPU_BENCH_PROBE_TIMEOUT", "180"))
    me = os.path.abspath(__file__)
    here = os.path.dirname(me)
    tag = args.watch
    log_path = os.path.join(here, f"WATCH_{tag}.log")

    def log(msg: str) -> None:
        line = f"{time.strftime('%Y-%m-%d %H:%M:%S')} {msg}"
        print(line, file=sys.stderr, flush=True)
        with open(log_path, "a") as fp:
            fp.write(line + "\n")

    deadline = time.monotonic() + max_hours * 3600
    attempt = 0
    log(f"watch start: interval={interval:.0f}s max_hours={max_hours}")
    while True:
        attempt += 1
        try:
            p = subprocess.run([sys.executable, me, "--probe"],
                               capture_output=True,
                               timeout=probe_timeout, text=True)
            alive = p.returncode == 0
            why = "" if alive else f"rc={p.returncode}"
        except subprocess.TimeoutExpired:
            alive, why = False, f"timeout {probe_timeout:.0f}s"
        if alive:
            log(f"probe #{attempt}: tunnel is UP — starting sweep")
            break
        log(f"probe #{attempt}: down ({why})")
        if time.monotonic() >= deadline:
            log("watch deadline expired; tunnel never answered")
            return 3
        time.sleep(interval)

    # the sweep: every step is its own subprocess chain with bench.py's
    # probe+retry already inside, so a mid-sweep re-wedge degrades to
    # honest bench_failed_backend lines instead of a hang
    if os.environ.get("CILIUM_TPU_WATCH_DRY"):
        log("dry mode: sweep armed, not run")  # test hook
        return 0
    sweep = [
        ([sys.executable, me, "--config", "all"],
         os.path.join(here, f"BENCH_ALL_{tag}.json")),
        ([sys.executable, os.path.join(here, "bench_service.py"),
          "--shim", "--out",
          os.path.join(here, f"SERVICE_LATENCY_{tag}.json")],
         None),
        # the pipelined-drain lever, measured on TPU (PLATFORM.md):
        # open-loop only, one closed-loop deadline for reference
        ([sys.executable, os.path.join(here, "bench_service.py"),
          "--deadlines", "2", "--drain-workers", "2", "--out",
          os.path.join(here, f"SERVICE_LATENCY_{tag}_pipelined.json")],
         None),
    ]
    # per-step hard timeout: bench.py steps carry their own probe+retry
    # but bench_service.py does not, and a mid-sweep re-wedge must cost
    # one killed step, not a hung watcher
    step_timeout = float(
        os.environ.get("CILIUM_TPU_WATCH_STEP_TIMEOUT", "14400"))
    rc = 0
    for cmd, out_path in sweep:
        log(f"run: {' '.join(os.path.basename(c) for c in cmd[1:])}")
        try:
            r = subprocess.run(cmd, stdout=subprocess.PIPE,
                               timeout=step_timeout)
            out, step_rc = r.stdout, r.returncode
        except subprocess.TimeoutExpired as e:
            out, step_rc = e.stdout or b"", 1
            log(f"step timed out after {step_timeout:.0f}s (killed)")
        if out_path is not None and out:
            with open(out_path, "wb") as fp:
                fp.write(out)
        sys.stdout.buffer.write(out or b"")
        sys.stdout.flush()
        log(f"done rc={step_rc}"
            + (f" → {os.path.basename(out_path)}" if out_path else ""))
        rc = rc or step_rc
    log(f"sweep complete rc={rc}")
    return rc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="http",
                    choices=["http", "fqdn", "kafka", "generic",
                             "mixed", "clustermesh", "regen", "all"])
    ap.add_argument("--rules", type=int, default=None,
                    help="rule count (default: per-config BASELINE shape)")
    ap.add_argument("--flows", type=int, default=None,
                    help="flow/tuple count (default: per-config BASELINE "
                         "shape: http/fqdn 10k, kafka 100k, mixed 1M, "
                         "clustermesh 100k)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--lat-iters", type=int, default=100, dest="lat_iters",
                    help="blocking latency samples for the p50/p99 pass "
                         "(non-streaming configs)")
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--check", action="store_true",
                    help="verify engine vs oracle on a sample (after timing)")
    ap.add_argument("--from-capture", metavar="FILE", dest="from_capture",
                    default="auto",
                    help="time end-to-end file→verdict replay of a "
                         "stored v2/v3 binary capture (written from the "
                         "synth scenario if FILE is absent) — the north "
                         "star's 'replaying a Hubble capture'. Default "
                         "'auto' (every config except regen, round 5) "
                         "uses a shape-keyed temp file; 'none' disables "
                         "the lane (the full-batch lane then reports)")
    ap.add_argument("--capture-flows", type=int, default=200000,
                    help="records to write when --from-capture creates "
                         "the file (default 200000)")
    ap.add_argument("--capture-cardinality", default="low",
                    choices=("low", "high"),
                    dest="capture_cardinality",
                    help="'high' gives every capture record a unique "
                         "string (ratio≈1: dedup declines, windows "
                         "stream full rows) — the non-dedup regime "
                         "beside the id-stream line")
    ap.add_argument("--replay-chunk", type=int, default=65536,
                    help="e2e capture-replay chunk size (the replay "
                         "pipeline's own batching — independent of the "
                         "BASELINE --flows batch shape the device "
                         "latency lane measures; small chunks pay "
                         "per-dispatch overhead ~20x at 10k vs 64k)")
    ap.add_argument("--profile", metavar="DIR",
                    help="capture a jax.profiler device trace of the "
                         "timed passes into DIR (open with Perfetto / "
                         "tensorboard; SURVEY.md §5.1)")
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--probe", action="store_true",
                    help="(internal) backend liveness probe; exits 42 "
                         "if the backend cannot initialize")
    ap.add_argument("--inner", action="store_true",
                    help="(internal) run one config in THIS process "
                         "(no probe/retry; used by the outer re-exec)")
    ap.add_argument("--watch", metavar="TAG", nargs="?", const="r04",
                    default=None,
                    help="loop a backend probe until the tunnel answers, "
                         "then capture the full evidence sweep "
                         "(--config all + bench_service.py) into "
                         "BENCH_ALL_TAG.json / SERVICE_LATENCY_TAG.json "
                         "(VERDICT r3 item 1; see WATCH_TAG.log)")
    args = ap.parse_args()

    if args.probe:
        return _probe()

    if args.watch:
        return _watch(args)

    if args.inner:
        _init_backend()
        try:
            result = run_config(args.config, args)
        except Exception as e:  # noqa: BLE001 — a bench bug must still
            # yield the one JSON line (and rc 1, not 42: a deterministic
            # failure after backend init is not worth the retry budget)
            result = {"metric": f"bench_failed_run_{args.config}",
                      "value": 0, "unit": type(e).__name__,
                      "vs_baseline": 0.0, "error": str(e)[:500]}
        # provenance fingerprint (perf ledger): platform / device /
        # jax / RTT probe / git rev, under the versioned BENCH schema —
        # what lets perf-report tell a code regression from a tunnel.
        # stamp() never raises; the one-line contract holds regardless
        from cilium_tpu.runtime.provenance import stamp

        # no RTT probe on a failed lane: the failure may BE a wedged
        # tunnel, and a hanging probe would eat the outer's timeout
        stamp(result, rtt=not result["metric"].startswith("bench_failed"))
        print(json.dumps(result), flush=True)
        return 1 if result["metric"].startswith("bench_failed") else 0

    # outer: never imports jax; one fresh subprocess per config (a
    # process that has done post-timing readbacks is permanently in
    # the tunnel's ~64ms sync mode — docs/PLATFORM.md), with probe +
    # bounded retry around every attempt
    configs = (("http", "fqdn", "kafka", "generic", "mixed",
                "clustermesh", "regen")
               if args.config == "all" else (args.config,))
    rc = 0
    backend_dead = False
    for config in configs:
        # backend liveness is global, not per-config: once one config
        # has exhausted the full retry budget against a dead backend,
        # give the rest a single attempt each (they still get their
        # guaranteed JSON line) instead of repeating the doomed cycle
        r = _run_config_resilient(
            config, args, max_attempts=1 if backend_dead else None)
        if r == _BACKEND_FAIL_RC:
            backend_dead = True
            r = 1
        rc = rc or r
    return rc


if __name__ == "__main__":
    sys.exit(main())
