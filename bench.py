#!/usr/bin/env python
"""Benchmark: L7 policy verdicts/sec on TPU.

Primary config (BASELINE.json configs[1]): 1k HTTP path/header regex
rules × 10k Hubble-replayed HTTP flows; the engine computes the full
L3/L4 + L7 verdict per flow. Baseline target: 10M verdicts/sec/chip
(`BASELINE.json ·north_star`); ``vs_baseline`` = value / 10e6.

Timing methodology (docs/PLATFORM.md): on the axon-tunneled TPU any
device→host readback permanently drops the process into a ~64ms-RTT
sync mode, so the timed region — and everything before it — performs
ZERO readbacks. Distinct permuted batches are staged from host numpy
(never round-tripped through the device), each timed call sees fresh
buffers, and verdict values are only read back after the last timer
stops. Oracle checking (--check) also runs after timing.

Prints exactly ONE JSON line per config (the BASELINE metric is
throughput AND latency, so the line carries both):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "p50_ms": N, "p99_ms": N}

``--config all`` runs every BASELINE config and prints one line each
(the default single-config invocation still prints exactly one line).

Usage: python bench.py [--rules 1000] [--flows 10000] [--iters 20]
       [--config http|fqdn|kafka|mixed|clustermesh|all] [--check]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

#: per-config BASELINE flow/tuple shapes
_DEFAULT_FLOWS = {"http": 10000, "fqdn": 10000, "kafka": 100000,
                  "mixed": 1000000, "clustermesh": 100000}
#: per-config BASELINE rule counts (configs[0] is "100 DNS names x 10
#: regex rules"; http is the 1k-rule north-star shape)
_DEFAULT_RULES = {"http": 1000, "fqdn": 10, "kafka": 1000,
                  "mixed": 0, "clustermesh": 0}


def run_config(config: str, args) -> dict:
    import jax
    import numpy as np

    from cilium_tpu.core.config import Config
    from cilium_tpu.engine.verdict import (
        encode_flows,
        flowbatch_to_host_dict,
        verdict_step,
    )
    from cilium_tpu.ingest import synth
    from cilium_tpu.runtime.loader import Loader
    from cilium_tpu.runtime.metrics import SpanStat

    def log(msg: str) -> None:
        if args.verbose:
            print(msg, file=sys.stderr)

    n_flows = args.flows if args.flows is not None else _DEFAULT_FLOWS[config]
    n_rules = (args.rules if args.rules is not None
               else _DEFAULT_RULES[config])

    import contextlib

    @contextlib.contextmanager
    def maybe_trace():
        """jax.profiler trace of the timed passes (--profile). The
        finally preserves the partial trace when a timed pass raises
        (the runs one most wants to profile) instead of leaving a
        dangling profiler session."""
        if not args.profile:
            yield
            return
        jax.profiler.start_trace(args.profile)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            log(f"profiler trace written to {args.profile}")

    if config == "http":
        scenario = synth.synth_http_scenario(n_rules=n_rules,
                                             n_flows=n_flows)
    elif config == "fqdn":
        scenario = synth.synth_fqdn_scenario(n_names=100, n_rules=n_rules,
                                             n_flows=n_flows)
    elif config == "mixed":
        # BASELINE configs[3]: examples/policies corpus × synthetic tuples
        import os
        corpus = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "examples", "policies")
        scenario = synth.synth_mixed_scenario(corpus, n_tuples=n_flows)
    elif config == "clustermesh":
        # BASELINE configs[4]: 10k identities × 5k CNP, streaming
        scenario = synth.synth_clustermesh_scenario(
            n_identities=10000, n_policies=5000, n_flows=n_flows)
    else:
        scenario = synth.synth_kafka_scenario(n_rules=n_rules,
                                              n_records=n_flows)
    streaming = config in ("mixed", "clustermesh")
    per_identity, scenario = synth.realize_scenario(scenario)

    cfg = Config.from_env()
    cfg.enable_tpu_offload = True
    loader = Loader(cfg)
    with SpanStat("bench_compile") as compile_span:
        engine = loader.regenerate(per_identity, revision=1)
    log(f"compile+stage: {compile_span.seconds:.1f}s "
        f"(cache dir {cfg.loader.cache_dir})")

    fb = encode_flows(scenario.flows, engine.policy.kafka_interns, cfg.engine)
    step = jax.jit(verdict_step)
    arrays = engine._arrays

    host = flowbatch_to_host_dict(fb)
    if streaming:
        # configs[3]/[4] methodology: stream the whole tuple set once,
        # chunked at the engine batch size. Every timed call sees a
        # first-use buffer (no repeat → no caching layer can shortcut),
        # and all chunks are staged to HBM before the timer starts so
        # the timed region has zero H2D traffic and zero readbacks.
        bs = cfg.engine.batch_size
        n_total = fb.size
        n_chunks = n_total // bs
        if n_chunks < args.warmup + 4:  # compile + >=1 latency + >=2 tput
            return {"metric": "bench_failed_setup", "value": 0,
                    "unit": "too few chunks", "vs_baseline": 0.0}
        chunks = []
        for c in range(n_chunks):
            sl = slice(c * bs, (c + 1) * bs)
            chunks.append({k: jax.device_put(v[sl]) for k, v in host.items()})
        jax.block_until_ready(chunks)

        out = step(arrays, chunks[0])
        jax.block_until_ready(out)  # compile
        for i in range(args.warmup):
            out = step(arrays, chunks[1 + i])
        jax.block_until_ready(out)

        with maybe_trace():
            # latency pass: block per chunk (p50/p99 are per-batch
            # latency); uses the first few timed chunks, which the
            # throughput pass then skips so every throughput-timed
            # buffer is still first-use
            n_lat = max(1, min(8, n_chunks - 1 - args.warmup - 2))
            times = []
            for c in range(1 + args.warmup, 1 + args.warmup + n_lat):
                t0 = time.perf_counter()
                out = step(arrays, chunks[c])
                jax.block_until_ready(out)
                times.append(time.perf_counter() - t0)
            # throughput pass: dispatch the whole remaining stream and
            # sync ONCE — chunks are distinct first-use buffers already
            # resident in HBM, so this measures pipelined device
            # execution, which is how a real flow stream runs (compute
            # overlaps dispatch)
            first = 1 + args.warmup + n_lat
            t_stream0 = time.perf_counter()
            outs = []
            for c in range(first, n_chunks):
                outs.append(step(arrays, chunks[c]))
            jax.block_until_ready(outs)
            t_stream = time.perf_counter() - t_stream0
        out = outs[-1]
        n_timed = (n_chunks - first) * bs
        vps = n_timed / t_stream
        times.sort()
        p50_ms = times[len(times) // 2] * 1e3
        p99_ms = times[min(len(times) - 1, int(len(times) * 0.99))] * 1e3
        log(f"streamed {n_timed} of {n_total} flows in {t_stream:.3f}s "
            f"(chunk={bs}, per-chunk p50={p50_ms:.2f}ms, "
            f"p99={p99_ms:.2f}ms) verdicts/s={vps:,.0f}")
    else:
        # Distinct, differently-permuted device copies per call — warmup
        # and timed — so no caching layer (compiler CSE, platform replay)
        # can shortcut repeat executions. Built from HOST numpy: a device
        # round trip here would poison the process (docs/PLATFORM.md).
        prng = np.random.default_rng(0)
        # compile + warmup + latency iters; throughput windows stage
        # their own copies one window at a time (below) so HBM holds at
        # most iters extra copies, not 3*iters. ALL copies are distinct
        # permutations so every timed call is first-use.
        n_copies = args.warmup + args.iters + 1
        batches = []
        for _ in range(n_copies):
            perm = prng.permutation(fb.size)
            batches.append({k: jax.device_put(v[perm])
                            for k, v in host.items()})
        jax.block_until_ready(batches)

        out = step(arrays, batches[0])
        jax.block_until_ready(out)  # compile
        for i in range(args.warmup):
            out = step(arrays, batches[1 + i])
        jax.block_until_ready(out)

        with maybe_trace():
            # latency pass: block per call (median/worst per-batch
            # latency)
            times = []
            for i in range(args.iters):
                batch = batches[1 + args.warmup + i]
                t0 = time.perf_counter()
                out = step(arrays, batch)
                jax.block_until_ready(out)
                times.append(time.perf_counter() - t0)
            times.sort()
            med = times[len(times) // 2]
            n = len(scenario.flows)
            # throughput pass: dispatch every timed batch (distinct
            # permuted first-use buffers, staged per window, untimed)
            # and sync ONCE per window — compute overlaps dispatch, as
            # a real replay pipeline runs. Median of 5 windows: the
            # tunneled transport's run-to-run jitter is ±30% on
            # identical binaries, so a single window reports tunnel
            # luck; the median is the defensible sustained figure (the
            # streaming configs are single-window by construction —
            # one first-use pass over the whole tuple set).
            window_times = []
            for _ in range(5):
                wb = []
                for _ in range(args.iters):
                    perm = prng.permutation(fb.size)
                    wb.append({k: jax.device_put(v[perm])
                               for k, v in host.items()})
                jax.block_until_ready(wb)
                t0 = time.perf_counter()
                outs = [step(arrays, b) for b in wb]
                jax.block_until_ready(outs)
                window_times.append(time.perf_counter() - t0)
            t_all = sorted(window_times)[len(window_times) // 2]
        out = outs[-1]
        vps = n * args.iters / t_all
        p50_ms = med * 1e3
        p99_ms = times[min(len(times) - 1, int(len(times) * 0.99))] * 1e3
        log(f"batch={n} latency: median={p50_ms:.2f}ms "
            f"p99={p99_ms:.2f}ms ({n/med:,.0f}/s blocking); "
            f"pipelined verdicts/s={vps:,.0f}")

    # ---- timing is over; readbacks are safe now -----------------------
    log(f"verdict mix: "
        f"{np.bincount(np.asarray(out['verdict']), minlength=6).tolist()}")

    if args.check:
        from cilium_tpu.policy.oracle import OracleVerdictEngine

        sample = scenario.flows[:500]
        want = OracleVerdictEngine(per_identity).verdict_flows(sample)["verdict"]
        got = engine.verdict_flows(sample)["verdict"]
        bad = int((got != want).sum())
        if bad:
            return {"metric": "bench_failed_check",
                    "value": bad, "unit": "mismatches",
                    "vs_baseline": 0.0}
        log("oracle check: OK")

    # http/fqdn/kafka wrap their N sub-rules in one Rule — n_rules is
    # the meaningful count there; mixed/clustermesh have real rule lists
    if streaming:
        n_rules = len(scenario.rules)
    return {
        "metric": f"l7_verdicts_per_sec_{config}_{n_rules}rules",
        "value": round(vps, 1),
        "unit": "verdicts/s",
        "vs_baseline": round(vps / 10e6, 4),
        # the BASELINE metric's second half: per-batch verdict latency
        "p50_ms": round(p50_ms, 3),
        "p99_ms": round(p99_ms, 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="http",
                    choices=["http", "fqdn", "kafka", "mixed",
                             "clustermesh", "all"])
    ap.add_argument("--rules", type=int, default=None,
                    help="rule count (default: per-config BASELINE shape)")
    ap.add_argument("--flows", type=int, default=None,
                    help="flow/tuple count (default: per-config BASELINE "
                         "shape: http/fqdn 10k, kafka 100k, mixed 1M, "
                         "clustermesh 100k)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--check", action="store_true",
                    help="verify engine vs oracle on a sample (after timing)")
    ap.add_argument("--profile", metavar="DIR",
                    help="capture a jax.profiler device trace of the "
                         "timed passes into DIR (open with Perfetto / "
                         "tensorboard; SURVEY.md §5.1)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    if args.config == "all":
        # one SUBPROCESS per config: after a config's post-timing
        # readbacks the process is permanently in the tunnel's ~64ms
        # sync mode (docs/PLATFORM.md), which would poison every
        # subsequent config's numbers by ~100x
        import os
        import subprocess

        rc = 0
        for config in ("http", "fqdn", "kafka", "mixed", "clustermesh"):
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--config", config,
                   "--iters", str(args.iters),
                   "--warmup", str(args.warmup)]
            if args.rules is not None:
                cmd += ["--rules", str(args.rules)]
            if args.flows is not None:
                cmd += ["--flows", str(args.flows)]
            if args.check:
                cmd.append("--check")
            if args.verbose:
                cmd.append("--verbose")
            if args.profile:
                cmd += ["--profile",
                        os.path.join(args.profile, config)]
            r = subprocess.run(cmd, stdout=subprocess.PIPE)
            sys.stdout.buffer.write(r.stdout)
            sys.stdout.flush()
            rc = rc or r.returncode
        return rc

    result = run_config(args.config, args)
    print(json.dumps(result), flush=True)
    return 1 if result["metric"].startswith("bench_failed") else 0


if __name__ == "__main__":
    sys.exit(main())
