#!/usr/bin/env python
"""Multi-chip scaling harness (VERDICT r4 item 5).

``dryrun_multichip`` proves the sharded paths are CORRECT
(bit-parity per strategy); this measures how they SCALE: per-device
throughput vs a single device (weak scaling) for DP, DP×EP, and TP,
with the overhead fraction (collectives + sharding glue) on each line.

Runs unchanged on real multi-chip hardware: with ``--platform native``
it uses ``jax.devices()`` as-is (a v5e-8 gives an 8-way mesh); the
default ``--platform cpu`` forces the virtual host-device mesh the
test suite uses, which is the only multi-device surface this
environment has — so the numbers are an EMULATION of the sharding/
collective structure, not ICI performance (the caveat rides the
artifact as ``platform``).

Methodology matches bench.py: distinct pre-staged first-use buffers,
zero readbacks inside timing, median of windows.

  python bench_multichip.py --devices 8 --out MULTICHIP_PERF_r05.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _median(xs):
    return sorted(xs)[len(xs) // 2]


def _time_windows(fn, windows: int):
    """Median seconds over ``windows`` calls of fn() (fn blocks)."""
    ts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return _median(ts)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--rules", type=int, default=256)
    ap.add_argument("--flows-per-device", type=int, default=4096,
                    dest="flows_per_device")
    ap.add_argument("--windows", type=int, default=7)
    ap.add_argument("--platform", choices=("cpu", "native"),
                    default="cpu",
                    help="cpu = virtual host-device mesh (emulates "
                         "the sharding structure, not ICI); native = "
                         "whatever jax.devices() offers (v5e-8 etc.)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    n = args.devices

    if args.platform == "cpu":
        from cilium_tpu.parallel.mesh import force_cpu_host_devices

        force_cpu_host_devices(n)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cilium_tpu.parallel.mesh import make_mesh

    devices = jax.devices()[:n]
    if len(devices) < n:
        print(json.dumps({"metric": "bench_failed_setup", "value": 0,
                          "unit": f"only {len(devices)} devices",
                          "vs_baseline": 0.0}))
        return 1

    from cilium_tpu.core.config import EngineConfig
    from cilium_tpu.engine.verdict import (
        CompiledPolicy,
        encode_flows,
        flowbatch_to_host_dict,
        verdict_step,
    )
    from cilium_tpu.ingest.synth import (
        realize_scenario,
        synth_http_scenario,
    )
    from cilium_tpu.parallel.sharding import (
        make_sharded_step,
        shard_flow_batch,
        shard_policy_arrays,
    )

    B = args.flows_per_device
    scenario = synth_http_scenario(n_rules=args.rules, n_flows=B)
    per_identity, scenario = realize_scenario(scenario)
    cfg = EngineConfig(bank_size=8)  # rules/8 banks: divisible by n
    policy = CompiledPolicy.build(per_identity, cfg)
    flows = list(scenario.flows)
    while len(flows) < B * n:
        flows = flows + flows
    host_full = flowbatch_to_host_dict(
        encode_flows(flows[:B * n], policy.kafka_interns, cfg))
    host_1 = {k: v[:B] for k, v in host_full.items()}

    points = []
    rng = np.random.default_rng(0)

    def permuted(host, size):
        perm = rng.permutation(size)
        return {k: v[perm] for k, v in host.items()}

    # -- single-device baseline -------------------------------------------
    dev0 = devices[0]
    arrays_1 = {k: jax.device_put(v, dev0)
                for k, v in policy.arrays.items()}
    step_1 = jax.jit(verdict_step)
    batches_1 = [
        {k: jax.device_put(v, dev0)
         for k, v in permuted(host_1, B).items()}
        for _ in range(args.windows)]
    jax.block_until_ready(batches_1)
    jax.block_until_ready(step_1(arrays_1, batches_1[0]))  # compile

    t1 = _time_windows(
        lambda it=iter(batches_1 * 2): jax.block_until_ready(
            step_1(arrays_1, next(it))), args.windows)
    vps_1 = B / t1
    points.append({"lane": "single_device", "devices": 1,
                   "verdicts_per_sec": round(vps_1, 1),
                   "per_device_vps": round(vps_1, 1)})

    # constant-silicon reference: the FULL B×n batch unsharded on one
    # logical device. On the virtual cpu mesh all n "devices" share
    # one physical CPU, so weak-scaling-vs-single-device mostly
    # measures host saturation; t_sharded / t_unsharded_full at equal
    # total work isolates what the artifact is really after — the
    # sharding + collective overhead of the partitioned program
    batches_full = [
        {k: jax.device_put(v, dev0)
         for k, v in permuted(host_full, B * n).items()}
        for _ in range(args.windows)]
    jax.block_until_ready(batches_full)
    jax.block_until_ready(step_1(arrays_1, batches_full[0]))
    t_full_1 = _time_windows(
        lambda it=iter(batches_full * 2): jax.block_until_ready(
            step_1(arrays_1, next(it))), args.windows)
    points.append({"lane": "single_device_full_batch", "devices": 1,
                   "batch": B * n,
                   "verdicts_per_sec": round(B * n / t_full_1, 1)})

    # -- DP (pure data parallel) ------------------------------------------
    def run_sharded(mesh, expert_axis, lane):
        arrays_s = shard_policy_arrays(policy.arrays, mesh,
                                       expert_axis=expert_axis)
        step_s = make_sharded_step(mesh, "data")
        batches = []
        for _ in range(args.windows):
            batches.append(shard_flow_batch(
                permuted(host_full, B * n), mesh, "data"))
        jax.block_until_ready(batches)
        jax.block_until_ready(step_s(arrays_s, batches[0]))
        t = _time_windows(
            lambda it=iter(batches * 2): jax.block_until_ready(
                step_s(arrays_s, next(it))), args.windows)
        vps = B * n / t
        eff = vps / (n * vps_1)
        points.append({
            "lane": lane, "devices": n,
            "mesh": dict(mesh.shape),
            "verdicts_per_sec": round(vps, 1),
            "per_device_vps": round(vps / n, 1),
            # vs n× the single-device-B rate — THE number on real
            # chips; on the cpu platform it mostly reflects that all
            # virtual devices share one CPU
            "weak_scaling_efficiency": round(eff, 4),
            # same total work, sharded vs unsharded on one device —
            # isolates sharding + collective overhead at constant
            # silicon (the meaningful number on the emulated mesh)
            "constant_silicon_efficiency": round(t_full_1 / t, 4),
            "sharding_overhead_fraction": round(
                max(0.0, 1 - t_full_1 / t), 4),
        })

    run_sharded(make_mesh((n,), ("data",), devices), None, "dp")
    if n % 2 == 0 and n >= 4:
        run_sharded(make_mesh((n // 2, 2), ("data", "expert"),
                              devices), "expert", "dp_x_ep")

    # -- TP (state-axis sharding of one scan) -----------------------------
    from cilium_tpu.engine.dfa_kernel import dfa_scan_banked
    from cilium_tpu.parallel.tp import dfa_scan_banked_tp, pad_states
    from cilium_tpu.policy.compiler.dfa import compile_patterns

    pats = [f"/api/v{i}[0-9]*" for i in range(24)] + [
        "/health", "/metrics", "abc+", "x.y",
        "/users/[0-9]+", "/orders/.*", "do.t", "[a-f]+42"]
    arrs = compile_patterns(pats, bank_size=2).stacked()
    SB = 64 * n
    data = rng.integers(0, 128, size=(SB, 64), dtype=np.uint8)
    lengths = np.full((SB,), 64, dtype=np.int32)
    j = {k: jnp.asarray(v) for k, v in arrs.items()}
    dj = jnp.asarray(data)
    lj = jnp.asarray(lengths)
    scan_1 = jax.jit(dfa_scan_banked)
    jax.block_until_ready(scan_1(j["trans"], j["byteclass"],
                                 j["start"], j["accept"], dj, lj))
    t_scan1 = _time_windows(lambda: jax.block_until_ready(
        scan_1(j["trans"], j["byteclass"], j["start"], j["accept"],
               dj, lj)), args.windows)

    from cilium_tpu.parallel.collectives import LEDGER

    tp_mesh = make_mesh((n,), ("state",), devices)
    trans_p, accept_p = pad_states(arrs["trans"], arrs["accept"], n)
    tpj, apj = jnp.asarray(trans_p), jnp.asarray(accept_p)
    # per-collective breakdown (perf ledger): reset → one traced call
    # → snapshot gives op kind / count per block / bytes — the
    # "99.99% collective overhead" number, decomposed
    LEDGER.reset()
    jax.block_until_ready(dfa_scan_banked_tp(
        tp_mesh, tpj, j["byteclass"], j["start"], apj, dj, lj))
    tp_collectives = LEDGER.snapshot()
    LEDGER.publish_metrics()
    t_tp = _time_windows(lambda: jax.block_until_ready(
        dfa_scan_banked_tp(tp_mesh, tpj, j["byteclass"], j["start"],
                           apj, dj, lj)), args.windows)
    speedup = t_scan1 / t_tp
    points.append({
        "lane": "tp", "devices": n, "mesh": {"state": n},
        "scan_batch": SB,
        "single_device_s": round(t_scan1, 4),
        "tp_s": round(t_tp, 4),
        "strong_scaling_speedup": round(speedup, 3),
        "strong_scaling_efficiency": round(speedup / n, 4),
        "overhead_fraction": round(max(0.0, 1 - speedup / n), 4),
        # the ledger's per-collective account: op kind, count per
        # block (the scan body's psum executes once per scanned
        # byte), bytes per call — evidence, not vibes
        "collectives": tp_collectives,
        # TP shards the DFA state axis, which costs a collective per
        # scanned byte — it exists as the states-don't-fit fallback
        # (parallel/tp.py MAX_TP_STATES), not a throughput play; the
        # emulated mesh makes that per-byte collective especially
        # expensive
        "note": "state-axis fallback lane; collective per byte",
    })

    dp = next(p for p in points if p["lane"] == "dp")
    if args.platform == "cpu":
        value = dp["constant_silicon_efficiency"]
        unit = ("DP constant-silicon efficiency (sharded vs unsharded "
                "at equal total work; virtual cpu mesh)")
    else:
        value = dp["weak_scaling_efficiency"]
        unit = "DP weak-scaling efficiency vs single device"
    line = {
        "metric": f"multichip_weak_scaling_{n}dev",
        "value": value,
        "unit": unit,
        "vs_baseline": 0.0,
        "platform": args.platform,
        "flows_per_device": B,
        "rules": args.rules,
        "points": points,
    }
    # provenance fingerprint (perf ledger): perf-report classifies
    # cross-round deltas off this
    from cilium_tpu.runtime.provenance import stamp

    stamp(line)
    print(json.dumps(line), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(line, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
