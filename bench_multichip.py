#!/usr/bin/env python
"""Multi-chip scaling harness (VERDICT r4 item 5, reworked round 7).

``dryrun_multichip`` proves the sharded paths are CORRECT
(bit-parity per strategy); this measures how they SCALE, one lane per
§2.6 layout:

* ``dp``      — batch-sharded verdict step (auto-partitioned);
* ``dp_x_ep`` — the auto-partitioned DP×EP mesh (the r05 lane that
  lost 34% to re-sharding — kept for comparison);
* ``ep``      — the one-shot Ulysses re-shard (parallel/ulysses.py):
  banks sharded, inputs staged replicated once, exactly ONE
  ``all_to_all`` between scan and match;
* ``cp``      — the payload-sharded blockwise scan (parallel/cp.py):
  ONE carry-exchange collective per compiled block;
* ``tp``      — the state-axis psum-per-byte lane (parallel/tp.py),
  kept as the states-don't-fit fallback it is.

Runs unchanged on real multi-chip hardware: with ``--platform native``
it uses ``jax.devices()`` as-is (a v5e-8 gives an 8-way mesh); the
default ``--platform cpu`` forces the virtual host-device mesh the
test suite uses — the numbers are an EMULATION of the sharding/
collective structure, not ICI performance. On the emulated mesh all n
"devices" share one physical CPU, so weak-scaling-vs-single-device
mostly measures host saturation; the honest per-lane number is
**constant-silicon efficiency** (sharded vs unsharded at equal total
work), and that is what the ``--strict-gate`` reads on the cpu
platform (``weak_scaling_efficiency`` on native).

Methodology: distinct pre-staged first-use buffers (explicit
NamedSharding ``device_put`` ONCE per lane, outside timing), zero
readbacks inside timing, and **pipelined windows** — all dispatches
issued back-to-back with one completion barrier at the end, so the
wall excludes the per-wave host sync the r05 run paid between every
window.

Evidence on every sharded point: the PR-6 collective ledger's
per-block rows (``collectives``) plus the lane's DECLARED budget
(``collective_budget_per_block``) — perf-report fails CI when the
recorded count exceeds the declared budget, so a regression back to
per-byte collectives is caught structurally, not by wall-clock noise.
Lanes partitioned by XLA (dp/dp_x_ep) carry the compiled module's
collective instruction counts (``xla_collectives``) as evidence
instead — nothing routed through the ledger, budget 0.

  python bench_multichip.py --devices 8 --strict-gate \
      --out MULTICHIP_PERF_r06.json
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time

#: strict-gate thresholds (ROADMAP / ISSUE 12 acceptance)
DP_EFFICIENCY_FLOOR = 0.8
CP_OVERHEAD_CEIL = 0.1
EP_OVERHEAD_CEIL = 0.1

_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter)(?:-start)?\(")


def _median(xs):
    return sorted(xs)[len(xs) // 2]


def _time_pipelined(fn, windows: int):
    """Seconds per window with every window's dispatch issued
    back-to-back and ONE completion barrier at the end — no per-wave
    host sync inside the timed region. ``fn()`` must return the
    dispatch's output (not block)."""
    import jax

    outs = []
    t0 = time.perf_counter()
    for _ in range(windows):
        outs.append(fn())
    jax.block_until_ready(outs)
    return (time.perf_counter() - t0) / windows


def _time_windows(fn, windows: int):
    """Median seconds over ``windows`` calls of fn() (fn blocks) —
    kept for compile warmup probes."""
    ts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return _median(ts)


def _hlo_collectives(compiled) -> list:
    """Collective instruction counts from a compiled module — the
    evidence rows for lanes whose collectives XLA inserts (no ledger
    routing). Degrades to [] when the AOT text is unavailable."""
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001 — backend without HLO text
        return []
    counts = {}
    for op in _HLO_COLLECTIVE_RE.findall(text):
        counts[op] = counts.get(op, 0) + 1
    return [{"op": op, "count": n, "source": "xla-hlo"}
            for op, n in sorted(counts.items())]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--rules", type=int, default=256)
    ap.add_argument("--flows-per-device", type=int, default=2048,
                    dest="flows_per_device")
    ap.add_argument("--windows", type=int, default=5)
    ap.add_argument("--platform", choices=("cpu", "native"),
                    default="cpu",
                    help="cpu = virtual host-device mesh (emulates "
                         "the sharding structure, not ICI); native = "
                         "whatever jax.devices() offers (v5e-8 etc.)")
    ap.add_argument("--strict-gate", action="store_true",
                    dest="strict_gate",
                    help=f"exit 1 unless DP efficiency >= "
                         f"{DP_EFFICIENCY_FLOOR}, CP overhead <= "
                         f"{CP_OVERHEAD_CEIL}, EP overhead <= "
                         f"{EP_OVERHEAD_CEIL}, and every declared "
                         f"collective budget holds")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    n = args.devices

    if args.platform == "cpu":
        from cilium_tpu.parallel.mesh import force_cpu_host_devices

        force_cpu_host_devices(n)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cilium_tpu.parallel.mesh import make_mesh

    devices = jax.devices()[:n]
    if len(devices) < n:
        print(json.dumps({"metric": "bench_failed_setup", "value": 0,
                          "unit": f"only {len(devices)} devices",
                          "vs_baseline": 0.0}))
        return 1

    from cilium_tpu.core.config import EngineConfig
    from cilium_tpu.engine.verdict import (
        CompiledPolicy,
        encode_flows,
        flowbatch_to_host_dict,
        verdict_step,
    )
    from cilium_tpu.ingest.synth import (
        realize_scenario,
        synth_http_scenario,
    )
    from cilium_tpu.parallel.collectives import LEDGER
    from cilium_tpu.parallel.sharding import (
        make_sharded_step,
        shard_flow_batch,
        shard_policy_arrays,
    )

    B = args.flows_per_device
    scenario = synth_http_scenario(n_rules=args.rules, n_flows=B)
    per_identity, scenario = realize_scenario(scenario)
    cfg = EngineConfig(bank_size=8)  # rules/8 banks: divisible by n
    policy = CompiledPolicy.build(per_identity, cfg)
    flows = list(scenario.flows)
    while len(flows) < B * n:
        flows = flows + flows
    host_full = flowbatch_to_host_dict(
        encode_flows(flows[:B * n], policy.kafka_interns, cfg))
    host_1 = {k: v[:B] for k, v in host_full.items()}

    points = []
    gate_failures = []
    rng = np.random.default_rng(0)

    def permuted(host, size):
        perm = rng.permutation(size)
        return {k: v[perm] for k, v in host.items()}

    def budget_check(lane: str, rows, budget: int):
        total = sum(int(r["count_per_block"]) for r in rows)
        if total > budget:
            gate_failures.append(
                f"{lane}: {total} ledger collectives/block exceeds "
                f"declared budget {budget}")
        return total

    # -- single-device baseline -------------------------------------------
    dev0 = devices[0]
    arrays_1 = {k: jax.device_put(v, dev0)
                for k, v in policy.arrays.items()}
    step_1 = jax.jit(verdict_step)
    batches_1 = [
        {k: jax.device_put(v, dev0)
         for k, v in permuted(host_1, B).items()}
        for _ in range(args.windows)]
    jax.block_until_ready(batches_1)
    jax.block_until_ready(step_1(arrays_1, batches_1[0]))  # compile

    it1 = iter(batches_1 * 2)
    t1 = _time_pipelined(lambda: step_1(arrays_1, next(it1)),
                         args.windows)
    vps_1 = B / t1
    points.append({"lane": "single_device", "devices": 1,
                   "verdicts_per_sec": round(vps_1, 1),
                   "per_device_vps": round(vps_1, 1)})

    # constant-silicon reference: the FULL B×n batch unsharded on one
    # logical device — t_sharded / t_unsharded_full at equal total
    # work isolates the sharding + collective overhead of the
    # partitioned program (the meaningful number on the emulated mesh)
    batches_full = [
        {k: jax.device_put(v, dev0)
         for k, v in permuted(host_full, B * n).items()}
        for _ in range(args.windows)]
    jax.block_until_ready(batches_full)
    jax.block_until_ready(step_1(arrays_1, batches_full[0]))
    itf = iter(batches_full * 2)
    t_full_1 = _time_pipelined(lambda: step_1(arrays_1, next(itf)),
                               args.windows)
    points.append({"lane": "single_device_full_batch", "devices": 1,
                   "batch": B * n,
                   "verdicts_per_sec": round(B * n / t_full_1, 1)})

    # -- DP / DP×EP (auto-partitioned) ------------------------------------
    def run_sharded(mesh, expert_axis, lane):
        # tables + batches staged ONCE with explicit NamedShardings —
        # replicated tensors stay device-resident across every window
        arrays_s = shard_policy_arrays(policy.arrays, mesh,
                                       expert_axis=expert_axis)
        step_s = make_sharded_step(mesh, "data")
        batches = []
        for _ in range(args.windows):
            batches.append(shard_flow_batch(
                permuted(host_full, B * n), mesh, "data"))
        jax.block_until_ready(batches)
        xla_rows = []
        try:
            compiled = step_s.lower(arrays_s, batches[0]).compile()
            xla_rows = _hlo_collectives(compiled)
        except Exception:  # noqa: BLE001 — AOT text is evidence only
            pass
        jax.block_until_ready(step_s(arrays_s, batches[0]))
        its = iter(batches * 2)
        t = _time_pipelined(lambda: step_s(arrays_s, next(its)),
                            args.windows)
        vps = B * n / t
        eff = vps / (n * vps_1)
        # nothing on this lane routes through the ledger: budget 0,
        # XLA's inserted collectives ride as separate evidence
        budget_check(lane, [], 0)
        points.append({
            "lane": lane, "devices": n,
            "mesh": dict(mesh.shape),
            "verdicts_per_sec": round(vps, 1),
            "per_device_vps": round(vps / n, 1),
            # vs n× the single-device-B rate — THE number on real
            # chips; on the cpu platform it mostly reflects that all
            # virtual devices share one CPU
            "weak_scaling_efficiency": round(eff, 6),
            # same total work, sharded vs unsharded on one device
            "constant_silicon_efficiency": round(t_full_1 / t, 6),
            "sharding_overhead_fraction": round(
                max(0.0, 1 - t_full_1 / t), 6),
            "collectives": [],
            "collective_budget_per_block": 0,
            "xla_collectives": xla_rows,
        })
        return points[-1]

    dp = run_sharded(make_mesh((n,), ("data",), devices), None, "dp")
    if n % 2 == 0 and n >= 4:
        run_sharded(make_mesh((n // 2, 2), ("data", "expert"),
                              devices), "expert", "dp_x_ep")

    # -- EP: one-shot all_to_all re-shard (parallel/ulysses.py) -----------
    from cilium_tpu.parallel.ulysses import (
        make_ep_verdict_step,
        stage_ep_arrays,
        stage_replicated,
    )

    ep_mesh = make_mesh((n,), ("expert",), devices)
    ep_arrays = stage_ep_arrays(policy.arrays, ep_mesh, "expert")
    ep_batches = [stage_replicated(permuted(host_full, B * n), ep_mesh)
                  for _ in range(args.windows)]
    jax.block_until_ready(ep_batches)
    ep_step = make_ep_verdict_step(ep_mesh, ep_arrays, ep_batches[0],
                                   "expert")
    LEDGER.reset()
    ep_out = ep_step(ep_arrays, ep_batches[0])
    jax.block_until_ready(ep_out)
    ep_rows = LEDGER.snapshot()
    LEDGER.publish_metrics()
    # parity spot-check rides the bench (cheap, and a wrong lane must
    # never publish a throughput number)
    ref_out = step_1(arrays_1, {
        k: jax.device_put(np.asarray(v), dev0)
        for k, v in ep_batches[0].items()})
    assert np.array_equal(np.asarray(ep_out["verdict"]),
                          np.asarray(ref_out["verdict"])), \
        "EP one-shot verdicts diverged from single-device"
    ite = iter(ep_batches * 2)
    t_ep = _time_pipelined(lambda: ep_step(ep_arrays, next(ite)),
                           args.windows)
    ep_overhead = max(0.0, 1 - t_full_1 / t_ep)
    ep_total = budget_check("ep", ep_rows, 1)
    points.append({
        "lane": "ep", "devices": n, "mesh": {"expert": n},
        "verdicts_per_sec": round(B * n / t_ep, 1),
        "per_device_vps": round(B * n / t_ep / n, 1),
        "weak_scaling_efficiency": round(
            (B * n / t_ep) / (n * vps_1), 6),
        "constant_silicon_efficiency": round(t_full_1 / t_ep, 6),
        "overhead_fraction": round(ep_overhead, 6),
        "collectives": ep_rows,
        "collective_count_per_block": ep_total,
        "collective_budget_per_block": 1,
        "note": "one-shot all_to_all between scan and match; banks "
                "sharded, inputs staged replicated once",
    })

    # -- CP: payload-sharded blockwise scan (parallel/cp.py) --------------
    from cilium_tpu.engine.dfa_kernel import dfa_scan_banked
    from cilium_tpu.parallel.cp import dfa_scan_banked_cp
    from cilium_tpu.policy.compiler.dfa import compile_patterns

    cp_pats = [".*attack-signature.*", ".*(GET|POST) /evil.*",
               ".*xx[0-9]{3}yy.*", ".*beacon[a-f0-9]{4}.*"]
    cp_arrs = compile_patterns(cp_pats, bank_size=8).stacked()
    CP_B, CP_L, CP_BLOCK = 64, 4096, 256
    cp_data = rng.integers(97, 123, size=(CP_B, CP_L), dtype=np.uint8)
    cp_data[0, CP_L // 2 - 8:CP_L // 2 + 8] = np.frombuffer(
        b"attack-signature", dtype=np.uint8)  # straddles a shard cut
    cp_lengths = np.full((CP_B,), CP_L, dtype=np.int32)
    cj = {k: jnp.asarray(v) for k, v in cp_arrs.items()}
    cdj, clj = jnp.asarray(cp_data), jnp.asarray(cp_lengths)

    scan_seq = jax.jit(dfa_scan_banked)
    jax.block_until_ready(scan_seq(cj["trans"], cj["byteclass"],
                                   cj["start"], cj["accept"], cdj, clj))
    t_seq_1 = _time_pipelined(lambda: scan_seq(
        cj["trans"], cj["byteclass"], cj["start"], cj["accept"],
        cdj, clj), args.windows)

    # equal-work single-device reference: the SAME blockwise
    # composition on a 1-device mesh — isolates sharding+collective
    # cost from the composition's S-wide work inflation
    mesh_cp1 = make_mesh((1,), ("seq",), devices[:1])
    jax.block_until_ready(dfa_scan_banked_cp(
        mesh_cp1, cj["trans"], cj["byteclass"], cj["start"],
        cj["accept"], cdj, clj, block=CP_BLOCK))
    t_block_1 = _time_pipelined(lambda: dfa_scan_banked_cp(
        mesh_cp1, cj["trans"], cj["byteclass"], cj["start"],
        cj["accept"], cdj, clj, block=CP_BLOCK), args.windows)

    mesh_cp = make_mesh((n,), ("seq",), devices)
    LEDGER.reset()
    cp_words = dfa_scan_banked_cp(
        mesh_cp, cj["trans"], cj["byteclass"], cj["start"],
        cj["accept"], cdj, clj, block=CP_BLOCK)
    jax.block_until_ready(cp_words)
    cp_rows = LEDGER.snapshot()
    LEDGER.publish_metrics()
    assert np.array_equal(
        np.asarray(cp_words),
        np.asarray(scan_seq(cj["trans"], cj["byteclass"], cj["start"],
                            cj["accept"], cdj, clj))), \
        "CP scan diverged from the sequential reference"
    t_cp = _time_pipelined(lambda: dfa_scan_banked_cp(
        mesh_cp, cj["trans"], cj["byteclass"], cj["start"],
        cj["accept"], cdj, clj, block=CP_BLOCK), args.windows)
    cp_overhead = max(0.0, 1 - t_block_1 / t_cp)
    cp_total = budget_check("cp", cp_rows, 1)
    points.append({
        "lane": "cp", "devices": n, "mesh": {"seq": n},
        "scan_batch": CP_B, "payload_len": CP_L,
        "cp_block": CP_BLOCK,
        "sequential_single_device_s": round(t_seq_1, 6),
        "blockwise_single_device_s": round(t_block_1, 6),
        "cp_s": round(t_cp, 6),
        "strong_scaling_speedup": round(t_seq_1 / t_cp, 6),
        "strong_scaling_efficiency": round(t_seq_1 / t_cp / n, 6),
        # sharded vs the same blockwise math on one device — the
        # collective + partitioning cost, nothing else
        "overhead_fraction": round(cp_overhead, 6),
        # what the blockwise identity costs vs the sequential scan at
        # constant silicon (the S-wide composition gathers) — on a
        # real mesh this amortizes over n devices, here it is honesty
        "blockwise_work_inflation": round(t_block_1 / t_seq_1, 6),
        "collectives": cp_rows,
        "collective_count_per_block": cp_total,
        "collective_budget_per_block": 1,
        "note": "payload-sharded blockwise scan; ONE carry exchange "
                "per block (TP pays one psum per scanned byte)",
    })

    # -- TP (state-axis sharding; the states-don't-fit fallback) ----------
    from cilium_tpu.parallel.tp import dfa_scan_banked_tp, pad_states

    pats = [f"/api/v{i}[0-9]*" for i in range(24)] + [
        "/health", "/metrics", "abc+", "x.y",
        "/users/[0-9]+", "/orders/.*", "do.t", "[a-f]+42"]
    arrs = compile_patterns(pats, bank_size=2).stacked()
    SB = 64 * n
    data = rng.integers(0, 128, size=(SB, 64), dtype=np.uint8)
    lengths = np.full((SB,), 64, dtype=np.int32)
    j = {k: jnp.asarray(v) for k, v in arrs.items()}
    dj = jnp.asarray(data)
    lj = jnp.asarray(lengths)
    scan_1 = jax.jit(dfa_scan_banked)
    jax.block_until_ready(scan_1(j["trans"], j["byteclass"],
                                 j["start"], j["accept"], dj, lj))
    t_scan1 = _time_windows(lambda: jax.block_until_ready(
        scan_1(j["trans"], j["byteclass"], j["start"], j["accept"],
               dj, lj)), args.windows)

    tp_mesh = make_mesh((n,), ("state",), devices)
    trans_p, accept_p = pad_states(arrs["trans"], arrs["accept"], n)
    tpj, apj = jnp.asarray(trans_p), jnp.asarray(accept_p)
    LEDGER.reset()
    jax.block_until_ready(dfa_scan_banked_tp(
        tp_mesh, tpj, j["byteclass"], j["start"], apj, dj, lj))
    tp_collectives = LEDGER.snapshot()
    LEDGER.publish_metrics()
    t_tp = _time_windows(lambda: jax.block_until_ready(
        dfa_scan_banked_tp(tp_mesh, tpj, j["byteclass"], j["start"],
                           apj, dj, lj)), args.windows)
    speedup = t_scan1 / t_tp
    points.append({
        "lane": "tp", "devices": n, "mesh": {"state": n},
        "scan_batch": SB,
        "single_device_s": round(t_scan1, 6),
        "tp_s": round(t_tp, 6),
        # 6 decimals: the r05 artifact rounded this to a useless 0.0
        "strong_scaling_speedup": round(speedup, 6),
        "strong_scaling_efficiency": round(speedup / n, 6),
        "overhead_fraction": round(max(0.0, 1 - speedup / n), 6),
        # the ledger's per-collective account: op kind, count per
        # block (the scan body's psum executes once per scanned
        # byte), bytes per call — evidence, not vibes. No budget is
        # declared: per-byte is this lane's documented contract, and
        # parallel/cp.py is the throughput lane that replaced it.
        "collectives": tp_collectives,
        "note": "state-axis fallback lane; collective per byte — "
                "use the cp lane unless states exceed one chip",
    })

    # -- headline + gates --------------------------------------------------
    if args.platform == "cpu":
        dp_eff = dp["constant_silicon_efficiency"]
        value = dp_eff
        unit = ("DP constant-silicon efficiency (sharded vs unsharded "
                "at equal total work; virtual cpu mesh)")
    else:
        dp_eff = dp["weak_scaling_efficiency"]
        value = dp_eff
        unit = "DP weak-scaling efficiency vs single device"
    if dp_eff < DP_EFFICIENCY_FLOOR:
        gate_failures.append(
            f"dp: efficiency {dp_eff} < {DP_EFFICIENCY_FLOOR}")
    if cp_overhead > CP_OVERHEAD_CEIL:
        gate_failures.append(
            f"cp: overhead_fraction {round(cp_overhead, 6)} > "
            f"{CP_OVERHEAD_CEIL}")
    if ep_overhead > EP_OVERHEAD_CEIL:
        gate_failures.append(
            f"ep: overhead_fraction {round(ep_overhead, 6)} > "
            f"{EP_OVERHEAD_CEIL}")

    line = {
        "metric": f"multichip_weak_scaling_{n}dev",
        "value": value,
        "unit": unit,
        "vs_baseline": 0.0,
        "platform": args.platform,
        "flows_per_device": B,
        "rules": args.rules,
        "points": points,
        "gates": {
            "dp_efficiency": dp_eff,
            "dp_efficiency_floor": DP_EFFICIENCY_FLOOR,
            "cp_overhead_fraction": round(cp_overhead, 6),
            "cp_overhead_ceil": CP_OVERHEAD_CEIL,
            "ep_overhead_fraction": round(ep_overhead, 6),
            "ep_overhead_ceil": EP_OVERHEAD_CEIL,
            "failures": gate_failures,
        },
    }
    # provenance fingerprint (perf ledger): perf-report classifies
    # cross-round deltas off this
    from cilium_tpu.runtime.provenance import stamp

    stamp(line)
    print(json.dumps(line), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(line, f, indent=1)
    if args.strict_gate and gate_failures:
        print("bench-multichip: GATE FAILED — "
              + "; ".join(gate_failures), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
