# CI lanes (SURVEY §4/§5.2). No pip/apt — everything runs from the
# baked environment at the repo root.

PY ?= python

.PHONY: test shim lint precommit determinism dryrun chaos obs soak churn \
        churn-fleet churn-fleet-smoke dst dst-validate serve-soak \
        serve-fleet serve-fleet-smoke canary canary-smoke \
        bench bench-all bench-e2e bench-service bench-regen bench-sp \
        bench-stage bench-stream bench-kernel bench-multichip \
        bench-protocols bench-watch perf-report check

test:            ## full suite (CPU, virtual 8-device mesh via conftest)
	$(PY) -m pytest tests/ -q

shim:            ## build the C++ proxylib-ABI shim
	$(MAKE) -C shim

# lint: ctlint codebase-aware static analysis (cilium_tpu/analysis —
# jit-purity, lock-order, registry consistency, swallowed exceptions,
# unused imports, the v2 dataflow families: shape-dtype,
# recompile-hazard, abi-surface, config-surface, the v3
# thread-safety family: guarded-field inference, check-then-act,
# lock-release windows, publication safety, plus the v4
# device-dataflow family: implicit-sync, hot-loop-h2d,
# readback-ordering, missing-donation over the serving hot path's
# residency lattice). Fails on any non-allowlisted finding;
# CTLINT.json is the CI report artifact (schema 4: findings
# byte-stable for a clean tree + timings_ms + racing-root and
# device-residency attribution). Rules run on a thread pool; the
# --wall-budget-ms gate (2x the v4 warm tree-wide baseline) keeps
# the lint lane's latency honest. Catalog: docs/ANALYSIS.md
lint:            ## ctlint static-analysis gate
	$(PY) -m cilium_tpu.analysis --format text --out CTLINT.json \
	    --wall-budget-ms 40000

# the pre-commit face: thread-safety + device-dataflow findings on
# changed files only — the two rule families whose hazards are
# cheapest to introduce in a hot-path edit and costliest to ship;
# fast enough (two families, changed-paths filter) to run on every
# commit without the full lint lane's latency
precommit:       ## changed-files thread-safety + device-dataflow lint
	$(PY) -m cilium_tpu.cli lint --rule thread-safety \
	    --rule implicit-sync --rule hot-loop-h2d \
	    --rule readback-ordering --rule missing-donation \
	    --changed-only

determinism:     ## deterministic-compile + debug_nans sanitizer lane
	$(PY) -m pytest tests/test_determinism.py -q

# chaos: golden corpus replayed under injected device failures /
# stream drops / mid-swap crashes (runtime/faults.py) — seeded and
# deterministic; marked slow so tier-1 timing never pays for it
chaos:           ## seeded fault-injection replay lane
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_faults.py -q -m chaos

# obs: flight-recorder tracing + metrics exposition tests, then a
# scrape-lint — expose the LIVE registry (after the tests populated
# it) and assert the Prometheus text parses with zero malformed lines
obs:             ## observability lane: tracing tests + scrape lint
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_tracing.py \
	    tests/test_observability.py tests/test_provenance.py \
	    tests/test_explain.py -q -m "not slow"
	JAX_PLATFORMS=cpu $(PY) -c "\
	from cilium_tpu.runtime.metrics import METRICS, lint_exposition; \
	METRICS.inc('cilium_tpu_scrape_lint_total'); \
	METRICS.observe('cilium_tpu_scrape_lint_seconds', 0.01); \
	text = METRICS.expose(); errs = lint_exposition(text); \
	assert not errs, errs; \
	print('scrape-lint OK:', len(text.splitlines()), 'lines')"

# soak: short synthetic overload (4× saturation) against the
# admission-controlled batcher path — asserts shed > 0 with the queue
# depth bounded at max_pending and admitted-request p99 within 2× the
# unloaded p99 (ISSUE 5 acceptance). Marked slow+soak so tier-1
# timing never pays for it.
# -s: the virtual-time fixture prints the simulated-vs-wall speedup
# on the lane output (ISSUE 10 — the lane now simulates its service
# times on an autojumping VirtualClock; one real-clock smoke stays)
soak:            ## synthetic-overload admission/shed lane
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_soak.py -q -s \
	    -m "soak and not churn and not serve"

# serve-soak: the ISSUE-11 acceptance lane — the DST load model
# (runtime/loadmodel.py) drives >=100k CONCURRENT virtual streams
# (heavy-tailed arrivals, diurnal swing, reconnect storms, seeded
# serve.lease/serve.ring_slot faults) through the continuously-
# batched serving loop (runtime/serveloop.py + engine/ring.py) under
# the autojumping VirtualClock, with lease-accounting / sampled-
# correctness / memo-honesty / explanation-decode invariants checked
# after every event.
# Gates: 0 violations, concurrency peak >= 95k, p99 <= 2x unloaded,
# shed rate bounded, memo-bypass bytes > 0, explanation coverage
# >= 0.999 of served verdicts, and declared-SLO burn rates <= 1.0
# over the whole-run window (ISSUE 14). One provenance-stamped
# line lands in BENCH_SERVE_r07.jsonl (consumed by perf-report).
serve-soak:      ## 100k-virtual-stream continuous-batching soak
	JAX_PLATFORMS=cpu $(PY) -m cilium_tpu.runtime.loadmodel \
	    --streams 100000 --out BENCH_SERVE_r07.jsonl

# serve-fleet: the ISSUE-16 acceptance lane — the DST fleet model
# (runtime/fleetserve.py) drives >=1M concurrent virtual streams
# across >=4 simulated hosts (each a real ServeLoop + ring + session
# over bank artifacts shared via the artifact store) behind the
# stream-affinity router, with mid-storm host KILL / partition /
# drain-restart / warm rejoin and seeded fleet.heartbeat +
# fleet.handoff faults. Gates: 0 invariant violations (fleet-exact
# lease books, lease conservation, sampled correctness + explanation
# honesty at the CITED generation), aggregate p99 <= 2x the committed
# single-host serve-soak baseline, shed rate <= 2%, zero survivor
# recompiles + a zero-compile warm restore on every rejoin, and zero
# unrecovered streams across the failovers. ISSUE 17 arms the fleet
# observability gates on the same run: >=400 handoffs with >=99%
# cross-host trace-stitch coverage, a non-empty merged Hubble flow
# export, a consistent fleet event journal, and observability
# overhead <= 2% of wall time.
serve-fleet:     ## 1M-stream serving fleet: failover + shedding soak
	JAX_PLATFORMS=cpu $(PY) -m cilium_tpu.runtime.fleetserve \
	    --streams 1050000 --hosts 4 --out BENCH_FLEET_SERVE_r08.jsonl

# the smoke face of the same driver — small enough for `make check`;
# the p99 gate stays off (tiny runs are all fixed overhead) and the
# handoff floor drops to 1 (a 60-virtual-second run can't stage 400
# failovers) but every failover/conservation/honesty gate — and the
# journal/books-consistency + stitch-coverage + flow-export +
# obs-overhead gates — is armed
serve-fleet-smoke: ## serving-fleet driver at check-sized smoke scale
	JAX_PLATFORMS=cpu $(PY) -m cilium_tpu.runtime.fleetserve \
	    --streams 2000 --hosts 4 --virtual-s 60 --storm-size 200 \
	    --no-p99-gate --min-handoffs 1 \
	    --out /tmp/BENCH_FLEET_SERVE_smoke.jsonl

# canary: the ISSUE-20 acceptance lane — shadow/canary policy rollout
# through a live ServeLoop (runtime/canary.py): stage a PLANTED bad
# generation (every verdict flipped to deny) as N+1 beside serving N,
# double-dispatch a sampled fraction of ring traffic through both
# engines in the same pack cycle, and prove the verdict-diff gate
# REFUSES the commit before a single bad verdict is served; then a
# clean rollout through the same pipeline must commit. Gates:
# diff_caught + serving_untouched + clean_committed + clean_verdicts
# + sampled, and double-dispatch overhead <= 5% of pack-cycle wall.
# One provenance-stamped line lands in BENCH_CANARY_r09.jsonl
# (consumed by perf-report, whose canary-budget gate holds the
# declared budget across rounds).
canary:          ## shadow-rollout verdict-diff gate + overhead budget
	JAX_PLATFORMS=cpu $(PY) -m cilium_tpu.runtime.canary \
	    --out BENCH_CANARY_r09.jsonl

# the smoke face of the same driver — small enough for `make check`;
# every gate stays armed (the lane is virtual-time cheap already)
canary-smoke:    ## canary rollout driver at check-sized smoke scale
	JAX_PLATFORMS=cpu $(PY) -m cilium_tpu.runtime.canary \
	    --chunks 48 --pool-chunks 12 \
	    --out /tmp/BENCH_CANARY_smoke.jsonl

# churn: the ISSUE-8 acceptance soak — sustained CNP add/delete +
# FQDN pattern churn through a live replay session across ≥50
# committed policy updates. Asserts zero ERROR verdicts and zero
# stale-allow/stale-deny vs the serving engine + sampled CPU oracle,
# bank-scoped compile work (O(Δ), not O(policy×updates)), and a
# steady-state memo hit ratio ≥0.99. Writes a provenance-stamped
# update→enforcement p99 bench line consumed by perf-report.
# CILIUM_TPU_DST_SEED: the lane's driving seed rides the bench line's
# provenance stamp (runtime/provenance.dst_stamp) so perf-report can
# tie an update-latency regression to the schedule that exposed it
churn:           ## sustained policy-churn soak (bank-scoped compile)
	JAX_PLATFORMS=cpu \
	CILIUM_TPU_CHURN_BENCH_OUT=BENCH_CHURN_r06.jsonl \
	CILIUM_TPU_DST_SEED=8 \
	$(PY) -m pytest tests/test_soak.py -q -m churn

# churn-fleet: the ISSUE-13 acceptance lane — BASELINE configs[4]
# scale (10k identities x 5k CNP over ~200 service classes) driven as
# a churn storm through one live Loader + replay session by
# runtime/fleet.py. Gates: zero stale/ERROR verdicts vs the serving
# engine + sampled oracle, bank compiles/update <= 1.1x the 27-bank
# churn ratio (O(Δ) survives two orders of magnitude more policy),
# update->enforcement p99 <= 2x the committed BENCH_CHURN_r06 number,
# and peak RSS under the declared bound (sharded registry +
# fingerprint store + artifact-cache LRU). One provenance-stamped
# line lands in BENCH_CHURN_FLEET_r07.jsonl (consumed by perf-report).
churn-fleet:     ## fleet-scale churn storm (10k ids x 5k CNP)
	JAX_PLATFORMS=cpu $(PY) -m cilium_tpu.runtime.fleet \
	    --identities 10000 --cnps 5000 --updates 56 \
	    --out BENCH_CHURN_FLEET_r07.jsonl

# the smoke face of the same driver — small enough for `make check`;
# the p99 gate stays off (the 27-bank baseline is not comparable at
# smoke scale) but every correctness gate is armed
churn-fleet-smoke: ## fleet churn driver at check-sized smoke scale
	JAX_PLATFORMS=cpu $(PY) -m cilium_tpu.runtime.fleet \
	    --identities 1000 --cnps 500 --updates 10 --no-p99-gate

# dst: deterministic simulation testing (runtime/dst.py) — seeded
# fault-SCHEDULE search under virtual time (runtime/simclock.py):
# each seed is a schedule of fault arms / policy churn / identity
# storms / drain-restore cycles / time advances against a real
# Loader+engine+breaker+session world, with standing invariants
# (oracle agreement, fail-closed, session/memo honesty, O(Δ) compile,
# breaker+quarantine liveness) checked after every event. The same
# CILIUM_TPU_DST_SEED replays byte-identically; a violation is
# delta-debugged to a minimal schedule under tests/dst/regressions/.
dst:             ## seeded fault-schedule search (DST) lane
	JAX_PLATFORMS=cpu $(PY) -m cilium_tpu.runtime.dst \
	    --schedules 200 --shrink --out BENCH_DST_r06.jsonl

# dst-validate: planted-bug proof — re-introduce a known FIXED bug
# behind the mutation flag and show the schedule search catches and
# shrinks it within a bounded seed budget (both known mutations).
dst-validate:    ## planted-bug validation of the DST searcher
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/dst/test_planted.py -q

dryrun:          ## driver multi-chip contract on a virtual CPU mesh
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as ge; ge.dryrun_multichip(8); \
	fn, a = ge.entry(); jax.block_until_ready(jax.jit(fn)(*a)); \
	print('entry OK')"

bench:           ## headline config on the attached accelerator
	$(PY) bench.py --config http --check

bench-all:       ## every BASELINE config, one JSON line each
	$(PY) bench.py --config all

bench-e2e:       ## file→verdict replay of a stored v2 Hubble capture
	$(PY) bench.py --config http --from-capture /tmp/ct_bench_capture.bin

bench-service:   ## socket→MicroBatcher→engine tail latency sweep
	$(PY) bench_service.py --shim --out SERVICE_LATENCY.json

bench-regen:     ## cold vs incremental vs restage regeneration latency
	$(PY) bench.py --config regen

bench-sp:        ## SP (associative-scan) vs sequential payload scan
	$(PY) bench_sp.py

# bench-stage: the fast staging microbench — columnar capture write +
# CaptureReplay session staging (tables/featurize/dedup/h2d phase
# split) + verdict-memo fill, one provenance-stamped line per lane.
# The cold stage_ms is the number the ISSUE-7 ≥10× budget tracks.
bench-stage:     ## capture→session staging microbench (phase split)
	$(PY) bench_stage.py

# bench-kernel: the megakernel microbench — fused verdict step (one
# dispatch) vs the three-op mapstate/scan/resolve path at the 1k-rule
# config, plus the per-bank-shape dense-DFA vs bitset-NFA autotune
# sweep. Provenance-stamped lines land in BENCH_KERNEL_r06.jsonl for
# perf-report; the lane FAILS (strict gate) if the fused speedup
# drops below 2x — the ROADMAP megakernel target.
bench-kernel:    ## fused megakernel vs three-op path + impl sweep
	$(PY) bench_kernel.py --min-speedup 2.0 --out BENCH_KERNEL_r06.jsonl

bench-stream:    ## online serving path: chunked binary stream transport
	$(PY) bench_service.py --stream --stream-only --rules 1000 \
	    --stream-chunk 16384 --stream-depth 16 \
	    --out SERVICE_LATENCY_stream.json

# bench-multichip: every §2.6 lane on the virtual 8-device mesh —
# DP (batch-sharded), DPxEP (auto-partitioned comparison), EP
# (one-shot all_to_all re-shard), CP (payload-sharded blockwise scan,
# one carry exchange per block), TP (state-axis fallback). STRICT
# gate (ISSUE 12): fails if DP constant-silicon efficiency < 0.8, CP
# or EP overhead_fraction > 0.1, or any lane records more ledger
# collectives per compiled block than the budget it declares on the
# line. The provenance-stamped artifact feeds perf-report, whose
# collective-budget gate holds the declared budgets across rounds.
bench-multichip: ## DP/EP/CP/TP scaling + collective-budget gate
	JAX_PLATFORMS=cpu $(PY) bench_multichip.py --devices 8 \
	    --flows-per-device 1024 --strict-gate \
	    --out MULTICHIP_PERF_r06.json

# bench-protocols: the ISSUE-15 lane — per-protocol verdict
# throughput for the frontend families (cassandra/memcache/r2d2 +
# the mixed protocols scenario, with an in-process http reference),
# each lane oracle-checked, plus the cross-cluster leg: a 50-update
# remote-identity churn storm streamed through clustermesh into the
# serving loader, gated on ZERO stale/ERROR verdicts and
# update->enforcement p99 <= 2x the committed single-cluster churn
# number. Provenance-stamped lines land in BENCH_PROTO_r07.jsonl
# (consumed by perf-report).
bench-protocols: ## frontend-family throughput + cross-cluster churn
	JAX_PLATFORMS=cpu $(PY) bench_protocols.py --updates 50 \
	    --out BENCH_PROTO_r07.jsonl

bench-watch:     ## probe until the tunnel answers, then capture the sweep
	$(PY) bench.py --watch r04

# perf-report: schema-validate every BENCH_*/MULTICHIP_*/SERVICE_*
# artifact, normalize them into the round trajectory
# (PERF_TRAJECTORY.json — the CI artifact), classify round-over-round
# deltas as code regression vs environment change (provenance/RTT
# evidence), and fail on an unexplained regression in the newest round
perf-report:     ## bench trajectory + regression gate
	$(PY) -m cilium_tpu.perf_report --root . --out PERF_TRAJECTORY.json

check: shim lint test determinism dryrun obs churn-fleet-smoke serve-fleet-smoke canary-smoke bench-multichip perf-report   ## the full CI gate
