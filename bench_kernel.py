#!/usr/bin/env python
"""Megakernel microbench: fused verdict step vs the three-op path,
plus the per-bank-shape dense-DFA vs bitset-NFA sweep.

The lane behind ``make bench-kernel``: where ``bench.py`` buries the
verdict step inside a full e2e run, this bench isolates exactly what
the MXU-native megakernel (``engine/megakernel.py``) changed:

* **headline lane** — the 1k-rule config's verdict step, measured two
  ways over distinct permuted device copies: the THREE-OP path
  (mapstate → scan → resolve as three separately-jitted,
  completion-forced dispatches — the pre-megakernel execution shape,
  the same decomposition ``EnginePhaseProbe`` attributes) vs the
  FUSED megakernel (one dispatch). The line carries both rates, the
  speedup, p50/p99 per batch for each path, the engine's kernel plan
  (autotune picks per field/bank shape), and the resolve-plan group
  count. ``--min-speedup`` (the strict-mode gate; default 2.0 per the
  ROADMAP target) fails the lane when the fused step stops paying.
* **shape sweep** — dense vs bitset-NFA measured per synthetic bank
  shape through the SAME autotuner the engine uses
  (``megakernel.autotune_field``): a literal-heavy bank (small DFA,
  small NFA), a state-explosion bank (alternation/wildcard-heavy:
  the regime the NFA arm exists for), and a wide dense bank. One
  provenance-stamped line per shape with both timings and the pick.

Every line is ``bench_schema``-stamped so ``cilium-tpu perf-report``
trends them and its regression gate covers the device-lane
verdicts/s trajectory.

Usage: python bench_kernel.py [--config http] [--rules 1000]
       [--flows 8192] [--min-speedup 2.0] [--out BENCH_KERNEL.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _percentile(sorted_times, q: float) -> float:
    i = min(len(sorted_times) - 1, int(len(sorted_times) * q))
    return sorted_times[i]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="http",
                    choices=["http", "fqdn", "kafka"])
    ap.add_argument("--rules", type=int, default=1000)
    ap.add_argument("--flows", type=int, default=8192)
    ap.add_argument("--reps", type=int, default=7)
    ap.add_argument("--min-speedup", type=float, default=2.0,
                    help="strict gate: fail when fused/three-op falls "
                         "below this (0 disables)")
    ap.add_argument("--out", default=None,
                    help="also append the JSON lines here")
    ap.add_argument("--skip-sweep", action="store_true")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    def log(msg: str) -> None:
        if args.verbose:
            print(msg, file=sys.stderr)

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax
    import numpy as np

    from cilium_tpu.core.config import Config
    from cilium_tpu.engine import megakernel
    from cilium_tpu.engine.phases import (
        _force,
        _live_mapstate,
        _live_resolve,
        _live_scan,
        _timed,
    )
    from cilium_tpu.engine.verdict import (
        encode_flows,
        flowbatch_to_host_dict,
    )
    from cilium_tpu.ingest import synth
    from cilium_tpu.runtime.loader import Loader
    from cilium_tpu.runtime.provenance import stamp

    cfg = Config.from_env()
    cfg.enable_tpu_offload = True

    per_identity, scenario = synth.realize_scenario(
        synth.scenario_by_name(args.config, args.rules, args.flows))
    loader = Loader(cfg)
    t0 = time.perf_counter()
    engine = loader.regenerate(per_identity, revision=1)
    log(f"policy staged in {time.perf_counter() - t0:.2f}s; "
        f"impl plan {engine.impl_plan}")

    host = flowbatch_to_host_dict(encode_flows(
        scenario.flows, engine.policy.kafka_interns, cfg.engine))
    arrays = engine._arrays
    _ms = jax.jit(_live_mapstate)
    _scan = jax.jit(_live_scan)
    _res = jax.jit(_live_resolve)
    fused = engine._step

    # distinct permuted device copies per timed call (bench.py
    # methodology: no caching layer may shortcut repeats)
    prng = np.random.default_rng(0)
    n = len(scenario.flows)

    def copies(k):
        out = []
        for _ in range(k):
            perm = prng.permutation(n)
            out.append({k2: jax.device_put(v[perm])
                        for k2, v in host.items()})
        jax.block_until_ready(out)
        return out

    warm = copies(1)[0]
    # compile both paths off the clock
    _timed(lambda: fused(arrays, warm), 1)

    def three_op(batch):
        m = _ms(arrays, batch)
        _force(m)
        w = _scan(arrays, batch)
        _force(w)
        return _res(arrays, m, w, batch)

    _timed(lambda: three_op(warm), 1)

    def run(step):
        batches = copies(args.reps)
        times = []
        for b in batches:
            t0 = time.perf_counter()
            _force(step(b))
            times.append(time.perf_counter() - t0)
        times.sort()
        return times

    t_three = run(three_op)
    t_fused = run(lambda b: fused(arrays, b))
    fused_p50 = t_fused[len(t_fused) // 2]
    three_p50 = t_three[len(t_three) // 2]
    fused_vps = n / fused_p50
    three_vps = n / three_p50
    speedup = three_p50 / fused_p50
    log(f"three-op {three_p50 * 1e3:.1f}ms ({three_vps:,.0f} vps)  "
        f"fused {fused_p50 * 1e3:.1f}ms ({fused_vps:,.0f} vps)  "
        f"{speedup:.2f}x")

    groups = (engine.policy.resolve_meta or {}).get("groups")
    lines = [{
        "metric": (f"kernel_fused_verdicts_per_sec_{args.config}_"
                   f"{args.rules}rules"),
        "value": round(fused_vps, 1),
        "unit": "verdicts/s (fused megakernel, per-batch forced)",
        "vs_baseline": round(fused_vps / 10e6, 4),
        "batch": n,
        "separate_op_verdicts_per_sec": round(three_vps, 1),
        "fused_speedup": round(speedup, 3),
        "fused_p50_ms": round(fused_p50 * 1e3, 3),
        "fused_p99_ms": round(_percentile(t_fused, 0.99) * 1e3, 3),
        "three_op_p50_ms": round(three_p50 * 1e3, 3),
        "three_op_p99_ms": round(_percentile(t_three, 0.99) * 1e3, 3),
        "fused_dispatches": 1,
        "three_op_dispatches": 3,
        "resolve_groups": groups,
        "impl_plan": dict(engine.impl_plan),
        "kernel_report": engine.kernel_report,
    }]

    # ---- per-bank-shape dense vs bitset-NFA sweep ----------------------
    if not args.skip_sweep:
        from cilium_tpu.core.config import EngineConfig
        from cilium_tpu.engine import nfa_kernel
        from cilium_tpu.policy.compiler.dfa import compile_patterns

        shapes = {
            # literal-heavy: tiny DFA and tiny NFA — gather's home turf
            "literal": ([f"/svc{i}/get" for i in range(24)], 8),
            # state-explosion regime: .* prefixes multiply DFA subsets
            # while the position count stays the pattern length sum
            "explosion": ([f"a.*{c}x[0-9]z" for c in "bcdefgh"], 7),
            # wide dense bank: many classes, mid-size DFA
            "wide": ([f"/api/v{i}/[a-z]+/{i}(/.*)?"
                      for i in range(16)], 4),
        }
        ecfg = EngineConfig()
        for name, (pats, bank_size) in shapes.items():
            banked = compile_patterns(pats, bank_size=bank_size)
            st = banked.stacked()
            arrays_s = {f"sweep_{k}": jax.device_put(v)
                        for k, v in st.items()}
            banks = nfa_kernel.banks_from_dfa(banked, ecfg)
            nfa_stacked = (nfa_kernel.stack_nfa_banks(banks)
                           if banks is not None else None)
            report = megakernel.autotune_field(
                f"sweep-{name}", arrays_s, "sweep", nfa_stacked,
                width=32, interpret=jax.default_backend() != "tpu")
            log(f"sweep {name}: {report}")
            lines.append({
                "metric": f"kernel_scan_sweep_{name}",
                "value": report["dense_ms"],
                "unit": "ms (dense arm, 256x32 probe batch)",
                "vs_baseline": 0.0,
                "dense_ms": report["dense_ms"],
                "nfa_ms": report["nfa_ms"],
                "impl": report["impl"],
                "dfa_states": int(st["trans"].shape[1]),
                "nfa_positions": (
                    int(nfa_stacked["nfa_follow"].shape[1])
                    if nfa_stacked is not None else None),
                "patterns": len(pats),
            })

    out_fp = open(args.out, "a") if args.out else None
    for line in lines:
        stamp(line)
        text = json.dumps(line)
        print(text, flush=True)
        if out_fp:
            out_fp.write(text + "\n")
    if out_fp:
        out_fp.close()

    if args.min_speedup and speedup < args.min_speedup:
        print(f"bench-kernel GATE FAILED: fused speedup {speedup:.2f}x "
              f"< {args.min_speedup}x", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
