"""Incremental verdict session: CaptureReplay's dedup machinery,
re-built for ONLINE streams.

Offline replay (engine.verdict.CaptureReplay) beats the host↔device
transport by staging a capture's string tables on device once and
streaming 2–4 bytes per flow (unique-row ids). An online stream has no
"whole capture" to stage — chunks keep arriving with fresh string
tables — but live traffic has the same statistical shape: strings and
15-tuples repeat heavily. This class makes the dedup INCREMENTAL:

* per-field session string tables grow as new strings appear; only the
  NEW strings are DFA-scanned on device (a delta scan +
  ``dynamic_update_slice`` into the staged match-word table) — the
  reference's per-string regex LRU (``pkg/fqdn/re``), as a growing
  device-resident table;
* a session unique-row table grows the same way; each chunk ships as
  int32 row ids (4 B/flow) + whatever delta rows/strings are new;
* steady state (no new strings/rows) a chunk's H2D is JUST the id
  stream — measured 244 B/flow (raw featurized blob) → 4 B/flow, which
  is the difference between ~60k/s and >1M/s through the ~10–30 MB/s
  tunneled transport (docs/PLATFORM.md round-5 notes).

Capacity is bounded: when the row table or a string table would
exceed its cap, the session RESETS (drops all tables and re-interns
from scratch) — the same "dedup must pay for itself" trade
``CaptureReplay.stage_unique`` makes with its ratio guard, expressed
as an eviction policy an unbounded stream needs.

Verdicts are bit-identical to ``VerdictEngine.verdict_l7_records``
(pinned by tests/test_incremental_session.py's differential).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.engine.dfa_kernel import dfa_scan_banked
from cilium_tpu.engine.memo import (
    VerdictMemo,
    auth_signature,
    hash_rows,
    memo_pack,
)
from cilium_tpu.engine.verdict import (
    _ROW_COLS,
    _gen_intern_rows,
    _gen_l7g_cols,
    verdict_step_capture,
)
from cilium_tpu.core.flow import TrafficDirection

#: session caps: beyond these the dedup tables stop paying for
#: themselves (high-cardinality traffic) and the session re-interns
MAX_ROWS = 1 << 18
MAX_STRINGS = 1 << 16

_FIELDS = ("path", "method", "host", "headers", "qname")
#: row-column index of the L7 type (the family key of the
#: bank-reference invalidation narrowing)
_L7_COL = _ROW_COLS.index("l7_types")
_DPORT_COL = _ROW_COLS.index("dports")
_PREFIX = {"path": "path", "method": "method", "host": "host",
           "headers": "hdr", "qname": "dns", "l7g": "l7g"}


def _pow2(n: int, floor: int = 256) -> int:
    return max(floor, 1 << max(0, n - 1).bit_length())


@functools.partial(jax.jit, donate_argnums=(4,))
def _delta_scan_update(trans, byteclass, start, accept, table,
                       data, lens, valid, offset):
    """Scan a (padded) delta of new strings through one field's banked
    DFA and splice the match words into the session table at
    ``offset``. Donating ``table`` lets XLA update in place — the
    table is device-resident state, not a per-call transfer."""
    words = dfa_scan_banked(trans, byteclass, start, accept, data, lens)
    flat = words.reshape(data.shape[0], -1)
    flat = jnp.where(valid[:, None], flat, 0)
    return jax.lax.dynamic_update_slice(
        table, flat.astype(table.dtype), (offset, 0))


@functools.partial(jax.jit, donate_argnums=(0,))
def _delta_rows_update(table, rows, offset):
    return jax.lax.dynamic_update_slice(table, rows, (offset, 0))


class _StringTable:
    """One field's session string table: host dict + device match
    words, delta-scanned on growth."""

    def __init__(self, engine, field: str, width: int):
        self.engine = engine
        self.field = field
        self.width = width
        self.ids: Dict[bytes, int] = {b"": 0}
        self.n = 1
        self.capacity = 0
        self.words: Optional[jax.Array] = None  # [cap, NW] on device
        self._nw: Optional[int] = None
        #: new (id, bytes) strings awaiting a device delta-scan
        self._pending: list = [(0, b"")]

    def intern(self, s: bytes) -> int:
        i = self.ids.get(s)
        if i is None:
            i = self.ids[s] = self.n
            self.n += 1
            self._pending.append((i, s))
        return i

    def flush(self) -> None:
        """Push pending strings' match words to the device table."""
        if not self._pending:
            return
        eng = self.engine
        prefix = _PREFIX[self.field]
        a = eng._arrays
        if f"{prefix}_trans" not in a:
            # the engine staged no automaton for this field (an l7g
            # table under a policy with no frontend rules): interning
            # continues host-side — ids stay stable across swaps —
            # and the pending delta scans when a policy that needs
            # the words arrives
            return
        if self._nw is None:
            # words-per-bank from the accept table: [NB, S, W] u32 →
            # flattened row is NB*W u32 lanes
            acc = a[f"{prefix}_accept"]
            self._nw = int(acc.shape[0]) * int(acc.shape[2])
        base = self._pending[0][0]
        D = _pow2(len(self._pending), floor=256)
        # capacity must cover base+D, not just n: dynamic_update_slice
        # CLAMPS an overrunning start index, which would silently slide
        # the (zero-padded) delta window over earlier rows' words
        cap_needed = _pow2(max(self.n, base + D))
        if cap_needed > self.capacity or self.words is None:
            old, old_cap = self.words, self.capacity
            self.capacity = cap_needed
            grown = jnp.zeros((self.capacity, self._nw),
                              dtype=jnp.uint32)
            if old is not None:
                grown = _delta_rows_update(
                    grown, old.astype(jnp.uint32), 0)
            self.words = grown
        # contiguous ids by construction (appended in intern order)
        raw = [s for _, s in self._pending]
        data = np.zeros((D, self.width), dtype=np.uint8)
        lens = np.zeros(D, dtype=np.int32)
        valid = np.zeros(D, dtype=bool)
        for j, s in enumerate(raw):
            b = s[:self.width]
            data[j, :len(b)] = np.frombuffer(b, dtype=np.uint8)
            lens[j] = len(b)
            # strings longer than the session width behave like the
            # raw path's fixed_len clip: invalid → zero words
            valid[j] = len(s) <= self.width
        self.words = _delta_scan_update(
            a[f"{prefix}_trans"], a[f"{prefix}_byteclass"],
            a[f"{prefix}_start"], a[f"{prefix}_accept"],
            self.words,
            jax.device_put(data, eng.device),
            jax.device_put(lens, eng.device),
            jax.device_put(valid, eng.device),
            base)
        self._pending = []


class IncrementalSession:
    """Online analog of CaptureReplay for one VerdictEngine.

    ``verdict_chunk(rec, l7, offsets, blob, gen, ...)`` returns
    ``(n, device verdict array)`` — dispatch only; the caller reads
    back (and can pipeline readbacks across chunks)."""

    def __init__(self, engine, widths: Optional[Dict[str, int]] = None,
                 max_rows: int = MAX_ROWS,
                 max_strings: int = MAX_STRINGS,
                 memo: bool = True, loader=None):
        from cilium_tpu.core.config import EngineConfig
        from cilium_tpu.engine.memo import policy_generation

        self.engine = engine
        #: optional Loader backref: makes the session swap-safe under
        #: churn — committed revisions are consumed as PolicyDeltas
        #: (bank-scoped: only rows touching a changed identity/bank
        #: recompute; a no-change commit drops nothing)
        self.loader = loader
        self._gen_epoch = policy_generation()
        #: device-resident verdict memo over the session row table
        #: (engine/memo.py): steady state, a chunk whose rows are all
        #: known costs one id H2D + one gather — the verdict step runs
        #: only for DELTA rows. Disable to force every chunk through
        #: the full step.
        self.memo_enabled = memo
        self.memo = VerdictMemo(device=engine.device) if memo else None
        cfg = EngineConfig()
        caps = {"path": max(cfg.http_path_buckets),
                "method": cfg.http_method_len,
                "host": cfg.http_host_len,
                "headers": 1024, "qname": cfg.dns_name_len,
                "l7g": cfg.l7g_len}
        self.widths = {f: min(int((widths or {}).get(f, caps[f])),
                              caps[f])
                       for f in _FIELDS + ("l7g",)}
        self.max_rows = max_rows
        self.max_strings = max_strings
        self.fmax = int(engine.policy.kafka_interns.get("gen_fmax", 4))
        # gen block: [proto id, frontend family, l7g string id,
        # pair ids...] (see CaptureFeaturizer.gen_rows)
        self.row_width = len(_ROW_COLS) + 3 + self.fmax
        self._step = jax.jit(verdict_step_capture)
        self.resets = 0
        self._init_state()

    def _init_state(self) -> None:
        self.tables = {f: _StringTable(self.engine, f, self.widths[f])
                       for f in _FIELDS}
        # the l7g (serialized frontend record) table interns host-side
        # unconditionally — string ids are policy-independent, so row
        # encodings survive swaps between fe and non-fe policies —
        # but only flushes/scans when the engine staged l7g arrays
        self.tables["l7g"] = _StringTable(self.engine, "l7g",
                                          self.widths["l7g"])
        self.kafka_memo: Dict[Tuple[str, bytes], int] = {}
        #: row-hash → [(row bytes, id), ...] chains (exact, see
        #: _row_idx)
        self.row_ids: Dict[int, list] = {}
        self.n_rows = 0
        self.row_capacity = 0
        self.rows_dev: Optional[jax.Array] = None
        self._pending_rows: list = []
        #: host mirror of each session row's (enforcement identity,
        #: l7 type, dport) — bounded by max_rows like the row table
        #: itself: the bank-reference invalidation mask is computed
        #: from it without a device readback
        self._row_eps: list = []
        #: session row ids a bank-scoped commit touched, awaiting a
        #: scatter refill in _memo_serve
        self._memo_dirty: Optional[np.ndarray] = None

    def reset(self, reason: str = "session-reset") -> None:
        self.resets += 1
        if self.memo is not None:
            # session row ids restart from 0 — memoized outputs keyed
            # by the old id space must go with them
            self.memo.invalidate(reason)
        self._init_state()

    # -- swap safety ------------------------------------------------------
    def _ensure_current(self) -> None:
        """Consume committed revisions' PolicyDeltas (mirrors
        ``CaptureReplay._ensure_current``): a no-change commit keeps
        every table and the memo; a bank-scoped commit rescans the
        session string tables through the new arrays (session strings
        are raw bytes — policy-independent) and queues only rows whose
        enforcement identity changed for a memo refill; anything else
        resets the session."""
        from cilium_tpu.engine.memo import (
            POLICY_GENERATION,
            policy_generation,
        )

        gen_now = policy_generation()
        if gen_now == self._gen_epoch:
            return
        delta = POLICY_GENERATION.deltas_since(self._gen_epoch)
        self._gen_epoch = gen_now
        new_engine = self.engine
        if self.loader is not None:
            cand = self.loader.engine
            if type(cand).__name__ == "VerdictEngine":
                new_engine = cand
        if delta.is_noop:
            self._rebind(new_engine)
            if self.memo is not None:
                self.memo.adopt()
            return
        partial = (not delta.full
                   and new_engine is not self.engine
                   and (new_engine.policy.kafka_interns
                        == self.engine.policy.kafka_interns))
        if not partial:
            self._rebind(new_engine)
            self.reset(reason="policy-swap")
            return
        self._rebind(new_engine)
        # rescan EVERY session string through the new policy's DFAs:
        # the match-word tables are policy-scoped even though the
        # strings themselves are not. O(session strings), bounded.
        for t in self.tables.values():
            t._pending = sorted(
                ((i, s) for s, i in t.ids.items()), key=lambda p: p[0])
            t.words = None
            t.capacity = 0
            t._nw = None
        if self.memo is not None and self.memo.filled:
            if delta.changed_identities:
                from cilium_tpu.engine.memo import affected_row_ids

                # bank-reference narrowing: only rows whose own L7
                # family AND entry port read a swapped bank refill —
                # an HTTP-path bank swap on one port keeps the same
                # identity's DNS/kafka rows AND its other ports'
                # HTTP rows serving (PolicyDelta.affects)
                pairs = self._row_eps[:self.memo.filled]
                affected = affected_row_ids(
                    delta,
                    np.fromiter((p[0] for p in pairs),
                                dtype=np.int64, count=len(pairs)),
                    np.fromiter((p[1] for p in pairs),
                                dtype=np.int64, count=len(pairs)),
                    dports=np.fromiter((p[2] for p in pairs),
                                       dtype=np.int64,
                                       count=len(pairs)))
                if len(affected):
                    self.memo.partial_invalidate(len(affected),
                                                 delta.reason)
                    prev = self._memo_dirty
                    self._memo_dirty = (affected if prev is None
                                        else np.union1d(prev, affected))
            self.memo.adopt()
        elif self.memo is not None:
            self.memo.adopt()

    def _rebind(self, engine) -> None:
        if engine is self.engine:
            return
        self.engine = engine
        for t in self.tables.values():
            t.engine = engine

    # -- per-chunk host featurize -----------------------------------------
    def _string_lut(self, field: str, idx: np.ndarray, offsets,
                    blob) -> np.ndarray:
        """Chunk string-table ids → session string ids (session table
        row == match-word row), interning new strings."""
        tbl = self.tables[field]
        uniq = np.unique(idx)
        lut = np.zeros(int(idx.max()) + 1 if len(idx) else 1,
                       dtype=np.int32)
        for u in uniq:
            s = blob[int(offsets[u]):int(offsets[u + 1])].tobytes()
            lut[u] = tbl.intern(s)
        return lut[idx]

    def _kafka_lut(self, key: str, idx: np.ndarray, offsets,
                   blob) -> np.ndarray:
        intern = self.engine.policy.kafka_interns.get(key, {})
        uniq, inv = np.unique(idx, return_inverse=True)
        out = np.empty(len(uniq), dtype=np.int32)
        for j, u in enumerate(uniq):
            s = blob[int(offsets[u]):int(offsets[u + 1])].tobytes()
            memo_key = (key, s)
            v = self.kafka_memo.get(memo_key)
            if v is None:
                v = self.kafka_memo[memo_key] = intern.get(
                    s.decode("utf-8", "replace"), -2)
            out[j] = v
        return out[inv]

    def _encode_rows(self, rec, l7, offsets, blob, gen) -> np.ndarray:
        B = len(rec)
        out = np.full((B, self.row_width), -2, dtype=np.int32)
        col = {c: i for i, c in enumerate(_ROW_COLS)}
        ingress = rec["direction"] == int(TrafficDirection.INGRESS)
        out[:, col["ep_ids"]] = np.where(
            ingress, rec["dst_identity"], rec["src_identity"])
        out[:, col["peer_ids"]] = np.where(
            ingress, rec["src_identity"], rec["dst_identity"])
        out[:, col["dports"]] = rec["dport"]
        out[:, col["protos"]] = rec["proto"]
        out[:, col["directions"]] = rec["direction"]
        out[:, col["l7_types"]] = rec["l7_type"]
        out[:, col["kafka_api_key"]] = l7["kafka_api_key"]
        out[:, col["kafka_api_version"]] = l7["kafka_api_version"]
        out[:, col["kafka_client"]] = self._kafka_lut(
            "client_id", l7["kafka_client"], offsets, blob)
        out[:, col["kafka_topic"]] = self._kafka_lut(
            "topic", l7["kafka_topic"], offsets, blob)
        for f in _FIELDS:
            out[:, col[f"{f}_row"]] = self._string_lut(
                f, l7[f], offsets, blob)
        ncols = len(_ROW_COLS)
        if gen is not None:
            gen_block = _gen_intern_rows(
                gen, offsets, blob, self.engine.policy.kafka_interns)
            fam, uniq_ser, l7g_row = _gen_l7g_cols(gen, offsets, blob)
            # serialized frontend records intern into the session l7g
            # table (delta-scanned like any string); non-frontend
            # records keep id 0 (the empty string)
            tbl = self.tables["l7g"]
            ser_ids = np.zeros(len(uniq_ser), dtype=np.int32)
            for j, s in enumerate(uniq_ser[1:], start=1):
                ser_ids[j] = tbl.intern(s)
            out[:, ncols] = gen_block[:, 0]
            out[:, ncols + 1] = fam
            out[:, ncols + 2] = ser_ids[l7g_row]
            out[:, ncols + 3:] = gen_block[:, 1:]
            # frontend records normalize the l7-type lane to their
            # family — same invariant as encode_flows; keys the fe
            # lane on device and the (ep, l7type, dport) memo mirror
            out[:, col["l7_types"]] = np.where(
                fam > 0, fam, out[:, col["l7_types"]])
        else:
            # no generic section: proto/pair slots stay -2 ("absent"),
            # matching encode_flows' defaults for non-generic flows;
            # the family/l7g columns read "no frontend record"
            out[:, ncols + 1] = 0
            out[:, ncols + 2] = 0
        return out

    @staticmethod
    def _hash_rows(rows: np.ndarray) -> np.ndarray:
        """The shared dedup row hash (``engine.memo.hash_rows`` — one
        implementation for the offline CaptureReplay dedup and this
        online session, so the two layers can't drift). Dedup by 1-D
        hash is ~10× cheaper than ``np.unique(rows, axis=0)``'s
        lexicographic row sort (29 ms → ~3 ms per 8k×21 chunk, the
        serving path's host hot spot); collisions are handled exactly,
        never assumed away."""
        return hash_rows(rows)

    def _row_idx(self, rows: np.ndarray) -> np.ndarray:
        """Chunk rows → session row ids, interning new unique rows.

        Exactness: hashes pick CANDIDATE matches only. Within the
        chunk, every row is verified against its hash-group
        representative; across the session, the id map chains on hash
        with stored row bytes compared before reuse. Any mismatch
        falls back to the exact row-sort path for this chunk."""
        h = self._hash_rows(rows)
        uh, first, inv = np.unique(h, return_index=True,
                                   return_inverse=True)
        # within-chunk verification: all rows must equal their hash
        # representative, or two distinct rows collided
        if not np.array_equal(rows, rows[first][inv]):
            return self._row_idx_exact(rows)
        lut = np.empty(len(uh), dtype=np.int32)
        for j in range(len(uh)):
            row = rows[first[j]]
            key = int(uh[j])
            chain = self.row_ids.get(key)
            rid = None
            if chain is not None:
                for stored_bytes, stored_id in chain:
                    if stored_bytes == row.tobytes():
                        rid = stored_id
                        break
            if rid is None:
                rid = self.n_rows
                self.n_rows += 1
                self._pending_rows.append(row.copy())
                self._row_eps.append((int(row[0]),
                                      int(row[_L7_COL]),
                                      int(row[_DPORT_COL])))
                if chain is None:
                    self.row_ids[key] = [(row.tobytes(), rid)]
                else:
                    chain.append((row.tobytes(), rid))
            lut[j] = rid
        return lut[inv].astype(np.int32)

    def _row_idx_exact(self, rows: np.ndarray) -> np.ndarray:
        """Exact fallback for an in-chunk hash collision (row sort)."""
        uniq, inv = np.unique(rows, axis=0, return_inverse=True)
        lut = np.empty(len(uniq), dtype=np.int32)
        for j in range(len(uniq)):
            row = uniq[j]
            key = int(self._hash_rows(row[None, :])[0])
            chain = self.row_ids.setdefault(key, [])
            rid = None
            for stored_bytes, stored_id in chain:
                if stored_bytes == row.tobytes():
                    rid = stored_id
                    break
            if rid is None:
                rid = self.n_rows
                self.n_rows += 1
                self._pending_rows.append(row.copy())
                self._row_eps.append((int(row[0]),
                                      int(row[_L7_COL]),
                                      int(row[_DPORT_COL])))
                chain.append((row.tobytes(), rid))
            lut[j] = rid
        return lut[inv].astype(np.int32)

    def _flush_rows(self) -> None:
        if not self._pending_rows:
            return
        base = self.n_rows - len(self._pending_rows)
        D = _pow2(len(self._pending_rows), floor=256)
        # cover base+D (same clamping hazard as _StringTable.flush)
        cap_needed = _pow2(max(self.n_rows, base + D))
        if cap_needed > self.row_capacity or self.rows_dev is None:
            old = self.rows_dev
            self.row_capacity = cap_needed
            grown = jnp.zeros((self.row_capacity, self.row_width),
                              dtype=jnp.int32)
            if old is not None:
                grown = _delta_rows_update(grown, old, 0)
            self.rows_dev = grown
        delta = np.zeros((D, self.row_width), dtype=np.int32)
        delta[:len(self._pending_rows)] = np.stack(self._pending_rows)
        self.rows_dev = _delta_rows_update(
            self.rows_dev, jax.device_put(delta, self.engine.device),
            base)
        self._pending_rows = []

    # -- the chunk entry point --------------------------------------------
    def encode_ids(self, rec, l7, offsets, blob, gen=None):
        """HOST half of a chunk: swap-safety check, capacity guard,
        featurize + intern → ``(idx, novel)`` where ``idx`` is the
        chunk's session row ids (int32, unpadded) and ``novel`` the
        number of rows this chunk interned for the first time. No
        device work happens here — the verdict ring packs many
        streams' encoded ids into ONE :meth:`serve_ids` dispatch.
        Rows already interned (``n - novel``) never ship their
        featurized bytes again: only the 4-byte id crosses, the
        memo-bypass selective-copy property the ring counts."""
        n = len(rec)
        if n == 0:
            return np.zeros(0, dtype=np.int32), 0
        self._ensure_current()
        if (self.n_rows >= self.max_rows
                or any(t.n >= self.max_strings
                       for t in self.tables.values())):
            self.reset()
        rows = self._encode_rows(rec, l7, offsets, blob, gen)
        before = self.n_rows
        idx = self._row_idx(rows)
        return idx, self.n_rows - before

    def serve_ids(self, idx: np.ndarray, authed_pairs=None,
                  provenance: bool = False):
        """DEVICE half: flush pending string/row deltas and serve one
        id vector — ONE fused dispatch (delta verdict step + memo
        fill) plus one on-device gather, however many streams'
        chunks were packed into ``idx``. Returns the device verdict
        array aligned to ``idx`` (padding sliced by the caller); with
        ``provenance=True`` returns a
        :class:`~cilium_tpu.engine.attribution.ServedPack` carrying
        the attribution lane, per-row cited generations, and the
        memo-hit/computed split alongside the verdicts (same
        dispatch — the extra lanes ride the gather the memo already
        does)."""
        for t in self.tables.values():
            t.flush()
        self._flush_rows()
        n = len(idx)
        B_pad = _pow2(n, floor=32)
        if B_pad > n:
            # pad ids point at row 0 — a REAL session row, but padded
            # verdicts are sliced off before anything reads them
            idx = np.concatenate(
                [idx, np.zeros(B_pad - n, dtype=np.int32)])
        from cilium_tpu.engine.verdict import DISPATCH_POINT, _faults

        _faults.maybe_fail(DISPATCH_POINT)
        table_words = {f: self.tables[f].words for f in _FIELDS}
        if "l7g_trans" in self.engine._arrays:
            table_words["l7g"] = self.tables["l7g"].words
        if self.memo is not None:
            return self._memo_serve(idx, table_words, authed_pairs,
                                    provenance=provenance)
        batch = {"rows": self.rows_dev,
                 "idx": jax.device_put(idx, self.engine.device)}
        self.engine._stage_auth(batch, authed_pairs)
        out = self._step(self.engine._arrays, table_words, batch)
        if not provenance:
            return out["verdict"]
        return self._pack_provenance(out, idx, memo_hit=None)

    def _pack_provenance(self, out, idx, memo_hit=None):
        """Build the ServedPack for one served id vector. ``out`` is
        the step/gather output dict; ``memo_hit`` the per-row
        hit mask (None = everything computed this dispatch)."""
        from cilium_tpu.engine.attribution import (
            ServedPack,
            kernel_label,
        )
        from cilium_tpu.engine.memo import policy_generation

        gen_now = policy_generation()
        n = len(idx)
        if memo_hit is None:
            memo_hit = np.zeros(n, dtype=bool)
        if self.memo is not None and self.memo.gens is not None:
            gens = self.memo.cited_gens(idx)
        else:
            gens = np.full(n, gen_now, dtype=np.int64)
        return ServedPack(
            verdict=out["verdict"],
            l7_match=out.get("l7_match"),
            match_spec=out["match_spec"],
            gens=gens, memo_hit=memo_hit, generation=gen_now,
            kernel=kernel_label(self.engine))

    def verdict_chunk(self, rec, l7, offsets, blob, gen=None,
                      authed_pairs=None):
        """Featurize + intern one chunk, push deltas, dispatch the
        gather+verdict step. Returns (n, device verdict array).
        Composition of :meth:`encode_ids` + :meth:`serve_ids` — the
        single-stream shape of what the verdict ring does for many
        streams per dispatch."""
        from cilium_tpu.runtime.tracing import (
            PHASE_DEVICE,
            PHASE_HOST,
            TRACER,
        )

        n = len(rec)
        if n == 0:
            return 0, None
        with TRACER.span("session.featurize", phase=PHASE_HOST,
                         records=n):
            idx, _ = self.encode_ids(rec, l7, offsets, blob, gen)
        with TRACER.span("session.dispatch", phase=PHASE_DEVICE,
                         records=n):
            # delta flushes are device transfers — device-dispatch,
            # like the step they feed
            return n, self.serve_ids(idx, authed_pairs=authed_pairs)

    def _memo_serve(self, idx: np.ndarray, table_words,
                    authed_pairs, provenance: bool = False):
        """Serve one (padded) id chunk from the verdict memo. Outputs
        for DELTA rows — session rows newer than the memo's fill mark
        — are computed first through the shared capture step (so
        memoized and recomputed verdicts are bit-equal by
        construction) and spliced into the device memo table; the
        chunk itself is then one gather. An auth-view change or policy
        generation bump drops the memo and the next chunk refills from
        row 0."""
        sig = auth_signature(authed_pairs)
        m = self.memo
        m.valid_for(sig)  # drops the memo on generation/auth change
        base0 = m.filled  # rows below this mark are memo HITS
        if m.filled < self.n_rows:
            base = m.filled
            n_new = self.n_rows - base
            D = _pow2(n_new, floor=32)
            # pad ids clamp to real rows; their (garbage) memo slots
            # sit beyond the fill mark and are rewritten by the next
            # delta before any id can reference them
            fill_idx = np.minimum(
                np.arange(base, base + D, dtype=np.int32),
                self.n_rows - 1)
            batch = {"rows": self.rows_dev,
                     "idx": jax.device_put(fill_idx,
                                           self.engine.device)}
            self.engine._stage_auth(batch, authed_pairs)
            out = self._step(self.engine._arrays, table_words, batch)
            m.fill(memo_pack(out), base, n_new, sig)
        dirty = self._memo_dirty
        if dirty is not None and len(dirty) and m.table is not None:
            # bank-scoped refill: rewrite ONLY the rows a committed
            # revision touched; everything else keeps serving
            D = _pow2(len(dirty), floor=32)
            ridx = (np.concatenate(
                [dirty, np.full(D - len(dirty), dirty[0],
                                dtype=dirty.dtype)])
                if D > len(dirty) else dirty)
            batch = {"rows": self.rows_dev,
                     "idx": jax.device_put(ridx, self.engine.device)}
            self.engine._stage_auth(batch, authed_pairs)
            out = self._step(self.engine._arrays, table_words, batch)
            m.refill_scatter(ridx, memo_pack(out), len(dirty))
        refilled = dirty if dirty is not None else None
        self._memo_dirty = None
        # gather() stages idx itself (memo.py) — a device_put here
        # would be a second, redundant transfer of the id block
        gathered = m.gather(idx)
        if not provenance:
            return gathered["verdict"]
        # memo-hit = the row was resident BEFORE this dispatch and was
        # not rewritten by the bank-scoped refill above — everything
        # else was computed under the current generation
        hit = idx < base0
        if refilled is not None and len(refilled):
            hit &= ~np.isin(idx, refilled)
        return self._pack_provenance(gathered, idx, memo_hit=hit)
