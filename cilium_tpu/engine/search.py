"""Vectorized lower-bound binary search over multi-word sorted keys.

The TPU replacement for the datapath's O(1) hash-map lookups
(``bpf/lib/policy.h`` / ``lb.h``): hashing is branch-heavy and
pointer-chasing on a TPU, while a fori_loop binary search over sorted
key columns is a handful of gathers — shared by the MapState lookup
(3-word keys) and the load-balancer service lookup (2-word keys).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def lower_bound(
    keys: Sequence[jax.Array],    # each [N], jointly lexsorted
    probes: Sequence[jax.Array],  # each [B] (broadcastable shapes)
) -> Tuple[jax.Array, jax.Array]:
    """Lexicographic lower bound of each probe tuple in the key table.

    Returns ``(index [B] int32 clipped to [0, N-1], found [B] bool)``
    where ``found`` marks exact matches.
    """
    if len(keys) != len(probes) or not keys:
        raise ValueError("keys and probes must be equal-length, non-empty")
    N = keys[0].shape[0]
    iters = max(1, int(N).bit_length())
    shape = jnp.broadcast_shapes(*(p.shape for p in probes))
    lo = jnp.zeros(shape, dtype=jnp.int32)
    hi = jnp.full(shape, N, dtype=jnp.int32)

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) >> 1
        # mid-key >= probe, lexicographically (built innermost-out)
        ge = keys[-1][mid] >= probes[-1]
        for k, p in zip(reversed(keys[:-1]), reversed(probes[:-1])):
            m = k[mid]
            ge = (m > p) | ((m == p) & ge)
        return jnp.where(ge, lo, mid + 1), jnp.where(ge, mid, hi)

    lo, hi = lax.fori_loop(0, iters, body, (lo, hi))
    idx = jnp.clip(lo, 0, N - 1)
    found = lo < N
    for k, p in zip(keys, probes):
        found = found & (k[idx] == p)
    return idx, found
