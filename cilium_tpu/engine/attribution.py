"""Verdict provenance: the host-side half of the attribution lane.

The megakernel's factored resolve already computes, per flow, which
rule-signature group won (``l7_match`` — an extra argmax over the
group-accept planes the dispatch holds anyway). This module maps that
device code back to something an operator can act on:

* :class:`AttributionMap` — built once per :class:`CompiledPolicy`,
  resolves ``(l7_type, l7_match)`` to concrete rule ids, the rule
  content, and the content-addressed automaton bank the match was
  read from (``policy.bank_plan``);
* :func:`pack_word` / :func:`unpack_word` — the packed provenance
  word that rides Hubble flow records and JSONL logs: winning code,
  family, memo-hit vs computed, the ``POLICY_GENERATION`` the verdict
  was computed under, the pack-cycle id, and the kernel impl;
* :class:`ServedPack` — the per-row provenance bundle the serving
  paths (``IncrementalSession.serve_ids``, the verdict ring) hand
  back alongside verdicts.

Attribution is exact at GROUP granularity: every member of a matched
group shares the winning signature (method/host/header lanes,
ruleset membership) and the group's path disjunction contains the
matched path — citing the group cites the set of rules that could
only match together. Plan-less policies (degenerate grouping, legacy
artifacts) attribute in RULE space; the map knows which space its
policy resolved in.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu.core.flow import L7Type

#: provenance word layout (bit offsets / widths). Fits in 63 bits so
#: the word survives JSON and int64 columns unharmed.
_CODE_BITS = 20       # winning group/rule/lane code + 1 (0 = none)
_FAMILY_SHIFT = 20    # 3 bits: L7Type (0 = none/l4)
_MEMO_SHIFT = 23      # 1 bit: memo-hit (served) vs computed
_GEN_SHIFT = 24       # 24 bits: POLICY_GENERATION mod 2^24
_CYCLE_SHIFT = 48     # 10 bits: pack-cycle id mod 1024
_KERNEL_SHIFT = 58    # 3 bits: kernel impl code
_VERSION_SHIFT = 61   # 2 bits: word schema version
WORD_VERSION = 1

#: kernel impl labels ⇄ word codes (0 = unknown/absent)
KERNEL_CODES = {"": 0, "legacy": 1, "dfa-dense": 2, "nfa-bitset": 3,
                "mixed": 4, "oracle": 5}
KERNEL_NAMES = {v: k for k, v in KERNEL_CODES.items()}

FAMILY_NAMES = {int(L7Type.HTTP): "http", int(L7Type.KAFKA): "kafka",
                int(L7Type.DNS): "dns", int(L7Type.GENERIC): "generic",
                int(L7Type.CASSANDRA): "cassandra",
                int(L7Type.MEMCACHE): "memcache",
                int(L7Type.R2D2): "r2d2"}

#: frontend family ids share ONE decode table ("fe"): their l7_match
#: codes live in the common fe-group (or fe-rule) space
_FE_FAMILIES = frozenset((int(L7Type.CASSANDRA), int(L7Type.MEMCACHE),
                          int(L7Type.R2D2)))


def flow_family(flow) -> int:
    """The ENGINE family of a flow object — what the attribution
    lane's code is scoped to. Frontend records carry ``l7 ==
    GENERIC`` on the wire; the engine normalizes their l7-type lane
    to the frontend family, so flow-side decoders must apply the
    same mapping or a cassandra code would resolve through the
    generic pair table."""
    from cilium_tpu.policy.compiler import frontends

    l7 = int(flow.l7)
    g = getattr(flow, "generic", None)
    if l7 == int(L7Type.GENERIC) and g is not None:
        fam = frontends.family_of(g.proto)
        if fam:
            return fam
    return l7


def kernel_label(engine) -> str:
    """One label for the engine's scan-impl plan: ``legacy`` (no
    fused plan), one arm's name when every field agrees, ``mixed``
    otherwise."""
    plan = getattr(engine, "impl_plan", None) or {}
    if not plan:
        return "legacy"
    impls = set(plan.values())
    if len(impls) == 1:
        return next(iter(impls))
    return "mixed"


def pack_word(code: int, family: int, memo_hit: bool, gen: int,
              pack_cycle: int = 0, kernel: str = "") -> int:
    """Pack one verdict's provenance into a single int word. ``code``
    is the device attribution lane value (-1 = no L7 winner — packs
    as 0 so "no provenance at all" and "attributed, no L7 match" stay
    distinguishable via the version bits)."""
    w = (min(max(int(code) + 1, 0), (1 << _CODE_BITS) - 1)
         | ((int(family) & 0x7) << _FAMILY_SHIFT)
         | ((1 if memo_hit else 0) << _MEMO_SHIFT)
         | ((max(int(gen), 0) & 0xFFFFFF) << _GEN_SHIFT)
         | ((max(int(pack_cycle), 0) & 0x3FF) << _CYCLE_SHIFT)
         | ((KERNEL_CODES.get(kernel, 0) & 0x7) << _KERNEL_SHIFT)
         | (WORD_VERSION << _VERSION_SHIFT))
    return int(w)


def unpack_word(word: int) -> Optional[Dict[str, object]]:
    """Inverse of :func:`pack_word`; None for 0/unversioned words
    (pre-provenance flows decode to nothing, never to garbage)."""
    word = int(word)
    if word <= 0 or (word >> _VERSION_SHIFT) != WORD_VERSION:
        return None
    return {
        "code": (word & ((1 << _CODE_BITS) - 1)) - 1,
        "family": (word >> _FAMILY_SHIFT) & 0x7,
        "memo_hit": bool((word >> _MEMO_SHIFT) & 1),
        "generation": (word >> _GEN_SHIFT) & 0xFFFFFF,
        "pack_cycle": (word >> _CYCLE_SHIFT) & 0x3FF,
        "kernel": KERNEL_NAMES.get((word >> _KERNEL_SHIFT) & 0x7, ""),
    }


def _rule_label(family: str, rid: int, rule) -> str:
    if family == "http":
        parts = [p for p in (
            f"path={rule.path!r}" if rule.path else "",
            f"method={rule.method!r}" if rule.method else "",
            f"host={rule.host!r}" if rule.host else "") if p]
        return f"http[{rid}] " + (" ".join(parts) or "<any>")
    if family == "dns":
        pat = rule.match_name or rule.match_pattern
        return f"dns[{rid}] {pat!r}"
    if family == "fe":
        proto, pairs = rule
        return f"{proto}[{rid}] l7={dict(pairs)!r}"
    if family == "kafka":
        parts = [p for p in (
            f"role={rule.role!r}" if rule.role else "",
            f"apiKey={rule.api_key!r}" if rule.api_key else "",
            f"topic={rule.topic!r}" if rule.topic else "") if p]
        return f"kafka[{rid}] " + (" ".join(parts) or "<any>")
    proto, pairs = rule
    return f"generic[{rid}] proto={proto!r} l7={dict(pairs)!r}"


class AttributionMap:
    """Host-side decoder of the ``l7_match`` lane for one compiled
    policy: code → member rule ids → rule content → bank key."""

    def __init__(self, space: str, members: Dict[str, List[Tuple[int, ...]]],
                 rules: Dict[str, list], bank_of: Dict[str, list],
                 bank_plan: Dict[str, Tuple[str, ...]]):
        #: "group" (fused resolve plan staged) or "rule"
        self.space = space
        #: family → code → member rule-id tuple
        self._members = members
        #: family → rule table (policy.http_rules etc.)
        self._rules = rules
        #: family → code → bank index within the family's field stack
        self._bank_of = bank_of
        #: field → serving content-addressed bank keys
        self._bank_plan = bank_plan

    # -- construction -----------------------------------------------------
    @classmethod
    def from_policy(cls, policy) -> "AttributionMap":
        a = policy.arrays
        meta = getattr(policy, "resolve_meta", None) or {}
        space = "group" if "rp_rule_group" in a else "rule"
        members: Dict[str, List[Tuple[int, ...]]] = {}
        bank_of: Dict[str, list] = {}

        n_http = len(policy.http_rules)
        path_lane = np.asarray(a.get("http_path_lane",
                                     np.full(max(1, n_http), -1)))
        pw = int(a["path_accept"].shape[2]) if "path_accept" in a else 1
        if space == "group":
            g_rules = meta.get("group_rules")
            if g_rules is None:
                rg = np.asarray(a["rp_rule_group"])
                n_g = int(rg.max()) + 1 if len(rg) and rg.max() >= 0 \
                    else 0
                g_rules = tuple(
                    tuple(int(r) for r in np.nonzero(rg == g)[0])
                    for g in range(n_g))
            members["http"] = [tuple(g) for g in g_rules]
        else:
            members["http"] = [(r,) for r in range(n_http)]
        bank_of["http"] = []
        for mem in members["http"]:
            lane = int(path_lane[mem[0]]) if mem and \
                mem[0] < len(path_lane) else -1
            bank_of["http"].append(lane // (32 * pw) if lane >= 0
                                   else -1)

        # DNS attribution is lane space in BOTH resolves
        n_dns = len(policy.dns_rules)
        dns_lane = np.asarray(a.get("dns_lane",
                                    np.full(max(1, n_dns), -1)))
        dw = int(a["dns_accept"].shape[2]) if "dns_accept" in a else 1
        n_lanes = int(dns_lane.max()) + 1 if len(dns_lane) and \
            dns_lane.max() >= 0 else 0
        members["dns"] = [
            tuple(int(r) for r in np.nonzero(dns_lane[:n_dns] == l)[0])
            for l in range(n_lanes)]
        bank_of["dns"] = [l // (32 * dw) for l in range(n_lanes)]

        n_kafka = len(policy.kafka_rules)
        if space == "group" and "rp_k_rule_group" in a:
            kg = meta.get("kafka_group_rules")
            if kg is None:
                rg = np.asarray(a["rp_k_rule_group"])[:n_kafka]
                n_g = int(rg.max()) + 1 if len(rg) and rg.max() >= 0 \
                    else 0
                kg = tuple(tuple(int(r)
                                 for r in np.nonzero(rg == g)[0])
                           for g in range(n_g))
            members["kafka"] = [tuple(g) for g in kg]
        else:
            members["kafka"] = [(r,) for r in range(n_kafka)]
        bank_of["kafka"] = [-1] * len(members["kafka"])  # columnar

        # protocol-frontend rules: one shared decode table for every
        # fe family (codes live in the common fe-group space); the
        # bank index derives from the rule's l7g automaton lane
        n_fe = len(getattr(policy, "fe_rules", ()) or ())
        fe_lane = np.asarray(a.get("fe_lane", np.full(max(1, n_fe),
                                                      -1)))
        lw = int(a["l7g_accept"].shape[2]) if "l7g_accept" in a else 1
        if space == "group" and "rp_fe_rule_group" in a:
            fg = meta.get("fe_group_rules")
            if fg is None:
                rg = np.asarray(a["rp_fe_rule_group"])[:n_fe]
                n_g = int(rg.max()) + 1 if len(rg) and rg.max() >= 0 \
                    else 0
                fg = tuple(tuple(int(r)
                                 for r in np.nonzero(rg == g)[0])
                           for g in range(n_g))
            members["fe"] = [tuple(g) for g in fg]
        else:
            members["fe"] = [(r,) for r in range(n_fe)]
        bank_of["fe"] = []
        for mem in members["fe"]:
            lane = int(fe_lane[mem[0]]) if mem and \
                mem[0] < len(fe_lane) else -1
            bank_of["fe"].append(lane // (32 * lw) if lane >= 0
                                 else -1)

        n_gen = len(policy.gen_rules)
        if space == "group" and "rp_gen_rule_group" in a:
            gg = meta.get("gen_group_rules")
            if gg is None:
                rg = np.asarray(a["rp_gen_rule_group"])[:n_gen]
                n_g = int(rg.max()) + 1 if len(rg) and rg.max() >= 0 \
                    else 0
                gg = tuple(tuple(int(r)
                                 for r in np.nonzero(rg == g)[0])
                           for g in range(n_g))
            members["generic"] = [tuple(g) for g in gg]
        else:
            members["generic"] = [(r,) for r in range(n_gen)]
        bank_of["generic"] = [-1] * len(members["generic"])

        return cls(space, members,
                   {"http": policy.http_rules,
                    "kafka": policy.kafka_rules,
                    "dns": policy.dns_rules,
                    "generic": policy.gen_rules,
                    "fe": list(getattr(policy, "fe_rules", ()) or ())},
                   bank_of, dict(getattr(policy, "bank_plan", {}) or {}))

    # -- resolution -------------------------------------------------------
    _FIELD_OF = {"http": "path", "dns": "dns", "fe": "l7g"}

    def resolve(self, l7_type: int, code: int
                ) -> Optional[Dict[str, object]]:
        """``(l7_type, l7_match code)`` → the explanation dict, or
        None when the code does not name a live rule (the
        "unexplainable" bucket the coverage gate counts). Frontend
        family codes (cassandra/memcache/r2d2) resolve through the
        shared "fe" table; the reported family stays the flow's own."""
        family = FAMILY_NAMES.get(int(l7_type))
        if family is None or code is None or int(code) < 0:
            return None
        report_family = family
        if int(l7_type) in _FE_FAMILIES:
            family = "fe"
        code = int(code)
        fam_members = self._members.get(family, [])
        if code >= len(fam_members) or not fam_members[code]:
            return None
        rule_ids = fam_members[code]
        rid = rule_ids[0]
        rules = self._rules.get(family, [])
        if rid >= len(rules):
            return None
        bank_idx = self._bank_of[family][code] \
            if code < len(self._bank_of.get(family, [])) else -1
        field = self._FIELD_OF.get(family, "")
        keys = self._bank_plan.get(field, ()) if field else ()
        bank_key = (keys[bank_idx]
                    if 0 <= bank_idx < len(keys) else "")
        return {
            "family": report_family,
            "space": self.space,
            "code": code,
            "rule_ids": list(rule_ids),
            "rule_index": rid,
            "rule": _rule_label(family, rid, rules[rid]),
            "bank_field": field,
            "bank_index": bank_idx,
            "bank_key": bank_key,
        }

    def rule_label(self, l7_type: int, code: int) -> str:
        """Compact label for flow records / logs:
        ``http:g3/r17`` (group space), ``dns:r2`` (rule/lane), or
        ``cassandra:g0/r1`` (frontend families, fe-group space)."""
        res = self.resolve(l7_type, code)
        if res is None:
            return ""
        tag = "g" if self.space == "group" else "r"
        if res["family"] == "dns":
            tag = "l"  # dns attribution is lane space in both arms
        return (f"{res['family']}:{tag}{res['code']}"
                f"/r{res['rule_index']}")


@dataclasses.dataclass
class ServedPack:
    """Per-row provenance bundle riding alongside served verdicts.
    ``verdict``/``l7_match``/``match_spec`` may be device arrays
    (sliced lazily); ``gens``/``memo_hit`` are host numpy."""

    verdict: object
    l7_match: object
    match_spec: object
    gens: np.ndarray            # cited POLICY_GENERATION per row
    memo_hit: np.ndarray        # served from memo vs computed
    generation: int             # the epoch current at dispatch
    kernel: str = ""
    pack_cycle: int = -1

    def slice(self, base: int, n: int) -> "ServedPack":
        return ServedPack(
            verdict=self.verdict[base:base + n],
            l7_match=self.l7_match[base:base + n],
            match_spec=self.match_spec[base:base + n],
            gens=self.gens[base:base + n],
            memo_hit=self.memo_hit[base:base + n],
            generation=self.generation,
            kernel=self.kernel,
            pack_cycle=self.pack_cycle)

    def host(self) -> "ServedPack":
        """Force the device lanes to host numpy in ONE batched
        readback (``jax.device_get`` on the lane tuple — a single
        transfer instead of three; identity on lanes that are already
        numpy). ``gens``/``memo_hit`` are host numpy by construction:
        :meth:`VerdictMemo.attribute` and the session serve path build
        them with ``np.full``/boolean masks on host, so converting
        them here would be a no-op readback."""
        import jax

        verdict, l7_match, match_spec = jax.device_get(
            (self.verdict, self.l7_match, self.match_spec))
        return ServedPack(
            verdict=np.asarray(verdict).astype(np.int32),
            l7_match=np.asarray(l7_match).astype(np.int32),
            match_spec=np.asarray(match_spec).astype(np.int32),
            gens=np.asarray(self.gens),
            memo_hit=np.asarray(self.memo_hit),
            generation=self.generation,
            kernel=self.kernel,
            pack_cycle=self.pack_cycle)

    def words(self) -> np.ndarray:
        """Vectorized packed provenance words for every row."""
        h = self.host()
        out = np.empty(len(h.gens), dtype=np.int64)
        fam = np.zeros(len(h.gens), dtype=np.int64)
        # family rides the attribution lane's sign: the l7_match code
        # is family-scoped, so family itself comes from the caller's
        # l7_types column when available; packed words without it
        # carry 0 and the explain entry supplies the family
        for i in range(len(out)):
            out[i] = pack_word(int(h.l7_match[i]), int(fam[i]),
                               bool(h.memo_hit[i]), int(h.gens[i]),
                               self.pack_cycle, self.kernel)
        return out
