"""End-to-end verdict pipeline: compile policy → tensors → jitted step.

This is the compile/execute split of SURVEY.md §7 in one place:

* :class:`CompiledPolicy` (host): per-identity MapStates + the L7 rule
  universe → packed tensors — the sorted L3/L4 key table, banked DFAs
  per HTTP field (path/method/host/headers) and for DNS patterns, Kafka
  ACL columns, and per-ruleset rule bitmaps.
* :class:`VerdictEngine` (device): one jitted function over those
  tensors computing, for a flow batch: L3/L4 precedence verdict →
  L7 automaton matches → per-rule conjunction → ruleset-any → final
  verdict codes. Mirrors the reference datapath stages ct→policy→L7
  (SURVEY.md §3.3/§3.4) as one fused batched program.

Verdict codes follow flowpb: FORWARDED=1, DROPPED=2, REDIRECTED=5
(L7-allowed flows report REDIRECTED — they traversed the proxy path).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.core.config import EngineConfig
from cilium_tpu.core.flow import (
    Flow,
    L7Type,
    TrafficDirection,
    Verdict,
)
from cilium_tpu.policy.api.l7 import L7Rules, PortRuleDNS, PortRuleHTTP, PortRuleKafka
from cilium_tpu.policy.compiler import matchpattern
from cilium_tpu.policy.compiler.dfa import BankedDFA, DFABank, compile_patterns
from cilium_tpu.policy.mapstate import MapState
from cilium_tpu.engine.dfa_kernel import dfa_scan_banked
from cilium_tpu.engine.search import lower_bound
from cilium_tpu.engine.mapstate_kernel import PackedMapState, pack_mapstate, mapstate_lookup


# --------------------------------------------------------------- helpers --
def encode_strings(
    strings: Sequence[bytes], max_len: int, pad_multiple: int = 32
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode byte strings → (data [B, L] uint8, lengths [B] int32,
    valid [B] bool). Overlong strings are truncated and marked invalid —
    the engine zeroes their match words (no false accepts)."""
    B = len(strings)
    longest = max((len(s) for s in strings), default=1)
    L = min(max_len, max(pad_multiple, -(-max(longest, 1) // pad_multiple)
                         * pad_multiple))
    data = np.zeros((B, L), dtype=np.uint8)
    lengths = np.zeros((B,), dtype=np.int32)
    valid = np.ones((B,), dtype=bool)
    for i, s in enumerate(strings):
        if len(s) > L:
            valid[i] = False
            s = s[:L]
        data[i, : len(s)] = np.frombuffer(s, dtype=np.uint8)
        lengths[i] = len(s)
    return data, lengths, valid


def serialize_headers(headers: Sequence[Tuple[str, str]]) -> bytes:
    """Canonical header block: lowercase names, sorted, ``name:value``
    lines each newline-terminated. The header automatons match
    contains-regexes over this form."""
    lines = sorted(f"{k.strip().lower()}:{v.strip()}" for k, v in headers)
    return ("".join(line + "\n" for line in lines)).encode("utf-8")


def header_requirement_regex(name: str, value: str) -> str:
    """Regex (over the serialized header block) for one required header.
    Empty value = presence check."""
    import re as _re

    n = _re.escape(name.strip().lower())
    if value:
        v = _re.escape(value.strip())
        line = f"{n}:{v}"
    else:
        line = f"{n}:[^\\n]*"
    return f"(?:[^\\n]*\\n)*{line}\\n(?:[^\\n]*\\n)*"


def _empty_banked() -> BankedDFA:
    """A 1-bank, 0-pattern automaton (matches nothing) so tensor shapes
    stay non-degenerate when a protocol has no rules."""
    bank = DFABank(
        trans=np.zeros((2, 1), dtype=np.int32),
        byteclass=np.zeros(256, dtype=np.int32),
        accept=np.zeros((2, 1), dtype=np.uint32),
        start=1,
        n_patterns=0,
    )
    return BankedDFA(
        banks=[bank],
        pattern_bank=np.zeros(0, dtype=np.int32),
        pattern_lane=np.zeros(0, dtype=np.int32),
        patterns=(),
    )


@dataclasses.dataclass
class _FieldMatcher:
    """A deduped pattern universe for one string field + its stacked
    tensors; rules reference patterns by global lane."""

    banked: BankedDFA
    arrays: Dict[str, np.ndarray]
    pattern_index: Dict[str, int]
    #: bankplan.FieldBankStats when built through a BankRegistry (the
    #: content-addressed churn path); None on the positional path
    bank_stats: object = None

    @classmethod
    def build(cls, patterns: List[str], cfg: EngineConfig,
              case_insensitive: bool = False,
              bank_cache=None, bank_registry=None,
              field: str = "") -> "_FieldMatcher":
        uniq: List[str] = []
        index: Dict[str, int] = {}
        for p in patterns:
            if p not in index:
                index[p] = len(uniq)
                uniq.append(p)
        stats = None
        if not uniq:
            banked = _empty_banked()
        elif bank_registry is not None:
            # content-addressed bank path (policy/compiler/bankplan):
            # membership is a pure function of the pattern set, so a
            # CNP add/delete recompiles only its bank(s), and a failed
            # bank quarantines instead of aborting the build
            banked, stats = bank_registry.compile_field(
                field or "field", uniq, cfg,
                case_insensitive=case_insensitive)
        else:
            banked = compile_patterns(
                uniq,
                bank_size=cfg.bank_size,
                max_states=cfg.max_dfa_states,
                max_quantifier=cfg.max_quantifier,
                case_insensitive=case_insensitive,
                bank_cache=bank_cache,
            )
        return cls(banked=banked, arrays=banked.stacked(),
                   pattern_index=index, bank_stats=stats)

    def lane(self, pattern: str) -> int:
        """Global lane of ``pattern``; -1 for the empty pattern (=no
        constraint)."""
        if not pattern:
            return -1
        return int(self.arrays["lane_of"][self.pattern_index[pattern]])


def _rule_bit(words: jax.Array, lanes: jax.Array) -> jax.Array:
    """words [B, NW] uint32, lanes [R] int32 (-1 = unconstrained) →
    bool [B, R]."""
    word_idx = jnp.clip(lanes >> 5, 0, words.shape[1] - 1)
    bit_idx = (lanes & 31).astype(jnp.uint32)
    w = jnp.take(words, word_idx, axis=1)            # [B, R]
    bits = (w >> bit_idx[None, :]) & jnp.uint32(1)
    return jnp.where(lanes[None, :] < 0, True, bits.astype(bool))


def _masks_to_array(masks: List[List[int]], n_rules: int) -> np.ndarray:
    W = max(1, (max(n_rules, 1) + 31) // 32)
    out = np.zeros((max(1, len(masks)), W), dtype=np.uint32)
    for i, rule_ids in enumerate(masks):
        for r in rule_ids:
            out[i, r // 32] |= np.uint32(1 << (r % 32))
    return out


# ---------------------------------------------------------------- policy --


@dataclasses.dataclass
class CompiledPolicy:
    """Everything the device step needs, as host numpy arrays."""

    mapstate: PackedMapState
    arrays: Dict[str, np.ndarray]           # flat tensor dict
    http_rules: List[PortRuleHTTP]
    kafka_rules: List[PortRuleKafka]
    dns_rules: List[PortRuleDNS]
    gen_rules: List[Tuple[str, Tuple[Tuple[str, str], ...]]]
    kafka_interns: Dict[str, Dict]          # intern tables (kafka + generic)
    path_matcher: _FieldMatcher
    method_matcher: _FieldMatcher
    host_matcher: _FieldMatcher
    header_matcher: _FieldMatcher
    dns_matcher: _FieldMatcher
    revision: int = 0
    #: protocol-frontend rules (policy/compiler/frontends/):
    #: (l7proto, sorted (key, value) pairs) per rule, compiled onto
    #: the ``l7g`` banked automaton instead of the generic pair path
    fe_rules: List[Tuple[str, Tuple[Tuple[str, str], ...]]] = \
        dataclasses.field(default_factory=list)
    #: the ``l7g`` field matcher over the frontend pattern universe;
    #: None when no frontend rules exist (the l7g_* arrays are then
    #: absent and every l7g code path is statically skipped)
    l7g_matcher: Optional[_FieldMatcher] = None
    #: per-HTTP-rule proxy-side header rewrites from ADD/DELETE/REPLACE
    #: mismatch actions: [(action, header-name, value), ...] — the
    #: shim/Envoy layer owns applying them; the verdict engine only
    #: carries them (reference: cilium.l7policy filter does the bytes)
    header_rewrites: List[List[Tuple[str, str, str]]] = \
        dataclasses.field(default_factory=list)
    #: content-addressed bank plan (field → serving bank-key tuple)
    #: when built through a BankRegistry — the loader diffs plans
    #: across commits to derive the bank-scoped invalidation delta
    bank_plan: Dict[str, Tuple[str, ...]] = \
        dataclasses.field(default_factory=dict)
    #: bank keys quarantined during this build (stale covers serving);
    #: non-empty marks the policy DEGRADED: never cached, never warm-
    #: snapshotted, commits a full invalidation delta
    bank_quarantined: Tuple[str, ...] = ()
    #: host-side metadata of the factored resolve plan
    #: (engine/megakernel.py): group count + the path-lane → group
    #: mapping the NFA arm's group plane derives from. None when the
    #: grouping degenerated (fused step falls back to legacy resolve).
    resolve_meta: Optional[Dict] = None
    #: field → scan-impl pick ("dfa-dense" / "nfa-bitset"), written at
    #: engine staging by the per-bank-shape autotuner; rides the
    #: policy object into bank_status and the bench lines
    kernel_plan: Dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def build(
        cls,
        per_identity: Dict[int, MapState],
        cfg: Optional[EngineConfig] = None,
        revision: int = 0,
        secret_lookup=None,
        bank_cache=None,
        bank_registry=None,
        audit: bool = False,
    ) -> "CompiledPolicy":
        """``bank_cache`` (compiler.dfa.BankCache): reuse compiled DFA
        banks across builds — incremental rule updates recompile only
        banks whose pattern membership changed. ``bank_registry``
        (compiler.bankplan.BankRegistry) supersedes it with the
        content-addressed partition + per-bank quarantine. ``audit`` =
        policy_audit_mode: would-be denials verdict AUDIT, not DROPPED
        (staged as a device scalar so the jitted step needs no
        recompile-per-mode)."""
        cfg = cfg or EngineConfig()

        # -- collect the L7 rule universe (deduped) and rulesets --------
        http_rules: List[PortRuleHTTP] = []
        http_index: Dict[PortRuleHTTP, int] = {}
        kafka_rules: List[PortRuleKafka] = []
        kafka_index: Dict[PortRuleKafka, int] = {}
        dns_rules: List[PortRuleDNS] = []
        dns_index: Dict[PortRuleDNS, int] = {}

        # generic (l7proto) rules: (proto, sorted (key, value) pairs);
        # an l7proto with no l7 constraints is the 0-pair allow-all rule
        gen_rules: List[Tuple[str, Tuple[Tuple[str, str], ...]]] = []
        gen_index: Dict[Tuple, int] = {}
        # protocol-FRONTEND rules: same (proto, pairs) shape, routed
        # to the l7g banked automaton (policy/compiler/frontends/) —
        # a proto with a registered frontend never compiles onto the
        # generic pair path, and an UNKNOWN proto (neither frontend
        # nor registered proxy parser) fails loudly right here
        from cilium_tpu.policy.compiler import frontends as _frontends

        fe_rules: List[Tuple[str, Tuple[Tuple[str, str], ...]]] = []
        fe_index: Dict[Tuple, int] = {}

        ruleset_key_to_id: Dict[Tuple, int] = {}
        # per ruleset: member rule ids in each protocol family's space —
        # a merged entry can carry several families (the oracle checks
        # all of them), so no single "dominant protocol" is picked
        ruleset_http: List[List[int]] = []
        ruleset_kafka: List[List[int]] = []
        ruleset_dns: List[List[int]] = []
        ruleset_gen: List[List[int]] = []
        ruleset_fe: List[List[int]] = []

        def intern_rule(table, index, rule):
            if rule not in index:
                index[rule] = len(table)
                table.append(rule)
            return index[rule]

        def ruleset_of(l7_rules_tuple: Tuple[L7Rules, ...]) -> int:
            http_ids, kafka_ids, dns_ids = [], [], []
            gen_ids, fe_ids = [], []
            for lr in l7_rules_tuple:
                for h in lr.http:
                    http_ids.append(intern_rule(http_rules, http_index, h))
                for k in lr.kafka:
                    kafka_ids.append(intern_rule(kafka_rules, kafka_index, k))
                for d in lr.dns:
                    dns_ids.append(intern_rule(dns_rules, dns_index, d))
                if lr.l7proto:
                    # the unified-registry check (ISSUE 15 satellite):
                    # an l7proto that is neither an engine frontend
                    # nor a registered proxy parser fails the COMPILE
                    # loudly instead of compiling to unmatched rules
                    _frontends.validate_l7proto(lr.l7proto)
                    fe = _frontends.get(lr.l7proto)
                    table, index, ids = (
                        (fe_rules, fe_index, fe_ids) if fe is not None
                        else (gen_rules, gen_index, gen_ids))
                    if not lr.l7:
                        ids.append(intern_rule(
                            table, index, (lr.l7proto, ())))
                    for g in lr.l7:
                        pairs = tuple(sorted(g.items()))
                        if fe is not None:
                            fe.validate_rule(pairs)
                        ids.append(intern_rule(
                            table, index, (lr.l7proto, pairs)))
            if not (http_ids or kafka_ids or dns_ids or gen_ids
                    or fe_ids):
                return -1
            key = (tuple(sorted(set(http_ids))),
                   tuple(sorted(set(kafka_ids))),
                   tuple(sorted(set(dns_ids))),
                   tuple(sorted(set(gen_ids))),
                   tuple(sorted(set(fe_ids))))
            rid = ruleset_key_to_id.get(key)
            if rid is None:
                rid = len(ruleset_http)
                ruleset_key_to_id[key] = rid
                ruleset_http.append(list(key[0]))
                ruleset_kafka.append(list(key[1]))
                ruleset_dns.append(list(key[2]))
                ruleset_gen.append(list(key[3]))
                ruleset_fe.append(list(key[4]))
            return rid

        # per-build memo keyed by the l7-rules tuple's OBJECT identity:
        # at fleet scale (10k identities over ~hundreds of shared
        # resolved MapStates) the same tuple reaches ruleset_of once
        # per identity — walking its rules every time is the dominant
        # per-update cost. The tuples stay alive for the whole build
        # (their entries hold them), so id() keys cannot be recycled.
        _ruleset_memo: Dict[int, int] = {}

        def ruleset_of_entry(ep, key, entry):
            rid = _ruleset_memo.get(id(entry.l7_rules))
            if rid is None:
                rid = ruleset_of(entry.l7_rules)
                _ruleset_memo[id(entry.l7_rules)] = rid
            return rid

        packed = pack_mapstate(
            per_identity,
            ruleset_of_entry=ruleset_of_entry,
        )

        # -- compile field matchers -------------------------------------
        path_matcher = _FieldMatcher.build(
            [h.path for h in http_rules if h.path], cfg,
            bank_cache=bank_cache, bank_registry=bank_registry,
            field="path")
        method_matcher = _FieldMatcher.build(
            [h.method for h in http_rules if h.method], cfg,
            bank_cache=bank_cache, bank_registry=bank_registry,
            field="method")
        host_matcher = _FieldMatcher.build(
            [h.host for h in http_rules if h.host], cfg,
            case_insensitive=True, bank_cache=bank_cache,
            bank_registry=bank_registry, field="host")
        from cilium_tpu.secrets import resolve_header_value

        header_pats: List[str] = []
        rule_header_lanes: List[List[str]] = []   # FAIL: gate the rule
        rule_log_lanes: List[List[str]] = []      # LOG: raise l7_log
        rule_dead: List[bool] = []   # FAIL w/ unresolvable secret
        header_rewrites: List[List[Tuple[str, str, str]]] = []
        for h in http_rules:
            pats = []
            log_pats = []
            rewrites: List[Tuple[str, str, str]] = []
            dead = False
            for hdr in h.headers:
                if ":" in hdr:
                    name, value = hdr.split(":", 1)
                else:
                    name, value = hdr, ""
                pats.append(header_requirement_regex(name, value))
            for hm in h.header_matches:
                action = hm.mismatch_action
                value = resolve_header_value(hm, secret_lookup)
                if action == "":
                    # FAIL: mismatch denies; an unresolvable secret
                    # kills the rule outright (fail closed)
                    if value is None:
                        dead = True
                    else:
                        pats.append(header_requirement_regex(
                            hm.name, value))
                elif action == "LOG":
                    if value is not None:
                        log_pats.append(header_requirement_regex(
                            hm.name, value))
                else:
                    # ADD/DELETE/REPLACE: never gate; the rewrite is
                    # proxy-side (exposed for the shim/Envoy layer)
                    rewrites.append((action, hm.name, value or ""))
            header_pats.extend(pats)
            header_pats.extend(log_pats)
            rule_header_lanes.append(pats)
            rule_log_lanes.append(log_pats)
            rule_dead.append(dead)
            header_rewrites.append(rewrites)
        header_matcher = _FieldMatcher.build(header_pats, cfg,
                                             bank_cache=bank_cache,
                                             bank_registry=bank_registry,
                                             field="hdr")

        dns_pats = []
        for d in dns_rules:
            if d.match_name:
                dns_pats.append(matchpattern.name_to_regex(d.match_name))
            else:
                dns_pats.append(matchpattern.to_regex(d.match_pattern))
        dns_matcher = _FieldMatcher.build(dns_pats, cfg,
                                          bank_cache=bank_cache,
                                          bank_registry=bank_registry,
                                          field="dns")

        # -- per-rule lane arrays ---------------------------------------
        # Rule-table row counts BUCKET past 64 (next multiple of 64):
        # every staged array sized by a rule count keeps its shape
        # across ±63 net rule adds, so incremental policy updates at
        # fleet scale reuse the jitted step's compiled executable
        # instead of paying an XLA recompile per update. Padded rows
        # are inert three ways over: lanes are -1, membership masks
        # never select them, and (for HTTP) the dead flag is set.
        # Small policies (≤64 rules) keep exact shapes.
        def _rbucket(n: int) -> int:
            return max(1, n) if n <= 64 else -(-n // 64) * 64

        Rh = _rbucket(len(http_rules))
        max_hdrs = max([len(p) for p in rule_header_lanes] + [1])
        max_logs = max([len(p) for p in rule_log_lanes] + [1])
        http_path_lane = np.full(Rh, -1, dtype=np.int32)
        http_method_lane = np.full(Rh, -1, dtype=np.int32)
        http_host_lane = np.full(Rh, -1, dtype=np.int32)
        http_header_lanes = np.full((Rh, max_hdrs), -1, dtype=np.int32)
        http_log_lanes = np.full((Rh, max_logs), -1, dtype=np.int32)
        http_rule_dead = np.zeros(Rh, dtype=bool)
        for i, h in enumerate(http_rules):
            if h.path:
                http_path_lane[i] = path_matcher.lane(h.path)
            if h.method:
                http_method_lane[i] = method_matcher.lane(h.method)
            if h.host:
                http_host_lane[i] = host_matcher.lane(h.host)
            for j, pat in enumerate(rule_header_lanes[i]):
                http_header_lanes[i, j] = header_matcher.lane(pat)
            for j, pat in enumerate(rule_log_lanes[i]):
                http_log_lanes[i, j] = header_matcher.lane(pat)
            http_rule_dead[i] = rule_dead[i]
        http_rule_dead[len(http_rules):] = True   # padding is inert

        Rk = _rbucket(len(kafka_rules))
        kafka_apikey_mask = np.zeros(Rk, dtype=np.uint32)   # 0 = any
        kafka_version = np.full(Rk, -1, dtype=np.int32)
        kafka_client = np.full(Rk, -1, dtype=np.int32)
        kafka_topic = np.full(Rk, -1, dtype=np.int32)
        client_intern: Dict[str, int] = {}
        topic_intern: Dict[str, int] = {}
        for i, k in enumerate(kafka_rules):
            for ak in k.allowed_api_keys():
                kafka_apikey_mask[i] |= np.uint32(1 << ak)
            if k.api_version:
                kafka_version[i] = int(k.api_version)
            if k.client_id:
                kafka_client[i] = client_intern.setdefault(
                    k.client_id, len(client_intern))
            if k.topic:
                kafka_topic[i] = topic_intern.setdefault(
                    k.topic, len(topic_intern))

        Rd = _rbucket(len(dns_rules))
        dns_lane = np.full(Rd, -1, dtype=np.int32)
        for i in range(len(dns_rules)):
            dns_lane[i] = dns_matcher.lane(dns_pats[i])

        # -- generic l7proto rules: proto + (key,value)-pair interning --
        # A rule matches a record when the record's pair-id set contains
        # every required pair id. Flows emit (proto,key,value) ids plus
        # (proto,key,"") presence ids; an empty rule value requires only
        # presence. Exact-value semantics, matching the oracle.
        gen_proto_intern: Dict[str, int] = {}
        gen_pair_intern: Dict[Tuple[str, str, str], int] = {}
        for proto, pairs in gen_rules:
            gen_proto_intern.setdefault(proto, len(gen_proto_intern))
            for k, v in pairs:
                gen_pair_intern.setdefault((proto, k, v),
                                           len(gen_pair_intern))
        Rg = _rbucket(len(gen_rules))
        gen_max_pairs = max([len(p) for _, p in gen_rules] + [1])
        gen_rule_proto = np.full(Rg, -1, dtype=np.int32)
        gen_rule_pairs = np.full((Rg, gen_max_pairs), -1, dtype=np.int32)
        for i, (proto, pairs) in enumerate(gen_rules):
            gen_rule_proto[i] = gen_proto_intern[proto]
            for j, (k, v) in enumerate(pairs):
                gen_rule_pairs[i, j] = gen_pair_intern[(proto, k, v)]

        # -- protocol-frontend rules: scan-field patterns + predicates --
        # Each frontend rule lowers (frontends.lower_rule) into (a)
        # one full-match pattern over its protocol's SCAN FIELD value
        # — compiled through the same content-defined bank pipeline
        # as the HTTP/DNS fields (bankplan partition → CompileQueue →
        # quarantine/artifacts), read off the l7g scan as a lane —
        # and (b) interned enum/presence predicates matched by the
        # generic pair-subset check. Exact-value patterns keep the
        # bank subset construction trie-shaped, so the universe
        # compiles in time linear in total literal length. An
        # unsatisfiable rule (two exact scan values — the oracle can
        # never match it either) compiles DEAD.
        l7g_matcher: Optional[_FieldMatcher] = None
        fe_lane = np.full(max(1, _rbucket(len(fe_rules))), -1,
                          dtype=np.int32)
        fe_family = np.full(len(fe_lane), -1, dtype=np.int32)
        fe_dead = np.zeros(len(fe_lane), dtype=bool)
        fe_dead[len(fe_rules):] = True       # padding is inert
        fe_max_pairs = 1
        fe_pairs = np.full((len(fe_lane), fe_max_pairs), -1,
                           dtype=np.int32)
        if fe_rules:
            lowered = [_frontends.get(proto).lower_rule(pairs)
                       for proto, pairs in fe_rules]
            for lo in lowered:
                for t in lo.pairs:
                    gen_pair_intern.setdefault(t,
                                               len(gen_pair_intern))
            fe_max_pairs = max([len(lo.pairs) for lo in lowered] + [1])
            fe_pairs = np.full((len(fe_lane), fe_max_pairs), -1,
                               dtype=np.int32)
            l7g_matcher = _FieldMatcher.build(
                [lo.pattern for lo in lowered
                 if lo.pattern is not None], cfg,
                bank_cache=bank_cache, bank_registry=bank_registry,
                field="l7g")
            for i, ((proto, _pairs), lo) in enumerate(
                    zip(fe_rules, lowered)):
                fe_family[i] = _frontends.family_of(proto)
                if lo.dead:
                    fe_dead[i] = True
                    continue
                if lo.pattern is not None:
                    fe_lane[i] = l7g_matcher.lane(lo.pattern)
                for j, t in enumerate(lo.pairs):
                    fe_pairs[i, j] = gen_pair_intern[t]

        # -- ruleset masks ----------------------------------------------
        http_members = ruleset_http
        kafka_members = ruleset_kafka
        dns_members = ruleset_dns

        arrays: Dict[str, np.ndarray] = {
            "audit_mode": np.array(audit, dtype=bool),
            "ms_key_w0": packed.key_w0,
            "ms_key_w1": packed.key_w1,
            "ms_key_w2": packed.key_w2,
            "ms_deny": packed.is_deny,
            "ms_ruleset": packed.ruleset_id,
            "ms_auth": packed.auth,
            "ms_enf_ids": packed.enf_ids,
            "ms_enf_flags": packed.enf_flags,
            "ms_plens": packed.port_plens,
            "ms_tmpl_ids": packed.tmpl_ids,
            # mask widths follow the BUCKETED rule counts so they
            # shape-stabilize with the lane arrays (padded bits stay 0)
            "rs_http_mask": _masks_to_array(http_members or [[]], Rh),
            "rs_kafka_mask": _masks_to_array(kafka_members or [[]],
                                             Rk),
            "rs_dns_mask": _masks_to_array(dns_members or [[]], Rd),
            "rs_gen_mask": _masks_to_array(ruleset_gen or [[]], Rg),
            "gen_rule_proto": gen_rule_proto,
            "gen_rule_pairs": gen_rule_pairs,
            "http_path_lane": http_path_lane,
            "http_method_lane": http_method_lane,
            "http_host_lane": http_host_lane,
            "http_header_lanes": http_header_lanes,
            "http_log_lanes": http_log_lanes,
            "http_rule_dead": http_rule_dead,
            "kafka_apikey_mask": kafka_apikey_mask,
            "kafka_version": kafka_version,
            "kafka_client": kafka_client,
            "kafka_topic": kafka_topic,
            "dns_lane": dns_lane,
        }
        matcher_stacks = [
            ("path", path_matcher),
            ("method", method_matcher),
            ("host", host_matcher),
            ("hdr", header_matcher),
            ("dns", dns_matcher),
        ]
        if l7g_matcher is not None:
            # the l7g stack + fe rule arrays exist ONLY when frontend
            # rules do: policies without them stage byte-identical
            # arrays (and every l7g code path is statically skipped
            # under jit — "l7g_trans" is the one gate)
            matcher_stacks.append(("l7g", l7g_matcher))
            arrays["rs_fe_mask"] = _masks_to_array(
                ruleset_fe or [[]], len(fe_lane))
            arrays["fe_lane"] = fe_lane
            arrays["fe_family"] = fe_family
            arrays["fe_dead"] = fe_dead
            arrays["fe_pairs"] = fe_pairs
        for prefix, m in matcher_stacks:
            for k, v in m.arrays.items():
                if k != "lane_of":
                    arrays[f"{prefix}_{k}"] = v

        # fixed per-flow pair-slot width: a flow can emit at most two ids
        # per field (value + presence) and never more than the interned
        # universe; deriving it from the POLICY keeps verdict_step's jit
        # shape static across batches (no data-driven recompiles)
        gen_fmax = max(4, min(len(gen_pair_intern),
                              2 * cfg.max_generic_fields))
        gen_fmax = -(-gen_fmax // 4) * 4

        bank_plan: Dict[str, Tuple[str, ...]] = {}
        bank_quarantined: List[str] = []
        for _prefix, m in matcher_stacks:
            st = m.bank_stats
            if st is not None:
                bank_plan[st.field] = st.bank_keys
                bank_quarantined.extend(st.quarantined)

        # factored resolve plan (engine/megakernel.py): rule-signature
        # groups + group-accept planes over the path automaton — the
        # rp_* arrays stage to device with everything else; the fused
        # step falls back to the legacy per-rule resolve when absent
        from cilium_tpu.engine import megakernel as _mk

        resolve_meta = None
        plan = _mk.build_resolve_plan(arrays, len(http_rules),
                                      len(dns_rules),
                                      n_kafka=len(kafka_rules),
                                      n_gen=len(gen_rules),
                                      n_fe=len(fe_rules))
        if plan is not None:
            rp_arrays, resolve_meta = plan
            arrays.update(rp_arrays)

        return cls(
            mapstate=packed,
            arrays=arrays,
            http_rules=http_rules,
            kafka_rules=kafka_rules,
            dns_rules=dns_rules,
            gen_rules=gen_rules,
            kafka_interns={"client_id": client_intern, "topic": topic_intern,
                           "gen_protos": gen_proto_intern,
                           "gen_pairs": gen_pair_intern,
                           "gen_fmax": gen_fmax},
            path_matcher=path_matcher,
            method_matcher=method_matcher,
            host_matcher=host_matcher,
            header_matcher=header_matcher,
            dns_matcher=dns_matcher,
            revision=revision,
            header_rewrites=header_rewrites,
            bank_plan=bank_plan,
            bank_quarantined=tuple(bank_quarantined),
            resolve_meta=resolve_meta,
            fe_rules=fe_rules,
            l7g_matcher=l7g_matcher,
        )


# ----------------------------------------------------------------- engine --
@dataclasses.dataclass
class FlowBatch:
    """Host-encoded flow tensors (all numpy; shapes static per bucket)."""

    ep_ids: np.ndarray
    peer_ids: np.ndarray
    dports: np.ndarray
    protos: np.ndarray
    directions: np.ndarray
    l7_types: np.ndarray
    path: Tuple[np.ndarray, np.ndarray, np.ndarray]
    method: Tuple[np.ndarray, np.ndarray, np.ndarray]
    host: Tuple[np.ndarray, np.ndarray, np.ndarray]
    headers: Tuple[np.ndarray, np.ndarray, np.ndarray]
    qname: Tuple[np.ndarray, np.ndarray, np.ndarray]
    kafka_api_key: np.ndarray
    kafka_api_version: np.ndarray
    kafka_client: np.ndarray
    kafka_topic: np.ndarray
    gen_proto: np.ndarray     # [B] interned l7proto id, -2 = none/unknown
    gen_pairs: np.ndarray     # [B, F] interned (proto,key,value) ids, -2 pad
    #: canonical serialized frontend record bytes (the l7g automaton's
    #: input; empty for non-frontend flows) — (data, len, valid)
    l7g: Tuple[np.ndarray, np.ndarray, np.ndarray] = None

    @property
    def size(self) -> int:
        return len(self.ep_ids)


def encode_flows(
    flows: Sequence[Flow],
    interns: Dict[str, Dict[str, int]],
    cfg: Optional[EngineConfig] = None,
) -> FlowBatch:
    """Featurize flows → FlowBatch (the host half of ingest; mirrors the
    reference's parse step feeding the verdict lookup)."""
    cfg = cfg or EngineConfig()
    B = len(flows)
    ep = np.zeros(B, dtype=np.int32)
    peer = np.zeros(B, dtype=np.int32)
    dport = np.zeros(B, dtype=np.int32)
    proto = np.zeros(B, dtype=np.int32)
    dirs = np.zeros(B, dtype=np.int32)
    l7t = np.zeros(B, dtype=np.int32)
    paths: List[bytes] = []
    methods: List[bytes] = []
    hosts: List[bytes] = []
    headerblocks: List[bytes] = []
    qnames: List[bytes] = []
    k_api = np.zeros(B, dtype=np.int32)
    k_ver = np.zeros(B, dtype=np.int32)
    k_cli = np.full(B, -2, dtype=np.int32)
    k_top = np.full(B, -2, dtype=np.int32)
    cintern = interns.get("client_id", {})
    tintern = interns.get("topic", {})
    gproto_intern = interns.get("gen_protos", {})
    gpair_intern = interns.get("gen_pairs", {})
    g_proto = np.full(B, -2, dtype=np.int32)
    g_pair_lists: List[List[int]] = [[] for _ in range(B)]
    from cilium_tpu.policy.compiler import frontends as _frontends

    l7g_strings: List[bytes] = []
    for i, f in enumerate(flows):
        ingress = f.direction == TrafficDirection.INGRESS
        ep[i] = f.dst_identity if ingress else f.src_identity
        peer[i] = f.src_identity if ingress else f.dst_identity
        dport[i] = f.dport
        proto[i] = int(f.protocol)
        dirs[i] = int(f.direction)
        l7t[i] = int(f.l7)
        h = f.http
        paths.append((h.path if h else "").encode("utf-8"))
        methods.append((h.method if h else "").encode("utf-8"))
        hosts.append((h.host.lower() if h else "").encode("utf-8"))
        headerblocks.append(serialize_headers(h.headers) if h else b"")
        d = f.dns
        qnames.append(
            matchpattern.sanitize_name(d.query).encode("utf-8")
            if d and d.query else b"")
        k = f.kafka
        if k:
            k_api[i] = k.api_key
            k_ver[i] = k.api_version
            k_cli[i] = cintern.get(k.client_id, -2)
            k_top[i] = tintern.get(k.topic, -2)
        g = f.generic
        fam = _frontends.family_of(g.proto) if g is not None else 0
        if fam:
            # frontend-routed record: the l7-type lane NORMALIZES to
            # the frontend family (memo row mirror + per-family
            # invalidation + the fe family gate key on it) and the
            # SCAN FIELD's value feeds the l7g automaton; the enum
            # predicates ride the shared pair-id probing below
            # (gen_proto stays -2 so generic rules never see it)
            l7t[i] = fam
            l7g_strings.append(_frontends.scan_value(g.proto,
                                                     g.fields))
        else:
            l7g_strings.append(b"")
        if g is not None:
            if not fam:
                g_proto[i] = gproto_intern.get(g.proto, -2)
            # only interned ids matter — pairs no rule references can
            # never satisfy a requirement (deduped: a field emits at
            # most one value id + one presence id). Sorted key order:
            # the capture path (_gen_intern_rows) reproduces this
            # exact id sequence, so Fmax truncation selects the SAME
            # subset live and on replay. Frontend records probe the
            # same table: their enum/presence predicates intern there.
            seen: set = set()
            for key, val in sorted(g.fields.items()):
                for probe in ((g.proto, key, val), (g.proto, key, "")):
                    pid = gpair_intern.get(probe)
                    if pid is not None and pid not in seen:
                        seen.add(pid)
                        g_pair_lists[i].append(pid)
    Fmax = int(interns.get("gen_fmax", 4))
    g_pairs = np.full((B, Fmax), -2, dtype=np.int32)
    for i, pl in enumerate(g_pair_lists):
        g_pairs[i, :min(len(pl), Fmax)] = pl[:Fmax]
    bucket = max(cfg.http_path_buckets)
    return FlowBatch(
        ep_ids=ep, peer_ids=peer, dports=dport, protos=proto,
        directions=dirs, l7_types=l7t,
        path=encode_strings(paths, bucket),
        method=encode_strings(methods, cfg.http_method_len),
        host=encode_strings(hosts, cfg.http_host_len),
        headers=encode_strings(headerblocks, 1024),
        qname=encode_strings(qnames, cfg.dns_name_len),
        kafka_api_key=k_api, kafka_api_version=k_ver,
        kafka_client=k_cli, kafka_topic=k_top,
        gen_proto=g_proto, gen_pairs=g_pairs,
        l7g=encode_strings(l7g_strings, cfg.l7g_len),
    )


def encode_records(rec, cfg: Optional[EngineConfig] = None,
                   fmax: int = 4) -> FlowBatch:
    """Vectorized FlowBatch straight from binary capture records
    (``ingest/binary.py`` structured arrays) — no per-flow Python
    objects anywhere between disk and device. Records are L3/L4
    tuples by format (L7 payloads ride JSONL), so every string field
    encodes empty and L7 interning is skipped wholesale.
    """
    cfg = cfg or EngineConfig()
    B = len(rec)
    ingress = rec["direction"] == int(TrafficDirection.INGRESS)
    ep = np.where(ingress, rec["dst_identity"],
                  rec["src_identity"]).astype(np.int32)
    peer = np.where(ingress, rec["src_identity"],
                    rec["dst_identity"]).astype(np.int32)

    def empty_field(width: int):
        # same width an all-empty batch gets from encode_strings
        # (min(max_len, one 32-byte pad block)): record batches then
        # share the flows path's jit cache entry instead of compiling
        # their own, and the empty buffers transfer 8-32x less
        width = min(width, 32)
        return (np.zeros((B, width), dtype=np.uint8),
                np.zeros(B, dtype=np.int32),
                np.ones(B, dtype=bool))

    return FlowBatch(
        ep_ids=ep, peer_ids=peer,
        dports=rec["dport"].astype(np.int32),
        protos=rec["proto"].astype(np.int32),
        directions=rec["direction"].astype(np.int32),
        l7_types=rec["l7_type"].astype(np.int32),
        path=empty_field(max(cfg.http_path_buckets)),
        method=empty_field(cfg.http_method_len),
        host=empty_field(cfg.http_host_len),
        headers=empty_field(1024),
        qname=empty_field(cfg.dns_name_len),
        kafka_api_key=np.zeros(B, dtype=np.int32),
        kafka_api_version=np.zeros(B, dtype=np.int32),
        kafka_client=np.full(B, -2, dtype=np.int32),
        kafka_topic=np.full(B, -2, dtype=np.int32),
        gen_proto=np.full(B, -2, dtype=np.int32),
        # fmax mirrors encode_flows' interned width so record batches
        # share the flows path's jit cache entry
        gen_pairs=np.full((B, fmax), -2, dtype=np.int32),
        l7g=empty_field(cfg.l7g_len),
    )


def _gather_table_field(blob: np.ndarray, offsets: np.ndarray,
                        idx: np.ndarray, max_len: int,
                        pad_multiple: int = 32,
                        fixed_len: Optional[int] = None):
    """Vectorized :func:`encode_strings` over a capture string table:
    ``idx`` [B] references strings in (offsets, blob); returns the same
    (data [B, L] u8, lengths, valid) triple — built entirely from numpy
    gathers (unique → fill → scatter back), no per-flow Python.
    ``fixed_len`` pins the padded width (chunked replay: every chunk
    must produce identical shapes so the jitted step compiles once)."""
    uniq, inv = np.unique(idx, return_inverse=True)
    starts = offsets[uniq].astype(np.int64)
    lens = offsets[uniq + 1].astype(np.int64) - starts
    if fixed_len is not None:
        L = fixed_len
    else:
        longest = int(lens.max()) if len(lens) else 1
        L = min(max_len,
                max(pad_multiple, -(-max(longest, 1) // pad_multiple)
                    * pad_multiple))
    valid_u = lens <= L
    lens_u = np.minimum(lens, L)
    pos = np.arange(L, dtype=np.int64)
    gidx = starts[:, None] + pos[None, :]
    mask = pos[None, :] < lens_u[:, None]
    if blob.size:
        data_u = np.where(mask, blob[np.minimum(gidx, blob.size - 1)], 0)
    else:
        data_u = np.zeros((len(uniq), L), dtype=np.uint8)
    return (data_u.astype(np.uint8, copy=False)[inv],
            lens_u.astype(np.int32)[inv], valid_u[inv])


def _intern_lut(offsets: np.ndarray, blob: np.ndarray, idx: np.ndarray,
                intern: Dict[str, int]) -> np.ndarray:
    """Map string-table indices → engine intern ids (-2 = unknown),
    resolving each UNIQUE string once."""
    uniq, inv = np.unique(idx, return_inverse=True)
    lut = np.full(len(uniq), -2, dtype=np.int32)
    for j, u in enumerate(uniq):
        s = blob[int(offsets[u]):int(offsets[u + 1])].tobytes()
        lut[j] = intern.get(s.decode("utf-8", "replace"), -2)
    return lut[inv]


def _gen_intern_rows(gen, offsets: np.ndarray, blob: np.ndarray,
                     interns: Dict[str, Dict]) -> np.ndarray:
    """v3 GENERIC section → row-aligned engine columns: one
    ``[N, 1 + gen_fmax]`` int32 block (col 0 = interned l7proto id,
    rest = interned pair ids, -2 pad). The (proto, key, value) triple
    resolution runs once per UNIQUE triple; per-row assembly is
    vectorized (dedup + left-pack), mirroring ``encode_flows``'s
    value-id + presence-id probing — set semantics, so slot order
    doesn't matter to the engine's membership check."""
    N = len(gen)
    Fe = int(interns.get("gen_fmax", 4))
    out = np.full((N, 1 + Fe), -2, dtype=np.int32)
    if N == 0:
        return out
    gproto = interns.get("gen_protos", {})
    gpair = interns.get("gen_pairs", {})
    proto_idx = np.asarray(gen["proto"], dtype=np.int64)
    out[:, 0] = _intern_lut(offsets, blob, proto_idx, gproto)
    pairs = np.asarray(gen["pairs"], dtype=np.int64)     # [N, F, 2]
    F = pairs.shape[1]
    triples = np.concatenate(
        [np.repeat(proto_idx, F)[:, None], pairs.reshape(-1, 2)],
        axis=1)                                          # [N*F, 3]
    uniq, inv = np.unique(triples, axis=0, return_inverse=True)

    def s(i: int) -> str:
        return blob[int(offsets[i]):int(offsets[i + 1])] \
            .tobytes().decode("utf-8", "replace")

    vid = np.full(len(uniq), -2, dtype=np.int32)
    pid = np.full(len(uniq), -2, dtype=np.int32)
    for j, (p, k, v) in enumerate(uniq):
        if k == 0:
            continue  # string 0 = "" = unused pair slot
        ps, ks, vs = s(int(p)), s(int(k)), s(int(v))
        vid[j] = gpair.get((ps, ks, vs), -2)
        pid[j] = gpair.get((ps, ks, ""), -2)
    # interleave value-id then presence-id per pair slot — the capture
    # writes pairs in sorted-key order and encode_flows probes
    # (value, presence) per sorted key, so this candidate sequence is
    # the SAME id sequence the live path builds; first-occurrence
    # dedup + left-pack + Fe cap therefore select an identical subset
    # (live/replay verdict parity even under Fmax truncation)
    cand = np.empty((N, 2 * F), dtype=np.int32)
    cand[:, 0::2] = vid[inv].reshape(N, F)
    cand[:, 1::2] = pid[inv].reshape(N, F)
    dup = np.zeros_like(cand, dtype=bool)
    for j in range(1, 2 * F):  # F is small (pair slots per flow)
        dup[:, j] = (cand[:, :j] == cand[:, j:j + 1]).any(axis=1)
    c = np.where(dup, -2, cand)
    order = np.argsort(c == -2, axis=1, kind="stable")
    packed = np.take_along_axis(c, order, axis=1)
    if packed.shape[1] < Fe:
        packed = np.pad(packed, ((0, 0), (0, Fe - packed.shape[1])),
                        constant_values=-2)
    out[:, 1:] = packed[:, :Fe]
    return out



def _gen_l7g_cols(gen, offsets: np.ndarray, blob: np.ndarray):
    """v3 GENERIC section → the frontend columns every capture path
    shares: ``(fam [N] int32, uniq_scan List[bytes], row [N] int32)``
    where ``fam`` is the frontend family id (0 = not a frontend
    record), ``uniq_scan`` the deduped SCAN-FIELD values
    (frontends.scan_value; index 0 is always empty), and ``row[i]``
    indexes a record's scan bytes in that list. The (proto,
    pair-row) → scan-value work runs once per UNIQUE section row —
    capture traffic repeats its records heavily, which is the same
    dedup the string tables ride."""
    from cilium_tpu.policy.compiler import frontends as _frontends

    N = len(gen)
    fam = np.zeros(N, dtype=np.int32)
    row = np.zeros(N, dtype=np.int32)
    uniq_serialized: List[bytes] = [b""]
    if N == 0:
        return fam, uniq_serialized, row
    proto_idx = np.asarray(gen["proto"], dtype=np.int64)
    pairs = np.asarray(gen["pairs"], dtype=np.int64)    # [N, F, 2]
    whole = np.concatenate(
        [proto_idx[:, None], pairs.reshape(N, -1)], axis=1)
    uniq, inv = np.unique(whole, axis=0, return_inverse=True)

    def s(i: int) -> str:
        return blob[int(offsets[i]):int(offsets[i + 1])] \
            .tobytes().decode("utf-8", "replace")

    ser_of = np.zeros(len(uniq), dtype=np.int32)
    fam_of = np.zeros(len(uniq), dtype=np.int32)
    index: Dict[bytes, int] = {b"": 0}
    for j, u in enumerate(uniq):
        proto = s(int(u[0]))
        f = _frontends.family_of(proto)
        if not f:
            continue
        fields = {}
        for k_idx, v_idx in u[1:].reshape(-1, 2):
            if k_idx:           # string 0 = "" = unused pair slot
                fields[s(int(k_idx))] = s(int(v_idx))
        ser = _frontends.scan_value(proto, fields)
        rid = index.get(ser)
        if rid is None:
            rid = index[ser] = len(uniq_serialized)
            uniq_serialized.append(ser)
        ser_of[j] = rid
        fam_of[j] = f
    fam[:] = fam_of[inv]
    row[:] = ser_of[inv]
    return fam, uniq_serialized, row


def _pad_rows_pow2(*arrays):
    """Pad each array's FIRST axis (same length across arrays) with
    zeros up to the next power of two — shape buckets so the jitted
    scans/gathers hit the persistent XLA cache across captures instead
    of compiling per-file exact sizes. Padded rows must never be
    referenced (valid-masked or absent from every id stream)."""
    n = len(arrays[0])
    S_pad = 1 << max(0, (max(1, n) - 1)).bit_length()
    if S_pad == n:
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(
        np.concatenate(
            [a, np.zeros((S_pad - n,) + a.shape[1:], dtype=a.dtype)])
        for a in arrays)
    return out if len(out) > 1 else out[0]


class CaptureFeaturizer:
    """Chunked-replay featurizer over one v2 capture: pays the string
    work ONCE per file, then each chunk is pure row gathers.

    At construction, every string each field references is encoded
    into a padded per-field table ([S_used, L] u8 + lengths + valid),
    kafka strings resolve to engine intern ids, and a string-table →
    row LUT is built per field. ``encode(rec, l7)`` then featurizes a
    chunk with ~8 numpy row-gathers — this is what lets file→verdict
    replay keep pace with the device (north star "replaying a Hubble
    capture"; the reference's per-request parse has no analog of this
    because its datapath consumes one packet at a time)."""

    _FIELD_CAPS = (("path", "http_path_buckets"),
                   ("method", "http_method_len"),
                   ("host", "http_host_len"),
                   ("headers", None),      # fixed 1024 cap
                   ("qname", "dns_name_len"))

    def __init__(self, l7, offsets, blob, interns: Dict[str, Dict],
                 cfg: Optional[EngineConfig] = None, gen=None):
        cfg = cfg or EngineConfig()
        self.cfg = cfg
        self.interns = interns
        self.fmax = int(interns.get("gen_fmax", 4))
        self.widths = capture_field_widths(l7, offsets, cfg)
        #: v3 captures: whole-capture generic columns, row-aligned
        #: ([N, 3+fmax] int32: interned proto id, frontend family id
        #: (0 = not a frontend record), row into the staged l7g
        #: string table, then the interned pair ids); chunk callers
        #: pass the slice matching their record slice to
        #: :meth:`encode_rows`
        self.gen_rows = None
        self._l7g_uniq = None
        if gen is not None:
            gen_block = _gen_intern_rows(gen, offsets, blob, interns)
            fam, uniq_ser, l7g_row = _gen_l7g_cols(gen, offsets, blob)
            self._l7g_uniq = uniq_ser
            self.gen_rows = np.concatenate(
                [gen_block[:, :1], fam[:, None].astype(np.int32),
                 l7g_row[:, None].astype(np.int32), gen_block[:, 1:]],
                axis=1)
        n_strings = len(offsets) - 1
        self.tables: Dict[str, tuple] = {}
        self.luts: Dict[str, np.ndarray] = {}
        for field, _ in self._FIELD_CAPS:
            used = np.unique(l7[field])
            data, lens, valid = _gather_table_field(
                blob, offsets, used, self.widths[field],
                fixed_len=self.widths[field])
            # shape-bucket the string count (_pad_rows_pow2): the
            # staged table scan (stage_capture_tables) then hits the
            # persistent XLA cache across captures — a fresh TPU
            # compile through the tunnel is 10-20s per shape
            data, lens, valid = _pad_rows_pow2(data, lens, valid)
            lut = np.zeros(n_strings, dtype=np.int32)
            lut[used] = np.arange(len(used), dtype=np.int32)
            self.tables[field] = (data, lens, valid)
            self.luts[field] = lut
        if self._l7g_uniq is not None:
            # frontend record serializations as one more staged string
            # table (scanned through the l7g automaton when the policy
            # carries frontend rules); no LUT — l7g_rows already
            # indexes this table directly
            self.tables["l7g"] = _pad_rows_pow2(
                *encode_strings(self._l7g_uniq, cfg.l7g_len))
        for col, key in (("kafka_client", "client_id"),
                         ("kafka_topic", "topic")):
            used = np.unique(l7[col])
            ids = _intern_lut(offsets, blob, used, interns.get(key, {}))
            lut = np.full(n_strings, -2, dtype=np.int32)
            lut[used] = ids
            self.luts[col] = lut

    def _field(self, name: str, idx: np.ndarray):
        data, lens, valid = self.tables[name]
        rows = self.luts[name][idx]
        return data[rows], lens[rows], valid[rows]

    def encode_rows(self, rec, l7, gen_rows=None) -> np.ndarray:
        """Chunk → ONE [B, 15] int32 block for
        :func:`verdict_step_capture`: per-flow scalars plus per-field
        ROW indices into the staged table match-words — the string
        bytes themselves never leave the string table (scanned once
        per file on device). ~0.3ms per 10k flows. ``gen_rows`` (the
        chunk's slice of :attr:`gen_rows`, v3 captures) appends the
        generic proto/pair columns → [B, 16 + gen_fmax]."""
        rec = np.asarray(rec)
        B = len(rec)
        out = np.empty((B, len(_ROW_COLS)), dtype=np.int32)
        col = {c: i for i, c in enumerate(_ROW_COLS)}
        ingress = rec["direction"] == int(TrafficDirection.INGRESS)
        out[:, col["ep_ids"]] = np.where(
            ingress, rec["dst_identity"], rec["src_identity"])
        out[:, col["peer_ids"]] = np.where(
            ingress, rec["src_identity"], rec["dst_identity"])
        out[:, col["dports"]] = rec["dport"]
        out[:, col["protos"]] = rec["proto"]
        out[:, col["directions"]] = rec["direction"]
        out[:, col["l7_types"]] = rec["l7_type"]
        out[:, col["kafka_api_key"]] = l7["kafka_api_key"]
        out[:, col["kafka_api_version"]] = l7["kafka_api_version"]
        out[:, col["kafka_client"]] = \
            self.luts["kafka_client"][l7["kafka_client"]]
        out[:, col["kafka_topic"]] = \
            self.luts["kafka_topic"][l7["kafka_topic"]]
        for name, _ in self._FIELD_CAPS:
            out[:, col[f"{name}_row"]] = self.luts[name][l7[name]]
        if gen_rows is not None:
            gen_rows = np.asarray(gen_rows, dtype=np.int32)
            # frontend records normalize the l7-type lane to their
            # family (gen col 1) — what keys the fe lane on device
            # and the (ep, l7type, dport) memo mirror host-side;
            # identical to encode_flows' live normalization
            fam = gen_rows[:, 1]
            out[:, col["l7_types"]] = np.where(
                fam > 0, fam, out[:, col["l7_types"]])
            out = np.concatenate([out, gen_rows], axis=1)
        return out

    def encode(self, rec, l7) -> FlowBatch:
        ingress = rec["direction"] == int(TrafficDirection.INGRESS)
        ep = np.where(ingress, rec["dst_identity"],
                      rec["src_identity"]).astype(np.int32)
        peer = np.where(ingress, rec["src_identity"],
                        rec["dst_identity"]).astype(np.int32)
        B = len(rec)
        return FlowBatch(
            ep_ids=ep, peer_ids=peer,
            dports=rec["dport"].astype(np.int32),
            protos=rec["proto"].astype(np.int32),
            directions=rec["direction"].astype(np.int32),
            l7_types=rec["l7_type"].astype(np.int32),
            path=self._field("path", l7["path"]),
            method=self._field("method", l7["method"]),
            host=self._field("host", l7["host"]),
            headers=self._field("headers", l7["headers"]),
            qname=self._field("qname", l7["qname"]),
            kafka_api_key=l7["kafka_api_key"].astype(np.int32),
            kafka_api_version=l7["kafka_api_version"].astype(np.int32),
            kafka_client=self.luts["kafka_client"][l7["kafka_client"]],
            kafka_topic=self.luts["kafka_topic"][l7["kafka_topic"]],
            gen_proto=np.full(B, -2, dtype=np.int32),
            gen_pairs=np.full((B, self.fmax), -2, dtype=np.int32),
            l7g=(np.zeros((B, 32), dtype=np.uint8),
                 np.zeros(B, dtype=np.int32),
                 np.ones(B, dtype=bool)),
        )


#: Column order of the [B, 15] "rows" block verdict_step_capture
#: consumes (see CaptureFeaturizer.encode_rows).
_ROW_COLS = (
    "ep_ids", "peer_ids", "dports", "protos", "directions", "l7_types",
    "kafka_api_key", "kafka_api_version", "kafka_client", "kafka_topic",
    "path_row", "method_row", "host_row", "headers_row", "qname_row",
)


#: (field, policy-array prefix) pairs of the staged string tables
_TABLE_FIELDS = (("path", "path"), ("method", "method"),
                 ("host", "host"), ("headers", "hdr"),
                 ("qname", "dns"))


def _stage_tables_step(arrays: Dict[str, jax.Array],
                       tables: Dict[str, tuple],
                       impl: str = "gather",
                       interpret: Optional[bool] = None
                       ) -> Dict[str, jax.Array]:
    """All five per-field table scans as ONE traced program. Fusing
    them matters twice over: one dispatch instead of ~40 eager ops per
    staging (the eager per-field loop cost ~0.3s of pure dispatch on
    CPU), and one XLA executable big enough to clear the persistent
    compilation cache's min-compile-time bar — a fresh process restages
    a repeat capture shape from disk in milliseconds instead of
    recompiling five sub-threshold programs (~2s, the dominant
    stage_ms phase of the tier-1 CPU config).

    With a factored resolve plan staged (``rp_path_gaccept``,
    engine/megakernel.py) the path table also emits per-row GROUP
    words — a second accept read off the same final states, bank-ORed
    into the ``"path_groups"`` table the fused capture resolve
    gathers. ``impl``/``interpret`` are trace-static (the engine
    resolves them at staging; see dfa_kernel.resolve_impl)."""
    tw: Dict[str, jax.Array] = {}
    table_fields = _TABLE_FIELDS
    if "l7g_trans" in arrays and "l7g" in tables:
        # frontend serialized-record table: scanned through the l7g
        # automaton exactly like the five string fields (static under
        # jit — policies without frontend rules skip it wholesale)
        table_fields = table_fields + (("l7g", "l7g"),)
    for field, prefix in table_fields:
        data, lens, valid = tables[field]
        want_groups = field == "path" and "rp_path_gaccept" in arrays
        out = dfa_scan_banked(
            arrays[f"{prefix}_trans"], arrays[f"{prefix}_byteclass"],
            arrays[f"{prefix}_start"], arrays[f"{prefix}_accept"],
            data, lens, impl=impl, interpret=interpret,
            extra_accept=(arrays["rp_path_gaccept"] if want_groups
                          else None))
        if want_groups:
            words, gw3 = out
            gwords = jax.lax.reduce(gw3, jnp.uint32(0),
                                    jax.lax.bitwise_or, (1,))
            tw["path_groups"] = jnp.where(valid[:, None], gwords, 0)
        else:
            words = out
        flat = words.reshape(data.shape[0], -1)
        tw[field] = jnp.where(valid[:, None], flat, 0)
    return tw


@functools.lru_cache(maxsize=8)
def _stage_tables_jit(impl: str, interpret: Optional[bool]):
    """One jitted staging program per (impl, interpret) static pair —
    the env/backend picks resolve on the host, never under trace."""
    return jax.jit(functools.partial(_stage_tables_step, impl=impl,
                                     interpret=interpret))

from cilium_tpu.engine.memo import memo_pack as _memo_pack  # noqa: E402

#: jitted verdict-output → [N, 9] int32 packer (memo fill path)
_MEMO_PACK_STEP = jax.jit(_memo_pack)


def stage_capture_tables(engine: "VerdictEngine",
                         feat: CaptureFeaturizer) -> Dict[str, jax.Array]:
    """Scan each per-field string table through its banked DFA ONCE and
    keep the match words on device ([S_used, NW] per field, invalid
    rows zeroed). The reference memoizes per-string regex results in an
    LRU (``pkg/fqdn/re``); here the whole capture string table is the
    cache, computed in one batched scan — per-chunk replay then only
    GATHERS match words by row index (:func:`verdict_step_capture`),
    so the DFA cost scales with UNIQUE strings, not flows. All five
    fields scan in one fused jitted program (:func:`_stage_tables_step`)
    so staging costs one dispatch and one persistently-cacheable
    compile."""
    host_tables = {field: feat.tables[field]
                   for field, _ in _TABLE_FIELDS}
    if "l7g" in feat.tables and "l7g_trans" in engine._arrays:
        host_tables["l7g"] = feat.tables["l7g"]
    # one batched pytree transfer, not one device_put per field
    tables = jax.device_put(host_tables, engine.device)
    step = _stage_tables_jit(getattr(engine, "_dfa_impl", "gather"),
                             getattr(engine, "_interpret", None))
    return step(engine._arrays, tables)


def verdict_step_capture(arrays: Dict[str, jax.Array],
                         table_words: Dict[str, jax.Array],
                         batch: Dict[str, jax.Array]
                         ) -> Dict[str, jax.Array]:
    """:func:`verdict_step` specialized for v2/v3-capture replay:
    string match words come from the staged per-file tables (gathered
    by row index) instead of per-flow DFA scans, then the shared
    :func:`_verdict_core` assembles the verdict — capture replay and
    live verdicts share one implementation of the semantics. A v3
    capture's generic columns ride the SAME row block (cols 15+:
    interned proto id + pair ids), so generic traffic costs no extra
    device argument; v2 row blocks are [B, 15] and skip the family.

    With ``batch["idx"]`` present (deduplicated replay,
    :meth:`CaptureReplay.stage_unique`), ``rows`` is the capture's
    UNIQUE-row table and ``idx`` the per-flow row ids: flows expand by
    an on-device gather, so the host→device stream carries 2–4 bytes
    per flow instead of 60+ — the same unique-then-gather shape the
    string tables use, one level up. Every flow is still verdicted
    individually after the gather."""
    rows = batch["rows"]
    idx = batch.get("idx")
    if idx is not None:
        rows = rows[idx.astype(jnp.int32)]
    col = {c: i for i, c in enumerate(_ROW_COLS)}

    def c(name):
        return rows[:, col[name]]

    ms = mapstate_lookup(
        arrays["ms_key_w0"], arrays["ms_key_w1"], arrays["ms_key_w2"],
        arrays["ms_deny"], arrays["ms_ruleset"],
        arrays["ms_enf_ids"], arrays["ms_enf_flags"],
        c("ep_ids"), c("peer_ids"), c("dports"),
        c("protos"), c("directions"),
        auth=arrays.get("ms_auth"),
        port_plens=arrays.get("ms_plens"),
        tmpl_ids=arrays.get("ms_tmpl_ids"),
    )
    words = (table_words["path"][c("path_row")],
             table_words["method"][c("method_row")],
             table_words["host"][c("host_row")],
             table_words["headers"][c("headers_row")],
             table_words["qname"][c("qname_row")])
    ingress = c("directions") == int(TrafficDirection.INGRESS)
    src = jnp.where(ingress, c("peer_ids"), c("ep_ids"))
    dst = jnp.where(ingress, c("ep_ids"), c("peer_ids"))
    n = len(_ROW_COLS)
    gen_cols = None
    # ctlint: disable=recompile-hazard  # row width is static per capture layout: one compile per layout, by design
    if rows.shape[1] > n:
        # gen block layout (CaptureFeaturizer / IncrementalSession):
        # [proto id, frontend family, l7g table row, pair ids...]
        gen_cols = (rows[:, n], rows[:, n + 3:])
        if "l7g_trans" in arrays and "l7g" in table_words:
            words = words + (
                table_words["l7g"][rows[:, n + 2]],)
    kafka_cols = (c("kafka_api_key"), c("kafka_api_version"),
                  c("kafka_client"), c("kafka_topic"))
    if "rp_g_method" in arrays and "path_groups" in table_words:
        # factored resolve (megakernel): the staged path table carries
        # per-row GROUP words; replay gathers them like any match word
        from cilium_tpu.engine import megakernel as _mk

        gwords = table_words["path_groups"][c("path_row")]
        return _mk.fused_verdict_core(
            arrays, ms, c("l7_types"), words, gwords, kafka_cols,
            (src, dst), batch, gen_cols=gen_cols)
    return _verdict_core(
        arrays, ms, c("l7_types"), words, kafka_cols,
        (src, dst), batch, gen_cols=gen_cols)


# canonical implementation lives in ingest.binary (pure numpy, usable
# by the replay cursor without jax); re-exported here for engine users
from cilium_tpu.ingest.binary import capture_field_widths  # noqa: E402


def encode_l7_records(rec, l7, offsets, blob,
                      interns: Dict[str, Dict],
                      cfg: Optional[EngineConfig] = None,
                      widths: Optional[Dict[str, int]] = None,
                      gen=None) -> FlowBatch:
    """Vectorized FlowBatch straight from a v2 binary capture
    (``ingest/binary.py`` base records + L7 sidecar): string fields
    gather from the capture's string table, kafka strings resolve to
    engine intern ids via a unique-string LUT — no per-flow Python
    objects between disk and device (VERDICT r2 item 2; north star
    "replaying a Hubble capture"). Strings were normalized at capture
    write time (see ``ingest.binary.flows_to_capture_l7``)."""
    cfg = cfg or EngineConfig()
    B = len(rec)
    ingress = rec["direction"] == int(TrafficDirection.INGRESS)
    ep = np.where(ingress, rec["dst_identity"],
                  rec["src_identity"]).astype(np.int32)
    peer = np.where(ingress, rec["src_identity"],
                    rec["dst_identity"]).astype(np.int32)
    fmax = int(interns.get("gen_fmax", 4))
    w = widths or {}
    gen_rows = (_gen_intern_rows(gen, offsets, blob, interns)
                if gen is not None else None)
    l7_types = rec["l7_type"].astype(np.int32)
    if gen is not None:
        fam, uniq_ser, l7g_row = _gen_l7g_cols(gen, offsets, blob)
        # frontend records: normalize the l7-type lane to the family
        # and encode the serialized records (same invariants as
        # encode_flows — a chunked caller's fixed widths come from
        # capture_field_widths, but l7g serializations are derived,
        # so the cap itself is the fixed width)
        l7_types = np.where(fam > 0, fam, l7_types)
        ser = [uniq_ser[r] for r in l7g_row]
        l7g_field = encode_strings(
            ser, cfg.l7g_len,
            pad_multiple=cfg.l7g_len if w else 32)
    else:
        l7g_field = (np.zeros((B, 32), dtype=np.uint8),
                     np.zeros(B, dtype=np.int32),
                     np.ones(B, dtype=bool))

    def field(name: str, cap: int):
        return _gather_table_field(blob, offsets, l7[name], cap,
                                   fixed_len=w.get(name))

    return FlowBatch(
        ep_ids=ep, peer_ids=peer,
        dports=rec["dport"].astype(np.int32),
        protos=rec["proto"].astype(np.int32),
        directions=rec["direction"].astype(np.int32),
        l7_types=l7_types,
        path=field("path", max(cfg.http_path_buckets)),
        method=field("method", cfg.http_method_len),
        host=field("host", cfg.http_host_len),
        headers=field("headers", 1024),
        qname=field("qname", cfg.dns_name_len),
        kafka_api_key=l7["kafka_api_key"].astype(np.int32),
        kafka_api_version=l7["kafka_api_version"].astype(np.int32),
        kafka_client=_intern_lut(offsets, blob, l7["kafka_client"],
                                 interns.get("client_id", {})),
        kafka_topic=_intern_lut(offsets, blob, l7["kafka_topic"],
                                interns.get("topic", {})),
        gen_proto=(gen_rows[:, 0] if gen_rows is not None
                   else np.full(B, -2, dtype=np.int32)),
        gen_pairs=(gen_rows[:, 1:] if gen_rows is not None
                   else np.full((B, fmax), -2, dtype=np.int32)),
        l7g=l7g_field,
    )


#: Column order of the packed int32 "scalars" array. Packing the 21
#: per-flow scalar/flag columns into ONE device argument (plus the five
#: byte buckets and gen_pairs: 7 arrays total instead of 27) cuts
#: per-dispatch overhead measurably on tunneled TPU transports, where
#: argument count — not bytes — dominates small-batch dispatch latency.
_SCALAR_COLS = (
    "ep_ids", "peer_ids", "dports", "protos", "directions", "l7_types",
    "kafka_api_key", "kafka_api_version", "kafka_client", "kafka_topic",
    "gen_proto",
    "path_len", "path_valid", "method_len", "method_valid",
    "host_len", "host_valid", "headers_len", "headers_valid",
    "qname_len", "qname_valid", "l7g_len", "l7g_valid",
)


def pack_batch(d: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """27-key flat layout → 7-array packed layout (host side). The five
    byte buckets stay separate: concatenating them into one blob was
    tried and benched SLOWER (the in-kernel slices deny the DFA scans a
    clean [B, L] layout and the host-side concat taxes every batch
    copy) — argument-count savings beyond the scalar block don't pay."""
    scalars = np.stack(
        [d[c].astype(np.int32) for c in _SCALAR_COLS], axis=1)
    out = {"scalars": np.ascontiguousarray(scalars)}
    for name in ("path", "method", "host", "headers", "qname", "l7g"):
        out[f"{name}_data"] = d[f"{name}_data"]
    out["gen_pairs"] = d["gen_pairs"]
    return out


def unpack_batch(packed: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Packed layout → flat names (inside jit: slices fuse for free).
    ``*_valid`` columns come back as bool."""
    scalars = packed["scalars"]
    out = {}
    for i, col in enumerate(_SCALAR_COLS):
        v = scalars[:, i]
        out[col] = (v != 0) if col.endswith("_valid") else v
    for name in ("path", "method", "host", "headers", "qname", "l7g"):
        out[f"{name}_data"] = packed[f"{name}_data"]
    out["gen_pairs"] = packed["gen_pairs"]
    if "auth_pairs" in packed:  # staged auth table rides alongside
        out["auth_pairs"] = packed["auth_pairs"]
    return out


#: masked-min sentinel for the attribution winners (any value past
#: every legal lane/group/rule index). A plain int, NOT a jnp
#: constant: a module-level jax array would initialize the backend at
#: import time, before tests/conftest.py can force the virtual mesh.
_ATTR_NONE = 0x7FFFFFFF


def _first_lane(words: "jax.Array") -> "jax.Array":
    """[B, W] uint32 masked match words → the lowest set LANE index
    per row (int32; -1 when no bit is set). The device half of the
    attribution lane: a lane here is a group index (group-accept
    words), a DNS pattern lane, or a kafka/generic predicate-group
    bit, depending on which words the caller masked."""
    nz = words != 0
    any_ = jnp.any(nz, axis=1)
    i0 = jnp.argmax(nz, axis=1).astype(jnp.int32)   # first nonzero word
    w = jnp.take_along_axis(words, i0[:, None], axis=1)[:, 0]
    lsb = w & (~w + jnp.uint32(1))
    bit = jax.lax.population_count(lsb - jnp.uint32(1)).astype(jnp.int32)
    return jnp.where(any_, i0 * 32 + bit, -1)


def _masked_min(matched: "jax.Array", values: "jax.Array"
                ) -> "jax.Array":
    """min over ``values[r]`` where ``matched[b, r]`` (and the value
    is non-negative) → [B] int32, -1 when nothing matched. The legacy
    per-rule face of the attribution winner — with ``values`` a
    rule→group map it equals the fused path's lowest matched group
    (a group matches iff one of its member rules does)."""
    v = values[None, :].astype(jnp.int32)
    big = jnp.where(matched & (v >= 0), v, _ATTR_NONE)
    m = jnp.min(big, axis=1)
    return jnp.where(m == _ATTR_NONE, -1, m)


def _combine_l7_match(http, kafka, dns, gen=None,
                      fe=None) -> "jax.Array":
    """Per-family (ok, win) pairs → ONE [B] int32 attribution lane.
    Families are mutually exclusive per flow (every family's ``ok``
    is gated on its own ``l7t``; frontend families are distinct
    l7-type values), so the combine is a select, not a priority."""
    http_ok, http_win = http
    kafka_ok, kafka_win = kafka
    dns_ok, dns_win = dns
    out = jnp.where(http_ok, http_win,
                    jnp.where(kafka_ok, kafka_win,
                              jnp.where(dns_ok, dns_win, -1)))
    if gen is not None:
        gen_ok, gen_win = gen
        out = jnp.where((out < 0) & gen_ok, gen_win, out)
    if fe is not None:
        fe_ok, fe_win = fe
        out = jnp.where((out < 0) & fe_ok, fe_win, out)
    return out.astype(jnp.int32)


def _l7_kafka(arrays, ruleset, kafka_cols, l7t):
    """Kafka columnar exact/set matching → ``(ruleset-any [B] bool,
    attribution winner [B] int32)``. Shared verbatim by the legacy
    and fused (megakernel) resolves; the winner is reported in GROUP
    space when the resolve plan staged ``rp_k_rule_group`` (bit-equal
    to the fused arm's lowest matched group), else in rule space."""
    k_api, k_ver, k_cli, k_top = kafka_cols
    ak = jnp.clip(k_api, 0, 31).astype(jnp.uint32)
    am = arrays["kafka_apikey_mask"][None, :]        # [1, Rk]
    # api_key < 0 is the unknown-role sentinel (flowpb decode): it
    # matches only api-key-unconstrained rules — the clip alone would
    # collapse it onto 0/produce and falsely match produce ACLs
    k_ok = (
        ((am == 0) | (((am >> ak[:, None]) & jnp.uint32(1)).astype(bool)
                      & (k_api >= 0)[:, None]))
        & ((arrays["kafka_version"][None, :] < 0)
           | (arrays["kafka_version"][None, :] == k_ver[:, None]))
        & ((arrays["kafka_client"][None, :] < 0)
           | (arrays["kafka_client"][None, :] == k_cli[:, None]))
        & ((arrays["kafka_topic"][None, :] < 0)
           | (arrays["kafka_topic"][None, :] == k_top[:, None]))
    )
    kafka_mask = arrays["rs_kafka_mask"][ruleset]
    k_words = _bools_to_words(k_ok, kafka_mask.shape[1])
    ok = (jnp.any((k_words & kafka_mask) != 0, axis=1)
          & (l7t == int(L7Type.KAFKA)))
    Rk = k_ok.shape[1]
    r_idx = jnp.arange(Rk)
    in_set = ((kafka_mask[:, r_idx >> 5]
               >> (r_idx & 31).astype(jnp.uint32)) & 1).astype(bool)
    values = (arrays["rp_k_rule_group"]
              if "rp_k_rule_group" in arrays
              else jnp.arange(Rk, dtype=jnp.int32))
    return ok, _masked_min(k_ok & in_set, values)


def _l7_generic(arrays, ruleset, gen_cols, l7t):
    """Generic l7proto pair-subset matching → ``(ruleset-any [B]
    bool, attribution winner [B] int32)``. Shared verbatim by the
    legacy and fused resolves (winner space: see ``_l7_kafka``)."""
    gen_proto, gen_pairs = gen_cols
    grp = arrays["gen_rule_pairs"]              # [Rg, Km]
    have = jnp.any(
        gen_pairs[:, None, None, :] == grp[None, :, :, None],
        axis=-1)                                # [B, Rg, Km]
    pair_ok = jnp.all(jnp.where(grp[None, :, :] < 0, True, have),
                      axis=-1)
    proto_ok = (arrays["gen_rule_proto"][None, :]
                == gen_proto[:, None])          # [B, Rg]
    g_ok = pair_ok & proto_ok & (arrays["gen_rule_proto"] >= 0)[None, :]
    gen_mask = arrays["rs_gen_mask"][ruleset]
    g_words = _bools_to_words(g_ok, gen_mask.shape[1])
    ok = (jnp.any((g_words & gen_mask) != 0, axis=1)
          & (l7t == int(L7Type.GENERIC)))
    Rg = g_ok.shape[1]
    r_idx = jnp.arange(Rg)
    in_set = ((gen_mask[:, r_idx >> 5]
               >> (r_idx & 31).astype(jnp.uint32)) & 1).astype(bool)
    values = (arrays["rp_gen_rule_group"]
              if "rp_gen_rule_group" in arrays
              else jnp.arange(Rg, dtype=jnp.int32))
    return ok, _masked_min(g_ok & in_set, values)


def _l7_frontend(arrays, ruleset, l7g_w, gen_pairs, l7t):
    """Protocol-frontend rule matching → ``(ruleset-any [B] bool,
    attribution winner [B] int32)``. Per rule: one automaton lane bit
    over the protocol's SCAN-FIELD value (``fe_lane``; -1 =
    unconstrained) AND a pair-subset check of the rule's interned
    enum/presence predicates (``fe_pairs``, same id space and same
    subset semantics as the generic path's ``gen_pairs`` column),
    gated on the rule's family matching the flow's normalized l7-type
    lane; dead rules (unsatisfiable / padding) never match. Shared
    verbatim by the legacy and fused resolves (winner space: see
    ``_l7_kafka``)."""
    lane_ok = _rule_bit(l7g_w, arrays["fe_lane"])
    grp = arrays["fe_pairs"]                    # [Rf, Km]
    have = jnp.any(
        gen_pairs[:, None, None, :] == grp[None, :, :, None],
        axis=-1)                                # [B, Rf, Km]
    pair_ok = jnp.all(jnp.where(grp[None, :, :] < 0, True, have),
                      axis=-1)
    fam = arrays["fe_family"]
    f_ok = (lane_ok & pair_ok
            & (fam[None, :] == l7t[:, None])
            & (fam >= 0)[None, :]
            & ~arrays["fe_dead"][None, :])
    fe_mask = arrays["rs_fe_mask"][ruleset]
    f_words = _bools_to_words(f_ok, fe_mask.shape[1])
    ok = jnp.any((f_words & fe_mask) != 0, axis=1)
    Rf = f_ok.shape[1]
    r_idx = jnp.arange(Rf)
    in_set = ((fe_mask[:, r_idx >> 5]
               >> (r_idx & 31).astype(jnp.uint32)) & 1).astype(bool)
    values = (arrays["rp_fe_rule_group"]
              if "rp_fe_rule_group" in arrays
              else jnp.arange(Rf, dtype=jnp.int32))
    return ok, _masked_min(f_ok & in_set, values)


def _assemble_verdict(arrays, ms, l7_ok, l7_log_http, auth_src_dst,
                      batch, l7_match=None):
    """Precedence + auth + audit assembly → the output dict. ONE
    implementation for every resolve path (legacy, fused, capture) so
    none can drift on the verdict-code semantics.

    ``l7_match`` is the attribution lane ([B] int32): the winning
    L7 rule-signature group (group space, the fused plan) or rule
    index (rule space, plan-less policies) of the family that
    matched; -1 = no L7 winner. The host side maps it to rule id +
    bank key through ``engine/attribution.AttributionMap``."""
    allowed = ms["allowed"] & (l7_ok | ~ms["redirect"])
    auth_required = ms["auth_required"]
    if "auth_pairs" in batch:  # static key check: enforcement staged
        # drop-until-authed (the reference's auth map): a winning allow
        # that demands auth forwards only if (src, dst) completed the
        # handshake. Pairs ride a lex-sorted [P, 2] int32 table
        # (two words, not a packed int64 — x64 is disabled under jax).
        src, dst = auth_src_dst
        pairs = batch["auth_pairs"]
        _, authed = lower_bound((pairs[:, 0], pairs[:, 1]), (src, dst))
        allowed = allowed & (~auth_required | authed)
    # policy_audit_mode: a would-be denial forwards with verdict AUDIT.
    # Per FLOW: the global scalar (device-staged — no recompile when
    # the mode flips) ORs with the owning endpoint's audit bit from
    # the enforcement table (reference: per-endpoint PolicyAuditMode —
    # one namespace can audit a new policy while the fleet enforces)
    audit = ms.get("audit", jnp.zeros_like(ms["allowed"]))
    if "audit_mode" in arrays:
        audit = audit | arrays["audit_mode"]
    deny_code = jnp.where(audit, int(Verdict.AUDIT),
                          int(Verdict.DROPPED)).astype(jnp.int32)
    verdict = jnp.where(
        allowed,
        jnp.where(ms["redirect"], int(Verdict.REDIRECTED),
                  int(Verdict.FORWARDED)),
        deny_code,
    ).astype(jnp.int32)
    if l7_match is None:
        l7_match = jnp.full(l7_ok.shape, -1, jnp.int32)
    return {
        "verdict": verdict,
        "allowed": allowed,
        "l3l4_allowed": ms["allowed"],
        "redirect": ms["redirect"],
        "l7_ok": l7_ok,
        "l7_log": l7_log_http & allowed & ms["redirect"],
        "match_spec": ms["match_spec"],
        "ruleset": ms["ruleset"],
        "auth_required": ms["auth_required"],
        "l7_match": l7_match.astype(jnp.int32),
    }


def _verdict_core(arrays, ms, l7t, words, kafka_cols, auth_src_dst,
                  batch, gen_cols=None):
    """Shared back half of :func:`verdict_step` and
    :func:`verdict_step_capture`: per-family rule conjunctions →
    ruleset-any → precedence + auth + audit assembly. Keeping it in
    ONE place is what guarantees capture replay and live verdicts
    cannot drift. (The megakernel's factored resolve
    (``engine/megakernel.py``) replaces only the HTTP/DNS conjunction
    halves; kafka/generic and the assembly are these same helpers.)

    ``words`` = (path_w, method_w, host_w, hdr_w, dns_w) match-word
    tensors; ``kafka_cols`` = (api_key, api_version, client, topic)
    int32 columns; ``auth_src_dst`` = (src, dst) identity columns for
    the authed-pairs check; ``gen_cols`` = (gen_proto, gen_pairs) or
    None when the caller's format cannot carry generic records (v2
    captures — a -2 proto could never match anyway). A sixth entry in
    ``words`` is the l7g (protocol-frontend) match words — present
    exactly when the policy staged an l7g automaton and the caller's
    format carries serialized frontend records."""
    ruleset = jnp.clip(ms["ruleset"], 0, arrays["rs_http_mask"].shape[0] - 1)
    path_w, method_w, host_w, hdr_w, dns_w = words[:5]
    l7g_w = words[5] if len(words) > 5 else None

    # HTTP: conjunction of per-field pattern bits per rule
    rule_ok = (
        _rule_bit(path_w, arrays["http_path_lane"])
        & _rule_bit(method_w, arrays["http_method_lane"])
        & _rule_bit(host_w, arrays["http_host_lane"])
    )
    hdr_lanes = arrays["http_header_lanes"]          # [R, H]
    hdr_ok = jax.vmap(lambda lanes: _rule_bit(hdr_w, lanes),
                      in_axes=1, out_axes=2)(hdr_lanes)  # [B, R, H]
    rule_ok = rule_ok & jnp.all(hdr_ok, axis=2)
    # a FAIL header match whose secret is unresolvable kills the rule
    # (fail closed — compiler marks it dead)
    if "http_rule_dead" in arrays:
        rule_ok = rule_ok & ~arrays["http_rule_dead"][None, :]

    http_mask = arrays["rs_http_mask"][ruleset]      # [B, Wh]
    rule_words = _bools_to_words(rule_ok, http_mask.shape[1])
    # a rule family only matches flows carrying that L7 record (oracle:
    # flow.http is None → no HTTP rule matches)
    http_ok = (jnp.any((rule_words & http_mask) != 0, axis=1)
               & (l7t == int(L7Type.HTTP)))
    r_idx = jnp.arange(rule_ok.shape[1])
    in_set = ((http_mask[:, r_idx >> 5]
               >> (r_idx & 31).astype(jnp.uint32)) & 1).astype(bool)
    # attribution winner: in GROUP space when the plan staged the
    # rule→group map (equals the fused arm's lowest matched group —
    # a group matches iff one of its member rules does), else the
    # lowest matched rule index
    http_win = _masked_min(
        rule_ok & in_set,
        (arrays["rp_rule_group"] if "rp_rule_group" in arrays
         else jnp.arange(rule_ok.shape[1], dtype=jnp.int32)))

    # LOG-action header matches: a matching rule whose LOG lane
    # mismatched raises the flow's l7_log lane (allow + log, the
    # reference's access-log annotation)
    if "http_log_lanes" in arrays:
        log_lanes = arrays["http_log_lanes"]         # [R, G]
        log_bits = jax.vmap(lambda lanes: _rule_bit(hdr_w, lanes),
                            in_axes=1, out_axes=2)(log_lanes)
        # padding lanes (-1) read True via _rule_bit → ~bits masks them
        log_fail = jnp.any(~log_bits, axis=2)        # [B, R]
        l7_log_http = jnp.any(rule_ok & in_set & log_fail, axis=1) \
            & http_ok
    else:
        l7_log_http = jnp.zeros_like(http_ok)

    kafka_ok, kafka_win = _l7_kafka(arrays, ruleset, kafka_cols, l7t)

    # DNS: qname automaton
    d_ok = (_rule_bit(dns_w, arrays["dns_lane"])
            & (arrays["dns_lane"] >= 0)[None, :])
    dns_mask = arrays["rs_dns_mask"][ruleset]
    d_words = _bools_to_words(d_ok, dns_mask.shape[1])
    dns_ok = (jnp.any((d_words & dns_mask) != 0, axis=1)
              & (l7t == int(L7Type.DNS)))
    # DNS attribution is always LANE space (the fused arm reads the
    # same lanes off its ruleset lane-mask)
    dr_idx = jnp.arange(d_ok.shape[1])
    dns_in_set = ((dns_mask[:, dr_idx >> 5]
                   >> (dr_idx & 31).astype(jnp.uint32)) & 1
                  ).astype(bool)
    dns_win = _masked_min(d_ok & dns_in_set, arrays["dns_lane"])

    # allow-list over the union of the ruleset's families (a merged
    # entry can carry several protocol families; oracle checks all)
    l7_ok = http_ok | kafka_ok | dns_ok

    gen_pair = None
    if gen_cols is not None:
        # generic l7proto records: pair-subset matching
        gen_ok, gen_win = _l7_generic(arrays, ruleset, gen_cols, l7t)
        l7_ok = l7_ok | gen_ok
        gen_pair = (gen_ok, gen_win)

    fe_pair = None
    if l7g_w is not None and gen_cols is not None \
            and "fe_lane" in arrays:
        # protocol-frontend records: scan-field automaton lane +
        # enum pair subset + family equality
        fe_ok, fe_win = _l7_frontend(arrays, ruleset, l7g_w,
                                     gen_cols[1], l7t)
        l7_ok = l7_ok | fe_ok
        fe_pair = (fe_ok, fe_win)

    l7_match = _combine_l7_match((http_ok, http_win),
                                 (kafka_ok, kafka_win),
                                 (dns_ok, dns_win), gen_pair,
                                 fe=fe_pair)
    return _assemble_verdict(arrays, ms, l7_ok, l7_log_http,
                             auth_src_dst, batch, l7_match=l7_match)


#: transfer order of the single-blob service transport (pack_blob_host
#: / unpack_blob): every per-batch array, one H2D
_BLOB_KEYS = ("scalars", "path_data", "method_data", "host_data",
              "headers_data", "qname_data", "l7g_data", "gen_pairs")


def pack_blob_host(host: Dict[str, np.ndarray]):
    """Packed 7-array layout → ONE contiguous u8 blob ([B, W]) plus a
    static layout tuple for :func:`unpack_blob`.

    The 27→7 packing note above stops at the byte buckets because
    in-KERNEL slicing hurt the DFA scans — but the SERVICE path's cost
    is different: at batch ≤ 256 over the tunneled transport, each of
    the 7 device_puts is a full RTT and dwarfs the device work
    (~450ms/batch observed, SERVICE_LATENCY_r04b). One blob = one RTT;
    the on-device split/bitcast back to clean [B, L] arrays is an HBM
    copy XLA fuses into the step."""
    parts, layout = [], []
    for k in _BLOB_KEYS:
        a = host[k]
        if a.dtype == np.int32:
            u8 = np.ascontiguousarray(a).view(np.uint8).reshape(
                len(a), -1)
            layout.append((k, "i32", int(a.shape[1])))
        else:
            u8 = np.ascontiguousarray(a, dtype=np.uint8)
            layout.append((k, "u8", int(a.shape[1])))
        parts.append(u8)
    return np.concatenate(parts, axis=1), tuple(layout)


def unpack_blob(batch: Dict[str, jax.Array], layout) -> Dict[str, jax.Array]:
    """Inverse of :func:`pack_blob_host` inside jit: slices +
    bitcasts rebuild the packed 7-array dict (auth table passes
    through untouched)."""
    blob = batch["blob"]
    out: Dict[str, jax.Array] = {}
    off = 0
    for k, kind, ncols in layout:
        if kind == "i32":
            w = ncols * 4
            part = blob[:, off:off + w]
            out[k] = jax.lax.bitcast_convert_type(
                part.reshape(part.shape[0], ncols, 4), jnp.int32)
        else:
            w = ncols
            out[k] = blob[:, off:off + w]
        off += w
    if "auth_pairs" in batch:
        out["auth_pairs"] = batch["auth_pairs"]
    return out


def verdict_step(arrays: Dict[str, jax.Array], batch: Dict[str, jax.Array]
                 ) -> Dict[str, jax.Array]:
    """The pure device function: full verdict for one batch.

    ``arrays`` = CompiledPolicy.arrays staged on device;
    ``batch`` = FlowBatch fields as device arrays, either packed
    (:func:`pack_batch`) or flat — the dict-key check is static under
    jit, so both layouts trace cleanly.
    """
    if "scalars" in batch:
        batch = unpack_batch(batch)
    # ICMP key encoding (marker bit in the port slot) happens inside
    # mapstate_lookup so the kernel matches its golden model for every
    # caller, not just this one
    ms = mapstate_lookup(
        arrays["ms_key_w0"], arrays["ms_key_w1"], arrays["ms_key_w2"],
        arrays["ms_deny"], arrays["ms_ruleset"],
        arrays["ms_enf_ids"], arrays["ms_enf_flags"],
        batch["ep_ids"], batch["peer_ids"], batch["dports"],
        batch["protos"], batch["directions"],
        auth=arrays.get("ms_auth"),
        port_plens=arrays.get("ms_plens"),
        tmpl_ids=arrays.get("ms_tmpl_ids"),
    )

    def scan_field(prefix: str, data, lengths, valid):
        words = dfa_scan_banked(
            arrays[f"{prefix}_trans"], arrays[f"{prefix}_byteclass"],
            arrays[f"{prefix}_start"], arrays[f"{prefix}_accept"],
            data, lengths,
        )
        B = words.shape[0]
        flat = words.reshape(B, -1)
        return jnp.where(valid[:, None], flat, 0)

    words = (scan_field("path", *batch_field(batch, "path")),
             scan_field("method", *batch_field(batch, "method")),
             scan_field("host", *batch_field(batch, "host")),
             scan_field("hdr", *batch_field(batch, "headers")),
             scan_field("dns", *batch_field(batch, "qname")))
    if "l7g_trans" in arrays:   # frontend rules staged (static)
        words = words + (
            scan_field("l7g", *batch_field(batch, "l7g")),)
    # flows rebuild (src, dst) from (ep, peer) by direction
    ingress = batch["directions"] == int(TrafficDirection.INGRESS)
    src = jnp.where(ingress, batch["peer_ids"], batch["ep_ids"])
    dst = jnp.where(ingress, batch["ep_ids"], batch["peer_ids"])
    return _verdict_core(
        arrays, ms, batch["l7_types"], words,
        (batch["kafka_api_key"], batch["kafka_api_version"],
         batch["kafka_client"], batch["kafka_topic"]),
        (src, dst), batch,
        gen_cols=(batch["gen_proto"], batch["gen_pairs"]))


def batch_field(batch: Dict[str, jax.Array], name: str):
    return (batch[f"{name}_data"], batch[f"{name}_len"],
            batch[f"{name}_valid"])


def _bools_to_words(bools: jax.Array, n_words: int) -> jax.Array:
    """[B, R] bool → [B, n_words] uint32 bitmap (R ≤ 32*n_words)."""
    B, R = bools.shape
    pad = n_words * 32 - R
    if pad:
        bools = jnp.pad(bools, ((0, 0), (0, pad)))
    b = bools.reshape(B, n_words, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(b << shifts[None, None, :], axis=2, dtype=jnp.uint32)


import time as _time

from cilium_tpu.runtime import simclock as _simclock

from cilium_tpu.runtime import faults as _faults
from cilium_tpu.runtime.metrics import (
    CAPTURE_STAGE_SECONDS as _CAPTURE_STAGE_SECONDS,
    METRICS as _METRICS,
)
from cilium_tpu.runtime.tracing import (
    PHASE_DEVICE as _PH_DEVICE,
    PHASE_HOST as _PH_HOST,
    TRACER as _TRACER,
)

#: fires at every device dispatch of the jitted engine (the oracle is
#: never injected — it is the fallback the breaker trips TO)
DISPATCH_POINT = _faults.register_point(
    "engine.dispatch", "device dispatch in VerdictEngine")


class _StagePhase:
    """Capture-staging phase timer (perf ledger): seconds into
    ``cilium_tpu_capture_stage_seconds{phase=...}`` plus a tracer span
    when a trace is active — benches read ``histo_sum`` deltas to put
    a machine-readable split next to ``stage_ms``."""

    __slots__ = ("phase", "_t0")

    def __init__(self, phase: str):
        self.phase = phase

    def __enter__(self) -> "_StagePhase":
        self._t0 = _time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        dur = _time.perf_counter() - self._t0
        _METRICS.observe(_CAPTURE_STAGE_SECONDS, dur,
                         labels={"phase": self.phase})
        ctx = _TRACER.current()
        if ctx is not None:
            _TRACER.add_span(ctx, f"capture.stage.{self.phase}",
                             _PH_HOST, _simclock.wall() - dur, dur)


class VerdictEngine:
    """Jitted wrapper around the verdict step for a CompiledPolicy.

    By default the step is the fused megakernel
    (``engine/megakernel.fused_verdict_step``): one device dispatch
    for mapstate gather + byte-scans + factored priority resolve,
    with the scan impl picked per bank shape at staging and recorded
    on ``policy.kernel_plan``. ``cfg.kernel_impl="legacy"`` (or a
    policy whose resolve plan degenerated) reverts to the unfused
    :func:`verdict_step` — bit-equal either way."""

    def __init__(self, policy: CompiledPolicy, device=None,
                 cfg: Optional[EngineConfig] = None):
        from cilium_tpu.engine import megakernel as _mk
        from cilium_tpu.engine.dfa_kernel import resolve_impl

        self.policy = policy
        self.device = device
        self.cfg = cfg or EngineConfig()
        #: trace-static scan choices, resolved ONCE here on the host
        #: (never under trace — the ctlint jit-purity contract)
        self._dfa_impl = resolve_impl()
        self._interpret = jax.default_backend() != "tpu"
        self._arrays = {
            k: jax.device_put(v, device) for k, v in policy.arrays.items()
        }
        #: True when some staged entry demands authentication — when
        #: False, callers skip staging the authed-pairs table
        self.needs_auth = bool(np.any(policy.arrays["ms_auth"]))
        #: field → scan impl of the staged step ({} on the legacy path)
        self.impl_plan: Dict[str, str] = {}
        #: per-field autotune report (impl, timings, shapes)
        self.kernel_report: Dict[str, Dict] = {}
        mode = getattr(self.cfg, "kernel_impl", "auto")
        if mode != "legacy":
            impl_plan, extra, report = _mk.plan_for_engine(
                policy, self.cfg, self._interpret)
            for k, v in extra.items():
                self._arrays[k] = jax.device_put(v, device)
            self.impl_plan = impl_plan
            self.kernel_report = report
            policy.kernel_plan = dict(impl_plan)
            self._step = jax.jit(functools.partial(
                _mk.fused_verdict_step,
                impl_plan=tuple(sorted(impl_plan.items())),
                dfa_impl=self._dfa_impl,
                interpret=self._interpret,
                use_pallas_nfa=not self._interpret))
        else:
            self._step = jax.jit(verdict_step)
        #: layout-tuple → jitted blob step (the layout is static per
        #: config; distinct layouts are distinct compiles)
        self._blob_steps: Dict[tuple, object] = {}
        #: lazily-built host-side attribution decoder (provenance)
        self._attribution = None

    @property
    def attribution(self):
        """Host-side :class:`~cilium_tpu.engine.attribution.
        AttributionMap` over this engine's policy — decodes the
        ``l7_match`` output lane to rule ids + bank keys. Built once
        per engine (the policy is immutable per revision)."""
        if self._attribution is None:
            from cilium_tpu.engine.attribution import AttributionMap

            self._attribution = AttributionMap.from_policy(self.policy)
        return self._attribution

    def verdict_batch_arrays(self, batch: Dict[str, jax.Array]):
        _faults.maybe_fail(DISPATCH_POINT)
        return self._step(self._arrays, batch)

    def _blob_step(self, layout):
        fn = self._blob_steps.get(layout)
        if fn is None:
            inner = self._step  # jitted-in-jitted inlines under trace

            def step(arrays, batch):
                return inner(arrays, unpack_blob(batch, layout))

            fn = jax.jit(step)
            # ctlint: disable=unbounded-registry  # keyed by bucketed blob layout (finite shape universe)
            self._blob_steps[layout] = fn
        return fn

    def verdict_flows_blob(self, flows: Sequence[Flow],
                           cfg: Optional[EngineConfig] = None,
                           authed_pairs: Optional[np.ndarray] = None,
                           outputs: Optional[Sequence[str]] = None):
        """:meth:`verdict_flows` over the single-blob transport: ONE
        host→device transfer per batch instead of seven (see
        :func:`pack_blob_host`) — the service path's per-batch wall is
        transport RTTs, not device work. Bit-identical verdicts to
        :meth:`verdict_flows` (pinned by differential test)."""
        _faults.maybe_fail(DISPATCH_POINT)
        # phase attribution (runtime/tracing.py): featurize/pack is
        # host-prep; transfer + jitted step + readback is
        # device-dispatch. Leaf spans — nothing else on this path
        # records a phase, so a request's phases sum to its latency.
        with _TRACER.span("engine.featurize", phase=_PH_HOST,
                          records=len(flows)):
            fb = encode_flows(flows, self.policy.kafka_interns, cfg)
            blob, layout = pack_blob_host(flowbatch_to_host_dict(fb))
        with _TRACER.span("engine.dispatch", phase=_PH_DEVICE,
                          records=len(flows)):
            batch = {"blob": jax.device_put(blob, self.device)}
            self._stage_auth(batch, authed_pairs)
            out = self._blob_step(layout)(self._arrays, batch)
            if outputs is not None:
                out = {k: out[k] for k in outputs}
            return jax.device_get(out)


    def _stage_auth(self, batch: Dict[str, jax.Array],
                    authed_pairs) -> None:
        """Stage the authed-pairs table for drop-until-authed.

        Fail-closed default: when the staged policy demands auth and no
        table was supplied (``None``), an EMPTY sentinel table is
        staged so auth-demanding flows DROP — a verdict path built
        without an AuthManager backref must not forward traffic that
        policy says waits on a handshake. ``AUTH_UNENFORCED`` opts into
        demand-lane-only behavior explicitly."""
        from cilium_tpu.auth import AUTH_UNENFORCED

        if not self.needs_auth or authed_pairs is AUTH_UNENFORCED:
            return
        if authed_pairs is None:
            # sentinel row that never matches (identities are >= 0)
            authed_pairs = np.full((1, 2), -1, dtype=np.int32)
        batch["auth_pairs"] = jax.device_put(authed_pairs, self.device)

    def verdict_flows(self, flows: Sequence[Flow],
                      cfg: Optional[EngineConfig] = None,
                      authed_pairs: Optional[np.ndarray] = None,
                      outputs: Optional[Sequence[str]] = None):
        """``authed_pairs`` (lex-sorted [P, 2] int32 (src, dst) table,
        AuthManager.pairs_array): drop-until-authed enforcement for
        entries demanding authentication. See :meth:`_stage_auth` for
        the None / AUTH_UNENFORCED contract.

        ``outputs``: materialize only these lanes. Each np.asarray is
        its own device→host transfer — on the tunneled TPU that is a
        full RTT per lane (docs/PLATFORM.md), so a caller that only
        consumes verdicts (the MicroBatcher service path) pays 1 RTT
        instead of one per output key."""
        with _TRACER.span("engine.featurize", phase=_PH_HOST,
                          records=len(flows)):
            fb = encode_flows(flows, self.policy.kafka_interns, cfg)
        with _TRACER.span("engine.dispatch", phase=_PH_DEVICE,
                          records=len(flows)):
            batch = flowbatch_to_device(fb, self.device)
            self._stage_auth(batch, authed_pairs)
            out = self.verdict_batch_arrays(batch)
            if outputs is not None:
                out = {k: out[k] for k in outputs}
            return jax.device_get(out)

    def verdict_records(self, rec, cfg: Optional[EngineConfig] = None,
                        authed_pairs: Optional[np.ndarray] = None):
        """Columnar fast path: binary capture records → verdicts with
        no per-flow Python objects (ingest/binary.py → encode_records
        → device)."""
        fmax = int(self.policy.kafka_interns.get("gen_fmax", 4))
        with _TRACER.span("engine.featurize", phase=_PH_HOST,
                          records=len(rec)):
            fb = encode_records(rec, cfg, fmax=fmax)
        with _TRACER.span("engine.dispatch", phase=_PH_DEVICE,
                          records=len(rec)):
            batch = flowbatch_to_device(fb, self.device)
            self._stage_auth(batch, authed_pairs)
            out = self.verdict_batch_arrays(batch)
            return jax.device_get(out)

    def verdict_l7_records(self, rec, l7, offsets, blob,
                           cfg: Optional[EngineConfig] = None,
                           authed_pairs: Optional[np.ndarray] = None,
                           widths: Optional[Dict[str, int]] = None,
                           gen=None):
        """Columnar fast path over a v2/v3 capture (base records + L7
        sidecar, ``gen`` = v3 GENERIC section slice): full
        HTTP/Kafka/DNS/generic verdicts, zero per-flow Python
        (ingest/binary.py → encode_l7_records → device). Chunked
        callers MUST pass whole-capture ``widths``
        (:func:`capture_field_widths`) or every chunk whose longest
        string rounds differently re-jits the step."""
        with _TRACER.span("engine.featurize", phase=_PH_HOST,
                          records=len(rec)):
            fb = encode_l7_records(rec, l7, offsets, blob,
                                   self.policy.kafka_interns, cfg,
                                   widths=widths, gen=gen)
        with _TRACER.span("engine.dispatch", phase=_PH_DEVICE,
                          records=len(rec)):
            batch = flowbatch_to_device(fb, self.device)
            self._stage_auth(batch, authed_pairs)
            out = self.verdict_batch_arrays(batch)
            return jax.device_get(out)


class CaptureReplay:
    """Replay session over one v2/v3 capture: string tables scanned
    once on device (:func:`stage_capture_tables`), chunks verdicted
    via :func:`verdict_step_capture` from [B, 15(+gen)] row blocks.
    The file→verdict hot path for the north star's capture replay.
    ``gen`` (v3 GENERIC section, whole capture) converts to interned
    columns once; per-chunk callers pass their record range via
    ``start``.

    With the rows deduped (:meth:`stage_unique`), chunks ride the
    device-resident verdict memo (``engine/memo.py``): unique rows are
    verdicted ONCE per policy revision, every later chunk is a 2–4 B/
    flow id H2D plus one on-device gather. ``loader`` (optional) makes
    the session swap-safe: every verdict entry point checks the global
    policy generation, and a committed revision — swap, rollback, or
    warm restore — re-stages the session against the loader's current
    engine and drops the memo + unique device buffer, so a policy swap
    can never serve a stale verdict (tests/test_faults.py pins it)."""

    def __init__(self, engine: "VerdictEngine", l7, offsets, blob,
                 cfg: Optional[EngineConfig] = None, gen=None,
                 loader=None):
        from cilium_tpu.engine.memo import policy_generation

        self.engine = engine
        self.loader = loader
        self.cfg = cfg
        self._gen_epoch = policy_generation()
        # raw capture sections, kept so a policy swap can re-stage the
        # session (feat LUTs intern against the POLICY's vocabulary)
        self._sections = (l7, offsets, blob, gen)
        # stage-phase attribution (perf ledger): each once-per-file
        # staging step lands in cilium_tpu_capture_stage_seconds{phase}
        # so the 12.5s stage_ms has a machine-readable split
        with _StagePhase("tables"):
            self.feat = CaptureFeaturizer(l7, offsets, blob,
                                          engine.policy.kafka_interns,
                                          cfg, gen=gen)
            self.table_words = stage_capture_tables(engine, self.feat)
        self._step = jax.jit(verdict_step_capture)
        #: whole-capture row block ([N, 15(+gen)] int32) once
        #: :meth:`stage_rows` has run — per-chunk featurize then
        #: drops from ~0.5ms/10k to a contiguous slice (~1µs)
        self.rows_all: Optional[np.ndarray] = None
        #: the (rec, l7) references stage_rows featurized, for re-
        #: staging after a policy swap
        self._staged_records = None
        #: device-resident unique-row table + per-flow ids once
        #: :meth:`stage_unique` has run (dedup replay stream)
        self.unique_rows: Optional[jax.Array] = None
        self._uniq_host: Optional[np.ndarray] = None
        self.row_idx: Optional[np.ndarray] = None
        self._drop_ratio: Optional[float] = None
        #: verdict memo over the unique-row universe (slot == unique
        #: row id — ids are assigned by row hash in _stage_unique)
        self._memo = None
        self._memo_enabled = (cfg.verdict_memo
                              if cfg is not None else True)
        #: unique-row ids a bank-scoped commit touched, awaiting a
        #: scatter refill at the next memo staging
        self._memo_dirty: Optional[np.ndarray] = None
        #: double-buffer: (start, n) → device idx issued ahead of use
        self._prefetched: Dict[tuple, jax.Array] = {}

    # -- swap safety ------------------------------------------------------
    def _ensure_current(self) -> None:
        """Re-validate the session against the policy generation,
        consuming the committed revisions' :class:`PolicyDelta`\\ s
        (bank-scoped invalidation, ISSUE 8):

        * **no-change delta** (same artifact key: a no-op regenerate,
          a warm restore of the serving policy) — keep EVERYTHING:
          staged tables, unique device buffer, memo; just follow the
          loader's engine object.
        * **bank-scoped delta** (CNP/FQDN churn; interns unchanged) —
          row encodings are policy-independent, so the unique buffer
          and row ids stay; the string-table scan restages against the
          new arrays, and only memo rows whose enforcement identity
          changed are queued for a scatter refill.
        * **full delta** (rollback, gate/audit/secret change,
          quarantine involved, or no loader to rebind through) — the
          old conservative path: full re-stage, memo dropped."""
        from cilium_tpu.engine.memo import (
            POLICY_GENERATION,
            policy_generation,
        )

        gen_now = policy_generation()
        if gen_now == self._gen_epoch:
            return
        delta = POLICY_GENERATION.deltas_since(self._gen_epoch)
        self._gen_epoch = gen_now
        new_engine = self.engine
        if self.loader is not None:
            cand = self.loader.engine
            if isinstance(cand, VerdictEngine):
                new_engine = cand
        if delta.is_noop:
            # same compiled artifact recommitted: arrays bit-identical
            # by fingerprint, so staged tables/buffers/memo all remain
            # valid — the warm-restart hit ratio survives (regression-
            # pinned by tests/test_faults.py)
            self.engine = new_engine
            if self._memo is not None:
                self._memo.adopt()
            return
        partial = (not delta.full
                   and new_engine is not self.engine
                   and isinstance(new_engine, VerdictEngine)
                   and (new_engine.policy.kafka_interns
                        == self.engine.policy.kafka_interns))
        if partial:
            self.engine = new_engine
            # capture-side tables and LUTs are policy-independent
            # given equal interns: only the staged DFA scan restages
            with _StagePhase("tables"):
                self.table_words = stage_capture_tables(new_engine,
                                                        self.feat)
            if self._memo is not None and self._memo.filled:
                affected = self._affected_unique_ids(delta)
                if affected is None:
                    self._memo.invalidate(delta.reason)
                    self._memo_dirty = None
                else:
                    if len(affected):
                        self._memo.partial_invalidate(
                            len(affected), delta.reason)
                        prev = self._memo_dirty
                        self._memo_dirty = (
                            affected if prev is None else
                            np.union1d(prev, affected))
                    self._memo.adopt()
            elif self._memo is not None:
                self._memo.adopt()
            return
        self._prefetched.clear()
        self.unique_rows = None  # device buffer dropped on full delta
        self._memo_dirty = None
        if self._memo is not None:
            self._memo.invalidate(delta.reason if delta.full
                                  else "policy-swap")
        if new_engine is not self.engine:
            self.engine = new_engine
            l7, offsets, blob, gen = self._sections
            with _StagePhase("tables"):
                self.feat = CaptureFeaturizer(
                    l7, offsets, blob, new_engine.policy.kafka_interns,
                    self.cfg, gen=gen)
                self.table_words = stage_capture_tables(new_engine,
                                                        self.feat)
            if self._staged_records is not None:
                rec, l7s = self._staged_records
                self.stage_rows(rec, l7s)
                if self._drop_ratio is not None or \
                        self.row_idx is not None:
                    self.stage_unique(self._drop_ratio)

    def _affected_unique_ids(self, delta) -> Optional[np.ndarray]:
        """Unique-row ids whose verdict may have moved under a
        bank-scoped delta. Identity granularity subsumes rule/bank
        granularity for memo outputs (every rule change alters its
        identities' fingerprints); with family fingerprints on the
        delta it narrows further to bank-REFERENCE granularity — a row
        re-verdicts only when its own L7 family read a swapped bank
        (``PolicyDelta.affects``), so an HTTP-path bank swap keeps the
        identity's DNS/kafka rows serving. None = can't tell (no
        staged host rows) → caller must drop."""
        from cilium_tpu.engine.memo import affected_row_ids

        if self._uniq_host is None or self.rows_all is None:
            return None
        if not delta.changed_identities:
            return np.zeros(0, dtype=np.int32)
        return affected_row_ids(
            delta,
            self._uniq_host[:self.n_unique,
                            _ROW_COLS.index("ep_ids")],
            self._uniq_host[:self.n_unique,
                            _ROW_COLS.index("l7_types")],
            dports=self._uniq_host[:self.n_unique,
                                   _ROW_COLS.index("dports")])

    def stage_rows(self, rec, l7) -> np.ndarray:
        """Featurize the WHOLE capture once, as part of session
        staging (the same amortization as the string-table device
        scan: per-file work paid at open, not per chunk). At TPU
        device rates the per-chunk featurize (~19M rows/s host-side)
        is otherwise the e2e ceiling."""
        self._staged_records = (rec, l7)
        with _StagePhase("featurize"):
            self.rows_all = self.feat.encode_rows(
                np.asarray(rec), l7, gen_rows=self.feat.gen_rows)
        return self.rows_all

    def stage_unique(self, drop_if_ratio_at_least: Optional[float]
                     = None) -> float:
        """Deduplicate the staged row block (capture traffic repeats
        its 15-tuples heavily — identities × ports × L7 fields draw
        from small sets): the unique-row table goes to the device once,
        and chunks replay as per-flow u16/u32 row ids expanded by an
        on-device gather. Over a bandwidth-limited host↔device link
        (the tunneled-TPU case, docs/PLATFORM.md) this cuts the
        steady-state stream from 60+ to 2–4 bytes per flow, which is
        the difference between the transport capping e2e below the
        device rate and not. Lossless; returns the dedup ratio
        (unique/total) so callers can fall back to plain row streaming
        when a capture doesn't repeat (ratio ~1 would stream MORE
        bytes via table+ids than rows).

        Host-side only: call :meth:`stage_unique_device` (or just
        :meth:`verdict_idx`) to push the table — so a caller that
        inspects the ratio and falls back never pays the H2D for a
        table it won't use. The table is padded to a power-of-two row
        count (padded ids are never emitted in ``row_idx``), keeping
        the jitted step's shapes in buckets the persistent XLA cache
        can hit across captures.

        ``drop_if_ratio_at_least``: a capture that barely repeats makes
        the id stream a net loss AND the unique table ≈ a full copy of
        ``rows_all`` — past this ratio the table/ids are discarded
        immediately (``row_idx`` stays None) instead of pinning ~2× the
        capture in host memory for a session that will stream rows."""
        assert self.rows_all is not None, "stage_rows first"
        self._drop_ratio = drop_if_ratio_at_least
        with _StagePhase("dedup"):
            return self._stage_unique(drop_if_ratio_at_least)

    def _stage_unique(self, drop_if_ratio_at_least: Optional[float]
                      = None) -> float:
        # dedup by row HASH (engine/memo.hash_rows): a 1-D u64 unique
        # is ~10× cheaper than np.unique(axis=0)'s 15-column row sort
        # (0.77s → ~0.06s on the 200k tier-1 capture). Exact: every
        # row is verified against its hash representative; a collision
        # falls back to the row-sort path. Row ids are therefore
        # hash-assigned — the key the verdict memo rides.
        from cilium_tpu.engine.memo import hash_rows

        h = hash_rows(self.rows_all)
        _, first, inverse = np.unique(h, return_index=True,
                                      return_inverse=True)
        uniq = self.rows_all[first]
        if not np.array_equal(uniq[inverse], self.rows_all):
            uniq, inverse = np.unique(self.rows_all, axis=0,
                                      return_inverse=True)
        n_true = len(uniq)
        ratio = n_true / max(1, len(self.rows_all))
        if drop_if_ratio_at_least is not None \
                and ratio >= drop_if_ratio_at_least:
            self._uniq_host = None
            self.unique_rows = None
            self.row_idx = None
            self.n_unique = n_true
            return ratio
        uniq = _pad_rows_pow2(uniq)
        self._uniq_host = uniq
        self.unique_rows = None
        self.n_unique = n_true
        idx_dtype = np.uint16 if len(uniq) <= (1 << 16) else np.int32
        self.row_idx = inverse.astype(idx_dtype)
        return ratio

    def stage_unique_device(self) -> jax.Array:
        """Push the (padded) unique-row table to the device, once.
        The buffer is memoized on the session and dropped ONLY on a
        policy-generation change (:meth:`_ensure_current`) — repeated
        calls (every ``verdict_idx`` chunk, the phase probes) must
        never re-pay the full-table H2D."""
        if self.unique_rows is None:
            with _StagePhase("table-h2d"):
                self.unique_rows = jax.device_put(self._uniq_host,
                                                  self.engine.device)
                np.asarray(self.unique_rows[:2])  # completion-forced
        return self.unique_rows

    # -- verdict memo -----------------------------------------------------
    @property
    def memo(self):
        """The session's :class:`~cilium_tpu.engine.memo.VerdictMemo`
        (created lazily; None until the dedup stream is staged)."""
        return self._memo

    def stage_verdict_memo(self, authed_pairs=None):
        """Verdict every session-unique row ONCE (one batched capture
        step over the staged unique table) and keep the packed outputs
        on device — chunks then replay as pure id gathers. No-op when
        the memo is current for this auth view; re-fills after an
        invalidation. Returns the memo (None when dedup was dropped or
        the memo is disabled)."""
        from cilium_tpu.engine import memo as memo_mod

        if not self._memo_enabled or self.row_idx is None:
            return None
        sig = memo_mod.auth_signature(authed_pairs)
        if self._memo is None:
            self._memo = memo_mod.VerdictMemo(device=self.engine.device)
        m = self._memo
        if m.valid_for(sig) and m.filled >= self.n_unique:
            dirty = self._memo_dirty
            if dirty is not None and len(dirty) and m.table is not None:
                # bank-scoped refill: recompute ONLY the rows a
                # committed revision touched and scatter them over the
                # live table — the rest of the memo keeps serving
                with _StagePhase("memo-fill"):
                    D = max(32, 1 << (int(len(dirty)) - 1).bit_length())
                    idx = np.concatenate(
                        [dirty, np.full(D - len(dirty), dirty[0],
                                        dtype=dirty.dtype)]) \
                        if D > len(dirty) else dirty
                    batch = {"rows": self.stage_unique_device(),
                             "idx": jax.device_put(idx,
                                                   self.engine.device)}
                    self.engine._stage_auth(batch, authed_pairs)
                    out = self._step(self.engine._arrays,
                                     self.table_words, batch)
                    m.refill_scatter(idx, _MEMO_PACK_STEP(out),
                                     len(dirty))
            self._memo_dirty = None
            return m
        with _StagePhase("memo-fill"):
            self._memo_dirty = None  # full fill supersedes any refill
            batch = {"rows": self.stage_unique_device()}
            self.engine._stage_auth(batch, authed_pairs)
            out = self._step(self.engine._arrays, self.table_words,
                             batch)
            packed = _MEMO_PACK_STEP(out)
            m.fill(packed, 0, self.n_unique, sig)
        return m

    def prefetch_idx(self, idx: np.ndarray, start: int) -> None:
        """Issue the H2D for a coming chunk's id stream ahead of use
        (double buffering: chunk N+1's transfer overlaps chunk N's
        dispatch/readback — jax device_put is async, so this returns
        immediately)."""
        key = (start, len(idx))
        if key not in self._prefetched:
            if len(self._prefetched) > 2:  # bound the in-flight window
                self._prefetched.clear()
            self._prefetched[key] = jax.device_put(idx,
                                                   self.engine.device)

    def _idx_device(self, idx: np.ndarray, start: Optional[int]
                    ) -> jax.Array:
        if start is not None:
            dev = self._prefetched.pop((start, len(idx)), None)
            if dev is not None:
                return dev
        return jax.device_put(idx, self.engine.device)

    def verdict_idx(self, idx: np.ndarray, authed_pairs=None,
                    start: Optional[int] = None
                    ) -> Dict[str, jax.Array]:
        """Verdict a chunk given per-flow unique-row ids (the
        :meth:`stage_unique` stream). With the verdict memo staged and
        current, this is ONE tiny id H2D + one on-device gather of the
        memoized outputs; otherwise one id H2D + the shared capture
        step. Auth staging matches :meth:`verdict_rows` — the id
        stream must enforce drop-until-authed exactly like every other
        replay path (None is fail-closed when the policy demands
        auth); the memo keys on the auth signature so a different auth
        view can never read another view's verdicts."""
        self._ensure_current()
        m = self.stage_verdict_memo(authed_pairs)
        idx_dev = self._idx_device(idx, start)
        if m is not None:
            return m.gather(idx_dev)
        batch = {"rows": self.stage_unique_device(), "idx": idx_dev}
        self.engine._stage_auth(batch, authed_pairs)
        return self._step(self.engine._arrays, self.table_words, batch)

    def verdict_rows(self, rows: np.ndarray, authed_pairs=None
                     ) -> Dict[str, jax.Array]:
        self._ensure_current()
        batch = {"rows": jax.device_put(rows, self.engine.device)}
        self.engine._stage_auth(batch, authed_pairs)
        return self._step(self.engine._arrays, self.table_words, batch)

    def verdict_chunk(self, rec, l7, authed_pairs=None, start: int = 0
                      ) -> Dict[str, np.ndarray]:
        """``start`` is the chunk's GLOBAL record index — mandatory
        for non-initial chunks once :meth:`stage_rows` (or a v3
        capture's gen columns) is in play. With the dedup stream
        staged the chunk rides :meth:`verdict_idx` (memo gather) and
        the NEXT chunk's id H2D is issued before this one's outputs
        are read back — sequential callers get double-buffered
        transfers for free."""
        self._ensure_current()
        n = len(rec)
        if self.row_idx is not None and self.rows_all is not None:
            if start + n > len(self.rows_all):
                raise ValueError(
                    f"chunk [{start}:{start + n}] outside the "
                    f"staged capture ({len(self.rows_all)} rows) — "
                    f"wrong start, or staged from different records")
            idx = self.row_idx[start:start + n]
            out = self.verdict_idx(idx, authed_pairs, start=start)
            nxt = self.row_idx[start + n:start + 2 * n]
            if len(nxt):
                self.prefetch_idx(nxt, start + n)
            return jax.device_get(out)
        if self.rows_all is not None:
            rows = self.rows_all[start:start + n]
            if len(rows) != n:
                raise ValueError(
                    f"chunk [{start}:{start + n}] outside the "
                    f"staged capture ({len(self.rows_all)} rows) — "
                    f"wrong start, or staged from different records")
        else:
            gen_rows = (self.feat.gen_rows[start:start + n]
                        if self.feat.gen_rows is not None else None)
            rows = self.feat.encode_rows(rec, l7, gen_rows=gen_rows)
        out = self.verdict_rows(rows, authed_pairs)
        return jax.device_get(out)


def flowbatch_to_host_dict(fb: FlowBatch) -> Dict[str, np.ndarray]:
    """FlowBatch → packed dict of HOST numpy arrays (same keys as
    :func:`flowbatch_to_device`): one int32 "scalars" block plus the
    five byte buckets and gen_pairs (see :func:`pack_batch` for why).
    Benchmarks build per-iteration device copies from this — staging
    from host avoids the device→host round-trip that degrades the axon
    platform (docs/PLATFORM.md)."""
    d: Dict[str, np.ndarray] = {
        "ep_ids": fb.ep_ids, "peer_ids": fb.peer_ids,
        "dports": fb.dports, "protos": fb.protos,
        "directions": fb.directions, "l7_types": fb.l7_types,
        "kafka_api_key": fb.kafka_api_key,
        "kafka_api_version": fb.kafka_api_version,
        "kafka_client": fb.kafka_client,
        "kafka_topic": fb.kafka_topic,
        "gen_proto": fb.gen_proto,
        "gen_pairs": fb.gen_pairs,
    }
    for name in ("path", "method", "host", "headers", "qname", "l7g"):
        data, lengths, valid = getattr(fb, name)
        d[f"{name}_data"] = data
        d[f"{name}_len"] = lengths
        d[f"{name}_valid"] = valid
    return pack_batch(d)


def flowbatch_to_device(fb: FlowBatch, device=None) -> Dict[str, jax.Array]:
    # one batched pytree transfer, not one device_put per column
    return jax.device_put(flowbatch_to_host_dict(fb), device)
