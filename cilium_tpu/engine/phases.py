"""Device-time attribution: where a verdict batch actually spends it.

The jitted hot path is ONE fused program by design (that is the whole
perf story), so per-phase numbers cannot come from instrumenting the
hot path — they come from a **probe** that re-runs the same staged
batch through separately-jitted sub-steps, each ending in a forced
2-element readback (the bench ``_force`` contract:
``block_until_ready`` is not a reliable completion barrier on the
tunneled platform):

=================  ======================================================
``featurize``      host encode: flows → packed numpy batch
``h2d``            host→device transfer of the packed batch, forced
``mapstate``       the L3/L4 mapstate gather (``mapstate_kernel``)
``dfa-scan``       the five per-field banked DFA scans (live path), or
``gather``         the staged-table match-word gathers (capture path)
``resolve``        per-rule conjunction → ruleset-any → priority/auth/
                   audit (the LEGACY formulation — the three-op
                   baseline the megakernel is judged against)
``fused-verdict``  the engine's staged megakernel step
                   (``engine/megakernel.py``): mapstate + scans +
                   factored resolve in ONE device dispatch, where the
                   three rows above are three
``dfa-dense``      the planned fields' scans through the dense-gather
                   DFA arm, per the engine's kernel plan
``nfa-bitset``     the planned fields' scans through the bitset-NFA
                   rules-as-lanes arm (only reported when the plan
                   uses it)
``compile``        first-call cost minus steady-state (the compile
                   half of the compile-vs-execute split)
``execute``        steady-state fused-step wall (the execute half)
=================  ======================================================

Coverage contract: ``attributed / wall``. Sub-steps jitted separately
forgo cross-phase fusion, so the device-side decomposition sums to
≥ the fused step on every platform measured — a coverage below ~0.9
means a phase is MISSING from the decomposition, which is exactly what
the number exists to catch. (With the megakernel staged, wall is the
ONE-dispatch fused step, so coverage well above 1 is the speedup
showing.) ``three_op_ms``/``fused_ms``/``fused_speedup`` on the
report carry the dispatch-count story explicitly:
``three_op_dispatches`` is 3 (mapstate, scan, resolve, each
completion-forced), ``fused_dispatches`` is 1. Results feed the
flight recorder (``runtime/tracing.py`` spans under an
``engine.phase_probe`` root) and the
``cilium_tpu_engine_phase_seconds{phase=...}`` family — and the bench
artifacts, where ROADMAP's open perf items (megakernel, multichip)
are judged against them.

This is an inspection instrument, not a hot-path layer: nothing here
runs per request.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.core.flow import TrafficDirection
from cilium_tpu.engine.dfa_kernel import dfa_scan_banked
from cilium_tpu.engine.mapstate_kernel import mapstate_lookup
from cilium_tpu.engine.verdict import (
    _ROW_COLS,
    _verdict_core,
    batch_field,
    encode_flows,
    flowbatch_to_host_dict,
    unpack_batch,
)
from cilium_tpu.runtime import simclock
from cilium_tpu.runtime.metrics import (ENGINE_HOST_SYNCS,
                                        ENGINE_PHASE_SECONDS, METRICS)
from cilium_tpu.runtime.tracing import PHASE_DEVICE, PHASE_HOST, TRACER

#: phase label values the probes emit (obs-doc-parity: each must be
#: documented in docs/OBSERVABILITY.md)
ENGINE_PHASES = ("featurize", "h2d", "mapstate", "dfa-scan", "resolve",
                 "fused-verdict", "dfa-dense", "nfa-bitset",
                 "compile", "execute")
CAPTURE_PHASES = ("gather", "mapstate", "resolve")


def _force(out, site: str = "") -> None:
    """Force remote completion via a tiny readback of the first array
    leaf (in-order queue: the last op's readback implies the rest).
    Each call is an INTENTIONAL host↔device sync — counted under
    ``cilium_tpu_engine_host_syncs_total{site=…}`` so the allowlisted
    sync points the ctlint device-dataflow family exempts stay
    observable at runtime (docs/ANALYSIS.md v4)."""
    METRICS.inc(ENGINE_HOST_SYNCS, labels={"site": site or "probe"})
    leaf = out
    while isinstance(leaf, dict):
        leaf = leaf[sorted(leaf)[0]]
    while isinstance(leaf, (tuple, list)):
        leaf = leaf[0]
    np.asarray(leaf[:2] if getattr(leaf, "ndim", 0) else leaf)


def _timed(fn, reps: int, site: str = ""):
    """(steady median s, first-call s, last output). The first call
    compiles; steady is the median of ``reps`` forced calls."""
    t0 = time.perf_counter()
    out = fn()
    _force(out, site)
    first = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        _force(out, site)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2], first, out


def _unpacked(batch):
    return unpack_batch(batch) if "scalars" in batch else batch


def _live_mapstate(arrays, batch):
    b = _unpacked(batch)
    return mapstate_lookup(
        arrays["ms_key_w0"], arrays["ms_key_w1"], arrays["ms_key_w2"],
        arrays["ms_deny"], arrays["ms_ruleset"],
        arrays["ms_enf_ids"], arrays["ms_enf_flags"],
        b["ep_ids"], b["peer_ids"], b["dports"],
        b["protos"], b["directions"],
        auth=arrays.get("ms_auth"),
        port_plens=arrays.get("ms_plens"),
        tmpl_ids=arrays.get("ms_tmpl_ids"))


def _live_scan(arrays, batch):
    b = _unpacked(batch)

    def scan_field(prefix, data, lengths, valid):
        words = dfa_scan_banked(
            arrays[f"{prefix}_trans"], arrays[f"{prefix}_byteclass"],
            arrays[f"{prefix}_start"], arrays[f"{prefix}_accept"],
            data, lengths)
        flat = words.reshape(words.shape[0], -1)
        return jnp.where(valid[:, None], flat, 0)

    words = (scan_field("path", *batch_field(b, "path")),
             scan_field("method", *batch_field(b, "method")),
             scan_field("host", *batch_field(b, "host")),
             scan_field("hdr", *batch_field(b, "headers")),
             scan_field("dns", *batch_field(b, "qname")))
    if "l7g_trans" in arrays:   # frontend automaton staged (static)
        words = words + (scan_field("l7g", *batch_field(b, "l7g")),)
    return words


def _live_resolve(arrays, ms, words, batch):
    b = _unpacked(batch)
    ingress = b["directions"] == int(TrafficDirection.INGRESS)
    src = jnp.where(ingress, b["peer_ids"], b["ep_ids"])
    dst = jnp.where(ingress, b["ep_ids"], b["peer_ids"])
    return _verdict_core(
        arrays, ms, b["l7_types"], words,
        (b["kafka_api_key"], b["kafka_api_version"],
         b["kafka_client"], b["kafka_topic"]),
        (src, dst), b, gen_cols=(b["gen_proto"], b["gen_pairs"]))


def _cap_rows(batch):
    rows = batch["rows"]
    idx = batch.get("idx")
    if idx is not None:
        rows = rows[idx.astype(jnp.int32)]
    return rows


def _cap_gather(table_words, batch):
    rows = _cap_rows(batch)
    col = {c: i for i, c in enumerate(_ROW_COLS)}
    words = tuple(
        table_words[field][rows[:, col[f"{field}_row"]]]
        for field in ("path", "method", "host", "headers", "qname"))
    # ctlint: disable=recompile-hazard  # row width is static per capture layout: one compile per layout, by design
    if "l7g" in table_words and rows.shape[1] > len(_ROW_COLS):
        # frontend serialized-record words ride the gen block's l7g
        # row column (gen layout: proto, family, l7g row, pairs...)
        words = words + (
            table_words["l7g"][rows[:, len(_ROW_COLS) + 2]],)
    return rows, words


def _cap_mapstate(arrays, batch):
    rows = _cap_rows(batch)
    col = {c: i for i, c in enumerate(_ROW_COLS)}
    return mapstate_lookup(
        arrays["ms_key_w0"], arrays["ms_key_w1"], arrays["ms_key_w2"],
        arrays["ms_deny"], arrays["ms_ruleset"],
        arrays["ms_enf_ids"], arrays["ms_enf_flags"],
        rows[:, col["ep_ids"]], rows[:, col["peer_ids"]],
        rows[:, col["dports"]], rows[:, col["protos"]],
        rows[:, col["directions"]],
        auth=arrays.get("ms_auth"),
        port_plens=arrays.get("ms_plens"),
        tmpl_ids=arrays.get("ms_tmpl_ids"))


def _cap_resolve(arrays, ms, rows, words, batch):
    col = {c: i for i, c in enumerate(_ROW_COLS)}

    def c(name):
        return rows[:, col[name]]

    ingress = c("directions") == int(TrafficDirection.INGRESS)
    src = jnp.where(ingress, c("peer_ids"), c("ep_ids"))
    dst = jnp.where(ingress, c("ep_ids"), c("peer_ids"))
    n = len(_ROW_COLS)
    # ctlint: disable=recompile-hazard  # row width is static per capture layout: one compile per layout, by design
    gen_cols = ((rows[:, n], rows[:, n + 3:])
                if rows.shape[1] > n else None)
    return _verdict_core(
        arrays, ms, c("l7_types"), words,
        (c("kafka_api_key"), c("kafka_api_version"),
         c("kafka_client"), c("kafka_topic")),
        (src, dst), batch, gen_cols=gen_cols)


def _record(report: Dict, reps: int) -> None:
    """Publish a probe report into METRICS + the flight recorder."""
    now = simclock.wall()
    with TRACER.trace("engine.phase_probe", batch=report.get("batch"),
                      reps=reps) as ctx:
        for phase, ms in report["phases_ms"].items():
            METRICS.observe(ENGINE_PHASE_SECONDS, ms / 1e3,
                            labels={"phase": phase})
            TRACER.add_span(
                ctx, f"engine.phase.{phase}",
                PHASE_HOST if phase == "featurize" else PHASE_DEVICE,
                now, ms / 1e3)
        for phase, key in (("compile", "compile_ms"),
                           ("execute", "execute_ms")):
            if report.get(key) is not None:
                METRICS.observe(ENGINE_PHASE_SECONDS,
                                report[key] / 1e3,
                                labels={"phase": phase})


def _impl_scan(arrays, batch, impl_plan, wanted: str,
               dfa_impl: str, interpret: bool):
    """Scan only the fields the engine's kernel plan runs through
    ``wanted`` — the per-impl attribution lanes (dfa-dense /
    nfa-bitset phase labels)."""
    from cilium_tpu.engine.megakernel import fused_scan_field, scan_fields

    b = _unpacked(batch)
    impls = dict(impl_plan)
    out = []
    for prefix, field in scan_fields(arrays):
        if impls.get(prefix, "dfa-dense") != wanted:
            continue
        w, _ = fused_scan_field(
            arrays, prefix, *batch_field(b, field), impl=wanted,
            dfa_impl=dfa_impl, interpret=interpret)
        out.append(w)
    return tuple(out)


#: jitted once at module scope — per-call wrapping would churn the jit
#: cache (the recompile-hazard rule's own lesson)
_IMPL_SCAN = jax.jit(_impl_scan, static_argnums=(2, 3, 4, 5))


class EnginePhaseProbe:
    """Per-phase attribution of the LIVE verdict path (featurize →
    h2d → mapstate → dfa-scan → resolve, plus the fused megakernel
    step and its per-impl scan lanes) for one engine."""

    def __init__(self, engine):
        self.engine = engine
        self._ms = jax.jit(_live_mapstate)
        self._scan = jax.jit(_live_scan)
        self._resolve = jax.jit(_live_resolve)
        # the engine's STAGED step (the fused megakernel unless the
        # engine was built legacy) — the wall the decomposition covers
        self._full = engine._step
        self._impl_plan = tuple(sorted(
            getattr(engine, "impl_plan", {}).items()))

    def measure_flows(self, flows: Sequence, cfg=None, reps: int = 5
                      ) -> Dict:
        """Featurize ``flows`` (timed: the ``featurize`` phase), then
        :meth:`measure` the resulting packed batch."""
        t0 = time.perf_counter()
        host = flowbatch_to_host_dict(
            encode_flows(flows, self.engine.policy.kafka_interns, cfg))
        feat_ms = (time.perf_counter() - t0) * 1e3
        report = self.measure(host, reps=reps, _defer_record=True)
        report["phases_ms"]["featurize"] = round(feat_ms, 3)
        report["attributed_ms"] = round(
            report["attributed_ms"] + feat_ms, 3)
        _record(report, reps)
        return report

    def measure(self, host_batch: Dict[str, np.ndarray], reps: int = 5,
                authed_pairs=None, _defer_record: bool = False) -> Dict:
        """``host_batch`` is the packed host layout
        (:func:`flowbatch_to_host_dict`). Returns the phase report;
        also records it (metrics + tracer spans)."""
        engine, arrays = self.engine, self.engine._arrays

        def put():
            batch = {k: jax.device_put(v, engine.device)
                     for k, v in host_batch.items()}
            engine._stage_auth(batch, authed_pairs)
            return batch

        h2d_s, _, batch = _timed(put, reps, site="engine-h2d")
        ms_s, _, ms = _timed(lambda: self._ms(arrays, batch), reps,
                             site="engine-mapstate")
        scan_s, _, words = _timed(lambda: self._scan(arrays, batch),
                                  reps, site="engine-dfa-scan")
        res_s, _, _ = _timed(
            lambda: self._resolve(arrays, ms, words, batch), reps,
            site="engine-resolve")
        full_s, full_first, _ = _timed(
            lambda: self._full(arrays, batch), reps,
            site="engine-fused-verdict")

        # the three-op baseline the megakernel replaces: mapstate →
        # scan → resolve as three completion-forced device dispatches
        # (the pre-fused execution shape, HBM round-trips included)
        def three_op():
            m = self._ms(arrays, batch)
            _force(m, "engine-three-op")
            w = self._scan(arrays, batch)
            _force(w, "engine-three-op")
            return self._resolve(arrays, m, w, batch)

        three_s, _, _ = _timed(three_op, reps, site="engine-three-op")

        phases_ms = {"h2d": round(h2d_s * 1e3, 3),
                     "mapstate": round(ms_s * 1e3, 3),
                     "dfa-scan": round(scan_s * 1e3, 3),
                     "resolve": round(res_s * 1e3, 3),
                     "fused-verdict": round(full_s * 1e3, 3)}
        # per-impl scan lanes, per the engine's kernel plan
        for impl in sorted({v for _, v in self._impl_plan} or
                           {"dfa-dense"}):
            impl_s, _, _ = _timed(
                lambda: _IMPL_SCAN(
                    arrays, batch, self._impl_plan, impl,
                    getattr(self.engine, "_dfa_impl", "gather"),
                    getattr(self.engine, "_interpret", True)),
                reps, site="engine-impl-scan")
            phases_ms[impl] = round(impl_s * 1e3, 3)
        attributed = (ms_s + scan_s + res_s) * 1e3
        report = {
            "batch": int(len(host_batch["scalars"])),
            "phases_ms": phases_ms,
            "wall_ms": round(full_s * 1e3, 3),
            "attributed_ms": round(attributed, 3),
            "coverage": round(attributed / max(full_s * 1e3, 1e-9), 4),
            "compile_ms": round(max(0.0, full_first - full_s) * 1e3, 3),
            "execute_ms": round(full_s * 1e3, 3),
            # the dispatch-count story the megakernel exists for: ONE
            # device dispatch where the baseline pays three
            "fused_ms": round(full_s * 1e3, 3),
            "fused_dispatches": 1,
            "three_op_ms": round(three_s * 1e3, 3),
            "three_op_dispatches": 3,
            "fused_speedup": round(three_s / max(full_s, 1e-9), 3),
            "impl_plan": dict(self._impl_plan),
        }
        if not _defer_record:
            _record(report, reps)
        return report


class CapturePhaseProbe:
    """Per-phase attribution of the CAPTURE-REPLAY path (h2d →
    gather → mapstate → resolve) for one staged
    :class:`~cilium_tpu.engine.verdict.CaptureReplay` session."""

    def __init__(self, replay):
        self.replay = replay
        self._gather = jax.jit(_cap_gather)
        self._ms = jax.jit(_cap_mapstate)
        self._resolve = jax.jit(_cap_resolve)
        # the session's staged step (fused when the policy carries a
        # resolve plan) — the wall the decomposition covers
        self._full = replay._step

    def measure(self, start: int = 0, n: Optional[int] = None,
                reps: int = 5, authed_pairs=None) -> Dict:
        """Attribute one chunk (records ``[start:start+n]`` of the
        staged capture; dedup id stream when the session staged one)."""
        replay, engine = self.replay, self.replay.engine
        arrays = engine._arrays
        assert replay.rows_all is not None, "stage_rows first"
        n = n if n is not None else min(len(replay.rows_all), 8192)

        if replay.row_idx is not None:
            idx_host = replay.row_idx[start:start + n]
            table = replay.stage_unique_device()

            def put():
                batch = {"rows": table,
                         "idx": jax.device_put(idx_host, engine.device)}
                engine._stage_auth(batch, authed_pairs)
                return batch
        else:
            rows_host = replay.rows_all[start:start + n]

            def put():
                batch = {"rows": jax.device_put(rows_host,
                                                engine.device)}
                engine._stage_auth(batch, authed_pairs)
                return batch

        h2d_s, _, batch = _timed(put, reps, site="capture-h2d")
        tw = replay.table_words

        # the end-to-end chunk wall the phases must cover: fresh H2D +
        # fused step + forced completion, as the replay loop pays it
        def chunk():
            return self._full(arrays, tw, put())

        wall_s, wall_first, _ = _timed(chunk, reps,
                                       site="capture-chunk")
        g_s, _, (rows, words) = _timed(
            lambda: self._gather(tw, batch), reps,
            site="capture-gather")
        ms_s, _, ms = _timed(lambda: self._ms(arrays, batch), reps,
                             site="capture-mapstate")
        res_s, _, _ = _timed(
            lambda: self._resolve(arrays, ms, rows, words, batch),
            reps, site="capture-resolve")
        step_s, _, _ = _timed(
            lambda: self._full(arrays, tw, batch), reps,
            site="capture-step")

        phases_ms = {"h2d": round(h2d_s * 1e3, 3),
                     "gather": round(g_s * 1e3, 3),
                     "mapstate": round(ms_s * 1e3, 3),
                     "resolve": round(res_s * 1e3, 3)}
        attributed = (h2d_s + g_s + ms_s + res_s) * 1e3
        report = {
            "batch": int(n),
            "stream": "id" if replay.row_idx is not None else "row",
            "phases_ms": phases_ms,
            "wall_ms": round(wall_s * 1e3, 3),
            "step_ms": round(step_s * 1e3, 3),
            "attributed_ms": round(attributed, 3),
            "coverage": round(attributed / max(wall_s * 1e3, 1e-9), 4),
            "compile_ms": round(max(0.0, wall_first - wall_s) * 1e3, 3),
            "execute_ms": round(wall_s * 1e3, 3),
        }
        _record(report, reps)
        return report
