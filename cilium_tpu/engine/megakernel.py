"""MXU-native automaton megakernel: the fused verdict step.

One device dispatch for the full verdict — the L3/L4 mapstate gather,
the five per-field byte-scans, and the priority resolve — where the
phase probe previously attributed three separately-dispatched ops with
intermediate HBM round-trips (``engine/phases.py`` mapstate /
dfa-scan / resolve). Two structural changes carry the win:

**Factored priority resolve.** The legacy resolve materializes a
``[B, R]`` per-(flow, rule) conjunction and then reduces it through
the ruleset bitmaps — at the 1k-rule config that is ~90% of device
time and pure VPU/gather work. This module factors it at *compile
time*: rules are grouped by their non-path signature (method lane,
host lane, header/LOG lanes, dead flag, ruleset membership), and each
group's path-pattern disjunction becomes an extra **group-accept
plane on the path automaton itself** — the scan's final state already
knows every matched pattern, so "any of this group's paths matched"
is one more accept-table read, not a per-rule loop. Resolve then runs
in group space (``G ≪ R``: the 1k-rule http policy has 15 groups) and
collapses to ruleset-any over a ``[RS, G]`` bitmap. Bit-equal to the
legacy path by construction (the factoring is exact boolean algebra);
pinned over the golden corpus and hypothesis-random policies by
tests/test_megakernel.py. Kafka and generic-l7 rule families ride the
same factored path as distinct-PREDICATE groups (no automaton lanes
to factor through, but identical predicates across rules collapse to
one group with OR'd ruleset membership), so every protocol family —
http, dns, kafka, generic — resolves in group space inside the one
fused launch; the precedence/auth/audit assembly stays the shared
``_assemble_verdict``.

**Per-bank-shape scan autotuning.** The byte-scan has two
implementations — the dense-gather DFA (``engine/dfa_kernel.py``) and
the bitset-NFA "rules-as-lanes" arm (``engine/nfa_kernel.py`` /
``engine/pallas_nfa.py``, block-diagonal one-hot matmuls on the MXU).
Which wins is a property of the bank *shape* (DFA state count vs NFA
position count, class count, backend), so the pick is made per field
stack at engine staging — heuristically under ``kernel_impl=auto``
(dense everywhere except TPU banks whose DFA busts the 128-state
Pallas budget while their positions fit), measured under
``kernel_impl=autotune`` — cached process-wide by shape+backend key,
recorded on the policy's kernel plan and the loader's bank registry,
and carried across warm restarts through the snapshot. Every arm is
bit-equal; the autotuner only ever changes *time*.

"One launch" here means one XLA executable and one device dispatch
per verdict batch: on TPU the Pallas scan kernels are fused into that
executable alongside the mapstate gather and the group-space resolve.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.core.flow import L7Type
from cilium_tpu.engine import nfa_kernel
from cilium_tpu.runtime.metrics import (
    KERNEL_AUTOTUNE_PICKS,
    KERNEL_AUTOTUNE_SECONDS,
    METRICS,
)

#: scan implementations the autotuner arbitrates between
IMPL_DENSE = "dfa-dense"
IMPL_NFA = "nfa-bitset"

#: past this many signature groups the factored resolve stops paying
#: (G → R degenerates to the per-rule path with extra indirection) and
#: the plan is skipped — the fused step then uses the legacy resolve,
#: still in one dispatch
GROUP_CAP = 2048

#: (prefix, batch-field) pairs of the five scanned string fields
SCAN_FIELDS = (("path", "path"), ("method", "method"),
               ("host", "host"), ("hdr", "headers"), ("dns", "qname"))

#: the l7g (protocol-frontend) field stack, present only on policies
#: carrying frontend rules — words slot 5 by convention
L7G_FIELD = ("l7g", "l7g")


def scan_fields(arrays) -> tuple:
    """The policy's scanned fields, in ``words``-tuple order: the
    five string fields plus — when the policy staged a frontend
    automaton (``l7g_trans`` present, a static property of the
    staged arrays) — the l7g serialized-record field."""
    if "l7g_trans" in arrays:
        return SCAN_FIELDS + (L7G_FIELD,)
    return SCAN_FIELDS


# ------------------------------------------------------------ plan build --
def _mask_bits(mask: np.ndarray, n: int) -> np.ndarray:
    """[RS, W] uint32 bitmap → [RS, n] bool membership matrix."""
    RS, W = mask.shape
    shifts = np.arange(32, dtype=np.uint32)
    bits = ((mask[:, :, None] >> shifts[None, None, :]) & 1).astype(bool)
    return bits.reshape(RS, W * 32)[:, :n]


def _dedup_kafka_groups(arrays: Dict[str, np.ndarray],
                        n_kafka: int) -> Tuple[Dict, int]:
    """Kafka rules deduped to distinct-predicate groups: a kafka rule
    is a pure conjunction of exact matches (apikey mask / version /
    client / topic), so identical predicates across rules — the common
    case when many rulesets reference the same ACL — collapse to one
    group whose ruleset membership is the OR of its members'. Exact by
    boolean algebra: ruleset-any over rules == ruleset-any over
    distinct predicates with OR'd membership."""
    RS = arrays["rs_kafka_mask"].shape[0]
    member = _mask_bits(arrays["rs_kafka_mask"], max(1, n_kafka))
    groups: Dict[tuple, set] = {}
    rule_keys: Dict[int, tuple] = {}
    for r in range(n_kafka):
        rss = np.nonzero(member[:, r])[0]
        if not len(rss):
            continue  # unreferenced rule can never fire
        key = (int(arrays["kafka_apikey_mask"][r]),
               int(arrays["kafka_version"][r]),
               int(arrays["kafka_client"][r]),
               int(arrays["kafka_topic"][r]))
        rule_keys[r] = key
        groups.setdefault(key, set()).update(int(x) for x in rss)
    G = max(1, len(groups))
    Gw = (G + 31) // 32
    # the empty/dummy slot carries an impossible predicate spelled as
    # "never a member": zero membership words keep it inert
    k_mask = np.zeros(G, np.uint32)
    k_ver = np.full(G, -1, np.int32)
    k_cli = np.full(G, -1, np.int32)
    k_top = np.full(G, -1, np.int32)
    rs_kmask = np.zeros((RS, Gw), np.uint32)
    group_of_key: Dict[tuple, int] = {}
    for g, (key, rss) in enumerate(groups.items()):
        group_of_key[key] = g
        k_mask[g], k_ver[g], k_cli[g], k_top[g] = key
        gbit = np.uint32(1 << (g % 32))
        for rs in rss:
            rs_kmask[rs, g // 32] |= gbit
    # rule → group map: the attribution lane's bridge between the
    # legacy per-rule resolve and the fused group space (a matched
    # rule's group is matched and vice versa — exact, so the lane is
    # bit-equal across arms). Sized to the BUCKETED rule table (the
    # legacy conjunction runs over padded rule lanes); padding = -1.
    k_rule_group = np.full(
        max(1, int(arrays["kafka_apikey_mask"].shape[0])), -1,
        np.int32)
    for r, key in rule_keys.items():
        k_rule_group[r] = group_of_key[key]
    return {"rp_k_apikey_mask": k_mask, "rp_k_version": k_ver,
            "rp_k_client": k_cli, "rp_k_topic": k_top,
            "rp_rs_kmask": rs_kmask,
            "rp_k_rule_group": k_rule_group}, len(groups)


def _dedup_gen_groups(arrays: Dict[str, np.ndarray],
                      n_gen: int) -> Tuple[Dict, int]:
    """Generic (l7proto) rules deduped to distinct (proto, pair-id
    SET) groups — pair matching is subset semantics, so order and
    duplicates inside a rule's pair row are irrelevant to the
    predicate identity."""
    RS = arrays["rs_gen_mask"].shape[0]
    member = _mask_bits(arrays["rs_gen_mask"], max(1, n_gen))
    groups: Dict[tuple, set] = {}
    rule_keys: Dict[int, tuple] = {}
    for r in range(n_gen):
        if int(arrays["gen_rule_proto"][r]) < 0:
            continue  # proto-less rule is dead by construction
        rss = np.nonzero(member[:, r])[0]
        if not len(rss):
            continue
        pairs = tuple(sorted({int(p)
                              for p in arrays["gen_rule_pairs"][r]
                              if p >= 0}))
        key = (int(arrays["gen_rule_proto"][r]), pairs)
        rule_keys[r] = key
        groups.setdefault(key, set()).update(int(x) for x in rss)
    G = max(1, len(groups))
    Gw = (G + 31) // 32
    Km = max([len(k[1]) for k in groups] + [1])
    g_proto = np.full(G, -1, np.int32)
    g_pairs = np.full((G, Km), -1, np.int32)
    rs_gmask = np.zeros((RS, Gw), np.uint32)
    group_of_key: Dict[tuple, int] = {}
    for g, (key, rss) in enumerate(groups.items()):
        group_of_key[key] = g
        proto, pairs = key
        g_proto[g] = proto
        g_pairs[g, :len(pairs)] = pairs
        gbit = np.uint32(1 << (g % 32))
        for rs in rss:
            rs_gmask[rs, g // 32] |= gbit
    gen_rule_group = np.full(
        max(1, int(arrays["gen_rule_proto"].shape[0])), -1, np.int32)
    for r, key in rule_keys.items():
        gen_rule_group[r] = group_of_key[key]
    return {"rp_gen_proto": g_proto, "rp_gen_pairs": g_pairs,
            "rp_rs_genmask": rs_gmask,
            "rp_gen_rule_group": gen_rule_group}, len(groups)


def _dedup_fe_groups(arrays: Dict[str, np.ndarray],
                     n_fe: int) -> Tuple[Dict, int]:
    """Protocol-frontend rules deduped to distinct (family, scan
    lane, enum pair-id SET) predicate groups — pair matching is
    subset semantics (order/duplicates inside a rule's pair row are
    irrelevant), so identical predicates across rulesets collapse
    exactly like kafka's columnar groups. Dead rules (unsatisfiable
    scan constraints) never join a group."""
    if "fe_lane" not in arrays:
        return {}, 0
    RS = arrays["rs_fe_mask"].shape[0]
    member = _mask_bits(arrays["rs_fe_mask"], max(1, n_fe))
    groups: Dict[tuple, set] = {}
    rule_keys: Dict[int, tuple] = {}
    for r in range(n_fe):
        if bool(arrays["fe_dead"][r]):
            continue
        rss = np.nonzero(member[:, r])[0]
        if not len(rss):
            continue
        pairs = tuple(sorted({int(p) for p in arrays["fe_pairs"][r]
                              if p >= 0}))
        key = (int(arrays["fe_family"][r]),
               int(arrays["fe_lane"][r]), pairs)
        rule_keys[r] = key
        groups.setdefault(key, set()).update(int(x) for x in rss)
    G = max(1, len(groups))
    Gw = (G + 31) // 32
    Km = max([len(k[2]) for k in groups] + [1])
    g_family = np.full(G, -1, np.int32)
    g_lane = np.full(G, -1, np.int32)
    g_pairs = np.full((G, Km), -1, np.int32)
    rs_fmask = np.zeros((RS, Gw), np.uint32)
    group_of_key: Dict[tuple, int] = {}
    for g, (key, rss) in enumerate(groups.items()):
        group_of_key[key] = g
        g_family[g], g_lane[g] = key[0], key[1]
        g_pairs[g, :len(key[2])] = key[2]
        gbit = np.uint32(1 << (g % 32))
        for rs in rss:
            rs_fmask[rs, g // 32] |= gbit
    fe_rule_group = np.full(
        max(1, int(arrays["fe_lane"].shape[0])), -1, np.int32)
    for r, key in rule_keys.items():
        fe_rule_group[r] = group_of_key[key]
    return {"rp_fe_family": g_family, "rp_fe_lane": g_lane,
            "rp_fe_pairs": g_pairs, "rp_rs_femask": rs_fmask,
            "rp_fe_rule_group": fe_rule_group}, len(groups)


def build_resolve_plan(arrays: Dict[str, np.ndarray], n_http: int,
                       n_dns: int, n_kafka: int = 0,
                       n_gen: int = 0,
                       n_fe: int = 0) -> Optional[Tuple[Dict, Dict]]:
    """Factor the per-rule HTTP conjunction, the DNS lane checks, and
    the kafka/generic predicate tables into group space. Returns
    ``(rp_arrays, meta)`` — ``rp_arrays`` joins
    ``CompiledPolicy.arrays`` (staged to device), ``meta`` stays
    host-side (NFA group-plane construction, observability) — or None
    when the grouping degenerates past :data:`GROUP_CAP`."""
    RS = arrays["rs_http_mask"].shape[0]
    member = _mask_bits(arrays["rs_http_mask"], max(1, n_http))
    groups: Dict[tuple, List[int]] = {}
    for r in range(n_http):
        if arrays["http_rule_dead"][r]:
            continue  # a dead rule can never match (fail closed)
        rss = tuple(np.nonzero(member[:, r])[0].tolist())
        if not rss:
            continue  # not referenced by any ruleset
        hdr = tuple(int(x) for x in arrays["http_header_lanes"][r]
                    if x >= 0)
        log = tuple(int(x) for x in arrays["http_log_lanes"][r]
                    if x >= 0)
        key = (int(arrays["http_method_lane"][r]),
               int(arrays["http_host_lane"][r]),
               hdr, log, rss,
               int(arrays["http_path_lane"][r]) < 0)
        groups.setdefault(key, []).append(r)
    if len(groups) > GROUP_CAP:
        return None

    G = max(1, len(groups))
    Hm = max([len(k[2]) for k in groups] + [1])
    Lm = max([len(k[3]) for k in groups] + [1])
    Gw = (G + 31) // 32
    g_method = np.full(G, -1, np.int32)
    g_host = np.full(G, -1, np.int32)
    g_hdr = np.full((G, Hm), -1, np.int32)
    g_log = np.full((G, Lm), -1, np.int32)
    g_anypath = np.zeros(G, bool)
    g_haslog = np.zeros(G, bool)
    rs_gmask = np.zeros((RS, Gw), np.uint32)
    # global path lane → group bitmap (the group-accept planes of BOTH
    # scan arms derive from this one mapping)
    acc = arrays["path_accept"]                  # [NB, S, W] uint32
    NB, S, W = acc.shape
    NL = NB * 32 * W
    lane_groups = np.zeros((NL, Gw), np.uint32)
    # rule → group map (attribution lane): every live referenced rule
    # belongs to exactly one signature group. Sized to the BUCKETED
    # rule table (the legacy conjunction runs over padded lanes).
    rule_group = np.full(
        max(1, int(arrays["http_path_lane"].shape[0])), -1, np.int32)
    for g, (key, rules) in enumerate(groups.items()):
        meth, host, hdr, log, rss, anypath = key
        g_method[g] = meth
        g_host[g] = host
        g_hdr[g, :len(hdr)] = hdr
        g_log[g, :len(log)] = log
        g_anypath[g] = anypath
        g_haslog[g] = bool(log)
        gbit = np.uint32(1 << (g % 32))
        for rs in rss:
            rs_gmask[rs, g // 32] |= gbit
        for r in rules:
            rule_group[r] = g
        if not anypath:
            for r in rules:
                lane_groups[int(arrays["http_path_lane"][r]),
                            g // 32] |= gbit
    # group-accept plane over the dense path automaton: bit g at state
    # s iff any of g's member patterns accepts at s — an OR of lane
    # bits the subset construction already computed. Computed as ONE
    # batched boolean matmul (lane-hit [NB,S,L] x lane→group-bit
    # [NB,L,G] in float32 BLAS, then re-packed to words): the old
    # per-bank where+reduce allocated [S,L,Gw] temporaries per bank
    # and dominated the 5k-CNP plan rebuild (~2s of the per-update
    # critical path at fleet scale).
    lane_hit = _mask_bits(
        acc.reshape(NB * S, W).astype(np.uint32), 32 * W)  # [NB*S, 32W]
    L = 32 * W
    G_real = len(groups)
    if G_real:
        # lane_groups words → bool [NL, G_real] membership
        lg_bool = _mask_bits(lane_groups, G_real)       # [NL, G]
        hits3 = lane_hit.reshape(NB, S, L).astype(np.float32)
        lg3 = lg_bool.reshape(NB, L, G_real).astype(np.float32)
        gacc_bool = np.matmul(hits3, lg3) > 0.5         # [NB, S, G]
        # pack bit g into word g//32 at bit g%32 (little-endian)
        gb = np.pad(gacc_bool.reshape(NB * S, G_real),
                    ((0, 0), (0, Gw * 32 - G_real)))
        packed = np.packbits(gb.reshape(NB * S, Gw, 32),
                             axis=2, bitorder="little")
        gacc = packed.view(np.uint32).reshape(NB, S, Gw) \
            if packed.flags["C_CONTIGUOUS"] else \
            np.ascontiguousarray(packed).view(np.uint32).reshape(
                NB, S, Gw)
    else:
        gacc = np.zeros((NB, S, Gw), np.uint32)

    # DNS: the per-rule check is a single lane bit, so the whole
    # family collapses to a ruleset → lane-mask any
    dacc = arrays["dns_accept"]                  # [NBd, Sd, Wd]
    NWd = dacc.shape[0] * dacc.shape[2]
    dmem = _mask_bits(arrays["rs_dns_mask"], max(1, n_dns))
    dns_rsmask = np.zeros((arrays["rs_dns_mask"].shape[0], NWd),
                          np.uint32)
    dl = arrays["dns_lane"]
    for r in range(n_dns):
        if dl[r] < 0:
            continue
        lane = int(dl[r])
        for rs in np.nonzero(dmem[:, r])[0]:
            dns_rsmask[rs, lane // 32] |= np.uint32(1 << (lane % 32))

    # kafka/generic ride the same factored path (distinct-predicate
    # groups, no accept planes needed — their predicates are columnar
    # exact matches): one fused launch resolves EVERY protocol family
    # in group space
    k_arrays, k_groups = _dedup_kafka_groups(arrays, n_kafka)
    gen_arrays, gen_groups = _dedup_gen_groups(arrays, n_gen)
    fe_arrays, fe_groups = _dedup_fe_groups(arrays, n_fe)
    if len(groups) + k_groups + gen_groups + fe_groups > GROUP_CAP:
        return None

    rp = {
        "rp_g_method": g_method, "rp_g_host": g_host,
        "rp_g_hdr": g_hdr, "rp_g_log": g_log,
        "rp_g_anypath": g_anypath, "rp_g_haslog": g_haslog,
        "rp_rs_gmask": rs_gmask, "rp_path_gaccept": gacc,
        "rp_dns_rsmask": dns_rsmask,
        "rp_rule_group": rule_group,
    }
    rp.update(k_arrays)
    rp.update(gen_arrays)
    rp.update(fe_arrays)
    meta = {"groups": len(groups), "lane_groups": lane_groups,
            "kafka_groups": k_groups, "gen_groups": gen_groups,
            "fe_groups": fe_groups,
            # attribution: group → ordered member rule ids per family
            # (host-side; the explain plane maps a winning group back
            # to concrete rules through these)
            "group_rules": tuple(tuple(int(r) for r in rules)
                                 for rules in groups.values()),
            "kafka_group_rules": tuple(
                tuple(int(r) for r in range(n_kafka)
                      if int(k_arrays["rp_k_rule_group"][r]) == g)
                for g in range(k_groups)),
            "gen_group_rules": tuple(
                tuple(int(r) for r in range(n_gen)
                      if int(gen_arrays["rp_gen_rule_group"][r]) == g)
                for g in range(gen_groups)),
            "fe_group_rules": tuple(
                tuple(int(r) for r in range(n_fe)
                      if int(fe_arrays["rp_fe_rule_group"][r]) == g)
                for g in range(fe_groups)) if fe_groups else ()}
    return rp, meta


# --------------------------------------------------------- fused resolve --
def _fused_l7_http(arrays, ruleset, words, gwords, l7t):
    """Group-space HTTP conjunction: (http_ok, l7_log_http, win)
    bit-equal to the legacy per-rule path — ``win`` is the lowest
    matched-and-in-ruleset group index (the attribution lane's value;
    -1 when nothing matched)."""
    from cilium_tpu.engine.verdict import (
        _bools_to_words,
        _first_lane,
        _rule_bit,
    )

    _path_w, method_w, host_w, hdr_w, _dns_w = words[:5]
    sig_ok = (_rule_bit(method_w, arrays["rp_g_method"])
              & _rule_bit(host_w, arrays["rp_g_host"]))
    hdr_ok = jax.vmap(lambda lanes: _rule_bit(hdr_w, lanes),
                      in_axes=1, out_axes=2)(arrays["rp_g_hdr"])
    sig_ok = sig_ok & jnp.all(hdr_ok, axis=2)
    G = arrays["rp_g_method"].shape[0]
    gbit = _rule_bit(gwords, jnp.arange(G, dtype=jnp.int32))
    ok_g = sig_ok & (arrays["rp_g_anypath"][None, :] | gbit)
    Gw = arrays["rp_rs_gmask"].shape[1]
    ok_words = _bools_to_words(ok_g, Gw)
    gmask = arrays["rp_rs_gmask"][ruleset]
    http_ok = (jnp.any((ok_words & gmask) != 0, axis=1)
               & (l7t == int(L7Type.HTTP)))
    win = _first_lane(ok_words & gmask)
    # LOG-action lanes ride the group signature: a matching group
    # whose LOG lane mismatched raises l7_log (allow + log)
    log_bits = jax.vmap(lambda lanes: _rule_bit(hdr_w, lanes),
                        in_axes=1, out_axes=2)(arrays["rp_g_log"])
    log_fail = (jnp.any(~log_bits, axis=2)
                & arrays["rp_g_haslog"][None, :])
    logw = _bools_to_words(ok_g & log_fail, Gw)
    l7_log_http = jnp.any((logw & gmask) != 0, axis=1) & http_ok
    return http_ok, l7_log_http, win


def _fused_l7_dns(arrays, ruleset, dns_w, l7t):
    from cilium_tpu.engine.verdict import _first_lane

    dmask = arrays["rp_dns_rsmask"][ruleset]
    ok = (jnp.any((dns_w & dmask) != 0, axis=1)
          & (l7t == int(L7Type.DNS)))
    return ok, _first_lane(dns_w & dmask)


def _fused_l7_kafka(arrays, ruleset, kafka_cols, l7t):
    """Group-space kafka conjunction over the DEDUPED predicate table
    (``rp_k_*``) — same formula as the legacy ``_l7_kafka``, evaluated
    once per distinct predicate instead of once per rule. Returns
    ``(ok, win)`` with ``win`` the lowest matched group index."""
    from cilium_tpu.engine.verdict import _bools_to_words, _first_lane

    k_api, k_ver, k_cli, k_top = kafka_cols
    ak = jnp.clip(k_api, 0, 31).astype(jnp.uint32)
    am = arrays["rp_k_apikey_mask"][None, :]        # [1, Gk]
    # api_key < 0 is the unknown-role sentinel — it matches only
    # api-key-unconstrained predicates (see _l7_kafka)
    g_ok = (
        ((am == 0) | (((am >> ak[:, None]) & jnp.uint32(1)).astype(bool)
                      & (k_api >= 0)[:, None]))
        & ((arrays["rp_k_version"][None, :] < 0)
           | (arrays["rp_k_version"][None, :] == k_ver[:, None]))
        & ((arrays["rp_k_client"][None, :] < 0)
           | (arrays["rp_k_client"][None, :] == k_cli[:, None]))
        & ((arrays["rp_k_topic"][None, :] < 0)
           | (arrays["rp_k_topic"][None, :] == k_top[:, None]))
    )
    gmask = arrays["rp_rs_kmask"][ruleset]
    g_words = _bools_to_words(g_ok, gmask.shape[1])
    ok = (jnp.any((g_words & gmask) != 0, axis=1)
          & (l7t == int(L7Type.KAFKA)))
    return ok, _first_lane(g_words & gmask)


def _fused_l7_generic(arrays, ruleset, gen_cols, l7t):
    """Group-space generic pair-subset matching over the deduped
    (proto, pair-set) predicate table (``rp_gen_*``)."""
    from cilium_tpu.engine.verdict import _bools_to_words, _first_lane

    gen_proto, gen_pairs = gen_cols
    grp = arrays["rp_gen_pairs"]                # [Gg, Km]
    have = jnp.any(
        gen_pairs[:, None, None, :] == grp[None, :, :, None],
        axis=-1)                                # [B, Gg, Km]
    pair_ok = jnp.all(jnp.where(grp[None, :, :] < 0, True, have),
                      axis=-1)
    proto_ok = (arrays["rp_gen_proto"][None, :]
                == gen_proto[:, None])          # [B, Gg]
    g_ok = pair_ok & proto_ok \
        & (arrays["rp_gen_proto"] >= 0)[None, :]
    gmask = arrays["rp_rs_genmask"][ruleset]
    g_words = _bools_to_words(g_ok, gmask.shape[1])
    ok = (jnp.any((g_words & gmask) != 0, axis=1)
          & (l7t == int(L7Type.GENERIC)))
    return ok, _first_lane(g_words & gmask)


def _fused_l7_frontend(arrays, ruleset, l7g_w, gen_pairs, l7t):
    """Group-space protocol-frontend matching over the deduped
    (family, scan lane, enum pair-set) predicate table (``rp_fe_*``)
    — the frontend analog of ``_fused_l7_kafka``: one scan-lane bit,
    one pair-subset check, one family equality per distinct
    predicate."""
    from cilium_tpu.engine.verdict import (
        _bools_to_words,
        _first_lane,
        _rule_bit,
    )

    grp = arrays["rp_fe_pairs"]                 # [Gf, Km]
    have = jnp.any(
        gen_pairs[:, None, None, :] == grp[None, :, :, None],
        axis=-1)                                # [B, Gf, Km]
    pair_ok = jnp.all(jnp.where(grp[None, :, :] < 0, True, have),
                      axis=-1)
    g_ok = (_rule_bit(l7g_w, arrays["rp_fe_lane"])
            & pair_ok
            & (arrays["rp_fe_family"][None, :] == l7t[:, None])
            & (arrays["rp_fe_family"] >= 0)[None, :])
    gmask = arrays["rp_rs_femask"][ruleset]
    g_words = _bools_to_words(g_ok, gmask.shape[1])
    ok = jnp.any((g_words & gmask) != 0, axis=1)
    return ok, _first_lane(g_words & gmask)


def fused_verdict_core(arrays, ms, l7t, words, gwords, kafka_cols,
                       auth_src_dst, batch, gen_cols=None):
    """The factored-resolve back half; shares the precedence/auth/
    audit assembly with the legacy ``_verdict_core`` so the two paths
    cannot drift on the verdict-code semantics. Kafka/generic use
    their deduped predicate groups when the plan staged them
    (``rp_k_*``/``rp_gen_*`` — every protocol family resolves in one
    fused launch); plans from older artifacts fall back to the
    per-rule helpers, still bit-equal."""
    from cilium_tpu.engine.verdict import (
        _assemble_verdict,
        _combine_l7_match,
        _l7_frontend,
        _l7_generic,
        _l7_kafka,
    )

    ruleset = jnp.clip(ms["ruleset"], 0,
                       arrays["rs_http_mask"].shape[0] - 1)
    l7g_w = words[5] if len(words) > 5 else None
    http_ok, l7_log_http, http_win = _fused_l7_http(
        arrays, ruleset, words, gwords, l7t)
    if "rp_rs_kmask" in arrays:      # static under jit
        kafka_ok, kafka_win = _fused_l7_kafka(arrays, ruleset,
                                              kafka_cols, l7t)
    else:
        kafka_ok, kafka_win = _l7_kafka(arrays, ruleset, kafka_cols,
                                        l7t)
    dns_ok, dns_win = _fused_l7_dns(arrays, ruleset, words[4], l7t)
    l7_ok = http_ok | kafka_ok | dns_ok
    gen_ok = gen_win = None
    if gen_cols is not None:
        if "rp_rs_genmask" in arrays:
            gen_ok, gen_win = _fused_l7_generic(arrays, ruleset,
                                                gen_cols, l7t)
        else:
            gen_ok, gen_win = _l7_generic(arrays, ruleset, gen_cols,
                                          l7t)
        l7_ok = l7_ok | gen_ok
    fe_ok = fe_win = None
    if l7g_w is not None and gen_cols is not None \
            and "fe_lane" in arrays:
        if "rp_rs_femask" in arrays:
            fe_ok, fe_win = _fused_l7_frontend(arrays, ruleset,
                                               l7g_w, gen_cols[1],
                                               l7t)
        else:
            fe_ok, fe_win = _l7_frontend(arrays, ruleset, l7g_w,
                                         gen_cols[1], l7t)
        l7_ok = l7_ok | fe_ok
    l7_match = _combine_l7_match(
        (http_ok, http_win), (kafka_ok, kafka_win),
        (dns_ok, dns_win),
        (gen_ok, gen_win) if gen_ok is not None else None,
        fe=(fe_ok, fe_win) if fe_ok is not None else None)
    return _assemble_verdict(arrays, ms, l7_ok, l7_log_http,
                             auth_src_dst, batch, l7_match=l7_match)


# ------------------------------------------------------------ fused step --
def _nfa_stack(arrays, prefix: str) -> Dict[str, jax.Array]:
    return {k: arrays[f"{prefix}_{k}"]
            for k in ("nfa_follow", "nfa_acc_cls", "nfa_byteclass",
                      "nfa_start", "nfa_accept", "nfa_empty")
            if f"{prefix}_{k}" in arrays}


def fused_scan_field(arrays, prefix: str, data, lengths, valid,
                     impl: str = IMPL_DENSE, dfa_impl: str = "gather",
                     interpret: bool = False,
                     use_pallas_nfa: bool = False,
                     want_groups: bool = False):
    """One field's banked scan under the planned impl → flat match
    words [B, NW] (+ bank-ORed group words [B, Gw])."""
    from cilium_tpu.engine.dfa_kernel import dfa_scan_banked

    if impl == IMPL_NFA:
        stacked = _nfa_stack(arrays, prefix)
        if want_groups:
            stacked["nfa_gaccept"] = arrays[f"{prefix}_nfa_gaccept"]
        out = nfa_kernel.nfa_scan_banked(
            stacked, data, lengths, extra_accept=want_groups,
            use_pallas=use_pallas_nfa, interpret=interpret)
    else:
        out = dfa_scan_banked(
            arrays[f"{prefix}_trans"], arrays[f"{prefix}_byteclass"],
            arrays[f"{prefix}_start"], arrays[f"{prefix}_accept"],
            data, lengths, impl=dfa_impl, interpret=interpret,
            extra_accept=(arrays["rp_path_gaccept"] if want_groups
                          else None))
    if want_groups:
        w3, g3 = out
        gwords = jax.lax.reduce(g3, jnp.uint32(0),
                                jax.lax.bitwise_or, (1,))
        gwords = jnp.where(valid[:, None], gwords, 0)
    else:
        w3, gwords = out, None
    flat = w3.reshape(w3.shape[0], -1)
    return jnp.where(valid[:, None], flat, 0), gwords


def fused_verdict_step(arrays, batch, *, impl_plan=(),
                       dfa_impl: str = "gather",
                       interpret: bool = False,
                       use_pallas_nfa: bool = False):
    """The megakernel: full verdict for one batch in ONE dispatch.

    ``impl_plan`` is a static tuple of (field-prefix, impl) picks from
    :func:`plan_for_engine`; fields absent default to the dense arm.
    Bit-equal to ``verdict_step`` for every plan."""
    from cilium_tpu.core.flow import TrafficDirection
    from cilium_tpu.engine.verdict import (
        _verdict_core,
        batch_field,
        unpack_batch,
    )
    from cilium_tpu.engine.mapstate_kernel import mapstate_lookup

    b = unpack_batch(batch) if "scalars" in batch else batch
    ms = mapstate_lookup(
        arrays["ms_key_w0"], arrays["ms_key_w1"], arrays["ms_key_w2"],
        arrays["ms_deny"], arrays["ms_ruleset"],
        arrays["ms_enf_ids"], arrays["ms_enf_flags"],
        b["ep_ids"], b["peer_ids"], b["dports"],
        b["protos"], b["directions"],
        auth=arrays.get("ms_auth"),
        port_plens=arrays.get("ms_plens"),
        tmpl_ids=arrays.get("ms_tmpl_ids"))
    plan_on = "rp_g_method" in arrays  # static under jit
    impls = dict(impl_plan)
    words = []
    gwords = None
    for prefix, field in scan_fields(arrays):
        w, gw = fused_scan_field(
            arrays, prefix, *batch_field(b, field),
            impl=impls.get(prefix, IMPL_DENSE), dfa_impl=dfa_impl,
            interpret=interpret, use_pallas_nfa=use_pallas_nfa,
            want_groups=(plan_on and prefix == "path"))
        words.append(w)
        if gw is not None:
            gwords = gw
    words = tuple(words)
    ingress = b["directions"] == int(TrafficDirection.INGRESS)
    src = jnp.where(ingress, b["peer_ids"], b["ep_ids"])
    dst = jnp.where(ingress, b["ep_ids"], b["peer_ids"])
    kafka_cols = (b["kafka_api_key"], b["kafka_api_version"],
                  b["kafka_client"], b["kafka_topic"])
    gen_cols = (b["gen_proto"], b["gen_pairs"])
    if not plan_on:
        return _verdict_core(arrays, ms, b["l7_types"], words,
                             kafka_cols, (src, dst), b,
                             gen_cols=gen_cols)
    return fused_verdict_core(arrays, ms, b["l7_types"], words, gwords,
                              kafka_cols, (src, dst), b,
                              gen_cols=gen_cols)


# -------------------------------------------------------------- autotune --
#: (shape key) → {"impl", "dense_ms", "nfa_ms"} — process-wide, and
#: snapshotted through the loader's warm-restart state so a restarted
#: daemon keeps its picks without re-benching
_AUTOTUNE_CACHE: Dict[tuple, Dict] = {}


def autotune_cache_snapshot() -> Dict:
    return {repr(k): dict(v) for k, v in _AUTOTUNE_CACHE.items()}


def autotune_cache_adopt(snap: Optional[Dict]) -> None:
    import ast

    if not snap:
        return
    for k, v in snap.items():
        try:
            key = ast.literal_eval(k)
        except (ValueError, SyntaxError):
            continue  # foreign snapshot entry: skip, never crash warm restore
        if isinstance(key, tuple):
            # ctlint: disable=unbounded-registry  # keyed by bucketed bank shape x backend (finite)
            _AUTOTUNE_CACHE.setdefault(key, dict(v))


def _shape_key(field: str, trans_shape, nfa_shape, L: int) -> tuple:
    return (field, tuple(trans_shape), tuple(nfa_shape or ()),
            int(L), jax.default_backend())


def _time_scan(fn, reps: int = 3) -> float:
    out = fn()
    jax.block_until_ready(out)  # compile excluded from the sample
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def autotune_field(field: str, arrays: Dict, prefix: str,
                   nfa_stacked: Optional[Dict], width: int,
                   interpret: bool, probe_batch: int = 256) -> Dict:
    """Measure dense vs bitset-NFA on this field's REAL bank tensors
    over a synthetic batch of the field's width; cached by shape key."""
    from cilium_tpu.engine.dfa_kernel import dfa_scan_banked

    trans = arrays[f"{prefix}_trans"]
    key = _shape_key(
        field, np.shape(trans),
        np.shape(nfa_stacked["nfa_follow"]) if nfa_stacked else None,
        width)
    hit = _AUTOTUNE_CACHE.get(key)
    if hit is not None:
        return hit
    t0 = time.perf_counter()
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 128, size=(probe_batch, width),
                                    dtype=np.uint8))
    lengths = jnp.asarray(
        rng.integers(0, width + 1, size=(probe_batch,)).astype(np.int32))
    dense_ms = _time_scan(lambda: jax.jit(dfa_scan_banked)(
        arrays[f"{prefix}_trans"], arrays[f"{prefix}_byteclass"],
        arrays[f"{prefix}_start"], arrays[f"{prefix}_accept"],
        data, lengths)) * 1e3
    if nfa_stacked is None:
        result = {"impl": IMPL_DENSE, "dense_ms": round(dense_ms, 3),
                  "nfa_ms": None}
    else:
        stacked = {k: jnp.asarray(v) for k, v in nfa_stacked.items()
                   if k != "nfa_gaccept"}
        nfa_ms = _time_scan(lambda: jax.jit(
            lambda s, d, l: nfa_kernel.nfa_scan_banked(
                s, d, l, interpret=interpret))(
            stacked, data, lengths)) * 1e3
        result = {"impl": IMPL_NFA if nfa_ms < dense_ms else IMPL_DENSE,
                  "dense_ms": round(dense_ms, 3),
                  "nfa_ms": round(nfa_ms, 3)}
    _AUTOTUNE_CACHE[key] = result
    METRICS.observe(KERNEL_AUTOTUNE_SECONDS, time.perf_counter() - t0)
    METRICS.inc(KERNEL_AUTOTUNE_PICKS,
                labels={"impl": result["impl"], "field": field})
    return result


def _field_widths(cfg) -> Dict[str, int]:
    return {"path": max(cfg.http_path_buckets),
            "method": cfg.http_method_len, "host": cfg.http_host_len,
            "hdr": 1024, "dns": cfg.dns_name_len,
            "l7g": getattr(cfg, "l7g_len", 256)}


def plan_for_engine(policy, cfg, interpret: bool) -> Tuple[
        Dict[str, str], Dict[str, np.ndarray], Dict[str, Dict]]:
    """Pick a scan impl per field stack; build the NFA tensors the
    picks need. Returns ``(impl_plan, extra_arrays, report)`` —
    ``extra_arrays`` joins the engine's device arrays, ``report``
    (per-field pick + timings) lands on the policy's kernel plan and
    the bench lines."""
    mode = getattr(cfg, "kernel_impl", "auto")
    degraded = bool(getattr(policy, "bank_quarantined", ()))
    matchers = {"path": policy.path_matcher,
                "method": policy.method_matcher,
                "host": policy.host_matcher,
                "hdr": policy.header_matcher,
                "dns": policy.dns_matcher}
    if getattr(policy, "l7g_matcher", None) is not None:
        # protocol-frontend automaton: autotuned/armed like any field
        matchers["l7g"] = policy.l7g_matcher
    widths = _field_widths(cfg)
    lane_groups = (policy.resolve_meta or {}).get("lane_groups") \
        if getattr(policy, "resolve_meta", None) is not None else None
    impl_plan: Dict[str, str] = {}
    extra: Dict[str, np.ndarray] = {}
    report: Dict[str, Dict] = {}

    for prefix, matcher in matchers.items():
        trans = policy.arrays[f"{prefix}_trans"]
        dense_pallas_ok = trans.shape[1] <= 128
        # only pay the NFA construction when the mode can actually use
        # it: forced/measured picks always, the heuristic only in its
        # one preferred regime (TPU + dense-Pallas-ineligible banks)
        want_nfa = (mode in ("autotune", IMPL_NFA)
                    or (mode == "auto" and not dense_pallas_ok
                        and jax.default_backend() == "tpu"))
        nfa_banks = None
        if not degraded and want_nfa:
            # stale quarantine covers can't be reconstructed from the
            # current pattern set — the NFA arm sits out degraded builds
            nfa_banks = nfa_kernel.banks_from_dfa(
                matcher.banked, cfg,
                case_insensitive=(prefix == "host"))
        nfa_stacked = None
        if nfa_banks is not None:
            gacc = None
            if prefix == "path" and lane_groups is not None:
                gacc = [_nfa_group_plane(b, i, trans.shape,
                                         policy.arrays, lane_groups)
                        for i, b in enumerate(nfa_banks)]
            nfa_stacked = nfa_kernel.stack_nfa_banks(
                nfa_banks, extra_accept=gacc)
        if mode == IMPL_NFA and nfa_stacked is not None:
            pick = {"impl": IMPL_NFA, "dense_ms": None, "nfa_ms": None}
        elif mode == "autotune":
            pick = autotune_field(prefix, policy.arrays, prefix,
                                  nfa_stacked, widths[prefix],
                                  interpret)
        elif mode == "auto" and jax.default_backend() == "tpu" \
                and not dense_pallas_ok and nfa_stacked is not None:
            # the one regime where the heuristic prefers the NFA arm
            # without measuring: the dense Pallas kernel can't hold the
            # bank (DFA blew the 128-state tile) but the positions fit
            pick = {"impl": IMPL_NFA, "dense_ms": None, "nfa_ms": None}
        else:
            pick = {"impl": IMPL_DENSE, "dense_ms": None,
                    "nfa_ms": None}
        impl = pick["impl"]
        if impl == IMPL_NFA and nfa_stacked is None:
            impl = IMPL_DENSE  # forced arm, ineligible bank → dense
        if impl == IMPL_NFA:
            for k, v in nfa_stacked.items():
                extra[f"{prefix}_{k}"] = v
        impl_plan[prefix] = impl
        report[prefix] = {**pick, "impl": impl,
                          "banks": int(trans.shape[0]),
                          "dfa_states": int(trans.shape[1]),
                          "nfa_positions": (
                              int(nfa_stacked["nfa_follow"].shape[1])
                              if nfa_stacked is not None else None)}
    return impl_plan, extra, report


def _nfa_group_plane(bank, bank_idx: int, trans_shape,
                     arrays, lane_groups: np.ndarray) -> np.ndarray:
    """Group-accept plane for one NFA bank: position → group bitmap,
    derived from the same lane→group mapping as the dense plane."""
    W = bank.accept.shape[1]
    Gw = lane_groups.shape[1]
    P = bank.n_positions
    if P == 0:
        return np.zeros((0, Gw), np.uint32)
    # the global lane space is laid out by the DENSE stack's word
    # width — recompute it from the policy's stacked accept tensor
    W_stack = arrays["path_accept"].shape[2]
    bits = _mask_bits(bank.accept.astype(np.uint32), 32 * W)
    out = np.zeros((P, Gw), np.uint32)
    base = bank_idx * 32 * W_stack
    for lane in range(32 * W):
        gl = base + lane
        if gl >= lane_groups.shape[0]:
            break
        row = lane_groups[gl]
        if not row.any():
            continue
        out |= np.where(bits[:, lane:lane + 1], row[None, :],
                        np.uint32(0))
    return out
