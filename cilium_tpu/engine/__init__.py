"""The TPU verdict engine — the "datapath".

JAX kernels replacing the reference's per-packet eBPF policy-map lookup
(``bpf/bpf_lxc.c`` + ``bpf/lib/policy.h``) and per-request L7 matching
(Envoy RE2 / proxylib state machines) with batched tensor computations
(SURVEY.md §2.3 table, §3.3/§3.4 call stacks).
"""

from cilium_tpu.engine.dfa_kernel import dfa_scan, dfa_scan_banked, match_bits
from cilium_tpu.engine.mapstate_kernel import (
    PackedMapState,
    pack_mapstate,
    mapstate_lookup,
)
from cilium_tpu.engine.verdict import (
    CompiledPolicy,
    VerdictEngine,
    encode_strings,
)

__all__ = [
    "dfa_scan",
    "dfa_scan_banked",
    "match_bits",
    "PackedMapState",
    "pack_mapstate",
    "mapstate_lookup",
    "CompiledPolicy",
    "VerdictEngine",
    "encode_strings",
]
