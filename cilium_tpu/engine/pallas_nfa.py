"""Pallas TPU kernel for the banked bitset-NFA byte-scan.

The MXU-resident face of ``engine/nfa_kernel.py``: the per-byte
position advance

    D' = ((Followᵀ · D) > 0) ⊙ (ClassAccept · onehot(class))

is two matmuls per byte — the block-structured follow advance and the
class-acceptance plane select — with the position bitset ``D`` living
as a ``[P ≤ 128, TILE]`` tile in VMEM for the whole byte loop of its
grid cell. Rules-as-lanes: every rule's positions ride the same tile,
so one MXU pass advances the whole bank. Like ``engine/pallas_dfa.py``
this is data-oblivious (fixed shapes, no data-dependent gathers) and
exact: all operands are 0/1, products accumulate counts ≤ 128 in f32
(``preferred_element_type`` pinned), thresholding recovers the OR.

Padding bytes use a *hold class* (index ``KP-1``): the host-side
byte→class lookup writes the hold class wherever t ≥ length, and the
kernel carries the bitset through unchanged on those lanes — no
length input and no masked loads in the hot loop. Zero-length strings
come out as the (frozen) start set; the caller's accept extraction
overrides them with the empty-string accept words, exactly like the
XLA formulation.

Constraints: positions per bank ≤ 128 (one MXU tile —
``nfa_kernel.MAX_POSITIONS``). Grid: (bank, batch-tile).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from cilium_tpu.engine.nfa_kernel import MAX_POSITIONS

TILE = 1024     # flows per grid cell (lane axis: 8×128 tiles)


def _nfa_kernel(cls_ref, follow_t_ref, acc_ref, start_ref, out_ref):
    """One (bank, batch-tile) cell: scan L bytes, emit final bitsets.

    cls_ref      [1, L, TILE]   int32  byte classes (KP-1 = hold/pad)
    follow_t_ref [1, PP, PP]    bf16   transposed ε-closed follow
    acc_ref      [1, KP, PP]    bf16   class-acceptance plane (class-major
                                       so the lane axis stays 128-wide)
    start_ref    [1, PP, 128]   f32    start bitset in column 0
    out_ref      [1, 1, PP, TILE] f32  final position bitsets (0/1)
    """
    _, L, TILE_ = cls_ref.shape
    _, KP, PP = acc_ref.shape
    follow_t = follow_t_ref[0]                               # [PP, PP]
    acc = acc_ref[0]                                         # [KP, PP]
    start = start_ref[0, :, 0:1]                             # [PP, 1]
    iota_k = lax.broadcasted_iota(jnp.int32, (KP, TILE_), 0)

    def masks(t):
        c = cls_ref[0, t]                                    # [TILE]
        oh_c = (iota_k == c[None, :]).astype(jnp.bfloat16)   # [KP, TILE]
        # contract the class axis directly — no in-kernel transpose
        am = lax.dot_general(acc, oh_c, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        hold = oh_c[KP - 1].astype(jnp.float32)              # [TILE]
        return am, hold

    am0, hold0 = masks(0)
    v0 = jnp.broadcast_to(start, (PP, TILE_)).astype(jnp.float32)
    v = jnp.where(hold0[None, :] > 0, v0, v0 * am0)

    def step(t, v):
        am, hold = masks(t)
        pre = jnp.dot(follow_t, v.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)    # [PP, TILE]
        nxt = (pre > 0).astype(jnp.float32) * am
        return jnp.where(hold[None, :] > 0, v, nxt)

    v = lax.fori_loop(1, L, step, v)
    out_ref[0, 0] = v


@functools.partial(jax.jit, static_argnames=("interpret", "tile"))
def nfa_finals_pallas(
    follow: jax.Array,      # [NB, P, P] f32, P ≤ 128
    acc_cls: jax.Array,     # [NB, P, K] f32
    byteclass: jax.Array,   # [NB, 256] int32
    start: jax.Array,       # [NB, P] f32
    data: jax.Array,        # [B, L] uint8/int32
    lengths: jax.Array,     # [B] int32
    interpret: bool = False,
    tile: int = TILE,
) -> jax.Array:
    """Final position bitsets for every (bank, flow) → [NB, B, P] f32.

    Zero-length flows come out as the frozen start set; callers mask
    them with the empty-string accept words (``nfa_kernel._accept_of``
    does exactly that)."""
    NB, P, K = acc_cls.shape
    if P > MAX_POSITIONS:
        raise ValueError(
            f"pallas NFA kernel needs ≤{MAX_POSITIONS} positions/bank, "
            f"got {P} (compile with a smaller bank_size)")
    B, L = data.shape
    PP = MAX_POSITIONS
    KP = max(8, -(-(K + 1) // 8) * 8)
    HOLD = KP - 1
    NT = max(1, -(-B // tile))
    BP = NT * tile

    follow_p = jnp.zeros((NB, PP, PP), jnp.float32) \
        .at[:, :P, :P].set(follow)
    follow_t = jnp.transpose(follow_p, (0, 2, 1)).astype(jnp.bfloat16)
    acc_p = jnp.zeros((NB, KP, PP), jnp.bfloat16) \
        .at[:, :K, :P].set(
            jnp.transpose(acc_cls, (0, 2, 1)).astype(jnp.bfloat16))
    start_p = jnp.zeros((NB, PP, 128), jnp.float32) \
        .at[:, :P, 0].set(start)

    # byte → class outside the kernel (256-entry table, bounded
    # entropy); padding positions get the hold class
    cls = jax.vmap(lambda bc: bc[data.astype(jnp.int32)])(byteclass)
    pad_pos = jnp.arange(L, dtype=jnp.int32)[None, :] >= lengths[:, None]
    cls = jnp.where(pad_pos[None, :, :], HOLD, cls)          # [NB, B, L]
    cls = jnp.transpose(cls, (0, 2, 1))                      # [NB, L, B]
    cls = jnp.pad(cls, ((0, 0), (0, 0), (0, BP - B)),
                  constant_values=HOLD)

    finals = pl.pallas_call(
        _nfa_kernel,
        grid=(NB, NT),
        in_specs=[
            pl.BlockSpec((1, L, tile), lambda b, t: (b, 0, t)),
            pl.BlockSpec((1, PP, PP), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, KP, PP), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, PP, 128), lambda b, t: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, PP, tile),
                               lambda b, t: (b, t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((NB, NT, PP, BP // NT),
                                       jnp.float32),
        interpret=interpret,
    )(cls, follow_t, acc_p, start_p)
    finals = jnp.transpose(finals, (0, 1, 3, 2)).reshape(NB, BP, PP)
    return finals[:, :B, :P]
