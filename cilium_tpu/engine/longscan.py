"""Long-payload automaton scanning: SP + CP (ring) parallelism.

The reference handles long payloads by *streaming* (proxylib ``OnData``
returns MORE with bounded buffers — SURVEY.md §5.7); a TPU wants the
whole payload resident and the scan *parallelized*. The key identity:
a DFA's per-byte step is a function ``f_c: S→S``, and function
composition is **associative** — so a payload's net effect can be
computed blockwise:

* **SP (sequence parallel, single device)** — split the payload into
  blocks; compute each block's composed transition vector ``g[S]`` with
  a sequential ``lax.scan`` *inside* the block but vectorized *across*
  blocks; combine blocks with ``lax.associative_scan`` (log depth).
  Parallelism L/block × S instead of a length-L sequential chain.
* **CP (context parallel, multi-device)** — shard the payload length
  across a mesh axis; each device composes its shard locally, then a
  **ring ``ppermute`` pass** circulates the small ``[S]`` carry
  (ring-attention-shaped: heavy local compute + neighbor exchange of a
  small state), giving each device the composition of everything to its
  left; one more local apply yields the final state.

Composition cost is an S-wide gather per step, so this pays off when
S is modest (payload automata: tens of states) and L is large (the
regime the reference's streaming parsers target).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _compose(f: jax.Array, g: jax.Array) -> jax.Array:
    """(f ∘ g)[s] = f[g[s]] — apply g first, then f.

    Supports leading batch dims on both (broadcast like jnp ops):
    f, g: [..., S] int32.
    """
    return jnp.take_along_axis(f, g, axis=-1)


def block_transitions(
    trans: jax.Array,       # [S, K] int32
    byteclass: jax.Array,   # [256] int32
    data: jax.Array,        # [..., L] uint8 — L is the block length
    valid: Optional[jax.Array] = None,  # [..., L] bool, False = skip byte
) -> jax.Array:
    """Composed transition vector for each block: out[..., S] with
    out[..., s] = state reached from s after consuming the block."""
    S = trans.shape[0]
    cls = byteclass[data.astype(jnp.int32)]            # [..., L]
    L = data.shape[-1]

    def step(g, t):
        # next g[s] = T[g[s], c_t]  (apply byte t after the prefix)
        c_t = cls[..., t]                               # [...]
        rows = jnp.take_along_axis(
            trans[g], c_t[..., None, None],
            axis=-1)[..., 0]                            # [..., S]
        if valid is not None:
            rows = jnp.where(valid[..., t, None], rows, g)
        return rows, None

    init = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32),
                            data.shape[:-1] + (S,))
    out, _ = lax.scan(step, init, jnp.arange(L, dtype=jnp.int32))
    return out


def payload_scan_sp(
    trans: jax.Array,       # [S, K]
    byteclass: jax.Array,   # [256]
    start: jax.Array,       # scalar int32
    data: jax.Array,        # [B, L] uint8
    lengths: jax.Array,     # [B] int32
    block: int = 256,
) -> jax.Array:
    """Final DFA states [B] for long payloads, blockwise-parallel."""
    B, L = data.shape
    pad = (-L) % block
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)))
    nblocks = data.shape[1] // block
    blocks = data.reshape(B, nblocks, block)
    pos = (jnp.arange(nblocks * block)
           .reshape(nblocks, block))                    # [nb, block]
    valid = pos[None, :, :] < lengths[:, None, None]    # [B, nb, block]

    g = block_transitions(trans, byteclass, blocks, valid)  # [B, nb, S]
    # left-to-right composition: net = g_nb ∘ ... ∘ g_1.
    # associative_scan composes adjacent pairs; with fn(a, b) where a is
    # the earlier block, the combined effect is b ∘ a (a applied first).
    net = lax.associative_scan(
        lambda a, b: _compose(b, a), g, axis=1)         # prefix compositions
    final_fn = net[:, -1, :]                            # [B, S]
    return jnp.take_along_axis(
        final_fn, jnp.broadcast_to(start, (B,))[:, None].astype(jnp.int32),
        axis=1)[:, 0]


@functools.lru_cache(maxsize=None)
def _cp_step(mesh: Mesh, seq_axis: str, block: int):
    """Cached shard_map wrapper per (mesh, axis, block): the wrapper
    used to be rebuilt inside :func:`payload_scan_cp`, so every call
    was a fresh closure — a jit-cache miss and a full re-trace per
    payload batch (ctlint recompile-hazard). Batch size and shard
    length are read off the shard inside, so the same compiled step
    serves every payload shape that hits it."""
    n_dev = mesh.shape[seq_axis]

    def local(trans, byteclass, start, data_shard, lengths):
        B, shard_len = data_shard.shape
        # my position on the ring
        idx = lax.axis_index(seq_axis)
        offset = idx * shard_len
        # local composed function over my shard (blockwise SP inside)
        pad = (-shard_len) % block
        d = jnp.pad(data_shard, ((0, 0), (0, pad))) if pad else data_shard
        nb = d.shape[1] // block
        blocks = d.reshape(B, nb, block)
        pos = offset + jnp.arange(nb * block).reshape(nb, block)
        valid = pos[None, :, :] < lengths[:, None, None]
        g = block_transitions(trans, byteclass, blocks, valid)
        net = lax.associative_scan(lambda a, b: _compose(b, a), g, axis=1)
        mine = net[:, -1, :]                            # [B, S]

        # ring exclusive-prefix composition: after n_dev-1 steps,
        # ``carry`` = composition of all shards strictly to my left.
        S = trans.shape[0]
        identity = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

        from cilium_tpu.parallel import collectives

        def ring_step(i, state):
            carry, send = state
            recv = collectives.ppermute(send, seq_axis, perm,
                                        site="cp.ring_carry")
            # recv = cumulative of the sender (my left neighbor, covering
            # shards [sender-k .. sender]); fold into my carry only while
            # it still describes shards left of me: step i delivers the
            # shard i+1 to my left.
            take = (idx - 1 - i) >= 0
            carry = jnp.where(take, _compose(carry, recv), carry)
            return carry, recv

        carry = identity
        send = mine
        # the ring body traces once, executes n_dev-1 times per block
        # (a 1-device mesh runs it zero times — factor 0 records 0)
        with collectives.LEDGER.scaled(n_dev - 1):
            carry, _ = lax.fori_loop(
                0, n_dev - 1, lambda i, st: ring_step(i, st),
                (carry, send))
        # NOTE: this fori ring passes each device's LOCAL function one
        # hop per step, so after k steps I have received the local
        # function of the device k hops left and composed it in order.
        final_fn = _compose(mine, carry)                # [B, S]
        states = jnp.take_along_axis(
            final_fn,
            jnp.broadcast_to(start, (B,))[:, None].astype(jnp.int32),
            axis=1)[:, 0]
        # device idx holds the composition of shards [0..idx]; only the
        # last device has the whole payload — gather and keep its answer
        all_states = collectives.all_gather(
            states, seq_axis, site="cp.final_gather")   # [n_dev, B]
        return all_states[n_dev - 1]

    from cilium_tpu.parallel.compat import shard_map

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P(), P(None, seq_axis), P()),
        out_specs=P(),
        check_vma=False,
    )


def payload_scan_cp(
    mesh: Mesh,
    trans,                  # [S, K]
    byteclass,              # [256]
    start,                  # scalar int32
    data,                   # [B, L] — L sharded over seq_axis
    lengths,                # [B]
    seq_axis: str = "seq",
    block: int = 256,
):
    """Context-parallel payload scan: L sharded across ``seq_axis``;
    per-device blockwise composition + ring ppermute of the carry."""
    n_dev = mesh.shape[seq_axis]
    _B, L = data.shape
    assert L % n_dev == 0, "payload length must divide the seq axis"
    fn = _cp_step(mesh, seq_axis, block)
    return fn(trans, byteclass, jnp.asarray(start, jnp.int32), data,
              lengths)
