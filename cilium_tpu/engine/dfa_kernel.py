"""Batched DFA byte-scan — the L7 automaton kernel.

The TPU replacement for the reference's per-request regex scans
(SURVEY.md §3.4: "per-request × per-rule scan is exactly what the batched
automaton pass replaces"). Design notes:

* The scan is a ``lax.scan`` over byte positions with a ``[batch]``
  state carry; each step is one gather from the flattened transition
  table — sequential in L (string length) but embarrassingly parallel in
  the batch and bank dimensions, which is where the throughput comes
  from (flows ≫ bytes).
* Transition tables are byte-class compressed ``[S, K]`` int32; padding
  bytes are masked with ``where`` so bucketed/padded strings need no
  sentinel symbol.
* Banks are vmapped: ``[n_banks, S, K]`` tables, one shared input batch.
  Banks are also the EP (expert-parallel) shard unit
  (``cilium_tpu.parallel``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def dfa_scan(
    trans: jax.Array,       # [S, K] int32
    byteclass: jax.Array,   # [256] int32
    start: jax.Array,       # scalar int32
    data: jax.Array,        # [B, L] uint8/int32 padded byte strings
    lengths: jax.Array,     # [B] int32
) -> jax.Array:
    """Run the DFA over each row of ``data``; returns final states [B]."""
    B, L = data.shape
    K = trans.shape[1]
    trans_flat = trans.reshape(-1)          # [S*K]
    cls = byteclass[data.astype(jnp.int32)]  # [B, L]

    def step(states, inputs):
        c_t, t = inputs
        nxt = trans_flat[states * K + c_t]
        states = jnp.where(t < lengths, nxt, states)
        return states, None

    init = jnp.full((B,), start, dtype=jnp.int32)
    ts = jnp.arange(L, dtype=jnp.int32)
    final, _ = lax.scan(step, init, (cls.T, ts))
    return final


def dfa_scan_banked(
    trans: jax.Array,       # [NB, S, K] int32
    byteclass: jax.Array,   # [NB, 256] int32
    start: jax.Array,       # [NB] int32
    accept: jax.Array,      # [NB, S, W] uint32
    data: jax.Array,        # [B, L]
    lengths: jax.Array,     # [B]
) -> jax.Array:
    """All banks over one batch → accept words ``[B, NB, W]`` uint32."""
    finals = jax.vmap(
        lambda tr, bc, st: dfa_scan(tr, bc, st, data, lengths)
    )(trans, byteclass, start)              # [NB, B]
    words = jax.vmap(lambda acc, fs: acc[fs])(accept, finals)  # [NB, B, W]
    return jnp.transpose(words, (1, 0, 2))  # [B, NB, W]


def match_bits(words: jax.Array) -> jax.Array:
    """Flatten ``[B, NB, W]`` accept words to ``[B, NB*W]`` — the global
    lane space used by rule bitmap masks (dfa.BankedDFA.stacked lane_of)."""
    B = words.shape[0]
    return words.reshape(B, -1)


def any_lane_match(words: jax.Array, mask: jax.Array) -> jax.Array:
    """``words [B, NW]`` uint32 vs ``mask [NW]`` (or broadcastable):
    True where any masked lane bit is set."""
    return jnp.any((words & mask) != 0, axis=-1)
