"""Batched DFA byte-scan — the dense-gather L7 automaton kernel.

The TPU replacement for the reference's per-request regex scans
(SURVEY.md §3.4: "per-request × per-rule scan is exactly what the batched
automaton pass replaces"). Design notes:

* The scan is a ``lax.scan`` over byte positions with a ``[batch]``
  state carry; each step is one gather from the flattened transition
  table — sequential in L (string length) but embarrassingly parallel in
  the batch and bank dimensions, which is where the throughput comes
  from (flows ≫ bytes).
* Transition tables are byte-class compressed ``[S, K]`` int32; padding
  bytes are masked with ``where`` so bucketed/padded strings need no
  sentinel symbol.
* Banks are vmapped: ``[n_banks, S, K]`` tables, one shared input batch.
  Banks are also the EP (expert-parallel) shard unit
  (``cilium_tpu.parallel``).
* This is the ``dfa-dense`` arm of the megakernel's per-bank-shape
  autotuner (``engine/megakernel.py``); the ``nfa-bitset``
  rules-as-lanes arm lives in ``engine/nfa_kernel.py``.

Implementation choice is a TRACE-STATIC argument: callers resolve it
once on the host (``resolve_impl()`` reads the env; the engine does it
at staging) and thread it through — nothing here reads the
environment or probes the backend under trace.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def resolve_impl(env=None) -> str:
    """HOST-side step-implementation resolution — call once at
    engine/bank staging and thread the result as a static argument
    (never under trace: flipping the env between traces would
    otherwise be an invisible recompile lever).

    Honest TPU numbers (measured in a clean process with distinct
    host-staged input buffers and zero device→host readbacks — earlier
    "gather is 45M/s" numbers were an artifact of benchmark processes
    poisoned by readbacks, see docs/PLATFORM.md):

    * "gather" — one transition-table lookup per (flow, byte, bank);
      XLA lowers it well on this TPU: ~150G lookups/s at banked-scan
      shapes. Algorithmically minimal work — the default everywhere.
    * "pallas" — engine/pallas_dfa.py MXU matmul step: data-oblivious
      (RE2-style input-independent timing) but pays K×S MACs per
      lookup; needs ≤128 states/bank. Kept as an option for
      constant-time-guarantee deployments.
    * "onehot" — the matmul formulation in plain XLA (any state
      count); portable reference implementation.
    """
    import os

    env = os.environ if env is None else env
    pick = env.get("CILIUM_TPU_DFA_IMPL", "")
    if pick in ("gather", "onehot", "pallas"):
        return pick
    return "gather"


def dfa_scan(
    trans: jax.Array,       # [S, K] int32
    byteclass: jax.Array,   # [256] int32
    start: jax.Array,       # scalar int32
    data: jax.Array,        # [B, L] uint8/int32 padded byte strings
    lengths: jax.Array,     # [B] int32
    impl: Optional[str] = None,
) -> jax.Array:
    """Run the DFA over each row of ``data``; returns final states [B].

    ``impl``: "gather" (one gather per step; the default) or "onehot"
    (two f32 matmuls per step — exact for state ids < 2^24,
    MXU-friendly). A trace-static choice; None means "gather".
    """
    impl = impl or "gather"
    if impl == "pallas":
        impl = "gather"  # single-bank path: pallas handled in banked entry
    if impl not in ("gather", "onehot"):
        raise ValueError(f"unknown dfa impl {impl!r}")
    B, L = data.shape
    S, K = trans.shape
    cls = byteclass[data.astype(jnp.int32)]  # [B, L]

    if impl == "gather":
        trans_flat = trans.reshape(-1)      # [S*K]

        def step(states, inputs):
            c_t, t = inputs
            nxt = trans_flat[states * K + c_t]
            states = jnp.where(t < lengths, nxt, states)
            return states, None
    else:
        trans_f32 = trans.astype(jnp.float32)

        def step(states, inputs):
            c_t, t = inputs
            oh_s = jax.nn.one_hot(states, S, dtype=jnp.float32)   # [B,S]
            # HIGHEST: TPU matmuls default to bf16 accumulation, which
            # rounds state ids > 256 — transitions must be exact f32
            rows = jnp.matmul(oh_s, trans_f32,
                              precision=lax.Precision.HIGHEST)    # [B,K]
            oh_c = jax.nn.one_hot(c_t, K, dtype=jnp.float32)      # [B,K]
            nxt = jnp.sum(rows * oh_c, axis=1).astype(jnp.int32)
            states = jnp.where(t < lengths, nxt, states)
            return states, None

    init = jnp.full((B,), start, dtype=jnp.int32)
    ts = jnp.arange(L, dtype=jnp.int32)
    final, _ = lax.scan(step, init, (cls.T, ts))
    return final


def _accept_rows(accept: jax.Array, finals: jax.Array,
                 impl: str) -> jax.Array:
    """accept [S, W] uint32, finals [B] → [B, W] uint32."""
    if impl == "gather":
        return accept[finals]
    # one-hot matmul, exact via byte-planes (each plane value ≤ 255 is
    # exact even in bf16, and each one-hot row has a single nonzero
    # product — but use HIGHEST anyway for uniform guarantees)
    S, W = accept.shape
    oh = jax.nn.one_hot(finals, S, dtype=jnp.float32)         # [B, S]
    out = jnp.zeros((finals.shape[0], W), dtype=jnp.uint32)
    for shift in (0, 8, 16, 24):
        plane = ((accept >> shift) & jnp.uint32(0xFF)).astype(jnp.float32)
        vals = jnp.matmul(oh, plane,
                          precision=lax.Precision.HIGHEST
                          ).astype(jnp.uint32)                 # [B, W]
        out = out | (vals << shift)
    return out


def dfa_finals_banked(
    trans: jax.Array,       # [NB, S, K] int32
    byteclass: jax.Array,   # [NB, 256] int32
    start: jax.Array,       # [NB] int32
    data: jax.Array,        # [B, L]
    lengths: jax.Array,     # [B]
    impl: Optional[str] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Final DFA states for every (bank, flow) → [NB, B] int32; the
    accept-table reads layer on top (``dfa_scan_banked``)."""
    impl = impl or "gather"
    if impl == "pallas":
        from cilium_tpu.engine import pallas_dfa

        # ctlint: disable=recompile-hazard  # impl pick per bank shape is a trace-time static choice, by design
        if pallas_dfa.pallas_supported(trans.shape):
            if interpret is None:
                interpret = pallas_dfa.use_interpret()
            return pallas_dfa.dfa_finals_pallas(
                trans, byteclass, start, data, lengths,
                interpret=interpret)
        # pallas is an explicit opt-in for its input-independent
        # timing guarantee; degrading to the data-dependent gather
        # must be loud, not silent
        import warnings

        warnings.warn(
            f"CILIUM_TPU_DFA_IMPL=pallas requested but a bank has "
            f"{trans.shape[1]} states (limit "
            f"{pallas_dfa.MAX_STATES}); falling back to the "
            f"data-dependent 'gather' path — the constant-time "
            f"guarantee does NOT hold. Compile with a smaller "
            f"bank_size to keep it.",
            RuntimeWarning, stacklevel=2)
        impl = "gather"
    return jax.vmap(
        lambda tr, bc, st: dfa_scan(tr, bc, st, data, lengths, impl=impl)
    )(trans, byteclass, start)              # [NB, B]


def dfa_scan_banked(
    trans: jax.Array,       # [NB, S, K] int32
    byteclass: jax.Array,   # [NB, 256] int32
    start: jax.Array,       # [NB] int32
    accept: jax.Array,      # [NB, S, W] uint32
    data: jax.Array,        # [B, L]
    lengths: jax.Array,     # [B]
    impl: Optional[str] = None,
    interpret: Optional[bool] = None,
    extra_accept: Optional[jax.Array] = None,
):
    """All banks over one batch → accept words ``[B, NB, W]`` uint32.

    ``impl``/``interpret`` are trace-static (resolve on the host via
    :func:`resolve_impl`; None = "gather" / backend-probe fallback for
    direct callers). ``extra_accept`` ([NB, S, Wg]) reads a second
    accept plane off the same final states — the megakernel's
    group-accept tables (one extra gather, no second scan) — and makes
    the return a ``(words, extra_words)`` tuple."""
    impl = impl or "gather"
    finals = dfa_finals_banked(trans, byteclass, start, data, lengths,
                               impl=impl, interpret=interpret)
    word_impl = "gather" if impl == "pallas" else impl

    def extract(acc):
        words = jax.vmap(
            lambda a, fs: _accept_rows(a, fs, word_impl)
        )(acc, finals)                      # [NB, B, W]
        return jnp.transpose(words, (1, 0, 2))  # [B, NB, W]

    words = extract(accept)
    if extra_accept is None:
        return words
    return words, extract(extra_accept)


def match_bits(words: jax.Array) -> jax.Array:
    """Flatten ``[B, NB, W]`` accept words to ``[B, NB*W]`` — the global
    lane space used by rule bitmap masks (dfa.BankedDFA.stacked lane_of)."""
    B = words.shape[0]
    return words.reshape(B, -1)


def any_lane_match(words: jax.Array, mask: jax.Array) -> jax.Array:
    """``words [B, NW]`` uint32 vs ``mask [NW]`` (or broadcastable):
    True where any masked lane bit is set."""
    return jnp.any((words & mask) != 0, axis=-1)
