"""Batched L3/L4 policy-map lookup.

TPU analog of ``bpf/lib/policy.h ·policy_can_access*`` (SURVEY.md §3.3):
the per-packet hash-map lookups become a batched binary search over a
sorted key tensor with wildcard probes and priority resolution.

Key layout (3×int32 words, lexicographically sorted):

* ``w0`` — policy TEMPLATE id (round 5): identities whose resolved
  entry sets are identical share one template's rows, and the lookup
  indirects identity → template through ``enf_ids``/``tmpl_ids``
  (``subject`` in :func:`mapstate_lookup`) before probing. This is
  ``pkg/policy/distillery.go``'s dedup applied to the packed tensor —
  at clustermesh scale it shrinks the table ~16× (10M → 625k rows).
  Hand-built tables (tests) may still key w0 by raw endpoint identity
  and pass ``tmpl_ids=None``.
* ``w1`` — peer identity (src for ingress, dst for egress); 0 = wildcard
* ``w2`` — ``(direction << 29) | (proto << 21) | (port_plen << 16) |
  dport``; proto 0 = wildcard. ``port_plen`` keys port RANGES as
  aligned prefix blocks (reference ``mapstate.go`` port-range mask
  entries): plen 16 = exact port, 0 = all ports, 1..15 = a
  ``2^(16-plen)``-wide block based at ``dport``.

Verdict precedence (mapstate.py's golden model, vectorized):

* probe every wildcard combination of (peer, port-prefix, proto) —
  the port dimension probes each DISTINCT prefix length present in
  the packed table (``port_plens``, sorted descending; {16, 0} when
  no ranges exist → the classic 8 probes);
* **deny wins** if any covering entry is deny (cilium: deny precedence
  regardless of breadth);
* else the most-specific covering allow wins (specificity = peer > port
  prefix-length > proto, the datapath's probe order);
* else default: allow iff the direction is unenforced for this endpoint.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.core.flow import TrafficDirection
from cilium_tpu.engine.search import lower_bound
from cilium_tpu.policy.mapstate import MapState


@dataclasses.dataclass
class PackedMapState:
    """Sorted key/entry tensors (host-side numpy; loader stages to device)."""

    key_w0: np.ndarray      # [N] int32 policy TEMPLATE id (see tmpl_ids)
    key_w1: np.ndarray      # [N] int32 peer identity
    key_w2: np.ndarray      # [N] int32 dir|proto|plen|port
    is_deny: np.ndarray     # [N] bool
    ruleset_id: np.ndarray  # [N] int32, -1 = no L7 restriction
    auth: np.ndarray        # [N] bool — entry demands mutual auth
    # per-endpoint-identity enforcement: sorted ids + 3-bit flags
    enf_ids: np.ndarray     # [M] int32 sorted endpoint identities
    enf_flags: np.ndarray   # [M, 3] bool (ingress, egress, audit)
    #: [M] int32 policy-template id per enf_ids row: identities whose
    #: resolved entry sets are IDENTICAL share one template's table
    #: rows — the distillery dedup (pkg/policy/distillery.go) applied
    #: to the packed tensor. At clustermesh scale (10k identities ×
    #: ~1k entries) this shrinks the key table ~100× (10M → distinct
    #: templates), which is the difference between the probe's binary
    #: search walking a 40 MB random-access table and a cache-resident
    #: one. None = w0 holds raw endpoint identities (legacy direct
    #: construction in tests).
    tmpl_ids: np.ndarray = None
    #: [P] int32 DISTINCT port prefix lengths present, sorted
    #: descending (always contains 16 and 0) — the lookup's port
    #: probe set; its SHAPE is static per compile, so a ruleset that
    #: introduces a new prefix length recompiles once
    port_plens: np.ndarray = None

    def __post_init__(self):
        if self.port_plens is None:
            self.port_plens = np.array([16, 0], dtype=np.int32)

    @property
    def n_entries(self) -> int:
        return len(self.key_w0)


def _pack_w2(direction: int, proto: int, dport: int,
             plen: int = 16) -> int:
    return (direction << 29) | (proto << 21) | (plen << 16) | dport


def pack_mapstate(
    per_identity: Dict[int, MapState],
    ruleset_of_entry=None,
) -> PackedMapState:
    """Pack per-endpoint-identity MapStates into one sorted table.

    ``ruleset_of_entry(ep_id, key, entry) -> int`` maps an entry's L7
    rule set to a global ruleset id (assigned by the loader); None or a
    return of -1 means no L7 restriction.
    """
    rows: List[Tuple[int, int, int, bool, int, bool]] = []
    enf: List[Tuple[int, bool, bool, bool]] = []
    tmpl_of_identity: List[int] = []
    tmpl_index: Dict[tuple, int] = {}
    plens = {16, 0}
    #: per-call memo keyed by the MapState's OBJECT identity: at fleet
    #: scale many identities share one resolved state object, and
    #: rebuilding its row tuple per identity is the packing hot spot.
    #: The per_identity dict keeps every ms alive for the call, so
    #: id() keys cannot be recycled mid-pack.
    ms_memo: Dict[int, tuple] = {}
    for ep_id, ms in sorted(per_identity.items()):
        enf.append((ep_id, ms.ingress_enforced, ms.egress_enforced,
                    getattr(ms, "audit", False)))
        cached = ms_memo.get(id(ms))
        if cached is None:
            ep_rows = []
            ep_plens = set()
            for key, entry in ms.entries.items():
                rid = -1
                if ruleset_of_entry is not None and entry.is_redirect:
                    rid = ruleset_of_entry(ep_id, key, entry)
                plen = getattr(key, "port_plen", None)
                if plen is None:
                    plen = 0 if key.dport == 0 else 16
                ep_plens.add(plen)
                ep_rows.append((
                    key.identity,
                    _pack_w2(key.direction, key.proto, key.dport, plen),
                    entry.is_deny,
                    rid,
                    getattr(entry, "auth_required", False),
                ))
            cached = ms_memo[id(ms)] = (tuple(sorted(ep_rows)),
                                        frozenset(ep_plens))
        fp, ep_plens = cached
        plens |= ep_plens
        # distillery dedup: identities with identical verdict-relevant
        # entry sets share one TEMPLATE; the table stores each template
        # once and the lookup indirects identity → template. rid is
        # content-keyed by the caller (ruleset_of dedups rule-id
        # sets), so shared entries share rulesets too.
        tmpl = tmpl_index.get(fp)
        if tmpl is None:
            tmpl = tmpl_index[fp] = len(tmpl_index)
            for r in fp:
                rows.append((tmpl,) + r)
        tmpl_of_identity.append(tmpl)
    if not rows:
        # sentinel row that can never match (template ids are >= 0)
        rows.append((-1, -1, -1, False, -1, False))
    arr = np.array([r[:3] for r in rows], dtype=np.int64)
    order = np.lexsort((arr[:, 2], arr[:, 1], arr[:, 0]))
    arr = arr[order]
    deny = np.array([rows[i][3] for i in order], dtype=bool)
    rid = np.array([rows[i][4] for i in order], dtype=np.int32)
    auth = np.array([rows[i][5] for i in order], dtype=bool)
    if not enf:
        enf.append((-1, False, False, False))
        tmpl_of_identity.append(-1)
    # tmpl_ids must stay aligned with the SORTED enf table
    enf_order = sorted(range(len(enf)), key=lambda i: enf[i])
    enf = [enf[i] for i in enf_order]
    tmpl_of_identity = [tmpl_of_identity[i] for i in enf_order]
    return PackedMapState(
        key_w0=arr[:, 0].astype(np.int32),
        key_w1=arr[:, 1].astype(np.int32),
        key_w2=arr[:, 2].astype(np.int32),
        is_deny=deny,
        ruleset_id=rid,
        auth=auth,
        enf_ids=np.array([e[0] for e in enf], dtype=np.int32),
        enf_flags=np.array([[e[1], e[2], e[3]] for e in enf],
                           dtype=bool),
        port_plens=np.array(sorted(plens, reverse=True),
                            dtype=np.int32),
        tmpl_ids=np.array(tmpl_of_identity, dtype=np.int32),
    )


def _lower_bound3(
    k0: jax.Array, k1: jax.Array, k2: jax.Array,
    p0: jax.Array, p1: jax.Array, p2: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Lower bound over 3-word sorted keys (shared engine/search.py)."""
    return lower_bound((k0, k1, k2), (p0, p1, p2))


#: match_spec value reported for an explicit deny verdict (above the
#: maximum allow specificity 34+32+1=67)
DENY_SPEC = 68


def mapstate_lookup(
    key_w0: jax.Array, key_w1: jax.Array, key_w2: jax.Array,
    is_deny: jax.Array, ruleset_id: jax.Array,
    enf_ids: jax.Array, enf_flags: jax.Array,
    ep_ids: jax.Array,      # [B] endpoint identity (policy owner)
    peer_ids: jax.Array,    # [B]
    dports: jax.Array,      # [B]
    protos: jax.Array,      # [B]
    directions: jax.Array,  # [B]
    auth: jax.Array = None,  # [N] bool entry auth flags (optional)
    port_plens: jax.Array = None,  # [P] int32 desc (default [16, 0])
    tmpl_ids: jax.Array = None,  # [M] int32 identity→template (see
    #                              PackedMapState.tmpl_ids); None = w0
    #                              holds raw endpoint identities
) -> Dict[str, jax.Array]:
    """Batched verdict lookup. Returns dict with:
    ``allowed`` [B] bool (L3/L4 verdict, pre-L7),
    ``denied`` [B] bool (explicit deny hit),
    ``redirect`` [B] bool (L7 evaluation required),
    ``ruleset`` [B] int32 (winning entry's ruleset id, -1 if none),
    ``match_spec`` [B] int32 (specificity of winning entry per
    MapStateKey.specificity, -1 default, DENY_SPEC on deny),
    ``auth_required`` [B] bool (winning allow demands mutual auth),
    ``audit`` [B] bool (the owning endpoint is in per-endpoint
    policy-audit mode — enf_flags column 2).
    """
    from cilium_tpu.policy.mapstate import ICMP_TYPE_BIT

    if port_plens is None:
        port_plens = jnp.array([16, 0], dtype=jnp.int32)
    B = ep_ids.shape[0]
    P = port_plens.shape[0]
    n_probes = 2 * P * 2
    # probe grid, descending specificity: peer (desc) → port prefix
    # length (desc; port_plens is sorted desc at pack time) → proto
    # (desc). Probe COUNT is static (shape of port_plens).
    peer_sel = jnp.repeat(jnp.array([1, 0], dtype=jnp.int32), P * 2)
    plen = jnp.tile(jnp.repeat(port_plens, 2), 2)       # [n_probes]
    proto_sel = jnp.tile(jnp.array([1, 0], dtype=jnp.int32), 2 * P)
    pmask = jnp.where(plen == 0, 0,
                      (0xFFFF << (16 - plen)) & 0xFFFF)  # [n_probes]
    specs = peer_sel * 34 + plen * 2 + proto_sel         # [n_probes]

    # ICMP key encoding lives HERE, beside the probes, so every caller
    # (and the hypothesis differential suite, which calls this
    # directly) matches the golden MapState.lookup: the type gets the
    # marker bit in the port slot (type 0 must never read as the port
    # wildcard — policy/mapstate.py effective_dport)
    is_icmp = (protos == 1) | (protos == 58)
    dports = jnp.where(is_icmp, dports | ICMP_TYPE_BIT, dports)

    # identity → enforcement row (reused below) and, with the
    # distillery dedup, identity → policy TEMPLATE: probes search the
    # deduped table by template id. An unknown identity maps to -1,
    # which matches no table row (template ids are >= 0) — identical
    # to the pre-dedup behavior where an absent identity's w0 found
    # nothing.
    eidx = jnp.clip(jnp.searchsorted(enf_ids, ep_ids), 0,
                    enf_ids.shape[0] - 1)
    eknown = enf_ids[eidx] == ep_ids
    if tmpl_ids is None:
        subject = ep_ids
    else:
        subject = jnp.where(eknown, tmpl_ids[eidx], -1)

    p0 = jnp.broadcast_to(subject[:, None], (B, n_probes))
    p1 = peer_ids[:, None] * peer_sel[None, :]
    w2 = (
        (directions[:, None] << 29)
        | ((protos[:, None] * proto_sel[None, :]) << 21)
        | (plen[None, :] << 16)
        | (dports[:, None] & pmask[None, :])
    )
    idx, found = _lower_bound3(
        key_w0, key_w1, key_w2,
        p0.reshape(-1), p1.reshape(-1), w2.reshape(-1),
    )
    idx = idx.reshape(B, n_probes)
    found = found.reshape(B, n_probes)
    # proto-ANY port entries are an L4 construct: an ICMP flow whose
    # marked type collides with the port value must not match them
    # (mirrors MapStateKey.covers); the (port-specific, proto-wildcard)
    # probes are masked for ICMP flows
    l4_only_probe = (plen > 0) & (proto_sel == 0)
    found = found & ~(is_icmp[:, None] & l4_only_probe[None, :])

    deny_hit = found & is_deny[idx]
    denied = jnp.any(deny_hit, axis=1)

    allow_hit = found & ~is_deny[idx]
    # probes are ordered descending specificity → first allow hit wins
    any_allow = jnp.any(allow_hit, axis=1)
    first_allow = jnp.argmax(allow_hit, axis=1)      # [B]
    win_idx = jnp.take_along_axis(idx, first_allow[:, None], axis=1)[:, 0]
    ruleset = jnp.where(any_allow, ruleset_id[win_idx], -1)
    match_spec = jnp.where(
        denied, DENY_SPEC, jnp.where(any_allow, specs[first_allow], -1)
    )

    # default enforcement per endpoint identity (eidx/eknown above)
    enforced = jnp.where(
        directions == int(TrafficDirection.INGRESS),
        enf_flags[eidx, 0], enf_flags[eidx, 1],
    ) & eknown

    allowed = ~denied & (any_allow | ~enforced)
    redirect = allowed & any_allow & (ruleset >= 0)
    if auth is None:
        auth_required = jnp.zeros_like(allowed)
    else:
        auth_required = allowed & any_allow & auth[win_idx]
    return {
        "allowed": allowed,
        "denied": denied,
        "redirect": redirect,
        "ruleset": ruleset,
        "match_spec": match_spec,
        "auth_required": auth_required,
        "audit": enf_flags[eidx, 2] & eknown,
    }
