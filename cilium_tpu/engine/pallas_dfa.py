"""Pallas TPU kernel for the banked DFA byte-scan.

Why a hand-written kernel: the MXU matmul step's cost is shape-only —
it gives the RE2-style linear-time, *input-independent* timing
guarantee the reference relies on (SURVEY.md §2.2), which matters for
deployments where verdict latency must not leak rule or payload
structure. It is NOT the throughput path: honest clean-process timing
(docs/PLATFORM.md) shows XLA's native gather sustains ~150G
transitions/s at banked-scan shapes, so "gather" is the default and
this kernel is opt-in via CILIUM_TPU_DFA_IMPL=pallas.

Layout: flows ride the lane axis (TILE=1024 lanes), the state axis
rides sublanes, and each step is

    rows = transᵀ @ onehot(state)        # [KP,SP] @ [SP,TILE] on MXU
    next = Σ_k rows ⊙ onehot(class)      # VPU column select
    s_oh = (iota_S == next)              # back to one-hot

One-hot columns have a single nonzero and all table values are state
ids < 128, so bf16 operands with f32 accumulation are exact.

Padding-byte handling uses an *identity class*: the table gets one extra
class column with trans[s, K] = s, and the host-side byte→class lookup
writes class K wherever t ≥ length — the scan then carries the state
through padding with no mask input and no `where` in the hot loop.

Constraints: per-bank state count S ≤ 128 (one MXU tile; compile with a
smaller ``bank_size`` to stay under — the banked entry point falls back
to the XLA gather path otherwise). The byte→class lookup stays an XLA
gather outside the kernel: its table is 256 entries (bounded entropy),
so it has no adversarial regime.

Grid: (bank, batch-tile); the transition tile stays resident in VMEM for
the whole L-step byte loop of its grid cell.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 1024         # flows per grid cell (lane axis: 8×128 tiles)
MAX_STATES = 128    # one MXU tile; also keeps bf16 state ids exact


def _scan_kernel(start_ref, cls_ref, trans_ref, out_ref):
    """One (bank, batch-tile) cell: scan L bytes, emit final states.

    start_ref [NB]          int32  bank start states (scalar prefetch)
    cls_ref   [1, L, TILE]  int32  byte classes (class KP-pad = identity)
    trans_ref [1, KP, SP]   bf16   transposed transition table
    out_ref   [1, 1, 8, 128] int32 final states
    """
    _, L, _ = cls_ref.shape
    _, KP, SP = trans_ref.shape
    trans_t = trans_ref[0]                                   # [KP, SP]
    start = start_ref[pl.program_id(0)]
    iota_k = lax.broadcasted_iota(jnp.int32, (KP, TILE), 0)
    iota_s = lax.broadcasted_iota(jnp.int32, (SP, TILE), 0)
    s_oh = (iota_s == start).astype(jnp.bfloat16)            # [SP, TILE]

    def step(t, s_oh):
        c = cls_ref[0, t]                                    # [TILE]
        oh_c = (iota_k == c[None, :]).astype(jnp.float32)    # [KP, TILE]
        rows = jnp.dot(trans_t, s_oh,
                       preferred_element_type=jnp.float32)   # [KP, TILE]
        nxt = jnp.sum(rows * oh_c, axis=0).astype(jnp.int32)
        return (iota_s == nxt[None, :]).astype(jnp.bfloat16)

    s_oh = lax.fori_loop(0, L, step, s_oh)
    final = jnp.sum(s_oh.astype(jnp.float32) * iota_s.astype(jnp.float32),
                    axis=0).astype(jnp.int32)                # [TILE]
    out_ref[0, 0] = final.reshape(8, 128)


@functools.partial(jax.jit, static_argnames=("interpret",))
def dfa_finals_pallas(
    trans: jax.Array,       # [NB, S, K] int32, S ≤ 128
    byteclass: jax.Array,   # [NB, 256] int32
    start: jax.Array,       # [NB] int32
    data: jax.Array,        # [B, L] uint8/int32
    lengths: jax.Array,     # [B] int32
    interpret: bool = False,
) -> jax.Array:
    """Final DFA states for every (bank, flow) → [NB, B] int32."""
    NB, S, K = trans.shape
    if S > MAX_STATES:
        raise ValueError(
            f"pallas DFA kernel needs ≤{MAX_STATES} states/bank, got {S} "
            f"(compile with a smaller bank_size)")
    B, L = data.shape
    SP = MAX_STATES
    KEEP = K                                   # identity-class index
    KP = max(8, -(-(K + 1) // 8) * 8)
    NT = max(1, -(-B // TILE))
    BP = NT * TILE

    trans_p = jnp.zeros((NB, SP, KP), jnp.int32).at[:, :S, :K].set(trans)
    ident = jnp.broadcast_to(jnp.arange(SP, dtype=jnp.int32)[None, :],
                             (NB, SP))
    trans_p = trans_p.at[:, :, KEEP].set(ident)
    trans_t = jnp.transpose(trans_p, (0, 2, 1)).astype(jnp.bfloat16)

    # byte → class outside the kernel (256-entry table, bounded entropy);
    # padding positions get the identity class
    cls = jax.vmap(lambda bc: bc[data.astype(jnp.int32)])(byteclass)
    pad_pos = jnp.arange(L, dtype=jnp.int32)[None, :] >= lengths[:, None]
    cls = jnp.where(pad_pos[None, :, :], KEEP, cls)          # [NB, B, L]
    cls = jnp.transpose(cls, (0, 2, 1))                      # [NB, L, B]
    cls = jnp.pad(cls, ((0, 0), (0, 0), (0, BP - B)),
                  constant_values=KEEP)

    finals = pl.pallas_call(
        _scan_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(NB, NT),
            in_specs=[
                pl.BlockSpec((1, L, TILE), lambda b, t, _s: (b, 0, t)),
                pl.BlockSpec((1, KP, SP), lambda b, t, _s: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, 8, 128),
                                   lambda b, t, _s: (b, t, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((NB, NT, 8, 128), jnp.int32),
        interpret=interpret,
    )(start.astype(jnp.int32), cls, trans_t)
    return finals.reshape(NB, BP)[:, :B]


def pallas_supported(trans_shape) -> bool:
    """True when the banked table fits the kernel's state budget."""
    return trans_shape[1] <= MAX_STATES


def use_interpret() -> bool:
    """Interpret mode off-TPU (CPU tests exercise kernel semantics)."""
    return jax.default_backend() != "tpu"
