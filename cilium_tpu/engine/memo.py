"""Device-resident verdict memo + the policy generation epoch.

The capture/stream replay paths dedup their featurized rows hard
(``unique_rows`` is 1991 of 200k on the http_1000rules capture —
≥99% of replay traffic re-derives a verdict the engine already
computed). This module carries that observation to its conclusion:
verdict OUTPUTS for the deduped row universe live on device, keyed by
featurized-row hash, and steady-state replay is one tiny id H2D plus
one on-device gather — the "carry compact reusable state instead of
recomputing" pattern of the Portable-O(1)-caching paper (PAPERS.md),
applied to verdicts instead of KV state.

Correctness contract: a policy swap can NEVER serve a stale verdict.
Every ``Loader`` revision commit — regenerate, rollback, and
``restore_warm`` alike — bumps the process-global
:data:`POLICY_GENERATION`; every memo read first checks its fill-time
generation (and auth-table signature) and drops itself on mismatch,
counting the invalidation. The memo is an accelerator over the shared
:func:`~cilium_tpu.engine.verdict.verdict_step_capture`, so memoized
and recomputed verdicts are bit-equal by construction (pinned by the
differential suites in tests/test_ingest_columnar.py).

jax is imported lazily (method bodies only): the oracle-only loader
path imports this module for the generation epoch and must stay
jax-free.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import threading
from typing import Dict, Optional

import numpy as np

from cilium_tpu.runtime.metrics import (
    METRICS,
    VERDICT_MEMO_HITS,
    VERDICT_MEMO_INVALIDATIONS,
    VERDICT_MEMO_MISSES,
)


#: L7 family names of the bank-reference granularity: which rule
#: family a memoized row's verdict actually READ. Rows carry their
#: family in the l7_types column; "l4" rows read no L7 banks at all
#: and move only on a structural (MapState) change. Codes 5..7 are
#: the protocol-frontend families (policy/compiler/frontends/) — the
#: featurize paths normalize frontend records' l7-type lane to these,
#: so a cassandra-bank swap refills ONLY cassandra rows. The
#: frontend-registry ctlint rule pins this map against the frontend
#: registry's declared families.
FAMILY_OF_L7TYPE = {0: "l4", 1: "http", 2: "kafka", 3: "dns",
                    4: "generic", 5: "cassandra", 6: "memcache",
                    7: "r2d2"}

#: wildcard family: the identity's STRUCTURAL state (MapState keys,
#: deny/auth/wildcard bits, enforcement flags) changed — every row of
#: the identity may verdict differently regardless of family
FAMILY_ALL = "*"

#: wildcard port of the bank-reference granularity: the family's
#: rules changed on a port-range/wildcard entry (or the producer
#: couldn't split by port) — every port's rows of that (identity,
#: family) may verdict differently
PORT_ALL = -1


@dataclasses.dataclass(frozen=True)
class PolicyDelta:
    """What one committed revision actually changed — the bank-scoped
    half of the staleness contract. ``full=True`` (the conservative
    default: rollbacks, gate flips, audit/secret/engine-config
    changes, quarantined builds) means "assume everything moved";
    otherwise only rows whose enforcement identity is in
    ``changed_identities`` can verdict differently (every rule change
    alters its identities' MapState fingerprints, so identity
    granularity subsumes rule/bank granularity for memo OUTPUTS), and
    ``changed_banks`` names the hot-swapped content-addressed bank
    keys for observability and the per-bank epoch map.

    ``changed_identity_families`` narrows to family granularity: each
    ``(identity, family)`` pair says which rule family of that
    identity actually changed, where family is one of
    :data:`FAMILY_OF_L7TYPE`'s values or :data:`FAMILY_ALL` (the
    identity's structural MapState moved — all rows affected). A row
    only re-verdicts when its identity changed AND its own L7 family
    read a swapped bank: an HTTP-path bank swap no longer refills the
    identity's DNS/kafka memo rows, because their verdicts never read
    the path automaton (every ``l7_ok`` contribution is gated on
    ``l7t == family``). Empty = unknown (producer predates family
    fingerprints) — consumers fall back to identity granularity.

    ``changed_identity_family_ports`` is the final step to TRUE
    bank-reference granularity (the PR-8 "remaining headroom",
    finished by ISSUE 13): ``(identity, family, dport)`` triples name
    the exact MapState ENTRY whose rule set moved — and a memo row
    reads a bank only through its entry's ruleset, so a 5k-CNP delta
    touching one port's rules refills exactly the rows whose
    ``(identity, l7-family, dport)`` routes through the changed
    banks. ``dport`` :data:`PORT_ALL` marks a port-range/wildcard
    entry (every port of the family affected). The triple set covers
    exactly the ``changed_identity_families`` pairs when non-empty;
    empty = no port information — consumers fall back to family
    granularity."""

    full: bool = True
    reason: str = "policy-swap"
    changed_identities: frozenset = frozenset()
    changed_banks: frozenset = frozenset()
    #: frozenset of (identity, family) pairs; family FAMILY_ALL marks
    #: a structural change. Covers exactly ``changed_identities`` when
    #: non-empty (the loader produces both from the same fingerprints)
    changed_identity_families: frozenset = frozenset()
    #: frozenset of (identity, family, dport) triples — the
    #: bank-reference granularity; dport PORT_ALL marks a range/
    #: wildcard entry. Covers exactly the family pairs when non-empty.
    changed_identity_family_ports: frozenset = frozenset()

    @classmethod
    def none(cls) -> "PolicyDelta":
        """A commit that changed nothing semantic (same artifact key:
        a no-op regenerate, a warm restore of the serving policy) —
        consumers keep memos, buffers, and staged tables."""
        return cls(full=False, reason="no-change")

    @classmethod
    def banks(cls, identities, banks, reason: str = "bank-swap",
              identity_families=(), identity_family_ports=()
              ) -> "PolicyDelta":
        return cls(full=False, reason=reason,
                   changed_identities=frozenset(identities),
                   changed_banks=frozenset(banks),
                   changed_identity_families=frozenset(
                       identity_families),
                   changed_identity_family_ports=frozenset(
                       identity_family_ports))

    @property
    def is_noop(self) -> bool:
        return (not self.full and not self.changed_identities
                and not self.changed_banks)

    def affects(self, identity: int, l7_type: int,
                dport: Optional[int] = None) -> bool:
        """May a memoized row with this (enforcement identity, L7
        type, destination port) verdict differently under this delta?
        The consumer-side face of the granularity ladder: full →
        identity → family → bank reference (port). ``dport=None`` =
        the caller has no port column — family granularity."""
        if self.full:
            return True
        if identity not in self.changed_identities:
            return False
        fams = self.changed_identity_families
        if not fams:
            return True          # identity-granular producer
        if (identity, FAMILY_ALL) in fams:
            return True
        family = FAMILY_OF_L7TYPE.get(int(l7_type))
        if family is None or (identity, family) not in fams:
            return False
        ports = self.changed_identity_family_ports
        if not ports or dport is None:
            return True          # family-granular producer/consumer
        return ((identity, family, PORT_ALL) in ports
                or (identity, family, int(dport)) in ports)

    def merge(self, other: "PolicyDelta") -> "PolicyDelta":
        if self.full or other.full:
            return PolicyDelta(full=True)
        if other.is_noop:
            return self
        if self.is_noop:
            return other
        # family narrowing only survives a merge when BOTH sides carry
        # it: a families-blind delta means "all families" for its
        # identities, and widening per-identity would lose the
        # invariant that the family set covers changed_identities
        if (self.changed_identity_families
                and other.changed_identity_families):
            fams = (self.changed_identity_families
                    | other.changed_identity_families)
        else:
            fams = frozenset()
        # ...and port narrowing likewise: both sides or neither (a
        # ports-blind delta means "all ports" for its family pairs)
        if fams and self.changed_identity_family_ports \
                and other.changed_identity_family_ports:
            ports = (self.changed_identity_family_ports
                     | other.changed_identity_family_ports)
        else:
            ports = frozenset()
        return PolicyDelta(
            full=False, reason=other.reason,
            changed_identities=(self.changed_identities
                                | other.changed_identities),
            changed_banks=self.changed_banks | other.changed_banks,
            changed_identity_families=fams,
            changed_identity_family_ports=ports)


def affected_row_ids(delta: "PolicyDelta", eps, l7_types,
                     dports=None) -> "np.ndarray":
    """Vectorized :meth:`PolicyDelta.affects` over aligned
    ``(enforcement identity, l7 type[, dport])`` columns → the
    affected row ids, int32. The shared consumer-side half of the
    bank-reference invalidation (CaptureReplay offline,
    IncrementalSession online, the verdict ring's shared session) —
    one implementation so the layers can't drift on what "row read
    the swapped bank" means. ``dports=None`` keeps family
    granularity (the pre-ISSUE-13 consumers)."""
    eps = np.asarray(eps, dtype=np.int64)
    l7s = np.asarray(l7_types, dtype=np.int64)
    if delta.full:
        return np.arange(len(eps), dtype=np.int32)
    if not delta.changed_identities:
        return np.zeros(0, dtype=np.int32)
    fams = delta.changed_identity_families
    ports = delta.changed_identity_family_ports
    if dports is not None:
        dps = np.asarray(dports, dtype=np.int64)
    else:
        dps = None
    mask = np.zeros(len(eps), dtype=bool)
    for ep in delta.changed_identities:
        sel = eps == ep
        if not sel.any():
            continue
        if not fams or (ep, FAMILY_ALL) in fams:
            mask |= sel        # identity-granular (or structural)
            continue
        for code, name in FAMILY_OF_L7TYPE.items():
            if (ep, name) not in fams:
                continue
            fam_sel = sel & (l7s == code)
            if not fam_sel.any():
                continue
            if ports and dps is not None \
                    and (ep, name, PORT_ALL) not in ports:
                # bank-reference narrowing: only rows whose entry
                # (port) routes through the changed rule set refill
                fam_ports = [p for (e, n, p) in ports
                             if e == ep and n == name]
                fam_sel = fam_sel & np.isin(dps, fam_ports)
            mask |= fam_sel
    return np.nonzero(mask)[0].astype(np.int32)


#: committed-revision deltas retained for lagging consumers; a session
#: further behind than this reads a conservative FULL delta
_DELTA_RING = 64


class _PolicyGeneration:
    """Process-global epoch of committed policy revisions. Monotone;
    bumped by ``Loader._commit`` (every backend: tpu / oracle / warm)
    AND by a rollback's restore — a reverted swap is still a serving-
    state change a memo must not read through.

    Each bump carries a :class:`PolicyDelta` (default: full). A
    bounded ring of recent deltas lets a consumer at epoch g ask
    "what changed since g?" and invalidate only the rows a bank-scoped
    commit touched; per-bank epochs record the generation at which a
    content-addressed bank key last entered/left the serving plan."""

    __slots__ = ("_lock", "_value", "_ring", "_bank_epochs",
                 "_last_full")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0
        self._ring: collections.deque = collections.deque(
            maxlen=_DELTA_RING)
        self._bank_epochs: Dict[str, int] = {}
        self._last_full = 0

    def bump(self, delta: Optional[PolicyDelta] = None) -> int:
        with self._lock:
            self._value += 1
            d = delta if delta is not None else PolicyDelta(full=True)
            self._ring.append((self._value, d))
            if d.full:
                self._last_full = self._value
            for k in d.changed_banks:
                self._bank_epochs[k] = self._value
            # the epoch map tracks retired keys too; keep it bounded
            if len(self._bank_epochs) > 65536:
                cut = sorted(self._bank_epochs.values())[
                    len(self._bank_epochs) // 2]
                self._bank_epochs = {
                    k: v for k, v in self._bank_epochs.items()
                    if v >= cut}
            return self._value

    @property
    def value(self) -> int:
        return self._value

    def bank_epoch(self, key: str) -> int:
        """Generation at which bank ``key`` last changed (0 = never
        seen). A full commit moves EVERY bank's effective epoch."""
        with self._lock:
            return max(self._bank_epochs.get(key, 0), self._last_full)

    def deltas_since(self, gen: int) -> PolicyDelta:
        """Merged delta of every commit after epoch ``gen``. Returns
        a no-op delta when ``gen`` is current, and a conservative FULL
        delta when the ring no longer covers the gap."""
        with self._lock:
            if gen >= self._value:
                return PolicyDelta.none()
            if not self._ring or self._ring[0][0] > gen + 1:
                return PolicyDelta(full=True)
            merged = PolicyDelta.none()
            for v, d in self._ring:
                if v > gen:
                    merged = merged.merge(d)
            return merged


POLICY_GENERATION = _PolicyGeneration()


def policy_generation() -> int:
    """The current policy epoch (see :class:`_PolicyGeneration`)."""
    return POLICY_GENERATION.value


def hash_rows(rows: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a-style u64 hash per row (over the int32
    columns) — THE row key of the dedup/memo machinery. Dedup by 1-D
    hash is ~10× cheaper than ``np.unique(rows, axis=0)``'s
    lexicographic row sort (0.77s → ~0.05s on the 200k×15 capture
    block); collisions are handled exactly by the callers, never
    assumed away. Shared by ``CaptureReplay`` (offline) and
    ``IncrementalSession`` (online) so the two dedup layers can't
    drift."""
    rows = np.ascontiguousarray(rows)
    with np.errstate(over="ignore"):
        h = np.full(len(rows), np.uint64(0xCBF29CE484222325))
        prime = np.uint64(0x100000001B3)
        for c in range(rows.shape[1]):
            h = (h ^ rows[:, c].astype(np.uint64)) * prime
    return h


def auth_signature(authed_pairs) -> Optional[str]:
    """Stable signature of the auth staging a verdict depends on:
    None / AUTH_UNENFORCED / a pairs table each produce a distinct
    value, so a memo filled under one auth view can never serve
    another."""
    from cilium_tpu.auth import AUTH_UNENFORCED

    if authed_pairs is AUTH_UNENFORCED:
        return "unenforced"
    if authed_pairs is None:
        return "none"
    a = np.ascontiguousarray(np.asarray(authed_pairs))
    return hashlib.sha1(a.tobytes()).hexdigest()


#: the reason-label values a memo drop can be counted under
#: (``cilium_tpu_verdict_memo_invalidations_total{reason=...}``) —
#: the canonical registry ctlint's ``obs-doc-parity`` reason-label
#: extension holds docs/OBSERVABILITY.md to
INVALIDATION_REASONS = ("policy-swap", "auth-change", "session-reset",
                        "bank-swap", "no-change")

#: column order of the packed [N, 10] int32 memo table — every output
#: lane of ``_verdict_core`` (bool lanes stored as 0/1). ``l7_match``
#: is the attribution lane: memoized verdicts keep their provenance,
#: so a memo-served row can still name the rule that produced it.
MEMO_COLS = ("verdict", "match_spec", "ruleset", "allowed",
             "l3l4_allowed", "redirect", "l7_ok", "l7_log",
             "auth_required", "l7_match")
_MEMO_INT = frozenset(("verdict", "match_spec", "ruleset", "l7_match"))


def memo_pack(out: Dict) -> "object":
    """Verdict-step output dict → one [N, 10] int32 block (traceable;
    fused into the fill step's jit). Outputs from a pre-attribution
    producer (no ``l7_match`` lane) pack -1 — "unattributed", the
    honest value."""
    import jax.numpy as jnp

    cols = []
    for c in MEMO_COLS:
        if c in out:
            cols.append(out[c].astype(jnp.int32))
        else:
            cols.append(jnp.full(out["verdict"].shape, -1, jnp.int32))
    return jnp.stack(cols, axis=1)


@functools.lru_cache(maxsize=1)
def _gather_step():
    """Jitted memo read: table [cap, 9] int32, idx [B] → output dict
    (bool lanes restored). One compile per (cap, B) shape bucket."""
    import jax
    import jax.numpy as jnp

    def gather(table, idx):
        cols = table[idx.astype(jnp.int32)]
        out = {}
        for i, name in enumerate(MEMO_COLS):
            v = cols[:, i]
            out[name] = v if name in _MEMO_INT else (v != 0)
        return out

    return jax.jit(gather)


@functools.lru_cache(maxsize=1)
def _update_step():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update(table, block, offset):
        return jax.lax.dynamic_update_slice(
            table, block.astype(jnp.int32), (offset, 0))

    return update


@functools.lru_cache(maxsize=1)
def _scatter_step():
    """Jitted scattered refill: rewrite the memo rows a bank-scoped
    policy commit touched, in place (duplicate indices write identical
    rows — padding by repetition is safe)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def scatter(table, idx, block):
        return table.at[idx.astype(jnp.int32)].set(
            block.astype(jnp.int32))

    return scatter


def _pow2(n: int, floor: int = 1) -> int:
    return max(floor, 1 << max(0, max(1, n) - 1).bit_length())


class VerdictMemo:
    """Device-resident verdict memo over one row universe.

    The OWNER (``CaptureReplay`` offline, ``IncrementalSession``
    online) assigns row ids by featurized-row hash (``hash_rows`` +
    exact-compare dedup); this class keeps the aligned device table of
    verdict outputs: slot i holds the packed outputs of row id i.
    ``fill`` appends outputs for new ids (one
    ``dynamic_update_slice``), ``gather`` serves a chunk's ids with
    one device gather, and ``valid_for`` enforces the staleness
    contract (policy generation + auth signature) — see the module
    docstring."""

    def __init__(self, device=None):
        self.device = device
        self._gen = policy_generation()
        self._auth_sig: Optional[str] = None
        self.table = None          # [cap, 10] int32 on device
        self.capacity = 0
        self.filled = 0            # row ids [0, filled) are memoized
        #: host-side per-slot CITED generation: the policy epoch each
        #: slot's outputs were computed under. A memo-served verdict
        #: cites its fill-time generation (the explanation-honesty
        #: contract: what you cite is what you computed under), which
        #: under bank-scoped deltas is legitimately older than the
        #: current epoch for untouched rows.
        self.gens: Optional[np.ndarray] = None
        #: lifetime counters (mirrors of the METRICS families)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    # -- validity ---------------------------------------------------------
    def valid_for(self, auth_sig: Optional[str]) -> bool:
        """True when the memo may serve under the current policy
        generation and this call's auth view; drops (and counts) the
        memo otherwise. A fresh/empty memo adopts the auth signature
        on its first fill instead of invalidating."""
        if self._gen != policy_generation():
            self.invalidate("policy-swap")
            return False
        if self.filled and auth_sig != self._auth_sig:
            self.invalidate("auth-change")
            return False
        return True

    def invalidate(self, reason: str) -> None:
        """Drop every memoized verdict (device table released) and
        re-adopt the current generation."""
        self.table = None
        self.capacity = 0
        self.filled = 0
        self.gens = None
        self._auth_sig = None
        self._gen = policy_generation()
        self.invalidations += 1
        METRICS.inc(VERDICT_MEMO_INVALIDATIONS,
                    labels={"reason": reason})

    def adopt(self) -> None:
        """Re-adopt the current policy generation WITHOUT dropping the
        table — the owner reconciled a bank-scoped :class:`PolicyDelta`
        itself (kept unaffected rows, queued affected ones for a
        scatter refill). Only owners that consumed
        ``POLICY_GENERATION.deltas_since`` may call this; anything
        else must go through :meth:`valid_for`'s full drop."""
        self._gen = policy_generation()

    def partial_invalidate(self, n_rows: int, reason: str) -> None:
        """Count a bank-scoped partial drop (``n_rows`` slots will be
        rewritten by :meth:`refill_scatter`). The table stays — that
        is the point."""
        if n_rows <= 0:
            return
        self.invalidations += 1
        METRICS.inc(VERDICT_MEMO_INVALIDATIONS,
                    labels={"reason": reason})

    def refill_scatter(self, idx, packed_block, n_real: int) -> None:
        """Rewrite the memo rows at ``idx`` with freshly-computed
        packed outputs (``idx``/``packed_block`` may be padded by
        repeating real ids — duplicates write identical rows). Counts
        ``n_real`` recomputed rows as misses, so the hit ratio stays
        honest under churn."""
        import jax
        import jax.numpy as jnp

        if self.table is None or n_real <= 0:
            return
        self.table = _scatter_step()(
            self.table, jax.device_put(idx, self.device),
            jnp.asarray(packed_block))
        if self.gens is not None:
            # refilled rows were COMPUTED now: they cite the current
            # generation; untouched rows keep citing theirs (the
            # hot-swap half of the explanation-honesty contract)
            real = np.asarray(idx[:n_real]).astype(np.int64)
            self.gens[real[real < len(self.gens)]] = \
                policy_generation()
        self.misses += n_real
        METRICS.inc(VERDICT_MEMO_MISSES, n_real)

    # -- write ------------------------------------------------------------
    def fill(self, packed_block, base: int, n_new: int,
             auth_sig: Optional[str]) -> None:
        """Append packed outputs for row ids ``[base, base + n_new)``
        (``packed_block`` may be padded longer; ids must be appended
        densely, in order). Counts the new ids as misses."""
        import jax
        import jax.numpy as jnp

        if n_new <= 0:
            return
        self._auth_sig = auth_sig
        block_rows = int(packed_block.shape[0])
        cap_needed = _pow2(max(base + block_rows, self.filled + n_new))
        if self.table is None or cap_needed > self.capacity:
            old = self.table
            self.capacity = cap_needed
            grown = jnp.zeros((self.capacity, len(MEMO_COLS)),
                              dtype=jnp.int32)
            if old is not None:
                grown = _update_step()(grown, old, 0)
            self.table = grown
        if self.gens is None or cap_needed > len(self.gens):
            grown_g = np.zeros(cap_needed, dtype=np.int64)
            if self.gens is not None:
                grown_g[:len(self.gens)] = self.gens
            self.gens = grown_g
        self.table = _update_step()(self.table,
                                    jnp.asarray(packed_block), base)
        self.gens[base:base + n_new] = policy_generation()
        self.filled = max(self.filled, base + n_new)
        self.misses += n_new
        METRICS.inc(VERDICT_MEMO_MISSES, n_new)

    def cited_gens(self, idx) -> "np.ndarray":
        """Host-side cited generation per served row id — the
        generation each slot's outputs were computed under (see
        :attr:`gens`). Unknown slots (pre-attribution memo, padding)
        cite -1."""
        ids = np.asarray(idx).astype(np.int64)
        if self.gens is None:
            return np.full(len(ids), -1, dtype=np.int64)
        out = np.full(len(ids), -1, dtype=np.int64)
        ok = (ids >= 0) & (ids < len(self.gens))
        out[ok] = self.gens[ids[ok]]
        return out

    # -- read -------------------------------------------------------------
    def gather(self, idx) -> Dict:
        """Serve one chunk of row ids from the device table → output
        dict (device arrays). Caller guarantees ``valid_for`` ran and
        every id is < ``filled``."""
        import jax

        out = _gather_step()(self.table,
                             jax.device_put(idx, self.device))
        n = int(len(idx))
        self.hits += n
        METRICS.inc(VERDICT_MEMO_HITS, n)
        return out
