"""Persistent device-resident verdict ring: the continuous-batching
engine face of the serving loop.

The pre-ring serving plane was request/response-shaped: MicroBatcher
formed batches host-side per request wave, every stream carried a
PRIVATE IncrementalSession, and every stream's bytes crossed the
socket/PCIe boundary even when the verdict memo already knew the
answer. The ring inverts all three:

* **One row universe for every admitted stream.** The ring owns one
  shared :class:`~cilium_tpu.engine.session.IncrementalSession` —
  string tables, unique-row table, and the device-resident verdict
  memo are RING-resident, not per-stream. Live traffic repeats its
  15-tuples across streams at least as hard as within one (identities
  × ports × L7 fields draw from small sets), so cross-stream dedup is
  strictly more memo-hits than per-stream dedup ever saw.
* **Continuous batching, one fused dispatch per pack.** Streams
  submit chunks into their leased slots; the pack cycle drains
  whatever slots have pending work and serves the CONCATENATED id
  vector through one ``serve_ids`` call — one fused megakernel
  dispatch for the delta rows plus one on-device memo gather for
  everything known, however many streams contributed. No per-wave
  host barrier: a slot that missed this pack rides the next.
* **Memo hits never cross the boundary.** ``encode_ids`` interns
  host-side; a row the ring has seen before ships 4 bytes of id
  instead of its featurized row block — the Libra selective-copy
  argument (PAPERS.md) applied at the H2D seam, with the saved bytes
  counted on ``cilium_tpu_serve_memo_bypass_bytes_total`` so the
  claim is a number, not an adjective.

Slot-resident session state survives policy hot-swaps through the
shared session's PR-8 delta path (``loader=``): a bank-scoped commit
refills only the memo rows whose identity+family read the swapped
bank; slots notice nothing.

Slot lifecycle (grant/TTL/expiry/admission) lives one layer up in
``runtime/serveloop.py`` — this module is the engine-side mechanism:
slots, packing, the fused dispatch, and the byte accounting.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu.engine.session import IncrementalSession
from cilium_tpu.runtime.metrics import (
    METRICS,
    SERVE_MEMO_BYPASS_BYTES,
    SERVE_PACK_RECORDS,
    SERVE_PACK_STREAMS,
)

#: hard bound on records one pack cycle may carry to the device —
#: chunks past it wait for the next cycle (pow2-padded shapes above
#: this would blow compile-shape variety and device memory, the same
#: bound the stream transport enforces per chunk)
PACK_MAX = 1 << 17


class RingSlot:
    """One leased stream's ring residency: pending (not yet packed)
    encoded chunks plus lifetime accounting. The slot holds ENCODED
    ids, never raw payloads — encoding happens at submit so the pack
    cycle is a concatenate, not a featurize loop."""

    __slots__ = ("slot_id", "stream_id", "pending", "records_in",
                 "records_out", "epoch")

    def __init__(self, slot_id: int):
        self.slot_id = slot_id
        self.stream_id: Optional[str] = None
        #: [(idx int32 array, completion callback or None), ...] —
        #: bounded by the serve loop's per-slot pending bound; the
        #: ring itself bounds the PACK, not the slot
        self.pending: List[Tuple[np.ndarray, object]] = []
        self.records_in = 0
        self.records_out = 0
        #: session reset epoch the pending ids were encoded under —
        #: a session reset orphans encoded ids, so stale pending work
        #: is re-encoded (see VerdictRing.submit/pack)
        self.epoch = 0


class RingFull(RuntimeError):
    """No free slot: the caller sheds the stream with an explicit
    reason instead of queueing it invisibly."""


class VerdictRing:
    """Fixed-capacity ring of stream slots over one shared
    incremental session. Thread-safe: the serve loop's pack thread
    and the per-connection submit paths interleave under one lock;
    the device dispatch itself runs outside it (jax dispatch is
    async, and two packs never run concurrently by construction —
    only the pack loop calls :meth:`pack`)."""

    def __init__(self, engine, capacity: int, loader=None,
                 widths: Optional[Dict[str, int]] = None,
                 memo: bool = True):
        self.capacity = max(1, int(capacity))
        self.session = IncrementalSession(engine, widths=widths,
                                          memo=memo, loader=loader)
        self._lock = threading.Lock()
        self._slots: Dict[int, RingSlot] = {}
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        #: slot ids with pending work, in submit order (bounded by
        #: capacity: a slot appears at most once)
        self._dirty: List[int] = []
        self._dirty_set: set = set()
        #: lifetime counters (the serve loop's bench/invariant face)
        self.packs = 0
        self.records_packed = 0
        self.bytes_saved = 0
        self.bytes_shipped = 0

    # -- slot lifecycle ---------------------------------------------------
    @property
    def occupancy(self) -> int:
        with self._lock:
            return len(self._slots)

    def acquire(self, stream_id: str) -> RingSlot:
        """Claim a free slot for ``stream_id``; raises
        :class:`RingFull` when the ring is at capacity — the caller
        sheds with reason ``ring-full``, never queues."""
        with self._lock:
            if not self._free:
                raise RingFull(
                    f"ring at capacity ({self.capacity} slots)")
            sid = self._free.pop()
            slot = self._slots.get(sid)
            if slot is None:
                slot = RingSlot(sid)
            slot.stream_id = stream_id
            slot.pending = []
            self._slots[sid] = slot
            return slot

    def release(self, slot: RingSlot) -> List[Tuple[np.ndarray, object]]:
        """Return a slot to the free list (lease expiry, stream end,
        drain). Pending unpacked chunks are DROPPED and returned —
        popped under the ring lock, so a chunk is resolved by EITHER
        the pack cycle (verdicts) or the releaser (error), never
        both."""
        with self._lock:
            dropped = slot.pending
            slot.pending = []
            slot.stream_id = None
            if slot.slot_id in self._slots:
                del self._slots[slot.slot_id]
                self._free.append(slot.slot_id)
            if slot.slot_id in self._dirty_set:
                self._dirty_set.discard(slot.slot_id)
                self._dirty = [s for s in self._dirty
                               if s != slot.slot_id]
            return dropped

    # -- submit -----------------------------------------------------------
    def submit(self, slot: RingSlot, rec, l7, offsets, blob, gen=None,
               done=None) -> int:
        """Encode one chunk into the slot's pending queue (host work
        only). ``done`` is an opaque completion token the pack cycle
        hands back with the chunk's verdicts. Returns the chunk's
        record count. Raises if the slot is not resident."""
        n = len(rec)
        with self._lock:
            if self._slots.get(slot.slot_id) is not slot:
                raise RuntimeError("slot is not ring-resident")
            # encode under the lock: the session's intern tables are
            # shared mutable state, and encode is the only writer
            # besides pack's dispatch (which never interns)
            idx, novel = self.session.encode_ids(rec, l7, offsets,
                                                 blob, gen)
            known = n - novel
            row_bytes = self.session.row_width * 4
            # selective-copy accounting: known rows ship a 4-byte id
            # instead of their featurized row block
            self.bytes_saved += known * max(0, row_bytes - 4)
            self.bytes_shipped += novel * row_bytes + n * 4
            if known:
                METRICS.inc(SERVE_MEMO_BYPASS_BYTES,
                            known * max(0, row_bytes - 4))
            slot.pending.append((idx, done))
            slot.records_in += n
            slot.epoch = self.session.resets
            if slot.slot_id not in self._dirty_set:
                self._dirty_set.add(slot.slot_id)
                self._dirty.append(slot.slot_id)
        return n

    # -- the pack cycle ---------------------------------------------------
    def pack(self, authed_pairs=None, max_records: int = PACK_MAX
             ) -> List[Tuple[RingSlot, int, object, object]]:
        """Drain pending chunks (submit order, up to ``max_records``)
        into ONE fused dispatch; returns ``[(slot, n, done, device
        verdict slice), ...]`` per packed chunk. Chunks whose ids
        predate a session reset are dropped with ``verdicts=None`` —
        the serve loop resubmits them (their payload is gone; the
        LOAD MODEL treats it as a retryable shed). Empty list when
        nothing was pending."""
        with self._lock:
            batch: List[Tuple[RingSlot, np.ndarray, object]] = []
            stale: List[Tuple[RingSlot, int, object]] = []
            total = 0
            epoch = self.session.resets
            taken_slots = 0
            while self._dirty and total < max_records:
                sid = self._dirty[0]
                slot = self._slots.get(sid)
                if slot is None or not slot.pending:
                    self._dirty.pop(0)
                    self._dirty_set.discard(sid)
                    continue
                idx, done = slot.pending[0]
                if slot.epoch != epoch:
                    # encoded before a session reset: the ids name
                    # rows that no longer exist
                    slot.pending.pop(0)
                    stale.append((slot, len(idx), done))
                    continue
                if total + len(idx) > max_records and batch:
                    break  # next cycle picks it up — no host barrier
                slot.pending.pop(0)
                batch.append((slot, idx, done))
                total += len(idx)
                if not slot.pending:
                    self._dirty.pop(0)
                    self._dirty_set.discard(sid)
                taken_slots += 1
            if not batch:
                return [(s, n, d, None) for s, n, d in stale]
            packed = np.concatenate([idx for _, idx, _ in batch])
        # dispatch OUTSIDE the lock: submits keep landing while the
        # fused step runs; only the pack loop calls pack(), so two
        # dispatches never race on the session's device tables
        try:
            verdicts = self.session.serve_ids(packed,
                                              authed_pairs=authed_pairs)
        except Exception:
            # dispatch failed (injected fault, sick device): put the
            # batch BACK at the slots' heads — the next cycle retries
            # it (transient faults recover), and no ticket is lost
            with self._lock:
                for slot, idx, done in reversed(batch):
                    slot.pending.insert(0, (idx, done))
                    if slot.slot_id not in self._dirty_set:
                        self._dirty_set.add(slot.slot_id)
                        self._dirty.insert(0, slot.slot_id)
            raise
        self.packs += 1
        self.records_packed += int(total)
        METRICS.observe(SERVE_PACK_RECORDS, float(total))
        METRICS.observe(SERVE_PACK_STREAMS,
                        float(len({s.slot_id for s, _, _ in batch})))
        out: List[Tuple[RingSlot, int, object, object]] = []
        base = 0
        for slot, idx, done in batch:
            n = len(idx)
            out.append((slot, n, done, verdicts[base:base + n]))
            slot.records_out += n
            base += n
        out.extend((s, n, d, None) for s, n, d in stale)
        return out

    def memo_stats(self) -> Dict[str, int]:
        m = self.session.memo
        if m is None:
            return {}
        return {"hits": m.hits, "misses": m.misses,
                "invalidations": m.invalidations}
