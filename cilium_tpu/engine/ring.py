"""Persistent device-resident verdict ring: the continuous-batching
engine face of the serving loop.

The pre-ring serving plane was request/response-shaped: MicroBatcher
formed batches host-side per request wave, every stream carried a
PRIVATE IncrementalSession, and every stream's bytes crossed the
socket/PCIe boundary even when the verdict memo already knew the
answer. The ring inverts all three:

* **One row universe for every admitted stream.** The ring owns one
  shared :class:`~cilium_tpu.engine.session.IncrementalSession` —
  string tables, unique-row table, and the device-resident verdict
  memo are RING-resident, not per-stream. Live traffic repeats its
  15-tuples across streams at least as hard as within one (identities
  × ports × L7 fields draw from small sets), so cross-stream dedup is
  strictly more memo-hits than per-stream dedup ever saw.
* **Continuous batching, one fused dispatch per pack.** Streams
  submit chunks into their leased slots; the pack cycle drains
  whatever slots have pending work and serves the CONCATENATED id
  vector through one ``serve_ids`` call — one fused megakernel
  dispatch for the delta rows plus one on-device memo gather for
  everything known, however many streams contributed. No per-wave
  host barrier: a slot that missed this pack rides the next.
* **Memo hits never cross the boundary.** ``encode_ids`` interns
  host-side; a row the ring has seen before ships 4 bytes of id
  instead of its featurized row block — the Libra selective-copy
  argument (PAPERS.md) applied at the H2D seam, with the saved bytes
  counted on ``cilium_tpu_serve_memo_bypass_bytes_total`` so the
  claim is a number, not an adjective.

Slot-resident session state survives policy hot-swaps through the
shared session's PR-8 delta path (``loader=``): a bank-scoped commit
refills only the memo rows whose identity+family read the swapped
bank; slots notice nothing.

Slot lifecycle (grant/TTL/expiry/admission) lives one layer up in
``runtime/serveloop.py`` — this module is the engine-side mechanism:
slots, packing, the fused dispatch, and the byte accounting.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from cilium_tpu.engine.session import IncrementalSession
from cilium_tpu.runtime.metrics import (
    METRICS,
    SERVE_MEMO_BYPASS_BYTES,
    SERVE_PACK_RECORDS,
    SERVE_PACK_STREAMS,
)

#: hard bound on records one pack cycle may carry to the device —
#: chunks past it wait for the next cycle (pow2-padded shapes above
#: this would blow compile-shape variety and device memory, the same
#: bound the stream transport enforces per chunk)
PACK_MAX = 1 << 17


class RingSlot:
    """One leased stream's ring residency: pending (not yet packed)
    encoded chunks plus lifetime accounting. The slot holds ENCODED
    ids, never raw payloads — encoding happens at submit so the pack
    cycle is a concatenate, not a featurize loop."""

    __slots__ = ("slot_id", "stream_id", "pending", "records_in",
                 "records_out")

    def __init__(self, slot_id: int):
        self.slot_id = slot_id
        self.stream_id: Optional[str] = None
        #: [(idx int32 array, completion token or None, session reset
        #: epoch the ids were encoded under), ...] — bounded by the
        #: serve loop's per-slot pending bound; the ring itself bounds
        #: the PACK, not the slot. The epoch rides EACH chunk: a
        #: session reset orphans the ids encoded before it, and a
        #: later submit into the same slot must not launder the stale
        #: chunk past pack()'s staleness check (see pack)
        self.pending: List[Tuple[np.ndarray, object, int]] = []
        self.records_in = 0
        self.records_out = 0


class RingFull(RuntimeError):
    """No free slot: the caller sheds the stream with an explicit
    reason instead of queueing it invisibly."""


class SlotNotResident(RuntimeError):
    """The slot was released (lease expiry/disconnect) between the
    caller's lease check and the ring operation — the serve loop
    translates this to its lease-lapsed contract."""


class VerdictRing:
    """Fixed-capacity ring of stream slots over one shared
    incremental session. Thread-safe: the serve loop's pack thread
    and the per-connection submit paths interleave under the ring
    lock; the shared session has its OWN lock (``_session_lock``)
    held by both the submit-side encode (which may reset the session
    or consume a policy delta) and the pack-side serve — the dispatch
    runs outside the RING lock so slot/lease operations stay
    responsive, but never concurrently with an encode that could
    mutate the tables it reads. Two packs never run concurrently by
    construction — only the pack loop calls :meth:`pack`."""

    def __init__(self, engine, capacity: int, loader=None,
                 widths: Optional[Dict[str, int]] = None,
                 memo: bool = True, provenance: bool = False,
                 host: str = ""):
        self.capacity = max(1, int(capacity))
        #: fleet replicas pass their identity so the ring's serve-
        #: plane families land as per-host series instead of N
        #: in-process rings colliding on one unlabeled series
        #: (ISSUE 17 satellite); standalone rings stay unlabeled
        self.host = str(host)
        self._host_labels = {"host": self.host} if self.host else None
        #: serve with the attribution/provenance lanes riding the
        #: dispatch (engine/attribution.ServedPack per chunk)
        self.provenance = bool(provenance)
        self.session = IncrementalSession(engine, widths=widths,
                                          memo=memo, loader=loader)
        self._lock = threading.Lock()
        #: serializes EVERY session touch: submit-side encode (which
        #: may reset the session or consume a policy delta, mutating
        #: tables/rows_dev/memo) against pack-side serve (which
        #: flushes and reads the same state outside the ring lock).
        #: Ordering: _lock may be held when taking _session_lock,
        #: never the reverse
        self._session_lock = threading.Lock()
        self._slots: Dict[int, RingSlot] = {}
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        #: slot ids with pending work, in submit order (bounded by
        #: capacity: a slot appears at most once)
        self._dirty: List[int] = []
        self._dirty_set: set = set()
        #: lifetime counters (the serve loop's bench/invariant face)
        self.packs = 0
        self.records_packed = 0
        self.bytes_saved = 0
        self.bytes_shipped = 0

    # -- slot lifecycle ---------------------------------------------------
    @property
    def occupancy(self) -> int:
        with self._lock:
            return len(self._slots)

    def acquire(self, stream_id: str) -> RingSlot:
        """Claim a free slot for ``stream_id``; raises
        :class:`RingFull` when the ring is at capacity — the caller
        sheds with reason ``ring-full``, never queues."""
        with self._lock:
            if not self._free:
                raise RingFull(
                    f"ring at capacity ({self.capacity} slots)")
            sid = self._free.pop()
            slot = self._slots.get(sid)
            if slot is None:
                slot = RingSlot(sid)
            slot.stream_id = stream_id
            slot.pending = []
            self._slots[sid] = slot
            return slot

    def release(self, slot: RingSlot
                ) -> List[Tuple[np.ndarray, object, int]]:
        """Return a slot to the free list (lease expiry, stream end,
        drain). Pending unpacked chunks are DROPPED and returned —
        popped under the ring lock, so a chunk is resolved by EITHER
        the pack cycle (verdicts) or the releaser (error), never
        both. Identity-checked: releasing a slot OBJECT whose id was
        already re-acquired by another stream must not evict the new
        resident."""
        with self._lock:
            dropped = slot.pending
            slot.pending = []
            slot.stream_id = None
            if self._slots.get(slot.slot_id) is slot:
                del self._slots[slot.slot_id]
                self._free.append(slot.slot_id)
                if slot.slot_id in self._dirty_set:
                    self._dirty_set.discard(slot.slot_id)
                    self._dirty = [s for s in self._dirty
                                   if s != slot.slot_id]
            return dropped

    # -- submit -----------------------------------------------------------
    def submit(self, slot: RingSlot, rec, l7, offsets, blob, gen=None,
               done=None) -> int:
        """Encode one chunk into the slot's pending queue (host work
        only). ``done`` is a completion token the pack cycle hands
        back with the chunk's verdicts; if non-None it must expose
        ``resolve(verdicts, error=...)`` so the ring can fail it
        directly when its slot vanishes mid-dispatch (see pack's
        failure handler). Returns the chunk's record count. Raises
        :class:`SlotNotResident` if the slot was released."""
        n = len(rec)
        with self._lock:
            if self._slots.get(slot.slot_id) is not slot:
                raise SlotNotResident("slot is not ring-resident")
            # encode under the session lock: encode may reset the
            # session or consume a policy delta, and pack's dispatch
            # reads the same tables outside the ring lock
            with self._session_lock:
                idx, novel = self.session.encode_ids(rec, l7, offsets,
                                                     blob, gen)
                epoch = self.session.resets
            known = n - novel
            row_bytes = self.session.row_width * 4
            # selective-copy accounting: known rows ship a 4-byte id
            # instead of their featurized row block
            self.bytes_saved += known * max(0, row_bytes - 4)
            self.bytes_shipped += novel * row_bytes + n * 4
            if known:
                METRICS.inc(SERVE_MEMO_BYPASS_BYTES,
                            known * max(0, row_bytes - 4),
                            labels=self._host_labels)
            # the epoch rides the chunk, not the slot: a later submit
            # after a reset must not launder THIS chunk's stale ids
            slot.pending.append((idx, done, epoch))
            slot.records_in += n
            if slot.slot_id not in self._dirty_set:
                self._dirty_set.add(slot.slot_id)
                self._dirty.append(slot.slot_id)
        return n

    # -- the pack cycle ---------------------------------------------------
    def pack(self, authed_pairs=None, max_records: int = PACK_MAX
             ) -> List[Tuple[RingSlot, int, object, object]]:
        """Drain pending chunks (submit order, up to ``max_records``)
        into ONE fused dispatch; returns ``[(slot, n, done, device
        verdict slice), ...]`` per packed chunk. Chunks whose ids
        predate a session reset are dropped with ``verdicts=None`` —
        the serve loop resubmits them (their payload is gone; the
        LOAD MODEL treats it as a retryable shed). Empty list when
        nothing was pending."""
        with self._lock:
            batch: List[Tuple[RingSlot, np.ndarray, object, int]] = []
            stale: List[Tuple[RingSlot, int, object]] = []
            total = 0
            epoch = self.session.resets
            taken_slots = 0
            while self._dirty and total < max_records:
                sid = self._dirty[0]
                slot = self._slots.get(sid)
                if slot is None or not slot.pending:
                    self._dirty.pop(0)
                    self._dirty_set.discard(sid)
                    continue
                idx, done, chunk_epoch = slot.pending[0]
                if chunk_epoch != epoch:
                    # encoded before a session reset: the ids name
                    # rows that no longer exist (the CHUNK's epoch —
                    # a post-reset submit into the same slot must not
                    # launder this one through)
                    slot.pending.pop(0)
                    stale.append((slot, len(idx), done))
                    continue
                if total + len(idx) > max_records and batch:
                    break  # next cycle picks it up — no host barrier
                slot.pending.pop(0)
                batch.append((slot, idx, done, chunk_epoch))
                total += len(idx)
                if not slot.pending:
                    self._dirty.pop(0)
                    self._dirty_set.discard(sid)
                taken_slots += 1
            if not batch:
                return [(s, n, d, None) for s, n, d in stale]
            packed = np.concatenate([idx for _, idx, _, _ in batch])
        # dispatch OUTSIDE the ring lock (slot/lease ops stay
        # responsive) but UNDER the session lock: a submit-side
        # encode may reset the session or consume a policy delta,
        # and must not mutate the tables a dispatch is reading
        orphans: List[Tuple[int, object]] = []
        try:
            with self._session_lock:
                if self.session.resets != epoch:
                    # a submit-triggered reset landed between the
                    # drain and the dispatch: the whole batch's ids
                    # are orphaned — same staleness as the per-chunk
                    # check, caught one window later
                    stale.extend((slot, len(idx), done)
                                 for slot, idx, done, _ in batch)
                    return [(s, n, d, None) for s, n, d in stale]
                verdicts = self.session.serve_ids(
                    packed, authed_pairs=authed_pairs,
                    provenance=self.provenance)
        except Exception:
            # dispatch failed (injected fault, sick device): put the
            # batch BACK at the slots' heads — the next cycle retries
            # it (transient faults recover), and no ticket is lost.
            # A slot released while the dispatch was in flight is no
            # longer ring-resident (acquire() builds a fresh RingSlot
            # for its id): its chunks cannot ride a retry, so their
            # tickets fail NOW instead of stranding the submitters
            with self._lock:
                for slot, idx, done, ce in reversed(batch):
                    if self._slots.get(slot.slot_id) is not slot:
                        orphans.append((len(idx), done))
                        continue
                    slot.pending.insert(0, (idx, done, ce))
                    if slot.slot_id not in self._dirty_set:
                        self._dirty_set.add(slot.slot_id)
                        self._dirty.insert(0, slot.slot_id)
            for _n, done in orphans:
                if done is not None:
                    done.resolve(None, error="slot-released")
            raise
        # pack/record totals race the submit path's occupancy reads
        # and a concurrent drain() pack cycle — bump under the ring
        # lock like every other book
        with self._lock:
            self.packs += 1
            self.records_packed += int(total)
        METRICS.observe(SERVE_PACK_RECORDS, float(total),
                        labels=self._host_labels)
        METRICS.observe(SERVE_PACK_STREAMS,
                        float(len({s.slot_id for s, _, _, _ in batch})),
                        labels=self._host_labels)
        if self.provenance and hasattr(verdicts, "slice"):
            # stamp the pack-cycle id on the bundle before slicing —
            # every chunk of this dispatch shares it
            verdicts.pack_cycle = self.packs
        out: List[Tuple[RingSlot, int, object, object]] = []
        base = 0
        for slot, idx, done, _ in batch:
            n = len(idx)
            piece = (verdicts.slice(base, n)
                     if hasattr(verdicts, "slice")
                     else verdicts[base:base + n])
            out.append((slot, n, done, piece))
            slot.records_out += n
            base += n
        out.extend((s, n, d, None) for s, n, d in stale)
        return out

    def memo_stats(self) -> Dict[str, int]:
        m = self.session.memo
        if m is None:
            return {}
        return {"hits": m.hits, "misses": m.misses,
                "invalidations": m.invalidations}

    # -- fleet handoff (runtime/fleetserve.py) ----------------------------
    def resident_keys(self) -> frozenset:
        """Content hashes of every session-resident unique row — the
        cross-host handoff manifest. Row hashes are content-addressed
        (``engine/memo.hash_rows`` over the featurized row bytes), so
        two hosts that interned the same 15-tuple/string row hold the
        same key even though their session row IDS differ. A lease
        migration ships this set (8 bytes/row) instead of featurized
        row blocks; the receiving host intersects with its own
        residency to learn which replayed rows need only a 4-byte id —
        the Libra selective-copy discipline applied at the HOST
        boundary instead of the H2D one."""
        with self._lock:
            with self._session_lock:
                return frozenset(self.session.row_ids.keys())

    def handoff_overlap(self, keys) -> Tuple[int, int]:
        """How much of a peer's residency manifest is already resident
        HERE: ``(rows, bytes_avoided)``. ``bytes_avoided`` is the
        featurized bytes a replay of those rows will not re-ship
        (row block minus the 4-byte id), mirroring the per-chunk
        ``bytes_saved`` accounting so the fleet lane's handoff numbers
        and the single-host memo-bypass numbers add up in the same
        currency."""
        with self._lock:
            with self._session_lock:
                mine = self.session.row_ids
                rows = sum(1 for k in keys if k in mine)
                row_bytes = self.session.row_width * 4
        return rows, rows * max(0, row_bytes - 4)
