"""Bitset-NFA byte-scan — the "rules-as-lanes" automaton arm.

The Hyperflex-style (PAPERS.md) alternative to the dense-gather DFA of
``engine/dfa_kernel.py``: instead of subset-constructing a union DFA
and gathering one next-state id per byte, the scan carries a **bitset
over the bank's NFA positions** (a position = one byte-consuming edge
of the Thompson NFA — the Glushkov position automaton derived through
the existing ``policy/compiler/nfa.py`` construction) and advances ALL
positions of ALL rules in the bank at once:

    D' = ((D · Follow) > 0) ⊙ ClassAccept[byte]

``Follow`` is the ε-closed position-to-position successor matrix —
**block-structured by rule** (positions of different patterns never
follow each other; the only cross-block rows are the shared start), so
the matmul is the block-diagonal one-hot advance of every rule lane in
one MXU pass. Acceptance is a second matmul: rule r matched iff D
intersects r's accept positions.

Why it earns a place next to the dense DFA:

* **No subset construction** — the position count is the pattern
  length sum, immune to the DFA state explosion that alternation-heavy
  banks hit (the ``max_dfa_states`` overflow/halving path). A bank
  whose DFA blows past the 128-state Pallas budget can still fit 128
  positions.
* **Data-oblivious** — two fixed-shape matmuls per byte, the RE2-style
  input-independent timing guarantee, on the MXU instead of the VPU.
* On CPU backends the matmul costs more than the gather; the
  per-bank-shape autotuner (``engine/megakernel.py``) measures both
  and records the pick, so the arm only serves where it wins.

Exactness: all matrices are 0/1; products accumulate counts ≤ P ≤ 128,
exact in f32 (``preferred_element_type`` pinned); thresholding ``> 0``
recovers the boolean OR. Verified bit-equal to ``dfa_scan_banked``
over the golden corpus and hypothesis-random banks
(tests/test_megakernel.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cilium_tpu.policy.compiler import regex_parser as rp
from cilium_tpu.policy.compiler.dfa import _byte_classes
from cilium_tpu.policy.compiler.nfa import build_nfa, eps_closure

#: position budget per bank: one MXU tile — the Pallas kernel's hard
#: cap, and the eligibility bound the autotuner respects on every
#: backend (past it the follow matmul outgrows its tile anyway)
MAX_POSITIONS = 128


@dataclasses.dataclass
class NFABank:
    """One bank's position-automaton tensors (host numpy)."""

    follow: np.ndarray      # [P, P] f32 0/1 ε-closed successor matrix
    acc_cls: np.ndarray     # [P, K] f32 0/1 class acceptance per position
    byteclass: np.ndarray   # [256] int32 byte → class
    start: np.ndarray       # [P] f32 0/1 positions live before byte 0
    accept: np.ndarray      # [P, W] uint32 rule bitmaps per position
    empty: np.ndarray       # [W] uint32 rules matching the empty string
    n_patterns: int

    @property
    def n_positions(self) -> int:
        return self.follow.shape[0]


def compile_nfa_bank(patterns: Sequence[str],
                     max_quantifier: int = 64,
                     case_insensitive: bool = False,
                     lanes: Optional[Sequence[int]] = None) -> NFABank:
    """Compile one bank of patterns into position-automaton tensors.

    ``lanes`` maps pattern i to its accept-bit lane (default i) so a
    registry-assembled bank keeps its served lane layout. An empty
    pattern list yields the 0-position dead bank (matches nothing) —
    the bitset-NFA face of a quarantined fail-closed bank."""
    lanes = list(lanes) if lanes is not None else list(range(len(patterns)))
    n_lanes = (max(lanes) + 1) if lanes else 1
    n_words = max(1, (max(n_lanes, 1) + 31) // 32)
    if not patterns:
        return NFABank(
            follow=np.zeros((0, 0), np.float32),
            acc_cls=np.zeros((0, 1), np.float32),
            byteclass=np.zeros(256, np.int32),
            start=np.zeros((0,), np.float32),
            accept=np.zeros((0, n_words), np.uint32),
            empty=np.zeros((n_words,), np.uint32),
            n_patterns=0)
    asts = [rp.parse(p, max_quantifier=max_quantifier,
                     case_insensitive=case_insensitive)
            for p in patterns]
    nfa = build_nfa(asts)
    byteclass, n_classes = _byte_classes(nfa)
    rep = [0] * n_classes
    for b in range(255, -1, -1):
        rep[int(byteclass[b])] = b
    # positions = byte-consuming edges, in deterministic state order
    edges = [(s, m, t) for s in range(nfa.n_states)
             for (m, t) in nfa.edges[s]]
    P = len(edges)
    acc_cls = np.zeros((P, max(1, n_classes)), np.float32)
    for i, (_, m, _) in enumerate(edges):
        for c in range(n_classes):
            if (m >> rep[c]) & 1:
                acc_cls[i, c] = 1.0
    closures = [eps_closure(nfa, [t]) for (_, _, t) in edges]
    start_cl = eps_closure(nfa, [nfa.start])
    follow = np.zeros((P, P), np.float32)
    for i in range(P):
        cl = closures[i]
        for j, (sj, _, _) in enumerate(edges):
            if sj in cl:
                follow[i, j] = 1.0
    start = np.array([1.0 if e[0] in start_cl else 0.0
                      for e in edges], np.float32)
    accept = np.zeros((P, n_words), np.uint32)
    empty = np.zeros((n_words,), np.uint32)

    def set_bit(words, idx):
        lane = lanes[idx]
        words[lane // 32] |= np.uint32(1 << (lane % 32))

    for i in range(P):
        for s in closures[i]:
            if nfa.accepts[s] >= 0:
                set_bit(accept[i], nfa.accepts[s])
    for s in start_cl:
        if nfa.accepts[s] >= 0:
            set_bit(empty, nfa.accepts[s])
    return NFABank(follow=follow, acc_cls=acc_cls, byteclass=byteclass,
                   start=start, accept=accept, empty=empty,
                   n_patterns=len(patterns))


def nfa_supported(banks: Sequence[NFABank]) -> bool:
    """True when every bank fits the position budget."""
    return all(b.n_positions <= MAX_POSITIONS for b in banks)


def stack_nfa_banks(banks: Sequence[NFABank],
                    extra_accept: Optional[Sequence[np.ndarray]] = None
                    ) -> Dict[str, np.ndarray]:
    """Pad + stack banks for the engine (mirror of
    ``BankedDFA.stacked``). ``extra_accept`` (optional, per bank
    ``[P, Wg]``) rides along as the group-accept plane of the factored
    resolve (``engine/megakernel.py``)."""
    NB = len(banks)
    Pm = max([b.n_positions for b in banks] + [1])
    Km = max([b.acc_cls.shape[1] for b in banks] + [1])
    Wm = max([b.accept.shape[1] for b in banks] + [1])
    out = {
        "nfa_follow": np.zeros((NB, Pm, Pm), np.float32),
        "nfa_acc_cls": np.zeros((NB, Pm, Km), np.float32),
        "nfa_byteclass": np.zeros((NB, 256), np.int32),
        "nfa_start": np.zeros((NB, Pm), np.float32),
        "nfa_accept": np.zeros((NB, Pm, Wm), np.uint32),
        "nfa_empty": np.zeros((NB, Wm), np.uint32),
    }
    for i, b in enumerate(banks):
        P, K, W = b.n_positions, b.acc_cls.shape[1], b.accept.shape[1]
        out["nfa_follow"][i, :P, :P] = b.follow
        out["nfa_acc_cls"][i, :P, :K] = b.acc_cls
        out["nfa_byteclass"][i] = b.byteclass
        out["nfa_start"][i, :P] = b.start
        out["nfa_accept"][i, :P, :W] = b.accept
        out["nfa_empty"][i, :W] = b.empty
    if extra_accept is not None:
        Wg = max([g.shape[1] for g in extra_accept] + [1])
        gacc = np.zeros((NB, Pm, Wg), np.uint32)
        for i, g in enumerate(extra_accept):
            gacc[i, :g.shape[0], :g.shape[1]] = g
        out["nfa_gaccept"] = gacc
    return out


def _or_reduce(masked: jax.Array, axis: int) -> jax.Array:
    return jax.lax.reduce(masked, jnp.uint32(0), jax.lax.bitwise_or,
                          (axis,))


def _accept_of(final: jax.Array, accept: jax.Array,
               empty: jax.Array, lengths: jax.Array) -> jax.Array:
    """Live-position bitset [B, P] → accept words [B, W]."""
    hit = final > 0
    words = _or_reduce(
        jnp.where(hit[:, :, None], accept[None, :, :], jnp.uint32(0)), 1)
    return jnp.where((lengths == 0)[:, None], empty[None, :], words)


def nfa_finals(follow: jax.Array, acc_cls: jax.Array,
               byteclass: jax.Array, start: jax.Array,
               data: jax.Array, lengths: jax.Array) -> jax.Array:
    """One bank's scan → final position bitset [B, P] (f32 0/1).

    The hot loop is two ops per byte: the follow matmul (MXU; counts
    are exact in f32) and the class-acceptance mask (a [B] gather into
    the [K, P] acceptance plane — on TPU the Pallas kernel
    (``engine/pallas_nfa.py``) replaces the gather with a one-hot
    matmul so the whole step is MXU-resident)."""
    B, L = data.shape
    cls = byteclass[data.astype(jnp.int32)]               # [B, L]
    acc_t = acc_cls.T                                     # [K, P]
    am0 = acc_t[cls[:, 0]] if L else jnp.zeros_like(start)[None]
    v0 = jnp.where((lengths > 0)[:, None],
                   start[None, :] * am0,
                   jnp.zeros((B, follow.shape[0]), jnp.float32))

    def step(v, inp):
        c_t, t = inp
        pre = jnp.matmul(v, follow,
                         preferred_element_type=jnp.float32)
        nxt = (pre > 0).astype(jnp.float32) * acc_t[c_t]
        return jnp.where((t < lengths)[:, None], nxt, v), None

    ts = jnp.arange(1, L, dtype=jnp.int32)
    final, _ = jax.lax.scan(step, v0, (cls.T[1:], ts))
    return final


def nfa_scan_banked(
    stacked: Dict[str, jax.Array],
    data: jax.Array,        # [B, L] uint8/int32
    lengths: jax.Array,     # [B]
    extra_accept: bool = False,
    use_pallas: bool = False,
    interpret: bool = False,
):
    """All banks over one batch → accept words ``[B, NB, W]`` uint32
    (+ group words ``[B, NB, Wg]`` when ``extra_accept`` and the stack
    carries a ``nfa_gaccept`` plane). Same contract as
    ``dfa_scan_banked`` — the two arms are interchangeable per bank
    shape, which is what the autotuner relies on."""
    if use_pallas:
        from cilium_tpu.engine.pallas_nfa import nfa_finals_pallas

        finals = nfa_finals_pallas(
            stacked["nfa_follow"], stacked["nfa_acc_cls"],
            stacked["nfa_byteclass"], stacked["nfa_start"],
            data, lengths, interpret=interpret)      # [NB, B, P]
    else:
        finals = jax.vmap(
            lambda f, a, bc, s: nfa_finals(f, a, bc, s, data, lengths)
        )(stacked["nfa_follow"], stacked["nfa_acc_cls"],
          stacked["nfa_byteclass"], stacked["nfa_start"])
    words = jax.vmap(
        lambda fin, acc, emp: _accept_of(fin, acc, emp, lengths)
    )(finals, stacked["nfa_accept"], stacked["nfa_empty"])
    words = jnp.transpose(words, (1, 0, 2))          # [B, NB, W]
    if not extra_accept:
        return words
    gacc = stacked["nfa_gaccept"]
    gwords = jax.vmap(
        lambda fin, acc: _accept_of(
            fin, acc, jnp.zeros((acc.shape[1],), jnp.uint32), lengths)
    )(finals, gacc)
    return words, jnp.transpose(gwords, (1, 0, 2))


def banks_from_dfa(banked, cfg, case_insensitive: bool = False
                   ) -> Optional[List[NFABank]]:
    """Rebuild each compiled DFA bank's pattern group as an NFA bank,
    preserving lane assignment (``pattern_bank``/``pattern_lane``).
    Returns None when any bank busts the position budget. Banks no
    current pattern references (stale quarantine covers) cannot be
    reconstructed faithfully — callers gate the arm on a
    quarantine-free build (``CompiledPolicy.bank_quarantined``)."""
    per_bank: Dict[int, List[Tuple[int, str]]] = {}
    for i, pat in enumerate(banked.patterns):
        per_bank.setdefault(int(banked.pattern_bank[i]), []).append(
            (int(banked.pattern_lane[i]), pat))
    # cheap pre-flight: positions ≥ literal occurrences, so a bank
    # whose pattern text alone dwarfs the budget can be rejected
    # before paying parse + closure work
    for members in per_bank.values():
        if sum(len(p) for _, p in members) > 16 * MAX_POSITIONS:
            return None
    banks: List[NFABank] = []
    for b in range(banked.n_banks):
        members = sorted(per_bank.get(b, ()))
        bank = compile_nfa_bank(
            [p for _, p in members],
            max_quantifier=cfg.max_quantifier,
            case_insensitive=case_insensitive,
            lanes=[lane for lane, _ in members])
        if bank.n_positions > MAX_POSITIONS:
            return None
        banks.append(bank)
    return banks
