"""Agent REST API over a Unix socket + client.

Reference: cilium's go-swagger REST API served on the agent's Unix
socket (``api/v1/openapi.yaml`` → generated server, ``pkg/client``
consumer — SURVEY.md §2.4); ``cilium-dbg`` drives it. We serve plain
HTTP/1.1 + JSON on an ``AF_UNIX`` socket with the same resource
shapes:

  GET    /v1/healthz        agent liveness + subsystem summary
  GET    /v1/config         daemon config (read)
  PATCH  /v1/config         mutate runtime-mutable fields (feature gate)
  GET    /v1/endpoint       list endpoints
  GET    /v1/endpoint/{id}  one endpoint
  PUT    /v1/endpoint/{id}  create/update (CNI ADD analog)
  DELETE /v1/endpoint/{id}  remove (CNI DEL analog)
  GET    /v1/policy         rules + revision
  PUT    /v1/policy         add CNP (YAML text or JSON body)
  DELETE /v1/policy         delete by labels (JSON body: {"labels": [...]})
  GET    /v1/identity       allocated identities
  GET    /v1/ip             ipcache dump
  GET    /v1/fqdn/cache     DNS cache dump
  GET    /v1/service        load-balancer services
  GET    /v1/metrics        Prometheus text exposition
  GET    /v1/explain        verdict provenance for ?trace_id= — the
                            recorded (rule, bank, generation), each
                            re-resolved through the CPU oracle
  GET    /v1/canary         shadow/canary rollout status: the staged
                            generation, the verdict-diff ledger, and
                            the commit/refuse decision surface
  GET    /v1/trace          flight-recorder spans (runtime/tracing.py);
                            ?trace_id= filters, ?limit= bounds,
                            ?format=chrome → Chrome trace-event JSON
  GET    /v1/debuginfo      full status dict

The verdict/proxylib data path stays on the binary verdict-service
socket (runtime/service.py) — control plane and data plane sockets are
separate, as in the reference (REST vs monitor/accesslog sockets).
"""

from __future__ import annotations

import http.client
import http.server
import json
import os
import socket
import socketserver
import threading
import urllib.parse
from typing import Dict, Optional, Tuple

from cilium_tpu.runtime import admission
from cilium_tpu.runtime.metrics import METRICS
from cilium_tpu.runtime.unixsock import unlink_if_stale

#: config fields PATCHable at runtime (the reference's runtime-mutable
#: DaemonConfig subset; everything else requires an agent restart)
_MUTABLE_CONFIG = ("enable_tpu_offload",)

#: control-class resources: the ops an operator needs DURING an
#: overload (health, config, policy mutation, drain, auth, the scrape
#: surface) — admitted with reserved headroom above the data-class
#: in-flight bound, so they never shed behind bulk reads
_CONTROL_PATHS = ("/v1/healthz", "/v1/config", "/v1/policy",
                  "/v1/drain", "/v1/auth", "/v1/metrics")


class _UnixHTTPServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class _Handler(http.server.BaseHTTPRequestHandler):
    # BaseHTTPRequestHandler expects TCP peers; over AF_UNIX the peer
    # address is a bare string — normalize so logging never crashes
    def address_string(self) -> str:  # noqa: D102
        return "unix"

    def log_message(self, fmt, *args):  # quiet; metrics cover access
        METRICS.inc("cilium_tpu_api_requests_total", 1)

    server_version = "cilium-tpu-api/1.0"
    agent = None  # set by APIServer

    # -- helpers ----------------------------------------------------------
    def _send(self, code: int, body, content_type="application/json"):
        data = (body if isinstance(body, bytes)
                else json.dumps(body, indent=2, default=str).encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(n) if n else b""

    def _route(self) -> Tuple[str, Dict[str, str]]:
        parsed = urllib.parse.urlparse(self.path)
        query = dict(urllib.parse.parse_qsl(parsed.query))
        return parsed.path.rstrip("/"), query

    def _ep_id(self, path: str) -> Optional[int]:
        try:
            return int(path.rsplit("/", 1)[1])
        except ValueError:
            return None

    # -- admission --------------------------------------------------------
    @staticmethod
    def _klass(path: str) -> str:
        for prefix in _CONTROL_PATHS:
            if path == prefix or path.startswith(prefix + "/"):
                return admission.CLASS_CONTROL
        return admission.CLASS_DATA

    def _admit(self) -> bool:
        """Bounded in-flight admission for REST ops: sheds with an
        explicit 503 (``shed: true``) instead of piling handler
        threads. Control paths get reserved headroom. A client-carried
        ``X-Cilium-Deadline-Ms`` that is already non-positive sheds
        immediately — the caller has given up."""
        slots = getattr(self.server, "slots", None)
        if slots is None:
            self._held_slot = False
            return True
        path, _ = self._route()
        klass = self._klass(path)
        deadline_ms = self.headers.get("X-Cilium-Deadline-Ms")
        if deadline_ms is not None:
            try:
                if float(deadline_ms) <= 0.0:
                    admission.count_shed("api", klass,
                                         admission.SHED_DEADLINE)
                    self._held_slot = False
                    self._send(503, {"error": "shed: deadline",
                                     "shed": True,
                                     "reason": admission.SHED_DEADLINE})
                    return False
            except ValueError:
                pass  # unparsable header: ignore, admit normally
        ok, reason = slots.acquire(klass)
        if not ok:
            self._held_slot = False
            self._send(503, {"error": f"shed: {reason}", "shed": True,
                             "reason": reason})
            return False
        self._held_slot = True
        return True

    def _release(self) -> None:
        if getattr(self, "_held_slot", False):
            self.server.slots.release()
            self._held_slot = False

    # -- methods ----------------------------------------------------------
    def do_GET(self):  # noqa: N802
        if not self._admit():
            return
        try:
            self._do_GET()
        finally:
            self._release()

    def _do_GET(self):
        agent = self.agent
        path, query = self._route()
        try:
            if path == "/v1/healthz":
                return self._send(200, {
                    "status": "ok",
                    "endpoints": len(list(agent.endpoint_manager.endpoints())),
                    "policy_revision": agent.repo.revision,
                    "engine_revision": agent.loader.revision,
                    "nodes": agent.health.summary()
                    if hasattr(agent.health, "summary") else {},
                })
            if path == "/v1/config":
                import dataclasses

                cfg = dataclasses.asdict(agent.config)
                return self._send(200, {"config": cfg,
                                        "mutable": list(_MUTABLE_CONFIG)})
            if path == "/v1/endpoint":
                return self._send(200, [
                    ep.to_json() for ep in agent.endpoint_manager.endpoints()
                ])
            if path.startswith("/v1/endpoint/"):
                ep_id = self._ep_id(path)
                if ep_id is None:
                    return self._send(400, {"error": "endpoint id must be "
                                            "an integer"})
                ep = agent.endpoint_manager.get(ep_id)
                if ep is None:
                    return self._send(404, {"error": "endpoint not found"})
                return self._send(200, ep.to_json())
            if path == "/v1/policy":
                return self._send(200, {
                    "rules": [
                        {"labels": list(r.labels),
                         "description": r.description}
                        for r in agent.repo.rules()
                    ],
                    "revision": agent.repo.revision,
                })
            if path == "/v1/identity":
                out = []
                for nid in agent.allocator.identities():
                    labels = agent.allocator.lookup(nid)
                    out.append({"id": int(nid),
                                "labels": sorted(map(str, labels))
                                if labels else []})
                return self._send(200, out)
            if path == "/v1/auth":
                return self._send(200, [
                    {"src_identity": s, "dst_identity": d,
                     "expires": exp}
                    for (s, d), exp in sorted(agent.auth.pairs().items())
                ])
            if path == "/v1/ip":
                return self._send(200, agent.ipcache.dump())
            if path == "/v1/fqdn/cache":
                return self._send(200, json.loads(agent.dns_cache.to_json()))
            if path == "/v1/service":
                return self._send(200, [
                    {"frontend": s.frontend.name,
                     "type": s.svc_type.name,
                     "backends": [b.name for b in s.backends],
                     "affinity": s.affinity}
                    for s in agent.services.list()
                ])
            if path == "/v1/selectors":
                # `cilium-dbg policy selectors` analog: live selector →
                # identity resolution state
                return self._send(200, agent.selector_cache.dump())
            if path == "/v1/proxy":
                # redirect table (`cilium-dbg status --all-redirects`
                # analog): live (l7proto, direction) → proxy port
                return self._send(200, agent.proxy_manager.dump())
            if path == "/v1/metrics":
                # Config.enable_metrics gates the scrape surface (the
                # reference's --enable-metrics): counters still count
                # internally, the exposition endpoint just declines
                if not getattr(agent.config, "enable_metrics", True):
                    return self._send(
                        404, b'{"error": "metrics disabled"}')
                return self._send(200, METRICS.expose().encode(),
                                  content_type="text/plain; version=0.0.4")
            if path == "/v1/explain":
                # verdict provenance for one trace id, re-resolved
                # through the CPU oracle (runtime/explain.py)
                from cilium_tpu.runtime.explain import resolve_explain

                tid = query.get("trace_id") or ""
                if not tid:
                    return self._send(400, {"error": "explain needs "
                                            "?trace_id="})
                # an agent fronting a serving fleet router-forwards
                # the query to whichever replica recorded the trace
                # (runtime/fleetserve.py — the store travels with the
                # host, so the answer survives handoffs and rejoins)
                fleet = getattr(agent, "fleet", None)
                if fleet is not None:
                    return self._send(200, fleet.explain(tid))
                return self._send(200,
                                  resolve_explain(agent.loader, tid))
            if path == "/v1/canary":
                # shadow/canary rollout status (runtime/canary.py):
                # the verdict-diff ledger for the staged generation.
                # The controller usually rides on the serve loop; an
                # agent without one still reports the loader's staged
                # revision so operators can see a canary is parked.
                ctrl = getattr(agent, "canary", None)
                if ctrl is None:
                    loop = getattr(agent, "serve_loop", None)
                    ctrl = getattr(loop, "canary", None) \
                        if loop is not None else None
                if ctrl is not None:
                    return self._send(200, ctrl.report())
                return self._send(200, {
                    "state": "idle",
                    "staged_revision": agent.loader.canary_revision,
                    "serving_revision": agent.loader.revision,
                })
            if path == "/v1/trace":
                from cilium_tpu.runtime.tracing import TRACER

                tid = query.get("trace_id") or None
                if query.get("format") == "chrome":
                    return self._send(200,
                                      TRACER.chrome_trace(trace_id=tid))
                try:
                    limit = int(query.get("limit", 0)) or None
                except ValueError:
                    return self._send(400, {"error": "limit must be "
                                            "an integer"})
                # a trace-id query against a fleet (or ?stitch=1)
                # returns the STITCHED cross-host timeline — spans
                # merged by id, ordered by (causal epoch, ts), host-
                # attributed (runtime/fleetserve.py handoff stitching)
                fleet = getattr(agent, "fleet", None)
                if tid and (fleet is not None or query.get("stitch")):
                    stitched = (fleet.trace(tid) if fleet is not None
                                else TRACER.stitch(tid))
                    if limit:
                        stitched["records"] = \
                            stitched["records"][:limit]
                    return self._send(200, stitched)
                return self._send(200, {
                    "enabled": TRACER.enabled,
                    "sample_rate": TRACER.sample_rate,
                    "dropped": TRACER.dropped,
                    "trace_ids": TRACER.trace_ids(),
                    "spans": TRACER.dump(trace_id=tid, limit=limit),
                })
            if path == "/v1/flows":
                # continuous Hubble flow export: per-host aggregated
                # (identity, identity, verdict, rule, bank,
                # generation) counts — router-merged with host
                # attribution when fronting a fleet
                try:
                    limit = int(query.get("limit", 0)) or None
                except ValueError:
                    return self._send(400, {"error": "limit must be "
                                            "an integer"})
                fleet = getattr(agent, "fleet", None)
                if fleet is not None:
                    return self._send(200, fleet.flows(limit=limit))
                loop = getattr(agent, "serve_loop", None)
                if loop is not None and \
                        getattr(loop, "flows", None) is not None:
                    return self._send(200,
                                      loop.flows.snapshot(limit=limit))
                from cilium_tpu.hubble.flowagg import merge_snapshots

                return self._send(200, merge_snapshots(()))
            if path == "/v1/debuginfo":
                return self._send(200, agent.status())
            return self._send(404, {"error": f"no such resource {path}"})
        except Exception as e:  # surface, never kill the server thread
            return self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def do_PUT(self):  # noqa: N802
        if not self._admit():
            return
        try:
            self._do_PUT()
        finally:
            self._release()

    def _do_PUT(self):
        agent = self.agent
        path, _ = self._route()
        try:
            if path.startswith("/v1/endpoint/"):
                ep_id = self._ep_id(path)
                if ep_id is None:
                    return self._send(400, {"error": "endpoint id must be "
                                            "an integer"})
                body = json.loads(self._body() or b"{}")
                named_ports = body.get("named_ports")
                with agent.write_lock:
                    ep = agent.endpoint_add(
                        ep_id,
                        dict(body.get("labels", {})),
                        ipv4=body.get("ipv4", ""),
                        # None (field absent) preserves an existing
                        # endpoint's table on re-PUT
                        named_ports=(
                            {str(k): int(v)
                             for k, v in named_ports.items()}
                            if named_ports is not None else None),
                    )
                return self._send(201, ep.to_json())
            if path == "/v1/policy":
                ctype = self.headers.get("Content-Type", "")
                raw = self._body()
                from cilium_tpu.policy.api.cnp import (
                    load_cnp_yaml_text,
                    parse_cnp,
                )

                if "json" in ctype:
                    cnps = [parse_cnp(json.loads(raw))]
                else:
                    cnps = load_cnp_yaml_text(raw.decode())
                rev = 0
                with agent.write_lock:
                    for cnp in cnps:
                        # upsert: a CNP update replaces same-name rules
                        agent.policy_delete(list(cnp.labels), wait=False)
                        rev = agent.policy_add(cnp, wait=False)
                    # ONE regeneration for the whole body, not per CNP
                    agent.endpoint_manager.regenerate_all(wait=True)
                return self._send(200, {"revision": rev,
                                        "count": len(cnps)})
            if path == "/v1/auth":
                # mutual-auth handshake completion (the auth service's
                # upsert into the auth map)
                body = json.loads(self._body() or b"{}")
                agent.auth.authenticate(
                    int(body["src_identity"]), int(body["dst_identity"]),
                    ttl=body.get("ttl"))
                return self._send(201, {"ok": True})
            if path == "/v1/profile":
                # pkg/pprof analog: profile the LIVE agent on demand
                # (SURVEY §5.1); blocks for `seconds`, returns the
                # artifact path
                from cilium_tpu.runtime.profiling import (
                    PROFILER,
                    ProfileBusy,
                )

                body = json.loads(self._body() or b"{}")
                try:
                    result = PROFILER.capture(
                        body.get("out", "/tmp/cilium_tpu_profile"),
                        seconds=float(body.get("seconds", 2.0)),
                        mode=body.get("mode", "host"),
                    )
                except ProfileBusy as e:
                    return self._send(409, {"error": str(e)})
                except ValueError as e:
                    return self._send(400, {"error": str(e)})
                return self._send(200, result)
            if path == "/v1/policy/trace":
                # `cilium policy trace` analog: explain the verdict
                # for HYPOTHETICAL src/dst label sets
                from cilium_tpu.core.labels import LabelSet
                from cilium_tpu.endpoint import with_cluster_label
                from cilium_tpu.policy.trace import trace

                body = json.loads(self._body() or b"{}")
                cluster = agent.config.cluster_name

                def _ls(v):
                    # list form preserves sources ("cidr:10.0.0.0/8",
                    # "reserved:world"); dict form parses each k=v via
                    # the shared label parser so source-prefixed keys
                    # survive too
                    if isinstance(v, dict):
                        items = [f"{k}={val}" if val else str(k)
                                 for k, val in v.items()]
                    else:
                        items = [str(s) for s in (v or ())]
                    return with_cluster_label(LabelSet.parse(items),
                                              cluster)

                result = trace(
                    agent.repo,
                    src_labels=_ls(body.get("src_labels")),
                    dst_labels=_ls(body.get("dst_labels")),
                    dport=int(body.get("dport", 0) or 0),
                    proto=int(body.get("protocol", 6) or 6),
                    ingress=(str(body.get("direction", "ingress"))
                             .lower() != "egress"),
                    cluster_name=cluster,
                    named_ports=body.get("named_ports"),
                )
                return self._send(200, result)
            return self._send(404, {"error": f"no such resource {path}"})
        except Exception as e:
            return self._send(400, {"error": f"{type(e).__name__}: {e}"})

    def do_POST(self):  # noqa: N802
        if not self._admit():
            return
        try:
            self._do_POST()
        finally:
            self._release()

    def _do_POST(self):
        agent = self.agent
        path, _ = self._route()
        try:
            if path == "/v1/drain":
                # graceful drain (SIGTERM's REST face): stop admitting
                # data-path verdicts, flush pending batches through the
                # engine, snapshot warm-restart state. The service
                # keeps answering control ops afterwards; restart +
                # Loader.restore_warm completes the warm cycle.
                return self._send(200, agent.drain())
            return self._send(404, {"error": f"no such resource {path}"})
        except Exception as e:
            return self._send(500, {"error": f"{type(e).__name__}: {e}"})

    def do_PATCH(self):  # noqa: N802
        if not self._admit():
            return
        try:
            self._do_PATCH()
        finally:
            self._release()

    def _do_PATCH(self):
        agent = self.agent
        path, _ = self._route()
        try:
            if path == "/v1/config":
                body = json.loads(self._body() or b"{}")
                # validate ALL keys and value types first: a rejected
                # request must not leave earlier fields mutated, and a
                # JSON string "false" must not truthy-enable a bool gate
                for k, v in body.items():
                    if k not in _MUTABLE_CONFIG:
                        return self._send(
                            400, {"error": f"config field {k!r} is not "
                                  f"runtime-mutable"})
                    want = type(getattr(agent.config, k))
                    if not isinstance(v, want):
                        return self._send(
                            400, {"error": f"config field {k!r} expects "
                                  f"{want.__name__}, got "
                                  f"{type(v).__name__}"})
                with agent.write_lock:
                    for k, v in body.items():
                        setattr(agent.config, k, v)
                    if "enable_tpu_offload" in body:
                        # the gate selects the loader's engine AND the
                        # DNS proxy's matcher — flip both, then restage
                        # (the reference's datapath reload)
                        agent.dns_proxy.use_tpu = bool(
                            body["enable_tpu_offload"])
                        agent.endpoint_manager.regenerate_all(wait=True)
                return self._send(200, {"changed": dict(body)})
            if path.startswith("/v1/endpoint/") \
                    and path.endswith("/config"):
                # per-endpoint options (`cilium-dbg endpoint config`):
                # currently PolicyAuditMode
                try:
                    ep_id = int(path.split("/")[3])
                except (ValueError, IndexError):
                    return self._send(400, {"error": "endpoint id must "
                                            "be an integer"})
                body = json.loads(self._body() or b"{}")
                unknown = set(body) - {"policy_audit_mode"}
                if unknown:
                    return self._send(
                        400, {"error": f"unknown endpoint option(s) "
                              f"{sorted(unknown)}"})
                pam = body.get("policy_audit_mode")
                if pam is not None and not isinstance(pam, bool):
                    return self._send(
                        400, {"error": "policy_audit_mode expects bool"})
                try:
                    ep = agent.endpoint_config(
                        ep_id, policy_audit_mode=pam)
                except KeyError:
                    return self._send(404, {"error": "endpoint not found"})
                return self._send(200, ep.to_json())
            return self._send(404, {"error": f"no such resource {path}"})
        except Exception as e:
            return self._send(400, {"error": f"{type(e).__name__}: {e}"})

    def do_DELETE(self):  # noqa: N802
        if not self._admit():
            return
        try:
            self._do_DELETE()
        finally:
            self._release()

    def _do_DELETE(self):
        agent = self.agent
        path, _ = self._route()
        try:
            if path.startswith("/v1/endpoint/"):
                ep_id = self._ep_id(path)
                if ep_id is None:
                    return self._send(400, {"error": "endpoint id must be "
                                            "an integer"})
                with agent.write_lock:
                    agent.endpoint_remove(ep_id)
                return self._send(200, {"deleted": True})
            if path == "/v1/policy":
                body = json.loads(self._body() or b"{}")
                labels = list(body.get("labels", ()))
                with agent.write_lock:
                    deleted = agent.policy_delete(labels)
                    rev = agent.repo.revision
                return self._send(200, {"deleted": deleted,
                                        "revision": rev})
            if path == "/v1/auth":
                body = json.loads(self._body() or b"{}")
                deleted = agent.auth.revoke(int(body["src_identity"]),
                                            int(body["dst_identity"]))
                return self._send(200, {"deleted": deleted})
            return self._send(404, {"error": f"no such resource {path}"})
        except Exception as e:
            return self._send(400, {"error": f"{type(e).__name__}: {e}"})


class APIServer:
    """Serve the REST API on ``socket_path`` (background thread pool)."""

    def __init__(self, agent, socket_path: str):
        self.socket_path = socket_path
        if os.path.exists(socket_path):
            unlink_if_stale(socket_path)
        handler = type("BoundHandler", (_Handler,), {"agent": agent})
        self._server = _UnixHTTPServer(socket_path, handler)
        # bounded in-flight admission (runtime/admission.py): data-
        # class requests shed at api_max_inflight; control paths get
        # control_reserve headroom
        self._server.slots = admission.RequestSlots.from_config(
            getattr(agent.config, "admission", None))
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "APIServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="api-server",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: float = 30.0):
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class APIClient:
    """``pkg/client`` analog: typed access to the agent REST API."""

    def __init__(self, socket_path: str, timeout: float = 30.0):
        self.socket_path = socket_path
        self.timeout = timeout

    def request(self, method: str, path: str, body=None,
                content_type: str = "application/json"):
        conn = _UnixHTTPConnection(self.socket_path, timeout=self.timeout)
        try:
            data = None
            if body is not None:
                data = (body if isinstance(body, (bytes, str))
                        else json.dumps(body))
            conn.request(method, path, body=data,
                         headers={"Content-Type": content_type})
            resp = conn.getresponse()
            raw = resp.read()
            if resp.headers.get_content_type() == "application/json":
                return resp.status, json.loads(raw or b"null")
            return resp.status, raw.decode()
        finally:
            conn.close()

    # typed helpers
    def healthz(self):
        return self.request("GET", "/v1/healthz")[1]

    def config(self):
        return self.request("GET", "/v1/config")[1]

    def patch_config(self, **fields):
        return self.request("PATCH", "/v1/config", body=fields)

    def endpoints(self):
        return self.request("GET", "/v1/endpoint")[1]

    def endpoint_put(self, endpoint_id: int, labels: Dict[str, str],
                     ipv4: str = ""):
        return self.request("PUT", f"/v1/endpoint/{endpoint_id}",
                            body={"labels": labels, "ipv4": ipv4})

    def endpoint_delete(self, endpoint_id: int):
        return self.request("DELETE", f"/v1/endpoint/{endpoint_id}")

    def auth_list(self):
        return self.request("GET", "/v1/auth")[1]

    def auth_put(self, src_identity: int, dst_identity: int, ttl=None):
        body = {"src_identity": src_identity,
                "dst_identity": dst_identity}
        if ttl is not None:
            body["ttl"] = ttl
        return self.request("PUT", "/v1/auth", body=body)

    def auth_delete(self, src_identity: int, dst_identity: int):
        return self.request("DELETE", "/v1/auth",
                            body={"src_identity": src_identity,
                                  "dst_identity": dst_identity})

    def drain(self):
        """Graceful drain: stop admitting, flush, warm-snapshot."""
        return self.request("POST", "/v1/drain")

    def policy_get(self):
        return self.request("GET", "/v1/policy")[1]

    def policy_put_yaml(self, yaml_text: str):
        return self.request("PUT", "/v1/policy", body=yaml_text,
                            content_type="application/yaml")

    def policy_delete(self, labels):
        return self.request("DELETE", "/v1/policy",
                            body={"labels": list(labels)})

    def identities(self):
        return self.request("GET", "/v1/identity")[1]

    def proxy_redirects(self):
        return self.request("GET", "/v1/proxy")[1]

    def selectors(self):
        return self.request("GET", "/v1/selectors")[1]

    def policy_trace(self, src_labels, dst_labels, dport=0,
                     protocol=6, direction="ingress", named_ports=None):
        return self.request("PUT", "/v1/policy/trace", {
            "src_labels": src_labels, "dst_labels": dst_labels,
            "dport": dport, "protocol": protocol,
            "direction": direction, "named_ports": named_ports})[1]

    def ipcache(self):
        return self.request("GET", "/v1/ip")[1]

    def fqdn_cache(self):
        return self.request("GET", "/v1/fqdn/cache")[1]

    def services(self):
        return self.request("GET", "/v1/service")[1]

    def metrics(self) -> str:
        return self.request("GET", "/v1/metrics")[1]

    def traces(self, trace_id: Optional[str] = None,
               limit: Optional[int] = None, chrome: bool = False):
        q = []
        if trace_id:
            q.append(f"trace_id={trace_id}")
        if limit:
            q.append(f"limit={int(limit)}")
        if chrome:
            q.append("format=chrome")
        path = "/v1/trace" + ("?" + "&".join(q) if q else "")
        return self.request("GET", path)[1]

    def canary(self):
        return self.request("GET", "/v1/canary")[1]

    def flows(self, limit: Optional[int] = None):
        q = f"?limit={int(limit)}" if limit else ""
        return self.request("GET", "/v1/flows" + q)[1]

    def debuginfo(self):
        return self.request("GET", "/v1/debuginfo")[1]
