"""Runtime: loader (tensor staging / revision swap / feature gate),
compiled-artifact checkpoint cache, metrics & spanstat timing.

Mirrors the reference's ``pkg/datapath/loader`` (stage + hot-swap under a
revision counter, behind the master gate), its metrics registry
(``pkg/metrics``) and spanstat (``pkg/spanstat``) — SURVEY.md §2.3, §5.
"""

from cilium_tpu.runtime.loader import Loader
from cilium_tpu.runtime.checkpoint import ArtifactCache, ruleset_fingerprint
from cilium_tpu.runtime.metrics import Metrics, SpanStat, METRICS
from cilium_tpu.runtime.tracing import TRACER, Tracer

__all__ = [
    "Loader",
    "ArtifactCache",
    "ruleset_fingerprint",
    "Metrics",
    "SpanStat",
    "METRICS",
    "TRACER",
    "Tracer",
]
