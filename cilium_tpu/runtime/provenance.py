"""Environment provenance for bench artifacts (the perf ledger's
identity stamp).

Round 5's "40× regression" was a ~100ms tunnel RTT, not a code change
— but nothing on the artifact said so, and the comparison was
unfalsifiable until a human re-derived the environment from log
warnings. Every bench line now carries a **provenance fingerprint**:
platform, device kind/count, jax version, an H2D round-trip probe to
the attached backend, and the git revision that produced the number.
``cilium-tpu perf-report`` (``cilium_tpu/perf_report.py``) uses the
fingerprint to classify a cross-round delta as *code regression* vs
*environment change* instead of guessing.

Everything here is best-effort: a fingerprint must never break the
one-JSON-line bench contract, so a missing backend or absent git
checkout degrades fields to ``None`` rather than raising.
"""

from __future__ import annotations

import os
import platform
import subprocess
import sys
import time
from typing import Dict, Optional

#: version of the stamped bench-artifact schema — every new-schema
#: bench line/artifact carries ``"bench_schema": BENCH_SCHEMA`` next to
#: ``"provenance"``; the perf-report normalizer keys validation on it
BENCH_SCHEMA = 1


def git_revision(root: Optional[str] = None) -> Dict[str, object]:
    """``{"git_rev": short-hash or None, "git_dirty": bool or None}``
    for the checkout containing ``root`` (default: this file's repo)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    try:
        rev = subprocess.run(
            ["git", "-C", root, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10)
        if rev.returncode != 0:
            return {"git_rev": None, "git_dirty": None}
        dirty = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=10)
        return {"git_rev": rev.stdout.strip(),
                "git_dirty": (bool(dirty.stdout.strip())
                              if dirty.returncode == 0 else None)}
    except (OSError, subprocess.TimeoutExpired):
        return {"git_rev": None, "git_dirty": None}


def rtt_probe(n: int = 7) -> Dict[str, Optional[float]]:
    """(p50, max) of a tiny H2D+readback round trip in ms — the
    tunnel-health marker (bench.py round 4: a 4× run-to-run spread is
    unfalsifiable without it). Requires an initialized jax backend;
    returns Nones when there isn't one."""
    try:
        import jax
        import numpy as np

        xs = np.zeros(16, dtype=np.int32)
        np.asarray(jax.device_put(xs))  # connection warm
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            np.asarray(jax.device_put(xs))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return {"rtt_p50_ms": round(ts[len(ts) // 2] * 1e3, 3),
                "rtt_max_ms": round(ts[-1] * 1e3, 3)}
    except Exception:  # noqa: BLE001 — probe is best-effort by contract
        return {"rtt_p50_ms": None, "rtt_max_ms": None}


def fingerprint(rtt: bool = True,
                root: Optional[str] = None) -> Dict[str, object]:
    """The full provenance fingerprint. ``rtt=False`` skips the
    backend probe (callers that never touch jax — the bench OUTER
    process — still get host/git identity)."""
    fp: Dict[str, object] = {
        "schema": BENCH_SCHEMA,
        # ctlint: disable=wall-clock  # provenance stamps record when the REAL world produced this artifact
        "captured_unix": int(time.time()),
        "host_platform": platform.platform(),
        "python": platform.python_version(),
        "argv0": os.path.basename(sys.argv[0]) if sys.argv else None,
        "jax_platforms_env": os.environ.get("JAX_PLATFORMS"),
        "jax_version": None,
        "backend": None,
        "device_kind": None,
        "device_count": None,
    }
    fp.update(git_revision(root))
    try:
        import jax

        fp["jax_version"] = jax.__version__
        devices = jax.devices()
        fp["backend"] = jax.default_backend()
        fp["device_kind"] = devices[0].device_kind if devices else None
        fp["device_count"] = len(devices)
    except Exception as e:  # noqa: BLE001 — no backend is a valid
        # environment; the fingerprint says so instead of raising
        fp["jax_error"] = str(e)[:120]
    if rtt and fp["backend"] is not None:
        fp.update(rtt_probe())
    else:
        fp.update({"rtt_p50_ms": None, "rtt_max_ms": None})
    return fp


def dst_stamp() -> Optional[Dict[str, object]]:
    """The deterministic-simulation provenance rider: when a lane runs
    under the DST harness (``CILIUM_TPU_DST_SEED`` set by `make dst` /
    the converted chaos/churn lanes), its bench lines carry the seed
    and schedule digest, so perf-report can tie a regression to the
    exact fault schedule that exposed it (replay:
    ``python -m cilium_tpu.runtime.dst --replay --seed N``)."""
    seed = os.environ.get("CILIUM_TPU_DST_SEED")
    if seed is None:
        return None
    out: Dict[str, object] = {}
    try:
        out["dst_seed"] = int(seed)
    except ValueError:
        out["dst_seed"] = seed
    digest = os.environ.get("CILIUM_TPU_DST_DIGEST")
    if digest:
        out["schedule_digest"] = digest
    mutation = os.environ.get("CILIUM_TPU_DST_MUTATION")
    if mutation:
        out["mutation"] = mutation
    return out


def stamp(obj: Dict, rtt: bool = True) -> Dict:
    """Stamp ``obj`` (a bench line or artifact dict) in place with the
    versioned schema tag + fingerprint; returns ``obj``. Never raises.

    Fleet lines additionally carry ``host_id`` — which host produced
    the number (``parallel/multihost.host_id``: ``CILIUM_TPU_HOST_ID``
    when the harness pins one, else the process identity). The id
    makes per-host numbers from the fleetserve lane attributable the
    way ``git_rev`` makes rounds attributable; callers that already
    set a ``host_id`` (the router stamping a replica's line) win."""
    try:
        obj["bench_schema"] = BENCH_SCHEMA
        obj["provenance"] = fingerprint(rtt=rtt)
        from cilium_tpu.parallel.multihost import host_id

        obj.setdefault("host_id", host_id())
        dst = dst_stamp()
        if dst is not None:
            obj["dst"] = dst
    except Exception as e:  # noqa: BLE001 — the bench line must still
        # print; the stamp records its own failure instead of raising
        obj.setdefault("provenance", None)
        obj["provenance_error"] = str(e)[:120]
    return obj
