"""Verdict service: Unix-socket server + micro-batcher + policy bridge.

The reference's agent↔Envoy channels are Unix sockets (NPDS xDS pushes,
access logs — SURVEY.md §2.7); ours is one Unix socket speaking
4-byte-length-prefixed JSON. The C++ shim (``shim/``) and the proxylib
parsers are the clients.

Protocol (request → response):
  {"op": "ping"}                       → {"ok": true, "revision": N}
  {"op": "verdict", "flows": [flowpb-ish dicts]}
                                       → {"verdicts": [1|2|5, ...]}
  {"op": "check", "flow": {...}}       → {"verdict": 1|2|5}   (batched)
  {"op": "on_new_connection", "proto": "kafka", "conn": 7,
   "ingress": true, "src": 1001, "dst": 1002, "dport": 9092}
                                       → {"ok": true}
  {"op": "on_data", "conn": 7, "reply": false, "end": false,
   "data_b64": "..."}                  → {"ops": [[op, n], ...]}

Micro-batching (SURVEY.md §7 hard part #4): single-record policy
checks are queued and flushed to the engine either when ``batch_max``
records are pending or after ``deadline_ms`` — trading p99 latency for
MXU utilization.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import socketserver
import struct
import threading
from typing import Callable, Dict, List, Optional, Sequence

from cilium_tpu.core.flow import (
    DNSInfo,
    Flow,
    GenericL7Info,
    HTTPInfo,
    KafkaInfo,
    L7Type,
    Protocol,
    TrafficDirection,
    Verdict,
)
from cilium_tpu.ingest.hubble import flow_from_dict
from cilium_tpu.proxylib.parser import Connection, create_parser
from cilium_tpu.runtime import admission, faults, simclock
from cilium_tpu.runtime.loader import Loader
from cilium_tpu.runtime.logging import get_logger
from cilium_tpu.runtime.metrics import (
    ADMISSION_REAPED,
    BREAKER_FALLBACK_VERDICTS,
    BREAKER_RECOVERIES,
    BREAKER_STATE,
    BREAKER_TRIPS,
    DRAINS,
    METRICS,
)
from cilium_tpu.runtime.tracing import (
    PHASE_FALLBACK,
    PHASE_QUEUE,
    PHASE_SHED,
    TRACER,
)

LOG = get_logger("service")

#: fires between stop-admitting and the pending flush in
#: VerdictService.drain — a crash mid-drain leaves the gate draining
#: (not half-open); the operator retries the drain
DRAIN_POINT = faults.register_point(
    "service.drain", "drain sequence in VerdictService.drain")


def verdict_flows_padded(engine, flows: Sequence[Flow],
                         authed_pairs=None) -> List[int]:
    """``engine.verdict_flows`` with the batch padded to the next
    power of two: service traffic produces arbitrary batch sizes, and
    each distinct size is a fresh XLA compile — pow2 bucketing bounds
    the shape space to ~log2(batch_max) sizes so p99 under live load
    isn't a compile storm (SURVEY.md §7 hard part #5). Pad flows are
    identity-0 tuples; their verdicts are sliced off. Only the verdict
    lane is read back: each output lane is a device→host RTT on the
    tunneled TPU, and this path's callers consume nothing else."""
    return [int(v) for v in
            verdict_outputs_padded(engine, flows,
                                   authed_pairs=authed_pairs,
                                   outputs=("verdict",))["verdict"]]


def verdict_outputs_padded(engine, flows: Sequence[Flow],
                           authed_pairs=None, outputs=None):
    """Full output lanes under the same pow2 padding (every lane
    sliced back to the real batch) — for callers that fan the batch
    out to observability and need match_spec/l7_log too. ``outputs``
    limits which lanes are read back (one transfer per lane)."""
    import numpy as np

    n = len(flows)
    target = 1 << max(0, n - 1).bit_length()
    if target > n:
        flows = list(flows) + [Flow()] * (target - n)
    # the blob transport (one H2D per batch instead of seven) exists
    # on the device engine only; the oracle has no transfers to save
    fn = getattr(engine, "verdict_flows_blob", engine.verdict_flows)
    out = fn(flows, authed_pairs=authed_pairs, outputs=outputs)
    return {k: np.asarray(v)[:n] for k, v in out.items()}


class CircuitBreaker:
    """TPU-lane circuit breaker (pkg/controller's backoff discipline
    applied to the datapath): CLOSED routes verdicts to the device
    engine; ``failure_threshold`` CONSECUTIVE dispatch failures trip
    it OPEN (every verdict then rides the CPU oracle — correct but
    slower); after ``probe_interval`` seconds one request is let
    through HALF_OPEN as a probe — success recovers to CLOSED, failure
    re-opens and re-arms the probe timer.

    Thread-safe; the MicroBatcher drain workers, the per-request
    "verdict" op and the stream sessions all share one instance, so
    "N consecutive failures" means N across the whole service, exactly
    like an operator would count them. ``clock`` is injectable so the
    chaos suite drives the probe timer deterministically; the default
    follows the process clock (runtime/simclock.py), so a DST run's
    virtual clock drives every breaker built after install."""

    CLOSED, OPEN, HALF_OPEN = 0, 1, 2
    _NAMES = {0: "closed", 1: "open", 2: "half-open"}

    def __init__(self, failure_threshold: int = 3,
                 probe_interval: float = 5.0, clock=None):
        self.failure_threshold = max(1, int(failure_threshold))
        self.probe_interval = float(probe_interval)
        self.clock = clock if clock is not None else simclock.now
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        #: (event, state-name) transition log — the replayable trace
        #: the chaos suite compares across seeded runs
        self.events: List = []
        METRICS.set_gauge(BREAKER_STATE, float(self.CLOSED))

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def _transition(self, state: int, event: str) -> None:
        self._state = state
        self.events.append((event, self._NAMES[state]))
        METRICS.set_gauge(BREAKER_STATE, float(state))

    def allow_primary(self) -> bool:
        """May this request try the device lane? OPEN returns False
        until the probe timer expires, then exactly one caller gets
        True as the HALF_OPEN probe (concurrent callers keep falling
        back — a thundering herd onto a possibly-sick device would
        defeat the probe's purpose)."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN and \
                    self.clock() - self._opened_at >= self.probe_interval:
                self._transition(self.HALF_OPEN, "probe")
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._transition(self.CLOSED, "recover")
                METRICS.inc(BREAKER_RECOVERIES)
            self._consecutive_failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == self.HALF_OPEN:
                # failed probe: back to OPEN, re-arm the timer
                self._opened_at = self.clock()
                self._transition(self.OPEN, "probe-failed")
            elif (self._state == self.CLOSED
                  and self._consecutive_failures
                  >= self.failure_threshold):
                self._opened_at = self.clock()
                self._transition(self.OPEN, "trip")
                METRICS.inc(BREAKER_TRIPS)


class ResilientVerdictor:
    """The degraded-mode verdict pipeline: device engine behind a
    :class:`CircuitBreaker`, CPU oracle (``Loader.fallback_engine``)
    as the always-correct fallback. Every verdict path in the service
    (MicroBatcher, the bulk "verdict" op, stream sessions) routes
    through one instance, so a sick device degrades the WHOLE service
    to correct-but-slower instead of erroring any single path.

    When the active engine already is the oracle (gate off) the
    breaker never engages — there is no faster lane to trip from."""

    def __init__(self, loader: Loader, breaker: Optional[CircuitBreaker]
                 = None, authed_pairs_fn=None):
        self.loader = loader
        cfg = getattr(loader.config, "breaker", None)
        if breaker is None:
            breaker = CircuitBreaker(
                failure_threshold=getattr(cfg, "failure_threshold", 3),
                probe_interval=getattr(cfg, "probe_interval", 5.0))
        self.breaker = breaker
        self.enabled = getattr(cfg, "enabled", True)
        self.authed_pairs_fn = authed_pairs_fn

    @staticmethod
    def _device_backed(engine) -> bool:
        # the jitted engine exposes the blob step; the oracle doesn't
        return hasattr(engine, "_blob_step")

    def _pairs(self, authed_pairs):
        if authed_pairs is not None:
            return authed_pairs
        return (self.authed_pairs_fn()
                if self.authed_pairs_fn is not None else None)

    # -- breaker bookkeeping shared with StreamSession ------------------
    def allow_device(self, engine) -> bool:
        if not self.enabled or not self._device_backed(engine):
            return True
        return self.breaker.allow_primary()

    def on_device_success(self) -> None:
        if self.enabled:
            self.breaker.record_success()

    def on_device_failure(self, exc: BaseException) -> None:
        if self.enabled:
            self.breaker.record_failure()
        TRACER.event("device.failure",
                     error=f"{type(exc).__name__}: {exc}")
        LOG.warning("device verdict lane failed; serving via oracle",
                    extra={"fields": {
                        "error": f"{type(exc).__name__}: {exc}"}})

    def fallback_outputs(self, flows: Sequence[Flow], authed_pairs=None,
                         outputs=None):
        """Oracle lane, with the fallback counter."""
        METRICS.inc(BREAKER_FALLBACK_VERDICTS, len(flows))
        with TRACER.span("oracle.verdict", phase=PHASE_FALLBACK,
                         records=len(flows)):
            return verdict_outputs_padded(
                self.loader.fallback_engine, flows,
                authed_pairs=self._pairs(authed_pairs), outputs=outputs)

    # -- the verdict entry points ---------------------------------------
    def outputs(self, flows: Sequence[Flow], authed_pairs=None,
                outputs=None, deadline: Optional[float] = None):
        """Full output lanes under pow2 padding, surviving device
        failure: device lane when the breaker allows, oracle
        otherwise or on dispatch failure — the request is answered
        either way, and always correctly. ``deadline`` (absolute
        monotonic) is the batch's propagated budget: recorded on the
        dispatch trace so a blown deadline is attributable to the
        phase that ate it."""
        if deadline is not None:
            TRACER.event("dispatch.deadline",
                         remaining_ms=round(
                             (deadline - simclock.now()) * 1e3, 3))
        engine = self.loader.engine
        if engine is None:
            raise RuntimeError("no policy loaded")
        pairs = self._pairs(authed_pairs)
        if not self.enabled or not self._device_backed(engine):
            if self._device_backed(engine):
                return verdict_outputs_padded(engine, flows,
                                              authed_pairs=pairs,
                                              outputs=outputs)
            # active engine IS the oracle (gate off): attribute the
            # whole evaluation to the fallback phase — there is no
            # host/device split to show
            with TRACER.span("oracle.verdict", phase=PHASE_FALLBACK,
                             records=len(flows)):
                return verdict_outputs_padded(engine, flows,
                                              authed_pairs=pairs,
                                              outputs=outputs)
        if self.breaker.allow_primary():
            try:
                out = verdict_outputs_padded(engine, flows,
                                             authed_pairs=pairs,
                                             outputs=outputs)
                self.breaker.record_success()
                return out
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                self.on_device_failure(e)
        else:
            TRACER.event("breaker.rerouted",
                         state=self.breaker.state)
        return self.fallback_outputs(flows, authed_pairs=pairs,
                                     outputs=outputs)

    def verdicts(self, flows: Sequence[Flow], authed_pairs=None,
                 deadline: Optional[float] = None) -> List[int]:
        return [int(v) for v in
                self.outputs(flows, authed_pairs=authed_pairs,
                             outputs=("verdict",),
                             deadline=deadline)["verdict"]]


class _Pending:
    """One queued check: the flow plus its rendezvous and deadline
    bookkeeping. ``abandoned`` flips when the caller gives up waiting
    — the drain worker reaps the entry before dispatch instead of
    spending a device batch slot on an answer nobody reads."""

    __slots__ = ("flow", "ev", "box", "t_enq", "ctx", "deadline",
                 "abandoned")

    def __init__(self, flow: Flow, deadline: Optional[float], ctx):
        self.flow = flow
        # clock-integrated event: a VirtualClock wakes the waiting
        # caller promptly when the drain worker answers in virtual time
        self.ev = simclock.event()
        self.box: List[int] = []
        self.t_enq = simclock.now()
        self.ctx = ctx
        self.deadline = deadline
        self.abandoned = False


class MicroBatcher:
    """Collects single flows; flushes as one engine batch on size or
    deadline.

    ``drain_workers`` long-lived drain workers run engine batches
    (default 1 = strictly serial: while a batch executes, new requests
    keep enqueuing and form the next batch — natural back-pressure;
    spawning a thread per flush instead would pile up unboundedly
    whenever the engine is slower than the arrival rate). With 2+
    workers, batch k+1 can accumulate AND dispatch while batch k's
    device round-trip is in flight — on a tunneled TPU the per-batch
    readback RTT is otherwise dead time, so pipelined drains raise
    the saturation throughput without touching the deadline
    semantics. Each request still gets exactly one verdict; ordering
    across batches is not part of the contract (never was — callers
    block per request).

    Overload discipline (runtime/admission.py): ``max_pending`` is the
    HARD queue bound, enforced under the lock — enqueues past it shed
    explicitly instead of growing the list; per-entry deadlines are
    carried to dispatch, and entries whose caller abandoned them or
    whose deadline lapsed in the queue are reaped before featurize."""

    def __init__(self, verdict_fn: Callable[[Sequence[Flow]], Sequence[int]],
                 batch_max: int = 256, deadline_ms: float = 2.0,
                 drain_workers: int = 1, max_pending: int = 0,
                 gate=None):
        self.verdict_fn = verdict_fn
        self.batch_max = batch_max
        self.deadline_s = deadline_ms / 1e3
        self.drain_workers = max(1, int(drain_workers))
        #: hard occupancy bound (0 = unbounded, standalone/test use;
        #: the service always passes its configured bound)
        self.max_pending = max(0, int(max_pending))
        #: optional AdmissionGate: fed the per-batch service rate for
        #: its deadline-feasibility estimate
        self.gate = gate
        # does the verdict_fn accept the batch deadline? (propagated
        # to engine dispatch when it does; plain fns stay plain)
        import inspect

        try:
            self._fn_takes_deadline = "deadline" in \
                inspect.signature(verdict_fn).parameters
        except (TypeError, ValueError):
            self._fn_takes_deadline = False
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: List[_Pending] = []
        self._inflight = 0               # entries popped, batch running
        self.peak_pending = 0            # high-water mark (soak lane)
        self._workers: List[threading.Thread] = []
        self._closed = False
        self._draining = False

    # -- enqueue ----------------------------------------------------------
    def check(self, flow: Flow, timeout: float = 5.0,
              deadline: Optional[float] = None) -> int:
        return self.check_ex(flow, timeout=timeout, deadline=deadline)[0]

    def check_ex(self, flow: Flow, timeout: float = 5.0,
                 deadline: Optional[float] = None):
        """(verdict, status): status is "ok", "shed" (queue at bound),
        "closed" (drained/stopped), or "timeout" (caller gave up; the
        entry is marked abandoned and reaped before dispatch).
        ``deadline`` is absolute monotonic seconds; None derives one
        from ``timeout`` so every entry is reapable."""
        if deadline is None:
            deadline = simclock.now() + timeout
        # the caller's trace context crosses the thread handoff WITH
        # the entry — the drain worker attributes this request's
        # queue-wait and fans the batch's phase spans back to it
        entry = _Pending(flow, deadline, TRACER.current())
        shed = False
        with self._cond:
            if self._closed or self._draining:
                return int(Verdict.ERROR), "closed"
            if self.max_pending and \
                    len(self._pending) >= self.max_pending:
                shed = True
            else:
                self._pending.append(entry)
                if len(self._pending) > self.peak_pending:
                    self.peak_pending = len(self._pending)
                if not self._workers:
                    self._workers = [
                        threading.Thread(target=self._drain, daemon=True)
                        for _ in range(self.drain_workers)]
                    for w in self._workers:
                        w.start()
                self._cond.notify()
        if shed:
            admission.count_shed("batcher", admission.CLASS_DATA,
                                 admission.SHED_QUEUE_FULL)
            if entry.ctx is not None:
                TRACER.add_span(entry.ctx, "admission.shed",
                                PHASE_SHED, simclock.wall(), 0.0,
                                reason=admission.SHED_QUEUE_FULL)
            return int(Verdict.ERROR), "shed"
        wait = min(timeout, max(0.0, deadline - simclock.now()))
        if not simclock.wait_on(entry.ev, wait):
            # caller is leaving: flag the entry so the drain worker
            # drops it before featurize/dispatch instead of wasting a
            # batch slot on it
            entry.abandoned = True
            return int(Verdict.ERROR), "timeout"
        return entry.box[0], "ok"

    # -- lifecycle --------------------------------------------------------
    def close(self, abort: bool = True) -> None:
        """``abort=True`` (default): stop now, pending entries get
        ERROR verdicts — the crash-stop path. ``abort=False`` delegates
        to :meth:`drain`: flush pending through the engine first."""
        if not abort:
            self.drain()
            return
        with self._cond:
            self._closed = True
            pending, self._pending = self._pending, []
            self._cond.notify_all()
        for entry in pending:
            entry.box.append(int(Verdict.ERROR))
            entry.ev.set()

    def drain(self, timeout: float = 30.0) -> int:
        """Flush pending entries THROUGH the engine, then stop: the
        graceful half of shutdown — in-flight requests get real
        verdicts, not ERRORs. Entries still unflushed when ``timeout``
        lapses (wedged engine) resolve as ERROR. Returns the number of
        entries flushed with real verdicts. Idempotent."""
        t_deadline = simclock.now() + max(0.0, timeout)
        with self._cond:
            if self._closed:
                return 0
            self._draining = True
            backlog = len(self._pending) + self._inflight
            self._cond.notify_all()
            while self._pending or self._inflight:
                left = t_deadline - simclock.now()
                if left <= 0:
                    break
                simclock.wait_cond(self._cond, min(left, 0.05))
            self._closed = True
            leftovers, self._pending = self._pending, []
            # snapshot the worker list under the cond's lock; joining
            # happens OUTSIDE it (workers need the lock to observe
            # _closed and exit)
            workers = list(self._workers)
            self._cond.notify_all()
        for entry in leftovers:
            entry.box.append(int(Verdict.ERROR))
            entry.ev.set()
        for w in workers:
            w.join(timeout=1.0)
        return max(0, backlog - len(leftovers))

    # -- drain workers ----------------------------------------------------
    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return
                # wait for a full batch or the oldest entry's deadline.
                # Non-emptiness re-checked after EVERY wake: a sibling
                # pipelined worker may have drained the queue while we
                # waited (indexing [0] blind would kill this thread,
                # and workers are never respawned). Drain mode flushes
                # immediately — coalescing gains nothing on the way out
                while (self._pending
                       and len(self._pending) < self.batch_max
                       and not self._closed and not self._draining):
                    oldest = self._pending[0].t_enq
                    left = oldest + self.deadline_s - simclock.now()
                    if left <= 0 or not simclock.wait_cond(self._cond,
                                                           left):
                        break
                if self._closed:
                    return
                if not self._pending:
                    continue  # sibling took everything; wait again
                # cap at batch_max: the engine's padding buckets assume
                # bounded batches, and an unbounded flush under overload
                # compiles new shapes mid-incident
                pending = self._pending[:self.batch_max]
                del self._pending[:self.batch_max]
                self._inflight += len(pending)
                if self._pending:
                    # a sibling drain worker (pipelined mode) can start
                    # on the remainder immediately
                    self._cond.notify()
            try:
                self._run_batch(pending)
            finally:
                with self._cond:
                    self._inflight -= len(pending)
                    self._cond.notify_all()

    def _reap(self, pending: List[_Pending]) -> List[_Pending]:
        """Drop abandoned/expired entries before dispatch. Reaped
        entries resolve ERROR (their caller is gone or about to be);
        the drop is counted and, for sampled traces, attributed to the
        shed phase — the trace says the request died in the queue."""
        now = simclock.now()
        live: List[_Pending] = []
        reaped: List[_Pending] = []
        for entry in pending:
            if entry.abandoned or (entry.deadline is not None
                                   and entry.deadline <= now):
                reaped.append(entry)
            else:
                live.append(entry)
        if reaped:
            if self.gate is not None:
                self.gate.reap(len(reaped))
            else:
                METRICS.inc(ADMISSION_REAPED, len(reaped))
            wall = simclock.wall()
            for entry in reaped:
                if entry.ctx is not None:
                    waited = now - entry.t_enq
                    TRACER.add_span(entry.ctx, "admission.reap",
                                    PHASE_SHED, wall - waited, waited)
                entry.box.append(int(Verdict.ERROR))
                entry.ev.set()
        return live

    def _run_batch(self, pending: List[_Pending]) -> None:
        pending = self._reap(pending)
        if not pending:
            return
        flows = [p.flow for p in pending]
        # per-request queue-wait attribution: monotonic deltas anchored
        # to wall time (one wall read per batch, not per request)
        t_drain = simclock.now()
        wall = simclock.wall()
        for entry in pending:
            if entry.ctx is not None:
                waited = t_drain - entry.t_enq
                TRACER.add_span(entry.ctx, "batch.queue", PHASE_QUEUE,
                                wall - waited, waited)
        # the batch dispatch runs under the GROUP of sampled member
        # contexts: each request's trace shows the batch's host/device
        # (or fallback) spans — its honest share of where time went
        group = TRACER.group([p.ctx for p in pending])
        # the batch deadline — the tightest member's — rides to the
        # engine dispatch when the verdict_fn can carry it
        deadlines = [p.deadline for p in pending
                     if p.deadline is not None]
        batch_deadline = min(deadlines) if deadlines else None
        # perf() so the EWMA service rate is measured in the currency
        # the batch was served in (virtual under a VirtualClock, where
        # synthetic service time is a virtual sleep)
        t0 = simclock.perf()
        try:
            with TRACER.activate(group):
                if self._fn_takes_deadline:
                    verdicts = self.verdict_fn(flows,
                                               deadline=batch_deadline)
                else:
                    verdicts = self.verdict_fn(flows)
        except Exception:
            verdicts = [int(Verdict.ERROR)] * len(flows)
        seconds = simclock.perf() - t0
        METRICS.observe("cilium_tpu_microbatch_seconds", seconds)
        METRICS.observe("cilium_tpu_microbatch_size", len(flows))
        if self.gate is not None:
            self.gate.note_batch(len(flows), seconds)
        for entry, v in zip(pending, verdicts):
            entry.box.append(int(v))
            entry.ev.set()


class PolicyBridge:
    """Adapts parsed L7 records (from proxylib parsers) to engine
    verdicts — the role of proxylib's ``policymap.go``."""

    def __init__(self, loader: Loader, batch_max: int = 256,
                 deadline_ms: float = 2.0, authed_pairs_fn=None,
                 accesslog_fn=None, drain_workers: int = 1,
                 verdictor: Optional[ResilientVerdictor] = None,
                 gate=None):
        self.loader = loader
        #: supplies AuthManager.pairs_array() — the L7 proxy path must
        #: enforce drop-until-authed exactly like Agent.process_flows,
        #: or auth-demanding traffic would slip through the proxy
        self.authed_pairs_fn = authed_pairs_fn
        #: shared degraded-mode pipeline (standalone bridges build
        #: their own so the breaker protects them too)
        self.verdictor = verdictor or ResilientVerdictor(
            loader, authed_pairs_fn=authed_pairs_fn)
        #: ``accesslog_fn(flow)``: sink for LOG-action accesslog records
        #: (the reference annotates the Envoy access log on a LOG
        #: header-match mismatch; ours emits the L7 flow to the hubble
        #: observer via this callback)
        self.accesslog_fn = accesslog_fn
        adm = getattr(loader.config, "admission", None)
        self.batcher = MicroBatcher(
            self._verdicts, batch_max=batch_max,
            deadline_ms=deadline_ms, drain_workers=drain_workers,
            max_pending=getattr(adm, "max_pending", 0), gate=gate)
        # has_proxy_actions memo, valid for ONE policy revision (reset
        # on revision change so dead snapshots aren't pinned alive)
        self._pa_cache: Dict = {}
        self._pa_revision = -1

    def _verdicts(self, flows: Sequence[Flow],
                  deadline: Optional[float] = None) -> Sequence[int]:
        if self.loader.engine is None:
            return [int(Verdict.DROPPED)] * len(flows)
        # breaker-guarded: a device failure serves this batch from the
        # oracle instead of erroring every queued request; the batch
        # deadline rides along for dispatch-side attribution
        return self.verdictor.verdicts(flows, deadline=deadline)

    def record_to_flow(self, conn: Connection, record) -> Flow:
        f = Flow(
            src_identity=conn.src_identity,
            dst_identity=conn.dst_identity,
            dport=conn.dport,
            protocol=Protocol.TCP,
            direction=(TrafficDirection.INGRESS if conn.ingress
                       else TrafficDirection.EGRESS),
        )
        if isinstance(record, HTTPInfo):
            f.l7, f.http = L7Type.HTTP, record
        elif isinstance(record, KafkaInfo):
            f.l7, f.kafka = L7Type.KAFKA, record
        elif isinstance(record, DNSInfo):
            f.l7, f.dns = L7Type.DNS, record
        elif isinstance(record, GenericL7Info):
            f.l7, f.generic = L7Type.GENERIC, record
        return f

    def http_proxy_actions(self, flow: Flow):
        """(rewrites, log) for an ALLOWED HTTP flow: the firing
        ADD/DELETE/REPLACE header-rewrite ops plus whether a LOG-action
        mismatch should annotate the access log (oracle and TPU engine
        share this host-side walk — it reads rule objects, which never
        leave the host). Gated on ``has_proxy_actions`` so policies
        with no mismatch actions (the common case) pay one cached set
        lookup, not a rule walk, per request."""
        from cilium_tpu.policy.oracle import (
            has_proxy_actions,
            http_proxy_actions,
            lookup_entry,
        )

        allowed, entry = lookup_entry(self.loader.per_identity, flow)
        if not allowed or entry is None or not entry.is_redirect:
            return [], False
        if self._pa_revision != self.loader.revision:
            self._pa_cache = {}
            self._pa_revision = self.loader.revision
        gate = self._pa_cache.get(entry.l7_rules)
        if gate is None:
            gate = self._pa_cache[entry.l7_rules] = \
                has_proxy_actions(entry.l7_rules)
        if not gate:
            return [], False
        secret_lookup = (self.loader.secrets.lookup
                         if self.loader.secrets is not None else None)
        return http_proxy_actions(entry.l7_rules, flow, secret_lookup)

    def policy_check(self, conn: Connection) -> Callable[[object], bool]:
        def check(record) -> bool:
            flow = self.record_to_flow(conn, record)
            v = self.batcher.check(flow)
            # AUDIT forwards: audit mode reports the would-be denial
            # but does not enforce it
            allowed = v in (int(Verdict.FORWARDED),
                            int(Verdict.REDIRECTED), int(Verdict.AUDIT))
            conn.pending_rewrites = []
            if allowed and flow.http is not None:
                rewrites, log = self.http_proxy_actions(flow)
                conn.pending_rewrites = rewrites
                if log and self.accesslog_fn is not None:
                    flow.verdict = Verdict(v)
                    self.accesslog_fn(flow)
            METRICS.inc("cilium_tpu_policy_l7_total",
                        labels={"proto": conn.proto,
                                "verdict": "allow" if allowed else "deny"})
            return allowed

        return check


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def send_msg(sock: socket.socket, obj: Dict) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Dict:
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    return json.loads(_recv_exact(sock, n))


class VerdictService:
    """The server. One instance wraps a Loader (oracle or TPU engine
    per the feature gate) and serves parsers/shims."""

    def __init__(self, loader: Loader, socket_path: str,
                 batch_max: int = 256, deadline_ms: float = 2.0,
                 agent=None, drain_workers: int = 1):
        self.loader = loader
        self.socket_path = socket_path
        self.agent = agent  # optional backref for introspection ops
        self.admission_config = getattr(loader.config, "admission",
                                        None)
        #: ONE breaker-guarded pipeline for every verdict path this
        #: service serves (batcher, bulk op, streams)
        self.verdictor = ResilientVerdictor(
            loader, authed_pairs_fn=(agent.auth.pairs_array
                                     if agent is not None else None))
        #: bounded admission in front of every verdict ingress; its
        #: depth_fn reads the real batcher backlog (len() is atomic —
        #: an instantaneous read is all the bound check needs)
        self.gate = admission.AdmissionGate.from_config(
            self.admission_config,
            depth_fn=lambda: len(self.bridge.batcher._pending))
        self.bridge = PolicyBridge(
            loader, batch_max=batch_max, deadline_ms=deadline_ms,
            authed_pairs_fn=(agent.auth.pairs_array
                             if agent is not None else None),
            accesslog_fn=(self._accesslog
                          if agent is not None else None),
            drain_workers=drain_workers, verdictor=self.verdictor,
            gate=self.gate)
        self._connections: Dict[int, Connection] = {}
        self._conn_lock = threading.Lock()
        self._server: Optional[socketserver.ThreadingUnixStreamServer] = None
        self._thread: Optional[threading.Thread] = None
        #: continuously-batched serving loop (runtime/serveloop.py),
        #: built lazily on the first stream once a device engine is
        #: serving — gated by Config.serve.enabled; stream sessions
        #: then dispatch through ring slot leases instead of private
        #: per-session state (verdict-bit-equal either way)
        self.serveloop = None
        self._serve_config = getattr(loader.config, "serve", None)

    def _ensure_serveloop(self):
        """The serve loop, when enabled and a device engine serves
        (None otherwise — sessions use their private dispatch)."""
        if not getattr(self._serve_config, "enabled", False):
            return None
        with self._conn_lock:
            if self.serveloop is None \
                    and hasattr(self.loader.engine, "_blob_step"):
                from cilium_tpu.runtime.serveloop import ServeLoop

                self.serveloop = ServeLoop.from_config(
                    self.loader, self._serve_config,
                    authed_pairs_fn=self.bridge.authed_pairs_fn,
                ).start()
            return self.serveloop

    def _accesslog(self, flow: Flow) -> None:
        """LOG-action sink: the annotated L7 flow lands in the agent's
        hubble observer ring (the reference's access-log path: Envoy →
        accesslog socket → pkg/hubble parser/seven)."""
        if not flow.time:
            flow.time = simclock.wall()
        from cilium_tpu.core.flow import PolicyMatchType

        flow.policy_match_type = PolicyMatchType.L7
        self.agent.observer.observe([flow])

    # -- stream mode ------------------------------------------------------
    def handle_stream(self, sock: socket.socket, req: Dict) -> None:
        """``stream_start``: ack, then hand the connection to a
        :class:`cilium_tpu.runtime.stream.StreamSession` until
        end-of-stream. The chunked binary path shares the engine (and
        its auth staging) with every other verdict path — only the
        transport differs."""
        from cilium_tpu.runtime.stream import StreamSession

        if self.loader.engine is None:
            send_msg(sock, {"error": "no policy loaded"})
            return
        ok, reason = self.gate.admit(admission.CLASS_DATA)
        if not ok:
            # a draining/overloaded service refuses NEW streams at the
            # handshake — existing sessions run to end-of-stream
            send_msg(sock, {"error": f"shed: {reason}", "shed": True,
                            "reason": reason})
            return
        # credit flow control: clients that opt in (``"credit": true``
        # in the hello) get a server-advertised chunk window; the
        # session grants a credit back per answered chunk, so a slow
        # consumer backpressures the producer instead of ballooning
        # server queues. Peers that don't opt in see neither the ack
        # field nor credit frames — unchanged interop.
        credit_window = 0
        if req.get("credit"):
            credit_window = int(getattr(
                self.admission_config, "stream_credit_window", 32))
        # "trace": this server accepts KIND_CHUNK_TRACED frames (the
        # flight-recorder id prefix) — clients only send them when
        # they see this, so old peers interoperate unchanged
        ack = {"ok": True, "revision": self.loader.revision,
               "trace": True}
        if credit_window > 0:
            ack["credit"] = credit_window
        send_msg(sock, ack)
        StreamSession(
            self.loader, sock,
            widths=req.get("widths") or None,
            authed_pairs_fn=self.bridge.authed_pairs_fn,
            pipeline_depth=int(req.get("pipeline_depth") or 8),
            verdictor=self.verdictor,
            credit_window=credit_window,
            serveloop=self._ensure_serveloop(),
        ).run()

    # -- request handling -------------------------------------------------
    def handle(self, req: Dict) -> Dict:
        op = req.get("op")
        try:
            if op in ("check", "verdict"):
                # verdict-path ingress: one trace per request, id
                # returned to the caller so client-side latency joins
                # the server-side phase spans
                with TRACER.trace(f"service.{op}") as ctx:
                    resp = self._handle(req)
                    if ctx is not None and "error" not in resp:
                        resp.setdefault("trace_id", ctx.trace_id)
                    return resp
            return self._handle(req)
        except Exception as e:  # malformed fields must not kill the conn
            return {"error": f"{type(e).__name__}: {e}"}

    def _handle(self, req: Dict) -> Dict:
        op = req.get("op")
        deadline = None
        if op in ("check", "verdict", "on_new_connection"):
            # data-path ingress: bounded admission + deadline
            # feasibility BEFORE any work. Control ops (ping, status,
            # policy, drain itself) never queue behind verdicts and
            # stay admitted during overload and drain.
            if op != "on_new_connection":
                deadline = admission.deadline_from_ms(
                    req.get("deadline_ms"),
                    getattr(self.admission_config,
                            "default_deadline_ms", 5000.0))
            ok, reason = self.gate.admit(admission.CLASS_DATA,
                                         deadline=deadline)
            if not ok:
                TRACER.add_span(TRACER.current(), "admission.shed",
                                PHASE_SHED, simclock.wall(), 0.0,
                                reason=reason)
                resp = {"shed": True, "reason": reason}
                if op == "check":
                    # explicit shed verdict: fail-closed for the
                    # caller, distinguishable from a policy DROP or a
                    # timeout ERROR by the shed flag
                    resp["verdict"] = int(Verdict.ERROR)
                else:
                    resp["error"] = f"shed: {reason}"
                return resp
        if op == "ping":
            return {"ok": True, "revision": self.loader.revision}
        if op == "drain":
            return self.drain()
        if op == "status":
            if self.agent is not None:
                status = self.agent.status()
                if isinstance(status, dict):
                    status.setdefault("banks",
                                      self.loader.bank_status())
                    if self.serveloop is not None:
                        status.setdefault("serve",
                                          self.serveloop.status())
                return status
            out = {"engine_revision": self.loader.revision,
                   "banks": self.loader.bank_status()}
            if self.serveloop is not None:
                out["serve"] = self.serveloop.status()
            return out
        if op == "explain":
            # the explain plane (runtime/explain.py): recorded
            # provenance for one trace id, re-resolved through the
            # CPU oracle at the current revision → served-vs-fresh
            from cilium_tpu.runtime.explain import resolve_explain

            tid = str(req.get("trace_id", "") or "")
            if not tid:
                return {"error": "explain needs trace_id"}
            return resolve_explain(self.loader, tid)
        if op == "metrics":
            return {"text": METRICS.expose()}
        if op == "mapstate_pull":
            # NPDS role (reference pkg/envoy xDS): the compiled L3/L4
            # MapState serialized for the shim's LOCAL fast path —
            # L4-only flows then verdict in-proxy with zero service
            # round-trips (runtime/npds.py documents blob + semantics)
            from cilium_tpu.runtime.npds import serialize_mapstates

            blob = serialize_mapstates(
                self.loader.per_identity, self.loader.revision,
                audit_global=self.loader.config.policy_audit_mode)
            METRICS.inc("cilium_tpu_npds_pulls_total")
            return {"revision": self.loader.revision,
                    "npds_b64": base64.b64encode(blob).decode()}
        if op == "policy_get":
            if self.agent is None:
                return {"error": "no agent attached"}
            return {"rules": [
                {"labels": list(r.labels), "description": r.description}
                for r in self.agent.repo.rules()
            ], "revision": self.agent.repo.revision}
        if op == "check":
            # single-record policy check through the MicroBatcher — the
            # per-request path a proxylib parser/shim sees (requests
            # coalesce across connections into one engine batch). The
            # wire deadline rides the queue entry: expire in the queue
            # and the entry is reaped before dispatch.
            flow = flow_from_dict(req.get("flow", {}))
            v, status = self.bridge.batcher.check_ex(
                flow, deadline=deadline)
            resp = {"verdict": v}
            if status in ("shed", "closed"):
                resp["shed"] = True
                resp["reason"] = (admission.SHED_QUEUE_FULL
                                  if status == "shed"
                                  else admission.SHED_DRAINING)
            return resp
        if op == "verdict":
            flows = [flow_from_dict(d) for d in req.get("flows", ())]
            if self.loader.engine is None:
                return {"error": "no policy loaded"}
            # breaker-guarded: device dispatch failures degrade this
            # request to the oracle lane instead of an error response
            out = self.verdictor.outputs(flows, deadline=deadline)
            verdicts = [int(v) for v in out["verdict"]]
            if self.agent is not None and flows:
                # the reference's datapath emits PolicyVerdictNotify
                # whenever policy evaluation happened, so
                # service-driven verdicts reach the monitor socket +
                # hubble ring like replayed ones
                self.agent.fan_out(flows, out)
            METRICS.inc("cilium_tpu_service_verdicts_total", len(flows))
            return {"verdicts": verdicts}
        if op == "on_new_connection":
            conn = Connection(
                proto=req["proto"],
                connection_id=int(req["conn"]),
                ingress=bool(req.get("ingress", True)),
                src_identity=int(req.get("src", 0)),
                dst_identity=int(req.get("dst", 0)),
                dport=int(req.get("dport", 0)),
                policy_name=req.get("policy_name", ""),
            )
            try:
                create_parser(req["proto"], conn,
                              self.bridge.policy_check(conn))
            except KeyError as e:
                return {"error": str(e)}
            with self._conn_lock:
                self._connections[conn.connection_id] = conn
            # the revision stamp is the shim's NPDS invalidation
            # signal (shim/cilium_shim.cpp re-pulls on mismatch)
            return {"ok": True, "revision": self.loader.revision}
        if op == "on_data":
            with self._conn_lock:
                conn = self._connections.get(int(req["conn"]))
            if conn is None:
                return {"error": f"unknown connection {req.get('conn')}"}
            data = base64.b64decode(req.get("data_b64", ""))
            ops = conn.on_data(bool(req.get("reply", False)),
                               bool(req.get("end", False)), data)
            resp = {"ops": [[int(o), int(n)] for o, n in ops]}
            inj = conn.take_inject(reply=True)
            if inj:
                resp["inject_b64"] = base64.b64encode(inj).decode()
            inj_req = conn.take_inject(reply=False)
            if inj_req:
                # upstream-bound bytes (rewritten request frames) ride
                # their own field so the shim never splices them into
                # the client-bound stream
                resp["inject_req_b64"] = \
                    base64.b64encode(inj_req).decode()
            return resp
        if op == "profile":
            # on-demand profiling of the serving process (pkg/pprof
            # analog; SURVEY §5.1) — blocks for `seconds`
            from cilium_tpu.runtime.profiling import (
                PROFILER,
                ProfileBusy,
            )

            try:
                return PROFILER.capture(
                    req.get("out", "/tmp/cilium_tpu_profile"),
                    seconds=float(req.get("seconds", 2.0)),
                    mode=req.get("mode", "host"),
                )
            except (ProfileBusy, ValueError) as e:
                return {"error": str(e)}
        if op == "bugtool":
            if self.agent is None:
                return {"error": "no agent attached"}
            from cilium_tpu.bugtool import collect
            path = collect(self.agent, req.get("out", "/tmp"),
                           archive=bool(req.get("archive", True)))
            return {"path": path}
        if op == "close_connection":
            with self._conn_lock:
                self._connections.pop(int(req.get("conn", -1)), None)
            return {"ok": True}
        return {"error": f"unknown op {op!r}"}

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        service = self
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):  # noqa: A003
                try:
                    while True:
                        try:
                            req = recv_msg(self.request)
                        except json.JSONDecodeError:
                            # malformed frame: answer with an error and
                            # drop the connection (framing is now
                            # unreliable), but never traceback
                            send_msg(self.request,
                                     {"error": "malformed request"})
                            return
                        if req.get("op") == "stream_start":
                            # switch this connection to the chunked
                            # binary verdict stream (runtime/stream.py)
                            # until end-of-stream; the connection is
                            # single-use in stream mode
                            service.handle_stream(self.request, req)
                            return
                        send_msg(self.request, service.handle(req))
                except (ConnectionError, struct.error, OSError):
                    pass

        self._server = socketserver.ThreadingUnixStreamServer(
            self.socket_path, Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def drain(self) -> Dict:
        """Graceful drain: stop admitting data-path work, flush — not
        error — pending batches through the engine, then snapshot the
        loader's warm state (revision + compiled policy + oracle
        snapshot) so a restarted service answers its first request
        verdict-identically without recompilation. Idempotent; the
        service keeps answering control ops (status, metrics, drain)
        afterwards. A fault injected at ``service.drain`` aborts
        between stop-admitting and the flush — the gate stays
        draining and the operator retries."""
        self.gate.begin_drain()
        faults.maybe_fail(DRAIN_POINT)
        timeout = getattr(self.admission_config, "drain_timeout_s",
                          30.0)
        flushed = self.bridge.batcher.drain(timeout=timeout)
        if self.serveloop is not None:
            # the ring drains too: pending packed chunks flush
            # through the engine, leases release
            flushed += self.serveloop.drain()
        warm = False
        if self.loader.revision > 0:
            warm = self.loader.snapshot_warm()
        METRICS.inc(DRAINS)
        TRACER.event("service.drained", flushed=flushed,
                     warm_snapshot=warm)
        LOG.info("service drained", extra={"fields": {
            "flushed": flushed, "warm_snapshot": warm,
            "revision": self.loader.revision}})
        return {"ok": True, "flushed": flushed,
                "warm_snapshot": warm,
                "revision": self.loader.revision}

    def stop(self, drain: bool = True) -> None:
        """Shutdown. ``drain=True`` (the default — Agent.stop and the
        daemon use it) flushes pending verdicts through the engine
        before stopping; ``drain=False`` is the crash-stop path
        (pending entries resolve ERROR)."""
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if drain:
            # flush quietly WITHOUT latching the gate into drain mode:
            # the socket server is already down, so nothing new is
            # admitted, and a later start() of this instance (tests do
            # this) must not find a permanently-draining gate — the
            # latched drain belongs to the explicit drain() op
            self.bridge.batcher.drain(timeout=getattr(
                self.admission_config, "drain_timeout_s", 30.0))
        self.bridge.batcher.close()
        if self.serveloop is not None:
            self.serveloop.stop()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)


class VerdictClient:
    """Python client for the service (what the C++ shim does in C)."""

    def __init__(self, socket_path: str):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(socket_path)
        self._lock = threading.Lock()

    def call(self, req: Dict) -> Dict:
        with self._lock:
            send_msg(self.sock, req)
            return recv_msg(self.sock)

    def close(self) -> None:
        self.sock.close()
