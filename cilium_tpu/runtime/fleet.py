"""Fleet-scale policy-plane churn driver (ISSUE 13 acceptance lane).

BASELINE configs[4] — "Cluster-mesh scale: 10k identities × 5k
CiliumNetworkPolicy, streaming verdicts on v5e-8" — as a churn STORM
through the live serving plane: ``identities`` endpoint identities
grouped into service classes (the distillery shape — production
meshes run thousands of pods over hundreds of distinct policy
shapes), ``cnps`` CNP-shaped L7 rules spread across the classes, and
a sustained add/delete update stream driven through one Loader + one
live capture-replay session while every update is checked for
staleness against the serving engine (and a sampled CPU oracle).

What the lane gates (`make churn-fleet`):

* **zero stale / zero ERROR verdicts** — the session is bit-equal to
  the serving engine after every committed update, and the sampled
  oracle agrees;
* **O(Δ) compile** — bank compiles per update stay within 1.1× the
  27-bank churn ratio (BENCH_CHURN_r06), i.e. two orders of magnitude
  more policy does NOT mean more work per change;
* **update→enforcement p99** ≤ 2× the 27-bank number (read from the
  committed BENCH_CHURN_r06.jsonl artifact);
* **bounded memory** — peak RSS under the declared bound (the sharded
  registry + fingerprint store + artifact-cache LRU are what make
  this hold at 5k-CNP pattern-universe scale).

One provenance-stamped line per run lands in
``BENCH_CHURN_FLEET_r07.jsonl`` (consumed by perf-report).
``tests/test_fleet.py`` runs the same driver at smoke scale inside
tier-1; the full scale rides ``make churn-fleet``.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

#: identities per service class at full scale: 10k identities over
#: 200 distinct resolved policies (the distillery dedup makes the
#: mapstate table scale with CLASSES; identity count scales only the
#: enforcement table)
DEFAULT_CLASS_SIZE = 50

#: declared peak-RSS bound for the full-scale lane, MiB
DEFAULT_MAX_RSS_MB = 8192

#: O(Δ) gate: compiles/update must stay within this factor of the
#: committed 27-bank churn ratio
ODELTA_FACTOR = 1.1

#: p99 gate: update→enforcement p99 must stay within this factor of
#: the committed 27-bank churn p99
P99_FACTOR = 2.0


def _baseline_churn(root: str) -> Tuple[float, float]:
    """(compiles_per_update, p99_ms) of the committed 27-bank churn
    lane — the denominators of the fleet gates. Reads every line of
    BENCH_CHURN_r06.jsonl and takes the max (re-runs vary with host
    load; gating against the most generous committed number keeps the
    gate about SCALING, not about host noise)."""
    path = os.path.join(root, "BENCH_CHURN_r06.jsonl")
    ratio, p99 = 0.929, 1158.772        # the committed r06 numbers
    try:
        with open(path) as fp:
            ratios, p99s = [], []
            for line in fp:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if d.get("metric") == "churn_update_p99_ms":
                    p99s.append(float(d["value"]))
                    if "compiles_per_update" in d:
                        ratios.append(float(d["compiles_per_update"]))
            if ratios:
                ratio = max(ratios)
            if p99s:
                p99 = max(p99s)
    except OSError:
        pass
    return ratio, p99


def _peak_rss_mb() -> float:
    import resource

    # ru_maxrss is KiB on Linux
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


class FleetWorld:
    """The resolved world: ``n_classes`` distinct policies shared by
    ``identities`` endpoint identities, ``cnps`` HTTP rules + one DNS
    rule per class, a live replay session over a sampled corpus."""

    def __init__(self, identities: int, cnps: int, cache_dir: str,
                 seed: int = 8, class_size: int = DEFAULT_CLASS_SIZE,
                 workers: int = 4):
        import numpy as np

        from cilium_tpu.core.config import Config
        from cilium_tpu.core.identity import IdentityAllocator
        from cilium_tpu.core.labels import LabelSet
        from cilium_tpu.runtime.loader import Loader

        self.rng = np.random.default_rng(seed)
        self.n_classes = max(1, min(identities,
                                    (identities + class_size - 1)
                                    // class_size))
        self.identities = identities
        self.cnps = cnps
        self.alloc = IdentityAllocator()
        self.web = self.alloc.allocate(LabelSet.from_dict(
            {"app": "web"}))
        #: class → list of (kind, pattern): the DESIRED rule state;
        #: CNP j lands in class j % n_classes
        self.rules_of: Dict[int, List[Tuple[str, str]]] = {
            c: [] for c in range(self.n_classes)}
        for j in range(cnps):
            c = j % self.n_classes
            self.rules_of[c].append(
                ("http", f"/cls{c}/cnp{j}/.*"))
        for c in range(self.n_classes):
            self.rules_of[c].append(("dns", f"cls{c}.corp.io"))
        #: fleet identity ids: synthetic, disjoint from the allocator
        #: range; identity i belongs to class i % n_classes
        self.ids = [100_000 + i for i in range(identities)]
        #: class → resolved MapState, REUSED across updates for
        #: unchanged classes (what makes the loader's fingerprint
        #: store O(Δ) — and what production resolvers achieve with
        #: their own per-endpoint caches)
        self._class_ms = {c: self._resolve_class(c)
                          for c in range(self.n_classes)}
        cfg = Config()
        cfg.enable_tpu_offload = True
        cfg.loader.cache_dir = cache_dir
        cfg.compile.workers = workers
        self.cfg = cfg
        self.loader = Loader(cfg)

    # -- policy -----------------------------------------------------------
    def _resolve_class(self, c: int):
        """One class's MapState via the real repository/resolver path
        (a fresh object per call — the immutability contract of the
        fingerprint store)."""
        from cilium_tpu.core.flow import Protocol
        from cilium_tpu.core.identity import IdentityAllocator
        from cilium_tpu.core.labels import LabelSet
        from cilium_tpu.policy.api import (
            EndpointSelector,
            IngressRule,
            PortProtocol,
            PortRule,
            Rule,
        )
        from cilium_tpu.policy.api.l7 import (
            L7Rules,
            PortRuleDNS,
            PortRuleHTTP,
        )
        from cilium_tpu.policy.mapstate import PolicyResolver
        from cilium_tpu.policy.repository import Repository
        from cilium_tpu.policy.selectorcache import SelectorCache

        http = tuple(PortRuleHTTP(path=p, method="GET")
                     for k, p in self.rules_of[c] if k == "http")
        dns = tuple(PortRuleDNS(match_name=p)
                    for k, p in self.rules_of[c] if k == "dns")
        repo = Repository()
        repo.add([Rule(
            endpoint_selector=EndpointSelector.from_labels(
                app=f"cls{c}"),
            ingress=(IngressRule(
                from_endpoints=(
                    EndpointSelector.from_labels(app="web"),),
                to_ports=(
                    PortRule(ports=(PortProtocol(80, Protocol.TCP),),
                             rules=L7Rules(http=http)),
                    PortRule(ports=(PortProtocol(53, Protocol.UDP),),
                             rules=L7Rules(dns=dns)),)),),
        )], sanitize=False)
        # a private allocator whose "web" maps to the SAME identity id
        # as the world's (first allocation is deterministic), so every
        # class's entries key on one peer id
        alloc = IdentityAllocator()
        web = alloc.allocate(LabelSet.from_dict({"app": "web"}))
        assert web == self.web
        cls_id = alloc.allocate(LabelSet.from_dict({"app": f"cls{c}"}))
        resolver = PolicyResolver(repo, SelectorCache(alloc))
        return resolver.resolve(alloc.lookup(cls_id))

    def per_identity(self) -> Dict[int, object]:
        return {ep: self._class_ms[i % self.n_classes]
                for i, ep in enumerate(self.ids)}

    # -- traffic ----------------------------------------------------------
    def _http(self, ep: int, path: str):
        from cilium_tpu.core.flow import (
            Flow,
            HTTPInfo,
            L7Type,
            Protocol,
            TrafficDirection,
        )

        return Flow(src_identity=self.web, dst_identity=ep,
                    dport=80, protocol=Protocol.TCP,
                    direction=TrafficDirection.INGRESS, l7=L7Type.HTTP,
                    http=HTTPInfo(method="GET", path=path))

    def _dns(self, ep: int, qname: str):
        from cilium_tpu.core.flow import (
            DNSInfo,
            Flow,
            L7Type,
            Protocol,
            TrafficDirection,
        )

        return Flow(src_identity=self.web, dst_identity=ep,
                    dport=53, protocol=Protocol.UDP,
                    direction=TrafficDirection.INGRESS, l7=L7Type.DNS,
                    dns=DNSInfo(query=qname))

    def corpus(self, sample_ids: int = 48, repeat: int = 20):
        """A FIXED serving corpus over identities sampled across
        classes: allowed paths, never-allowed probes, DNS — repeated
        to capture-replay dedup shape."""
        flows = []
        step = max(1, len(self.ids) // max(1, sample_ids))
        for i in range(0, len(self.ids), step):
            ep = self.ids[i]
            c = i % self.n_classes
            pats = [p for k, p in self.rules_of[c]
                    if k == "http"][:3]
            for p in pats:
                flows.append(self._http(ep, p.replace("/.*", "/x")))
            flows.append(self._http(ep, "/never/allowed"))
            flows.append(self._dns(ep, f"cls{c}.corp.io"))
            flows.append(self._dns(ep, "evil.example"))
        return flows * repeat, len(flows)


def run(identities: int, cnps: int, updates: int, cache_dir: str,
        seed: int = 8, workers: int = 4,
        max_rss_mb: float = DEFAULT_MAX_RSS_MB,
        gate_p99: bool = True, root: str = ".",
        progress=print) -> Dict:
    """Drive the storm; returns the result dict (also asserted —
    a gate failure raises AssertionError)."""
    import numpy as np

    from cilium_tpu.core.flow import Verdict
    from cilium_tpu.engine.verdict import CaptureReplay
    from cilium_tpu.ingest.columnar import flows_to_columns

    t_start = time.perf_counter()
    world = FleetWorld(identities, cnps, cache_dir, seed=seed,
                       workers=workers)
    loader = world.loader
    base_ratio, base_p99 = _baseline_churn(root)

    t0 = time.perf_counter()
    loader.regenerate(world.per_identity(), revision=1)
    cold_s = time.perf_counter() - t0
    banks_t0 = sum(len(k) for k in loader._bank_plan.values())
    compiles_t0 = loader.bank_registry.compiles
    progress(f"[fleet] t0: {identities} ids x {cnps} cnps "
             f"({world.n_classes} classes, {banks_t0} banks) "
             f"cold build {cold_s:.1f}s")

    flows, distinct = world.corpus()
    cols = flows_to_columns(flows)
    replay = CaptureReplay(loader.engine, cols.l7, cols.offsets,
                           cols.blob, world.cfg.engine, gen=cols.gen,
                           loader=loader)
    replay.stage_rows(cols.rec, cols.l7)
    replay.stage_unique()

    def session_verdicts():
        out = replay.verdict_chunk(cols.rec, cols.l7)
        # one bulk readback, then host ints — not one sync per row
        return [int(v) for v in np.asarray(out["verdict"])]

    def engine_verdicts(fl):
        return [int(v) for v in
                np.asarray(loader.engine.verdict_flows(fl)["verdict"])]

    base = session_verdicts()
    assert int(Verdict.ERROR) not in base, "ERROR at t0"
    assert base == engine_verdicts(flows), "session stale at t0"

    rng = world.rng
    added: List[Tuple[int, str]] = []
    update_ms: List[float] = []
    schedule = []
    changes = 0
    for step in range(updates):
        c = int(rng.integers(world.n_classes))
        if added and (step % 3 == 2):          # delete a churned rule
            j = int(rng.integers(len(added)))
            c, pat = added.pop(j)
            world.rules_of[c].remove(("http", pat))
            probe = None
        else:                                  # CNP add
            pat = f"/cls{c}/churn{step}/.*"
            world.rules_of[c].append(("http", pat))
            added.append((c, pat))
            probe = world._http(world.ids[c], pat.replace("/.*", "/x"))
        # only the touched class re-resolves — every other identity
        # keeps its MapState object, so the loader fingerprints O(Δ)
        world._class_ms[c] = world._resolve_class(c)
        changes += 1
        schedule.append((step, c, pat))
        t1 = time.perf_counter()
        loader.regenerate(world.per_identity(), revision=2 + step)
        if probe is not None:
            got = engine_verdicts([probe])
            assert got == [5], f"new rule not enforced: {got}"
        update_ms.append((time.perf_counter() - t1) * 1e3)
        got = session_verdicts()
        assert int(Verdict.ERROR) not in got, f"ERROR at step {step}"
        assert got == engine_verdicts(flows), f"stale at step {step}"
        if step % 10 == 0 or step == updates - 1:
            sample = flows[:distinct]
            oracle = loader.fallback_engine
            want = [int(v) for v in
                    oracle.verdict_flows(sample)["verdict"]]
            assert got[:distinct] == want, f"oracle mismatch @ {step}"
        if (step + 1) % 10 == 0:
            progress(f"[fleet] {step + 1}/{updates} updates, "
                     f"p50 so far "
                     f"{sorted(update_ms)[len(update_ms) // 2]:.0f}ms")

    # -- gates ------------------------------------------------------------
    fleet_compiles = loader.bank_registry.compiles - compiles_t0
    per_update = fleet_compiles / max(1, changes)
    ratio_bound = ODELTA_FACTOR * base_ratio
    assert per_update <= ratio_bound, (
        f"O(Δ) broke at fleet scale: {per_update:.3f} compiles/update "
        f"> {ratio_bound:.3f} (= {ODELTA_FACTOR} x the 27-bank "
        f"{base_ratio:.3f})")

    m = replay.memo
    hit_ratio = (m.hits / max(1, m.hits + m.misses)) if m else 0.0

    p99 = sorted(update_ms)[min(len(update_ms) - 1,
                                int(0.99 * len(update_ms)))]
    p50 = sorted(update_ms)[len(update_ms) // 2]
    p99_bound = P99_FACTOR * base_p99
    if gate_p99:
        assert p99 <= p99_bound, (
            f"update->enforcement p99 {p99:.0f}ms blew the bound "
            f"{p99_bound:.0f}ms (= {P99_FACTOR} x the 27-bank "
            f"{base_p99:.0f}ms) at {identities} ids x {cnps} cnps")

    rss_mb = _peak_rss_mb()
    assert rss_mb <= max_rss_mb, (
        f"peak RSS {rss_mb:.0f}MiB over the declared bound "
        f"{max_rss_mb}MiB — the plane is not serving in bounded "
        f"memory")

    st = loader.bank_status()
    result = {
        "metric": "churn_fleet_update_p99_ms",
        "value": round(p99, 3),
        "unit": "ms update->enforcement p99",
        "lane": "churn-fleet",
        "identities": identities,
        "cnps": cnps,
        "classes": world.n_classes,
        "updates": updates,
        "banks_t0": banks_t0,
        "cold_build_s": round(cold_s, 3),
        "bank_compiles": fleet_compiles,
        "compiles_per_update": round(per_update, 3),
        "odelta_bound": round(ratio_bound, 3),
        "baseline_ratio_r06": base_ratio,
        "p50_ms": round(p50, 3),
        "p99_bound_ms": round(p99_bound, 3),
        "baseline_p99_r06_ms": base_p99,
        "p99_gated": bool(gate_p99),
        "memo_hit_ratio": round(hit_ratio, 6),
        "rss_peak_mb": round(rss_mb, 1),
        "rss_bound_mb": max_rss_mb,
        "registry_bytes": st.get("bytes"),
        "registry_evictions": st.get("evictions"),
        "artifact_hits": st.get("artifact_hits"),
        "compile_queue": st.get("queue"),
        "fp_store": st.get("fp_store"),
        "wall_s": round(time.perf_counter() - t_start, 1),
        "schedule_digest": hashlib.sha256(
            json.dumps(schedule, sort_keys=True).encode()
        ).hexdigest()[:16],
    }
    loader.close()
    return result


def main(argv: Optional[List[str]] = None) -> int:
    import tempfile

    ap = argparse.ArgumentParser(
        description="fleet-scale policy-plane churn lane "
                    "(10k identities x 5k CNP)")
    ap.add_argument("--identities", type=int, default=10000)
    ap.add_argument("--cnps", type=int, default=5000)
    ap.add_argument("--updates", type=int, default=56)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--seed", type=int, default=8)
    ap.add_argument("--max-rss-mb", type=float,
                    default=DEFAULT_MAX_RSS_MB)
    ap.add_argument("--no-p99-gate", action="store_true",
                    help="skip the p99 gate (smoke scales, where the "
                         "27-bank baseline is not comparable)")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    with tempfile.TemporaryDirectory(prefix="ct_fleet_") as cache:
        result = run(args.identities, args.cnps, args.updates, cache,
                     seed=args.seed, workers=args.workers,
                     max_rss_mb=args.max_rss_mb,
                     gate_p99=not args.no_p99_gate)
    from cilium_tpu.runtime.provenance import stamp

    os.environ["CILIUM_TPU_DST_SEED"] = str(args.seed)
    os.environ["CILIUM_TPU_DST_DIGEST"] = result["schedule_digest"]
    line = stamp(dict(result))
    if args.out:
        with open(args.out, "a") as fp:
            fp.write(json.dumps(line) + "\n")
    print(f"[fleet] OK: {args.identities} ids x {args.cnps} cnps, "
          f"{args.updates} updates — p99 {result['value']:.0f}ms "
          f"(bound {result['p99_bound_ms']:.0f}), "
          f"{result['compiles_per_update']} compiles/update "
          f"(bound {result['odelta_bound']}), "
          f"RSS {result['rss_peak_mb']:.0f}MiB, "
          f"memo hit {result['memo_hit_ratio']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
