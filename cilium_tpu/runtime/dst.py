"""Deterministic simulation testing: seeded fault-schedule search over
the serving plane.

``make chaos``/``make churn`` replay the handful of fault schedules a
human had patience to write; this module SEARCHES the schedule space.
A schedule is a seeded list of events — fault arms at named injection
points (runtime/faults.py), policy churn, identity churn storms,
traffic rounds, drain→warm-restore cycles, virtual-time advances —
executed against a small but real serving world (Loader + compiled
engine + circuit breaker + capture-replay session + kvstore) under a
driven :class:`~cilium_tpu.runtime.simclock.VirtualClock`, with
standing invariants checked after every event:

* **Oracle agreement** — served verdicts match a freshly-resolved CPU
  oracle of the COMMITTED rule set whenever the loader is not
  degraded (no stale reads, whatever faults fired), and are never
  ERROR.
* **Fail closed** — under bank quarantine the plane may deny more,
  never serve ERROR; probes for never-allowed traffic always deny.
* **Session honesty** — the live replay session's verdicts are
  bit-equal to the serving engine's, and its memo accounting
  (hits+misses == lookups) never lies.
* **O(Δ) compile** — bank compiles grow with the CHANGE count, never
  with policy size × updates.
* **Explanation honesty** — every sampled ring-served verdict's
  provenance is trustworthy: the cited rule re-resolves to the served
  verdict under the committed rule set AT THE CITED GENERATION, rows
  computed this round cite the current generation, and memo-served
  rows cite the (possibly older) generation they were actually
  computed under — the exact staleness class the PR-11 review found
  by hand, now searched continuously.
* **Liveness** — with faults exhausted, bounded virtual time recovers
  everything: the breaker re-closes past its probe interval and
  quarantined banks clear past their TTL.

Determinism: the same ``CILIUM_TPU_DST_SEED`` replays a byte-identical
event trace (pinned across runs AND ``PYTHONHASHSEED``\\ s by
tests/dst/). A violating schedule is shrunk by delta debugging
(:func:`shrink`) to a minimal event list and emitted as a committable
JSON regression case. Planted-bug validation
(``faults.mutation_active``) re-introduces a known fixed bug behind
``CILIUM_TPU_DST_MUTATION`` and proves the search catches it within a
bounded seed budget.

``make dst`` sweeps ``Config.dst.schedules`` seeds and writes one
provenance-stamped summary line (the perf ledger ties any later
regression back to the schedule that exposed it via the
``dst_seed``/``schedule_digest`` stamp — runtime/provenance.py).
"""

from __future__ import annotations

import argparse
import functools
import hashlib
import json
import os
import random
import sys
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from cilium_tpu.runtime import faults, simclock


@functools.lru_cache(maxsize=1)
def _ref_step():
    """Memoized single-device reference step for the multichip arm —
    one jit wrapper for the process (ctlint recompile-hazard)."""
    import jax

    from cilium_tpu.engine.verdict import verdict_step

    return jax.jit(verdict_step)

#: schedule format epoch, stamped on every trace + shrunken case
SCHEDULE_FORMAT = 1

#: injection points the generator arms (all pre-registered by their
#: owning modules)
FAULT_POINTS = (
    "engine.dispatch",
    "loader.swap",
    "loader.bank_compile",
    "kvstore.churn_storm",
    "serve.lease",
    "serve.ring_slot",
    # ISSUE 13 — the fleet compile plane's fault surface: a worker
    # dying mid-compile (retried with backoff; exhaustion quarantines
    # with cover) and a lost/corrupt distributed bank artifact
    # (degrades to a counted recompile)
    "compile.worker",
    "artifact.fetch",
    # ISSUE 15 — the cross-cluster surface: a remote-cluster event
    # ingest dying mid-delivery (isolated by the kvstore watch; the
    # re-announce repairs it) and a publisher heartbeat miss (the
    # lease keeps state alive until the next beat)
    "clustermesh.session",
    "clustermesh.heartbeat",
    # ISSUE 16 — the horizontal serving fleet's fault surface: a lost
    # replica heartbeat (suspicion runs on the virtual clock; aging
    # past the TTL is a fail-closed death + handoff) and a handoff
    # interrupted mid-re-grant (the un-re-granted remainder rides the
    # client resume protocol instead of double-granting)
    "fleet.heartbeat",
    "fleet.handoff",
    # ISSUE 20 — the multi-tenant control plane's fault surface: a
    # lost per-tenant quota-store read (falls to the conservative
    # default share, never unbounded) and a failed shadow dispatch
    # (aborts the canary safely; serving generation N untouched)
    "tenant.quota",
    "canary.dispatch",
)

#: breaker/quarantine timings the schedules steer around; small so
#: liveness checks cross them with single advances
PROBE_INTERVAL_S = 5.0
QUARANTINE_TTL_S = 30.0

#: virtual advances the generator picks from — chosen to straddle the
#: probe interval and quarantine TTL boundaries
ADVANCES = (0.5, 2.0, 6.0, 31.0)

#: bank compiles per committed change the O(Δ) invariant tolerates
#: (matches the `make churn` acceptance bound)
COMPILES_PER_CHANGE_BOUND = 4.0


class InvariantViolation(AssertionError):
    """One failed standing invariant, anchored to the event index."""

    def __init__(self, index: int, name: str, detail: str):
        super().__init__(f"event {index}: [{name}] {detail}")
        self.index = index
        self.invariant = name
        self.detail = detail


class SchedulePlan(faults.FaultPlan):
    """A FaultPlan armed incrementally by schedule events: each
    ``arm`` grants a point N one-shot fires consumed by its next
    hits. Decisions are a pure function of the arm/hit sequence, so
    the recorded trace replays byte-identically."""

    def __init__(self):
        super().__init__(rules=(), seed=0)
        self._budget: Dict[str, int] = {}
        self._lock = threading.Lock()
        #: (point, hit-ordinal-at-fire) — the replayable fire log
        self.fires: List[Tuple[str, int]] = []
        self._hits: Dict[str, int] = {}

    def arm(self, point: str, times: int = 1) -> None:
        with self._lock:
            self._budget[point] = self._budget.get(point, 0) + times

    def disarm_all(self) -> None:
        with self._lock:
            self._budget.clear()

    def check(self, point: str) -> Optional[Exception]:
        with self._lock:
            idx = self._hits.get(point, 0)
            # ctlint: disable=unbounded-registry  # keyed by registered fault points (finite)
            self._hits[point] = idx + 1
            left = self._budget.get(point, 0)
            if left <= 0:
                return None
            self._budget[point] = left - 1
            self.fires.append((point, idx))
        return faults.FaultInjected(
            f"dst scheduled fault at {point} (hit {idx})")


# -- the world ---------------------------------------------------------------


class DSTWorld:
    """A small, real slice of the serving plane: resolved policy →
    Loader → compiled engine + CPU oracle, breaker-guarded verdictor,
    a live capture-replay session with the device-resident memo, and
    a kvstore-backed identity allocator. Everything time-driven reads
    the installed (virtual) clock."""

    N_IDS = 3
    BASE_PATHS = 4

    def __init__(self, cache_dir: str):
        from cilium_tpu.core.config import Config
        from cilium_tpu.core.identity import IdentityAllocator
        from cilium_tpu.core.labels import LabelSet
        from cilium_tpu.runtime.loader import Loader
        from cilium_tpu.runtime.service import (
            CircuitBreaker,
            ResilientVerdictor,
        )

        cfg = Config()
        cfg.enable_tpu_offload = True
        cfg.engine.bank_size = 2       # many small banks: O(Δ) visible
        cfg.loader.cache_dir = cache_dir
        cfg.loader.bank_quarantine_ttl_s = QUARANTINE_TTL_S
        cfg.breaker.failure_threshold = 2
        cfg.breaker.probe_interval = PROBE_INTERVAL_S
        # ONE compile worker: the queue machinery (deadlines, backoff,
        # priority pops, worker-death respawn) is fully armed, but
        # per-bank fault ATTRIBUTION stays a pure function of the
        # schedule — with N workers racing, WHICH bank an armed
        # loader.bank_compile/compile.worker fault hits would depend
        # on thread scheduling and byte-identical replay would break
        cfg.compile.workers = 1
        self.cfg = cfg
        self.alloc = IdentityAllocator()
        self.web = self.alloc.allocate(LabelSet.from_dict({"app": "web"}))
        self.dbs = [self.alloc.allocate(
            LabelSet.from_dict({"app": f"db{i}"}))
            for i in range(self.N_IDS)]
        #: identity index → list of (kind, pattern); the DESIRED
        #: state. The protocol-frontend kinds (ISSUE 15) put one
        #: cassandra/memcache/r2d2 rule per identity in the BASE
        #: policy, so the oracle-agreement + fail-closed invariants
        #: arm over the new families on every schedule and a
        #: loader.bank_compile fault can land on an l7g bank
        self.rules_of = {
            i: [("http", f"/svc{i}/p{j}/.*")
                for j in range(self.BASE_PATHS)]
            + [("dns", f"api{i}.corp.io"),
               ("cass", f"tbl{i}"), ("mc", f"k{i}"),
               ("r2d2", f"f{i}.dat")]
            for i in range(self.N_IDS)}
        # ISSUE 20: the world is TENANT-PARTITIONED — db0 is tenant
        # "a", db1 is tenant "b", db2 rides the default namespace.
        # Partitioning is on for EVERY schedule (the namespaced bank
        # planner lives inside the whole searched fault space), and
        # the `tenant` arm proves A's faults never move B's verdicts,
        # banks, or admission outcomes.
        cfg.tenant.enabled = True
        cfg.tenant.ranges = (f"a:{self.dbs[0]}-{self.dbs[0]}",
                             f"b:{self.dbs[1]}-{self.dbs[1]}")
        #: the last state a successful commit (or warm restore) staged
        #: — the oracle the serving plane is held to
        self.committed = {i: list(v) for i, v in self.rules_of.items()}
        self.loader = Loader(cfg)
        self.loader.regenerate(self._resolve(), revision=1)
        self.revision = 1
        self.breaker = CircuitBreaker(
            failure_threshold=cfg.breaker.failure_threshold,
            probe_interval=cfg.breaker.probe_interval)
        self.verdictor = ResilientVerdictor(self.loader,
                                            breaker=self.breaker)
        self._session = None
        self._session_cols = None
        #: bank compiles carried across warm-restart loader swaps so
        #: the O(Δ) bound sees the whole schedule's work
        self._compiles_carry = 0
        self.compiles0 = self.bank_compiles()
        self.changes = 0
        #: regenerate ATTEMPTS (committed, rolled back, and liveness
        #: retries alike) — the denominator of the O(Δ) bound: every
        #: attempt may compile its delta, rollbacks included
        self.attempts = 0
        #: kvstore identity plane for churn storms
        from cilium_tpu.identity_kvstore import ClusterIdentityAllocator
        from cilium_tpu.kvstore import KVStore

        self.store = KVStore()
        self.cluster_alloc = ClusterIdentityAllocator(self.store).start()
        self.storm_pool = [LabelSet.from_dict({"storm": f"s{i}"})
                           for i in range(8)]
        #: lazily-built clustermesh slice (publisher → kvstore →
        #: remote watcher, ISSUE 15): (store, remote ipcache, local
        #: ipcache, publisher, RemoteCluster)
        self._mesh = None
        self._mesh_n = 0
        #: lazily-built continuously-batched serving loop
        #: (runtime/serveloop.py) — a SMALL ring (capacity 4, short
        #: lease TTL) so ring-full sheds and TTL expiries are
        #: reachable inside a 12-event schedule; dropped on
        #: drain-restore (a restarted process builds a fresh one)
        self._serve = None
        self._serve_streams = 0
        #: lazily-built horizontal serving fleet (ISSUE 16,
        #: runtime/fleetserve.py): 3 simulated host replicas SHARING
        #: this world's loader behind a stream-affinity router, tiny
        #: rings so saturation/spill/shed are reachable in-schedule;
        #: dropped on drain-restore like the single loop
        self._fleet = None
        #: generation → (committed rules at that epoch, degraded?) —
        #: the explanation-honesty invariant's re-resolve base:
        #: memo-served rows cite the generation they were computed
        #: under, and the cited rule set must still produce the
        #: served verdict. Recorded lazily at every serve round (the
        #: only place ring memo fills happen), bounded.
        self._gen_snapshots: Dict[int, tuple] = {}
        self._serve_gen = -1

    def bank_compiles(self) -> int:
        """Compile-or-fetch WORK units: with bank artifacts on, a
        wholesale membership shift can serve from artifacts compiled
        earlier in the same schedule — cheaper than recompiling, but
        still O(policy) plan churn. The O(Δ) invariant bounds work
        per change, so fetches count (a fetch-masked positional-banks
        regression must still trip it — tests/dst/test_planted.py)."""
        reg = self.loader.bank_registry
        return self._compiles_carry + (
            (reg.compiles + reg.artifact_hits) if reg else 0)

    # -- policy ----------------------------------------------------------
    def _resolve(self):
        from cilium_tpu.core.flow import Protocol
        from cilium_tpu.policy.api import (
            EndpointSelector,
            IngressRule,
            PortProtocol,
            PortRule,
            Rule,
        )
        from cilium_tpu.policy.api.l7 import (
            L7Rules,
            PortRuleDNS,
            PortRuleHTTP,
        )
        from cilium_tpu.policy.mapstate import PolicyResolver
        from cilium_tpu.policy.repository import Repository
        from cilium_tpu.policy.selectorcache import SelectorCache

        from cilium_tpu.policy.api.l7 import PortRuleL7

        repo = Repository()
        rules = []
        for i in range(self.N_IDS):
            http = tuple(PortRuleHTTP(path=p, method="GET")
                         for k, p in self.rules_of[i] if k == "http")
            dns = tuple(PortRuleDNS(match_name=p)
                        for k, p in self.rules_of[i] if k == "dns")
            cass = tuple(PortRuleL7.from_dict(
                {"query_action": "select", "query_table": p})
                for k, p in self.rules_of[i] if k == "cass")
            mc = tuple(PortRuleL7.from_dict({"cmd": "get", "key": p})
                       for k, p in self.rules_of[i] if k == "mc")
            r2 = tuple(PortRuleL7.from_dict(
                {"cmd": "READ", "file": p})
                for k, p in self.rules_of[i] if k == "r2d2")
            ports = [
                PortRule(ports=(PortProtocol(80, Protocol.TCP),),
                         rules=L7Rules(http=http)),
                PortRule(ports=(PortProtocol(53, Protocol.UDP),),
                         rules=L7Rules(dns=dns)),
            ]
            for proto, port, rr in (("cassandra", 9042, cass),
                                    ("memcache", 11211, mc),
                                    ("r2d2", 4040, r2)):
                if rr:
                    ports.append(PortRule(
                        ports=(PortProtocol(port, Protocol.TCP),),
                        rules=L7Rules(l7proto=proto, l7=rr)))
            rules.append(Rule(
                endpoint_selector=EndpointSelector.from_labels(
                    app=f"db{i}"),
                ingress=(IngressRule(
                    from_endpoints=(
                        EndpointSelector.from_labels(app="web"),),
                    to_ports=tuple(ports)),),
            ))
        repo.add(rules, sanitize=False)
        resolver = PolicyResolver(repo, SelectorCache(self.alloc))
        return {db: resolver.resolve(self.alloc.lookup(db))
                for db in self.dbs}

    def _http(self, i: int, path: str):
        from cilium_tpu.core.flow import (
            Flow,
            HTTPInfo,
            L7Type,
            Protocol,
            TrafficDirection,
        )

        return Flow(src_identity=self.web, dst_identity=self.dbs[i],
                    dport=80, protocol=Protocol.TCP,
                    direction=TrafficDirection.INGRESS, l7=L7Type.HTTP,
                    http=HTTPInfo(method="GET", path=path))

    def _dns(self, i: int, qname: str):
        from cilium_tpu.core.flow import (
            DNSInfo,
            Flow,
            L7Type,
            Protocol,
            TrafficDirection,
        )

        return Flow(src_identity=self.web, dst_identity=self.dbs[i],
                    dport=53, protocol=Protocol.UDP,
                    direction=TrafficDirection.INGRESS, l7=L7Type.DNS,
                    dns=DNSInfo(query=qname))

    #: frontend probe shapes per rules_of kind: (l7proto, dport,
    #: record-fields builder). The record matching the committed
    #: pattern must be ALLOWED; the fixed never-records are the
    #: fail-closed canaries of the new families.
    _FE_KINDS = {
        "cass": ("cassandra", 9042,
                 lambda p: {"query_action": "select",
                            "query_table": p}),
        "mc": ("memcache", 11211, lambda p: {"cmd": "get", "key": p}),
        "r2d2": ("r2d2", 4040, lambda p: {"cmd": "READ", "file": p}),
    }

    def _fe(self, i: int, proto: str, dport: int, fields):
        from cilium_tpu.core.flow import (
            Flow,
            GenericL7Info,
            L7Type,
            Protocol,
            TrafficDirection,
        )

        return Flow(src_identity=self.web, dst_identity=self.dbs[i],
                    dport=dport, protocol=Protocol.TCP,
                    direction=TrafficDirection.INGRESS,
                    l7=L7Type.GENERIC,
                    generic=GenericL7Info(proto=proto,
                                          fields=dict(fields)))

    def corpus(self):
        """The probe corpus: every pattern in the UNION of committed
        and desired states, plus never-allowed probes. Probing
        desired-but-rolled-back patterns is what catches a plane
        serving an aborted revision (it allows what the committed
        oracle denies); the fixed probes are the fail-closed
        canaries. Deterministic order. The frontend kinds (ISSUE 15)
        probe their families the same way — the oracle here is the
        parser-semantics CPU matcher, so oracle-agreement covers the
        l7g automaton + enum-predicate lowering end to end."""
        flows = []
        for i in range(self.N_IDS):
            pats = list(self.committed[i])
            pats += [kp for kp in self.rules_of[i] if kp not in pats]
            for kind, pat in pats:
                if kind == "http":
                    flows.append(self._http(
                        i, pat.replace("/.*", "/x")))
                elif kind == "dns":
                    flows.append(self._dns(i, pat))
                else:
                    proto, dport, mk = self._FE_KINDS[kind]
                    flows.append(self._fe(i, proto, dport, mk(pat)))
            flows.append(self._http(i, "/never/allowed"))
            flows.append(self._dns(i, "evil.example"))
            flows.append(self._fe(i, "cassandra", 9042,
                                  {"query_action": "drop",
                                   "query_table": "forbidden"}))
            flows.append(self._fe(i, "memcache", 11211,
                                  {"cmd": "flush_all"}))
            flows.append(self._fe(i, "r2d2", 4040, {"cmd": "HALT"}))
        return flows

    def oracle_verdicts(self, flows) -> List[int]:
        """Ground truth: an OracleVerdictEngine over a FRESH resolve
        of the committed rule set — independent of every staged/cached
        structure the faults may have corrupted."""
        from cilium_tpu.policy.oracle import OracleVerdictEngine

        saved = {i: list(v) for i, v in self.rules_of.items()}
        self.rules_of = {i: list(v) for i, v in self.committed.items()}
        try:
            per_identity = self._resolve()
        finally:
            self.rules_of = saved
        oracle = OracleVerdictEngine(per_identity)
        return [int(v) for v in
                oracle.verdict_flows(flows)["verdict"]]

    # -- event executors --------------------------------------------------
    def churn(self, op: str, i: int, step: int) -> Dict:
        """One policy update (add/delete a pattern) committed through
        the loader; a swap/bank fault may make it roll back or commit
        degraded — both recorded."""
        if op == "delete":
            extras = [(k, p) for k, p in self.rules_of[i]
                      if "/churn" in p or p.startswith("churn")
                      or p.startswith("ctbl")]
            if not extras:
                op = "add"  # nothing churned-in yet: degrade to add
            else:
                self.rules_of[i].remove(extras[0])
        if op == "add":
            # every 4th churned-in pattern lands on the cassandra
            # frontend (ISSUE 15): l7g bank churn rides the same O(Δ)
            # bound, bank-compile faults, and memo-refill machinery
            # as the http banks
            if step % 4 == 3:
                self.rules_of[i].append(("cass", f"ctbl{step}"))
            else:
                self.rules_of[i].append(("http", f"/churn{step}/.*"))
        self.revision += 1
        rolled_back = False
        reg = self.loader.bank_registry
        quarantined_before = reg.status()["quarantined"] if reg else 0
        # a registry with no cached groups (fresh process after a warm
        # restore) legitimately compiles the whole plan on its first
        # build — the adjacency bound only holds for a warm registry
        warm_registry = bool(reg and reg.status()["groups"])
        compiles_before = self.bank_compiles()
        self.attempts += 1
        try:
            self.loader.regenerate(self._resolve(),
                                   revision=self.revision)
        except Exception:
            # rollback path: the previous revision keeps serving and
            # the DESIRED state stays un-committed
            rolled_back = True
        else:
            self.committed = {j: list(v)
                              for j, v in self.rules_of.items()}
            self.changes += 1
        compiles = self.bank_compiles() - compiles_before
        if not warm_registry:
            # cold-start rebuild: baseline it out of the O(Δ) window
            self.compiles0 += compiles
            self.attempts -= 1
        quarantined_after = reg.status()["quarantined"] if reg else 0
        if (op == "delete" and not rolled_back and warm_registry
                and quarantined_before == 0 and quarantined_after == 0
                and compiles > COMPILES_PER_CHANGE_BOUND):
            # the content-defined partition's core property: a delete
            # perturbs only the adjacent bank(s). The positional-banks
            # planted bug shifts every later bank and trips this.
            raise InvariantViolation(
                step, "o-delta-compile",
                f"one clean delete compiled {compiles} banks "
                f"(> {COMPILES_PER_CHANGE_BOUND}: membership shifted "
                f"wholesale)")
        return {"op": op, "identity": i, "rolled_back": rolled_back,
                "compiles": compiles,
                "degraded": bool(self.loader.bank_status().get(
                    "degraded"))}

    def churn_burst(self, n: int, step: int) -> Dict:
        """A churn STORM (ISSUE 13): ``n`` CNP pattern mutations land
        between regenerations (the debounced-identity-storm shape),
        then ONE regenerate drives the whole multi-bank delta through
        the parallel compile queue. The O(Δ) accounting charges the
        attempt ``n`` change-units, so the per-change compile bound
        still holds — a storm may compile many banks, but only O(its
        own size)."""
        applied = 0
        for k in range(n):
            i = (step + k) % self.N_IDS
            if k % 3 == 2:
                extras = [(kk, p) for kk, p in self.rules_of[i]
                          if "/storm" in p or "/churn" in p]
                if extras:
                    self.rules_of[i].remove(extras[0])
                    applied += 1
                    continue
            self.rules_of[i].append(("http", f"/storm{step}k{k}/.*"))
            applied += 1
        self.revision += 1
        rolled_back = False
        reg = self.loader.bank_registry
        warm_registry = bool(reg and reg.status()["groups"])
        compiles_before = self.bank_compiles()
        self.attempts += max(1, applied)
        try:
            self.loader.regenerate(self._resolve(),
                                   revision=self.revision)
        except Exception:
            rolled_back = True
        else:
            self.committed = {j: list(v)
                              for j, v in self.rules_of.items()}
            self.changes += applied
        compiles = self.bank_compiles() - compiles_before
        if not warm_registry:
            self.compiles0 += compiles
            self.attempts -= max(1, applied)
        return {"mutations": applied, "rolled_back": rolled_back,
                "compiles": compiles,
                "degraded": bool(self.loader.bank_status().get(
                    "degraded"))}

    def traffic(self, index: int) -> Dict:
        """One verdict round through the breaker-guarded verdictor +
        the live session, with the oracle/fail-closed/session
        invariants."""
        from cilium_tpu.core.flow import Verdict

        flows = self.corpus()
        want = self.oracle_verdicts(flows)
        got = self.verdictor.verdicts(flows)
        if int(Verdict.ERROR) in got:
            raise InvariantViolation(index, "no-error-verdicts",
                                     f"served ERROR: {got}")
        degraded = bool(self.loader.bank_status().get("degraded"))
        if not degraded and got != want:
            raise InvariantViolation(
                index, "oracle-agreement",
                f"served {got} != oracle {want} (not degraded)")
        if degraded:
            # fail-closed: a quarantined plane may deny more than the
            # oracle, never allow what the oracle denies
            for k, (g, w) in enumerate(zip(got, want)):
                if w == int(Verdict.DROPPED) and g != w:
                    raise InvariantViolation(
                        index, "fail-closed",
                        f"flow {k}: oracle denies, degraded plane "
                        f"served {g}")
        sess = self.session_verdicts(index)
        return {"verdicts": _digest(got), "degraded": degraded,
                "breaker": self.breaker.state, "session": sess}

    def session_verdicts(self, index: int) -> Dict:
        """The live capture-replay session must follow every commit
        (bit-equal to the serving engine) with honest memo accounting."""
        from cilium_tpu.core.flow import Verdict

        try:
            if self._session is None:
                from cilium_tpu.engine.verdict import CaptureReplay
                from cilium_tpu.ingest.columnar import flows_to_columns

                # the staged capture is pinned at session birth: later
                # churn invalidates memo rows bank-scoped, it does not
                # change which rows the session replays
                self._session_flows = self.corpus() * 4
                cols = flows_to_columns(self._session_flows)
                self._session_cols = cols
                replay = CaptureReplay(self.loader.engine, cols.l7,
                                       cols.offsets, cols.blob,
                                       self.cfg.engine, gen=cols.gen,
                                       loader=self.loader)
                replay.stage_rows(cols.rec, cols.l7)
                replay.stage_unique()
                self._session = replay
            cols = self._session_cols
            out = self._session.verdict_chunk(cols.rec, cols.l7)
        except InvariantViolation:
            raise
        except Exception as e:  # noqa: BLE001 — an injected dispatch
            # fault failing the session chunk is a legitimate outcome
            # (the stream path rebuilds its session the same way);
            # the NEXT round must stage fresh and agree again
            self._session = None
            return {"faulted": type(e).__name__}
        got = [int(v) for v in out["verdict"]]
        if int(Verdict.ERROR) in got:
            raise InvariantViolation(index, "session-no-error",
                                     "session served ERROR")
        engine = self.loader.engine
        try:
            want = [int(v) for v in engine.verdict_flows(
                self._session_flows)["verdict"]]
        except Exception as e:  # noqa: BLE001 — injected dispatch fault
            # on the comparison round: skip the bit-equality check,
            # keep the session; its verdicts were already checked
            # ERROR-free above
            return {"verdicts": _digest(got),
                    "compare_faulted": type(e).__name__}
        if got != want:
            raise InvariantViolation(
                index, "session-stale",
                "session verdicts diverged from the serving engine")
        m = self._session.memo
        memo = {}
        if m is not None:
            if m.hits + m.misses < m.hits or m.hits < 0 or m.misses < 0:
                raise InvariantViolation(index, "memo-accounting",
                                         f"hits={m.hits} "
                                         f"misses={m.misses}")
            memo = {"hits": m.hits, "misses": m.misses,
                    "invalidations": m.invalidations}
        return {"verdicts": _digest(got), "memo": memo}

    def serve(self, n_streams: int, index: int) -> Dict:
        """One round through the continuously-batched serving loop:
        ``n_streams`` virtual streams connect (reconnect-with-resume
        — a live lease renews, never re-grants), each submits the
        probe corpus as a chunk, ONE inline pack cycle serves them.
        Invariants: every chunk resolves or sheds explicitly (nothing
        vanishes), ring verdicts are bit-equal to the serving engine
        when not degraded and never ERROR, and lease accounting is
        exact. Armed ``serve.lease``/``serve.ring_slot`` faults are
        explicit sheds, recorded in the trace."""
        from cilium_tpu.core.flow import Verdict
        from cilium_tpu.ingest.columnar import flows_to_columns
        from cilium_tpu.runtime.serveloop import (
            LeaseExpired,
            ServeLoop,
            ShedError,
        )

        from cilium_tpu.engine.memo import policy_generation

        if self._serve is None:
            self._serve = ServeLoop(self.loader, capacity=4,
                                    lease_ttl_s=10.0,
                                    pack_interval_s=0.01)
        loop = self._serve
        flows = self.corpus()
        # explanation-honesty base: pin what "the committed rule set
        # at this generation" MEANS before any fill can cite it. Ring
        # memo fills only happen inside serve rounds, so lazily
        # snapshotting here covers every citable generation.
        self._serve_gen = policy_generation()
        degraded_now = bool(self.loader.bank_status().get("degraded"))
        self._gen_snapshots.setdefault(
            self._serve_gen,
            ({i: list(v) for i, v in self.committed.items()},
             degraded_now))
        while len(self._gen_snapshots) > 128:
            self._gen_snapshots.pop(min(self._gen_snapshots))
        cols = flows_to_columns(flows)
        sections = (cols.rec, cols.l7, cols.offsets, cols.blob,
                    cols.gen)
        tickets = []
        sheds = 0
        grants_before = loop.grants
        for k in range(n_streams):
            sid = f"dst-s{k}"
            try:
                lease = loop.connect(sid, resume=True)
            except ShedError:
                sheds += 1
                continue
            try:
                tickets.append(loop.submit(lease, *sections))
            except (ShedError, LeaseExpired):
                sheds += 1
        try:
            loop.step()
        except Exception as e:  # noqa: BLE001 — an injected dispatch
            # fault failing the pack is a legitimate outcome; the
            # restarted loop must converge next round
            self._serve = None
            return {"faulted": type(e).__name__, "sheds": sheds}
        degraded = bool(self.loader.bank_status().get("degraded"))
        want = None
        got_digest = ""
        prov_checked = 0
        for t in tickets:
            if not t.done:
                raise InvariantViolation(
                    index, "serve-liveness",
                    "a submitted chunk neither resolved nor shed "
                    "after the pack cycle")
            if t.error is not None:
                sheds += 1  # session-reset/lease loss: explicit
                continue
            got = [int(v) for v in t.verdicts]
            if int(Verdict.ERROR) in got:
                raise InvariantViolation(index, "serve-no-error",
                                         "ring served ERROR")
            if want is None:
                try:
                    want = [int(v) for v in
                            self.loader.engine.verdict_flows(
                                flows)["verdict"]]
                except Exception:  # noqa: BLE001 — injected dispatch
                    want = got  # comparison round faulted: skip
            if not degraded and got != want:
                raise InvariantViolation(
                    index, "serve-stale",
                    "ring verdicts diverged from the serving engine")
            got_digest = _digest(got)
            if not prov_checked and t.prov is not None:
                # one ticket's worth of sampled explanation-honesty
                # checks per round (tickets share the corpus; one
                # bound keeps the schedule cost flat)
                prov_checked = self._check_explanation_honesty(
                    t, flows, index, degraded)
        st = loop.status()
        if st["grants"] - st["expiries"] - st["releases"] \
                != st["occupancy"]:
            raise InvariantViolation(
                index, "serve-lease-accounting",
                f"grants {st['grants']} - expiries {st['expiries']} "
                f"- releases {st['releases']} != occupancy "
                f"{st['occupancy']}")
        return {"streams": n_streams, "sheds": sheds,
                "grants_new": loop.grants - grants_before,
                "occupancy": st["occupancy"],
                "bytes_saved": st["bytes_saved"],
                "verdicts": got_digest,
                "prov_checked": prov_checked}

    def _check_explanation_honesty(self, ticket, flows, index: int,
                                   degraded_now: bool) -> int:
        """The explanation-honesty invariant over one resolved
        ticket's provenance: sampled rows must (a) cite a generation
        whose committed rule set was recorded, (b) cite the CURRENT
        generation when computed this round (memo-hit rows may
        legitimately cite older epochs — that is the point), and (c)
        re-resolve, under the cited generation's committed rules, to
        the served verdict (fail-closed comparison when either epoch
        was degraded). Returns sampled-row count."""
        import numpy as np

        from cilium_tpu.core.flow import Verdict
        from cilium_tpu.policy.oracle import OracleVerdictEngine

        prov = ticket.prov
        l7m = np.asarray(prov.l7_match)
        gens = np.asarray(prov.gens)
        hits = np.asarray(prov.memo_hit)
        verd = np.asarray(prov.verdict)
        n = min(len(flows), len(verd))
        step = max(1, n // 8)
        oracles: Dict[int, object] = {}
        checked = 0
        for r in range(0, n, step):
            gen = int(gens[r])
            snap = self._gen_snapshots.get(gen)
            if snap is None:
                raise InvariantViolation(
                    index, "explanation-honesty",
                    f"row {r} cites generation {gen} — no committed "
                    f"snapshot ever recorded for it (a fabricated or "
                    f"pre-fill citation)")
            if not bool(hits[r]) and gen != self._serve_gen:
                raise InvariantViolation(
                    index, "explanation-honesty",
                    f"row {r} was computed this round but cites "
                    f"generation {gen} != current {self._serve_gen}")
            rules_at, degraded_at = snap
            oracle = oracles.get(gen)
            if oracle is None:
                saved = self.rules_of
                self.rules_of = {i: list(v)
                                 for i, v in rules_at.items()}
                try:
                    per_identity = self._resolve()
                finally:
                    self.rules_of = saved
                oracle = oracles[gen] = OracleVerdictEngine(
                    per_identity)
            want = int(oracle.verdict_flows([flows[r]])["verdict"][0])
            got = int(verd[r])
            if degraded_at or degraded_now:
                # a degraded epoch may deny more, never allow what
                # the cited oracle denies
                if want == int(Verdict.DROPPED) and got != want:
                    raise InvariantViolation(
                        index, "explanation-honesty",
                        f"row {r}: degraded plane allowed what the "
                        f"cited-generation {gen} oracle denies")
            elif got != want:
                hint = ("memo-served" if bool(hits[r])
                        else "computed")
                raise InvariantViolation(
                    index, "explanation-honesty",
                    f"row {r} ({hint}, l7_match={int(l7m[r])}): "
                    f"served {got} != cited-generation {gen} oracle "
                    f"{want}")
            checked += 1
        return checked

    def multichip(self, index: int) -> Dict:
        """Sampled invariant checks through the SHARDED verdict lanes
        on a small virtual mesh (ISSUE 12): the DP-sharded step and
        the payload-sharded CP step must match the single-device step
        bit-for-bit on EVERY output lane, serve no ERROR, and hold
        oracle agreement / fail-closed exactly like the single-device
        plane — so mesh configs enter the searched fault space
        instead of living only in the bench."""
        import jax

        devs = jax.devices()
        if len(devs) < 2:
            return {"skipped": "single-device backend"}
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from cilium_tpu.core.flow import Verdict
        from cilium_tpu.engine.verdict import (
            encode_flows,
            flowbatch_to_host_dict,
        )
        from cilium_tpu.parallel.cp import (
            cp_shard_batch,
            make_cp_verdict_step,
        )
        from cilium_tpu.parallel.mesh import make_mesh
        from cilium_tpu.parallel.sharding import (
            make_sharded_step,
            shard_flow_batch,
            shard_policy_arrays,
        )

        n = 2
        flows = self.corpus()
        pad = (-len(flows)) % n
        padded = flows + flows[:pad]
        policy = self.loader.engine.policy
        try:
            host = flowbatch_to_host_dict(encode_flows(
                padded, policy.kafka_interns, self.cfg.engine))
            ref = _ref_step()(
                {k: jnp.asarray(v) for k, v in policy.arrays.items()},
                {k: jnp.asarray(v) for k, v in host.items()})
            mesh = make_mesh((n,), ("data",), devs[:n])
            arrays = shard_policy_arrays(policy.arrays, mesh)
            out = make_sharded_step(mesh, "data")(
                arrays, shard_flow_batch(host, mesh))
            cmesh = make_mesh((n,), ("seq",), devs[:n])
            cout = make_cp_verdict_step(cmesh, host)(
                {k: jax.device_put(v, NamedSharding(cmesh, P()))
                 for k, v in policy.arrays.items()},
                cp_shard_batch(host, cmesh))
        except InvariantViolation:
            raise
        except Exception as e:  # noqa: BLE001 — an injected fault
            # failing the staging/dispatch is a legitimate outcome;
            # the next round must stage fresh and agree again
            return {"faulted": type(e).__name__}
        for lane, sharded in (("dp", out), ("cp", cout)):
            for key in ref:
                if not np.array_equal(np.asarray(sharded[key]),
                                      np.asarray(ref[key])):
                    raise InvariantViolation(
                        index, f"multichip-{lane}-parity",
                        f"sharded output lane {key!r} diverged from "
                        f"the single-device step")
        got = [int(v) for v in np.asarray(out["verdict"])[:len(flows)]]
        if int(Verdict.ERROR) in got:
            raise InvariantViolation(index, "multichip-no-error",
                                     "sharded step served ERROR")
        want = self.oracle_verdicts(flows)
        degraded = bool(self.loader.bank_status().get("degraded"))
        if not degraded and got != want:
            raise InvariantViolation(
                index, "multichip-oracle-agreement",
                f"sharded step served {got} != oracle {want} "
                f"(not degraded)")
        if degraded:
            for k, (g, w) in enumerate(zip(got, want)):
                if w == int(Verdict.DROPPED) and g != w:
                    raise InvariantViolation(
                        index, "multichip-fail-closed",
                        f"flow {k}: oracle denies, degraded sharded "
                        f"plane served {g}")
        return {"devices": n, "flows": len(flows),
                "verdicts": _digest(got), "degraded": degraded}

    def fleet(self, n_streams: int, action: str, index: int) -> Dict:
        """One round through the HORIZONTAL serving fleet (ISSUE 16):
        a scheduled fleet action (host kill / partition / heartbeat
        round / rejoin), then ``n_streams`` virtual streams connect
        through the stream-affinity router and submit the probe
        corpus. Armed ``fleet.heartbeat``/``fleet.handoff`` faults
        land on the beat and the death handoff. Invariants on every
        round: chunks resolve or shed explicitly (a HostDead submit
        resumes, never vanishes), no ERROR / stale verdicts off any
        replica ring, the fleet lease books are EXACT (sum over all
        replicas, dead ones included), and lease conservation — no
        stream holds leases on two live hosts, however the kill /
        handoff-interrupt / rejoin events interleave."""
        from cilium_tpu.core.flow import Verdict
        from cilium_tpu.ingest.columnar import flows_to_columns
        from cilium_tpu.runtime.fleetserve import (
            FleetRouter,
            HostDead,
            HostReplica,
        )
        from cilium_tpu.runtime.serveloop import LeaseExpired, ShedError

        if self._fleet is None:
            replicas = [HostReplica(i, self.loader, capacity=4,
                                    lease_ttl_s=10.0,
                                    pack_interval_s=0.01)
                        for i in range(3)]
            self._fleet = FleetRouter(replicas,
                                      heartbeat_interval_s=0.5,
                                      suspicion_ttl_s=2.0,
                                      spill_headroom=0.0)
        router = self._fleet
        # -- the scheduled fleet action (deterministic target pick:
        # the highest-index live replica, never the last one standing)
        live = [r for r in router.replicas if r.alive]
        did = action
        if action == "kill" and len(live) >= 2:
            router.kill(live[-1].name)
        elif action == "partition" and len(live) >= 2 \
                and not live[-1].cut:
            # the cut host fails CLOSED immediately (sheds
            # ``partitioned``); the suspicion sweep declares it dead
            # only once virtual time advances past the TTL — exactly
            # the window the conservation invariant must survive
            router.partition(live[-1].name)
        elif action == "rejoin":
            dead = [r for r in router.replicas if not r.alive]
            if dead:
                # loader=None: the revived replica keeps the world's
                # shared loader — the zero-recompile warm-restore path
                router.rejoin(dead[0].name)
            else:
                did = "rejoin-noop"
        else:
            did = f"{action}-noop" if action != "beat" else "beat"
        died = router.beat()
        # -- the serve round through the router ---------------------------
        flows = self.corpus()
        cols = flows_to_columns(flows)
        sections = (cols.rec, cols.l7, cols.offsets, cols.blob,
                    cols.gen)
        tickets = []
        sheds = 0
        replays = 0
        for k in range(n_streams):
            sid = f"dstf-s{k}"
            for _attempt in (0, 1):
                try:
                    _host, lease = router.connect(sid, resume=True)
                except ShedError:
                    sheds += 1
                    break
                except HostDead:
                    replays += 1
                    continue
                try:
                    tickets.append(router.submit(sid, lease, sections))
                    break
                except HostDead:
                    # died between admit and submit: the typed resume
                    # path — reconnect and replay, never stream-fatal
                    replays += 1
                    continue
                except (ShedError, LeaseExpired):
                    sheds += 1
                    break
            else:
                sheds += 1  # resume budget exhausted: explicit shed
        try:
            router.step_all()
        except Exception as e:  # noqa: BLE001 — an injected dispatch
            # fault failing a pack is a legitimate outcome; the fresh
            # fleet must converge next round
            self._fleet = None
            return {"faulted": type(e).__name__, "sheds": sheds}
        degraded = bool(self.loader.bank_status().get("degraded"))
        want = None
        resolved = 0
        for t in tickets:
            if not t.done:
                raise InvariantViolation(
                    index, "fleet-liveness",
                    "a chunk submitted through the router neither "
                    "resolved nor shed after the fleet pack cycle")
            if t.error is not None:
                sheds += 1  # lease-closed from a death: explicit
                continue
            resolved += 1
            got = [int(v) for v in t.verdicts]
            if int(Verdict.ERROR) in got:
                raise InvariantViolation(
                    index, "fleet-no-error",
                    "a replica ring served ERROR")
            if want is None:
                try:
                    want = [int(v) for v in
                            self.loader.engine.verdict_flows(
                                flows)["verdict"]]
                except Exception:  # noqa: BLE001 — injected dispatch
                    want = got  # comparison round faulted: skip
            if not degraded and got != want:
                raise InvariantViolation(
                    index, "fleet-stale",
                    "a replica ring's verdicts diverged from the "
                    "shared serving engine")
        bal, occ = router.books()
        if bal != occ:
            raise InvariantViolation(
                index, "fleet-lease-accounting",
                f"fleet-wide grants - expiries - releases = {bal} != "
                f"occupancy {occ} (summed over ALL replicas)")
        dup = router.conservation_violation()
        if dup is not None:
            raise InvariantViolation(
                index, "lease-conservation",
                f"stream {dup[0]!r} holds live leases on {dup[1]} "
                f"and {dup[2]}")
        journal_bad = router.journal_consistent()
        if journal_bad is not None:
            raise InvariantViolation(
                index, "fleet-journal-consistency",
                f"folding the fleet event journal diverged from the "
                f"router's books: {journal_bad}")
        return {"streams": n_streams, "action": did,
                "beat_deaths": list(died), "sheds": sheds,
                "replays": replays, "resolved": resolved,
                "live_hosts": sum(1 for r in router.replicas
                                  if r.alive),
                "handoffs": router.handoffs,
                "partial_handoffs": router.partial_handoffs,
                "occupancy": occ}

    def _tenant_probe_flows(self, i: int):
        """Tenant ``i``'s slice of the probe corpus (its committed
        patterns + a never-allowed canary), deterministic order."""
        flows = []
        for kind, pat in self.committed[i]:
            if kind == "http":
                flows.append(self._http(i, pat.replace("/.*", "/x")))
            elif kind == "dns":
                flows.append(self._dns(i, pat))
            else:
                proto, dport, mk = self._FE_KINDS[kind]
                flows.append(self._fe(i, proto, dport, mk(pat)))
        flows.append(self._http(i, "/never/allowed"))
        return flows

    def tenant_isolation(self, mode: str, index: int) -> Dict:
        """The ISSUE-20 tenant-isolation invariant: tenant A's faults
        — an A-only churn storm (with whatever bank-compile faults
        the schedule armed), a quota lapse/fault while A floods a
        congested admission window, or a bad canary rollout scoped to
        A's entries — must provably never move tenant B's served
        verdicts, B's compiled banks (namespace-attributed keys), or
        B's admission outcomes."""
        A, B = 0, 1
        reg = self.loader.bank_registry
        flows_b = self._tenant_probe_flows(B)

        def b_verdicts():
            try:
                return [int(v) for v in self.loader.engine
                        .verdict_flows(flows_b)["verdict"]]
            except Exception:  # noqa: BLE001 — an injected dispatch
                return None    # fault: skip the equality leg

        before = b_verdicts()
        keys_before = tuple(reg.keys_in_namespace("b")) if reg else ()
        out: Dict = {"mode": mode}
        if mode == "churn-storm":
            # tenant A's churn storm: 3 A-only mutations, one
            # regenerate — with the namespaced planner, only A (and
            # shared) banks may compile; an armed loader.bank_compile
            # fault can only quarantine those
            # 6 patterns: enough banks in A's namespace (bank_size 2)
            # that a positional wholesale shift on the delete leg
            # below exceeds the O(Δ) adjacency bound — the planted
            # positional-banks mutation stays catchable in the
            # NAMESPACED world (tests/dst/test_planted.py budget)
            applied = 6
            for k in range(applied):
                # the "/churn" stem keeps these deletable by the churn
                # executor: the storm's delete leg below rides the
                # same O(Δ) adjacency check as plain churn deletes
                self.rules_of[A].append(
                    ("http", f"/churnt{index}k{k}/.*"))
            self.revision += 1
            rolled_back = False
            warm_registry = bool(reg and reg.status()["groups"])
            compiles_before = self.bank_compiles()
            self.attempts += applied
            try:
                self.loader.regenerate(self._resolve(),
                                       revision=self.revision)
            except Exception:
                rolled_back = True
            else:
                self.committed = {j: list(v)
                                  for j, v in self.rules_of.items()}
                self.changes += applied
            compiles = self.bank_compiles() - compiles_before
            if not warm_registry:
                self.compiles0 += compiles
                self.attempts -= applied
            out.update({"mutations": applied,
                        "rolled_back": rolled_back,
                        "compiles": compiles,
                        "degraded": bool(self.loader.bank_status()
                                         .get("degraded"))})
            if not rolled_back:
                # the storm's DELETE leg: tenant A retracts one of its
                # churned-in patterns through the ordinary churn
                # executor — a warm A-namespace delete must perturb
                # only the adjacent A bank(s) (the o-delta-compile
                # check inside churn() enforces it), and B's banks/
                # verdicts stay unmoved either way
                out["delete"] = self.churn("delete", A, index)
        elif mode == "quota":
            from cilium_tpu.runtime import admission as adm
            from cilium_tpu.runtime.tenant import (
                FairShareWindow,
                TenantMap,
                TenantQuotas,
            )

            tmap = TenantMap.from_config(self.cfg)
            quotas = TenantQuotas.from_config(self.cfg)
            # A's generous share lapses AT the tick (ttl 0, closed
            # boundary): every read from here is the conservative
            # default — and an armed tenant.quota fault forces the
            # same default, so A is bounded either way
            quotas.set_share("a", 0.9, ttl_s=0.0)
            fair = FairShareWindow(
                quantum_s=self.cfg.tenant.quantum_s,
                max_share=self.cfg.tenant.max_share,
                weight_of=tmap.weight_of)
            gate = adm.AdmissionGate(
                max_pending=8, control_reserve=2,
                depth_fn=lambda: 6,  # congested: fairness armed
                fairness=fair, quotas=quotas)
            for _ in range(3):   # B establishes presence first
                ok, _r = gate.admit(adm.CLASS_DATA, tenant="b")
                if not ok:
                    raise InvariantViolation(
                        index, "tenant-isolation",
                        "tenant B shed before tenant A stormed")
            a_ok = a_shed = 0
            for _ in range(12):  # tenant A floods the window
                ok, reason = gate.admit(adm.CLASS_DATA, tenant="a")
                if ok:
                    a_ok += 1
                    continue
                a_shed += 1
                if reason != adm.SHED_TENANT_QUOTA:
                    raise InvariantViolation(
                        index, "tenant-isolation",
                        f"tenant A's flood shed with reason "
                        f"{reason!r} — not tenant-attributed")
            if a_shed == 0:
                raise InvariantViolation(
                    index, "tenant-isolation",
                    "tenant A stormed 12 admits past its share and "
                    "never shed tenant-quota")
            # B's outcomes unmoved by A's storm: B's fair allotment
            # (2 more of this window under equal weights) must admit
            for _ in range(2):
                ok, reason = gate.admit(adm.CLASS_DATA, tenant="b")
                if not ok:
                    raise InvariantViolation(
                        index, "tenant-isolation",
                        f"tenant B shed ({reason}) while only tenant "
                        f"A stormed the window")
            out.update({"a_admitted": a_ok, "a_shed": a_shed,
                        "quota": quotas.status()["default_share"]})
        else:  # canary
            if bool(self.loader.bank_status().get("degraded")):
                # a quarantined plane may already DENY A's flows —
                # the bad rollout would legitimately diff zero; the
                # arm only proves the gate on a healthy plane
                out["skipped"] = "degraded"
            else:
                import copy

                from cilium_tpu.runtime.canary import (
                    CanaryController,
                    CanaryRefused,
                )

                rev_before = self.loader.revision
                try:
                    flows = self.corpus()
                    served = [int(v) for v in self.loader.engine
                              .verdict_flows(flows)["verdict"]]
                except Exception as e:  # noqa: BLE001 — injected
                    out["faulted"] = type(e).__name__
                    return out
                bad = copy.deepcopy(self._resolve())
                for entry in bad[self.dbs[A]].entries.values():
                    entry.is_deny = True  # A's bad CNP: mass-deny
                ctl = CanaryController(self.loader,
                                       sample_fraction=1.0,
                                       diff_budget=0.0,
                                       min_samples=1)
                ctl.stage(bad, revision=rev_before + 1)
                ctl.observe_chunk(flows, served)
                refused = aborted = False
                try:
                    ctl.try_commit()
                except CanaryRefused:
                    refused = True
                except RuntimeError:
                    # an armed canary.dispatch fault aborted the
                    # rollout before commit — the safe degradation
                    aborted = ctl.state == "aborted"
                if not (refused or aborted):
                    raise InvariantViolation(
                        index, "tenant-isolation",
                        "a bad tenant-A canary COMMITTED through "
                        "the verdict-diff gate")
                if self.loader.revision != rev_before:
                    raise InvariantViolation(
                        index, "tenant-isolation",
                        "a refused/aborted canary moved the serving "
                        "revision")
                out.update({"refused": refused, "aborted": aborted,
                            "diffs": ctl.report()["diffs"]})
        keys_after = tuple(reg.keys_in_namespace("b")) if reg else ()
        if keys_before and keys_after != keys_before:
            raise InvariantViolation(
                index, "tenant-isolation",
                f"tenant A's {mode} moved tenant B's bank keys "
                f"({len(keys_before)} -> {len(keys_after)})")
        after = b_verdicts()
        if before is not None and after is not None \
                and after != before:
            raise InvariantViolation(
                index, "tenant-isolation",
                f"tenant A's {mode} changed tenant B's served "
                f"verdicts")
        out["b_verdicts"] = _digest(after if after is not None
                                    else [])
        return out

    def storm(self, n: int, index: int) -> Dict:
        """A burst of identity add/delete through the kvstore watch
        (the churn_storm point may lose deliveries); local allocation
        and a fresh replay-then-follow must converge regardless."""
        from cilium_tpu.identity_kvstore import (
            ClusterIdentityAllocator,
            VALUE_PREFIX,
        )

        for k in range(n):
            labels = self.storm_pool[k % len(self.storm_pool)]
            if k % 3 == 2:
                nid = self.cluster_alloc.lookup_by_labels(labels)
                if nid is not None:
                    enc = ";".join(sorted(labels.format()))
                    self.store.delete(VALUE_PREFIX + enc)
            else:
                self.cluster_alloc.allocate(labels)
        # convergence: a fresh allocator replaying the store agrees
        # with the store's authoritative mappings
        fresh = ClusterIdentityAllocator(self.store).start()
        try:
            for key, raw in self.store.list_prefix(
                    VALUE_PREFIX).items():
                enc = key[len(VALUE_PREFIX):]
                from cilium_tpu.identity_kvstore import _decode_enc

                nid = fresh.lookup_by_labels(_decode_enc(enc))
                if nid != int(raw):
                    raise InvariantViolation(
                        index, "identity-convergence",
                        f"fresh replay maps {enc!r} to {nid}, "
                        f"store says {raw}")
        finally:
            fresh.close()
        return {"events": n, "store_keys": len(self.store)}

    def clustermesh_sync(self, n: int, index: int) -> Dict:
        """A remote-cluster sync round (ISSUE 15): ``n`` remote
        endpoint announcements ride a LocalStatePublisher → kvstore →
        RemoteCluster watch into the LOCAL allocator/ipcache, with
        the ``clustermesh.session``/``clustermesh.heartbeat`` fault
        points live on the path. A session fault eats one delivery
        (isolated by the kvstore watch) and a heartbeat fault skips a
        lease keepalive — both must CONVERGE under the bounded repair
        loop (re-upsert + heartbeat), or the mesh is silently
        diverging: every published prefix must resolve locally to an
        identity tagged with the remote cluster's name."""
        import json as _json

        from cilium_tpu.clustermesh import (
            CLUSTER_LABEL_KEY,
            IP_PREFIX,
            LocalStatePublisher,
            RemoteCluster,
        )
        from cilium_tpu.core.labels import SOURCE_K8S, LabelSet
        from cilium_tpu.ipcache import IPCache
        from cilium_tpu.kvstore import KVStore

        if self._mesh is None:
            store = KVStore()
            remote_alloc_ipc = IPCache(self.alloc)
            local_ipc = IPCache(self.alloc)
            pub = LocalStatePublisher(store, "alpha", self.alloc,
                                      remote_alloc_ipc,
                                      lease_ttl=3600.0)
            rc = RemoteCluster("alpha", store, self.alloc,
                               local_ipc).connect()
            self._mesh = (store, remote_alloc_ipc, local_ipc, pub, rc)
            self._mesh_n = 0
        store, remote_ipc, local_ipc, pub, rc = self._mesh
        faulted = 0
        for k in range(n):
            idx = self._mesh_n
            self._mesh_n += 1
            nid = self.alloc.allocate(LabelSet.from_dict(
                {"meshapp": f"m{idx % 6}"}))
            try:
                remote_ipc.upsert(f"10.9.{idx // 200}."
                                  f"{idx % 200 + 1}/32", nid)
            except Exception:  # noqa: BLE001 — injected session fault
                faulted += 1
        try:
            pub.heartbeat()
        except Exception:  # noqa: BLE001 — injected heartbeat fault
            faulted += 1
        # bounded repair: re-announce every published entry (value
        # bumped so the watch re-delivers) + heartbeat, then REQUIRE
        # convergence — the re-announce is exactly the reference's
        # reconcile loop, so an unconverged mesh is a real bug
        for _attempt in range(3):
            try:
                for e in remote_ipc.dump():
                    nid = int(e["identity"])
                    labels = self.alloc.lookup(nid)
                    store.set(
                        f"{IP_PREFIX}alpha/{e['cidr']}",
                        _json.dumps({
                            "prefix": e["cidr"], "identity": nid,
                            "labels": (list(labels.format())
                                       if labels else []),
                            "cluster": "alpha",
                            "seq": _attempt}))
                pub.heartbeat()
                break
            except Exception:  # noqa: BLE001 — still-armed faults
                faulted += 1
        for e in remote_ipc.dump():
            nid = local_ipc.lookup(e["cidr"].split("/")[0])
            if nid is None:
                raise InvariantViolation(
                    index, "clustermesh-convergence",
                    f"published prefix {e['cidr']} missing from the "
                    f"local ipcache after repair")
            labels = self.alloc.lookup(nid)
            tag = (labels.get(CLUSTER_LABEL_KEY, SOURCE_K8S)
                   if labels else None)
            if tag is None or tag.value != "alpha":
                raise InvariantViolation(
                    index, "clustermesh-convergence",
                    f"prefix {e['cidr']} resolved without the remote "
                    f"cluster tag")
        return {"announced": n, "entries": rc.num_entries(),
                "faulted": faulted}

    def drain_restore(self, index: int) -> Dict:
        """Warm-restart cycle: snapshot the serving state, restore it
        into a FRESH loader (the restarted process), and re-point the
        verdictor/session at it — first answers must be verdict-
        identical (the traffic invariant right after proves it)."""
        from cilium_tpu.runtime.loader import Loader
        from cilium_tpu.runtime.service import ResilientVerdictor

        warm = self.loader.snapshot_warm()
        restored = False
        crashed = ""
        if warm:
            fresh = Loader(self.cfg)
            try:
                restored = fresh.restore_warm()
            except Exception as e:  # noqa: BLE001 — an injected swap
                # fault mid-restore models a crash during warm boot;
                # the OLD process keeps serving (restored stays False)
                crashed = type(e).__name__
            if restored:
                self._compiles_carry = self.bank_compiles()
                self.loader.close()   # old incarnation's workers die
                self.loader = fresh
                self.verdictor = ResilientVerdictor(
                    self.loader, breaker=self.breaker)
                # the restarted process stages a fresh session, and
                # its empty bank registry re-compiles the plan once —
                # cold-start cost, not churn cost: reset the O(Δ)
                # accounting window to this incarnation
                self._session = None
                # ...and a fresh serving loop: ring/lease state is
                # process-resident, not snapshot state
                self._serve = None
                # ...the fleet too: the replicas' rings died with the
                # old process, and they must share the NEW loader
                self._fleet = None
                self.compiles0 = self.bank_compiles()
                self.attempts = 0
        return {"warm_snapshot": warm, "restored": restored,
                "crashed": crashed, "revision": self.loader.revision}

    # -- end-of-schedule liveness -----------------------------------------
    def check_liveness(self, plan: SchedulePlan, clock, index: int,
                      ) -> Dict:
        """With faults exhausted, bounded virtual time recovers the
        plane: breaker re-closes, quarantines clear, verdicts match."""
        from cilium_tpu.runtime.service import CircuitBreaker

        plan.disarm_all()
        clock.advance(PROBE_INTERVAL_S + 0.1)
        out = self.traffic(index)
        if self.breaker.state != CircuitBreaker.CLOSED:
            raise InvariantViolation(
                index, "breaker-liveness",
                f"breaker state {self.breaker.state} after a healthy "
                f"round past the probe interval")
        if out["degraded"]:
            clock.advance(QUARANTINE_TTL_S + 0.1)
            reg = self.loader.bank_registry
            quarantined = reg.status()["quarantined"] if reg else 0
            self.revision += 1
            self.attempts += 1
            self.loader.regenerate(self._resolve(),
                                   revision=self.revision)
            # the recovery regenerate recompiles each previously-
            # quarantined bank once — O(injected faults), the cost of
            # RECOVERY, not wholesale churn work: baseline it out of
            # the O(Δ) window like cold-start rebuilds (a schedule
            # arming 5 bank-compile faults must not read as 5
            # compiles/attempt)
            self.compiles0 += quarantined
            self.committed = {j: list(v)
                              for j, v in self.rules_of.items()}
            if self.loader.bank_status().get("degraded"):
                raise InvariantViolation(
                    index, "quarantine-liveness",
                    "bank quarantine survived TTL + regeneration "
                    "with faults exhausted")
            out = self.traffic(index)
        compiles = self.bank_compiles() - self.compiles0
        if self.attempts and compiles / self.attempts > \
                COMPILES_PER_CHANGE_BOUND:
            raise InvariantViolation(
                index, "o-delta-compile",
                f"{compiles} bank compiles over {self.attempts} "
                f"regenerate attempts "
                f"(> {COMPILES_PER_CHANGE_BOUND}/attempt: "
                f"wholesale recompiles)")
        # restart survivability: with faults exhausted, a clean
        # drain → warm-restore cycle must stage the SERVING policy —
        # a poisoned artifact pointer left behind by an earlier
        # faulted sequence (the PR-7 rollback-artifact-key shape)
        # surfaces HERE as oracle disagreement on the restarted
        # process's first round, however the faults masked it while
        # they were armed (a crashed restore hides the bad pointer;
        # the exhausted retry does not)
        restart = self.drain_restore(index)
        out = self.traffic(index)
        return {"final": out, "bank_compiles": compiles,
                "changes": self.changes, "attempts": self.attempts,
                "restart": restart}

    def close(self) -> None:
        if self._mesh is not None:
            self._mesh[4].disconnect()
            self._mesh = None
        self.cluster_alloc.close()
        self.loader.close()


def _digest(verdicts: Sequence[int]) -> str:
    return hashlib.sha256(bytes(int(v) & 0xFF
                                for v in verdicts)).hexdigest()[:16]


# -- schedules ---------------------------------------------------------------


def generate(seed: int, max_events: int = 12) -> List[List]:
    """The seeded schedule: a concrete event list (JSON-serializable,
    self-contained) so a shrunken subset re-runs without the RNG."""
    rng = random.Random(seed)
    n = rng.randint(max(3, max_events // 2), max_events)
    events: List[List] = []
    for k in range(n):
        roll = rng.random()
        if roll < 0.22:
            point = rng.choice(FAULT_POINTS)
            events.append(["fault", point, rng.randint(1, 3)])
        elif roll < 0.36:
            events.append(["churn",
                           rng.choice(["add", "add", "delete"]),
                           rng.randrange(DSTWorld.N_IDS)])
        elif roll < 0.44:
            # ISSUE 13: a churn STORM through the parallel compile
            # queue — n mutations, one regenerate, O(Δ) still bounded.
            # Sizes stay small: every net-new pattern grows the probe
            # corpus, and each distinct corpus size re-traces the
            # jitted step — a size-9 burst tripled the sweep's wall
            # time for no extra invariant coverage.
            events.append(["churn-burst", rng.randint(2, 5)])
        elif roll < 0.56:
            events.append(["traffic"])
        elif roll < 0.66:
            events.append(["serve", rng.randint(2, 6)])
        elif roll < 0.70:
            # ISSUE 16: the horizontal fleet enters the searched
            # space — a scheduled host kill/partition/beat/rejoin
            # with the heartbeat+handoff fault points armable, then a
            # routed serve round; lease conservation and exact
            # fleet-wide books checked every time
            events.append(["fleet", rng.randint(2, 6),
                           rng.choice(["kill", "partition", "beat",
                                       "rejoin"])])
        elif roll < 0.72:
            # ISSUE 12: sharded-lane checks ride the schedule space —
            # a fault armed two events earlier now also hits the mesh
            events.append(["multichip"])
        elif roll < 0.77:
            # ISSUE 15: a cross-cluster sync round — remote-identity
            # announcements through the clustermesh watch, with the
            # session/heartbeat fault points in the armable set and a
            # convergence invariant after the bounded repair loop
            events.append(["clustermesh", rng.randint(2, 6)])
        elif roll < 0.83:
            events.append(["advance", rng.choice(ADVANCES)])
        elif roll < 0.88:
            # ISSUE 20: the tenant-isolation invariant enters the
            # searched space — tenant A storms/lapses/stages a bad
            # canary (whatever faults are armed land on it) and
            # tenant B's verdicts, banks, and admission outcomes are
            # checked unmoved
            events.append(["tenant",
                           rng.choice(["churn-storm", "quota",
                                       "canary"])])
        elif roll < 0.91:
            events.append(["storm", rng.randint(4, 24)])
        else:
            events.append(["drain-restore"])
    # every schedule ends with the liveness epilogue (implicit)
    return events


def schedule_digest(events: Sequence[Sequence]) -> str:
    return hashlib.sha256(json.dumps(
        list(events), sort_keys=True).encode()).hexdigest()[:16]


def run_schedule(seed: int, events: Optional[List[List]] = None,
                 cache_dir: Optional[str] = None,
                 max_events: int = 12) -> Dict:
    """Execute one schedule under a fresh world + driven VirtualClock.
    Returns ``{"seed", "events", "trace", "digest", "violation"}``;
    the trace is byte-identical for identical (seed, events)."""
    if events is None:
        events = generate(seed, max_events=max_events)
    # a FRESH artifact-cache dir per schedule: a pre-warmed cache
    # would skip bank compiles and change the trace's compile counts —
    # byte-identical replay requires a byte-identical starting state
    import shutil
    import tempfile

    own_cache = cache_dir is None
    if own_cache:
        cache_dir = tempfile.mkdtemp(prefix="ct_dst_")
    trace: List[Dict] = []
    violation: Optional[Dict] = None
    plan = SchedulePlan()
    clock = simclock.VirtualClock()
    with simclock.use(clock):
        world = DSTWorld(cache_dir)
        try:
            with faults.inject(plan):
                for i, ev in enumerate(events):
                    kind = ev[0]
                    try:
                        if kind == "fault":
                            plan.arm(ev[1], int(ev[2]))
                            out = {"armed": ev[1], "times": int(ev[2])}
                        elif kind == "churn":
                            out = world.churn(ev[1], int(ev[2]) %
                                              DSTWorld.N_IDS, step=i)
                        elif kind == "churn-burst":
                            out = world.churn_burst(int(ev[1]), step=i)
                        elif kind == "traffic":
                            out = world.traffic(i)
                        elif kind == "serve":
                            out = world.serve(int(ev[1]), i)
                        elif kind == "fleet":
                            out = world.fleet(int(ev[1]), str(ev[2]), i)
                        elif kind == "multichip":
                            out = world.multichip(i)
                        elif kind == "clustermesh":
                            out = world.clustermesh_sync(int(ev[1]), i)
                        elif kind == "advance":
                            clock.advance(float(ev[1]))
                            out = {"now": round(clock.now(), 6)}
                        elif kind == "tenant":
                            out = world.tenant_isolation(str(ev[1]), i)
                        elif kind == "storm":
                            out = world.storm(int(ev[1]), i)
                        elif kind == "drain-restore":
                            out = world.drain_restore(i)
                        else:
                            raise ValueError(f"unknown event {ev!r}")
                    except InvariantViolation as v:
                        violation = {"index": v.index,
                                     "invariant": v.invariant,
                                     "detail": v.detail}
                        trace.append({"i": i, "t": round(clock.now(), 6),
                                      "event": list(ev),
                                      "violation": violation})
                        break
                    trace.append({"i": i, "t": round(clock.now(), 6),
                                  "event": list(ev), "out": out})
                if violation is None:
                    try:
                        out = world.check_liveness(plan, clock,
                                                   len(events))
                        trace.append({"i": len(events),
                                      "t": round(clock.now(), 6),
                                      "event": ["liveness"],
                                      "out": out})
                    except InvariantViolation as v:
                        violation = {"index": v.index,
                                     "invariant": v.invariant,
                                     "detail": v.detail}
                        trace.append({"i": len(events),
                                      "t": round(clock.now(), 6),
                                      "event": ["liveness"],
                                      "violation": violation})
        finally:
            world.close()
            if own_cache:
                shutil.rmtree(cache_dir, ignore_errors=True)
    blob = json.dumps({"format": SCHEDULE_FORMAT, "seed": seed,
                       "events": events, "trace": trace},
                      sort_keys=True)
    return {"seed": seed, "events": events, "trace": trace,
            "digest": hashlib.sha256(blob.encode()).hexdigest(),
            "schedule_digest": schedule_digest(events),
            "violation": violation}


# -- search + shrink ---------------------------------------------------------


def search(schedules: int, seed0: int = 0, max_events: int = 12,
           cache_dir: Optional[str] = None,
           progress=None) -> Tuple[int, Optional[Dict]]:
    """Run ``schedules`` seeded schedules; returns (count_run, first
    violating result or None)."""
    for k in range(schedules):
        res = run_schedule(seed0 + k, cache_dir=cache_dir,
                           max_events=max_events)
        if progress is not None:
            progress(k, res)
        if res["violation"] is not None:
            return k + 1, res
    return schedules, None


def shrink(seed: int, events: List[List],
           cache_dir: Optional[str] = None) -> Dict:
    """Delta-debug a violating schedule to a (1-)minimal event list:
    repeatedly drop chunks, keeping any subset that still violates.
    Returns the final violating result (its ``events`` are minimal —
    removing any single event no longer violates)."""
    def violates(evs: List[List]) -> Optional[Dict]:
        res = run_schedule(seed, events=evs, cache_dir=cache_dir)
        return res if res["violation"] is not None else None

    best = violates(events)
    assert best is not None, "shrink() needs a violating schedule"
    n = 2
    evs = list(events)
    while len(evs) >= 2:
        chunk = max(1, len(evs) // n)
        shrunk = False
        for start in range(0, len(evs), chunk):
            cand = evs[:start] + evs[start + chunk:]
            if not cand:
                continue
            res = violates(cand)
            if res is not None:
                evs, best = cand, res
                n = max(n - 1, 2)
                shrunk = True
                break
        if not shrunk:
            if n >= len(evs):
                break
            n = min(len(evs), n * 2)
    return best


def emit_regression(result: Dict, out_dir: str) -> str:
    """Write a violating (ideally shrunken) schedule as a committable
    regression case; tests/dst/ replays every file in its corpus
    directory."""
    os.makedirs(out_dir, exist_ok=True)
    name = (f"dst_seed{result['seed']}_"
            f"{result['schedule_digest']}.json")
    path = os.path.join(out_dir, name)
    with open(path, "w") as fp:
        json.dump({"format": SCHEDULE_FORMAT,
                   "seed": result["seed"],
                   "events": result["events"],
                   "violation": result["violation"],
                   "mutation": os.environ.get(faults.MUTATION_ENV, "")},
                  fp, indent=2, sort_keys=True)
        fp.write("\n")
    return path


# -- the `make dst` lane -----------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    from cilium_tpu.core.config import Config

    # the multichip arm needs >=2 virtual devices; force them before
    # any jax use (a backend already initialized narrower just makes
    # the arm record "skipped" — never fails the lane)
    try:
        from cilium_tpu.parallel.mesh import force_cpu_host_devices

        force_cpu_host_devices(2)
    except RuntimeError:
        pass

    cfg = Config.from_env()
    ap = argparse.ArgumentParser(
        description="seeded fault-schedule search (DST)")
    ap.add_argument("--schedules", type=int, default=cfg.dst.schedules)
    ap.add_argument("--seed", type=int, default=cfg.dst.seed,
                    help="first seed (CILIUM_TPU_DST_SEED)")
    ap.add_argument("--max-events", type=int, default=cfg.dst.max_events)
    ap.add_argument("--replay", action="store_true",
                    help="run ONLY --seed and print its trace")
    ap.add_argument("--shrink", action="store_true",
                    help="delta-debug the first violation to a "
                         "minimal schedule")
    ap.add_argument("--out", default="BENCH_DST_r06.jsonl")
    ap.add_argument("--regressions", default="tests/dst/regressions")
    args = ap.parse_args(argv)

    t0 = simclock.perf()
    if args.replay:
        res = run_schedule(args.seed, max_events=args.max_events)
        print(json.dumps(res, indent=2, sort_keys=True))
        return 1 if res["violation"] else 0

    distinct = set()
    sim_s = [0.0]

    def progress(k, res):
        distinct.add(res["schedule_digest"])
        sim_s[0] += res["trace"][-1]["t"] if res["trace"] else 0.0
        if (k + 1) % 25 == 0:
            print(f"[dst] {k + 1}/{args.schedules} schedules, "
                  f"{len(distinct)} distinct, "
                  f"{sim_s[0]:.0f}s simulated", flush=True)

    ran, failing = search(args.schedules, seed0=args.seed,
                          max_events=args.max_events,
                          progress=progress)
    wall_s = simclock.perf() - t0
    line = {
        "metric": "dst_schedules_explored",
        "value": ran,
        "unit": "schedules",
        "lane": "dst",
        "distinct_schedules": len(distinct),
        "violations": 0 if failing is None else 1,
        "simulated_s": round(sim_s[0], 3),
        "wall_s": round(wall_s, 3),
        "speedup_vs_real_time": round(sim_s[0] / max(wall_s, 1e-9), 1),
        "seed0": args.seed,
        "max_events": args.max_events,
        "mutation": os.environ.get(faults.MUTATION_ENV, ""),
    }
    if failing is not None:
        line["failing_seed"] = failing["seed"]
        line["failing_invariant"] = failing["violation"]["invariant"]
        print(f"[dst] VIOLATION at seed {failing['seed']}: "
              f"{failing['violation']}", flush=True)
        if args.shrink:
            small = shrink(failing["seed"], failing["events"])
            path = emit_regression(small, args.regressions)
            line["shrunk_events"] = len(small["events"])
            line["regression_case"] = path
            print(f"[dst] shrunk to {len(small['events'])} events "
                  f"-> {path}", flush=True)
    from cilium_tpu.runtime.provenance import stamp

    # the lane's own bench line rides the dst provenance stamp: seed0
    # + a digest over the distinct schedules explored
    os.environ["CILIUM_TPU_DST_SEED"] = str(args.seed)
    os.environ["CILIUM_TPU_DST_DIGEST"] = hashlib.sha256(
        ",".join(sorted(distinct)).encode()).hexdigest()[:16]
    stamp(line)
    with open(args.out, "a") as fp:
        fp.write(json.dumps(line) + "\n")
    print(f"[dst] {ran} schedules ({len(distinct)} distinct), "
          f"{line['violations']} violation(s); simulated "
          f"{sim_s[0]:.0f}s of virtual time in {wall_s:.1f}s wall "
          f"({line['speedup_vs_real_time']}x)", flush=True)
    return 1 if failing is not None else 0


if __name__ == "__main__":
    sys.exit(main())
