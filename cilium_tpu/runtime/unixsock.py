"""Shared Unix-socket hygiene for the agent's servers."""

from __future__ import annotations

import os
import socket
import stat


def unlink_if_stale(path: str) -> None:
    """Remove ``path`` only if it is a dead leftover socket. A live
    server or a non-socket file raises — never silently hijack."""
    st = os.stat(path)
    if not stat.S_ISSOCK(st.st_mode):
        raise FileExistsError(
            f"{path} exists and is not a socket; refusing to unlink")
    probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        probe.settimeout(1.0)
        probe.connect(path)
    except (ConnectionRefusedError, FileNotFoundError):
        os.unlink(path)  # stale: nobody listening
    except OSError:
        os.unlink(path)  # unreachable/broken socket counts as stale
    else:
        raise FileExistsError(
            f"another server is live on {path}; refusing to replace")
    finally:
        probe.close()
